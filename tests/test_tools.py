"""Repo tools (reference `tools/CrossStackProfiler/` + the op-benchmark CI
gate `tools/check_op_benchmark_result.py`): trace merging with per-rank
lanes and clock alignment, the cross-rank op summary, and the bench
regression gate against real BENCH_r*.json artifacts."""
import json
import os
import pathlib
import subprocess
import sys

import numpy as np
import pytest

REPO = str(pathlib.Path(__file__).resolve().parent.parent)
sys.path.insert(0, os.path.join(REPO, "tools"))

import check_bench_result as gate  # noqa: E402
import cross_stack_profiler as csp  # noqa: E402


def _trace(events):
    return {"traceEvents": [
        {"name": n, "ph": "X", "cat": "op", "ts": ts, "dur": d,
         "pid": 1234, "tid": 0} for n, ts, d in events]}


class TestCrossStackProfiler:
    def test_merge_assigns_rank_lanes_and_aligns(self, tmp_path):
        (tmp_path / "rank_0.json").write_text(json.dumps(
            _trace([("matmul", 1000.0, 5.0)])))
        (tmp_path / "rank_1.json").write_text(json.dumps(
            _trace([("matmul", 9000.0, 7.0)])))  # different host clock
        traces = csp.load_rank_traces(str(tmp_path))
        merged = csp.merge_traces(traces, align=True)
        xs = [e for e in merged["traceEvents"] if e.get("ph") == "X"]
        assert {e["pid"] for e in xs} == {0, 1}
        assert all(e["ts"] == 0.0 for e in xs)  # aligned to rank t0
        names = [e for e in merged["traceEvents"]
                 if e.get("ph") == "M" and e["name"] == "process_name"]
        assert {m["args"]["name"] for m in names} == {"rank 0", "rank 1"}

    def test_op_summary_aggregates_across_ranks(self):
        traces = {0: _trace([("conv", 0, 10.0), ("conv", 20, 30.0)]),
                  1: _trace([("conv", 0, 20.0), ("relu", 5, 1.0)])}
        rows = csp.op_summary(traces)
        conv = next(r for r in rows if r["name"] == "conv")
        assert conv["calls"] == 3
        assert conv["total_us"] == pytest.approx(60.0)
        assert conv["max_us"] == pytest.approx(30.0)
        assert conv["by_rank"] == {0: 40.0, 1: 20.0}
        assert rows[0]["name"] == "conv"  # sorted by total desc

    def test_cli_end_to_end(self, tmp_path):
        d = tmp_path / "traces"
        d.mkdir()
        (d / "worker_0.json").write_text(json.dumps(
            _trace([("step", 0, 100.0)])))
        out = tmp_path / "merged.json"
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "cross_stack_profiler.py"),
             "--trace_dir", str(d), "--out", str(out), "--summary"],
            capture_output=True, text=True, timeout=120)
        assert r.returncode == 0, r.stderr
        assert out.exists()
        assert "step" in r.stdout

    def test_merges_real_profiler_export(self, tmp_path):
        """End-to-end with the actual paddle_tpu profiler output format."""
        import paddle_tpu as paddle
        from paddle_tpu import profiler as P
        prof = P.Profiler()
        prof.start()
        with P.RecordEvent("span_a"):
            paddle.to_tensor(np.ones(4)) * 2
        prof.stop()
        f0 = str(tmp_path / "rank_0.json")
        prof.export(f0)
        traces = csp.load_rank_traces([f0])
        rows = csp.op_summary(traces)
        assert any(r["name"] == "span_a" for r in rows)


class TestBenchGate:
    BASE = {"configs": {
        "gpt": {"tokens_per_sec_chip": 100000.0},
        "resnet": {"samples_per_sec_chip": 2000.0},
        "ps": {"examples_per_sec": 10000.0}}}

    def test_ok_and_improved(self):
        cur = {"configs": {
            "gpt": {"tokens_per_sec_chip": 101000.0},
            "resnet": {"samples_per_sec_chip": 2500.0},
            "ps": {"examples_per_sec": 9900.0}}}
        rows = gate.compare(self.BASE, cur, 0.05)
        by = {r[0]: r[5] for r in rows}
        assert by == {"gpt": "ok", "resnet": "improved", "ps": "ok"}

    def test_regression_detected(self):
        cur = {"configs": {
            "gpt": {"tokens_per_sec_chip": 80000.0},
            "resnet": {"samples_per_sec_chip": 2000.0},
            "ps": {"examples_per_sec": 10000.0}}}
        rows = gate.compare(self.BASE, cur, 0.05)
        assert ("gpt", "tokens_per_sec_chip", 100000.0, 80000.0, -0.2,
                "regressed") in rows

    def test_same_metric_enforced(self):
        """Current config reporting a DIFFERENT (higher-priority) metric
        must read as missing, not compared across units."""
        cur = {"configs": {
            "gpt": {"tokens_per_sec_chip": 100000.0},
            "resnet": {"tokens_per_sec_chip": 500000.0},  # unit switch
            "ps": {"examples_per_sec": 10000.0}}}
        rows = gate.compare(self.BASE, cur, 0.05)
        by = {r[0]: r[5] for r in rows}
        assert by["resnet"] == "missing"

    def test_zero_baseline_unusable(self):
        base = {"configs": {"gpt": {"tokens_per_sec_chip": 0.0}}}
        cur = {"configs": {"gpt": {"tokens_per_sec_chip": 1.0}}}
        rows = gate.compare(base, cur, 0.05)
        assert rows[0][5] == "missing"

    def test_duplicate_rank_files_rejected(self, tmp_path):
        (tmp_path / "rank_0.json").write_text(json.dumps(_trace([])))
        (tmp_path / "worker_0.json").write_text(json.dumps(_trace([])))
        with pytest.raises(ValueError, match="rank 0"):
            csp.load_rank_traces(str(tmp_path))

    def test_missing_config_fails(self):
        cur = {"configs": {"gpt": {"tokens_per_sec_chip": 100000.0}}}
        rows = gate.compare(self.BASE, cur, 0.05)
        assert any(r[5] == "missing" for r in rows)

    def test_cli_on_real_driver_artifacts(self, tmp_path):
        """The gate must parse the actual driver BENCH files in the repo."""
        base = os.path.join(REPO, "BENCH_r02.json")
        cur = os.path.join(REPO, "BENCH_r04.json")
        if not (os.path.exists(base) and os.path.exists(cur)):
            pytest.skip("driver bench artifacts absent")
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "check_bench_result.py"),
             "--baseline", base, "--current", cur, "--threshold", "0.05"],
            capture_output=True, text=True, timeout=120)
        assert r.returncode in (0, 1), r.stderr  # parses + gates
        assert "gpt2_small" in r.stdout

class TestObservabilitySchemaGate:
    """check_bench_result.py validates `observability` sections against the
    step-record and event schemas (fleet-observability satellite)."""

    @staticmethod
    def _good_doc():
        import time as _time
        from paddle_tpu.profiler.monitor import make_step_record
        return {
            "configs": {"gpt": {"tokens_per_sec_chip": 100000.0}},
            "observability": {
                "step_records": [make_step_record(
                    step=10, window_steps=10, window_time_s=1.0)],
                "events_tail": [{"ts": _time.time(), "kind": "retrace",
                                 "host": "trainer-0", "severity": "info"}],
            },
        }

    def test_valid_observability_passes(self):
        doc = self._good_doc()
        assert gate.validate_observability(doc) == []

    def test_bad_step_record_and_event_named(self):
        doc = self._good_doc()
        doc["observability"]["step_records"][0].pop("ts")
        doc["observability"]["events_tail"][0]["kind"] = "Not Legal"
        problems = gate.validate_observability(doc)
        assert len(problems) == 2
        assert any("step_records[0]" in p and "ts" in p for p in problems)
        assert any("events_tail[0]" in p and "kind" in p for p in problems)

    def test_per_config_blocks_validated(self):
        doc = self._good_doc()
        doc["configs"]["gpt"]["observability"] = {
            "step_records": [{"bogus": True}]}
        problems = gate.validate_observability(doc)
        assert any("configs.gpt.observability" in p for p in problems)

    def test_missing_observability_is_fine(self):
        assert gate.validate_observability(
            {"configs": {"gpt": {"tokens_per_sec_chip": 1.0}}}) == []

    def test_gate_fails_on_schema_violation(self, tmp_path, capsys):
        base = tmp_path / "base.json"
        cur = tmp_path / "cur.json"
        base.write_text(json.dumps(self._good_doc()))
        bad = self._good_doc()
        bad["observability"]["events_tail"][0].pop("host")
        cur.write_text(json.dumps(bad))
        rc = gate.main(["--baseline", str(base), "--current", str(cur)])
        assert rc == 1
        out = capsys.readouterr().out
        assert "observability schema violations" in out
        # --no-obs-check restores the old perf-only gate
        assert gate.main(["--baseline", str(base), "--current", str(cur),
                          "--no-obs-check"]) == 0

    def test_real_driver_artifact_validates(self):
        path = os.path.join(REPO, "BENCH_r05.json")
        if not os.path.exists(path):
            pytest.skip("no driver artifact on this box")
        assert gate.validate_observability(gate._load(path)) == []


class TestAsyncCheckpointMetricsGate:
    """checkpoint_async_* families in an observability metrics snapshot
    must be the right kind with a consistent shape (sharded-checkpoint
    satellite)."""

    @staticmethod
    def _doc_with_metrics(metrics):
        doc = TestObservabilitySchemaGate._good_doc()
        doc["observability"]["metrics"] = metrics
        return doc

    @staticmethod
    def _good_metrics():
        return {
            "checkpoint_async_pending": {
                "kind": "gauge", "help": "h",
                "values": [{"labels": {}, "value": 0.0}]},
            "checkpoint_async_bytes": {
                "kind": "counter", "help": "h",
                "values": [{"labels": {}, "value": 1024.0}]},
            "checkpoint_async_seconds": {
                "kind": "histogram", "help": "h",
                "values": [{"labels": {},
                            "buckets": {"0.1": 1, "+Inf": 2},
                            "sum": 0.5, "count": 2}]},
        }

    def test_live_registry_snapshot_validates(self):
        # the REAL families registered by sharded_checkpoint must pass
        import paddle_tpu.distributed.sharded_checkpoint  # noqa: F401
        from paddle_tpu.profiler.metrics import default_registry
        snap = default_registry().snapshot()
        assert set(_k for _k in snap if _k.startswith("checkpoint_async")) \
            == {"checkpoint_async_pending", "checkpoint_async_bytes",
                "checkpoint_async_seconds"}
        doc = self._doc_with_metrics(snap)
        assert gate.validate_observability(doc) == []

    def test_good_families_pass(self):
        assert gate.validate_observability(
            self._doc_with_metrics(self._good_metrics())) == []

    def test_wrong_kind_named(self):
        m = self._good_metrics()
        m["checkpoint_async_pending"]["kind"] = "counter"
        problems = gate.validate_observability(self._doc_with_metrics(m))
        assert any("checkpoint_async_pending" in p and "gauge" in p
                   for p in problems)

    def test_inconsistent_histogram_named(self):
        m = self._good_metrics()
        m["checkpoint_async_seconds"]["values"][0]["buckets"]["+Inf"] = 99
        problems = gate.validate_observability(self._doc_with_metrics(m))
        assert any("checkpoint_async_seconds" in p and "inconsistent" in p
                   for p in problems)

    def test_negative_value_and_unknown_family_named(self):
        m = self._good_metrics()
        m["checkpoint_async_bytes"]["values"][0]["value"] = -1
        m["checkpoint_async_queue"] = {"kind": "gauge", "values": []}
        problems = gate.validate_observability(self._doc_with_metrics(m))
        assert any("checkpoint_async_bytes" in p for p in problems)
        assert any("checkpoint_async_queue" in p and "unknown" in p
                   for p in problems)

    def test_other_families_ignored(self):
        doc = self._doc_with_metrics(
            {"op_calls_total": {"kind": "counter", "values": "garbage"}})
        assert gate.validate_observability(doc) == []

    def test_malformed_values_reported_not_crash(self):
        for bad in ("garbage", [1, 2], [{"value": 1}, "x"]):
            m = {"checkpoint_async_pending": {"kind": "gauge",
                                             "values": bad}}
            problems = gate.validate_observability(self._doc_with_metrics(m))
            assert any("checkpoint_async_pending" in p for p in problems), \
                f"values={bad!r} did not produce a named violation"


class TestXplaneLaneMerge:
    """cross_stack_profiler --xplane_dir: each rank's backend work lanes
    interleave under its host lane, clock-shifted to the shared zero."""

    @staticmethod
    def _xplane_doc():
        return {"traceEvents": [
            {"ph": "M", "name": "process_name", "pid": 9,
             "args": {"name": "/host:CPU"}},
            {"ph": "M", "name": "thread_name", "pid": 9, "tid": 1,
             "args": {"name": "python"}},
            {"ph": "X", "name": "$frame", "ts": 5000.0, "dur": 100.0,
             "pid": 9, "tid": 1},
            {"ph": "X", "name": "dot.3", "ts": 5010.0, "dur": 40.0,
             "pid": 9, "tid": 2},
            {"ph": "X", "name": "fusion.1", "ts": 5060.0, "dur": 20.0,
             "pid": 9, "tid": 2},
            {"ph": "X", "name": "ThreadpoolListener::StartRegion",
             "ts": 5000.0, "dur": 500.0, "pid": 9, "tid": 2},
        ]}

    def test_device_lanes_interleave_under_rank(self, tmp_path):
        host = {0: _trace([("train_step", 1000.0, 50.0)])}
        merged = csp.merge_traces(
            host, align=True, xplane={0: self._xplane_doc()["traceEvents"]})
        evs = merged["traceEvents"]
        work = [e for e in evs if e.get("ph") == "X"
                and e["name"] in ("dot.3", "fusion.1")]
        assert len(work) == 2
        assert all(e["pid"] == 0 for e in work), "device lane not re-homed"
        # clock shifted: first work event at 0, second keeps its offset
        assert min(e["ts"] for e in work) == 0.0
        assert max(e["ts"] for e in work) == pytest.approx(50.0)
        # infra markers stay out; synthetic thread is labeled xplane:
        assert not any(e.get("name", "").startswith("ThreadpoolListener")
                       for e in evs)
        tnames = [e["args"]["name"] for e in evs
                  if e.get("ph") == "M" and e["name"] == "thread_name"]
        assert any(t.startswith("xplane:") for t in tnames)
        assert merged["metadata"]["xplane_ranks"] == [0]

    def test_load_xplane_dir_files_and_session_dirs(self, tmp_path):
        import gzip
        d = tmp_path / "xp"
        d.mkdir()
        (d / "rank_0.trace.json.gz").write_bytes(
            gzip.compress(json.dumps(self._xplane_doc()).encode()))
        sess = d / "rank_1" / "plugins" / "profile" / "2026_01_01"
        sess.mkdir(parents=True)
        (sess / "host.trace.json.gz").write_bytes(
            gzip.compress(json.dumps(self._xplane_doc()).encode()))
        by_rank = csp.load_xplane_dir(str(d))
        assert set(by_rank) == {0, 1}
        assert any(e.get("name") == "dot.3" for e in by_rank[0])

    def test_cli_with_xplane_dir(self, tmp_path):
        td = tmp_path / "traces"
        td.mkdir()
        (td / "rank_0.json").write_text(json.dumps(
            _trace([("step", 0, 100.0)])))
        xd = tmp_path / "xp"
        xd.mkdir()
        (xd / "rank_0.json").write_text(json.dumps(self._xplane_doc()))
        out = tmp_path / "merged.json"
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "cross_stack_profiler.py"),
             "--trace_dir", str(td), "--out", str(out),
             "--xplane_dir", str(xd)],
            capture_output=True, text=True, timeout=120)
        assert r.returncode == 0, r.stderr
        doc = json.load(open(out))
        assert any(e.get("name") == "dot.3" for e in doc["traceEvents"])
        assert "1 xplane device traces" in r.stdout


class TestObsTailDiagnoseAndFollow:
    @staticmethod
    def _diag_event(step=40, dominant="data_wait"):
        return {"ts": 1722700000.0, "kind": "step_diagnosis",
                "host": "trainer-0", "severity": "info", "wall_s": 2.0,
                "steps": 20, "step": step, "dominant": dominant,
                "dominant_frac": 0.55,
                "terms": {"data_wait": 1.1, "host_dispatch": 0.3,
                          "device_compute": 0.0, "unattributed": 0.6}}

    def test_diagnose_renders_breakdown(self, tmp_path, capsys):
        import obs_tail
        path = tmp_path / "ev.jsonl"
        with open(path, "w") as f:
            f.write(json.dumps(self._diag_event()) + "\n")
            f.write(json.dumps({"ts": 1.0, "kind": "retrace",
                                "host": "trainer-0"}) + "\n")
        rc = obs_tail.main([str(path), "--diagnose"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "dominant=data_wait (55% of wall)" in out
        assert "data_wait=1100.0ms" in out
        assert "step 40" in out
        assert "retrace" not in out  # --diagnose implies the kind filter

    def test_diagnose_respects_explicit_kind(self, tmp_path, capsys):
        import obs_tail
        path = tmp_path / "ev.jsonl"
        with open(path, "w") as f:
            f.write(json.dumps({"ts": 1.0, "kind": "retrace",
                                "host": "h"}) + "\n")
        rc = obs_tail.main([str(path), "--diagnose", "--kind", "retrace"])
        assert rc == 0
        assert "retrace" in capsys.readouterr().out

    def test_follow_for_is_time_bounded(self, tmp_path, capsys):
        """Satellite: --follow gets direct (and bounded) coverage — events
        appended while following are printed, and --follow-for returns."""
        import threading as _threading
        import time as _time
        import obs_tail
        path = tmp_path / "ev.jsonl"
        with open(path, "w") as f:
            f.write(json.dumps({"ts": 1.0, "kind": "retrace",
                                "host": "h", "seq": 0}) + "\n")

        def append_later():
            _time.sleep(0.4)
            with open(path, "a") as f:
                f.write(json.dumps({"ts": 2.0, "kind": "retrace",
                                    "host": "h", "seq": 1}) + "\n")

        th = _threading.Thread(target=append_later)
        th.start()
        t0 = _time.monotonic()
        rc = obs_tail.main([str(path), "--follow", "--follow-for", "1.2",
                            "--json"])
        took = _time.monotonic() - t0
        th.join()
        assert rc == 0
        assert took < 5.0, "follow-for did not bound the tail"
        lines = [json.loads(l) for l in
                 capsys.readouterr().out.strip().splitlines()]
        assert [l["seq"] for l in lines] == [0, 1]


class TestDeviceTimeAndMemoryGate:
    """check_bench_result: device_time provenance (incl. the new
    device_src="xplane") and device_memory_* family validation."""

    @staticmethod
    def _doc(dt=None, metrics=None):
        obs = {}
        if dt is not None:
            obs["device_time"] = dt
        if metrics is not None:
            obs["metrics"] = metrics
        return {"configs": {}, "observability": obs}

    def test_xplane_src_and_mode_valid(self):
        dt = {"mode": "xplane",
              "rows": [{"op": "matmul", "calls": 3, "host_ms": 1.0,
                        "device_ms": 0.5, "src": "xplane"},
                       {"op": "softmax", "calls": 3, "host_ms": 1.0,
                        "device_ms": 0.2, "src": "estimate"}]}
        assert gate.validate_observability(self._doc(dt=dt)) == []

    def test_unknown_src_and_mode_fail(self):
        dt = {"mode": "vibes",
              "rows": [{"op": "matmul", "calls": 1, "host_ms": 1.0,
                        "device_ms": 0.5, "src": "guessed"}]}
        problems = gate.validate_observability(self._doc(dt=dt))
        assert any("mode" in p and "vibes" in p for p in problems)
        assert any("src" in p and "guessed" in p for p in problems)

    def test_malformed_rows_named(self):
        dt = {"rows": [{"op": "", "calls": -1, "host_ms": "x",
                        "device_ms": 0.1, "src": "estimate"}, "junk"]}
        problems = gate.validate_observability(self._doc(dt=dt))
        assert any(".op" in p for p in problems)
        assert any(".calls" in p for p in problems)
        assert any(".host_ms" in p for p in problems)
        assert any("rows[1]" in p for p in problems)

    def test_device_memory_families(self):
        good = {"device_memory_bytes_in_use": {
            "kind": "gauge", "help": "by device",
            "values": [{"labels": {"device": "cpu:0"}, "value": 1024}]}}
        assert gate.validate_observability(self._doc(metrics=good)) == []
        bad = {"device_memory_peak_bytes": {
            "kind": "counter", "help": "",
            "values": [{"labels": {}, "value": -5}]}}
        problems = gate.validate_observability(self._doc(metrics=bad))
        assert any("expected gauge" in p for p in problems)
        missing = {"device_memory_peak_bytes": {
            "kind": "gauge", "help": "",
            "values": [{"labels": {}, "value": -5}]}}
        problems = gate.validate_observability(self._doc(metrics=missing))
        assert any("non-negative" in p for p in problems)
        assert any("'device' label" in p for p in problems)

    def test_real_capture_summary_device_time_validates(self, tmp_path):
        """A real CaptureSession summary's device_time block passes the
        gate with src=xplane rows (the BENCH_r06 shape)."""
        import numpy as np
        import paddle_tpu as paddle
        from paddle_tpu.profiler import xplane
        sess = xplane.CaptureSession(str(tmp_path / "gate"))
        sess.start()
        try:
            a = paddle.to_tensor(np.ones((64, 64), np.float32))
            paddle.matmul(a, a)
        finally:
            summary = sess.stop(steps=1)
        assert gate.validate_observability(
            self._doc(dt=summary["device_time"])) == []


class TestHealthGate:
    """check_bench_result: the bench `observability.health` block and the
    `health_*`/`amp_*` metric families (training-health PR)."""

    @staticmethod
    def _doc(health=None, metrics=None):
        doc = {"configs": {"gpt": {"tokens_per_sec_chip": 1.0}},
               "observability": {}}
        if health is not None:
            doc["observability"]["health"] = health
        if metrics is not None:
            doc["observability"]["metrics"] = metrics
        return doc

    @staticmethod
    def _good_block():
        return {"step_ms_off": 10.0, "step_ms_on": 10.1,
                "overhead_frac": 0.01, "interval": 1, "groups": 13,
                "sentinel": {"loss": 2.5, "grad_norm": 1.0,
                             "update_ratio": 0.001, "nonfinite": False},
                "note": "probe"}

    @staticmethod
    def _good_metrics():
        return {
            "health_loss": {"kind": "gauge", "help": "",
                            "values": [{"labels": {}, "value": -0.5}]},
            "health_layer_grad_norm": {
                "kind": "gauge", "help": "",
                "values": [{"labels": {"group": "fc1"}, "value": 2.0}]},
            "health_nonfinite_total": {
                "kind": "counter", "help": "",
                "values": [{"labels": {"src": "sentinel"}, "value": 1}]},
            "amp_found_inf_total": {"kind": "counter", "help": "",
                                    "values": [{"labels": {}, "value": 2}]},
            "amp_loss_scale": {"kind": "gauge", "help": "",
                               "values": [{"labels": {}, "value": 32768.0}]},
            "fleet_health_status": {
                "kind": "gauge", "help": "",
                "values": [{"labels": {"host": "t0"}, "value": 2}]},
        }

    def test_good_block_and_metrics_pass(self):
        assert gate.validate_observability(
            self._doc(self._good_block(), self._good_metrics())) == []

    def test_failed_probe_reports_itself(self):
        assert gate.validate_observability(
            self._doc({"error": "TimeoutError: slow box"})) == []

    def test_bad_overhead_and_negative_ms_named(self):
        h = self._good_block()
        h["overhead_frac"] = -2.0
        h["step_ms_on"] = -1.0
        problems = gate.validate_observability(self._doc(h))
        assert any("overhead_frac" in p for p in problems)
        assert any("step_ms_on" in p for p in problems)

    def test_bad_sentinel_named(self):
        h = self._good_block()
        h["sentinel"]["nonfinite"] = "yes"
        h["sentinel"]["grad_norm"] = "big"
        problems = gate.validate_observability(self._doc(h))
        assert any("nonfinite" in p for p in problems)
        assert any("grad_norm" in p for p in problems)

    def test_wrong_kind_and_unknown_family_named(self):
        m = self._good_metrics()
        m["health_nonfinite_total"]["kind"] = "gauge"
        m["health_surprise_total"] = {"kind": "counter", "values": []}
        problems = gate.validate_observability(self._doc(metrics=m))
        assert any("health_nonfinite_total" in p and "counter" in p
                   for p in problems)
        assert any("health_surprise_total" in p and "unknown" in p
                   for p in problems)

    def test_missing_label_and_nonfinite_value_named(self):
        m = self._good_metrics()
        m["health_layer_grad_norm"]["values"][0]["labels"] = {}
        m["health_loss"]["values"][0]["value"] = float("nan")
        problems = gate.validate_observability(self._doc(metrics=m))
        assert any("'group' label" in p for p in problems)
        assert any("health_loss" in p and "finite" in p for p in problems)

    def test_negative_counter_named(self):
        m = self._good_metrics()
        m["amp_found_inf_total"]["values"][0]["value"] = -1
        problems = gate.validate_observability(self._doc(metrics=m))
        assert any("amp_found_inf_total" in p and "negative" in p
                   for p in problems)

    def test_live_registry_snapshot_validates(self):
        """Real registry series seeded by the health plane pass the gate."""
        from paddle_tpu.profiler import health
        from paddle_tpu.profiler.metrics import default_registry
        health.reset()
        health.record_step_stats(
            {"loss": 1.5, "nonfinite": False, "grad_norm": 2.0,
             "update_ratio": 0.01, "group_grad_norms": {"fc1": 2.0}},
            step=1)
        snap = default_registry().snapshot()
        assert gate.validate_observability(self._doc(metrics=snap)) == []

    def test_bench_probe_block_validates(self):
        """bench.health_overhead_probe output passes the gate on a tiny
        model (the BENCH_r06 shape)."""
        import paddle_tpu as paddle
        from paddle_tpu import nn, optimizer
        from paddle_tpu.jit import TrainStep
        from paddle_tpu.nn import functional as F
        sys.path.insert(0, REPO)
        try:
            import bench
        finally:
            sys.path.remove(REPO)
        paddle.seed(0)
        net = nn.Linear(8, 4)
        x = paddle.to_tensor(np.ones((4, 8), np.float32))
        y = paddle.to_tensor(np.array([0, 1, 2, 3], np.int64))

        def mk(on):
            opt = optimizer.SGD(learning_rate=0.01,
                                parameters=net.parameters())
            return TrainStep(net, F.cross_entropy, opt, health=on)

        block = bench.health_overhead_probe(mk, (x, y), iters=3, warmup=1)
        assert block["groups"] == 1
        assert block["sentinel"]["nonfinite"] is False
        assert gate.validate_observability(self._doc(block)) == []


class TestObsTailHealth:
    """obs_tail --health: filter + operator rendering of the numerics
    plane's events."""

    @staticmethod
    def _write(tmp_path):
        path = tmp_path / "ev.jsonl"
        recs = [
            {"ts": 10.0, "kind": "retrace", "host": "t0", "name": "mm"},
            {"ts": 11.0, "kind": "tensor_health", "host": "t0",
             "severity": "error", "src": "sentinel", "step": 40,
             "bad_groups": ["blocks.3"]},
            {"ts": 12.0, "kind": "tensor_health", "host": "t0",
             "severity": "error", "src": "eager", "op": "matmul",
             "layer": "blocks.3.attn", "bad_kind": "nan",
             "shape": [8, 64], "dtype": "float32", "output_index": 0},
            {"ts": 13.0, "kind": "health_alert", "host": "t0",
             "severity": "warn", "signal": "grad_explosion",
             "grad_norm": 1e9, "step": 41},
            {"ts": 14.0, "kind": "health_rollback", "host": "t0",
             "severity": "warn", "reason": "nonfinite", "step": 42,
             "restored_step": 35, "rollbacks": 1},
            {"ts": 15.0, "kind": "fleet_health", "host": "t0",
             "severity": "error", "unhealthy": "trainer-1",
             "status": "diverged"},
        ]
        with open(path, "w") as f:
            for r in recs:
                f.write(json.dumps(r) + "\n")
        return str(path)

    def test_health_filters_and_renders(self, tmp_path, capsys):
        import obs_tail
        rc = obs_tail.main([self._write(tmp_path), "--health"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "retrace" not in out          # filtered to health kinds
        assert "nan in blocks.3.attn op=matmul" in out
        assert "blocks.3" in out             # sentinel bad_groups
        assert "grad_explosion" in out
        assert "restored checkpoint step 35" in out
        assert "host trainer-1 went diverged" in out

    def test_health_respects_explicit_kind(self, tmp_path, capsys):
        import obs_tail
        rc = obs_tail.main([self._write(tmp_path), "--health",
                            "--kind", "health_rollback"])
        out = capsys.readouterr().out
        assert rc == 0
        lines = [l for l in out.splitlines() if l.strip()]
        assert len(lines) == 1 and "restored checkpoint" in lines[0]

    def test_health_with_diagnose_combines(self, tmp_path, capsys):
        import obs_tail
        path = tmp_path / "ev.jsonl"
        with open(path, "w") as f:
            f.write(json.dumps({"ts": 1.0, "kind": "health_alert",
                                "host": "t0", "signal": "loss_spike"}) + "\n")
            f.write(json.dumps({"ts": 2.0, "kind": "step_diagnosis",
                                "host": "t0", "wall_s": 1.0, "steps": 5,
                                "dominant": "data_wait",
                                "dominant_frac": 0.5,
                                "terms": {"data_wait": 0.5}}) + "\n")
        rc = obs_tail.main([str(path), "--health", "--diagnose"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "loss_spike" in out
        assert "dominant=data_wait" in out


class TestObsTailErrorPaths:
    def test_unreadable_file_exits_2(self, tmp_path, capsys):
        import obs_tail
        path = tmp_path / "ev.jsonl"
        path.write_text("{}\n")
        os.chmod(path, 0)
        try:
            if os.access(path, os.R_OK):
                pytest.skip("running as root: chmod 0 still readable")
            assert obs_tail.main([str(path)]) == 2
            assert "obs_tail:" in capsys.readouterr().err
        finally:
            os.chmod(path, 0o644)

    def test_follow_backlog_has_no_gap(self, tmp_path, capsys):
        """Events appended between backlog render and tail start must not
        be dropped: follow() reads the backlog through the SAME handle it
        tails."""
        import obs_tail
        path = tmp_path / "ev.jsonl"
        with open(path, "w") as f:
            for i in range(3):
                f.write(json.dumps({"ts": float(i), "kind": "retrace",
                                    "host": "h", "seq": i}) + "\n")

        real_parse = obs_tail.parse_lines
        appended = {"done": False}

        def racing_parse(lines):
            # first call = the backlog parse; append an event right after
            # the backlog lines were read but before the tail loop starts
            out = real_parse(lines)
            if not appended["done"]:
                appended["done"] = True
                with open(path, "a") as f:
                    f.write(json.dumps({"ts": 9.0, "kind": "retrace",
                                        "host": "h", "seq": 3}) + "\n")
            return out

        obs_tail.parse_lines = racing_parse
        try:
            rc = obs_tail.main([str(path), "--follow", "--follow-for",
                                "1.0", "--json"])
        finally:
            obs_tail.parse_lines = real_parse
        assert rc == 0
        seqs = [json.loads(l)["seq"] for l in
                capsys.readouterr().out.strip().splitlines()]
        assert seqs == [0, 1, 2, 3]  # the racing append is NOT lost


class TestAutotuneGate:
    """`autotune_*` metric families and the per-config / observability
    `autotune` blocks must validate (kernel-autotuner satellite)."""

    @staticmethod
    def _doc_with_metrics(metrics):
        doc = TestObservabilitySchemaGate._good_doc()
        doc["observability"]["metrics"] = metrics
        return doc

    @staticmethod
    def _good_metrics():
        return {
            "autotune_cache_events_total": {
                "kind": "counter", "help": "h",
                "values": [{"labels": {"event": "hit", "op": "flash_fwd"},
                            "value": 3.0}]},
            "autotune_tunes_total": {
                "kind": "counter", "help": "h",
                "values": [{"labels": {"op": "flash_fwd"}, "value": 1.0}]},
            "autotune_probe_seconds": {
                "kind": "histogram", "help": "h",
                "values": [{"labels": {"op": "flash_fwd"},
                            "buckets": {"0.1": 1, "+Inf": 1},
                            "sum": 0.05, "count": 1}]},
            "autotune_chosen_config": {
                "kind": "gauge", "help": "h",
                "values": [{"labels": {"op": "flash_fwd",
                                       "config": "q256-k512"},
                            "value": 1.25}]},
        }

    @staticmethod
    def _good_block():
        return {
            "enabled": True, "mode": "on", "cache_dir": "/tmp/at",
            "events": {"miss": 1, "persist": 1},
            "tuned": [{"op": "flash_fwd", "key": [1024, 1024],
                       "chip": "v5e", "config": "q256-k512",
                       "probe_ms": 1.25, "source": "tuned"}],
        }

    def test_good_families_and_blocks_pass(self):
        doc = self._doc_with_metrics(self._good_metrics())
        doc["observability"]["autotune"] = self._good_block()
        doc["configs"]["gpt"]["autotune"] = self._good_block()
        assert gate.validate_observability(doc) == []

    def test_live_registry_snapshot_validates(self):
        # the REAL families the autotuner registers must pass the gate
        from paddle_tpu.ops.pallas import autotune as at
        at._M_EVENTS.inc(event="miss", op="gate_op")
        at._M_TUNES.inc(op="gate_op")
        at._M_PROBE_SECONDS.observe(0.01, op="gate_op")
        at._M_CHOSEN.set(0.5, op="gate_op", config="rows256")
        from paddle_tpu.profiler.metrics import default_registry
        snap = {k: v for k, v in default_registry().snapshot().items()
                if k.startswith("autotune_")}
        assert set(snap) == {"autotune_cache_events_total",
                             "autotune_tunes_total",
                             "autotune_probe_seconds",
                             "autotune_chosen_config"}
        assert gate.validate_observability(
            self._doc_with_metrics(snap)) == []

    def test_live_summary_block_validates(self):
        from paddle_tpu.ops.pallas import autotune as at
        doc = TestObservabilitySchemaGate._good_doc()
        doc["observability"]["autotune"] = at.summary()
        assert gate.validate_observability(doc) == []

    def test_wrong_kind_and_unknown_family_named(self):
        m = self._good_metrics()
        m["autotune_tunes_total"]["kind"] = "gauge"
        m["autotune_best_ms"] = {"kind": "gauge", "values": []}
        problems = gate.validate_observability(self._doc_with_metrics(m))
        assert any("autotune_tunes_total" in p and "counter" in p
                   for p in problems)
        assert any("autotune_best_ms" in p and "unknown" in p
                   for p in problems)

    def test_negative_value_and_missing_label_named(self):
        m = self._good_metrics()
        m["autotune_cache_events_total"]["values"][0]["value"] = -1
        m["autotune_chosen_config"]["values"][0]["labels"] = {"op": "x"}
        problems = gate.validate_observability(self._doc_with_metrics(m))
        assert any("autotune_cache_events_total" in p and "non-negative" in p
                   for p in problems)
        assert any("autotune_chosen_config" in p and "config" in p
                   for p in problems)

    def test_inconsistent_histogram_named(self):
        m = self._good_metrics()
        m["autotune_probe_seconds"]["values"][0]["buckets"]["+Inf"] = 7
        problems = gate.validate_observability(self._doc_with_metrics(m))
        assert any("autotune_probe_seconds" in p and "inconsistent" in p
                   for p in problems)

    def test_bad_config_block_named(self):
        doc = TestObservabilitySchemaGate._good_doc()
        doc["configs"]["gpt"]["autotune"] = {
            "enabled": "yes",                      # not a bool
            "mode": "sometimes",                   # unknown mode
            "events": {"miss": -2},                # negative count
            "tuned": [{"op": "", "config": 7, "probe_ms": -1.0}],
        }
        problems = gate.validate_observability(doc)
        joined = "\n".join(problems)
        assert "configs.gpt.autotune.enabled" in joined
        assert "configs.gpt.autotune.mode" in joined
        assert "events['miss']" in joined or "events" in joined
        assert any("tuned[0]" in p for p in problems)

    def test_malformed_blocks_reported_not_crash(self):
        doc = TestObservabilitySchemaGate._good_doc()
        for bad in ("garbage", [1], {"tuned": "x"}, {"events": [1]}):
            doc["configs"]["gpt"]["autotune"] = bad
            problems = gate.validate_observability(doc)
            assert problems, f"autotune={bad!r} produced no violation"


class TestPlatformAwareGate:
    """r06: cross-platform rounds/configs read 'incomparable', never
    'regressed' — a CPU dev-box round vs a TPU driver round is not a
    perf regression. Undeclared-vs-undeclared keeps the old behavior."""

    BASE = {"configs": {
        "gpt": {"tokens_per_sec_chip": 100000.0},
        "ps_cpu": {"examples_per_sec": 30000.0, "platform": "cpu"}}}

    def test_declared_mismatch_is_incomparable(self):
        cur = {"platform": "cpu", "configs": {
            "gpt": {"tokens_per_sec_chip": 50.0, "platform": "cpu"},
            "ps_cpu": {"examples_per_sec": 3000.0, "platform": "cpu"}}}
        rows = gate.compare(self.BASE, cur, 0.05,
                            baseline_platform="tpu")
        by = {r[0]: r[5] for r in rows}
        # round platforms differ -> EVERY row incomparable, including the
        # all-CPU PS config (it ran on a different HOST)
        assert by == {"gpt": "incomparable", "ps_cpu": "incomparable"}

    def test_no_assumption_keeps_status_quo(self):
        cur = {"configs": {
            "gpt": {"tokens_per_sec_chip": 50.0},
            "ps_cpu": {"examples_per_sec": 30000.0}}}
        rows = gate.compare(self.BASE, cur, 0.05)
        by = {r[0]: r[5] for r in rows}
        assert by["gpt"] == "regressed"

    def test_axon_is_tpu_family(self):
        base = {"configs": {
            "gpt": {"tokens_per_sec_chip": 100000.0, "platform": "axon"}}}
        cur = {"configs": {
            "gpt": {"tokens_per_sec_chip": 99000.0, "platform": "tpu"}}}
        rows = gate.compare(base, cur, 0.05)
        assert rows[0][5] == "ok"

    def test_incomparable_does_not_fail_cli(self, tmp_path):
        base = tmp_path / "base.json"
        cur = tmp_path / "cur.json"
        base.write_text(json.dumps(self.BASE))
        cur.write_text(json.dumps({"platform": "cpu", "configs": {
            "gpt": {"tokens_per_sec_chip": 50.0, "platform": "cpu"},
            "ps_cpu": {"examples_per_sec": 3000.0, "platform": "cpu"}}}))
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "check_bench_result.py"),
             "--baseline", str(base), "--current", str(cur),
             "--assume-baseline-platform", "tpu"],
            capture_output=True, text=True, timeout=120)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "incomparable" in r.stdout


class TestSegmentsAndConvFusionGate:
    """r06 satellite: the per-segment breakdown block and the conv-fusion
    A/B probe block validate with NAMED violations."""

    @staticmethod
    def _doc(profile=None, conv_fusion=None):
        cfg = {"samples_per_sec_chip": 100.0}
        if profile is not None:
            cfg["profile"] = profile
        if conv_fusion is not None:
            cfg["conv_fusion"] = conv_fusion
        return {"configs": {"resnet50": cfg}}

    def test_valid_segments_pass(self):
        doc = self._doc(profile={"segments": {
            "segments": {
                "attention_fwd": {"device_ms": 1.5, "events": 10,
                                  "frac": 0.5},
                "unattributed": {"device_ms": 1.5, "events": 3,
                                 "frac": 0.5}},
            "total_device_ms": 3.0, "attributed_frac": 0.5}})
        assert gate.validate_observability(doc) == []

    def test_garbled_segments_named(self):
        doc = self._doc(profile={"segments": {
            "segments": {
                "mlp": {"device_ms": -1.0, "events": 2, "frac": 1.7}},
            "total_device_ms": "nope", "attributed_frac": None}})
        probs = gate.validate_observability(doc)
        blob = "\n".join(probs)
        assert "configs.resnet50.profile.segments" in blob
        assert "device_ms" in blob and "frac" in blob \
            and "total_device_ms" in blob

    def test_valid_conv_fusion_passes(self):
        doc = self._doc(conv_fusion={
            "enabled": True, "engaged": False,
            "probe_ms_on": 12.5, "probe_ms_off": 14.0,
            "speedup_vs_off": 1.12, "hbm_gb_per_step_on": 40.0,
            "hbm_gb_per_step_off": 46.0, "hbm_pct_saved": 13.0,
            "kernel_stats": {"pallas_fwd": 0, "xla_fwd": 0}})
        assert gate.validate_observability(doc) == []

    def test_garbled_conv_fusion_named(self):
        doc = self._doc(conv_fusion={
            "enabled": "yes", "probe_ms_on": -3,
            "hbm_pct_saved": 250.0,
            "kernel_stats": {"pallas_fwd": -1}})
        probs = gate.validate_observability(doc)
        blob = "\n".join(probs)
        assert "configs.resnet50.conv_fusion.enabled" in blob
        assert "probe_ms_on" in blob
        assert "hbm_pct_saved" in blob
        assert "kernel_stats" in blob

    def test_probe_error_block_not_gated(self):
        doc = self._doc(conv_fusion={"enabled": True,
                                     "error": "RuntimeError: boom"})
        assert gate.validate_observability(doc) == []

    def test_micro_ab_block_validates(self):
        doc = self._doc(conv_fusion={
            "enabled": True, "engaged": False,
            "micro_ab": {"rows": [
                {"shape": "b128x56x56 64->256",
                 "composed_gb_cost_analysis": 3.8,
                 "composed_gb_model": 0.87, "fused_gb_model": 0.67,
                 "pct_saved": 23.5}],
                "total_pct_saved": 23.5}})
        assert gate.validate_observability(doc) == []
        bad = self._doc(conv_fusion={
            "enabled": True,
            "micro_ab": {"rows": [{"shape": 7, "fused_gb_model": -1,
                                   "pct_saved": 120.0}]}})
        blob = "\n".join(gate.validate_observability(bad))
        assert "micro_ab.rows[0].shape" in blob
        assert "fused_gb_model" in blob and "pct_saved" in blob


class TestScaleAwareGate:
    """Review regression: a scale=ci round must never gate against a
    full-scale baseline even on the SAME platform (bench.py's contract:
    scaled rounds can never be mistaken for full-scale numbers)."""

    def test_scale_mismatch_is_incomparable(self):
        base = {"configs": {"gpt": {"tokens_per_sec_chip": 100000.0,
                                    "platform": "tpu"}}}
        cur = {"configs": {"gpt": {"tokens_per_sec_chip": 50.0,
                                   "platform": "tpu", "scale": "ci"}}}
        rows = gate.compare(base, cur, 0.05)
        assert rows[0][5] == "incomparable"
        # and the reverse direction (full vs ci baseline)
        rows = gate.compare(cur, base, 0.05)
        assert rows[0][5] == "incomparable"

    def test_matching_scales_still_gate(self):
        base = {"configs": {"gpt": {"tokens_per_sec_chip": 100000.0,
                                    "platform": "tpu", "scale": "ci"}}}
        cur = {"configs": {"gpt": {"tokens_per_sec_chip": 80000.0,
                                   "platform": "tpu", "scale": "ci"}}}
        rows = gate.compare(base, cur, 0.05)
        assert rows[0][5] == "regressed"


class TestControllerGate:
    """`controller_*` metric families and `controller_decision` events in
    observability blocks (self-driving fleet satellite): kind/label/shape
    contracts with named violations."""

    @staticmethod
    def _doc(metrics=None, events=None):
        doc = {"configs": {"gpt": {"tokens_per_sec_chip": 1.0}},
               "observability": {}}
        if metrics is not None:
            doc["observability"]["metrics"] = metrics
        if events is not None:
            doc["observability"]["events_tail"] = events
        return doc

    @staticmethod
    def _decision(**over):
        ev = {"ts": 12.0, "kind": "controller_decision", "host": "sup-0",
              "severity": "warn", "policy": "straggler_evict",
              "action": "evict", "target": "trainer-1",
              "outcome": "applied", "decision": 1, "np": 1,
              "evidence": {"windows": 3, "p50_s": 0.4}, "dry_run": False}
        ev.update(over)
        return ev

    def test_valid_controller_metrics_and_event_pass(self):
        metrics = {
            "controller_decisions_total": {"kind": "counter", "values": [
                {"labels": {"policy": "straggler_evict",
                            "outcome": "applied"}, "value": 1}]},
            "controller_evictions_total": {"kind": "counter", "values": [
                {"labels": {"host": "trainer-1"}, "value": 1}]},
            "controller_relaunch_to_first_step_seconds": {
                "kind": "gauge", "values": [
                    {"labels": {"policy": "straggler_evict"},
                     "value": 2.5}]},
        }
        doc = self._doc(metrics=metrics, events=[self._decision()])
        assert gate.validate_observability(doc) == []

    def test_live_registry_snapshot_passes(self):
        from paddle_tpu.profiler import metrics as metrics_mod
        from paddle_tpu.distributed.fleet import controller as ctl
        ctl._M_DECISIONS.inc(policy="health_rollback", outcome="dry_run")
        ctl._M_ROLLBACKS.inc(host="trainer-0")
        snap = metrics_mod.default_registry().snapshot()
        ctl_fams = {k: v for k, v in snap.items()
                    if k.startswith("controller_")}
        assert ctl_fams
        assert gate.validate_observability(self._doc(metrics=ctl_fams)) == []

    def test_unknown_family_and_wrong_kind_named(self):
        metrics = {
            "controller_bogus_total": {"kind": "counter", "values": []},
            "controller_evictions_total": {"kind": "gauge", "values": []},
        }
        blob = "\n".join(gate.validate_observability(self._doc(
            metrics=metrics)))
        assert "controller_bogus_total" in blob and "unknown" in blob
        assert "controller_evictions_total" in blob and "gauge" in blob

    def test_missing_label_bad_outcome_negative_value_named(self):
        metrics = {
            "controller_decisions_total": {"kind": "counter", "values": [
                {"labels": {"policy": "straggler_evict",
                            "outcome": "exploded"}, "value": 1},
                {"labels": {"outcome": "applied"}, "value": -3},
            ]},
        }
        blob = "\n".join(gate.validate_observability(self._doc(
            metrics=metrics)))
        assert "'exploded'" in blob
        assert "missing the 'policy' label" in blob
        assert "-3" in blob

    def test_decision_event_contract_violations_named(self):
        bad = [
            self._decision(outcome="maybe"),
            self._decision(decision=0),
            self._decision(policy=""),
            self._decision(evidence="not-an-object"),
        ]
        blob = "\n".join(gate.validate_observability(self._doc(events=bad)))
        assert "'maybe'" in blob
        assert "'decision' must be a positive integer" in blob
        assert "'policy' must be a non-empty string" in blob
        assert "'evidence' must be an object" in blob

    def test_non_decision_events_not_held_to_decision_contract(self):
        ev = {"ts": 1.0, "kind": "elastic_restart", "host": "sup-0",
              "severity": "warn", "reason": "controller_evict"}
        assert gate.validate_observability(self._doc(events=[ev])) == []


class TestObsTailController:
    """obs_tail --controller: filter + operator rendering of the fleet
    controller's decision events."""

    @staticmethod
    def _write(tmp_path):
        path = tmp_path / "ev.jsonl"
        recs = [
            {"ts": 10.0, "kind": "retrace", "host": "t0", "name": "mm"},
            {"ts": 11.0, "kind": "controller_decision", "host": "sup-0",
             "severity": "warn", "policy": "straggler_evict",
             "action": "evict", "target": "trainer-1", "outcome": "applied",
             "decision": 1, "np": 1,
             "evidence": {"windows": 3, "p50_s": 0.41,
                          "straggling": ["trainer-1"]}, "dry_run": False},
            {"ts": 12.0, "kind": "controller_decision", "host": "sup-0",
             "severity": "info", "policy": "straggler_evict",
             "action": "relaunch_observed", "outcome": "applied",
             "decision": 1, "relaunch_to_first_step_s": 2.75,
             "dry_run": False},
            {"ts": 13.0, "kind": "controller_decision", "host": "sup-0",
             "severity": "warn", "policy": "health_rollback",
             "action": "rollback", "target": "trainer-0",
             "outcome": "dry_run", "decision": 2, "np": 2,
             "evidence": {"diverged": ["trainer-0"]}, "dry_run": True},
        ]
        with open(path, "w") as f:
            for r in recs:
                f.write(json.dumps(r) + "\n")
        return str(path)

    def test_controller_filters_and_renders(self, tmp_path, capsys):
        import obs_tail
        rc = obs_tail.main([self._write(tmp_path), "--controller"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "retrace" not in out          # filtered to decisions
        assert "straggler_evict" in out
        assert "target=trainer-1" in out and "windows=3" in out
        assert "relaunch→first-step 2.75s" in out
        assert "DRY-RUN" in out              # the dry-run rollback line
        assert "health_rollback" in out

    def test_controller_composes_with_health(self, tmp_path, capsys):
        import obs_tail
        path = tmp_path / "ev.jsonl"
        with open(path, "w") as f:
            f.write(json.dumps({"ts": 1.0, "kind": "health_alert",
                                "host": "t0", "signal": "loss_spike"}) + "\n")
            f.write(json.dumps({"ts": 2.0, "kind": "controller_decision",
                                "host": "sup-0", "policy": "health_rollback",
                                "action": "rollback", "outcome": "applied",
                                "decision": 3}) + "\n")
        rc = obs_tail.main([str(path), "--controller", "--health"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "loss_spike" in out
        assert "health_rollback" in out and "decision #3" in out

    def test_controller_respects_explicit_kind(self, tmp_path, capsys):
        import obs_tail
        rc = obs_tail.main([self._write(tmp_path), "--controller",
                            "--kind", "retrace"])
        out = capsys.readouterr().out
        assert rc == 0
        # explicit --kind composes: retraces AND decisions both stream
        assert "retrace" in out
        assert "straggler_evict" in out


class TestServingGate:
    """`serving_*` metric families + the gpt2_decode config block
    (paged-KV decode satellite): kind/label/shape contracts and the
    TTFT/TPOT/goodput/A/B decode-bench contract, named violations."""

    @staticmethod
    def _doc(cfg=None, metrics=None):
        doc = {"configs": {"gpt2_decode": cfg or
                           {"tokens_per_sec_chip": 50.0}}}
        if metrics is not None:
            doc["observability"] = {"metrics": metrics}
        return doc

    @staticmethod
    def _decode_cfg(**over):
        cfg = {
            "tokens_per_sec_chip": 66.0, "decode_tokens_per_sec": 220.0,
            "goodput_tokens": 240, "streams": 24, "completed": 24,
            "preemptions": 0, "batch_occupancy_mean": 3.9,
            "serving": {"ttft_s": {"p50": 0.4, "p99": 1.2},
                        "tpot_s": {"p50": 0.004, "p99": 0.02},
                        "wall_s": 3.6},
            "paged_vs_dense": {
                "rows": [{"ctx": 32, "paged_ms_per_token": 2.0,
                          "dense_ms_per_token": 2.6},
                         {"ctx": 128, "paged_ms_per_token": 1.9,
                          "dense_ms_per_token": 5.9}],
                "paged_growth": 0.95, "dense_growth": 2.27,
                "speedup_at_max_ctx": 3.1},
        }
        cfg.update(over)
        return cfg

    def test_valid_decode_block_passes(self):
        assert gate.validate_observability(
            self._doc(cfg=self._decode_cfg())) == []

    def test_real_bench_block_passes(self):
        """The ACTUAL bench_gpt2_decode output shape validates (wired via
        a canned copy of its structure — the full bench run is the BENCH
        round's job)."""
        cfg = self._decode_cfg()
        cfg["platform"] = "cpu"
        cfg["scale"] = "ci"
        assert gate.validate_observability(self._doc(cfg=cfg)) == []

    def test_malformed_percentiles_and_rows_named(self):
        cfg = self._decode_cfg()
        cfg["serving"]["ttft_s"]["p99"] = -1.0
        cfg["serving"]["tpot_s"] = "fast"
        cfg["paged_vs_dense"]["rows"][0]["ctx"] = 0
        cfg["paged_vs_dense"]["rows"][1]["dense_ms_per_token"] = None
        cfg["goodput_tokens"] = -5
        blob = "\n".join(gate.validate_observability(self._doc(cfg=cfg)))
        assert "ttft_s.p99" in blob
        assert "tpot_s is not an object" in blob
        assert "rows[0].ctx" in blob
        assert "rows[1].dense_ms_per_token" in blob
        assert "goodput_tokens" in blob

    def test_missing_percentile_families_named(self):
        cfg = self._decode_cfg()
        del cfg["serving"]["ttft_s"]
        blob = "\n".join(gate.validate_observability(self._doc(cfg=cfg)))
        assert "serving.ttft_s is missing" in blob

    def test_error_ab_probe_reports_itself(self):
        cfg = self._decode_cfg(paged_vs_dense={"error": "XlaError: boom"})
        assert gate.validate_observability(self._doc(cfg=cfg)) == []

    @staticmethod
    def _v2_blocks():
        return {
            "fused_vs_eager": {"fused_ms_per_token": 9.0,
                               "eager_ms_per_token": 21.0,
                               "speedup": 2.33, "identical_tokens": True},
            "shared_prefix": {
                "on": {"min_free_pages": 60, "prefix_hit_tokens": 180,
                       "shared_admissions": 6, "cow_copies": 6,
                       "preemptions": 0, "completed": 8,
                       "leaked_pages": 0},
                "off": {"min_free_pages": 51, "prefix_hit_tokens": 0,
                        "shared_admissions": 0, "cow_copies": 0,
                        "preemptions": 0, "completed": 8,
                        "leaked_pages": 0},
            },
        }

    def test_valid_v2_ab_blocks_pass(self):
        cfg = self._decode_cfg(**self._v2_blocks())
        assert gate.validate_observability(self._doc(cfg=cfg)) == []

    def test_fused_eager_token_drift_fails_the_gate(self):
        """fused and eager decode disagreeing on tokens is a correctness
        bug the schema gate must catch, not a perf footnote."""
        blocks = self._v2_blocks()
        blocks["fused_vs_eager"]["identical_tokens"] = False
        blob = "\n".join(gate.validate_observability(
            self._doc(cfg=self._decode_cfg(**blocks))))
        assert "identical_tokens" in blob and "disagreed" in blob

    def test_shared_prefix_leak_and_phantom_hits_named(self):
        blocks = self._v2_blocks()
        blocks["shared_prefix"]["on"]["leaked_pages"] = 2
        blocks["shared_prefix"]["off"]["prefix_hit_tokens"] = 9
        blocks["shared_prefix"]["on"]["cow_copies"] = -1
        blob = "\n".join(gate.validate_observability(
            self._doc(cfg=self._decode_cfg(**blocks))))
        assert "on.leaked_pages" in blob
        assert "off.prefix_hit_tokens" in blob and "disabled" in blob
        assert "on.cow_copies" in blob

    def test_v2_error_blocks_report_themselves(self):
        cfg = self._decode_cfg(
            fused_vs_eager={"error": "XlaError: boom"},
            shared_prefix={"error": "RuntimeError: pool"})
        assert gate.validate_observability(self._doc(cfg=cfg)) == []

    @staticmethod
    def _distributed_blocks():
        return {
            "tp_decode": {"single_ms_per_token": 12.0,
                          "tp_ms_per_token": 12.4, "tp_degree": 2,
                          "tpot_ratio": 1.033, "identical_tokens": True,
                          "collective_bytes_by_link": {"ici": 512.0,
                                                       "dcn": 0.0}},
            "disagg": {"colocated_ms_per_token": 12.0,
                       "disagg_ms_per_token": 12.2, "tpot_ratio": 1.017,
                       "handoffs": 5, "prefill_workers": 1,
                       "decode_prefills": 0, "identical_tokens": True},
        }

    def test_valid_distributed_decode_blocks_pass(self):
        cfg = self._decode_cfg(**self._distributed_blocks())
        assert gate.validate_observability(self._doc(cfg=cfg)) == []

    def test_tp_token_drift_and_bad_degree_named(self):
        """TP is a layout change: token drift vs single-chip is a
        correctness bug, and a tp_degree < 2 means no sharding ran."""
        blocks = self._distributed_blocks()
        blocks["tp_decode"]["identical_tokens"] = False
        blocks["tp_decode"]["tp_degree"] = 1
        blocks["tp_decode"]["collective_bytes_by_link"]["ici"] = -1
        blob = "\n".join(gate.validate_observability(
            self._doc(cfg=self._decode_cfg(**blocks))))
        assert "tp_decode.identical_tokens" in blob and "disagreed" in blob
        assert "tp_decode.tp_degree" in blob
        assert "collective_bytes_by_link.ici" in blob

    def test_disagg_decode_side_prefill_fails_the_gate(self):
        """A nonzero decode-side prefill count means the stages were
        never actually split — the disaggregation claim is void."""
        blocks = self._distributed_blocks()
        blocks["disagg"]["decode_prefills"] = 3
        blocks["disagg"]["handoffs"] = 0
        blob = "\n".join(gate.validate_observability(
            self._doc(cfg=self._decode_cfg(**blocks))))
        assert "decode_prefills" in blob and "ran prefills itself" in blob
        assert "disagg.handoffs" in blob

    def test_distributed_blocks_may_skip_or_error(self):
        """A 1-device box skips the TP A/B; a failed probe reports
        itself — both stay schema-valid."""
        cfg = self._decode_cfg(
            tp_decode={"skipped": "needs >=2 devices"},
            disagg={"error": "RuntimeError: boom"})
        assert gate.validate_observability(self._doc(cfg=cfg)) == []

    def test_handoff_families_and_stage_enum_enforced(self):
        metrics = {
            "serving_handoff_wait_seconds": {
                "kind": "histogram", "values": [
                    {"labels": {"model": "m"},
                     "buckets": {"+Inf": 3}, "sum": 0.01, "count": 3}]},
            "serving_handoff_bytes_total": {
                "kind": "counter", "values": [
                    {"labels": {"model": "m"}, "value": 8192.0}]},
            "serving_handoff_depth": {
                "kind": "gauge", "values": [
                    {"labels": {"model": "m"}, "value": 0}]},
            "serving_stage_occupancy": {
                "kind": "gauge", "values": [
                    {"labels": {"model": "m", "stage": "prefill"},
                     "value": 1}]},
        }
        assert gate.validate_observability(self._doc(metrics=metrics)) == []
        metrics["serving_stage_occupancy"]["values"][0]["labels"][
            "stage"] = "warp"
        blob = "\n".join(gate.validate_observability(
            self._doc(metrics=metrics)))
        assert "stage label" in blob and "warp" in blob

    def test_path_label_value_enum_enforced(self):
        metrics = {
            "serving_ttft_seconds": {"kind": "histogram", "values": [
                {"labels": {"model": "m", "path": "warp"},
                 "buckets": {"+Inf": 1}, "sum": 0.1, "count": 1}]},
        }
        blob = "\n".join(gate.validate_observability(
            self._doc(metrics=metrics)))
        assert "path label" in blob and "warp" in blob

    def test_path_label_optional_for_back_compat(self):
        """Pre-v2 artifacts (BENCH_r07 and earlier) carry no path label
        on the latency histograms — they must keep validating."""
        metrics = {
            "serving_tpot_seconds": {"kind": "histogram", "values": [
                {"labels": {"model": "m"},
                 "buckets": {"+Inf": 2}, "sum": 0.1, "count": 2}]},
        }
        assert gate.validate_observability(
            self._doc(metrics=metrics)) == []

    def test_valid_serving_metrics_pass(self):
        metrics = {
            "serving_queue_depth": {"kind": "gauge", "values": [
                {"labels": {"model": "gpt"}, "value": 2}]},
            "serving_goodput_tokens_total": {"kind": "counter", "values": [
                {"labels": {"model": "gpt"}, "value": 240}]},
            "serving_ttft_seconds": {"kind": "histogram", "values": [
                {"labels": {"model": "gpt"},
                 "buckets": {"0.1": 1, "+Inf": 2}, "sum": 0.6,
                 "count": 2}]},
        }
        assert gate.validate_observability(
            self._doc(metrics=metrics)) == []

    def test_live_registry_serving_snapshot_passes(self):
        from paddle_tpu.profiler import metrics as metrics_mod
        from paddle_tpu.inference import serving as srv
        srv._M_QUEUE.set(1, model="gatetest")
        srv._M_TTFT.observe(0.2, model="gatetest")
        srv._M_TPOT.observe(0.01, model="gatetest")
        srv._M_GOODPUT.inc(10, model="gatetest")
        snap = metrics_mod.default_registry().snapshot()
        fams = {k: v for k, v in snap.items() if k.startswith("serving_")}
        assert fams
        assert gate.validate_observability(self._doc(metrics=fams)) == []

    def test_unknown_family_wrong_kind_missing_label_named(self):
        metrics = {
            "serving_bogus_total": {"kind": "counter", "values": []},
            "serving_queue_depth": {"kind": "counter", "values": []},
            "serving_goodput_tokens_total": {"kind": "counter", "values": [
                {"labels": {}, "value": 3}]},
            "serving_tpot_seconds": {"kind": "histogram", "values": [
                {"labels": {"model": "m"},
                 "buckets": {"+Inf": 5}, "sum": 1.0, "count": 4}]},
        }
        blob = "\n".join(gate.validate_observability(
            self._doc(metrics=metrics)))
        assert "serving_bogus_total" in blob and "unknown" in blob
        assert "serving_queue_depth" in blob and "expected gauge" in blob
        assert "missing the 'model' label" in blob
        assert "inconsistent" in blob  # +Inf 5 != count 4


class TestMetricsDumpServingHistograms:
    """tools/metrics_dump.py renders the serving latency histograms with
    estimated percentiles (the satellite's operator view)."""

    def test_serving_histograms_render_quantiles(self, capsys, tmp_path):
        import metrics_dump
        from paddle_tpu.profiler import metrics as metrics_mod
        reg = metrics_mod.MetricsRegistry()
        h = reg.histogram("serving_ttft_seconds",
                          "ttft by model")
        for v in (0.02, 0.04, 0.06, 0.3, 1.2):
            h.observe(v, model="gpt")
        reg.gauge("serving_queue_depth", "queue by model").set(
            3, model="gpt")
        path = tmp_path / "snap.json"
        path.write_text(json.dumps(reg.snapshot()))
        rc = metrics_dump.main([str(path), "--filter", "serving"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "serving_ttft_seconds [histogram]" in out
        assert "count=5" in out and "p50=" in out and "p99=" in out
        assert "serving_queue_depth [gauge]" in out

    def test_driver_bench_wrapper_is_understood(self, capsys):
        """The driver's BENCH_r{N}.json wrapper (bench object under
        `parsed`/`tail`) renders directly — found driving the serving
        satellite: the operator view of a published round's serving
        histograms previously required hand-extracting the tail."""
        import metrics_dump
        path = os.path.join(REPO, "BENCH_r07.json")
        rc = metrics_dump.main([path, "--filter", "serving_ttft"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "serving_ttft_seconds [histogram]" in out
        assert "p99=" in out

    def test_prom_text_roundtrip_for_serving_families(self):
        import metrics_dump
        from paddle_tpu.profiler import metrics as metrics_mod
        reg = metrics_mod.MetricsRegistry()
        reg.histogram("serving_tpot_seconds", "tpot by model").observe(
            0.01, model="gpt")
        snap = metrics_dump.parse_prometheus_text(reg.to_prometheus_text())
        fam = snap["serving_tpot_seconds"]
        assert fam["kind"] == "histogram"
        assert fam["values"][0]["count"] == 1

    def test_serving_summary_view_splits_by_path(self, capsys, tmp_path):
        """--serving: the SLO summary breaks TTFT/TPOT out per decode
        path (fused vs eager) with quantiles."""
        import metrics_dump
        from paddle_tpu.profiler import metrics as metrics_mod
        reg = metrics_mod.MetricsRegistry()
        ttft = reg.histogram("serving_ttft_seconds",
                             "ttft by model and path")
        tpot = reg.histogram("serving_tpot_seconds",
                             "tpot by model and path")
        for v in (0.02, 0.05, 0.4):
            ttft.observe(v, model="gpt", path="fused")
            tpot.observe(v / 10, model="gpt", path="fused")
        ttft.observe(0.9, model="gpt", path="eager")
        reg.gauge("serving_batch_occupancy", "occ by model").set(
            4, model="gpt")
        path = tmp_path / "snap.json"
        path.write_text(json.dumps(reg.snapshot()))
        rc = metrics_dump.main([str(path), "--serving"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "path=fused" in out and "path=eager" in out
        assert "ttft" in out and "tpot" in out
        assert "p50=" in out and "p99=" in out
        assert "serving_batch_occupancy" in out

    def test_serving_summary_view_on_published_bench(self, capsys):
        """--serving degrades gracefully on a pre-v2 artifact (no path
        label) and still summarizes the families."""
        import metrics_dump
        path = os.path.join(REPO, "BENCH_r07.json")
        rc = metrics_dump.main([path, "--serving"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "ttft" in out and "serving summary" in out


class TestObsTailServing:
    """obs_tail --serving: filter + operator rendering of the request
    lifecycle events."""

    @staticmethod
    def _write(tmp_path):
        path = tmp_path / "ev.jsonl"
        recs = [
            {"ts": 10.0, "kind": "retrace", "host": "t0", "name": "mm"},
            {"ts": 11.0, "kind": "serving_admission", "host": "t0",
             "model": "gpt", "request": 7, "slot": 2, "prompt_len": 33,
             "bucket": 64, "queue_wait_s": 0.12, "preemptions": 0,
             "free_pages": 90},
            {"ts": 12.0, "kind": "serving_eviction", "host": "t0",
             "severity": "info", "model": "gpt", "request": 7,
             "reason": "eos", "generated": 18, "free_pages": 95},
            {"ts": 13.0, "kind": "serving_eviction", "host": "t0",
             "severity": "warn", "model": "gpt", "request": 9,
             "reason": "preempted", "generated": 4, "free_pages": 10},
        ]
        with open(path, "w") as f:
            for r in recs:
                f.write(json.dumps(r) + "\n")
        return str(path)

    def test_serving_filters_and_renders(self, tmp_path, capsys):
        import obs_tail
        rc = obs_tail.main([self._write(tmp_path), "--serving"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "retrace" not in out              # filtered to lifecycle
        assert "request 7 -> slot 2" in out
        assert "prompt 33 -> bucket 64" in out
        assert "eos after 18 token(s)" in out
        assert "preempted after 4 token(s)" in out

    def test_serving_composes_with_controller(self, tmp_path, capsys):
        import obs_tail
        path = tmp_path / "ev.jsonl"
        with open(path, "w") as f:
            f.write(json.dumps(
                {"ts": 1.0, "kind": "serving_admission", "host": "t0",
                 "request": 1, "slot": 0, "prompt_len": 4, "bucket": 16,
                 "queue_wait_s": 0.0, "free_pages": 3}) + "\n")
            f.write(json.dumps(
                {"ts": 2.0, "kind": "controller_decision", "host": "s0",
                 "policy": "straggler_skip", "action": "skip",
                 "outcome": "applied", "decision": 4}) + "\n")
        rc = obs_tail.main([str(path), "--serving", "--controller"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "request 1 -> slot 0" in out
        assert "straggler_skip" in out and "decision #4" in out


class TestProgramAuditGate:
    """Per-config `program_audit` blocks and `analysis_*` metric families
    (static program auditor, ISSUE 15): shape/label contracts with named
    violations, plus a live-registry roundtrip through an actual audit."""

    @staticmethod
    def _block(**over):
        block = {"counts": {"info": 0, "low": 1, "medium": 0, "high": 0},
                 "clean_high": True,
                 "reports": [{"name": "GPT#1", "entry": "train_step",
                              "counts": {"info": 0, "low": 1, "medium": 0,
                                         "high": 0},
                              "findings": [{"check": "dtype",
                                            "severity": "low",
                                            "code": "silent-upcast",
                                            "message": "m"}]}]}
        block.update(over)
        return block

    def _doc(self, block):
        return {"configs": {"gpt2": {"tokens_per_sec_chip": 1.0,
                                     "program_audit": block}}}

    def test_valid_block_passes(self):
        assert gate.validate_observability(self._doc(self._block())) == []

    def test_error_block_is_legal(self):
        doc = self._doc({"error": "TypeError: boom"})
        assert gate.validate_observability(doc) == []

    def test_clean_high_contradiction_named(self):
        block = self._block(
            counts={"info": 0, "low": 0, "medium": 0, "high": 2},
            clean_high=True)
        probs = gate.validate_observability(self._doc(block))
        assert any("clean_high" in p and "contradicts" in p for p in probs)

    def test_illegal_check_and_severity_named(self):
        block = self._block()
        block["reports"][0]["findings"][0]["check"] = "vibes"
        block["reports"][0]["findings"][0]["severity"] = "fatal"
        probs = gate.validate_observability(self._doc(block))
        assert any("'vibes'" in p for p in probs)
        assert any("'fatal'" in p for p in probs)

    def test_negative_count_named(self):
        block = self._block(
            counts={"info": 0, "low": -1, "medium": 0, "high": 0})
        probs = gate.validate_observability(self._doc(block))
        assert any("counts.low" in p for p in probs)

    def test_analysis_metrics_roundtrip_from_live_registry(self):
        """An actual audit's emitted metrics validate through the gate."""
        import jax
        import jax.numpy as jnp
        from paddle_tpu.analysis import audit_program
        from paddle_tpu.profiler import metrics as metrics_mod

        def step(params, x):
            return jax.tree_util.tree_map(lambda p: p * 0.9, params), \
                x.sum()

        audit_program(step, ({"w": jnp.ones((512, 1024))},
                             jnp.ones((4,))), name="gate_t", emit=True)
        snap = metrics_mod.default_registry().snapshot()
        metrics = {k: v for k, v in snap.items()
                   if k.startswith("analysis_")}
        assert "analysis_findings_total" in metrics
        doc = {"configs": {}, "observability": {"metrics": metrics}}
        assert gate.validate_observability(doc) == []

    def test_unknown_analysis_family_named(self):
        metrics = {"analysis_mystery_total": {
            "kind": "counter", "help": "x",
            "values": [{"labels": {}, "value": 1}]}}
        doc = {"configs": {}, "observability": {"metrics": metrics}}
        probs = gate.validate_observability(doc)
        assert any("analysis_mystery_total" in p and "unknown" in p
                   for p in probs)

    def test_bad_severity_label_named(self):
        metrics = {"analysis_findings_total": {
            "kind": "counter", "help": "x",
            "values": [{"labels": {"check": "dtype",
                                   "severity": "fatal"}, "value": 1}]}}
        doc = {"configs": {}, "observability": {"metrics": metrics}}
        probs = gate.validate_observability(doc)
        assert any("severity" in p and "'fatal'" in p for p in probs)

    def test_obs_tail_analysis_view(self, tmp_path, capsys):
        import obs_tail
        path = tmp_path / "ev.jsonl"
        with open(path, "w") as f:
            f.write(json.dumps(
                {"ts": 1.0, "kind": "analysis_finding", "host": "t0",
                 "severity": "error", "program": "GPT#1",
                 "entry": "train_step", "check": "donation",
                 "code": "undonated-large-input",
                 "finding_severity": "high", "param": "['w']",
                 "message": "big and dead",
                 "fix_hint": "donate it"}) + "\n")
            f.write(json.dumps(
                {"ts": 2.0, "kind": "retrace", "host": "t0",
                 "site": "eager"}) + "\n")
        rc = obs_tail.main([str(path), "--analysis"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "donation/undonated-large-input" in out
        assert "GPT#1[train_step]" in out and "donate it" in out
        assert "retrace" not in out  # filtered to analysis kinds


class TestReqTraceAndSLOGate:
    """`reqtrace`/`slo` observability blocks + `slo_*` metric families:
    the bench gate's request-trace and SLO-window shape contracts."""

    @staticmethod
    def _trace(**over):
        t = {"trace_id": 5, "rid": 3, "model": "gpt",
             "state": "complete", "finish_reason": "eos",
             "preemptions": 1, "decode_iterations": 6,
             "decode_tokens": 6, "shared_tokens": 0, "e2e_s": 0.5,
             "phases": {"queued": 0.1, "prefill": 0.1, "decode": 0.25,
                        "preempted": 0.05},
             "spans": [
                 {"phase": "queued", "start": 0.0, "end": 0.1},
                 {"phase": "prefill", "start": 0.1, "end": 0.15,
                  "bucket": 16, "prompt_tokens": 9},
                 {"phase": "preempted", "start": 0.15, "end": 0.2},
                 {"phase": "prefill", "start": 0.2, "end": 0.25,
                  "bucket": 16, "prompt_tokens": 11, "requeue": True},
                 {"phase": "decode", "start": 0.25, "end": 0.5,
                  "bucket": 2, "path": "fused", "iters": 6},
                 {"phase": "complete", "start": 0.5, "end": 0.5}]}
        t.update(over)
        return t

    def _reqtrace(self, **over):
        rt = {"enabled": True, "model": "gpt", "live": [],
              "completed": [self._trace()], "ring_size": 256,
              "decode_every": 8}
        rt.update(over)
        return rt

    @staticmethod
    def _slo(**over):
        s = {"enabled": True, "model": "gpt", "window": 512,
             "min_samples": 8, "targets": {"ttft": 0.5},
             "signals": {
                 "ttft": {"count": 10, "p50": 0.1, "p95": 0.2,
                          "p99": 0.3},
                 "tpot": {"count": 0, "p50": None, "p95": None,
                          "p99": None}},
             "breached": {}, "status": "ok",
             "stats": {"breaches": 1, "recoveries": 1,
                       "observations": 40}}
        s.update(over)
        return s

    @staticmethod
    def _doc(reqtrace=None, slo=None, metrics=None):
        obs = {}
        if reqtrace is not None:
            obs["reqtrace"] = reqtrace
        if slo is not None:
            obs["slo"] = slo
        if metrics is not None:
            obs["metrics"] = metrics
        return {"observability": obs}

    def test_valid_blocks_pass(self):
        assert gate.validate_observability(self._doc(
            reqtrace=self._reqtrace(), slo=self._slo())) == []

    def test_live_engine_payloads_validate(self):
        """The gate accepts what the engine actually serves: run a tiny
        engine and pipe its /requests + /slo payloads straight in."""
        import tempfile
        from paddle_tpu.framework import flags as flags_mod
        import paddle_tpu as paddle
        from paddle_tpu.inference.serving import ServingEngine
        from paddle_tpu.models.gpt import GPT, GPTConfig
        cache = os.path.join(tempfile.gettempdir(), "pt_serving_ccache")
        os.makedirs(cache, exist_ok=True)
        flags_mod.set_flags({"FLAGS_compile_cache_dir": cache})
        try:
            paddle.seed(0)
            cfg = GPTConfig(vocab_size=512, max_position_embeddings=128,
                            hidden_size=32, num_layers=2, num_heads=2,
                            dropout=0.0, attn_dropout=0.0)
            m = GPT(cfg)
            m.eval()
            eng = ServingEngine(m, max_batch=2, max_len=48, page_size=8,
                                name="gate_live")
            req = eng.submit(list(range(1, 9)), max_new_tokens=3)
            eng.run_until_idle()
            req.result(timeout=10)
            doc = self._doc(reqtrace=eng.requests_snapshot(),
                            slo=eng.slo.snapshot())
            assert gate.validate_observability(doc) == []
        finally:
            flags_mod.set_flags({"FLAGS_compile_cache_dir": ""})

    def test_bad_trace_ids_phase_and_span_named(self):
        t = self._trace(trace_id=0, e2e_s=float("inf"))
        t["phases"]["warmup"] = 0.1
        t["spans"].append({"phase": "decode", "start": 2.0, "end": 1.0})
        probs = gate.validate_observability(self._doc(
            reqtrace=self._reqtrace(completed=[t])))
        text = "\n".join(probs)
        assert "trace_id" in text
        assert "e2e_s" in text
        assert "warmup" in text and "unknown phase" in text
        assert "end 1.0 < start 2.0" in text

    def test_non_monotone_quantiles_named(self):
        s = self._slo()
        s["signals"]["ttft"]["p95"] = 0.05  # p50 0.1 > p95
        probs = gate.validate_observability(self._doc(slo=s))
        assert any("not monotone" in p for p in probs)

    def test_nonfinite_quantile_and_negative_stats_named(self):
        s = self._slo()
        s["signals"]["ttft"]["p99"] = float("nan")
        s["stats"]["breaches"] = -1
        probs = gate.validate_observability(self._doc(slo=s))
        text = "\n".join(probs)
        assert "finite non-negative" in text
        assert "stats.breaches" in text

    def test_unknown_slo_family_and_wrong_kind_named(self):
        metrics = {
            "slo_breach_count": {"kind": "counter", "values": []},
            "slo_breached": {"kind": "counter", "values": []},
            "slo_breaches_total": {
                "kind": "counter",
                "values": [{"labels": {"model": "gpt"}, "value": 1}]},
        }
        probs = gate.validate_observability(self._doc(metrics=metrics))
        text = "\n".join(probs)
        assert "slo_breach_count: unknown slo family" in text
        assert "slo_breached: kind" in text and "expected gauge" in text
        assert "missing the 'signal' label" in text

    def test_error_blocks_report_themselves(self):
        assert gate.validate_observability(self._doc(
            reqtrace={"error": "probe failed"},
            slo={"error": "probe failed"})) == []

    def test_queue_wait_percentiles_in_decode_block(self):
        cfg = {"tokens_per_sec_chip": 50.0,
               "serving": {"ttft_s": {"p50": 0.1, "p99": 0.2},
                           "tpot_s": {"p50": 0.01, "p99": 0.02},
                           "queue_wait_s": {"p50": 0.05, "p99": 0.4}}}
        assert gate.validate_observability(
            {"configs": {"gpt2_decode": cfg}}) == []
        cfg["serving"]["queue_wait_s"]["p99"] = -0.4
        probs = gate.validate_observability(
            {"configs": {"gpt2_decode": cfg}})
        assert any("queue_wait_s" in p for p in probs)


class TestObsTailSLO:
    """--slo: the serving SLO plane view (breach excursions + completed
    request traces) with kind-filter composition."""

    @staticmethod
    def _breach_event():
        return {"ts": 1722700000.0, "kind": "slo_breach", "host": "t0",
                "severity": "warn", "model": "gpt", "signal": "ttft",
                "quantile": "p99", "value": 0.82, "target": 0.5,
                "window": 24}

    @staticmethod
    def _trace_event():
        return {"ts": 1722700001.0, "kind": "request_trace",
                "host": "t0", "severity": "info", "trace_id": 9,
                "rid": 4, "model": "gpt", "finish_reason": "eos",
                "preemptions": 1, "decode_tokens": 16, "e2e_s": 1.25,
                "phases": {"queued": 0.2, "prefill": 0.15,
                           "decode": 0.85, "preempted": 0.05}}

    def test_slo_filters_and_renders(self, tmp_path, capsys):
        import obs_tail
        path = tmp_path / "ev.jsonl"
        with open(path, "w") as f:
            f.write(json.dumps(self._breach_event()) + "\n")
            f.write(json.dumps(self._trace_event()) + "\n")
            f.write(json.dumps({"ts": 1.0, "kind": "retrace",
                                "host": "t0"}) + "\n")
        rc = obs_tail.main([str(path), "--slo"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "ttft p99=820.0ms breached target 500.0ms" in out
        assert "over 24 sample(s)" in out
        assert "re-arms on recovery" in out
        assert "trace 9 request 4 eos e2e 1250.0ms" in out
        assert "preemptions=1" in out
        assert "decode=850.0ms" in out
        assert "retrace" not in out  # --slo implies the kind filter

    def test_slo_composes_with_explicit_kind(self, tmp_path, capsys):
        import obs_tail
        path = tmp_path / "ev.jsonl"
        with open(path, "w") as f:
            f.write(json.dumps(self._breach_event()) + "\n")
            f.write(json.dumps({"ts": 2.0, "kind": "retrace",
                                "host": "t0"}) + "\n")
            f.write(json.dumps({"ts": 3.0, "kind": "xla_compile",
                                "host": "t0"}) + "\n")
        rc = obs_tail.main([str(path), "--slo", "--kind", "retrace"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "slo_breach" in out and "retrace" in out
        assert "xla_compile" not in out


class TestMetricsDumpRequests:
    """--requests: per-request phase breakdowns from a bench artifact,
    a /requests payload file, or the live endpoint."""

    @staticmethod
    def _payload():
        return {
            "enabled": True, "model": "gpt", "ring_size": 256,
            "decode_every": 8,
            "live": [{"trace_id": 7, "rid": 5, "state": "running",
                      "preemptions": 0, "decode_tokens": 3,
                      "phases": {"queued": 0.01, "prefill": 0.04}}],
            "completed": [{"trace_id": 6, "rid": 4,
                           "finish_reason": "eos", "preemptions": 2,
                           "decode_tokens": 8, "e2e_s": 0.9,
                           "phases": {"queued": 0.1, "prefill": 0.2,
                                      "decode": 0.55,
                                      "preempted": 0.05}}],
            "introspection": [
                {"iteration": 41, "active": 3, "lanes": 4,
                 "occupancy": 3, "queue_depth": 2, "free_pages": 11,
                 "used_pages": 20, "cow_shared_pages": 5,
                 "decode_mode": "fused"}],
        }

    def test_requests_view_from_payload_file(self, tmp_path, capsys):
        import metrics_dump
        path = tmp_path / "requests.json"
        path.write_text(json.dumps(self._payload()))
        rc = metrics_dump.main([str(path), "--requests"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "request traces (model gpt, tracer on)" in out
        assert "LIVE trace    7 request    5" in out
        assert "DONE trace    6 request    4 eos" in out
        assert "preempt=2" in out and "e2e=900.0ms" in out
        assert "decode=550.0ms" in out
        assert "pages free/used/shared=11/20/5" in out

    def test_requests_view_from_bench_observability(self, tmp_path,
                                                    capsys):
        import metrics_dump
        path = tmp_path / "bench.json"
        path.write_text(json.dumps(
            {"observability": {"reqtrace": self._payload()}}))
        rc = metrics_dump.main([str(path), "--requests"])
        out = capsys.readouterr().out
        assert rc == 0 and "DONE trace    6" in out

    def test_requests_view_without_traces_reports_it(self, tmp_path,
                                                     capsys):
        import metrics_dump
        path = tmp_path / "bench.json"
        path.write_text(json.dumps(
            {"observability": {"reqtrace": {
                "enabled": True, "model": "gpt", "live": [],
                "completed": []}}}))
        rc = metrics_dump.main([str(path), "--requests"])
        assert rc == 0
        assert "(no traces recorded)" in capsys.readouterr().out

    def test_requests_view_from_live_endpoint(self, capsys):
        from paddle_tpu.profiler.server import ObservabilityServer
        import metrics_dump
        import urllib.request  # noqa: F401  (exercised inside the tool)
        payload = self._payload()

        class _Stub:
            @staticmethod
            def requests_snapshot(n=50):
                return payload
        srv = ObservabilityServer()
        srv.start(0)
        try:
            import paddle_tpu.profiler.server as server_mod
            orig = server_mod.ObservabilityServer._engine
            server_mod.ObservabilityServer._engine = staticmethod(
                lambda name=None: _Stub())
            try:
                rc = metrics_dump.main(
                    [f"http://127.0.0.1:{srv.port}/requests",
                     "--requests"])
            finally:
                server_mod.ObservabilityServer._engine = orig
        finally:
            srv.stop()
        out = capsys.readouterr().out
        assert rc == 0 and "DONE trace    6" in out
