"""Tier-1 static-analysis gate (tools/program_audit.py): the shipped
GPT-2 / ResNet-50 / BERT TrainSteps and the gpt2_decode serving path
must audit clean of high-severity findings, and the gate must actually
gate — a seeded hazard flips the exit code. The per-check seeded-hazard
fixtures (each check fires, naming the right param/layer) live in
tests/test_analysis.py; this module drives the real CLI end to end.
"""
import json
import os
import sys

import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))
import program_audit  # noqa: E402


class TestGate:
    @pytest.mark.slow  # full-zoo trace+lower runs >70s on the 1-core gate
    def test_shipped_models_audit_high_clean(self, capsys):
        """THE acceptance gate: every headline program — the real
        architectures, CPU-feasible batch shapes — reports zero
        high-severity findings, exit 0.

        Slow tier since the serving audit grew the fused decode step
        (all layers + sampling in one executable); tier-1 keeps the
        serving half of this gate fast via
        test_serving_v2.py::test_audit_covers_fused_decode_and_prefill
        and the lint-mode sibling below."""
        rc = program_audit.main(["--fail-on=high"])
        out = capsys.readouterr().out
        assert rc == 0, f"gate failed:\n{out}"
        assert "0 finding(s) at/above threshold" in out
        # all four programs actually ran (decode audits two executables)
        for frag in ("GPT#", "ResNet#", "BertCls#", "serving_decode",
                     "serving_prefill"):
            assert frag in out, f"{frag} missing from gate output:\n{out}"

    def test_seeded_hazard_flips_the_gate(self, monkeypatch, capsys):
        """The gate gates: a model whose program carries an undonated
        large dead buffer exits 1 under --fail-on=high."""
        import jax.numpy as jnp
        from paddle_tpu.analysis import audit_program

        def seeded(scale):
            import jax

            def step(params, x):
                return jax.tree_util.tree_map(lambda p: p * 0.9,
                                              params), x.sum()

            params = {"w": jnp.ones((512, 1024), jnp.float32)}
            return [audit_program(step, (params, jnp.ones((4,))),
                                  name="seeded", emit=False)]

        monkeypatch.setitem(program_audit.MODELS, "seeded", seeded)
        rc = program_audit.main(["--model", "seeded", "--fail-on=high"])
        out = capsys.readouterr().out
        assert rc == 1
        assert "undonated-large-input" in out

    def test_broken_builder_exits_2(self, monkeypatch, capsys):
        def broken(scale):
            raise RuntimeError("cannot build")

        monkeypatch.setitem(program_audit.MODELS, "broken", broken)
        rc = program_audit.main(["--model", "broken"])
        err = capsys.readouterr().err
        assert rc == 2 and "cannot build" in err

    def test_json_output_shape(self, capsys):
        rc = program_audit.main(["--model", "gpt2_decode", "--json",
                                 "--scale", "tiny", "--fail-on=high"])
        doc = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert doc["fail_on"] == "high" and doc["gated_findings"] == 0
        assert doc["errors"] == []
        entries = {r["entry"] for r in doc["reports"]}
        assert entries == {"serving_decode", "serving_prefill"}
        for r in doc["reports"]:
            assert set(r["counts"]) == {"info", "low", "medium", "high"}

    def test_lint_mode_is_clean(self, capsys):
        rc = program_audit.main(["--lint"])
        out = capsys.readouterr().out
        assert rc == 0
        for lint in ("env-knob-parses", "fault-sites", "threads",
                     "event-kinds", "env-knob-docs"):
            assert f"[{lint}] clean" in out
