"""Fault-tolerance runtime tests: retry policy, fault injector, checkpoint
corruption recovery, CheckpointManager GC/preemption, store retry, PS
structured errors, and the elastic membership-slot release regression.

Reference inspiration: the reference proves recovery via
`test_auto_checkpoint.py` (resume correctness) and the fleet elastic
manager tests; corruption/chaos coverage is TPU-side new (preemptible pods
make failure the common case, not the exception).
"""
import os
import signal
import struct
import sys
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import fault
from paddle_tpu.distributed import checkpoint as dist_ckpt
from paddle_tpu.distributed.checkpoint import (CheckpointCorruptError,
                                               CheckpointManager)
from paddle_tpu.distributed.store import TCPStore
from paddle_tpu.profiler import metrics as metrics_mod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_injector():
    fault.reset()
    yield
    fault.reset()


# ---------------------------------------------------------------------------
# RetryPolicy
# ---------------------------------------------------------------------------
class TestRetryPolicy:
    def test_succeeds_after_transient_failures(self):
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise RuntimeError("transient")
            return "ok"

        pol = fault.RetryPolicy(max_attempts=4, base_delay=0.001)
        assert pol.call(flaky, op="t.flaky") == "ok"
        assert len(calls) == 3

    def test_exhaustion_raises_structured_error(self):
        pol = fault.RetryPolicy(max_attempts=2, base_delay=0.001)

        def always():
            raise ValueError("nope")

        with pytest.raises(fault.RetryExhaustedError) as ei:
            pol.call(always, op="t.always")
        assert ei.value.op == "t.always"
        assert ei.value.attempts == 2
        assert isinstance(ei.value.last, ValueError)
        assert "nope" in str(ei.value)

    def test_backoff_schedule_deterministic_and_bounded(self):
        a = fault.RetryPolicy(max_attempts=8, base_delay=0.1, max_delay=0.5,
                              jitter=0.25, seed=7)
        b = fault.RetryPolicy(max_attempts=8, base_delay=0.1, max_delay=0.5,
                              jitter=0.25, seed=7)
        da = [a.delay(i) for i in range(6)]
        db = [b.delay(i) for i in range(6)]
        assert da == db  # same seed -> identical schedule
        for i, d in enumerate(da):
            base = min(0.5, 0.1 * 2 ** i)
            assert base <= d <= base * 1.25

    def test_non_retryable_exception_propagates(self):
        pol = fault.RetryPolicy(max_attempts=3, base_delay=0.001,
                                retry_on=(OSError,))
        with pytest.raises(KeyError):
            pol.call(lambda: (_ for _ in ()).throw(KeyError("x")), op="t.kerr")

    def test_attempt_timeout_retries_slow_attempts(self):
        calls = []

        def slow_then_fast():
            calls.append(1)
            if len(calls) == 1:
                time.sleep(0.5)
            return len(calls)

        pol = fault.RetryPolicy(max_attempts=3, base_delay=0.001,
                                attempt_timeout=0.1)
        assert pol.call(slow_then_fast, op="t.slow") == 2

    def test_decorator_form(self):
        calls = []

        @fault.retryable("t.deco", fault.RetryPolicy(max_attempts=3,
                                                     base_delay=0.001))
        def fn():
            calls.append(1)
            if len(calls) < 2:
                raise RuntimeError("once")
            return 5

        assert fn() == 5

    def test_metrics_recorded(self):
        reg = metrics_mod.default_registry()
        before = reg.get("retry_attempts_total").value(op="t.metrics")
        pol = fault.RetryPolicy(max_attempts=3, base_delay=0.001)
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 2:
                raise RuntimeError("x")

        pol.call(flaky, op="t.metrics")
        assert reg.get("retry_attempts_total").value(op="t.metrics") == \
            before + 1
        assert reg.get("retry_recovered_total").value(op="t.metrics") >= 1

    def test_from_env(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_XYZ_RETRIES", "7")
        monkeypatch.setenv("PADDLE_TPU_XYZ_BACKOFF", "0.25")
        pol = fault.RetryPolicy.from_env("xyz", max_attempts=2)
        assert pol.max_attempts == 7
        assert pol.base_delay == 0.25


# ---------------------------------------------------------------------------
# FaultInjector
# ---------------------------------------------------------------------------
class TestFaultInjector:
    def test_unarmed_site_is_noop(self):
        fault.site("nothing.armed")  # no raise

    def test_count_and_start_window(self):
        inj = fault.FaultInjector(spec="")
        inj.configure("s.op", times=2, start=3)
        fired = 0
        for _ in range(6):
            try:
                inj.site("s.op")
            except fault.InjectedFault:
                fired += 1
        assert fired == 2
        assert inj.fired("s.op") == 2

    def test_spec_parsing_kinds(self):
        inj = fault.FaultInjector(
            spec="a.b=1; c.d=2@3:timeout ; e.f=1:oserror")
        with pytest.raises(fault.InjectedFault):
            inj.site("a.b")
        inj.site("a.b")  # only the first occurrence faults
        inj.site("c.d")
        inj.site("c.d")
        with pytest.raises(fault.InjectedTimeout):
            inj.site("c.d")  # 3rd
        with pytest.raises(fault.InjectedTimeout):
            inj.site("c.d")  # 4th
        inj.site("c.d")  # 5th clean
        with pytest.raises(fault.InjectedIOError):
            inj.site("e.f")

    def test_malformed_clause_warns_not_crashes(self):
        with pytest.warns(UserWarning, match="malformed clause"):
            inj = fault.FaultInjector(spec="good.site=1;bad_clause;also=bad!x")
        with pytest.raises(fault.InjectedFault):
            inj.site("good.site")

    def test_env_reload(self, monkeypatch):
        monkeypatch.setenv(fault.SPEC_ENV, "env.site=1")
        fault.reload_spec()
        with pytest.raises(fault.InjectedFault):
            fault.site("env.site")
        fault.site("env.site")  # exhausted
        monkeypatch.delenv(fault.SPEC_ENV)
        fault.reload_spec()
        fault.site("env.site")  # disarmed

    def test_injection_metric(self):
        reg = metrics_mod.default_registry()
        before = reg.get("fault_injected_total").value(site="m.site",
                                                       kind="error")
        fault.configure("m.site", times=1)
        with pytest.raises(fault.InjectedFault):
            fault.site("m.site")
        assert reg.get("fault_injected_total").value(
            site="m.site", kind="error") == before + 1


# ---------------------------------------------------------------------------
# Checkpoint corruption recovery
# ---------------------------------------------------------------------------
class TestCheckpointCorruption:
    def _save(self, tmp_path, step, value):
        p = str(tmp_path / f"ckpt_{step}")
        dist_ckpt.save({"w": np.full(4, value, np.float32), "step": step}, p)
        return p

    def test_truncated_raises_clear_error(self, tmp_path):
        p = self._save(tmp_path, 1, 1.0)
        raw = open(p, "rb").read()
        open(p, "wb").write(raw[:len(raw) // 2])
        with pytest.raises(CheckpointCorruptError, match="truncated"):
            dist_ckpt.load(p)

    def test_bitflip_raises_crc_error(self, tmp_path):
        p = self._save(tmp_path, 1, 1.0)
        raw = bytearray(open(p, "rb").read())
        raw[-3] ^= 0xFF  # flip a payload byte
        open(p, "wb").write(bytes(raw))
        ok, reason = dist_ckpt.verify(p)
        assert not ok and "CRC32" in reason
        with pytest.raises(CheckpointCorruptError, match="CRC32"):
            dist_ckpt.load(p)

    def test_zero_length_file(self, tmp_path):
        p = str(tmp_path / "ckpt_1")
        open(p, "wb").close()
        ok, reason = dist_ckpt.verify(p)
        assert not ok
        with pytest.raises(CheckpointCorruptError):
            dist_ckpt.load(p)

    def test_latest_valid_skips_corrupt(self, tmp_path):
        self._save(tmp_path, 1, 1.0)
        p2 = self._save(tmp_path, 2, 2.0)
        p3 = self._save(tmp_path, 3, 3.0)
        open(p3, "wb").write(open(p3, "rb").read()[:-4])  # torn newest
        open(p2, "wb").close()                            # zeroed middle
        with pytest.warns(UserWarning, match="corrupt"):
            best = dist_ckpt.latest_valid(str(tmp_path))
        assert best.endswith("ckpt_1")
        assert float(np.asarray(dist_ckpt.load(best)["w"])[0]) == 1.0

    def test_latest_valid_counts_skips_in_metrics(self, tmp_path):
        reg = metrics_mod.default_registry()
        before = reg.get("checkpoint_corrupt_skipped_total").total()
        p = self._save(tmp_path, 5, 5.0)
        open(p, "wb").write(b"PTCKPT01garbage")
        with pytest.warns(UserWarning):
            assert dist_ckpt.latest_valid(str(tmp_path)) is None
        assert reg.get("checkpoint_corrupt_skipped_total").total() > before

    def test_legacy_plain_pickle_still_loads(self, tmp_path):
        import pickle
        p = str(tmp_path / "ckpt_9")
        with open(p, "wb") as f:
            pickle.dump({"state": {"x": np.ones(2, np.float32)}, "specs": {},
                         "version": 1}, f)
        assert dist_ckpt.verify(p)[0]
        out = dist_ckpt.load(p)
        np.testing.assert_array_equal(np.asarray(out["x"]), np.ones(2))

    def test_tmp_orphans_ignored_by_latest_and_gcd(self, tmp_path):
        self._save(tmp_path, 1, 1.0)
        orphan = tmp_path / "ckpt_7.tmp.abc123"
        orphan.write_bytes(b"partial write from a crashed host")
        assert dist_ckpt.latest(str(tmp_path)).endswith("ckpt_1")
        assert dist_ckpt.latest_valid(str(tmp_path)).endswith("ckpt_1")
        removed = dist_ckpt.cleanup_tmp(str(tmp_path))
        assert removed == 1 and not orphan.exists()


class TestCheckpointManager:
    def test_keep_last_n_gc(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), keep_last_n=3)
        for s in range(7):
            mgr.save({"s": s}, step=s)
        assert mgr.steps() == [6, 5, 4]
        state, step = mgr.load_latest()
        assert step == 6 and state["s"] == 6

    def test_init_cleans_orphaned_tmp(self, tmp_path):
        (tmp_path / "ckpt_3.tmp.xyz").write_bytes(b"torn")
        CheckpointManager(str(tmp_path))
        assert not (tmp_path / "ckpt_3.tmp.xyz").exists()

    def test_load_latest_falls_back_over_corruption(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), keep_last_n=5)
        mgr.save({"s": 1}, step=1)
        mgr.save({"s": 2}, step=2)
        p2 = mgr.path_for(2)
        open(p2, "wb").write(open(p2, "rb").read()[:-1])
        with pytest.warns(UserWarning, match="corrupt"):
            state, step = mgr.load_latest()
        assert step == 1 and state["s"] == 1

    def test_empty_dir_returns_none(self, tmp_path):
        assert CheckpointManager(str(tmp_path)).load_latest() is None

    def test_async_manager_waits_before_load(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), async_save=True)
        mgr.save({"s": 41}, step=41)
        state, step = mgr.load_latest()
        assert step == 41 and state["s"] == 41

    def test_preemption_handler_saves_once_then_exits(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        captured = {"n": 0}

        def state_fn():
            captured["n"] += 1
            return {"final": True, "n": captured["n"]}

        assert mgr.install_preemption_handler(state_fn, step_fn=lambda: 99)
        try:
            with pytest.raises(SystemExit) as ei:
                os.kill(os.getpid(), signal.SIGTERM)
                # the handler runs at the next bytecode boundary
                for _ in range(100):
                    time.sleep(0.01)
            assert ei.value.code == 143
        finally:
            mgr.uninstall_preemption_handler()
        assert captured["n"] == 1
        state, step = mgr.load_latest()
        assert step == 99 and state["final"] is True

    def test_reshard_fallback_warns_and_counts(self, tmp_path):
        # a 3-wide dim cannot split over the 8-device axis: restore must
        # fall back to replication LOUDLY (warning + counter), not silently
        import jax
        from jax.sharding import Mesh
        mesh = Mesh(np.array(jax.devices()[:8]), axis_names=("dp",))
        reg = metrics_mod.default_registry()
        before = reg.get("checkpoint_reshard_fallback_total").total()
        arr = np.arange(24, dtype=np.float32).reshape(8, 3)
        with pytest.warns(UserWarning, match="could not apply saved sharding"):
            out = dist_ckpt._apply_shardings({"x": arr},
                                            {"/x": (None, "dp")}, mesh)
        np.testing.assert_array_equal(np.asarray(out["x"]), arr)
        assert reg.get("checkpoint_reshard_fallback_total").total() > before


# ---------------------------------------------------------------------------
# Store retry under injected faults
# ---------------------------------------------------------------------------
class TestStoreRetry:
    def test_get_recovers_from_injected_fault(self):
        store = TCPStore("127.0.0.1", 0, is_master=True,
                         retry=fault.RetryPolicy(max_attempts=3,
                                                 base_delay=0.001))
        try:
            store.set("k", "v")
            reg = metrics_mod.default_registry()
            before = reg.get("retry_attempts_total").value(op="store.get")
            fault.configure("store.get", times=1)
            assert store.get("k") == b"v"  # first attempt faulted, retried
            assert reg.get("retry_attempts_total").value(op="store.get") == \
                before + 1
        finally:
            store.stop()

    def test_exhaustion_surfaces_retry_error(self):
        store = TCPStore("127.0.0.1", 0, is_master=True,
                         retry=fault.RetryPolicy(max_attempts=2,
                                                 base_delay=0.001))
        try:
            store.set("k", "v")
            fault.configure("store.get", times=10)
            with pytest.raises(fault.RetryExhaustedError, match="store.get"):
                store.get("k")
        finally:
            fault.reset()
            store.stop()

    def test_add_retries(self):
        store = TCPStore("127.0.0.1", 0, is_master=True,
                         retry=fault.RetryPolicy(max_attempts=3,
                                                 base_delay=0.001))
        try:
            fault.configure("store.add", times=1)
            assert store.add("ctr", 2) == 2
            assert store.add("ctr", 3) == 5
        finally:
            store.stop()


# ---------------------------------------------------------------------------
# PS client structured error
# ---------------------------------------------------------------------------
class TestPSClientErrors:
    def test_exhausted_rpc_names_endpoint(self):
        from paddle_tpu.distributed.ps.client import (PSClient, PSRequestError,
                                                      TableConfig)
        from paddle_tpu.distributed.ps.server import PSServer
        srv = PSServer(port=0)
        ep = f"127.0.0.1:{srv.port}"
        cli = PSClient([ep], retry=fault.RetryPolicy(max_attempts=2,
                                                     base_delay=0.001))
        cli.create_table(TableConfig(table_id=1, kind="dense", dense_size=4))
        cli.set_dense(1, np.zeros(4, np.float32))
        fault.configure("ps.pull_dense", times=10)
        with pytest.raises(PSRequestError) as ei:
            cli.pull_dense(1)
        assert ei.value.endpoint == ep
        assert ei.value.table_id == 1
        assert ei.value.op == "pull_dense"
        assert ep in str(ei.value)
        fault.reset()
        np.testing.assert_array_equal(cli.pull_dense(1),
                                      np.zeros(4, np.float32))
        srv.stop()

    def test_transient_rpc_fault_recovers(self):
        from paddle_tpu.distributed.ps.client import PSClient, TableConfig
        from paddle_tpu.distributed.ps.server import PSServer
        srv = PSServer(port=0)
        cli = PSClient([f"127.0.0.1:{srv.port}"],
                       retry=fault.RetryPolicy(max_attempts=3,
                                               base_delay=0.001))
        cli.create_table(TableConfig(table_id=1, kind="dense", dense_size=4))
        cli.set_dense(1, np.arange(4, dtype=np.float32))
        fault.configure("ps.pull_dense", times=1)
        np.testing.assert_array_equal(cli.pull_dense(1),
                                      np.arange(4, dtype=np.float32))
        srv.stop()


# ---------------------------------------------------------------------------
# Elastic membership slot release (regression)
# ---------------------------------------------------------------------------
class TestElasticSlotRelease:
    def test_clean_exit_releases_and_reuses_slot(self):
        import struct as _struct
        from paddle_tpu.distributed.fleet.elastic import ElasticManager
        master = TCPStore("127.0.0.1", 0, is_master=True)
        try:
            def member_count():
                # membership keys are namespaced by fleet size (np=1 here)
                # so a relaunch with a changed --np starts a fresh fleet
                return _struct.unpack("<q",
                                      master.get("fleet1/member_count"))[0]

            # restart cycle: join/exit 3 times — the slot must be reused,
            # not leaked (member_count grew without bound before the fix)
            for i in range(3):
                m = ElasticManager(host_id=f"gen{i}", ttl=1.0, np=1,
                                   store=master)
                m.join()
                assert m.alive_members() == [f"gen{i}"]
                m.exit()
                assert m.alive_members() == []
            assert member_count() == 1
            # tombstoned slots never resurface as members
            m = ElasticManager(host_id="final", ttl=1.0, np=1, store=master)
            m.join()
            assert m.alive_members() == ["final"]
            assert member_count() == 1
            m.exit()
        finally:
            master.stop()


# ---------------------------------------------------------------------------
# ckpt_inspect tool
# ---------------------------------------------------------------------------
class TestCkptInspect:
    def test_reports_ok_and_corrupt(self, tmp_path, capsys):
        sys.path.insert(0, os.path.join(REPO, "tools"))
        import ckpt_inspect
        good = str(tmp_path / "ckpt_1")
        dist_ckpt.save({"w": np.ones((2, 3), np.float32), "epoch": 4}, good)
        bad = str(tmp_path / "ckpt_2")
        open(bad, "wb").write(open(good, "rb").read()[:-9])
        rc = ckpt_inspect.main([good, bad])
        out = capsys.readouterr().out
        assert rc == 1  # corrupt file present
        assert "status: OK" in out
        assert "CORRUPT" in out and "truncated" in out
        assert "/w" in out and "(2, 3)" in out
        assert "/epoch = 4" in out

    def test_inspect_file_fields(self, tmp_path):
        sys.path.insert(0, os.path.join(REPO, "tools"))
        import ckpt_inspect
        p = str(tmp_path / "ckpt_5")
        dist_ckpt.save({"a": np.zeros(3, np.float32)}, p)
        info = ckpt_inspect.inspect_file(p)
        assert info["status"] == "ok"
        assert info["crc_stored"] == info["crc_computed"]
        assert info["arrays"][0][0] == "/a"


# ---------------------------------------------------------------------------
# Retry-aware collective init (ROADMAP open item, PR 4 satellite)
# ---------------------------------------------------------------------------
class TestCollectiveInitRetry:
    def test_rendezvous_retries_under_store_policy(self, monkeypatch):
        """A transient coordinator hiccup during init_parallel_env's
        rendezvous is retried under the STORE policy via the named
        `parallel.init` fault site, instead of killing the job."""
        import jax
        from paddle_tpu.distributed import parallel as par

        calls = []
        monkeypatch.setattr(jax.distributed, "initialize",
                            lambda **kw: calls.append(kw))
        monkeypatch.setenv("PADDLE_TRAINERS_NUM", "2")
        monkeypatch.setenv("PADDLE_TRAINER_ENDPOINTS",
                           "127.0.0.1:6170,127.0.0.1:6171")
        monkeypatch.setenv("PADDLE_TRAINER_ID", "0")
        monkeypatch.setattr(par, "_parallel_env_initialized", False)
        fault.configure("parallel.init", times=1)
        rec_before = metrics_mod.default_registry().get(
            "retry_recovered_total").value(op="parallel.init")
        par.init_parallel_env()
        assert calls == [{"coordinator_address": "127.0.0.1:6170",
                          "num_processes": 2, "process_id": 0}]
        assert fault.default_injector().fired("parallel.init") == 1
        rec_after = metrics_mod.default_registry().get(
            "retry_recovered_total").value(op="parallel.init")
        assert rec_after == rec_before + 1
        # the monkeypatched module global is restored by pytest; the env
        # stays usable either way because init is idempotent

    def test_rendezvous_exhaustion_raises(self, monkeypatch):
        import jax
        from paddle_tpu.distributed import parallel as par
        from paddle_tpu.fault import RetryExhaustedError

        monkeypatch.setattr(jax.distributed, "initialize",
                            lambda **kw: None)
        monkeypatch.setenv("PADDLE_TPU_STORE_RETRIES", "2")
        monkeypatch.setenv("PADDLE_TPU_STORE_BACKOFF", "0.001")
        fault.configure("parallel.init", times=5)
        with pytest.raises(RetryExhaustedError):
            par._rendezvous_initialize({"coordinator_address": "x:1",
                                        "num_processes": 2,
                                        "process_id": 0})
        assert fault.default_injector().fired("parallel.init") == 2


# ---------------------------------------------------------------------------
# collective timeout detection (fault site + deadline watchdog)
# ---------------------------------------------------------------------------
class TestCollectiveTimeout:
    """A hung eager collective (dead peer mid-rendezvous) must surface as a
    typed CollectiveTimeoutError naming the group and rank — never a silent
    hang — and every detection lands in collective_timeout_total."""

    def setup_method(self, _):
        import paddle_tpu.distributed as dist
        from paddle_tpu.distributed.topology import (HybridCommunicateGroup,
                                                     build_mesh)
        mesh = build_mesh({"dp": 8})
        dist.set_hybrid_communicate_group(HybridCommunicateGroup(mesh=mesh))
        dist.destroy_process_group()
        self.group = dist.new_group(axis_name="dp")

    def teardown_method(self, _):
        import paddle_tpu.distributed as dist
        dist.set_hybrid_communicate_group(None)
        dist.destroy_process_group()

    @staticmethod
    def _timeouts(**labels):
        m = metrics_mod.default_registry().get("collective_timeout_total")
        if m is None:
            return 0.0
        return sum(v["value"] for v in m.snapshot()["values"]
                   if all(v["labels"].get(k) == lv
                          for k, lv in labels.items()))

    def test_injected_fault_raises_typed_error(self):
        import paddle_tpu.distributed as dist
        from paddle_tpu.distributed.collective import CollectiveTimeoutError
        fault.configure("collective.timeout", times=1, kind="timeout")
        x = paddle.to_tensor(np.ones((4,), np.float32))
        t0 = self._timeouts(kind="all_reduce")
        with pytest.raises(CollectiveTimeoutError) as ei:
            dist.all_reduce(x, group=self.group)
        assert ei.value.kind == "all_reduce"
        assert ei.value.group_name == self.group.name
        assert "rank" in str(ei.value) and self.group.name in str(ei.value)
        assert self._timeouts(kind="all_reduce") == t0 + 1
        # injector exhausted: the very next collective completes normally
        y = paddle.to_tensor(np.ones((4,), np.float32))
        dist.all_reduce(y, group=self.group)
        np.testing.assert_allclose(y.numpy(), np.full(4, 8.0))

    def test_bare_spec_default_kind_still_types_and_meters(self):
        """`collective.timeout=1` (no :kind, so the grammar's default
        kind=error) must coerce to the same typed timeout — every injected
        kind at this site models a hung collective, and an escaping raw
        InjectedFault would skip collective_timeout_total."""
        import paddle_tpu.distributed as dist
        from paddle_tpu.distributed.collective import CollectiveTimeoutError
        fault.configure("collective.timeout", times=1)  # default kind
        x = paddle.to_tensor(np.ones((4,), np.float32))
        t0 = self._timeouts(kind="all_reduce")
        with pytest.raises(CollectiveTimeoutError):
            dist.all_reduce(x, group=self.group)
        assert self._timeouts(kind="all_reduce") == t0 + 1

    def test_armable_via_env_spec(self, monkeypatch):
        import paddle_tpu.distributed as dist
        from paddle_tpu.distributed.collective import CollectiveTimeoutError
        monkeypatch.setenv(fault.SPEC_ENV, "collective.timeout=1:timeout")
        fault.reload_spec()
        x = paddle.to_tensor(np.ones((4,), np.float32))
        with pytest.raises(CollectiveTimeoutError):
            dist.all_reduce(x, group=self.group)
        inj = metrics_mod.default_registry().get("fault_injected_total")
        assert sum(v["value"] for v in inj.snapshot()["values"]
                   if v["labels"].get("site") == "collective.timeout") >= 1

    def test_deadline_raises_instead_of_hanging(self, monkeypatch):
        from paddle_tpu.distributed.collective import (CollectiveTimeoutError,
                                                       _guard_collective)
        monkeypatch.setenv("PADDLE_TPU_COLLECTIVE_TIMEOUT", "0.1")
        t0 = self._timeouts(kind="probe")
        start = time.time()
        with pytest.raises(CollectiveTimeoutError, match="did not complete"):
            _guard_collective("probe", self.group,
                              lambda: time.sleep(30))
        assert time.time() - start < 10  # bounded, nowhere near the sleep
        assert self._timeouts(kind="probe") == t0 + 1

    def test_deadline_passes_fast_collectives(self, monkeypatch):
        import paddle_tpu.distributed as dist
        monkeypatch.setenv("PADDLE_TPU_COLLECTIVE_TIMEOUT", "60")
        x = paddle.to_tensor(np.ones((4,), np.float32))
        dist.all_reduce(x, group=self.group)
        np.testing.assert_allclose(x.numpy(), np.full(4, 8.0))

    def test_thunk_error_propagates_unwrapped(self, monkeypatch):
        from paddle_tpu.distributed.collective import _guard_collective
        monkeypatch.setenv("PADDLE_TPU_COLLECTIVE_TIMEOUT", "30")

        def boom():
            raise ValueError("not a timeout")

        with pytest.raises(ValueError, match="not a timeout"):
            _guard_collective("probe", self.group, boom)


# ---------------------------------------------------------------------------
# device OOM detection at the eager allocator boundary
# ---------------------------------------------------------------------------
class TestDeviceOOM:
    def test_armable_via_env_spec(self, monkeypatch):
        from paddle_tpu.fault import DeviceOOMError
        a = paddle.to_tensor(np.ones((4,), np.float32))
        b = paddle.to_tensor(np.ones((4,), np.float32))
        monkeypatch.setenv(fault.SPEC_ENV, "device.alloc=1")
        fault.reload_spec()
        oom = metrics_mod.default_registry().get("device_oom_total")
        before = oom.total()
        with pytest.raises(DeviceOOMError) as ei:
            paddle.add(a, b)
        assert ei.value.op == "add"
        assert oom.total() == before + 1
        inj = metrics_mod.default_registry().get("fault_injected_total")
        assert sum(v["value"] for v in inj.snapshot()["values"]
                   if v["labels"].get("site") == "device.alloc") >= 1
        # site exhausted: the op works again (caller can shrink and retry)
        np.testing.assert_allclose(paddle.add(a, b).numpy(), np.full(4, 2.0))

    def test_resource_exhausted_becomes_typed_oom(self):
        from paddle_tpu import ops
        from paddle_tpu.fault import DeviceOOMError

        def alloc_hog(x):
            raise RuntimeError(
                "RESOURCE_EXHAUSTED: Out of memory allocating 1073741824 "
                "bytes (probably XlaRuntimeError on a real device)")

        x = paddle.to_tensor(np.ones((8,), np.float32))
        oom = metrics_mod.default_registry().get("device_oom_total")
        before = oom.value(op="alloc_hog")
        with pytest.raises(DeviceOOMError) as ei:
            ops.call(alloc_hog, (x,))
        assert ei.value.op == "alloc_hog"
        assert ei.value.bytes_estimate > 0  # named with the bytes touched
        assert "RESOURCE_EXHAUSTED" in str(ei.value)
        assert oom.value(op="alloc_hog") == before + 1

    def test_unrelated_errors_pass_through_unwrapped(self):
        from paddle_tpu import ops

        def bad_op(x):
            raise ValueError("shape mismatch, not an OOM")

        x = paddle.to_tensor(np.ones((2,), np.float32))
        with pytest.raises(ValueError, match="not an OOM"):
            ops.call(bad_op, (x,))


class TestGuardWorkerReuse:
    """Satellite (carried ROADMAP follow-up): `_guard_collective` reuses
    ONE long-lived watchdog worker across guarded eager collectives
    instead of spawning+joining a thread per call."""

    def setup_method(self, _):
        import paddle_tpu.distributed as dist
        from paddle_tpu.distributed import collective as coll
        from paddle_tpu.distributed.topology import (HybridCommunicateGroup,
                                                     build_mesh)
        mesh = build_mesh({"dp": 8})
        dist.set_hybrid_communicate_group(HybridCommunicateGroup(mesh=mesh))
        dist.destroy_process_group()
        self.group = dist.new_group(axis_name="dp")
        coll._guard_worker = None  # fresh worker accounting per test

    def teardown_method(self, _):
        import paddle_tpu.distributed as dist
        from paddle_tpu.distributed import collective as coll
        dist.set_hybrid_communicate_group(None)
        dist.destroy_process_group()
        coll._guard_worker = None

    def test_sequential_guarded_collectives_reuse_worker(self, monkeypatch):
        import paddle_tpu.distributed as dist
        from paddle_tpu.distributed import collective as coll
        monkeypatch.setenv("PADDLE_TPU_COLLECTIVE_TIMEOUT", "60")
        spawns0 = coll._guard_worker_spawns
        for _ in range(3):
            x = paddle.to_tensor(np.ones((4,), np.float32))
            dist.all_reduce(x, group=self.group)
            np.testing.assert_allclose(x.numpy(), np.full(4, 8.0))
        assert coll._guard_worker_spawns == spawns0 + 1
        worker = coll._guard_worker
        assert worker is not None and worker.thread.is_alive()
        # a different collective kind reuses the SAME worker thread
        dist.barrier(group=self.group)
        assert coll._guard_worker is worker

    def test_timed_out_worker_is_abandoned_then_replaced(self, monkeypatch):
        from paddle_tpu.distributed import collective as coll
        from paddle_tpu.distributed.collective import (CollectiveTimeoutError,
                                                       _guard_collective)
        monkeypatch.setenv("PADDLE_TPU_COLLECTIVE_TIMEOUT", "0.15")
        spawns0 = coll._guard_worker_spawns
        with pytest.raises(CollectiveTimeoutError):
            _guard_collective("probe", self.group, lambda: time.sleep(30))
        # the wedged worker must NOT be reused: the hung thunk may still
        # complete later on it and interleave with a fresh job
        assert coll._guard_worker is None
        assert _guard_collective("probe2", self.group, lambda: 41) == 41
        assert coll._guard_worker_spawns == spawns0 + 2

    def test_unguarded_path_spawns_no_worker(self, monkeypatch):
        import paddle_tpu.distributed as dist
        from paddle_tpu.distributed import collective as coll
        monkeypatch.delenv("PADDLE_TPU_COLLECTIVE_TIMEOUT", raising=False)
        spawns0 = coll._guard_worker_spawns
        x = paddle.to_tensor(np.ones((4,), np.float32))
        dist.all_reduce(x, group=self.group)
        assert coll._guard_worker_spawns == spawns0
        assert coll._guard_worker is None


# ---------------------------------------------------------------------------
# HA control-plane fault sites (PR 20)
# ---------------------------------------------------------------------------
class TestHAFaultSites:
    """`controller.lease` (drop lease renews to force a standby takeover)
    and `disagg.prefill` (kill a prefill worker mid-dispatch) must be
    registered — the AST convention lint holds call sites against the
    registry — and armable through the PADDLE_TPU_FAULT_SPEC grammar."""

    def test_registered_in_known_sites(self):
        from paddle_tpu.fault.inject import KNOWN_SITES
        assert "controller.lease" in KNOWN_SITES
        assert "disagg.prefill" in KNOWN_SITES
        # descriptions feed the README fault-sites table; empty ones
        # would document nothing
        assert KNOWN_SITES["controller.lease"]
        assert KNOWN_SITES["disagg.prefill"]

    def test_spec_grammar_arms_lease_site(self, monkeypatch):
        monkeypatch.setenv(fault.SPEC_ENV, "controller.lease=2:oserror")
        fault.reload_spec()
        for _ in range(2):
            with pytest.raises(fault.InjectedIOError):
                fault.site("controller.lease")
        fault.site("controller.lease")  # exhausted -> clean

    def test_spec_grammar_arms_prefill_site_with_start(self):
        inj = fault.FaultInjector(spec="disagg.prefill=1@2")
        inj.site("disagg.prefill")  # occurrence 1: clean
        with pytest.raises(fault.InjectedFault):
            inj.site("disagg.prefill")  # occurrence 2: faulted
        inj.site("disagg.prefill")  # exhausted
        assert inj.fired("disagg.prefill") == 1

    def test_lease_renew_path_honors_armed_site(self):
        """The injector must reach the actual renew write: a leader whose
        `controller.lease` site is armed fails its renew (and, once past
        the TTL, self-fences) instead of silently skipping the fault."""
        from paddle_tpu.distributed.fleet import leader as leader_mod
        store = TCPStore("127.0.0.1", 0, is_master=True)
        try:
            lease = leader_mod.LeaderLease(store, controller_id="c0",
                                           ttl=0.3, register=False)
            assert lease.tick() == "acquired" and lease.is_leader
            fault.configure("controller.lease", times=100, kind="oserror")
            time.sleep(0.35)
            deadline = time.monotonic() + 5.0
            while lease.is_leader and time.monotonic() < deadline:
                lease.tick()
                time.sleep(0.02)
            assert not lease.is_leader  # self-fenced: renews kept failing
        finally:
            store.stop()
