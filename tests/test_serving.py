"""Continuous-batching serving engine (inference/serving.py): page
allocator, per-iteration admission into the fixed decode batch,
bucketed-prefill retrace boundedness, EOS/length completion with page
freeing, pool-exhaustion preemption, the serving_* metric families, and
the admission/eviction event stream.

fast-sibling: everything here is tier-1-fast (tiny GPT, XLA decode
path); the serving-at-scale numbers live in bench.py's gpt2_decode
config.
"""
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference.serving import (PageAllocator, Request,
                                          ServingEngine)
from paddle_tpu.models.gpt import GPT, GPTConfig
from paddle_tpu.profiler import events
from paddle_tpu.profiler import metrics as metrics_mod


@pytest.fixture(autouse=True)
def _clean_events():
    events.default_event_log().clear()
    yield
    events.default_event_log().clear()


@pytest.fixture(scope="module", autouse=True)
def _shared_compile_cache():
    """Every test here rebuilds the same tiny-model engine, and each
    rebuild re-compiles identical fused-step/prefill executables; on the
    1-core tier-1 box that XLA backend time dominates the module.  Point
    jax's persistent compilation cache at a shared dir so only the first
    construction pays it (tests in this module assert on TRACE counts and
    audits, never on backend-compile counters, so cache hits are inert)."""
    import os
    import tempfile
    from paddle_tpu.framework import flags as flags_mod
    cache = os.path.join(tempfile.gettempdir(), "pt_serving_ccache")
    os.makedirs(cache, exist_ok=True)
    flags_mod.set_flags({"FLAGS_compile_cache_dir": cache})
    yield
    flags_mod.set_flags({"FLAGS_compile_cache_dir": ""})


def _model(vocab=512):
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=vocab, max_position_embeddings=128,
                    hidden_size=32, num_layers=2, num_heads=2,
                    dropout=0.0, attn_dropout=0.0)
    m = GPT(cfg)
    m.eval()
    return m, cfg


def _prompts(cfg, n, lo=4, hi=12, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, cfg.vocab_size,
                         (int(rng.integers(lo, hi)),)).tolist()
            for _ in range(n)]


class TestPageAllocator:
    def test_null_page_never_handed_out(self):
        a = PageAllocator(4)
        got = a.alloc(3)
        assert sorted(got) == [1, 2, 3]
        assert a.alloc(1) is None

    def test_partial_grab_never_dangles(self):
        a = PageAllocator(4)
        assert a.alloc(5) is None
        assert a.free_pages == 3  # nothing leaked

    def test_free_recycles_but_not_null(self):
        a = PageAllocator(4)
        got = a.alloc(2)
        a.free(got + [0])  # the null page in a free list is ignored
        assert a.free_pages == 3
        assert 0 not in a._free


class TestEngineBasics:
    def test_requests_complete_with_exact_token_budget(self):
        m, cfg = _model()
        eng = ServingEngine(m, max_batch=2, max_len=48, page_size=8,
                            name="t")
        reqs = [eng.submit(p, max_new_tokens=5)
                for p in _prompts(cfg, 5)]
        eng.run_until_idle()
        for r in reqs:
            out = r.result(timeout=5)
            assert len(out) == 5
            assert r.state == "done" and r.finish_reason == "length"
        # all pages back in the pool, batch empty
        st = eng.status()
        assert st["free_pages"] == eng.cache.num_pages - 1
        assert st["occupancy"] == 0 and st["queue_depth"] == 0

    @pytest.mark.slow  # fused-vs-generate_paged parity stays fast in
    # test_serving_v2.py::test_temperature_zero_matches_reference_greedy
    def test_matches_reference_paged_decode(self):
        """The engine's continuous-batching output for one request is
        exactly the model's reference greedy paged decode."""
        m, cfg = _model()
        eng = ServingEngine(m, max_batch=3, max_len=48, page_size=8,
                            name="t")
        prompts = _prompts(cfg, 4, seed=3)
        reqs = [eng.submit(p, max_new_tokens=6) for p in prompts]
        eng.run_until_idle()
        for p, r in zip(prompts, reqs):
            ids = paddle.to_tensor(np.asarray([p], np.int32))
            ref = np.asarray(m.generate_paged(ids, 6, page_size=8).data)
            assert r.result() == ref[0, len(p):].tolist()

    def test_eos_frees_slot_early(self):
        m, cfg = _model()
        eng = ServingEngine(m, max_batch=1, max_len=48, page_size=8,
                            name="t")
        probe = eng.submit(_prompts(cfg, 1)[0], max_new_tokens=6)
        eng.run_until_idle()
        # pick as EOS a token whose FIRST occurrence is past index 0, so
        # the eos path must fire exactly at that position on the rerun
        toks = probe.result()
        j = next(i for i in range(1, len(toks))
                 if toks[i] not in toks[:i])
        req = eng.submit(probe.prompt, max_new_tokens=10, eos_id=toks[j])
        eng.run_until_idle()
        out = req.result()
        assert req.finish_reason == "eos"
        assert out == toks[:j + 1]
        assert eng.status()["free_pages"] == eng.cache.num_pages - 1

    def test_continuous_admission_refills_slots(self):
        """More streams than slots: every iteration may admit — total
        completions equal submissions and max occupancy == max_batch."""
        m, cfg = _model()
        eng = ServingEngine(m, max_batch=2, max_len=48, page_size=8,
                            name="t")
        reqs = [eng.submit(p, max_new_tokens=4)
                for p in _prompts(cfg, 7, seed=5)]
        eng.run_until_idle()
        assert all(len(r.result()) == 4 for r in reqs)
        assert eng.stats["completed"] == 7
        occ = metrics_mod.default_registry().get("serving_batch_occupancy")
        assert occ.value(model="t") == 0.0  # drained at the end

    def test_background_thread_drives_to_completion(self):
        m, cfg = _model()
        eng = ServingEngine(m, max_batch=2, max_len=48, page_size=8,
                            name="bg")
        eng.start(poll_s=0.002)
        try:
            reqs = [eng.submit(p, max_new_tokens=3)
                    for p in _prompts(cfg, 3, seed=9)]
            for r in reqs:
                assert len(r.result(timeout=60)) == 3
        finally:
            eng.close()

    def test_dead_decode_loop_fails_requests_not_hangs(self):
        """Review regression: an exception out of step() used to kill
        the background thread silently, stranding every client in
        result() forever — it must fail outstanding requests loudly."""
        m, cfg = _model()
        eng = ServingEngine(m, max_batch=1, max_len=48, page_size=8,
                            name="dead")
        eng.step = lambda: (_ for _ in ()).throw(RuntimeError("boom"))
        req = eng.submit(_prompts(cfg, 1, seed=41)[0], max_new_tokens=3)
        with pytest.warns(UserWarning, match="decode loop died"):
            eng.start(poll_s=0.001)
            with pytest.raises(RuntimeError, match="decode loop died"):
                req.result(timeout=30)
        eng.close()
        with pytest.raises(RuntimeError, match="closed"):
            eng.submit([1, 2], max_new_tokens=1)

    def test_submit_after_close_raises(self):
        m, cfg = _model()
        eng = ServingEngine(m, max_batch=1, max_len=48, page_size=8,
                            name="cl2")
        eng.close()
        with pytest.raises(RuntimeError, match="closed"):
            eng.submit([1, 2, 3], max_new_tokens=1)

    def test_submit_validates_budget(self):
        m, cfg = _model()
        eng = ServingEngine(m, max_batch=1, max_len=32, page_size=8,
                            name="t")
        with pytest.raises(ValueError, match="exceeds max_len"):
            eng.submit(list(range(1, 30)), max_new_tokens=10)
        with pytest.raises(ValueError, match="empty"):
            eng.submit([], max_new_tokens=1)


class TestBucketedPrefill:
    def test_prefill_signatures_bounded_by_buckets(self):
        """Many distinct prompt lengths must compile at most
        len(prefill_buckets) prefill signatures (the retrace-watchdog
        quietness contract) and exactly ONE decode signature per
        active-lane bucket site."""
        from paddle_tpu.profiler.watchdog import get_watchdog
        m, cfg = _model()
        eng = ServingEngine(m, max_batch=2, max_len=64, page_size=8,
                            prefill_buckets=(16, 64), name="bk")
        for p in _prompts(cfg, 8, lo=3, hi=40, seed=11):
            eng.submit(p, max_new_tokens=2)
        eng.run_until_idle()
        wd = get_watchdog()
        sigs = wd._seen
        pre = sigs.get(("to_static", "serving_prefill:bk"), set())
        assert 1 <= len(pre) <= 2, pre
        dec_sites = {site: seen for (kind, site), seen in sigs.items()
                     if kind == "to_static"
                     and site.startswith("serving_decode:bk:w")}
        assert dec_sites, "no decode lane-bucket sites observed"
        assert len(dec_sites) <= len(eng.decode_buckets), dec_sites
        for site, seen in dec_sites.items():
            assert len(seen) == 1, (site, seen)

    @pytest.mark.slow  # two engines per run; signature-count sibling stays fast
    def test_bucket_padding_does_not_change_tokens(self):
        """A prompt served through a larger bucket yields the same
        generation as through a tight one."""
        m, cfg = _model()
        prompt = _prompts(cfg, 1, lo=6, hi=7, seed=13)[0]
        outs = []
        for buckets in ((8, 64), (64,)):
            eng = ServingEngine(m, max_batch=1, max_len=64, page_size=8,
                                prefill_buckets=buckets, name="pad")
            r = eng.submit(prompt, max_new_tokens=5)
            eng.run_until_idle()
            outs.append(r.result())
        assert outs[0] == outs[1]


class TestPreemption:
    @pytest.mark.slow  # drain/close preemption siblings below stay fast
    def test_pool_exhaustion_preempts_youngest_and_recovers(self):
        """A page pool too small for the whole batch: the youngest
        running request is preempted (pages freed, requeued with its
        generated prefix) and every request still completes with its
        full token budget and the right tokens."""
        m, cfg = _model()
        # pool: 2 sequences x 24 tokens need 6 pages; give 5 (+null)
        eng = ServingEngine(m, max_batch=2, max_len=40, page_size=8,
                            num_pages=6, name="pre")
        prompts = _prompts(cfg, 2, lo=14, hi=15, seed=17)
        reqs = [eng.submit(p, max_new_tokens=12) for p in prompts]
        eng.run_until_idle()
        assert eng.stats["preemptions"] >= 1
        assert sum(r.preemptions for r in reqs) >= 1
        for p, r in zip(prompts, reqs):
            out = r.result()
            assert len(out) == 12
            ids = paddle.to_tensor(np.asarray([p], np.int32))
            ref = np.asarray(m.generate_paged(ids, 12, page_size=8).data)
            assert out == ref[0, len(p):].tolist(), \
                "preemption changed the greedy tokens"
        ev = [e for e in events.recent(100, kind="serving_eviction")
              if e.get("reason") == "preempted"]
        assert ev and ev[0]["severity"] == "warn"

    def test_request_too_big_for_pool_rejected_at_submit(self):
        """Review regression: a request the pool can NEVER satisfy used
        to sit at the queue head forever (admission waits for frees that
        cannot come) — submit now validates total page need up front."""
        m, cfg = _model()
        eng = ServingEngine(m, max_batch=1, max_len=40, page_size=8,
                            num_pages=3, name="oom")
        with pytest.raises(ValueError, match="KV pages"):
            eng.submit(list(range(1, 15)), max_new_tokens=12)  # 4 > 2

    def test_external_pool_drain_fails_the_sole_runner_loudly(self):
        """A dry pool with nothing to preempt (pages consumed outside
        the running set) fails the request instead of wedging."""
        m, cfg = _model()
        eng = ServingEngine(m, max_batch=1, max_len=40, page_size=8,
                            name="drain")
        req = eng.submit(list(range(1, 8)), max_new_tokens=12)
        eng.step()  # admit + prefill + first decode
        eng.allocator.alloc(eng.allocator.free_pages)  # drain the pool
        eng.run_until_idle()
        with pytest.raises(RuntimeError, match="page pool exhausted"):
            req.result(timeout=5)
        assert req.state == "failed"

    def test_close_fails_outstanding_requests(self):
        """Review regression: close() used to join the thread and leave
        queued/running requests un-completed — a client blocked in
        result() hung forever on a closed engine."""
        m, cfg = _model()
        eng = ServingEngine(m, max_batch=1, max_len=48, page_size=8,
                            name="cl")
        running = eng.submit(_prompts(cfg, 1, seed=31)[0],
                             max_new_tokens=30)
        queued = eng.submit(_prompts(cfg, 1, seed=32)[0],
                            max_new_tokens=5)
        eng.step()  # `running` admitted into the batch, `queued` waits
        eng.close()
        for req in (running, queued):
            with pytest.raises(RuntimeError, match="engine closed"):
                req.result(timeout=5)
        assert eng.status()["free_pages"] == eng.cache.num_pages - 1


class TestServingObservability:
    def test_metric_families_populated(self):
        m, cfg = _model()
        reg = metrics_mod.default_registry()
        eng = ServingEngine(m, max_batch=2, max_len=48, page_size=8,
                            name="obs")
        reqs = [eng.submit(p, max_new_tokens=4)
                for p in _prompts(cfg, 4, seed=19)]
        eng.run_until_idle()
        assert reg.get("serving_goodput_tokens_total").value(
            model="obs") == sum(len(r.generated) for r in reqs)
        ttft = [v for v in reg.get("serving_ttft_seconds").snapshot()
                ["values"] if v["labels"].get("model") == "obs"]
        assert ttft and ttft[0]["count"] == 4
        tpot = [v for v in reg.get("serving_tpot_seconds").snapshot()
                ["values"] if v["labels"].get("model") == "obs"]
        assert tpot and tpot[0]["count"] == 4
        for r in reqs:
            assert r.ttft_s is not None and r.ttft_s >= 0
            assert r.tpot_s is not None and r.tpot_s >= 0

    def test_admission_and_eviction_events(self):
        m, cfg = _model()
        eng = ServingEngine(m, max_batch=1, max_len=48, page_size=8,
                            name="ev")
        req = eng.submit(_prompts(cfg, 1, seed=23)[0], max_new_tokens=3)
        eng.run_until_idle()
        adm = events.recent(50, kind="serving_admission")
        evi = events.recent(50, kind="serving_eviction")
        assert len(adm) == 1 and len(evi) == 1
        a, e = adm[0], evi[0]
        events.validate_event(a)
        events.validate_event(e)
        assert a["request"] == req.rid and a["slot"] == 0
        assert a["prompt_len"] == len(req.prompt)
        assert a["bucket"] >= a["prompt_len"]
        assert a["queue_wait_s"] >= 0
        assert e["request"] == req.rid and e["reason"] == "length"
        assert e["generated"] == 3

    def test_status_shape(self):
        m, cfg = _model()
        eng = ServingEngine(m, max_batch=2, max_len=48, page_size=8,
                            name="st")
        st = eng.status()
        for key in ("model", "max_batch", "max_len", "page_size",
                    "num_pages", "free_pages", "queue_depth",
                    "occupancy", "prefill_buckets", "stats"):
            assert key in st
        import json
        json.dumps(st)  # endpoint-serializable
