"""Sliding-window SLO tracker (profiler/slo.py) and the chaos drill
that proves the serving plane's breach alerting.

The ISSUE-17 contracts: window p50/p95/p99 against PADDLE_TPU_SLO_*
targets, exactly ONE `slo_breach` event per excursion with silent
re-arm on recovery (the PR-9 health-detector transition shape), the
fleet-digest mirror (`serving_slo`), and the end-to-end chaos check —
a `delay`-faulted `serving.decode` drives a p99 TTFT breach that emits
one event, re-arms when the window recovers, and fires again on the
next excursion, all while tokens keep flowing.
"""
import json

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import fault
from paddle_tpu.models.gpt import GPT, GPTConfig
from paddle_tpu.profiler import events
from paddle_tpu.profiler import metrics as metrics_mod
from paddle_tpu.profiler import slo
from paddle_tpu.profiler.slo import SLOTracker, _quantile


@pytest.fixture(autouse=True)
def _clean_events():
    events.default_event_log().clear()
    fault.reset()
    yield
    events.default_event_log().clear()
    fault.reset()


@pytest.fixture(scope="module", autouse=True)
def _shared_compile_cache():
    """Shared persistent XLA compile cache with the other serving
    suites (identical tiny-GPT HLO)."""
    import os
    import tempfile
    from paddle_tpu.framework import flags as flags_mod
    cache = os.path.join(tempfile.gettempdir(), "pt_serving_ccache")
    os.makedirs(cache, exist_ok=True)
    flags_mod.set_flags({"FLAGS_compile_cache_dir": cache})
    yield
    flags_mod.set_flags({"FLAGS_compile_cache_dir": ""})


def _model(vocab=512):
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=vocab, max_position_embeddings=128,
                    hidden_size=32, num_layers=2, num_heads=2,
                    dropout=0.0, attn_dropout=0.0)
    m = GPT(cfg)
    m.eval()
    return m, cfg


class TestSLOTrackerUnit:
    def test_quantile_interpolation(self):
        vals = [1.0, 2.0, 3.0, 4.0]
        assert _quantile(vals, 0.5) == pytest.approx(2.5)
        assert _quantile(vals, 0.0) == 1.0
        assert _quantile(vals, 1.0) == 4.0
        assert _quantile([7.0], 0.99) == 7.0

    def test_window_quantiles_and_snapshot_shape(self):
        t = SLOTracker("unit", window=16, min_samples=4, targets={})
        for v in range(1, 11):
            t.observe("ttft", v / 10.0)
        qs = t.quantiles("ttft")
        assert qs["count"] == 10
        assert qs["p50"] <= qs["p95"] <= qs["p99"] <= 1.0
        snap = t.snapshot()
        assert snap["status"] == "ok" and snap["breached"] == {}
        assert set(snap["signals"]) == set(slo.SIGNALS)
        assert snap["signals"]["tpot"]["count"] == 0
        assert snap["signals"]["tpot"]["p99"] is None
        json.dumps(snap)

    def test_unknown_signal_raises(self):
        t = SLOTracker("unit", targets={})
        with pytest.raises(ValueError, match="unknown SLO signal"):
            t.observe("latency", 1.0)

    def test_one_event_per_excursion_and_rearm(self):
        """Breach entry emits exactly ONE slo_breach; further breached
        samples are silent; recovery re-arms silently; the NEXT
        excursion emits again."""
        t = SLOTracker("unit_excur", window=4, min_samples=2,
                       targets={"ttft": 0.1})
        for _ in range(4):
            t.observe("ttft", 1.0)  # deep breach, many samples
        evs = events.recent(kind="slo_breach")
        assert len(evs) == 1
        ev = evs[0]
        assert ev["severity"] == "warn" and ev["signal"] == "ttft"
        assert ev["value"] > ev["target"] == 0.1
        assert t.status() == "breach:ttft"
        assert t.stats["breaches"] == 1
        # recovery: fast samples flush the window -> silent re-arm
        for _ in range(4):
            t.observe("ttft", 0.01)
        assert t.status() == "ok" and t.breached() == {}
        assert t.stats["recoveries"] == 1
        assert len(events.recent(kind="slo_breach")) == 1  # no new event
        # next excursion fires again
        for _ in range(4):
            t.observe("ttft", 2.0)
        assert len(events.recent(kind="slo_breach")) == 2
        assert t.stats["breaches"] == 2

    def test_min_samples_gates_checking(self):
        t = SLOTracker("unit_min", window=32, min_samples=8,
                       targets={"e2e": 0.001})
        for _ in range(7):
            t.observe("e2e", 5.0)
        assert t.status() == "ok"  # not enough samples yet
        t.observe("e2e", 5.0)
        assert t.status() == "breach:e2e"

    def test_unset_target_is_never_checked(self):
        t = SLOTracker("unit_unset", window=8, min_samples=1,
                       targets={"ttft": 0.1})
        for _ in range(8):
            t.observe("tpot", 100.0)  # no tpot target -> no breach
        assert t.status() == "ok"
        assert events.recent(kind="slo_breach") == []

    def test_kill_switch_disables_observation(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_SLO", "0")
        t = SLOTracker("unit_off", window=8, min_samples=1,
                       targets={"ttft": 0.001})
        for _ in range(8):
            t.observe("ttft", 9.0)
        assert t.snapshot()["enabled"] is False
        assert t.snapshot()["signals"]["ttft"]["count"] == 0
        assert events.recent(kind="slo_breach") == []

    def test_default_targets_from_env(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_SLO_TTFT_P99_S", "0.25")
        monkeypatch.setenv("PADDLE_TPU_SLO_E2E_P99_S", "3.5")
        monkeypatch.delenv("PADDLE_TPU_SLO_TPOT_P99_S", raising=False)
        monkeypatch.delenv("PADDLE_TPU_SLO_QUEUE_P99_S", raising=False)
        assert slo.default_targets() == {"ttft": 0.25, "e2e": 3.5}

    def test_breach_metric_families(self):
        t = SLOTracker("unit_fam", window=4, min_samples=2,
                       targets={"queue_wait": 0.05})
        for _ in range(3):
            t.observe("queue_wait", 1.0)
        snap = metrics_mod.default_registry().snapshot()

        def series(fam):
            return {tuple(sorted(v["labels"].items())): v["value"]
                    for v in snap[fam]["values"]}
        key = (("model", "unit_fam"), ("signal", "queue_wait"))
        assert series("slo_breaches_total")[key] == 1
        assert series("slo_breached")[key] == 1
        assert series("slo_window_p99_seconds")[key] > 0.05
        for _ in range(4):
            t.observe("queue_wait", 0.001)
        snap = metrics_mod.default_registry().snapshot()
        assert series("slo_breached")[key] == 0  # gauge re-armed
        assert series("slo_breaches_total")[key] == 1  # excursions, not samples

    def test_last_status_and_current_snapshot_track_newest(self):
        t = SLOTracker("unit_cur", window=4, min_samples=1,
                       targets={"ttft": 0.1})
        assert slo.last_status() == "ok"
        t.observe("ttft", 1.0)
        assert slo.last_status() == "breach:ttft"
        snap = slo.current_snapshot()
        assert snap["model"] == "unit_cur"

    def test_fleet_digest_mirrors_slo_status(self):
        from paddle_tpu.distributed.fleet.telemetry import FleetReporter
        t = SLOTracker("unit_digest", window=4, min_samples=1,
                       targets={"e2e": 0.01})  # held: _current is a weakref
        t.observe("e2e", 5.0)
        assert FleetReporter._serving_slo_status() == "breach:e2e"


class TestSLOChaosDrill:
    """End-to-end: latency chaos at `serving.decode` drives a TTFT
    breach; the alert fires once, re-arms, and fires again."""

    def test_delay_fault_drives_single_breach_then_rearms(self,
                                                          monkeypatch):
        # tight target + tiny window so the drill is deterministic and
        # the recovery flush is cheap
        monkeypatch.setenv("PADDLE_TPU_SLO_TTFT_P99_S", "0.01")
        monkeypatch.setenv("PADDLE_TPU_SLO_MIN_SAMPLES", "2")
        monkeypatch.setenv("PADDLE_TPU_SLO_WINDOW", "8")
        from paddle_tpu.inference.serving import ServingEngine
        m, cfg = _model()
        eng = ServingEngine(m, max_batch=1, max_len=48, page_size=8,
                            name="slo_chaos")
        assert eng.slo.targets == {"ttft": 0.01}
        # every decode dispatch sleeps PADDLE_TPU_FAULT_DELAY: with
        # max_batch=1 the queued requests' TTFT inherits the slowdown
        fault.configure("serving.decode", times=64, kind="delay")
        rng = np.random.default_rng(9)
        prompts = [rng.integers(1, cfg.vocab_size, (6,)).tolist()
                   for _ in range(3)]
        reqs = [eng.submit(p, max_new_tokens=4) for p in prompts]
        eng.run_until_idle()
        outs = [r.result(timeout=30) for r in reqs]
        assert all(len(o) == 4 for o in outs)  # tokens kept flowing
        assert fault.default_injector().fired("serving.decode") > 0
        evs = events.recent(kind="slo_breach")
        assert len(evs) == 1, evs  # exactly ONE event for the excursion
        ev = evs[0]
        assert ev["model"] == "slo_chaos" and ev["signal"] == "ttft"
        assert ev["quantile"] == "p99" and ev["value"] > 0.01
        assert eng.slo.status() == "breach:ttft"
        assert eng.slo.snapshot()["breached"]["ttft"]["target"] == 0.01
        # recovery: healthy samples flush the 8-deep window -> re-arm,
        # still only one event
        fault.reset()
        for _ in range(8):
            eng.slo.observe("ttft", 0.001)
        assert eng.slo.status() == "ok"
        assert eng.slo.stats["recoveries"] == 1
        assert len(events.recent(kind="slo_breach")) == 1
        # a second excursion alerts again
        for _ in range(8):
            eng.slo.observe("ttft", 1.0)
        assert len(events.recent(kind="slo_breach")) == 2

    def test_engine_feeds_all_four_signals(self):
        from paddle_tpu.inference.serving import ServingEngine
        m, cfg = _model()
        eng = ServingEngine(m, max_batch=2, max_len=48, page_size=8,
                            name="slo_feed")
        reqs = [eng.submit(list(range(1, 9)), max_new_tokens=4)
                for _ in range(2)]
        eng.run_until_idle()
        for r in reqs:
            r.result(timeout=10)
        sig = eng.slo.snapshot()["signals"]
        for s in ("ttft", "tpot", "queue_wait", "e2e"):
            assert sig[s]["count"] >= 2, s
            assert sig[s]["p50"] <= sig[s]["p95"] <= sig[s]["p99"], s
