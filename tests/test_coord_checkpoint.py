"""Multi-host coordinated checkpoint barrier: two-phase commit, abort
paths, fleet resume negotiation, and the ckpt_inspect --dir audit.

These are the FAST single-process siblings of the subprocess e2e in
test_elastic_e2e.py: "hosts" are threads sharing one in-process TCPStore
master, each with its own client connection and checkpoint directory —
the same protocol state machine without process spawn / jit warmup cost.
"""
import os
import threading
import time
import warnings

import numpy as np
import pytest

from paddle_tpu import fault
from paddle_tpu.distributed import checkpoint as dist_ckpt
from paddle_tpu.distributed.checkpoint import (CheckpointCoordinator,
                                               CheckpointManager,
                                               coordinator_from_env)
from paddle_tpu.distributed.store import TCPStore
from paddle_tpu.profiler import metrics as metrics_mod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_injector():
    fault.reset()
    yield
    fault.reset()


@pytest.fixture()
def master():
    st = TCPStore("127.0.0.1", 0, is_master=True)
    yield st
    st.stop()


def _state(seed=0):
    return {"w": np.arange(4, dtype=np.float32) + seed}


def _manager(master, rank, tmp_path, world=2, timeout=5.0, **kw):
    """One simulated host: own store client + own checkpoint dir."""
    store = TCPStore("127.0.0.1", master.port)
    coord = CheckpointCoordinator(store, rank, world, timeout=timeout,
                                  poll_interval=0.005, **kw)
    d = str(tmp_path / f"host{rank}")
    os.makedirs(d, exist_ok=True)
    return CheckpointManager(d, coordinator=coord)


def _join_all(threads):
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
        assert not t.is_alive(), "barrier thread wedged"


def _counter_total(name, **labels):
    m = metrics_mod.default_registry().get(name)
    if m is None:
        return 0.0
    return sum(v["value"] for v in m.snapshot()["values"]
               if all(v["labels"].get(k) == lv for k, lv in labels.items()))


class TestCoordinatedCommit:
    def test_both_hosts_commit_step(self, master, tmp_path):
        commits0 = _counter_total("ckpt_barrier_commits_total")
        m0 = _manager(master, 0, tmp_path)
        m1 = _manager(master, 1, tmp_path)
        res = {}
        _join_all([
            threading.Thread(target=lambda: res.update(a=m0.save(_state(), 1))),
            threading.Thread(target=lambda: res.update(b=m1.save(_state(), 1))),
        ])
        assert res == {"a": True, "b": True}
        for m in (m0, m1):
            newest = dist_ckpt.latest_valid(m.dirname)
            assert newest is not None and newest.endswith("ckpt_1")
            ok, reason = dist_ckpt.verify(newest)
            assert ok, reason
            # no leftover prepare tmp after a commit
            assert not any(".tmp." in f for f in os.listdir(m.dirname))
        assert _counter_total("ckpt_barrier_commits_total") >= commits0 + 2

    def test_single_host_has_no_barrier(self, tmp_path):
        m = CheckpointManager(str(tmp_path))  # world_size==1: plain save
        assert m.coordinator is None
        assert m.save(_state(), 1) is True
        assert dist_ckpt.latest_valid(str(tmp_path)) is not None

    def test_coordinated_manager_keeps_at_least_two(self, master, tmp_path):
        """keep_last_n=1 + coordinator is a resume wedge waiting to happen:
        after a two-generals crash the fleet agrees on N-1, which this
        host's GC already deleted. Coordinated managers floor it at 2."""
        m = _manager(master, 0, tmp_path)
        m.keep_last_n = 1  # what __init__ must have prevented
        m2 = CheckpointManager(str(tmp_path / "h"), keep_last_n=1,
                               coordinator=m.coordinator)
        assert m2.keep_last_n == 2
        plain = CheckpointManager(str(tmp_path / "p"), keep_last_n=1)
        assert plain.keep_last_n == 1  # single-host: no skew, no floor

    def test_world_size_one_coordinator_rejected(self, master):
        store = TCPStore("127.0.0.1", master.port)
        with pytest.raises(ValueError, match="world_size"):
            CheckpointCoordinator(store, 0, 1)

    def test_missing_peer_aborts_without_final_file(self, master, tmp_path):
        aborts0 = _counter_total("ckpt_barrier_aborts_total",
                                 reason="timeout")
        m0 = _manager(master, 0, tmp_path, timeout=0.5)
        with pytest.warns(UserWarning, match="aborted"):
            assert m0.save(_state(), 7) is False  # peer never arrives
        assert os.listdir(m0.dirname) == []  # tmp GC'd, nothing published
        assert _counter_total("ckpt_barrier_aborts_total",
                              reason="timeout") >= aborts0 + 1

    def test_commit_fault_aborts_fleet_wide(self, master, tmp_path):
        """The e2e's kill-between-prepare-and-commit, in-process: host 0
        faults at the ckpt.commit site (never votes), so host 1 times out
        and aborts — NO host publishes a final file for the step."""
        fault.configure("ckpt.commit", times=1)
        m0 = _manager(master, 0, tmp_path, timeout=2.0)
        m1 = _manager(master, 1, tmp_path, timeout=1.0)
        res = {}

        def host0():
            try:
                m0.save(_state(), 3)
            except fault.InjectedFault:
                res["a"] = "died"

        def host1():
            # the single armed injection must go to host 0: don't enter the
            # commit phase (and race for it) until host 0 has consumed it
            deadline = time.time() + 30
            while (fault.default_injector().fired("ckpt.commit") < 1
                   and time.time() < deadline):
                time.sleep(0.005)
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                res["b"] = m1.save(_state(), 3)

        _join_all([threading.Thread(target=host0),
                   threading.Thread(target=host1)])
        assert res == {"a": "died", "b": False}
        for m in (m0, m1):
            assert dist_ckpt.latest_valid(m.dirname) is None
            assert not os.path.exists(m.path_for(3))
        # the faulted host flagged the abort before dying: peers observe
        # it (or time out) instead of hanging, and both paths are metered
        assert fault.default_injector().fired("ckpt.commit") == 1
        assert _counter_total("ckpt_barrier_aborts_total") >= 1

    def test_reused_step_gets_fresh_barrier(self, master, tmp_path):
        """A step number committed in an earlier round (epoch-end save,
        then SIGTERM preemption save before the next step advances) must
        run a FRESH barrier — not insta-commit on the previous round's
        stale prep votes while a peer's prepare never happened."""
        m0 = _manager(master, 0, tmp_path, timeout=1.0)
        m1 = _manager(master, 1, tmp_path, timeout=1.0)
        res = {}
        _join_all([
            threading.Thread(target=lambda: res.update(a=m0.save(_state(), 1))),
            threading.Thread(target=lambda: res.update(b=m1.save(_state(), 1))),
        ])
        assert res == {"a": True, "b": True}
        # host 0 re-saves step 1 alone: peer never prepares, so the round
        # must time out and abort (stale round-0 votes must not satisfy it)
        with pytest.warns(UserWarning, match="aborted"):
            assert m0.save(_state(seed=9), 1) is False
        # the round-0 final file survives untouched
        newest = dist_ckpt.latest_valid(m0.dirname)
        assert newest is not None and newest.endswith("ckpt_1")
        ok, reason = dist_ckpt.verify(newest)
        assert ok, reason

    def test_aborted_step_number_can_recommit(self, master, tmp_path):
        """A step number whose round aborted must be retryable: the next
        round's barrier must not observe the previous round's abort flag
        (a preemption save re-using an aborted step would otherwise be
        silently dropped fleet-wide)."""
        m0 = _manager(master, 0, tmp_path, timeout=0.8)
        m1 = _manager(master, 1, tmp_path, timeout=0.8)
        # each host burns round 0 with a solo abort on DISJOINT steps
        # (lockstep: same number of rounds per host, like the real protocol
        # where an abort is observed by the whole fleet) — host 0's abort
        # flags step 7
        def solo(m, step):
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                assert m.save(_state(), step) is False
        _join_all([threading.Thread(target=solo, args=(m0, 7)),
                   threading.Thread(target=solo, args=(m1, 6))])
        # round 1: the fleet re-commits step 7 — the round-0 abort flag
        # must not poison it
        res = {}
        _join_all([
            threading.Thread(target=lambda: res.update(a=m0.save(_state(), 7))),
            threading.Thread(target=lambda: res.update(b=m1.save(_state(), 7))),
        ])
        assert res == {"a": True, "b": True}
        for m in (m0, m1):
            assert os.path.exists(m.path_for(7))

    def test_prepare_failure_aborts_promptly_and_keeps_rounds(
            self, master, tmp_path, monkeypatch):
        """A prepare-phase failure (disk full, SIGTERM during the tmp
        write) must poison the round: the peer aborts promptly instead of
        burning the barrier timeout, and the failed host's round counter
        stays lockstep so its NEXT save still works."""
        m0 = _manager(master, 0, tmp_path, timeout=30.0)
        m1 = _manager(master, 1, tmp_path, timeout=30.0)
        orig = dist_ckpt._encode_snapshot

        def failing(host_state, specs):
            if isinstance(host_state, dict) and host_state.get("boom"):
                raise RuntimeError("disk full")
            return orig(host_state, specs)
        monkeypatch.setattr(dist_ckpt, "_encode_snapshot", failing)
        res = {}

        def host0():
            try:
                m0.save({"boom": True, "w": np.zeros(2)}, 1)
            except RuntimeError:
                res["a"] = "failed"

        def host1():
            t0 = time.time()
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                res["b"] = m1.save(_state(), 1)
            res["b_secs"] = time.time() - t0
        _join_all([threading.Thread(target=host0),
                   threading.Thread(target=host1)])
        assert res["a"] == "failed" and res["b"] is False
        assert res["b_secs"] < 15  # prompt peer_abort, not the 30s timeout
        # round counters stayed lockstep: the next fleet save commits
        res2 = {}
        _join_all([
            threading.Thread(target=lambda: res2.update(a=m0.save(_state(), 2))),
            threading.Thread(target=lambda: res2.update(b=m1.save(_state(), 2))),
        ])
        assert res2 == {"a": True, "b": True}

    def test_ckpt_commit_armable_via_env_spec(self, master, tmp_path,
                                              monkeypatch):
        monkeypatch.setenv(fault.SPEC_ENV, "ckpt.commit=1")
        fault.reload_spec()
        m0 = _manager(master, 0, tmp_path, timeout=1.0)
        with pytest.raises(fault.InjectedFault):
            m0.save(_state(), 1)
        assert _counter_total("fault_injected_total", site="ckpt.commit") >= 1
        assert os.listdir(m0.dirname) == []  # tmp cleaned on the error path

    def test_abort_flag_honored_by_peer(self, master, tmp_path):
        """A host that observes a peer's abort flag drops its own tmp even
        if every prepare vote eventually lands."""
        m0 = _manager(master, 0, tmp_path, timeout=5.0)
        m0.coordinator.mark_abort(5, "timeout")  # peer aborted step 5
        m1 = _manager(master, 1, tmp_path, timeout=5.0)
        with pytest.warns(UserWarning, match="aborted"):
            assert m1.save(_state(), 5) is False
        assert os.listdir(m1.dirname) == []

    def test_namespace_isolates_generations(self, master, tmp_path):
        """A stale abort flag from the generation that died must not poison
        the restarted generation's rounds: the supervisor bumps
        PADDLE_TPU_ELASTIC_RESTART_NUM and the coordinator namespaces by it."""
        stale = _manager(master, 0, tmp_path, namespace="ckptbar/0")
        stale.coordinator.mark_abort(1, "timeout")
        m0 = _manager(master, 0, tmp_path, namespace="ckptbar/1")
        m1 = _manager(master, 1, tmp_path, namespace="ckptbar/1")
        res = {}
        _join_all([
            threading.Thread(target=lambda: res.update(a=m0.save(_state(), 1))),
            threading.Thread(target=lambda: res.update(b=m1.save(_state(), 1))),
        ])
        assert res == {"a": True, "b": True}

    def test_preemption_publish_routes_through_barrier(self, master,
                                                       tmp_path):
        """SIGTERM's one final save uses the same two-phase commit: both
        hosts' _publish_sync barrier together and publish, or neither."""
        m0 = _manager(master, 0, tmp_path)
        m1 = _manager(master, 1, tmp_path)
        res = {}
        _join_all([
            threading.Thread(
                target=lambda: res.update(a=m0._publish_sync(_state(), 9))),
            threading.Thread(
                target=lambda: res.update(b=m1._publish_sync(_state(), 9))),
        ])
        assert res == {"a": True, "b": True}
        for m in (m0, m1):
            assert os.path.exists(m.path_for(9))


class TestResumeNegotiation:
    def test_divergent_hosts_resume_from_fleet_committed_step(
            self, master, tmp_path):
        """Regression (satellite): host 0 renamed step 3 just before the
        fleet died, host 1 never did. Resume must pick the barrier-committed
        step 2 on BOTH hosts — never host 0's lexically newest file."""
        m0 = _manager(master, 0, tmp_path)
        m1 = _manager(master, 1, tmp_path)
        for step in (1, 2):
            res = {}
            _join_all([
                threading.Thread(
                    target=lambda: res.update(a=m0.save(_state(step), step))),
                threading.Thread(
                    target=lambda: res.update(b=m1.save(_state(step), step))),
            ])
            assert res == {"a": True, "b": True}
        # host 0 alone publishes step 3 (plain local save: the rename
        # happened, the fleet's vote on the NEXT round never completed)
        dist_ckpt.save(_state(3), m0.path_for(3))
        assert dist_ckpt.latest_valid(m0.dirname).endswith("ckpt_3")

        res = {}
        _join_all([
            threading.Thread(target=lambda: res.update(a=m0.load_latest())),
            threading.Thread(target=lambda: res.update(b=m1.load_latest())),
        ])
        for key, host in (("a", "host0"), ("b", "host1")):
            state, step = res[key]
            assert step == 2, f"{host} resumed from step {step}, wanted 2"
            np.testing.assert_array_equal(np.asarray(state["w"]), _state(2)["w"])

    def test_all_hosts_empty_resumes_fresh(self, master, tmp_path):
        m0 = _manager(master, 0, tmp_path)
        m1 = _manager(master, 1, tmp_path)
        res = {}
        _join_all([
            threading.Thread(target=lambda: res.update(a=m0.load_latest())),
            threading.Thread(target=lambda: res.update(b=m1.load_latest())),
        ])
        assert res == {"a": None, "b": None}

    def test_one_empty_host_forces_fresh_start(self, master, tmp_path):
        """A host that lost its disk (fresh node joining after restart)
        has nothing: the fleet cannot resume a step that host lacks."""
        m0 = _manager(master, 0, tmp_path)
        m1 = _manager(master, 1, tmp_path)
        dist_ckpt.save(_state(1), m0.path_for(1))  # only host 0 has data
        res = {}
        _join_all([
            threading.Thread(target=lambda: res.update(a=m0.load_latest())),
            threading.Thread(target=lambda: res.update(b=m1.load_latest())),
        ])
        assert res == {"a": None, "b": None}

    def test_negotiation_timeout_raises_and_poisons_round(self, master,
                                                          tmp_path):
        """Consistency over availability: a host whose peers never arrive
        must NOT silently resume its local step (a peer landing just past
        the deadline would resume the fleet minimum — split brain). The
        timeout raises, and the poisoned round makes the late arriver
        raise too instead of resuming alone."""
        m0 = _manager(master, 0, tmp_path, resume_timeout=0.3)
        dist_ckpt.save(_state(4), m0.path_for(4))
        with pytest.raises(RuntimeError, match="negotiation timed out"):
            m0.load_latest()
        # the late arriver finds every key published (its own + host 0's)
        # but the round is poisoned: it must refuse as well
        m1 = _manager(master, 1, tmp_path, resume_timeout=5.0)
        dist_ckpt.save(_state(4), m1.path_for(4))
        with pytest.raises(RuntimeError, match="abandoned by a peer"):
            m1.load_latest()


class TestCoordinatorFromEnv:
    def test_builds_from_trainer_env_contract(self, master, monkeypatch):
        monkeypatch.setenv("PADDLE_TRAINERS_NUM", "2")
        monkeypatch.setenv("PADDLE_TRAINER_ID", "1")
        monkeypatch.setenv("MASTER_ADDR", "127.0.0.1")
        monkeypatch.setenv("MASTER_PORT", str(master.port))
        co = coordinator_from_env()
        assert co is not None and co.rank == 1 and co.world_size == 2

    def test_single_host_env_returns_none(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TRAINERS_NUM", "1")
        monkeypatch.setenv("MASTER_ADDR", "127.0.0.1")
        monkeypatch.setenv("MASTER_PORT", "1")
        assert coordinator_from_env() is None

    def test_kill_switch_env(self, master, monkeypatch):
        monkeypatch.setenv("PADDLE_TRAINERS_NUM", "2")
        monkeypatch.setenv("MASTER_ADDR", "127.0.0.1")
        monkeypatch.setenv("MASTER_PORT", str(master.port))
        monkeypatch.setenv("PADDLE_TPU_CKPT_BARRIER", "0")
        assert coordinator_from_env() is None

    def test_garbled_master_port_fails_loudly(self, monkeypatch):
        """A >=2 fleet with an unparseable MASTER_PORT must raise a named
        error, not silently degrade to the single-host path — this host
        would skip the barrier while its peers wait on it."""
        monkeypatch.setenv("PADDLE_TRAINERS_NUM", "2")
        monkeypatch.setenv("MASTER_ADDR", "127.0.0.1")
        monkeypatch.setenv("MASTER_PORT", "auto")
        with pytest.raises(ValueError, match="MASTER_PORT"):
            coordinator_from_env()

    def test_missing_rank_fails_loudly(self, master, monkeypatch):
        """A >=2 fleet without PADDLE_TRAINER_ID must raise a named error:
        defaulting to rank 0 would have EVERY host vote as rank 0 and
        each coordinated save burn the barrier timeout."""
        monkeypatch.setenv("PADDLE_TRAINERS_NUM", "2")
        monkeypatch.setenv("MASTER_ADDR", "127.0.0.1")
        monkeypatch.setenv("MASTER_PORT", str(master.port))
        monkeypatch.delenv("PADDLE_TRAINER_ID", raising=False)
        with pytest.raises(ValueError, match="PADDLE_TRAINER_ID"):
            coordinator_from_env()

    def test_namespace_follows_restart_num(self, master, monkeypatch):
        monkeypatch.setenv("PADDLE_TRAINERS_NUM", "2")
        monkeypatch.setenv("PADDLE_TRAINER_ID", "0")
        monkeypatch.setenv("MASTER_ADDR", "127.0.0.1")
        monkeypatch.setenv("MASTER_PORT", str(master.port))
        monkeypatch.setenv("PADDLE_TPU_ELASTIC_RESTART_NUM", "4")
        co = coordinator_from_env()
        assert co.namespace == "ckptbar/4"


class TestAbortExitContract:
    """FaultTolerantCheckpoint implements the generation-resync contract:
    persistent coordinated-save aborts exit ELASTIC_EXIT_CODE so the
    elastic supervisors relaunch the whole fleet into one generation."""

    def _cb(self, tmp_path, committed_seq):
        from paddle_tpu.hapi.callbacks import FaultTolerantCheckpoint
        cb = FaultTolerantCheckpoint(str(tmp_path), coordinator=None,
                                     preemption_save=False)
        seq = list(committed_seq)

        class FakeMgr:
            coordinator = object()  # coordinated manager

            def save(self, state, step):
                return seq.pop(0)

            def uninstall_preemption_handler(self):
                pass
        cb.manager = FakeMgr()
        cb._capture = lambda: {}
        return cb

    def test_consecutive_aborts_exit_101(self, tmp_path):
        from paddle_tpu.distributed.fleet.elastic import ELASTIC_EXIT_CODE
        cb = self._cb(tmp_path, [False, False])
        cb._save()  # first abort tolerated (transiently slow peer)
        with pytest.raises(SystemExit) as e:
            cb._save()
        assert e.value.code == ELASTIC_EXIT_CODE

    def test_committed_save_resets_the_streak(self, tmp_path):
        cb = self._cb(tmp_path, [False, True, False])
        cb._save()
        cb._save()  # commit resets the abort streak
        cb._save()  # a single new abort: no exit
        assert cb._aborted_saves == 1

    def test_knob_disables_exit(self, tmp_path, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_CKPT_ABORT_EXIT", "0")
        cb = self._cb(tmp_path, [False] * 5)
        for _ in range(5):
            cb._save()


class TestCkptInspectDir:
    def _mkdir(self, tmp_path):
        d = str(tmp_path)
        dist_ckpt.save(_state(1), os.path.join(d, "ckpt_1"))
        dist_ckpt.save(_state(2), os.path.join(d, "ckpt_2"))
        # step 3: prepared by the barrier but never renamed (torn tmp)
        with open(os.path.join(d, "ckpt_3.tmp.prep"), "wb") as f:
            f.write(b"half a payload")
        # step 4: committed then corrupted on disk
        p4 = os.path.join(d, "ckpt_4")
        dist_ckpt.save(_state(4), p4)
        raw = open(p4, "rb").read()
        open(p4, "wb").write(raw[:-5])
        # step 5: an interrupted PLAIN atomic write (io._atomic_write
        # mkstemp suffix) — NOT a barrier tmp, must not read as torn
        with open(os.path.join(d, "ckpt_5.tmp.Ab3xQ9"), "wb") as f:
            f.write(b"half a plain write")
        return d

    def test_dir_status_classifies_steps(self, tmp_path):
        sys_path_guard = list(os.sys.path)
        os.sys.path.insert(0, os.path.join(REPO, "tools"))
        try:
            from ckpt_inspect import dir_status
        finally:
            os.sys.path[:] = sys_path_guard
        st = dir_status(self._mkdir(tmp_path))
        by_step = {e["step"]: e["status"] for e in st["steps"]}
        assert by_step == {1: "committed", 2: "committed",
                           3: "torn-tmp", 4: "corrupt", 5: "stale-tmp"}
        assert st["newest_valid"] == 2
        assert [e["step"] for e in st["steps"]] == [5, 4, 3, 2, 1]  # newest 1st

    def test_cli_dir_report(self, tmp_path, capsys):
        import subprocess
        import sys as _sys
        d = self._mkdir(tmp_path)
        env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
        out = subprocess.run(
            [_sys.executable, os.path.join(REPO, "tools", "ckpt_inspect.py"),
             "--dir", d], env=env, capture_output=True, text=True, timeout=120)
        assert out.returncode != 0  # corrupt file present -> nonzero exit
        assert "torn-tmp" in out.stdout
        assert "newest-valid: step 2" in out.stdout


class TestBarrierKeyGC:
    """Store-key GC for resolved rounds (carried ROADMAP follow-up): each
    host lag-2-deletes its OWN prep key and the round's abort flag once a
    round resolves, so flags stop accreting in the master store for the
    job's lifetime."""

    @staticmethod
    def _round_keys(coord, round_id, step):
        return [coord._k("prep", round_id, step, coord.rank),
                coord._k("abort", round_id, step)]

    def test_resolved_round_keys_are_gced_with_lag_two(self, master,
                                                       tmp_path):
        m0 = _manager(master, 0, tmp_path)
        m1 = _manager(master, 1, tmp_path)
        n_rounds = 5
        for step in range(1, n_rounds + 1):
            res = {}
            _join_all([
                threading.Thread(
                    target=lambda s=step: res.update(a=m0.save(_state(), s))),
                threading.Thread(
                    target=lambda s=step: res.update(b=m1.save(_state(), s))),
            ])
            assert res == {"a": True, "b": True}
        probe = TCPStore("127.0.0.1", master.port)
        lag = m0.coordinator.GC_LAG
        for m in (m0, m1):
            c = m.coordinator
            # rounds are 0-based; rounds older than newest-lag are gone
            for r in range(n_rounds - lag):
                for key in self._round_keys(c, r, r + 1):
                    assert not probe.check(key), \
                        f"round {r} key {key!r} survived GC"
            # the newest `lag` rounds keep their prep votes (not yet GCd)
            newest = n_rounds - 1
            assert probe.check(c._k("prep", newest, n_rounds, c.rank))
        # bound: per host, at most GC_LAG rounds of keys remain
        assert len(m0.coordinator._round_steps) <= lag
        assert len(m1.coordinator._round_steps) <= lag

    def test_aborted_round_keys_are_gced_too(self, master, tmp_path):
        """Abort flags are exactly what accretes on a flaky fleet — they
        must be GC'd once later rounds prove everyone moved on."""
        m0 = _manager(master, 0, tmp_path, timeout=0.3)
        m1 = _manager(master, 1, tmp_path, timeout=0.3)
        with pytest.warns(UserWarning, match="aborted"):
            assert m0.save(_state(), 1) is False  # round 0: peer missing
        # peer consumes its round 0 too (lockstep, also aborts)
        with pytest.warns(UserWarning, match="aborted"):
            assert m1.save(_state(), 1) is False
        abort_key = m0.coordinator._k("abort", 0, 1)
        probe = TCPStore("127.0.0.1", master.port)
        assert probe.check(abort_key)  # round 0 abort flag exists
        for step in range(2, 5):  # rounds 1..3 commit in lockstep
            res = {}
            _join_all([
                threading.Thread(
                    target=lambda s=step: res.update(a=m0.save(_state(), s))),
                threading.Thread(
                    target=lambda s=step: res.update(b=m1.save(_state(), s))),
            ])
            assert res == {"a": True, "b": True}
        assert not probe.check(abort_key), "aborted round's flag never GCd"

    def test_resume_round_keys_are_gced(self, master, tmp_path):
        m0 = _manager(master, 0, tmp_path)
        m1 = _manager(master, 1, tmp_path)
        for step in (1, 2):
            res = {}
            _join_all([
                threading.Thread(
                    target=lambda s=step: res.update(a=m0.save(_state(), s))),
                threading.Thread(
                    target=lambda s=step: res.update(b=m1.save(_state(), s))),
            ])
            assert res == {"a": True, "b": True}
        for _ in range(4):  # four lockstep resume negotiations
            res = {}
            _join_all([
                threading.Thread(target=lambda: res.update(a=m0.load_latest())),
                threading.Thread(target=lambda: res.update(b=m1.load_latest())),
            ])
            assert res["a"][1] == res["b"][1] == 2
        probe = TCPStore("127.0.0.1", master.port)
        lag = m0.coordinator.GC_LAG
        for m in (m0, m1):
            c = m.coordinator
            newest = c._resume_round
            for r in range(1, newest - lag + 1):
                assert not probe.check(c._k("resume", r, c.rank)), \
                    f"resume round {r} key survived GC"
            assert probe.check(c._k("resume", newest, c.rank))
