"""Recompile/retrace watchdog (profiler/watchdog.py) wired through the jit
entry points: the eager dispatch cache, jit.to_static, and TrainStep.

On TPU a silent retrace is THE perf killer this PR exists to surface: the
acceptance test deliberately changes an input shape across jit calls and
asserts the miss counter moves and the structured event names the changed
dimension.
"""
import logging

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.framework import flags
from paddle_tpu.ops import _dispatch
from paddle_tpu.profiler import metrics
from paddle_tpu.profiler.watchdog import (RetraceWatchdog, describe_delta,
                                          get_watchdog, signature_of)


@pytest.fixture()
def wd():
    w = get_watchdog()
    w.reset()
    yield w
    w.reset()


class TestDeltaNaming:
    def test_shape_delta_names_dimension(self):
        old = signature_of([np.ones((4, 8), np.float32)])
        new = signature_of([np.ones((6, 8), np.float32)])
        d = describe_delta(old, new)
        assert "dim0 4->6" in d and "(4, 8)" in d and "(6, 8)" in d

    def test_dtype_delta(self):
        old = signature_of([np.ones((2,), np.float32)])
        new = signature_of([np.ones((2,), np.int32)])
        assert "dtype float32->int32" in describe_delta(old, new)

    def test_rank_and_arity_delta(self):
        a = signature_of([np.ones((2, 3), np.float32)])
        b = signature_of([np.ones((2, 3, 4), np.float32)])
        assert "rank 2->3" in describe_delta(a, b)
        c = signature_of([np.ones((2,)), np.ones((2,))])
        assert "arity 1->2" in describe_delta(a, c)

    def test_static_args_delta(self):
        old = signature_of([np.ones((2,))], static={"axis": 0})
        new = signature_of([np.ones((2,))], static={"axis": 1})
        assert "static args" in describe_delta(old, new)
        assert "axis" in describe_delta(old, new)


class TestWatchdogCore:
    def test_first_compile_is_not_a_retrace(self, wd):
        assert wd.observe("s", "f", [np.ones((2,))]) is None
        assert wd.total_retraces() == 0

    def test_repeat_signature_is_hit(self, wd):
        wd.observe("s", "f", [np.ones((2,))])
        assert wd.observe("s", "f", [np.ones((2,))]) is None
        assert wd.total_retraces() == 0

    def test_new_signature_is_retrace_with_delta(self, wd):
        wd.observe("s", "f", [np.ones((2, 4), np.float32)])
        ev = wd.observe("s", "f", [np.ones((3, 4), np.float32)])
        assert ev is not None and ev.count == 1
        assert "dim0 2->3" in ev.delta
        assert wd.total_retraces("s") == 1
        assert wd.counts() == {"s:f": 1}
        snap = wd.snapshot()
        assert snap["total_retraces"] == 1
        assert snap["events"][-1]["delta"] == ev.delta

    def test_seen_signatures_become_hits(self, wd):
        """A->B->A: the return to A is a cache HIT (both signatures hold a
        compiled executable), so only the first A->B transition counts as a
        retrace — the counter measures compiles, not signature flips."""
        a, b = [np.ones((2,))], [np.ones((3,))]
        wd.observe("s", "f", a)
        wd.observe("s", "f", b)
        # both signatures now seen: further calls are hits, not retraces
        assert wd.observe("s", "f", a) is None
        assert wd.total_retraces() == 1

    def test_warn_threshold_logs_once_per_window(self, wd, caplog):
        wd.warn_threshold = 2
        with caplog.at_level(logging.WARNING, logger="paddle_tpu.retrace"):
            for n in (1, 2, 3, 4):
                wd.observe("s", "hot_op", [np.ones((n, 8))])
        warns = [r for r in caplog.records if "retraced" in r.getMessage()]
        assert len(warns) == 1
        assert "hot_op" in warns[0].getMessage()
        caplog.clear()
        wd.reset_window()
        with caplog.at_level(logging.WARNING, logger="paddle_tpu.retrace"):
            for n in (5, 6, 7):
                wd.observe("s", "hot_op", [np.ones((n, 8))])
        assert any("retraced" in r.getMessage() for r in caplog.records)

    def test_counters_mirrored_to_metrics(self, wd):
        reg = metrics.default_registry()
        misses0 = reg.counter("jit_cache_misses_total").value(site="tw")
        retr0 = reg.counter("jit_retraces_total").value(site="tw")
        wd.observe("tw", "f", [np.ones((2,))])
        wd.observe("tw", "f", [np.ones((3,))])
        wd.observe("tw", "f", [np.ones((3,))])  # hit
        assert reg.counter("jit_cache_misses_total").value(site="tw") \
            == misses0 + 2
        assert reg.counter("jit_retraces_total").value(site="tw") == retr0 + 1


class TestJitWiring:
    def test_to_static_shape_change_observed(self, wd):
        """Acceptance: deliberately change an input shape across jit calls;
        the miss counter increments and the event names the dimension."""
        reg = metrics.default_registry()
        miss0 = reg.counter("jit_cache_misses_total").value(site="to_static")

        @paddle.jit.to_static
        def double(a):
            return a * 2.0

        double(paddle.to_tensor(np.ones((4, 8), np.float32)))
        double(paddle.to_tensor(np.ones((6, 8), np.float32)))
        assert reg.counter("jit_cache_misses_total").value(site="to_static") \
            >= miss0 + 2
        evs = [e for e in wd.events if e.site == "to_static"]
        assert evs, "shape change must produce a retrace event"
        assert "dim0 4->6" in evs[-1].delta

    def test_static_layer_batch_size_change_observed(self, wd):
        layer = paddle.jit.to_static(nn.Linear(8, 4))
        layer(paddle.to_tensor(np.ones((2, 8), np.float32)))
        layer(paddle.to_tensor(np.ones((5, 8), np.float32)))
        evs = [e for e in wd.events if e.site == "to_static"]
        assert evs and "2->5" in evs[-1].delta

    def test_eager_cache_miss_notes_watchdog(self, wd):
        _dispatch.clear_eager_cache()
        flags.set_flags({"FLAGS_eager_op_cache": True})
        x4 = paddle.to_tensor(np.ones((4, 4), np.float32))
        x6 = paddle.to_tensor(np.ones((6, 6), np.float32))
        with paddle.no_grad():
            (x4 @ x4).numpy()
            (x6 @ x6).numpy()
        evs = [e for e in wd.events if e.site == "eager"]
        assert any("matmul" == e.name and "4" in e.delta and "6" in e.delta
                   for e in evs), [(e.name, e.delta) for e in evs]

    def test_stable_shapes_do_not_retrace(self, wd):
        @paddle.jit.to_static
        def f(a):
            return a + 1.0

        for _ in range(4):
            f(paddle.to_tensor(np.ones((3, 3), np.float32)))
        assert wd.total_retraces("to_static") == 0

    def test_to_static_function_jits_once_per_signature(self, wd):
        """Regression (found by this PR's watchdog work): the function path
        used to rebuild its @jax.jit wrapper per call, re-tracing every
        invocation while the watchdog showed the site retrace-free. The
        trace count — the fn body runs only at trace time under jit — must
        match the number of DISTINCT signatures, not the number of calls."""
        traces = []

        @paddle.jit.to_static
        def g(a):
            traces.append(1)
            return a * 3.0

        for _ in range(4):
            g(paddle.to_tensor(np.ones((2, 2), np.float32)))
        assert len(traces) == 1, f"re-traced {len(traces)}x for one signature"
        g(paddle.to_tensor(np.ones((5, 2), np.float32)))
        assert len(traces) == 2
        assert wd.total_retraces("to_static") == 1

    def test_seen_set_is_bounded(self):
        w = RetraceWatchdog()
        w._SEEN_MAX = 8
        for n in range(50):
            w.observe("s", "f", [np.ones((n + 1,))])
        assert len(w._seen[("s", "f")]) <= 8

    def test_kwargs_order_does_not_fake_a_retrace(self, wd):
        """Two call sites building identical static kwargs in different
        insertion orders share ONE signature (matching the eager cache's
        sorted canonicalization)."""
        a = signature_of([np.ones((2,))], static={"axis": 0, "keepdim": True})
        b = signature_of([np.ones((2,))], static={"keepdim": True, "axis": 0})
        assert a == b
        wd.observe("s", "f", [np.ones((2,))],
                   static={"axis": 0, "keepdim": True})
        wd.observe("s", "f", [np.ones((2,))],
                   static={"keepdim": True, "axis": 0})
        assert wd.total_retraces() == 0

    def test_static_layer_instances_do_not_cross_talk(self, wd):
        """Each StaticLayer owns a jit cache, so the watchdog key is per
        instance: a second instance's first compile (any batch size) is a
        first compile, not a retrace of the first instance."""
        l1 = paddle.jit.to_static(nn.Linear(4, 2))
        l2 = paddle.jit.to_static(nn.Linear(4, 2))
        l1(paddle.to_tensor(np.ones((2, 4), np.float32)))
        l2(paddle.to_tensor(np.ones((7, 4), np.float32)))
        assert wd.total_retraces("to_static") == 0


class TestToStaticLiveness:
    """The hoisted one-jit-per-conversion function path must not freeze
    closure state or randomness as trace constants."""

    def test_closure_tensor_updates_stay_visible(self, wd):
        w = paddle.to_tensor(np.full((3,), 2.0, np.float32))

        @paddle.jit.to_static
        def scale(x):
            return x * w

        x = paddle.to_tensor(np.ones((3,), np.float32))
        np.testing.assert_allclose(scale(x).numpy(), [2, 2, 2])
        w.data = paddle.to_tensor(np.full((3,), 5.0, np.float32)).data
        # same input signature -> jit cache HIT, yet the new value must land
        np.testing.assert_allclose(scale(x).numpy(), [5, 5, 5])

    def test_closure_layer_params_stay_visible(self, wd):
        lin = nn.Linear(3, 3)

        @paddle.jit.to_static
        def fwd(x):
            return lin(x)

        x = paddle.to_tensor(np.ones((2, 3), np.float32))
        before = fwd(x).numpy()
        for p in lin.parameters():
            p.data = (p + 1.0).data
        after = fwd(x).numpy()
        assert not np.allclose(before, after), \
            "parameter update was baked into the compiled function"

    def test_independent_conversions_do_not_cross_talk(self, wd):
        """Each to_static(fn) call owns a fresh jit cache, so the watchdog
        key is per conversion: the second conversion's first compile at a
        different shape is a first compile, not a retrace of the first."""
        def fn(a):
            return a + 1.0

        f1 = paddle.jit.to_static(fn)
        f2 = paddle.jit.to_static(fn)
        f1(paddle.to_tensor(np.ones((1, 2), np.float32)))
        f2(paddle.to_tensor(np.ones((2, 2), np.float32)))
        assert wd.total_retraces("to_static") == 0

    def test_closure_cell_rebinding_stays_visible(self, wd):
        """`nonlocal w; w = new_tensor` after conversion must reach the
        compiled function (cells are re-read per call, not snapshot once)."""
        w = paddle.to_tensor(np.full((3,), 2.0, np.float32))

        def fn(x):
            return x * w

        f = paddle.jit.to_static(fn)
        x = paddle.to_tensor(np.ones((3,), np.float32))
        np.testing.assert_allclose(f(x).numpy(), [2, 2, 2])
        w = paddle.to_tensor(np.full((3,), 7.0, np.float32))  # rebind cell
        np.testing.assert_allclose(f(x).numpy(), [7, 7, 7])

    def test_kwargs_rejected_loudly(self, wd):
        """The compiled function path is positional-only: silently tracing
        with defaults returned wrong numbers, so kwargs must raise."""
        def fn(x, scale=1.0):
            return x * scale

        f = paddle.jit.to_static(fn)
        x = paddle.to_tensor(np.ones((2,), np.float32))
        np.testing.assert_allclose(f(x, 2.0).numpy(), [2, 2])
        with pytest.raises(TypeError, match="scale"):
            f(x, scale=2.0)

    def test_closure_tensor_shape_change_is_a_visible_retrace(self, wd):
        """A closure tensor whose SHAPE changes re-traces the jit exactly
        like an input change — the watchdog must see it (aux rides the
        observed signature)."""
        w = paddle.to_tensor(np.ones((3,), np.float32))

        def fn(x):
            return x * w

        f = paddle.jit.to_static(fn)
        x3 = paddle.to_tensor(np.ones((3,), np.float32))
        f(x3)
        w = paddle.to_tensor(np.ones((1,), np.float32))  # broadcastable
        f(x3)
        assert wd.total_retraces("to_static") == 1
        assert "3" in wd.events[-1].delta and "1" in wd.events[-1].delta

    def test_module_global_layer_params_stay_visible(self, wd):
        """The common global-model pattern: a to_static function referencing
        a module-global Layer must see parameter updates (globals the code
        references are captured and threaded like closure cells)."""
        import types
        mod = types.ModuleType("_tsg_mod")
        exec(
            "import paddle_tpu as paddle\n"
            "from paddle_tpu import nn\n"
            "lin = nn.Linear(2, 1)\n"
            "def fwd(x):\n"
            "    return lin(x)\n", mod.__dict__)
        f = paddle.jit.to_static(mod.fwd)
        x = paddle.to_tensor(np.ones((1, 2), np.float32))
        before = f(x).numpy()
        for p in mod.lin.parameters():
            p.data = (p + 1.0).data
        after = f(x).numpy()
        np.testing.assert_allclose(after - before, [[3.0]], rtol=1e-5), \
            "global layer's parameter update was baked in as a constant"

    def test_static_layer_kw_shape_change_observed(self, wd):
        """kw arguments ride the jit signature too: a varying kw shape is a
        retrace the watchdog must see."""
        class WithMask(nn.Layer):
            def __init__(self):
                super().__init__()
                self.lin = nn.Linear(4, 4)

            def forward(self, x, mask=None):
                out = self.lin(x)
                return out * mask if mask is not None else out

        st = paddle.jit.to_static(WithMask())
        x = paddle.to_tensor(np.ones((2, 4), np.float32))
        st(x, mask=paddle.to_tensor(np.ones((2, 4), np.float32)).data)
        st(x, mask=paddle.to_tensor(np.ones((1, 4), np.float32)).data)
        assert wd.total_retraces("to_static") == 1
        assert "2" in wd.events[-1].delta and "1" in wd.events[-1].delta

    def test_randomness_stays_fresh_across_calls(self, wd):
        from paddle_tpu.nn import functional as F

        @paddle.jit.to_static
        def drop(x):
            return F.dropout(x, p=0.5, training=True)

        x = paddle.to_tensor(np.ones((64, 64), np.float32))
        outs = [drop(x).numpy() for _ in range(3)]
        assert not np.allclose(outs[0], outs[1])
        assert not np.allclose(outs[1], outs[2])
