"""Test config: force an 8-device virtual CPU mesh before jax initializes.

Mirrors the reference's test strategy of simulating clusters on localhost
(`/root/reference/python/paddle/fluid/tests/unittests/test_dist_base.py:968`):
distributed tests run on 8 virtual CPU devices via
--xla_force_host_platform_device_count.
"""
import os

os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=8")
if "--xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] += " --xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture(autouse=True)
def _seed_all():
    import paddle_tpu as paddle
    paddle.seed(1234)
    np.random.seed(1234)
    yield
    from paddle_tpu.framework import tape
    tape.reset_tape()
