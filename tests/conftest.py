"""Test config: force an 8-device virtual CPU mesh before jax initializes.

Mirrors the reference's test strategy of simulating clusters on localhost
(`/root/reference/python/paddle/fluid/tests/unittests/test_dist_base.py:968`):
distributed tests run on 8 virtual CPU devices via
--xla_force_host_platform_device_count.
"""
import os

os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=8")
if "--xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] += " --xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


def pytest_addoption(parser):
    parser.addoption(
        "--slow", action="store_true", default=False,
        help="also run tests marked slow (multi-process cluster variants, "
             "long convergence runs); default suite skips them to stay "
             "under the CI wall-clock budget")


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running test, needs --slow to run")


def pytest_collection_modifyitems(config, items):
    if config.getoption("--slow"):
        return
    skip = pytest.mark.skip(reason="slow: pass --slow to run")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip)


@pytest.fixture(autouse=True)
def _seed_all():
    import paddle_tpu as paddle
    paddle.seed(1234)
    np.random.seed(1234)
    yield
    from paddle_tpu.framework import tape
    tape.reset_tape()
