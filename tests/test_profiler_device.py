"""Tests: paddle_tpu.profiler, framework.flags (+NaN check), paddle_tpu.device.

Reference suites: `unittests/test_profiler.py`, `test_newprofiler.py`,
`test_nan_inf.py`, `test_get_set_flags.py`, `test_cuda_*` device tests.
"""
import json
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import profiler as prof


class TestScheduler:
    def test_make_scheduler_windows(self):
        sch = prof.make_scheduler(closed=1, ready=1, record=2, repeat=1,
                                  skip_first=1)
        states = [sch(i) for i in range(6)]
        S = prof.ProfilerState
        assert states == [S.CLOSED, S.CLOSED, S.READY, S.RECORD,
                          S.RECORD_AND_RETURN, S.CLOSED]

    def test_repeat(self):
        sch = prof.make_scheduler(closed=0, ready=0, record=1, repeat=2)
        S = prof.ProfilerState
        assert sch(0) == S.RECORD_AND_RETURN
        assert sch(1) == S.RECORD_AND_RETURN
        assert sch(2) == S.CLOSED


class TestRecordEventAndExport:
    def test_spans_collected_and_exported(self, tmp_path):
        traces = []
        p = prof.Profiler(
            targets=[prof.ProfilerTarget.CPU],
            scheduler=prof.make_scheduler(closed=0, ready=0, record=3, repeat=1),
            on_trace_ready=lambda pr: traces.append(
                pr.export(str(tmp_path / "trace.json"))))
        p.start()
        for i in range(3):
            with prof.RecordEvent("train_step"):
                with prof.RecordEvent("forward"):
                    x = paddle.to_tensor(np.ones((8, 8), np.float32))
                    (x @ x).numpy()
            p.step()
        p.stop()
        assert traces, "on_trace_ready never fired"
        data = json.load(open(traces[0]))
        names = [e["name"] for e in data["traceEvents"]]
        assert names.count("train_step") == 3
        assert names.count("forward") == 3
        fwd = [e for e in data["traceEvents"] if e["name"] == "forward"][0]
        assert fwd["args"].get("parent") == "train_step"
        assert fwd["dur"] > 0

    def test_back_to_back_windows_all_export(self, tmp_path):
        traces = []
        p = prof.Profiler(
            targets=[prof.ProfilerTarget.CPU],
            scheduler=prof.make_scheduler(closed=0, ready=0, record=2, repeat=2),
            on_trace_ready=lambda pr: traces.append(
                pr.export(str(tmp_path / f"w{len(traces)}.json"))))
        p.start()
        for _ in range(4):
            with prof.RecordEvent("s"):
                pass
            p.step()
        p.stop()
        assert len(traces) == 2, f"each record window must export, got {len(traces)}"
        for t in traces:
            assert len(json.load(open(t))["traceEvents"]) == 2

    def test_statistics_summary(self):
        p = prof.Profiler(targets=[prof.ProfilerTarget.CPU])
        p.start()
        for _ in range(2):
            with prof.RecordEvent("opA"):
                pass
        with prof.RecordEvent("opB"):
            pass
        p.stop()
        stat = p.statistic_data()
        assert stat.by_name["opA"].calls == 2
        assert stat.by_name["opB"].calls == 1
        report = prof.summary_report(stat)
        assert "opA" in report and "Calls" in report

    def test_record_event_disabled_is_cheap(self):
        # outside a Profiler window spans are dropped
        from paddle_tpu.profiler.recorder import get_recorder
        get_recorder().clear()
        with prof.RecordEvent("ghost"):
            pass
        assert all(s.name != "ghost" for s in get_recorder().collect())

    def test_load_profiler_result(self, tmp_path):
        p = prof.Profiler(targets=[prof.ProfilerTarget.CPU])
        p.start()
        with prof.RecordEvent("x"):
            pass
        p.stop()
        path = p.export(str(tmp_path / "t.json"))
        data = prof.load_profiler_result(path)
        assert data["metadata"]["producer"] == "paddle_tpu.profiler"


class TestBenchmarkTimer:
    def test_ips(self):
        p = prof.Profiler(timer_only=True)
        p.start()
        for _ in range(5):
            p.step(num_samples=32)
        p.stop()
        info = p.step_info()
        assert "batch_cost" in info and "ips" in info
        rep = prof.benchmark().report()
        assert rep["total_samples"] >= 160


class TestFlags:
    def test_get_set(self):
        flags = paddle.get_flags(["FLAGS_check_nan_inf", "FLAGS_benchmark"])
        assert flags["FLAGS_check_nan_inf"] is False
        paddle.set_flags({"FLAGS_benchmark": True})
        assert paddle.get_flags("FLAGS_benchmark")["FLAGS_benchmark"] is True
        paddle.set_flags({"FLAGS_benchmark": False})

    def test_unknown_flag_raises(self):
        with pytest.raises(ValueError):
            paddle.get_flags("FLAGS_not_a_real_flag")

    def test_nan_check(self):
        paddle.set_flags({"FLAGS_check_nan_inf": True})
        try:
            x = paddle.to_tensor(np.array([1.0, 0.0], np.float32))
            # 0/0 -> NaN; either the per-op dispatch check or jax_debug_nans
            # (whichever sees it first) must raise
            with pytest.raises(FloatingPointError, match="div|NaN|nan"):
                (x / x).log()
        finally:
            paddle.set_flags({"FLAGS_check_nan_inf": False})

    def test_nan_check_off_by_default(self):
        x = paddle.to_tensor(np.array([0.0], np.float32))
        out = (x / x).numpy()  # nan, but no raise
        assert np.isnan(out).all()


class TestDevice:
    def test_discovery(self):
        types = paddle.device.get_all_device_type()
        assert "cpu" in types or "tpu" in types
        avail = paddle.device.get_available_device()
        assert len(avail) >= 1

    def test_compiled_with(self):
        assert paddle.device.is_compiled_with_cuda() is False
        assert isinstance(paddle.device.is_compiled_with_tpu(), bool)

    def test_synchronize_and_streams(self):
        paddle.device.synchronize()
        s = paddle.device.cuda.current_stream()
        ev = s.record_event()
        assert ev.query()
        with paddle.device.cuda.stream_guard(paddle.device.cuda.Stream()):
            x = paddle.to_tensor(np.ones(4, np.float32)) * 2
        s.synchronize()
        np.testing.assert_allclose(x.numpy(), 2.0)

    def test_memory_stats_shape(self):
        # numbers are device dependent; just exercise the API
        assert paddle.device.cuda.memory_allocated() >= 0
        assert paddle.device.cuda.max_memory_allocated() >= 0
        props = paddle.device.cuda.get_device_properties()
        assert props.multi_processor_count >= 1

    def test_device_tpu_alias(self):
        assert paddle.device.tpu is paddle.device.cuda
