"""Autograd tape + functional transforms tests.

Reference test analog: `unittests/autograd/` + eager grad tests.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.framework.param import Parameter
from paddle_tpu.framework.tensor import Tensor


def test_backward_chain():
    x = Parameter(np.array([2.0, 3.0], np.float32))
    y = (x * x + x).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), 2 * x.numpy() + 1)


def test_grad_accumulation():
    x = Parameter(np.ones(3, np.float32))
    (x * 2).sum().backward()
    (x * 3).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [5.0] * 3)
    x.clear_grad()
    assert x.grad is None


def test_stop_gradient():
    x = Parameter(np.ones(3, np.float32))
    y = Tensor(np.ones(3, np.float32))  # stop_gradient=True
    z = (x * y).sum()
    z.backward()
    assert x.grad is not None and y.grad is None


def test_no_grad():
    x = Parameter(np.ones(3, np.float32))
    with paddle.no_grad():
        y = (x * x).sum()
    assert y.stop_gradient
    from paddle_tpu.framework import tape
    assert tape.tape_size() == 0


def test_detach():
    x = Parameter(np.ones(3, np.float32))
    y = x * 2
    d = y.detach()
    assert d.stop_gradient
    (d * x).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0] * 3)


def test_paddle_grad_api():
    x = Parameter(np.array([1.0, 2.0], np.float32))
    y = (x ** 3.0).sum()
    gx, = paddle.grad(y, [x])
    np.testing.assert_allclose(gx.numpy(), 3 * x.numpy() ** 2, rtol=1e-5)
    assert x.grad is None  # paddle.grad must not write .grad


def test_multi_output_op_grad():
    x = Parameter(np.random.randn(4, 5).astype(np.float32))
    vals, idx = paddle.topk(x, 2, axis=1)
    vals.sum().backward()
    g = x.grad.numpy()
    assert (g.sum(axis=1) == 2).all()


def test_fanin_accumulation():
    x = Parameter(np.array([2.0], np.float32))
    a = x * 3
    b = x * 4
    (a + b).backward()
    np.testing.assert_allclose(x.grad.numpy(), [7.0])


def test_retain_graph():
    x = Parameter(np.array([2.0], np.float32))
    y = x * x
    y.backward(retain_graph=True)
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [8.0])


def test_functional_vjp_jvp():
    from paddle_tpu import autograd

    def f(x):
        return x.exp().sum()

    x = Tensor(np.array([0.0, 1.0], np.float32))
    out, g = autograd.vjp(f, x)
    np.testing.assert_allclose(g.numpy(), np.exp(x.numpy()), rtol=1e-5)
    out, jv = autograd.jvp(f, x)
    np.testing.assert_allclose(jv.numpy(), np.exp(x.numpy()).sum(), rtol=1e-5)


def test_jacobian_hessian():
    from paddle_tpu import autograd

    def f(x):
        return (x * x).sum()

    x = Tensor(np.array([1.0, 2.0, 3.0], np.float32))
    h = autograd.Hessian(f, x)
    np.testing.assert_allclose(h[:].numpy(), 2 * np.eye(3), atol=1e-5)


def test_pylayer():
    from paddle_tpu.autograd import PyLayer

    class Double(PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x * 2

        @staticmethod
        def backward(ctx, dy):
            return dy * 2

    x = Parameter(np.array([3.0], np.float32))
    y = Double.apply(x)
    y.backward()
    np.testing.assert_allclose(y.numpy(), [6.0])
    np.testing.assert_allclose(x.grad.numpy(), [2.0])


def test_double_grad_functional():
    # higher-order via functional transforms (tape create_graph unsupported)
    import jax
    import jax.numpy as jnp
    g2 = jax.grad(jax.grad(lambda x: jnp.sum(x ** 3)))(2.0)
    assert abs(float(g2) - 12.0) < 1e-5
