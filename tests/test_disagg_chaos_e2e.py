"""Chaos drill B (slow tier): prefill worker killed mid-prefill under
LIVE threaded traffic.

The ``disagg.prefill`` fault site kills a PrefillWorker while the
pipeline's worker threads and the decode engine loop are all running.
Every in-flight request must complete with its ORIGINAL trace id and
greedy tokens bit-exact vs the colocated single-engine reference; the
``disagg_requeue_total`` / ``serving_stage_occupancy`` families must
reflect the reroute; the decode pools must recycle every page (zero
leaks). A second drill wipes out EVERY worker (respawn cap 0) and the
decode engine's own colocated prefill absorbs the full stream.

fast-sibling: tests/test_disagg.py
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import fault
from paddle_tpu.inference.disagg import DisaggPipeline
from paddle_tpu.inference.serving import ServingEngine
from paddle_tpu.models.gpt import GPT, GPTConfig
from paddle_tpu.profiler import events
from paddle_tpu.profiler import metrics as metrics_mod

pytestmark = pytest.mark.slow


@pytest.fixture(autouse=True)
def _clean_state():
    fault.reset()
    events.default_event_log().clear()
    yield
    fault.reset()
    events.default_event_log().clear()


@pytest.fixture(scope="module", autouse=True)
def _shared_compile_cache():
    """Shares test_serving.py's persistent-compile-cache dir — this
    drill compiles the same tiny-model executables."""
    import os
    import tempfile
    from paddle_tpu.framework import flags as flags_mod
    cache = os.path.join(tempfile.gettempdir(), "pt_serving_ccache")
    os.makedirs(cache, exist_ok=True)
    flags_mod.set_flags({"FLAGS_compile_cache_dir": cache})
    yield
    flags_mod.set_flags({"FLAGS_compile_cache_dir": ""})


def _model():
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=512, max_position_embeddings=128,
                    hidden_size=32, num_layers=2, num_heads=2,
                    dropout=0.0, attn_dropout=0.0)
    m = GPT(cfg)
    m.eval()
    return m, cfg


def _ref(m, prompt, n, page_size=8):
    ids = paddle.to_tensor(np.asarray([prompt], np.int32))
    out = np.asarray(m.generate_paged(ids, n, page_size=page_size).data)
    return out[0, len(prompt):].tolist()


def _traffic(cfg, n=8, seed=23):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, cfg.vocab_size,
                         (int(rng.integers(2, 20)),)).tolist()
            for _ in range(n)]


class TestDisaggChaos:
    def test_worker_killed_mid_prefill_under_live_traffic(self):
        """One worker dies mid-prefill with threads live: the reroute is
        invisible to clients — same trace ids, bit-exact tokens — and
        visible to operators — requeue counter, restart event, both
        stage-occupancy series."""
        m, cfg = _model()
        prompts = _traffic(cfg)
        reg = metrics_mod.default_registry()
        requeued0 = reg.get("disagg_requeue_total").value(
            reason="worker_error")
        restarts0 = reg.get("disagg_worker_restarts_total").value()
        eng = ServingEngine(m, max_batch=4, max_len=64, page_size=8,
                            name="chaosb")
        pipe = DisaggPipeline(eng, num_workers=2)
        pipe.start(poll_s=0.002)
        fault.configure("disagg.prefill", times=1)  # next dispatch dies
        reqs = [pipe.submit(p, max_new_tokens=8) for p in prompts]
        tids = [r.trace_id for r in reqs]
        outs = [r.result(timeout=60) for r in reqs]

        # client-visible contract: original trace ids, exact tokens
        for p, r, tid, out in zip(prompts, reqs, tids, outs):
            assert r.trace_id == tid, "reroute must keep the trace id"
            assert out == _ref(m, p, 8), \
                "worker death changed the greedy tokens"

        # operator-visible contract: the reroute is metered
        assert reg.get("disagg_requeue_total").value(
            reason="worker_error") == requeued0 + 1
        st = pipe.status()["stages"]["prefill"]
        assert sum(st["restarts"].values()) == 1
        assert st["alive"] == 2               # the slot respawned
        # (the disagg_worker_restart EVENT is asserted in the fast
        # sibling — under live traffic the lifecycle-trace flood can
        # rotate it out of the bounded ring; the counter is durable)
        assert reg.get("disagg_worker_restarts_total").value() == \
            restarts0 + 1
        # survivors absorbed the stream: no colocated fallback needed
        assert eng.stats["prefills"] == 0
        assert eng.stats["handoffs"] == len(prompts)
        stages = {v["labels"].get("stage")
                  for v in reg.get("serving_stage_occupancy")
                  .snapshot()["values"]
                  if v["labels"].get("model") == "chaosb"}
        assert stages == {"prefill", "decode"}

        # zero page leaks on the decode pools
        assert eng.status()["free_pages"] == eng.cache.num_pages - 1
        pipe.close()
        assert eng._closed

    def test_total_worker_loss_colocated_absorbs_live_stream(self):
        """Both workers die (respawn cap 0) with traffic in flight: the
        decode engine's own prefill is the last resort — everything
        still completes exactly, nothing strands in the queue."""
        m, cfg = _model()
        prompts = _traffic(cfg, n=6, seed=31)
        reg = metrics_mod.default_registry()
        colo0 = reg.get("disagg_requeue_total").value(reason="colocated")
        eng = ServingEngine(m, max_batch=4, max_len=64, page_size=8,
                            name="chaosb2")
        pipe = DisaggPipeline(eng, num_workers=2, max_worker_restarts=0)
        pipe.start(poll_s=0.002)
        fault.configure("disagg.prefill", times=2)  # both workers die
        reqs = [pipe.submit(p, max_new_tokens=6) for p in prompts]
        tids = [r.trace_id for r in reqs]
        outs = [r.result(timeout=60) for r in reqs]

        for p, r, tid, out in zip(prompts, reqs, tids, outs):
            assert r.trace_id == tid
            assert out == _ref(m, p, 6)

        st = pipe.status()["stages"]["prefill"]
        assert st["alive"] == 0               # cap 0: slots disabled
        assert reg.get("disagg_requeue_total").value(
            reason="colocated") > colo0
        # the decode engine prefilled whatever the dead workers dropped
        assert eng.stats["prefills"] >= len(prompts) - 2
        assert pipe.status()["queue_depth"] == 0
        assert eng.status()["free_pages"] == eng.cache.num_pages - 1
        pipe.close()
