"""OpTest harness — numeric-gradient checking utilities.

Reference: `unittests/op_test.py:289` (`check_output`, `check_grad` with
finite differences at `op_test.py:120`).
"""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.framework.param import Parameter
from paddle_tpu.framework.tensor import Tensor


def numeric_grad(fn, inputs, wrt=0, eps=1e-3):
    """Central finite differences of sum(fn(*inputs)) w.r.t. inputs[wrt]."""
    inputs = [np.asarray(x, np.float64) for x in inputs]
    base = inputs[wrt]
    grad = np.zeros_like(base)
    it = np.nditer(base, flags=["multi_index"])
    while not it.finished:
        idx = it.multi_index
        orig = base[idx]
        base[idx] = orig + eps
        plus = float(np.sum(np.asarray(fn(*[x.astype(np.float32) for x in inputs]))))
        base[idx] = orig - eps
        minus = float(np.sum(np.asarray(fn(*[x.astype(np.float32) for x in inputs]))))
        base[idx] = orig
        grad[idx] = (plus - minus) / (2 * eps)
        it.iternext()
    return grad


def check_grad(op, np_inputs, wrt=0, atol=5e-3, rtol=5e-3, **op_kwargs):
    """Compare tape backward() grads against finite differences."""
    params = [Parameter(x.astype(np.float32)) for x in np_inputs]
    out = op(*params, **op_kwargs)
    loss = paddle.sum(out) if not np.isscalar(out) else out
    loss.backward()
    analytic = params[wrt].grad.numpy()

    def fn(*xs):
        with paddle.no_grad():
            ts = [Tensor(x) for x in xs]
            return op(*ts, **op_kwargs).numpy()

    numeric = numeric_grad(fn, np_inputs, wrt=wrt)
    np.testing.assert_allclose(analytic, numeric, atol=atol, rtol=rtol)


def check_output(op, np_inputs, np_ref_fn, atol=1e-5, rtol=1e-5, **op_kwargs):
    ts = [Tensor(x) for x in np_inputs]
    out = op(*ts, **op_kwargs)
    ref = np_ref_fn(*np_inputs)
    if isinstance(out, (tuple, list)):
        for o, r in zip(out, ref):
            np.testing.assert_allclose(o.numpy(), r, atol=atol, rtol=rtol)
    else:
        np.testing.assert_allclose(out.numpy(), ref, atol=atol, rtol=rtol)
