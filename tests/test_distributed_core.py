"""Distributed core: topology, groups, collectives on the 8-device CPU mesh.

Mirrors the reference's collective-op tests
(`/root/reference/python/paddle/fluid/tests/unittests/test_collective_api_base.py`)
which assert numerical results of allreduce/allgather/… across local ranks —
here ranks are the 8 virtual devices of the conftest mesh.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from paddle_tpu._jax_compat import shard_map

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed.topology import (
    CommunicateTopology, HybridCommunicateGroup, build_mesh)


class TestTopology:
    def test_communicate_topology(self):
        # reference topology.py:36 semantics
        topo = CommunicateTopology(["data", "pipe", "model"], [2, 2, 2])
        assert topo.world_size() == 8
        assert topo.get_hybrid_group_names() == ["dp", "pp", "mp"]
        assert topo.get_dim("model") == 2
        assert topo.get_rank(dp=1, pp=0, mp=1) == 5
        assert topo.get_coord(5) == (1, 0, 1)
        assert topo.get_axis_list("dp", 0) == [0, 1, 2, 3]
        comm = topo.get_comm_list("mp")
        assert [0, 1] in comm and [6, 7] in comm and len(comm) == 4
        assert topo.get_rank_from_stage(0, pp=1) == 2

    def test_build_mesh_axis_order(self):
        mesh = build_mesh({"dp": 2, "mp": 2, "pp": 2})
        assert mesh.axis_names == ("dp", "pp", "mp")
        assert mesh.devices.shape == (2, 2, 2)

    def test_build_mesh_absorb_remaining(self):
        mesh = build_mesh({"mp": 2})
        assert mesh.axis_names == ("dp", "mp")
        assert mesh.devices.shape == (4, 2)

    def test_hcg(self):
        hcg = HybridCommunicateGroup(dims={"dp": 2, "mp": 4})
        assert hcg.get_data_parallel_world_size() == 2
        assert hcg.get_model_parallel_world_size() == 4
        assert hcg.get_pipe_parallel_world_size() == 1
        assert hcg.get_model_parallel_group().nranks == 4
        assert hcg.get_parallel_mode() == "model_parallel"


class TestEagerCollectives:
    """Eager collectives over sharded/replicated Tensors."""

    def setup_method(self, _):
        mesh = build_mesh({"dp": 8})
        hcg = HybridCommunicateGroup(mesh=mesh)
        dist.set_hybrid_communicate_group(hcg)
        dist.destroy_process_group()
        self.mesh = mesh
        self.group = dist.new_group(axis_name="dp")

    def teardown_method(self, _):
        dist.set_hybrid_communicate_group(None)
        dist.destroy_process_group()

    def _sharded(self, arr):
        return jax.device_put(arr, NamedSharding(self.mesh, P("dp")))

    def test_all_reduce_sum_sharded(self):
        # per-"rank" rows 0..7; all_reduce over a per-rank scalar view
        vals = np.arange(8, dtype=np.float32)
        x = paddle.to_tensor(self._sharded(vals))
        dist.all_reduce(x, group=self.group)
        np.testing.assert_allclose(x.numpy(), np.full(8, 28.0))

    def test_all_reduce_max_min(self):
        vals = np.arange(8, dtype=np.float32)
        x = paddle.to_tensor(self._sharded(vals.copy()))
        dist.all_reduce(x, op=dist.ReduceOp.MAX, group=self.group)
        np.testing.assert_allclose(x.numpy(), np.full(8, 7.0))
        y = paddle.to_tensor(self._sharded(vals.copy()))
        dist.all_reduce(y, op=dist.ReduceOp.MIN, group=self.group)
        np.testing.assert_allclose(y.numpy(), np.zeros(8))

    def test_all_reduce_replicated_counts_ranks(self):
        x = paddle.to_tensor(np.ones((4,), np.float32))
        dist.all_reduce(x, group=self.group)
        np.testing.assert_allclose(x.numpy(), np.full(4, 8.0))

    def test_broadcast(self):
        vals = np.arange(8, dtype=np.float32)
        x = paddle.to_tensor(self._sharded(vals))
        dist.broadcast(x, src=3, group=self.group)
        np.testing.assert_allclose(x.numpy(), np.full(8, 3.0))

    def test_all_gather(self):
        vals = np.arange(8, dtype=np.float32)
        x = paddle.to_tensor(self._sharded(vals))
        outs = []
        dist.all_gather(outs, x, group=self.group)
        assert len(outs) == 8
        for i, o in enumerate(outs):
            np.testing.assert_allclose(np.asarray(o), [float(i)])

    def test_reduce_scatter(self):
        # each rank holds [8] row -> after reduce_scatter each holds sum/8th
        vals = np.tile(np.arange(8, dtype=np.float32), (8, 1))  # [8,8]
        x = jax.device_put(vals, NamedSharding(self.mesh, P("dp", None)))
        out = paddle.to_tensor(np.zeros(8, np.float32))
        dist.reduce_scatter(out, paddle.to_tensor(x), group=self.group)
        # rank i gets sum over ranks of row-chunk i = 8 * i
        np.testing.assert_allclose(out.numpy(), 8.0 * np.arange(8))

    def test_barrier_and_wait(self):
        dist.barrier(self.group)
        t = paddle.to_tensor([1.0])
        assert dist.wait(t) is t


class TestInTraceCollectives:
    """SPMD path: collectives inside shard_map (the hot path)."""

    def test_psum_inside_shard_map(self):
        mesh = build_mesh({"dp": 8})
        g = dist.Group(mesh, ("dp",))

        def f(x):
            t = paddle.to_tensor(x)
            dist.all_reduce(t, group=g)
            return t.data

        vals = np.arange(8, dtype=np.float32).reshape(8, 1)
        out = shard_map(f, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"))(
            jnp.asarray(vals))
        np.testing.assert_allclose(np.asarray(out), np.full((8, 1), 28.0))

    def test_ppermute_ring(self):
        mesh = build_mesh({"pp": 8})
        g = dist.Group(mesh, ("pp",))

        def f(x):
            return dist.ppermute(x, group=g)

        vals = np.arange(8, dtype=np.float32).reshape(8, 1)
        out = shard_map(f, mesh=mesh, in_specs=P("pp"), out_specs=P("pp"))(
            jnp.asarray(vals))
        expect = np.roll(vals, 1, axis=0)
        np.testing.assert_allclose(np.asarray(out), expect)

    def test_alltoall_in_trace(self):
        mesh = build_mesh({"mp": 8})
        g = dist.Group(mesh, ("mp",))

        def f(x):
            return dist.alltoall(x, group=g)

        # rank r holds rows [r*8 .. r*8+7]; chunk c goes to rank c
        vals = np.arange(64, dtype=np.float32).reshape(64, 1)
        out = shard_map(f, mesh=mesh, in_specs=P("mp"), out_specs=P("mp"))(
            jnp.asarray(vals))
        got = np.asarray(out).reshape(8, 8)
        expect = np.arange(64).reshape(8, 8).T  # transpose of rank/chunk grid
        np.testing.assert_allclose(got, expect)


class TestParallelEnvAndDP:
    def test_parallel_env_defaults(self):
        env = dist.init_parallel_env()
        assert env.rank == 0
        assert dist.get_rank() == 0
        assert dist.get_world_size() >= 1

    def test_data_parallel_matches_single_device(self):
        from paddle_tpu import nn, optimizer
        from paddle_tpu.nn import functional as F

        mesh = build_mesh({"dp": 8})
        dist.set_hybrid_communicate_group(HybridCommunicateGroup(mesh=mesh))
        try:
            paddle.seed(7)
            net = nn.Linear(16, 4)
            ref_w = net.weight.numpy().copy()
            X = np.random.RandomState(0).randn(32, 16).astype(np.float32)
            Y = np.random.RandomState(1).randint(0, 4, (32,)).astype(np.int32)

            # single-device reference step
            opt = optimizer.SGD(learning_rate=0.1,
                                parameters=net.parameters())
            loss = F.cross_entropy(net(paddle.to_tensor(X)),
                                   paddle.to_tensor(Y))
            loss.backward()
            opt.step()
            ref_after = net.weight.numpy().copy()
            ref_loss = float(loss)

            # DP step: same math, batch sharded over 8 devices
            paddle.seed(7)
            net2 = nn.Linear(16, 4)
            np.testing.assert_allclose(net2.weight.numpy(), ref_w)
            dp = dist.DataParallel(net2)
            opt2 = optimizer.SGD(learning_rate=0.1,
                                 parameters=dp.parameters())
            xb = dist.shard_batch(paddle.to_tensor(X), mesh=mesh)
            yb = dist.shard_batch(paddle.to_tensor(Y), mesh=mesh)
            loss2 = F.cross_entropy(dp(xb), yb)
            loss2.backward()
            opt2.step()
            assert abs(float(loss2) - ref_loss) < 1e-5
            np.testing.assert_allclose(net2.weight.numpy(), ref_after,
                                       rtol=1e-5, atol=1e-6)
        finally:
            dist.set_hybrid_communicate_group(None)
            dist.destroy_process_group()


class TestEagerAllReduceSemantics:
    """Single-controller all_reduce semantics (docstring contract): a tensor
    SHARDED over the group axis reduces per-shard values — the case real
    data-parallel pipelines hit; a replicated tensor sums N equal copies."""

    def test_sharded_input_reduces_per_shard_values(self):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        dist.init_parallel_env()
        g = dist.new_group(list(range(8)))
        mesh = g.mesh
        # 8 shards, shard r holds value r: sum must be 0+1+...+7 = 28
        per_rank = np.arange(8, dtype=np.float32).reshape(8, 1)
        x = paddle.to_tensor(per_rank)
        x.data = jax.device_put(x.data, NamedSharding(mesh, P(g.axis)))
        dist.all_reduce(x)
        np.testing.assert_allclose(np.asarray(x.data),
                                   np.full((8, 1), 28.0, np.float32))

    def test_replicated_input_counts_group_size(self):
        dist.init_parallel_env()
        g = dist.new_group(list(range(8)))
        x = paddle.to_tensor(np.full((4,), 2.0, np.float32))
        dist.all_reduce(x)
        np.testing.assert_allclose(np.asarray(x.data),
                                   np.full((4,), 16.0, np.float32))
