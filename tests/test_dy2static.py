"""dygraph <-> static parity with data-dependent control flow.

Reference test style: `unittests/dygraph_to_static/` runs the same model
eagerly and transpiled and asserts equal outputs (SURVEY §4.6). Here the
transpile is `jit.dy2static.ast_transform` → lax.cond / lax.while_loop.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.jit import to_static
from paddle_tpu.jit.dy2static import ast_transform, needs_transform


class BranchyNet(nn.Layer):
    """Forward with a genuine data-dependent branch + loop."""

    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(4, 8)
        self.fc2 = nn.Linear(8, 8)
        self.head = nn.Linear(8, 2)

    def forward(self, x):
        h = self.fc1(x)
        if paddle.mean(h) > 0:          # tensor-dependent if
            h = paddle.tanh(self.fc2(h))
        else:
            h = paddle.nn.functional.relu(self.fc2(h)) - 1.0
        scale = paddle.max(paddle.abs(h))
        while scale > 1.0:              # tensor-dependent while
            h = h / 2.0
            scale = scale / 2.0
        return self.head(h)


class TestDy2StaticParity:
    def _data(self, seed, lo=-1.0, hi=1.0):
        rng = np.random.default_rng(seed)
        return paddle.to_tensor(
            rng.uniform(lo, hi, size=(4, 4)).astype(np.float32))

    def test_branch_model_parity_both_branches(self):
        paddle.seed(0)
        model = BranchyNet()
        static_model = to_static(model)
        hit = set()
        for seed in range(8):
            x = self._data(seed, -2.0, 2.0)
            eager = model(x).numpy()
            static = static_model(x).numpy()
            np.testing.assert_allclose(eager, static, rtol=2e-5, atol=2e-5)
            hit.add(bool(np.mean(model.fc1(x).numpy()) > 0))
        assert hit == {True, False}, (
            f"test data exercised only one branch: {hit}")

    def test_function_if_while_parity(self):
        def fn(x):
            if paddle.sum(x) > 0:
                y = x * 3.0
            else:
                y = -x
            n = paddle.to_tensor(np.float32(0.0))
            while paddle.max(y) > 1.0:
                y = y / 2.0
                n = n + 1.0
            return y, n

        st = to_static(fn)
        for seed in (0, 1, 2):
            x = self._data(seed, -3.0, 3.0)
            ey, en = fn(x)
            sy, sn = st(x)
            np.testing.assert_allclose(ey.numpy(), sy.numpy(), rtol=1e-6)
            assert float(en) == float(sn)

    def test_trace_only_fast_path_kept(self):
        def plain(x):
            return x * 2 + 1
        assert not needs_transform(plain)
        assert ast_transform(plain) is plain

    def test_return_in_tensor_branch_raises_precisely(self):
        def bad(x):
            if paddle.mean(x) > 0:
                return x * 2
            return x

        st = to_static(bad)
        # concrete condition still fine eagerly (python fast path)…
        x = self._data(0)
        with pytest.raises(NotImplementedError,
                           match="return/break/continue"):
            st(x)

    def test_bool_ops_over_tensors(self):
        def fn(x, flag):
            if flag and paddle.mean(x) > 0:
                y = x + 10.0
            else:
                y = x
            if not (paddle.sum(x) > 100.0):
                y = y + 1.0
            return y

        st = to_static(fn)
        for seed in (0, 3):
            x = self._data(seed, -2.0, 2.0)
            np.testing.assert_allclose(fn(x, True).numpy(),
                                       st(x, True).numpy(), rtol=1e-6)
