"""Ring attention (sequence/context parallel) vs dense reference.

No reference-counterpart suite exists (the snapshot has no sequence
parallelism, SURVEY.md §5.7); test strategy follows the OpTest pattern:
exact-math comparison against the XLA dense composition, forward AND
gradients, on the 8-device CPU mesh.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.ops.pallas.flash_attention import flash_attention_xla
from paddle_tpu.ops.pallas.ring_attention import ring_attention


@pytest.fixture(autouse=True)
def _clean():
    yield
    dist.set_hybrid_communicate_group(None)


def _mesh(sp=4, dp=2):
    devs = np.array(jax.devices()[:sp * dp]).reshape(dp, sp)
    return Mesh(devs, ("dp", "sp"))


def _qkv(B=2, L=64, H=4, D=16, seed=0):
    rs = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rs.randn(B, L, H, D).astype(np.float32))
    return mk(), mk(), mk()


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_forward_matches_dense(self, causal):
        mesh = _mesh()
        q, k, v = _qkv()
        ref = flash_attention_xla(q, k, v, causal=causal)
        got = ring_attention(q, k, v, mesh=mesh, causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_match_dense(self, causal):
        mesh = _mesh()
        q, k, v = _qkv(seed=1)

        def loss_ring(q, k, v):
            return jnp.sum(ring_attention(q, k, v, mesh=mesh,
                                          causal=causal) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(flash_attention_xla(q, k, v, causal=causal) ** 2)

        g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_ring, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-4, atol=5e-4)

    def test_sharded_inputs_stay_sharded(self):
        """Works under jit with sp-sharded inputs (the engine's layout)."""
        mesh = _mesh()
        q, k, v = _qkv()
        sh = NamedSharding(mesh, P(None, "sp", None, None))
        qs, ks, vs = (jax.device_put(x, sh) for x in (q, k, v))
        out = jax.jit(lambda a, b, c: ring_attention(
            a, b, c, mesh=mesh, causal=True))(qs, ks, vs)
        ref = flash_attention_xla(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_uneven_mask_rows_nonfinite_free(self):
        """Non-causal + causal both finite for bf16 inputs."""
        mesh = _mesh()
        q, k, v = _qkv(seed=2)
        q = q.astype(jnp.bfloat16)
        k = k.astype(jnp.bfloat16)
        v = v.astype(jnp.bfloat16)
        out = ring_attention(q, k, v, mesh=mesh, causal=True)
        assert bool(jnp.all(jnp.isfinite(out.astype(jnp.float32))))


class TestSequenceParallelGPT:
    @pytest.mark.slow  # heavy e2e; full-suite only (tier-1 budget)
    def test_gpt_sp_engine_uses_ring(self):
        """GPT train step with sp>1 routes attention through the ring and
        matches the sp=1 run."""
        from paddle_tpu import optimizer
        from paddle_tpu.nn import functional as F
        from paddle_tpu.distributed import fleet
        from paddle_tpu.distributed.fleet import DistributedStrategy
        from paddle_tpu.distributed.meta_parallel.engine import (
            HybridParallelTrainStep)
        from paddle_tpu.distributed.topology import HybridCommunicateGroup
        from paddle_tpu.models.gpt import GPT, GPTConfig

        cfg = GPTConfig.tiny()
        rs = np.random.RandomState(0)
        ids = rs.randint(0, cfg.vocab_size, (8, 32)).astype(np.int32)
        labels = rs.randint(0, cfg.vocab_size, (8, 32)).astype(np.int32)

        def run(dims):
            fleet.init(is_collective=True, strategy=DistributedStrategy())
            hcg = HybridCommunicateGroup(dims=dims)
            dist.set_hybrid_communicate_group(hcg)
            try:
                paddle.seed(0)
                model = GPT(cfg)
                opt = optimizer.Adam(learning_rate=1e-3,
                                     parameters=model.parameters())
                step = HybridParallelTrainStep(model, F.cross_entropy, opt,
                                               hcg=hcg, donate=False)
                return [float(step(paddle.to_tensor(ids),
                                   paddle.to_tensor(labels)))
                        for _ in range(2)]
            finally:
                dist.set_hybrid_communicate_group(None)

        ref = run({"dp": 8})
        got = run({"dp": 2, "sp": 4})
        np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


class TestUlyssesAttention:
    """Ulysses all-to-all sequence parallelism (ops/pallas/ulysses.py) —
    same OpTest pattern: exact-math vs the dense composition."""

    @pytest.mark.parametrize("causal", [False, True])
    def test_forward_matches_dense(self, causal):
        from paddle_tpu.ops.pallas.ulysses import ulysses_attention
        mesh = _mesh()
        q, k, v = _qkv()  # H=4 divisible by sp=4
        ref = flash_attention_xla(q, k, v, causal=causal)
        got = ulysses_attention(q, k, v, mesh=mesh, causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_match_dense(self, causal):
        from paddle_tpu.ops.pallas.ulysses import ulysses_attention
        mesh = _mesh()
        q, k, v = _qkv(seed=3)

        def loss_uly(q, k, v):
            return jnp.sum(ulysses_attention(q, k, v, mesh=mesh,
                                             causal=causal) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(flash_attention_xla(q, k, v, causal=causal) ** 2)

        g_u = jax.grad(loss_uly, argnums=(0, 1, 2))(q, k, v)
        g_r = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_u, g_r):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-4, atol=5e-4)

    def test_sdpa_routes_by_sp_mode(self):
        """strategy hybrid_configs sp_mode='ulysses' flips the attention
        flavor; heads not divisible by sp falls back to ring."""
        from paddle_tpu.distributed.topology import HybridCommunicateGroup
        from paddle_tpu.nn.functional import _sp_ring_config
        hcg = HybridCommunicateGroup(dims={"dp": 2, "sp": 4})
        hcg.sp_mode = "ulysses"
        dist.set_hybrid_communicate_group(hcg)
        q = paddle.to_tensor(np.zeros((2, 64, 4, 16), np.float32))
        mesh, axis, mode = _sp_ring_config(q, q, None)
        assert mode == "ulysses" and axis == "sp"
        q3 = paddle.to_tensor(np.zeros((2, 64, 3, 16), np.float32))
        _, _, mode = _sp_ring_config(q3, q3, None)  # 3 heads % 4 != 0
        assert mode == "ring"
        hcg.sp_mode = "ring"
        _, _, mode = _sp_ring_config(q, q, None)
        assert mode == "ring"

    @pytest.mark.slow  # heavy e2e; full-suite only (tier-1 budget)
    def test_gpt_trains_with_ulysses(self):
        """End-to-end: hybrid engine + sp axis + sp_mode=ulysses trains."""
        from paddle_tpu.distributed import fleet
        from paddle_tpu.distributed.fleet import DistributedStrategy
        from paddle_tpu.distributed.meta_parallel.engine import (
            HybridParallelTrainStep)
        from paddle_tpu.models.gpt import GPT, GPTConfig
        from paddle_tpu import optimizer
        from paddle_tpu.nn import functional as F
        strategy = DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 2, "sep_degree": 4,
                                   "sp_mode": "ulysses"}
        fleet.init(is_collective=True, strategy=strategy)
        hcg = fleet.get_hybrid_communicate_group()
        assert hcg.sp_mode == "ulysses"
        paddle.seed(0)
        cfg = GPTConfig.tiny()
        model = GPT(cfg)
        opt = optimizer.AdamW(learning_rate=1e-3,
                              parameters=model.parameters())
        step = HybridParallelTrainStep(
            model, F.cross_entropy, opt, hcg=hcg, strategy=strategy)
        rng = np.random.RandomState(0)
        B, L = 4, 64
        ids = paddle.to_tensor(
            rng.randint(0, cfg.vocab_size, (B, L)).astype(np.int32))
        labels = paddle.to_tensor(
            rng.randint(0, cfg.vocab_size, (B, L)).astype(np.int32))
        losses = [float(step(ids, labels)) for _ in range(4)]
        assert all(np.isfinite(losses)) and losses[-1] < losses[0], losses


class TestRingAttentionDropout:
    """Weight-dropout inside the ring (VERDICT r2 weak #3): masks are
    regenerated in the backward ring pass, semantics match the dense
    weight-dropout reference path."""

    def test_dropout_zero_key_matches_no_dropout_api(self):
        mesh = _mesh()
        q, k, v = _qkv(seed=3)
        base = ring_attention(q, k, v, mesh=mesh, causal=False)
        key = jax.random.PRNGKey(7)
        out = ring_attention(q, k, v, mesh=mesh, causal=False,
                             dropout_p=0.0, dropout_key=key)
        np.testing.assert_allclose(np.asarray(out), np.asarray(base))

    def test_weight_dropout_keeps_duplicated_columns_tied(self):
        mesh = _mesh()
        q, k, v = _qkv(seed=4)
        v = v.at[..., 1].set(v[..., 0])
        key = jax.random.PRNGKey(11)
        out = np.asarray(ring_attention(q, k, v, mesh=mesh, causal=False,
                                        dropout_p=0.5, dropout_key=key))
        ref = np.asarray(ring_attention(q, k, v, mesh=mesh, causal=False))
        assert not np.allclose(out, ref), "dropout had no effect"
        np.testing.assert_allclose(out[..., 0], out[..., 1],
                                   rtol=1e-6, atol=1e-6)

    def test_dropout_grads_finite_and_nonzero(self):
        mesh = _mesh()
        q, k, v = _qkv(seed=5)
        key = jax.random.PRNGKey(13)

        def loss(q, k, v):
            return jnp.sum(ring_attention(q, k, v, mesh=mesh, causal=True,
                                          dropout_p=0.3, dropout_key=key) ** 2)

        g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        for a in g:
            a = np.asarray(a)
            assert np.isfinite(a).all()
            assert np.abs(a).max() > 0

    def test_dropout_grad_unbiased_linear_loss(self):
        """For a loss LINEAR in the attention output the gradient is linear
        in the dropout masks, so E[grad] over seeds must equal the
        no-dropout grad (the vjp regenerates each (shard, chunk) mask
        correctly; a wrong bwd mask would bias this mean)."""
        mesh = _mesh()
        q, k, v = _qkv(seed=6)
        w = jnp.asarray(np.random.default_rng(9).normal(
            size=np.asarray(q).shape).astype(np.float32))

        def gref(q, k, v):
            return jax.grad(lambda a, b, c: jnp.sum(w * ring_attention(
                a, b, c, mesh=mesh, causal=False)))(q, k, v)

        ref = np.asarray(gref(q, k, v))
        acc = np.zeros_like(ref)
        n = 16  # a WRONG bwd mask biases the mean O(1); noise here ~0.2
        gfn = jax.jit(lambda a, b, c, key: jax.grad(
            lambda a, b, c: jnp.sum(w * ring_attention(
                a, b, c, mesh=mesh, causal=False, dropout_p=0.3,
                dropout_key=key)))(a, b, c))
        for s in range(n):
            acc += np.asarray(gfn(q, k, v, jax.random.PRNGKey(100 + s)))
        err = np.abs(acc / n - ref).max() / (np.abs(ref).max() + 1e-9)
        assert err < 0.5, err
