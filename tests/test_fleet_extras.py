"""fleet.utils.fs, fleet.metrics, incubate optimizers (LookAhead/
ModelAverage/LocalSGD/DGC) — reference tests: test_fleet_fs.py,
test_fleet_metric.py, test_lookahead.py, test_modelaverage.py,
test_dgc_optimizer.py."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.distributed.fleet import metrics
from paddle_tpu.distributed.fleet.utils import HDFSClient, LocalFS
from paddle_tpu.incubate.optimizer import (DGCMomentumOptimizer, LookAhead,
                                           LocalSGDOptimizer, ModelAverage)


class TestLocalFS:
    def test_basic_ops(self, tmp_path):
        fs = LocalFS()
        d = str(tmp_path / "sub")
        fs.mkdirs(d)
        assert fs.is_dir(d) and fs.is_exist(d)
        f = os.path.join(d, "a.txt")
        fs.touch(f)
        assert fs.is_file(f)
        dirs, files = fs.ls_dir(str(tmp_path))
        assert dirs == ["sub"] and files == []
        fs.mv(f, os.path.join(d, "b.txt"))
        assert fs.is_file(os.path.join(d, "b.txt"))
        fs.delete(d)
        assert not fs.is_exist(d)

    def test_hdfs_without_hadoop_raises(self):
        client = HDFSClient()
        if client._hadoop is None:
            with pytest.raises(Exception, match="hadoop"):
                client.mkdirs("/tmp/x")


class TestFleetMetrics:
    def test_single_process_passthrough(self):
        assert float(metrics.sum(np.array([3.0]))) == 3.0
        assert metrics.acc(np.array([8.0]), np.array([10.0])) == pytest.approx(0.8)

    def test_auc_from_buckets(self):
        # perfect separation: all negatives in bucket 0, positives in bucket 9
        pos = np.zeros(10); pos[9] = 100
        neg = np.zeros(10); neg[0] = 100
        assert metrics.auc(pos, neg) == pytest.approx(1.0)
        # random: identical distributions
        pos = np.ones(10) * 10
        neg = np.ones(10) * 10
        assert metrics.auc(pos, neg) == pytest.approx(0.5, abs=0.05)


def _quad_problem():
    paddle.seed(0)
    net = nn.Linear(4, 1)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(32, 4)).astype(np.float32)
    w = rng.normal(size=(4, 1)).astype(np.float32)
    y = x @ w
    return net, x, y


def _loss(net, x, y):
    return ((net(paddle.to_tensor(x)) - paddle.to_tensor(y)) ** 2).mean()


class TestLookAhead:
    def test_converges_and_syncs_slow_weights(self):
        net, x, y = _quad_problem()
        inner = optimizer.SGD(learning_rate=0.1, parameters=net.parameters())
        opt = LookAhead(inner, alpha=0.5, k=5)
        losses = []
        for _ in range(40):
            loss = _loss(net, x, y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.2


class TestModelAverage:
    def test_apply_restore(self):
        net, x, y = _quad_problem()
        opt = optimizer.SGD(learning_rate=0.1, parameters=net.parameters())
        avg = ModelAverage(parameters=net.parameters())
        for _ in range(10):
            loss = _loss(net, x, y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            avg.step()
        raw = np.asarray(net.weight.data).copy()
        avg.apply()
        averaged = np.asarray(net.weight.data)
        assert not np.allclose(raw, averaged)
        avg.restore()
        np.testing.assert_allclose(np.asarray(net.weight.data), raw)


class TestLocalSGD:
    def test_single_process_trains(self):
        net, x, y = _quad_problem()
        opt = LocalSGDOptimizer(
            optimizer.SGD(learning_rate=0.1, parameters=net.parameters()),
            k_steps=3)
        l0 = None
        for _ in range(30):
            loss = _loss(net, x, y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            l0 = l0 or float(loss)
        assert float(loss) < l0 * 0.2


class TestDGC:
    def test_sparsified_training_converges(self):
        net, x, y = _quad_problem()
        opt = DGCMomentumOptimizer(learning_rate=0.05, momentum=0.9,
                                   parameters=net.parameters(),
                                   rampup_begin_step=5, sparsity=[0.75])
        losses = []
        for _ in range(60):
            loss = _loss(net, x, y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.3, (losses[0], losses[-1])

    def test_residual_accumulates(self):
        net, x, y = _quad_problem()
        opt = DGCMomentumOptimizer(learning_rate=0.05,
                                   parameters=net.parameters(),
                                   rampup_begin_step=0, sparsity=[0.75])
        loss = _loss(net, x, y)
        loss.backward()
        opt.step()
        # with 75% sparsity most of v is retained as residual
        v = opt._v[id(net.weight)]
        assert np.count_nonzero(np.asarray(v)) >= v.size // 2
