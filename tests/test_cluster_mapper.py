"""Cluster description + mesh mapper (reference `auto_parallel/mapper.py:81`
link-aware process placement, `cluster.py` machine/link model): axis->link
classification, replica-group attribution, and the planner choosing
DIFFERENT plans for a 1x8 slice vs a 2x4-slice topology."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.distributed.auto_parallel import Cluster, Mapper, Planner
from paddle_tpu.distributed.auto_parallel.cluster import _parse_replica_groups


class TestAxisLinks:
    def test_single_slice_all_ici(self):
        m = Mapper(Cluster(n_slices=1, chips_per_slice=8))
        links = m.axis_links({"dp": 2, "mp": 4})
        assert links == {"dp": "ici", "mp": "ici"}

    def test_outer_axis_crosses_slices(self):
        m = Mapper(Cluster(n_slices=2, chips_per_slice=4))
        links = m.axis_links({"dp": 2, "mp": 4})
        assert links["mp"] == "ici"  # stride 1, size 4 == chips_per_slice
        assert links["dp"] == "dcn"  # stride 4, spans both slices

    def test_inner_axis_too_big_for_slice(self):
        m = Mapper(Cluster(n_slices=2, chips_per_slice=4))
        links = m.axis_links({"dp": 1, "mp": 8})
        assert links["mp"] == "dcn"
        assert links["dp"] == "ici"  # size-1 axis is local

    def test_size_one_axes_never_dcn(self):
        m = Mapper(Cluster(n_slices=4, chips_per_slice=2))
        links = m.axis_links({"pp": 4, "dp": 1, "mp": 2})
        assert links == {"pp": "dcn", "dp": "ici", "mp": "ici"}


class TestReplicaGroupParsing:
    def test_explicit_lists(self):
        g = _parse_replica_groups(
            "%ar = f32[8] all-reduce(%x), replica_groups={{0,1},{2,3}}")
        assert g == [[0, 1], [2, 3]]

    def test_iota_form(self):
        g = _parse_replica_groups(
            "%ar = f32[8] all-reduce(%x), replica_groups=[2,4]<=[8]")
        assert g == [[0, 1, 2, 3], [4, 5, 6, 7]]

    def test_iota_transposed(self):
        g = _parse_replica_groups(
            "%ar = f32[8] all-reduce(%x), replica_groups=[4,2]<=[2,4]T(1,0)")
        # arange(8).reshape(2,4).T.reshape(4,2) -> pairs stride 4
        assert g == [[0, 4], [1, 5], [2, 6], [3, 7]]

    def test_absent(self):
        assert _parse_replica_groups("%a = f32[8] add(%x, %y)") is None

    def test_empty_all_replica_form(self):
        """XLA's `replica_groups={}` means ONE group spanning all devices —
        parsed as [] (distinct from None/absent) so the mapper can attribute
        it by topology."""
        assert _parse_replica_groups(
            "%ar = f32[8] all-reduce(%x), replica_groups={}") == []


class TestAllReplicaAttribution:
    """`replica_groups={}` (and groups-less collectives) span every device:
    on a >1-slice cluster their bytes are DCN, on one slice ICI."""

    def _bytes(self, cluster, line):
        import paddle_tpu.distributed.auto_parallel.planner as planner_mod

        class FakeCompiled:
            pass

        orig = planner_mod._iter_collective_lines
        planner_mod._iter_collective_lines = lambda c: [(1000.0, line)]
        try:
            return Mapper(cluster).collective_bytes_by_link(FakeCompiled())
        finally:
            planner_mod._iter_collective_lines = orig

    def test_empty_groups_multislice_is_dcn(self):
        line = "%ar = f32[8] all-reduce(%x), replica_groups={}"
        ici, dcn = self._bytes(Cluster(n_slices=2, chips_per_slice=4), line)
        assert dcn == 1000.0 and ici == 0.0

    def test_empty_groups_single_slice_is_ici(self):
        line = "%ar = f32[8] all-reduce(%x), replica_groups={}"
        ici, dcn = self._bytes(Cluster(n_slices=1, chips_per_slice=8), line)
        assert ici == 1000.0 and dcn == 0.0

    def test_missing_groups_multislice_is_dcn(self):
        line = "%ar = f32[8] all-reduce(%x)"
        ici, dcn = self._bytes(Cluster(n_slices=2, chips_per_slice=4), line)
        assert dcn == 1000.0 and ici == 0.0

    def test_explicit_in_slice_groups_stay_ici(self):
        line = "%ar = f32[8] all-reduce(%x), replica_groups={{0,1,2,3},{4,5,6,7}}"
        ici, dcn = self._bytes(Cluster(n_slices=2, chips_per_slice=4), line)
        assert ici == 1000.0 and dcn == 0.0

    def test_permute_priced_by_its_pairs_not_blanket_dcn(self):
        """collective-permute never carries replica_groups: an in-slice ring
        (ring attention over an ICI axis) must stay ICI on a multislice
        cluster, and only slice-crossing pairs go to DCN."""
        ring_in_slice = ("%cp = f32[8] collective-permute(%x), "
                        "source_target_pairs={{0,1},{1,2},{2,3},{3,0}}")
        ici, dcn = self._bytes(Cluster(n_slices=2, chips_per_slice=4),
                               ring_in_slice)
        assert ici == 1000.0 and dcn == 0.0
        crossing = ("%cp = f32[8] collective-permute(%x), "
                    "source_target_pairs={{0,4},{4,0}}")
        ici, dcn = self._bytes(Cluster(n_slices=2, chips_per_slice=4),
                               crossing)
        assert dcn == 1000.0 and ici == 0.0


def _tp_heavy_model():
    """Params >> activations: TP-sharding params wins on HBM/collectives
    within one slice, but an mp axis spanning slices pays activation psums
    over DCN."""
    paddle.seed(0)
    return nn.Sequential(nn.Linear(1024, 1024), nn.ReLU(),
                         nn.Linear(1024, 1024), nn.ReLU(),
                         nn.Linear(1024, 8))


class TestPlannerWithCluster:
    def test_topology_changes_the_plan(self):
        """The SAME workload must map differently onto 1x8 vs 2x4 slices:
        scores must differ through the DCN term, and the 2x4 winner must
        not put a size-8 axis across the slice boundary."""
        model = _tp_heavy_model()
        opt = optimizer.SGD(learning_rate=0.1,
                            parameters=model.parameters())
        lossf = nn.CrossEntropyLoss()
        x = paddle.to_tensor(
            np.random.default_rng(0).normal(size=(16, 1024)).astype(
                "float32"))
        y = paddle.to_tensor(np.arange(16) % 8)

        def best(cluster):
            paddle.seed(0)
            m = _tp_heavy_model()
            o = optimizer.SGD(learning_rate=0.1, parameters=m.parameters())
            pl = Planner(m, lambda out, yy: lossf(out, yy), o,
                         templates=("dp", "tp_alternating"),
                         cluster=cluster)
            return pl.plan(x, y)

        one = best(Cluster(n_slices=1, chips_per_slice=8, dcn_bw=1e9))
        two = best(Cluster(n_slices=2, chips_per_slice=4, dcn_bw=1e9))
        assert one.score != two.score
        # no axis of the 2-slice winner may span slices with heavy traffic
        links = Mapper(Cluster(n_slices=2, chips_per_slice=4)).axis_links(
            two.mesh_dims)
        # params >> activations here, so the dp grad-allreduce must NOT be
        # the slice-crossing axis when an in-slice alternative exists
        if "dcn" in links.values():
            assert two.cost.get("dcn_bytes", 0.0) <= one.cost.get(
                "ici_bytes", float("inf"))

    def test_dcn_bytes_attributed(self):
        """On a 2x4 cluster, a pure-dp plan's grad all-reduce crosses
        slices: the mapper must bill nonzero DCN bytes for it."""
        model = _tp_heavy_model()
        opt = optimizer.SGD(learning_rate=0.1,
                            parameters=model.parameters())
        lossf = nn.CrossEntropyLoss()
        x = paddle.to_tensor(
            np.random.default_rng(0).normal(size=(16, 1024)).astype(
                "float32"))
        y = paddle.to_tensor(np.arange(16) % 8)
        pl = Planner(model, lambda out, yy: lossf(out, yy), opt,
                     templates=("dp",),
                     cluster=Cluster(n_slices=2, chips_per_slice=4))
        plan = pl.plan(x, y)
        assert plan.template == "dp"
        assert plan.cost["dcn_bytes"] > 0, plan.cost
