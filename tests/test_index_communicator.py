"""TDM tree index/samplers + PS async communicator tests (reference:
`test_index_dataset.py`, `index_dataset` C++ tests, communicator tests)."""
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed.index_dataset import (LayerWiseSampler,
                                                  TreeIndex,
                                                  beam_search_retrieval)
from paddle_tpu.distributed.ps import PSClient, PSServer, TableConfig
from paddle_tpu.distributed.ps.communicator import Communicator


class TestTreeIndex:
    def test_structure(self):
        items = np.arange(100, 108, dtype=np.uint64)  # 8 items, binary tree
        t = TreeIndex(items, branch=2)
        assert t.height == 4          # 1+2+4+8
        assert t.total_node_nums() == 15
        assert t.layer_size(0) == 1 and t.layer_size(3) == 8

    def test_ancestors(self):
        items = np.arange(100, 108, dtype=np.uint64)
        t = TreeIndex(items, branch=2)
        # item 100 is leaf node 7 (first of last layer)
        anc = t.get_ancestors([100], layer=3)
        assert anc[0] == 7
        assert t.get_ancestors([100], layer=2)[0] == 3
        assert t.get_ancestors([100], layer=0)[0] == 0
        # unknown item -> -1
        assert t.get_ancestors([999], layer=2)[0] == -1

    def test_children_and_node_items(self):
        items = np.arange(100, 108, dtype=np.uint64)
        t = TreeIndex(items, branch=2)
        ch = t.get_children([0])
        np.testing.assert_array_equal(ch[0], [1, 2])
        leaves = t.get_children([3])  # children of node 3 -> nodes 7,8
        np.testing.assert_array_equal(leaves[0], [7, 8])
        np.testing.assert_array_equal(t.node_items([7, 8]), [100, 101])
        assert t.node_items([0])[0] == -1  # root is not a leaf

    def test_non_power_tree(self):
        items = np.arange(5, dtype=np.uint64)  # 5 items in an 8-leaf tree
        t = TreeIndex(items, branch=2)
        # children beyond the real leaves are -1
        ch = t.get_children([5])  # node 5's children are leaves 11,12
        assert (ch >= -1).all()
        leaf_nodes = t.get_ancestors(items, layer=t.height - 1)
        assert len(set(leaf_nodes.tolist())) == 5


class TestLayerWiseSampler:
    def test_sample_shapes_and_labels(self):
        items = np.arange(1000, 1064, dtype=np.uint64)
        t = TreeIndex(items, branch=2)
        s = LayerWiseSampler(t, start_layer=1, neg_per_layer=3)
        nodes, labels = s.sample([1000, 1005])
        layers = t.height - 1
        assert nodes.shape == (2, layers * 4)
        # exactly one positive per layer
        assert labels.reshape(2, layers, 4)[:, :, 0].all()
        assert not labels.reshape(2, layers, 4)[:, :, 1:].any()

    def test_positives_are_ancestors(self):
        items = np.arange(1000, 1016, dtype=np.uint64)
        t = TreeIndex(items, branch=2)
        s = LayerWiseSampler(t, start_layer=1, neg_per_layer=1)
        nodes, labels = s.sample([1003])
        layers = t.height - 1
        pos = nodes.reshape(layers, 2)[:, 0]
        for i, layer in enumerate(range(1, t.height)):
            assert pos[i] == t.get_ancestors([1003], layer)[0]

    def test_unknown_item_raises(self):
        t = TreeIndex(np.arange(4, dtype=np.uint64))
        with pytest.raises(KeyError):
            LayerWiseSampler(t).sample([77])


class TestBeamSearch:
    def test_retrieves_best_item(self):
        items = np.arange(200, 232, dtype=np.uint64)  # 32 items
        t = TreeIndex(items, branch=2)
        target_leaf = t.get_ancestors([219], layer=t.height - 1)[0]

        def score_fn(nodes):
            # score = closeness of the subtree to the target leaf: use
            # negative distance of node id to target's ancestor at that depth
            nodes = np.asarray(nodes)
            out = np.empty(len(nodes))
            for i, n in enumerate(nodes):
                # walk target ancestor chain; reward exact ancestors
                anc = target_leaf
                score = 0.0
                while anc > 0:
                    if anc == n:
                        score = 10.0
                        break
                    anc = (anc - 1) // 2
                if n == 0:
                    score = 10.0
                out[i] = score
            return out

        got = beam_search_retrieval(t, score_fn, beam=2)
        assert 219 in got.tolist()


class TestAsyncCommunicator:
    def test_merges_and_flushes(self):
        server = PSServer(0)
        client = PSClient([server.endpoint])
        try:
            client.create_table(TableConfig(table_id=0, dim=4,
                                            optimizer="sgd",
                                            learning_rate=1.0,
                                            init_range=0.0))
            comm = Communicator(client, merge_size=100, send_wait_ms=10)
            comm.start()
            keys = np.array([5, 5, 9], np.uint64)
            grads = np.ones((3, 4), np.float32)
            comm.push_sparse(0, keys, grads)
            comm.push_sparse(0, keys, grads)
            comm.flush()
            # key 5 got 4 unit grads merged, key 9 got 2; sgd lr=1 -> w=-n
            vals = client.pull_sparse(0, np.array([5, 9], np.uint64))
            np.testing.assert_allclose(vals[0], -4 * np.ones(4))
            np.testing.assert_allclose(vals[1], -2 * np.ones(4))
            comm.stop()
        finally:
            client.stop_servers()

    def test_dense_accumulation(self):
        server = PSServer(0)
        client = PSClient([server.endpoint])
        try:
            client.create_table(TableConfig(table_id=1, kind="dense",
                                            dense_size=4, optimizer="sgd",
                                            learning_rate=1.0))
            client.set_dense(1, np.zeros(4, np.float32))
            comm = Communicator(client, merge_size=100, send_wait_ms=10)
            comm.start()
            for _ in range(5):
                comm.push_dense(1, np.ones(4, np.float32))
            comm.flush()
            np.testing.assert_allclose(client.pull_dense(1), -5 * np.ones(4))
            comm.stop()
        finally:
            client.stop_servers()

    def test_interval_flush_without_explicit_flush(self):
        server = PSServer(0)
        client = PSClient([server.endpoint])
        try:
            client.create_table(TableConfig(table_id=2, kind="dense",
                                            dense_size=2, optimizer="sgd",
                                            learning_rate=1.0))
            client.set_dense(2, np.zeros(2, np.float32))
            comm = Communicator(client, merge_size=1000, send_wait_ms=30)
            comm.start()
            comm.push_dense(2, np.ones(2, np.float32))
            time.sleep(0.5)  # sender should drain on its own
            np.testing.assert_allclose(client.pull_dense(2), -np.ones(2))
            comm.stop()
        finally:
            client.stop_servers()
