"""Tests for the API-completion sweep: RNN family, pooling/pad extras,
CTC and misc losses, beam-search decode, top-level extras."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.nn import functional as F


class TestRNNFamily:
    def _xy(self, B=4, T=6, I=5):
        rng = np.random.default_rng(0)
        return paddle.to_tensor(rng.normal(size=(B, T, I)).astype(np.float32))

    def test_lstm_shapes_and_training(self):
        paddle.seed(0)
        x = self._xy()
        lstm = nn.LSTM(5, 8, num_layers=2)
        head = nn.Linear(8, 1)
        out, (h, c) = lstm(x)
        assert tuple(out.shape) == (4, 6, 8)
        assert tuple(h.shape) == (2, 4, 8) and tuple(c.shape) == (2, 4, 8)
        opt = optimizer.Adam(learning_rate=1e-2,
                             parameters=lstm.parameters() + head.parameters())
        y = paddle.to_tensor(np.ones((4, 1), np.float32))
        losses = []
        for _ in range(15):
            out, _ = lstm(x)
            loss = ((head(out[:, -1]) - y) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.5

    def test_gru_and_simple(self):
        x = self._xy()
        for cls in (nn.GRU, nn.SimpleRNN):
            m = cls(5, 8)
            out, h = m(x)
            assert tuple(out.shape) == (4, 6, 8)
            assert tuple(h.shape) == (1, 4, 8)

    def test_bidirectional(self):
        x = self._xy()
        m = nn.LSTM(5, 8, direction="bidirect")
        out, (h, c) = m(x)
        assert tuple(out.shape) == (4, 6, 16)
        assert tuple(h.shape) == (2, 4, 8)

    def test_cell_matches_scan(self):
        """RNN(cell) over time == manually stepping the cell."""
        paddle.seed(1)
        cell = nn.LSTMCell(5, 8)
        rnn = nn.RNN(cell)
        x = self._xy(B=2, T=4)
        out, (h_n, c_n) = rnn(x)
        from paddle_tpu.ops import zeros
        h = zeros([2, 8]); c = zeros([2, 8])
        for t in range(4):
            step_out, (h, c) = cell(x[:, t], (h, c))
            np.testing.assert_allclose(out[:, t].numpy(), step_out.numpy(),
                                       rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(h.numpy(), h_n.numpy(), rtol=2e-5,
                                   atol=2e-5)

    def test_reverse_rnn(self):
        cell = nn.GRUCell(5, 8)
        fwd = nn.RNN(cell)
        rev = nn.RNN(cell, is_reverse=True)
        x = self._xy(B=2, T=4)
        xr = paddle.to_tensor(np.flip(x.numpy(), axis=1).copy())
        out_rev, _ = rev(x)
        out_fwd, _ = fwd(xr)
        np.testing.assert_allclose(out_rev.numpy(),
                                   np.flip(out_fwd.numpy(), axis=1),
                                   rtol=2e-5, atol=2e-5)


class TestPadPool:
    def test_pad_modes(self):
        x = paddle.to_tensor(np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
        out = F.pad(x, [1, 1, 2, 2], mode="constant", value=7.0)
        assert tuple(out.shape) == (1, 1, 8, 6)
        assert out.numpy()[0, 0, 0, 0] == 7.0
        refl = F.pad(x, [1, 1, 1, 1], mode="reflect")
        assert tuple(refl.shape) == (1, 1, 6, 6)
        z = F.zeropad2d(x, 2)
        assert tuple(z.shape) == (1, 1, 8, 8)

    def test_pool3d(self):
        x = paddle.to_tensor(np.random.default_rng(0).normal(
            size=(2, 3, 4, 4, 4)).astype(np.float32))
        assert tuple(F.max_pool3d(x, 2).shape) == (2, 3, 2, 2, 2)
        assert tuple(F.avg_pool3d(x, 2).shape) == (2, 3, 2, 2, 2)
        assert tuple(F.adaptive_avg_pool3d(x, 2).shape) == (2, 3, 2, 2, 2)
        assert tuple(nn.MaxPool3D(2)(x).shape) == (2, 3, 2, 2, 2)

    def test_max_unpool2d_roundtrip(self):
        x = paddle.to_tensor(np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
        pooled, idx = F.max_pool2d(x, 2, return_mask=True)
        un = F.max_unpool2d(pooled, idx, 2)
        assert tuple(un.shape) == (1, 1, 4, 4)
        # max of each 2x2 block restored at its original position
        assert un.numpy()[0, 0, 1, 1] == 5.0
        assert un.numpy()[0, 0, 0, 0] == 0.0

    def test_conv_transposes(self):
        x1 = paddle.to_tensor(np.random.default_rng(0).normal(
            size=(2, 3, 8)).astype(np.float32))
        m1 = nn.Conv1DTranspose(3, 5, 3, stride=2)
        assert m1(x1).shape[1] == 5
        x3 = paddle.to_tensor(np.random.default_rng(0).normal(
            size=(1, 2, 4, 4, 4)).astype(np.float32))
        m3 = nn.Conv3DTranspose(2, 4, 2, stride=2)
        assert tuple(m3(x3).shape) == (1, 4, 8, 8, 8)

    def test_fold(self):
        # fold(unfold(x)) with non-overlapping patches reproduces x
        x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
        patches = x.reshape(1, 2, 2, 2, 2).transpose(0, 1, 3, 2, 4)
        cols = np.zeros((1, 4, 4), np.float32)  # [B, C*kh*kw, L]
        L = 0
        for i in range(2):
            for j in range(2):
                cols[0, :, L] = x[0, 0, i*2:i*2+2, j*2:j*2+2].reshape(-1)
                L += 1
        out = F.fold(paddle.to_tensor(cols), (4, 4), (2, 2), strides=2)
        np.testing.assert_allclose(out.numpy()[0, 0], x[0, 0])


class TestSpatialOps:
    def test_affine_grid_identity_sample(self):
        x = paddle.to_tensor(np.random.default_rng(0).normal(
            size=(1, 2, 5, 5)).astype(np.float32))
        theta = paddle.to_tensor(
            np.array([[[1.0, 0, 0], [0, 1, 0]]], np.float32))
        grid = F.affine_grid(theta, [1, 2, 5, 5])
        out = F.grid_sample(x, grid)
        np.testing.assert_allclose(out.numpy(), x.numpy(), rtol=1e-4,
                                   atol=1e-4)

    def test_temporal_shift_shape(self):
        x = paddle.to_tensor(np.random.default_rng(0).normal(
            size=(6, 4, 3, 3)).astype(np.float32))
        out = F.temporal_shift(x, seg_num=3, shift_ratio=0.25)
        assert tuple(out.shape) == (6, 4, 3, 3)


class TestLossesExtra:
    def test_ctc_loss_perfect_alignment_is_low(self):
        """Logits overwhelmingly favoring the target labeling give near-zero
        loss; uniform logits give a clearly larger one."""
        T, B, V = 8, 1, 5
        labels = np.array([[1, 2, 3]], np.int64)
        # construct a path: 1,1,2,2,3,3,blank,blank
        path = [1, 1, 2, 2, 3, 3, 0, 0]
        good = np.full((T, B, V), -10.0, np.float32)
        for t, c in enumerate(path):
            good[t, 0, c] = 10.0
        il = np.array([T], np.int64)
        ll = np.array([3], np.int64)
        l_good = float(F.ctc_loss(paddle.to_tensor(good),
                                  paddle.to_tensor(labels),
                                  paddle.to_tensor(il),
                                  paddle.to_tensor(ll)))
        unif = np.zeros((T, B, V), np.float32)
        l_unif = float(F.ctc_loss(paddle.to_tensor(unif),
                                  paddle.to_tensor(labels),
                                  paddle.to_tensor(il),
                                  paddle.to_tensor(ll)))
        assert l_good < 0.2 and l_unif > 1.0, (l_good, l_unif)

    def test_ctc_loss_trains(self):
        paddle.seed(0)
        T, B, V = 10, 2, 6
        net = nn.Linear(4, V)
        rng = np.random.default_rng(0)
        x = paddle.to_tensor(rng.normal(size=(T, B, 4)).astype(np.float32))
        labels = paddle.to_tensor(rng.integers(1, V, (B, 3)).astype(np.int64))
        il = paddle.to_tensor(np.full((B,), T, np.int64))
        ll = paddle.to_tensor(np.full((B,), 3, np.int64))
        opt = optimizer.Adam(learning_rate=5e-2, parameters=net.parameters())
        crit = nn.CTCLoss()
        losses = []
        for _ in range(25):
            loss = crit(net(x), labels, il, ll)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])



    def test_ctc_loss_zero_length_label(self):
        """ext_len==1 (empty label): loss is exactly -log P(all-blank path),
        no double-counting (ADVICE r1)."""
        T, B, V = 4, 1, 3
        logp = np.zeros((T, B, V), np.float32)  # uniform: log_softmax = -log 3
        il = np.array([T], np.int64)
        ll = np.array([0], np.int64)
        labels = np.zeros((B, 2), np.int64)
        got = float(F.ctc_loss(paddle.to_tensor(logp),
                               paddle.to_tensor(labels),
                               paddle.to_tensor(il), paddle.to_tensor(ll),
                               reduction="sum"))
        want = T * np.log(V)  # single path: blank at every step
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_ctc_loss_norm_by_times(self):
        T, B, V = 8, 1, 5
        rng = np.random.default_rng(0)
        logp = rng.normal(size=(T, B, V)).astype(np.float32)
        labels = np.array([[1, 2]], np.int64)
        il = np.array([T], np.int64)
        ll = np.array([2], np.int64)
        args = (paddle.to_tensor(logp), paddle.to_tensor(labels),
                paddle.to_tensor(il), paddle.to_tensor(ll))
        plain = float(F.ctc_loss(*args, reduction="sum"))
        normed = float(F.ctc_loss(*args, reduction="sum", norm_by_times=True))
        np.testing.assert_allclose(normed, plain / T, rtol=1e-5)


    def test_misc_losses(self):
        rng = np.random.default_rng(0)
        p = paddle.to_tensor(rng.random((4, 1)).astype(np.float32))
        y = paddle.to_tensor((rng.random((4, 1)) > 0.5).astype(np.float32))
        assert np.isfinite(float(F.log_loss(p, y).mean()))
        probs = paddle.to_tensor(
            np.full((2, 3), 1 / 3, np.float32))
        lab = paddle.to_tensor(np.array([[0], [2]], np.int64))
        assert np.isfinite(float(F.dice_loss(probs, lab)))
        a = paddle.to_tensor(rng.normal(size=(4, 8)).astype(np.float32))
        pos = paddle.to_tensor(rng.normal(size=(4, 8)).astype(np.float32))
        lbl = paddle.to_tensor(np.array([0, 1, 0, 2], np.int64))
        assert np.isfinite(float(F.npair_loss(a, pos, lbl)))
        hel = nn.HingeEmbeddingLoss()
        assert np.isfinite(float(hel(a, paddle.to_tensor(
            np.sign(rng.normal(size=(4, 8))).astype(np.float32)))))

    def test_hsigmoid_trains(self):
        paddle.seed(0)
        m = nn.HSigmoidLoss(8, 10)
        x = paddle.to_tensor(np.random.default_rng(0).normal(
            size=(4, 8)).astype(np.float32), stop_gradient=False)
        lab = paddle.to_tensor(np.array([1, 3, 5, 7], np.int64))
        loss = m(x, lab)
        loss.backward()
        assert x.grad is not None


class TestBeamSearch:
    def test_decode_prefers_high_prob_tokens(self):
        paddle.seed(0)
        V, H = 8, 16

        class BiasCell(nn.Layer):
            """Cell whose logits always favor token 5 then EOS (7)."""

            def __init__(self):
                super().__init__()
                self.emb = nn.Embedding(V, H)
                self.cell = nn.GRUCell(H, H)
                self.out = nn.Linear(H, V)

            def forward(self, tokens, states):
                h = self.emb(tokens)
                out, new_s = self.cell(h, states)
                logits = self.out(out)
                return logits, new_s

        cell = BiasCell()
        # bias the output layer hard toward token 5
        b = np.zeros(V, np.float32)
        b[5] = 8.0
        cell.out.bias.set_value(b)
        from paddle_tpu.nn import BeamSearchDecoder, dynamic_decode
        from paddle_tpu.ops import zeros
        dec = BeamSearchDecoder(cell, start_token=1, end_token=7, beam_size=3)
        ids, scores = dynamic_decode(dec, inits=zeros([2, 16]),
                                     max_step_num=5)
        assert tuple(ids.shape)[:2] == (2, 3)
        assert (ids.numpy()[:, 0] == 5).all()  # best beam rides token 5


class TestDynamicDecodeEarlyStop:
    """dynamic_decode early-stop contract: when every beam hits
    end_token before max_step_num the loop stops at the finishing step
    (the cell is never over-stepped and the dead states are not
    reordered one last time), finished beams extend only with end_token
    at zero cost, and a finished beam's state chain stays its own."""

    END, V = 7, 10

    def _script_cell(self, plan):
        """Cell whose per-call logits come from `plan` (a list of
        {input_token: logits_row} dicts, last entry repeating); state is
        a base-100 token-history fingerprint: new = state*100 + token."""
        from paddle_tpu.framework.tensor import Tensor
        import jax.numpy as jnp
        calls = {"n": 0, "states_in": [], "tokens_in": []}

        class Cell:
            def __call__(cell_self, tokens, states):
                t = min(calls["n"], len(plan) - 1)
                calls["n"] += 1
                tok_np = np.asarray(tokens.data)
                calls["tokens_in"].append(tok_np.copy())
                calls["states_in"].append(np.asarray(states.data).copy())
                logits = np.stack([plan[t].get(int(tk),
                                               np.full(self.V, -5.0,
                                                       np.float32))
                                   for tk in tok_np])
                new_states = Tensor(
                    states.data * 100.0 + jnp.asarray(
                        tok_np[:, None].astype(np.float32)))
                return Tensor(jnp.asarray(logits)), new_states

        return Cell(), calls

    def _row(self, **tok_logit):
        row = np.full(self.V, -20.0, np.float32)
        for tok, lg in tok_logit.items():
            row[int(tok[1:])] = lg
        return row

    def test_all_beams_end_early_no_overstep(self):
        """Every beam decisively emits end at step 2: the decode must
        stop there — cell called exactly twice, T == 2."""
        from paddle_tpu.nn import BeamSearchDecoder, dynamic_decode
        from paddle_tpu.ops import zeros
        plan = [{1: self._row(t2=4.0, t3=3.0)},     # step 1: tokens 2/3
                {2: self._row(t7=30.0), 3: self._row(t7=30.0)}]
        cell, calls = self._script_cell(plan)
        dec = BeamSearchDecoder(cell, start_token=1, end_token=self.END,
                                beam_size=2)
        ids, scores = dynamic_decode(dec, inits=zeros([1, 1]),
                                     max_step_num=10)
        assert calls["n"] == 2, "over-stepped past the all-finished step"
        assert tuple(ids.shape) == (1, 2, 2)
        assert np.asarray(ids.data)[0, 0].tolist() == [2, self.END]
        assert np.asarray(ids.data)[0, 1].tolist() == [3, self.END]

    def test_finished_beam_keeps_own_state_and_zero_cost_extension(self):
        """Beam 0 finishes at step 2 while beam 1 runs on: the finished
        beam's state fed into later cell steps is ITS OWN chain (parent
        == itself, never re-gathered from the live beam), its token
        extensions are all end_token, and its score stays frozen."""
        from paddle_tpu.nn import BeamSearchDecoder, dynamic_decode
        from paddle_tpu.ops import zeros
        plan = [
            {1: self._row(t2=4.0, t3=3.0)},          # beams (2), (3)
            {2: self._row(t7=30.0),                  # beam (2) finishes
             3: self._row(t5=3.0)},                  # beam (3) -> 5
            {self.END: self._row(),                  # finished: all floor
             5: self._row(t7=30.0)},                 # beam (3,5) finishes
        ]
        cell, calls = self._script_cell(plan)
        dec = BeamSearchDecoder(cell, start_token=1, end_token=self.END,
                                beam_size=2)
        ids, scores = dynamic_decode(dec, inits=zeros([1, 1]),
                                     max_step_num=10)
        assert calls["n"] == 3
        out = np.asarray(ids.data)[0]
        rows = {tuple(r) for r in out.tolist()}
        # finished beam extended ONLY with end_token
        assert (2, self.END, self.END) in rows
        assert (3, 5, self.END) in rows
        # call 3's states: the finished beam carried its own fingerprint
        # chain 0 -> 1 -> 102 (start, then token 2), NOT the live beam's
        # 103 — finished beams are never re-gathered from another parent
        st3 = calls["states_in"][2].ravel().tolist()
        tk3 = calls["tokens_in"][2].tolist()
        fin_rows = [i for i, t in enumerate(tk3) if t == self.END]
        assert fin_rows, f"no finished-beam row in step-3 inputs {tk3}"
        for i in fin_rows:
            assert st3[i] == 102.0, (st3, tk3)
        # zero-cost extension: the finished hypothesis' score is exactly
        # its score at finish time (log-softmax of a 30-margin row ~ 0)
        s = np.asarray(scores.data)[0]
        best = s.max()
        assert abs(best - s[out.tolist().index([2, self.END, self.END])]) \
            < 1e-6

    def test_batch_rows_finish_independently(self):
        """One batch row finishing early must not stop the other."""
        from paddle_tpu.nn import BeamSearchDecoder, dynamic_decode
        from paddle_tpu.framework.tensor import Tensor
        import jax.numpy as jnp

        calls = {"n": 0}

        class Cell:
            def __call__(cell_self, tokens, states):
                calls["n"] += 1
                tok = np.asarray(tokens.data)
                B = tok.shape[0]
                logits = np.full((B, self.V), -5.0, np.float32)
                half = B // 2
                # batch row 0 (first half of merged beams): end now;
                # batch row 1: end only from call 3
                logits[:half, self.END] = 30.0
                if calls["n"] >= 3:
                    logits[half:, self.END] = 30.0
                else:
                    logits[half:, 4] = 6.0
                return (Tensor(jnp.asarray(logits)),
                        Tensor(states.data + 1.0))

        from paddle_tpu.ops import zeros
        dec = BeamSearchDecoder(Cell(), start_token=1, end_token=self.END,
                                beam_size=2)
        ids, _ = dynamic_decode(dec, inits=zeros([2, 3]), max_step_num=10)
        assert calls["n"] == 3
        out = np.asarray(ids.data)
        assert out[0, 0].tolist() == [self.END, self.END, self.END]
        assert out[1, 0].tolist() == [4, 4, self.END]


class TestTopLevelExtras:
    def test_assorted(self):
        x = paddle.to_tensor(np.array([[1.0, 2], [3, 4]], np.float32))
        y = paddle.to_tensor(np.array([[1.0, 1], [1, 1]], np.float32))
        np.testing.assert_allclose(paddle.add_n([x, y]).numpy(),
                                   [[2, 3], [4, 5]])
        assert paddle.broadcast_shape([2, 1, 3], [4, 3]) == [2, 4, 3]
        np.testing.assert_allclose(
            float(paddle.dist(x, y)), np.linalg.norm((x.numpy() - 1).ravel()))
        v = paddle.to_tensor(np.array([1.0, 0], np.float32))
        np.testing.assert_allclose(paddle.mv(x, v).numpy(), [1, 3])
        assert paddle.rank(x).numpy() == 2
        assert paddle.tolist(x) == [[1.0, 2.0], [3.0, 4.0]]
        parts = paddle.unstack(x, axis=0)
        assert len(parts) == 2
        td = paddle.tensordot(x, y, axes=1)
        assert tuple(td.shape) == (2, 2)
        d = paddle.diff(paddle.to_tensor(np.array([1.0, 3, 6], np.float32)))
        np.testing.assert_allclose(d.numpy(), [2, 3])
        assert paddle.is_floating_point(x) and not paddle.is_complex(x)

    def test_inplace_variants(self):
        x = paddle.to_tensor(np.zeros((2, 3), np.float32))
        paddle.reshape_(x, [3, 2])
        assert tuple(x.shape) == (3, 2)
        paddle.tanh_(x)
        np.testing.assert_allclose(x.numpy(), np.zeros((3, 2)))
        paddle.increment(x, 2.0)
        np.testing.assert_allclose(x.numpy(), np.full((3, 2), 2.0))

    def test_shard_index(self):
        ids = paddle.to_tensor(np.array([0, 5, 9, 13], np.int64))
        out = paddle.shard_index(ids, index_num=16, nshards=2, shard_id=0)
        np.testing.assert_array_equal(out.numpy(), [0, 5, -1, -1])
        out1 = paddle.shard_index(ids, index_num=16, nshards=2, shard_id=1)
        np.testing.assert_array_equal(out1.numpy(), [-1, -1, 1, 5])


class TestReviewRegressions:
    def test_grouped_conv1d_transpose(self):
        paddle.seed(0)
        m = nn.Conv1DTranspose(4, 4, 3, stride=2, groups=2)
        x = paddle.to_tensor(np.random.default_rng(0).normal(
            size=(2, 4, 8)).astype(np.float32))
        out = m(x)
        assert out.shape[1] == 4
        # group isolation: zeroing group-2 input must not change group-1 out
        x2 = x.numpy().copy()
        x2[:, 2:, :] = 0
        out2 = m(paddle.to_tensor(x2))
        np.testing.assert_allclose(out.numpy()[:, :2], out2.numpy()[:, :2],
                                   rtol=1e-5, atol=1e-5)

    def test_max_unpool_with_padding(self):
        x = paddle.to_tensor(np.random.default_rng(0).normal(
            size=(1, 1, 6, 6)).astype(np.float32))
        pooled, idx = F.max_pool2d(x, 3, stride=2, padding=1,
                                   return_mask=True)
        # even input sizes are ambiguous under the inverse formula (as in
        # torch) — pass output_size explicitly
        un = F.max_unpool2d(pooled, idx, 3, stride=2, padding=1,
                            output_size=(6, 6))
        assert tuple(un.shape) == (1, 1, 6, 6)
        # default formula case: odd input, (in-1)*s + k - 2p == in
        x5 = paddle.to_tensor(np.random.default_rng(1).normal(
            size=(1, 1, 5, 5)).astype(np.float32))
        p5, i5 = F.max_pool2d(x5, 3, stride=2, padding=1, return_mask=True)
        u5 = F.max_unpool2d(p5, i5, 3, stride=2, padding=1)
        assert tuple(u5.shape) == (1, 1, 5, 5)

    def test_lstm_initial_states_used(self):
        paddle.seed(0)
        m = nn.LSTM(4, 6)
        x = paddle.to_tensor(np.random.default_rng(0).normal(
            size=(2, 3, 4)).astype(np.float32))
        h0 = paddle.to_tensor(np.ones((1, 2, 6), np.float32) * 5)
        c0 = paddle.to_tensor(np.ones((1, 2, 6), np.float32) * 5)
        out_zero, _ = m(x)
        out_init, _ = m(x, (h0, c0))
        assert not np.allclose(out_zero.numpy(), out_init.numpy())

    def test_hsigmoid_non_power_of_two(self):
        paddle.seed(0)
        m = nn.HSigmoidLoss(6, 5)  # 5 classes: path lengths differ
        x = paddle.to_tensor(np.random.default_rng(0).normal(
            size=(5, 6)).astype(np.float32))
        lab = paddle.to_tensor(np.arange(5, dtype=np.int64))
        loss = m(x, lab)
        assert np.isfinite(float(loss))

    def test_spectral_norm_converges(self):
        rng = np.random.default_rng(0)
        w = rng.normal(size=(8, 8)).astype(np.float32)
        sn = nn.SpectralNorm((8, 8), power_iters=1)
        for _ in range(30):  # persisted u/v: repeated calls converge
            out = sn(paddle.to_tensor(w))
        sigma_true = np.linalg.svd(w, compute_uv=False)[0]
        np.testing.assert_allclose(np.asarray(out.numpy()) * sigma_true, w,
                                   rtol=5e-2, atol=5e-2)

    def test_sequence_length_masking(self):
        paddle.seed(0)
        m = nn.LSTM(4, 6)
        x = paddle.to_tensor(np.random.default_rng(0).normal(
            size=(2, 5, 4)).astype(np.float32))
        lens = paddle.to_tensor(np.array([2, 5], np.int64))
        out, (h, c) = m(x, sequence_length=lens)
        o = out.numpy()
        assert np.allclose(o[0, 2:], 0.0)      # padded steps zeroed
        assert not np.allclose(o[1, 2:], 0.0)  # full row unaffected

    def test_custom_cell_generic_loop(self):
        class NormCell(nn.SimpleRNNCell):
            def forward(self, inputs, states=None):
                out, st = super().forward(inputs, states)
                return out * 2.0, st
        paddle.seed(0)
        cell = NormCell(4, 6)
        rnn = nn.RNN(cell)
        x = paddle.to_tensor(np.random.default_rng(0).normal(
            size=(2, 3, 4)).astype(np.float32))
        out, _ = rnn(x)
        # the override IS honored (fused scan would ignore the *2)
        assert tuple(out.shape) == (2, 3, 6)

    def test_ceil_mode_pool3d(self):
        x = paddle.to_tensor(np.random.default_rng(0).normal(
            size=(1, 1, 5, 5, 5)).astype(np.float32))
        out = F.max_pool3d(x, 2, stride=2, ceil_mode=True)
        assert tuple(out.shape) == (1, 1, 3, 3, 3)
        out_f = F.max_pool3d(x, 2, stride=2, ceil_mode=False)
        assert tuple(out_f.shape) == (1, 1, 2, 2, 2)

    def test_conv_transpose_output_size(self):
        x = paddle.to_tensor(np.random.default_rng(0).normal(
            size=(1, 3, 8)).astype(np.float32))
        m = nn.Conv1DTranspose(3, 2, 3, stride=2)
        # default output length is (8-1)*2 + 3 = 17; stride 2 also reaches 18
        out = m(x, output_size=[18])
        assert out.shape[-1] == 18
        import pytest as _pytest
        with _pytest.raises(ValueError, match="unreachable"):
            m(x, output_size=[16])
