"""paddle.device equivalent.

Reference parity: `python/paddle/device/__init__.py` (set_device/get_device,
device-type discovery, is_compiled_with_*) and `python/paddle/device/cuda/`
(streams/events/memory stats) — the latter exposed both as `device.cuda`
(API parity) and `device.tpu` (honest name); both talk to the same JAX
accelerator runtime. XLA owns streams and memory, so stream objects are
ordering no-ops and memory stats read `jax.Device.memory_stats()`.
"""
from __future__ import annotations

from typing import List

import jax

from ..framework.place import (CPUPlace, CustomPlace, Place, TPUPlace,
                               device_count, get_device, set_device,
                               is_compiled_with_tpu)
from . import cuda
from . import cuda as tpu  # same accelerator runtime, honest alias

__all__ = [
    'set_device', 'get_device', 'get_all_device_type',
    'get_all_custom_device_type', 'get_available_device',
    'get_available_custom_device', 'is_compiled_with_tpu',
    'is_compiled_with_cuda', 'is_compiled_with_rocm',
    'is_compiled_with_xpu', 'is_compiled_with_npu', 'is_compiled_with_mlu',
    'is_compiled_with_ipu', 'is_compiled_with_cinn',
    'XPUPlace', 'IPUPlace', 'MLUPlace', 'NPUPlace',
    'cuda', 'tpu', 'synchronize',
]


def get_all_device_type() -> List[str]:
    return sorted({d.platform for d in jax.devices()} | {"cpu"})


def get_all_custom_device_type() -> List[str]:
    return [t for t in get_all_device_type() if t not in ("cpu", "gpu", "tpu")]


def get_available_device() -> List[str]:
    out = []
    for d in jax.devices():
        out.append(f"{d.platform}:{d.id}")
    return out


def get_available_custom_device() -> List[str]:
    return [s for s in get_available_device()
            if s.split(":")[0] not in ("cpu", "gpu", "tpu")]


def synchronize(device=None):
    """Block until all queued work on the device is complete (reference
    `device/cuda/__init__.py` synchronize; here: a tiny transfer barrier —
    jax dispatch is async, fetching forces completion)."""
    if device is not None:
        from . import cuda
        return cuda.synchronize(device)
    for d in jax.devices():
        jax.device_put(0, d).block_until_ready()


# compiled-with predicates: honest answers for a TPU-only build
def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_rocm() -> bool:
    return False


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_npu() -> bool:
    return False


def is_compiled_with_mlu() -> bool:
    return False


def is_compiled_with_ipu() -> bool:
    return False


def is_compiled_with_cinn() -> bool:
    return False


class XPUPlace(CustomPlace):
    def __init__(self, device_id: int = 0):
        super().__init__("xpu", device_id)


class IPUPlace(CustomPlace):
    def __init__(self, device_id: int = 0):
        super().__init__("ipu", device_id)


class MLUPlace(CustomPlace):
    def __init__(self, device_id: int = 0):
        super().__init__("mlu", device_id)


class NPUPlace(CustomPlace):
    def __init__(self, device_id: int = 0):
        super().__init__("npu", device_id)
