"""Accelerator runtime utilities (paddle.device.cuda parity, TPU semantics).

Reference parity: `python/paddle/device/cuda/__init__.py` (Stream, Event,
current_stream, stream_guard, synchronize, device_count, memory stats) and
`python/paddle/device/cuda/streams.py`. On TPU, XLA owns stream scheduling:
program order IS stream order, so Stream/Event are ordering markers that
`synchronize`/`record` map onto `block_until_ready` barriers. Memory stats
read `jax.Device.memory_stats()` (HBM), replacing cudaMemGetInfo.
"""
from __future__ import annotations

from contextlib import contextmanager
from typing import Optional

import jax

from ..framework.place import get_expected_place


def _device(device=None) -> jax.Device:
    if isinstance(device, jax.Device):
        return device
    if device is None:
        return get_expected_place().jax_device
    if isinstance(device, int):
        devs = jax.devices()
        return devs[device]
    if hasattr(device, "jax_device"):
        return device.jax_device
    raise TypeError(f"cannot interpret {device!r} as a device")


def device_count() -> int:
    try:
        return len([d for d in jax.devices() if d.platform != "cpu"]) or \
            len(jax.devices())
    except Exception:
        return 0


def synchronize(device=None):
    """Wait for all work on `device` (reference cuda.synchronize)."""
    d = _device(device)
    jax.device_put(0, d).block_until_ready()


def current_stream(device=None) -> "Stream":
    global _current
    if device is None:
        if _current is None:
            _current = Stream()
        return _current
    return Stream(device=device)


@contextmanager
def stream_guard(stream: "Stream"):
    """Parity context: XLA compiles its own schedule; the guard only tracks
    the 'current stream' object for API compatibility."""
    global _current
    prev = _current
    _current = stream
    try:
        yield
    finally:
        _current = prev


class Event:
    """Ordering marker (reference `streams.py` Event)."""

    def __init__(self, enable_timing: bool = False, blocking: bool = False,
                 interprocess: bool = False):
        self._recorded = False

    def record(self, stream: Optional["Stream"] = None):
        self._recorded = True

    def query(self) -> bool:
        return self._recorded

    def synchronize(self):
        synchronize()


class Stream:
    """Ordering domain (reference `streams.py` Stream). XLA's latency-hiding
    scheduler already overlaps compute/comm; explicit streams are a no-op
    ordering API kept for code portability."""

    def __init__(self, device=None, priority: int = 2):
        self.device = _device(device)
        self.priority = priority

    def record_event(self, event: Optional[Event] = None) -> Event:
        ev = event or Event()
        ev.record(self)
        return ev

    def wait_event(self, event: Event):
        pass  # program order is stream order under XLA

    def wait_stream(self, stream: "Stream"):
        pass

    def query(self) -> bool:
        return True

    def synchronize(self):
        synchronize(self.device)


# lazily created by current_stream(): constructing a Stream touches
# jax.devices(); import-time device init would defeat flags that must
# be set before first device use
_current = None


# -- memory stats (jax.Device.memory_stats → cudaMemGetInfo parity) ---------
def _stats(device=None) -> dict:
    d = _device(device)
    return d.memory_stats() or {}


def memory_allocated(device=None) -> int:
    return int(_stats(device).get("bytes_in_use", 0))


def max_memory_allocated(device=None) -> int:
    return int(_stats(device).get("peak_bytes_in_use", 0))


def memory_reserved(device=None) -> int:
    s = _stats(device)
    return int(s.get("bytes_reserved", s.get("bytes_in_use", 0)))


def max_memory_reserved(device=None) -> int:
    return int(_stats(device).get("peak_bytes_in_use", 0))


def empty_cache():
    """XLA's allocator manages HBM; nothing to flush (parity no-op)."""
    return None


def get_device_properties(device=None):
    d = _device(device)

    class _Props:
        name = f"{d.platform}:{d.id} ({getattr(d, 'device_kind', 'unknown')})"
        total_memory = int(_stats(d).get("bytes_limit", 0))
        multi_processor_count = getattr(d, "num_cores", 1) or 1
        major, minor = 0, 0
    return _Props()


def get_device_name(device=None) -> str:
    d = _device(device)
    return getattr(d, "device_kind", d.platform)


def get_device_capability(device=None):
    return (0, 0)
