"""paddle.onnx parity (reference `python/paddle/onnx/export.py`, which
shells out to paddle2onnx). ONNX tooling is not in this environment; the
portable interchange format here is the StableHLO export (`jit.save` /
`static.save_inference_model`), which `export` produces alongside a clear
error about true .onnx output."""
from .export import export  # noqa: F401
