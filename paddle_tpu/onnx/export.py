"""paddle.onnx.export (reference export.py -> paddle2onnx)."""
from __future__ import annotations

from typing import Optional, Sequence


def export(layer, path: str, input_spec: Optional[Sequence] = None,
           opset_version: int = 9, **configs):
    """Export `layer` for interchange.

    If the `onnx` package is importable, real ONNX conversion could run; in
    this environment it is not, so the function writes the StableHLO export
    (`<path>.pdmodel` + params) — the TPU deployment artifact consumed by
    `paddle_tpu.inference.Predictor` — and raises only if even that fails.
    """
    try:
        import onnx  # noqa: F401
        have_onnx = True
    except ImportError:
        have_onnx = False

    from .. import jit as jit_mod
    prefix = path[:-5] if path.endswith(".onnx") else path
    jit_mod.save(layer, prefix, input_spec=input_spec)

    if have_onnx:
        # onnx present but converter (paddle2onnx equivalent) is out of
        # scope for this build; the StableHLO artifact stands in
        pass
    return prefix
