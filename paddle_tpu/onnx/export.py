"""paddle.onnx.export — real ONNX graph emission from the recorded Program.

Reference: `/root/reference/python/paddle/onnx/export.py:36` shells out to
paddle2onnx; here the recorded static Program (`static.Program`, the
append_op capture of the layer's forward) is walked op-by-op into ONNX
NodeProtos and serialized with the in-repo wire writer (`onnx/proto.py`) —
no external converter or `onnx` package. The supported op set is the
inference zoo's (conv/bn/pool/matmul/linear/softmax/reshape/activations);
an unsupported op raises listing itself rather than emitting a broken
graph. Alongside the `.onnx`, the StableHLO artifact for
`paddle_tpu.inference.Predictor` is still written (the TPU serving path).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from . import proto


def _attrs_of(op) -> Dict[str, Any]:
    """Static attrs = impl keyword-only defaults overlaid by call kwargs
    (per-call impls bake attrs into __kwdefaults__)."""
    out = dict(getattr(op.impl, "__kwdefaults__", None) or {})
    out.update(op.kwargs or {})
    return out


def _pads4(pad) -> List[int]:
    """[(h_lo,h_hi),(w_lo,w_hi)] -> ONNX [h_lo, w_lo, h_hi, w_hi]."""
    (hl, hh), (wl, wh) = pad
    return [int(hl), int(wl), int(hh), int(wh)]


class _Converter:
    def __init__(self, prog, graph_name: str, dyn_batch: bool = False):
        self.prog = prog
        self.dyn_batch = dyn_batch
        self.graph_name = graph_name
        self.nodes: List[bytes] = []
        self.inits: List[bytes] = []
        self.names: Dict[int, str] = {}   # vid -> onnx value name
        self._n_const = 0
        self._n_node = 0
        for pname, vid in prog.param_vids.items():
            self.names[vid] = pname
            self.inits.append(proto.tensor_proto(
                pname, np.asarray(prog.params[pname])))
        for fname, vid in prog.inputs.items():
            self.names[vid] = fname

    # -- helpers ------------------------------------------------------------
    def vname(self, vid: int) -> str:
        if vid not in self.names:
            self.names[vid] = f"v{vid}"
        return self.names[vid]

    def const(self, arr, hint="const") -> str:
        name = f"{hint}_{self._n_const}"
        self._n_const += 1
        self.inits.append(proto.tensor_proto(name, np.asarray(arr)))
        return name

    def emit(self, op_type: str, ins: Sequence[str], outs: Sequence[str],
             **attrs):
        self._n_node += 1
        self.nodes.append(proto.node(
            op_type, ins, outs, name=f"{op_type}_{self._n_node}",
            attrs=attrs or None))

    def in_names(self, op) -> List[str]:
        out = []
        for kind, ref in op.inputs:
            if kind == "var":
                out.append(self.vname(ref))
            elif ref is None:
                out.append("")
            else:
                out.append(self.const(np.asarray(ref)))
        return out

    def out_shape(self, op, i=0):
        return tuple(int(d) for d in self.prog.vars[op.out_ids[i]].shape)

    # -- op lowerings -------------------------------------------------------
    def convert(self, op):
        a = _attrs_of(op)
        ins = self.in_names(op)
        outs = [self.vname(v) for v in op.out_ids]
        n = op.name
        if n == "conv2d":
            if a.get("lhs_spec", "NCHW") != "NCHW":
                raise NotImplementedError(
                    "onnx export: Conv is NCHW-only in ONNX; re-export the "
                    f"model with data_format='NCHW' (got "
                    f"{a.get('lhs_spec')!r})")
            pad = a.get("pad")
            kw = dict(strides=[int(s) for s in a.get("stride", (1, 1))],
                      dilations=[int(d) for d in a.get("dilation", (1, 1))],
                      group=int(a.get("groups", 1)))
            if isinstance(pad, str):
                kw["auto_pad"] = {"SAME": "SAME_UPPER",
                                  "VALID": "VALID"}[pad]
            else:
                kw["pads"] = _pads4(pad)
            self.emit("Conv", ins, outs, **kw)
        elif n == "batch_norm":
            # recorded input order (x, mean, var, scale, bias) -> ONNX
            # (x, scale, bias, mean, var)
            x, rm, rv, w, b = ins
            self.emit("BatchNormalization", [x, w, b, rm, rv], outs,
                      epsilon=float(a.get("epsilon", 1e-5)))
        elif n == "batch_norm_infer_act":
            # fused BN(+add)+act inference op (Pallas fused-BN family):
            # decompose to BatchNormalization [+ Add] [+ Relu]
            x, rm, rv, w, b = ins[:5]
            res = ins[5] if len(ins) > 5 else None
            cur = outs[0] + "_bn"
            self.emit("BatchNormalization", [x, w, b, rm, rv], [cur],
                      epsilon=float(a.get("epsilon", 1e-5)))
            if res:
                nxt = outs[0] + "_add"
                self.emit("Add", [cur, res], [nxt])
                cur = nxt
            if a.get("act") == "relu":
                self.emit("Relu", [cur], outs)
            else:
                self.emit("Identity", [cur], outs)
        elif n in ("max_pool2d", "avg_pool2d", "pool2d"):
            window = a["window"]
            strides = a["strides"]
            pads = a["pads"]
            if window[0] != 1 or window[1] != 1:
                raise NotImplementedError(
                    "onnx export: pooling is NCHW-only in ONNX; re-export "
                    f"with data_format='NCHW' (window {tuple(window)})")
            kw = dict(kernel_shape=[int(window[-2]), int(window[-1])],
                      strides=[int(strides[-2]), int(strides[-1])],
                      pads=_pads4(pads[-2:]))
            if a.get("mode", "max" if n == "max_pool2d" else "avg") == "max":
                self.emit("MaxPool", ins[:1], outs, **kw)
            else:
                kw["count_include_pad"] = 0 if a.get("exclusive", True) else 1
                self.emit("AveragePool", ins[:1], outs, **kw)
        elif n == "adaptive_avg_pool2d":
            if tuple(a.get("os", ())) != (1, 1):
                raise NotImplementedError(
                    "onnx export: adaptive_avg_pool2d only with "
                    f"output_size (1,1), got {a.get('os')}")
            self.emit("GlobalAveragePool", ins[:1], outs)
        elif n in ("relu", "sigmoid", "tanh", "exp", "sqrt", "abs", "floor",
                   "ceil", "erf", "identity", "assign"):
            self.emit({"relu": "Relu", "sigmoid": "Sigmoid",
                       "tanh": "Tanh", "exp": "Exp", "sqrt": "Sqrt",
                       "abs": "Abs", "floor": "Floor", "ceil": "Ceil",
                       "erf": "Erf", "identity": "Identity",
                       "assign": "Identity"}[n], ins[:1], outs)
        elif n in ("add", "subtract", "multiply", "divide", "maximum",
                   "minimum", "pow"):
            self.emit({"add": "Add", "subtract": "Sub", "multiply": "Mul",
                       "divide": "Div", "maximum": "Max", "minimum": "Min",
                       "pow": "Pow"}[n], ins[:2], outs)
        elif n == "gelu":
            # opset<20 has no Gelu: exact erf composition
            x = ins[0]
            h = outs[0]
            s = self.const(np.asarray(np.sqrt(2.0), np.float32))
            self.emit("Div", [x, s], [h + "_div"])
            self.emit("Erf", [h + "_div"], [h + "_erf"])
            one = self.const(np.asarray(1.0, np.float32))
            self.emit("Add", [h + "_erf", one], [h + "_1p"])
            half = self.const(np.asarray(0.5, np.float32))
            self.emit("Mul", [x, h + "_1p"], [h + "_x1p"])
            self.emit("Mul", [h + "_x1p", half], outs)
        elif n in ("flatten", "reshape", "squeeze", "unsqueeze"):
            tgt = list(self.out_shape(op))
            # dynamic batch: ONNX Reshape dim 0 -> copy from input (the
            # exported graph then serves any batch size, like paddle2onnx's
            # dynamic axes), instead of baking the probe batch
            if (self.dyn_batch and op.inputs[0][0] == "var"
                    and len(tgt) >= 1
                    and tgt[0] == self.prog.vars[op.inputs[0][1]].shape[0]):
                tgt[0] = 0
            shape = self.const(np.asarray(tgt, np.int64), "shape")
            self.emit("Reshape", [ins[0], shape], outs)
        elif n == "transpose":
            perm = a.get("perm") or a.get("axes")
            self.emit("Transpose", ins[:1], outs,
                      perm=[int(p) for p in perm])
        elif n == "linear":
            x, w = ins[0], ins[1]
            b = ins[2] if len(ins) > 2 else None
            in_rank = len(self.prog.vars[op.inputs[0][1]].shape) \
                if op.inputs[0][0] == "var" else None
            if in_rank == 2:
                gemm_in = [x, w] + ([b] if b else [])
                self.emit("Gemm", gemm_in, outs, alpha=1.0, beta=1.0,
                          transA=0, transB=0)
            else:  # batched: MatMul (+ Add)
                mm_out = outs[0] + "_mm" if b else outs[0]
                self.emit("MatMul", [x, w], [mm_out])
                if b:
                    self.emit("Add", [mm_out, b], outs)
        elif n in ("matmul", "mm", "bmm"):
            x, w = ins[0], ins[1]
            if a.get("transpose_x"):
                xt = x + "_T"
                rank = len(self.prog.vars[op.inputs[0][1]].shape)
                perm = list(range(rank - 2)) + [rank - 1, rank - 2]
                self.emit("Transpose", [x], [xt], perm=perm)
                x = xt
            if a.get("transpose_y"):
                wt = w + "_T"
                rank = len(self.prog.vars[op.inputs[1][1]].shape) \
                    if op.inputs[1][0] == "var" else 2
                perm = list(range(rank - 2)) + [rank - 1, rank - 2]
                self.emit("Transpose", [w], [wt], perm=perm)
                w = wt
            self.emit("MatMul", [x, w], outs)
        elif n in ("softmax", "log_softmax"):
            self.emit("Softmax" if n == "softmax" else "LogSoftmax",
                      ins[:1], outs, axis=int(a.get("axis", -1)))
        elif n == "dropout":
            self.emit("Identity", ins[:1], outs)  # inference graphs only
        elif n == "cast":
            self.emit("Cast", ins[:1], outs,
                      to=proto.DT[str(np.dtype(a["dtype"]))])
        elif n in ("mean", "reduce_mean"):
            axes = a.get("axis")
            kw = dict(keepdims=int(bool(a.get("keepdim", False))))
            if axes is not None:
                axs = [axes] if isinstance(axes, int) else list(axes)
                kw["axes"] = [int(x) for x in axs]
            self.emit("ReduceMean", ins[:1], outs, **kw)
        else:
            raise NotImplementedError(
                f"onnx export: op '{n}' has no ONNX lowering (supported "
                "set is the inference zoo: conv/bn/pool/linear/matmul/"
                "activations/reshape/softmax)")

    def finish(self, out_vids) -> bytes:
        def in_shape(fname, vid):
            shp = list(self.prog.vars[vid].shape)
            if 0 in self.prog.dyn_dims.get(fname, ()):
                shp[0] = "batch"  # dim_param: dynamic axis
            return shp
        g_inputs = [proto.value_info(
            fname, str(self.prog.vars[vid].dtype), in_shape(fname, vid))
            for fname, vid in self.prog.inputs.items()]

        def out_shape_of(v):
            shp = list(self.prog.vars[v].shape)
            if self.dyn_batch and shp:
                shp[0] = "batch"
            return shp
        g_outputs = [proto.value_info(
            self.vname(v), str(self.prog.vars[v].dtype), out_shape_of(v))
            for v in out_vids]
        g = proto.graph(self.nodes, self.graph_name, self.inits,
                        g_inputs, g_outputs)
        return proto.model(g)


def export_program(prog, out_vids, path: str, graph_name="paddle_tpu",
                   dyn_batch: bool = False):
    """Serialize a recorded Program (inference slice) to `path` (.onnx)."""
    conv = _Converter(prog, graph_name, dyn_batch=dyn_batch)
    for op in prog.ops:
        conv.convert(op)
    data = conv.finish(out_vids)
    with open(path, "wb") as f:
        f.write(data)
    return path


def export(layer, path: str, input_spec: Optional[Sequence] = None,
           opset_version: int = 13, **configs):
    """Export `layer` as a real ONNX model (+ the StableHLO Predictor
    artifact). `input_spec`: list of InputSpec/Tensors (static shapes)."""
    from .. import jit as jit_mod
    from .. import static
    from ..framework.tensor import Tensor
    from ..static import InputSpec

    if input_spec is None:
        raise ValueError("paddle.onnx.export needs input_spec")
    specs = []
    for i, s in enumerate(input_spec):
        if isinstance(s, InputSpec):
            specs.append(s)
        elif isinstance(s, Tensor):
            specs.append(InputSpec.from_tensor(s, name=f"x{i}"))
        else:
            raise TypeError(f"input_spec[{i}]: {type(s)}")

    was_training = layer.training
    layer.eval()
    prog = static.Program()
    static._enable_static()
    try:
        with static.program_guard(prog):
            # raw spec shapes: static.data turns None/-1 dims into probe
            # size 1 AND records them in prog.dyn_dims (the dynamic-axis
            # information the converter needs for dim_param emission)
            feeds = [static.data(s.name or f"x{i}", list(s.shape), s.dtype)
                     for i, s in enumerate(specs)]
            out = layer(*feeds)
    finally:
        static._disable_static()
        if was_training:
            layer.train()
    outs = out if isinstance(out, (tuple, list)) else (out,)
    out_vids = [o._vid for o in outs]
    dyn_batch = any(0 in d for d in prog.dyn_dims.values())

    onnx_path = path if path.endswith(".onnx") else path + ".onnx"
    export_program(prog, out_vids, onnx_path,
                   graph_name=type(layer).__name__, dyn_batch=dyn_batch)
    # TPU serving artifact alongside (Predictor consumes this, not ONNX)
    prefix = path[:-5] if path.endswith(".onnx") else path
    try:
        jit_mod.save(layer, prefix, input_spec=specs)
    except Exception:
        pass  # the .onnx file is the contract here; StableHLO best-effort
    return onnx_path
