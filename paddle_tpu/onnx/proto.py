"""Minimal ONNX protobuf wire-format writer (and reader, for tests).

The reference delegates ONNX serialization to paddle2onnx + the `onnx`
package (`/root/reference/python/paddle/onnx/export.py:36`); neither is in
this environment, so the exporter emits the wire format directly. Only the
message subset the zoo needs is implemented, with field numbers from the
public onnx.proto (stable since IR version 3): ModelProto{ir_version=1,
producer_name=2, graph=7, opset_import=8}, GraphProto{node=1, name=2,
initializer=5, input=11, output=12}, NodeProto{input=1, output=2, name=3,
op_type=4, attribute=5}, AttributeProto{name=1, f=2, i=3, s=4, t=5,
floats=7, ints=8, type=20}, TensorProto{dims=1, data_type=2, name=8,
raw_data=9}, ValueInfoProto{name=1, type=2}, TypeProto{tensor_type=1},
TypeProto.Tensor{elem_type=1, shape=2}, TensorShapeProto{dim=1},
Dimension{dim_value=1}.
"""
from __future__ import annotations

import struct
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

# ONNX TensorProto.DataType values
DT = {"float32": 1, "uint8": 2, "int8": 3, "uint16": 4, "int16": 5,
      "int32": 6, "int64": 7, "bool": 9, "float16": 10, "float64": 11,
      "bfloat16": 16}
_NP_OF_DT = {v: k for k, v in DT.items()}

# AttributeProto.AttributeType
ATTR_FLOAT, ATTR_INT, ATTR_STRING, ATTR_TENSOR = 1, 2, 3, 4
ATTR_FLOATS, ATTR_INTS, ATTR_STRINGS = 6, 7, 8


def _varint(n: int) -> bytes:
    out = bytearray()
    n &= (1 << 64) - 1
    while True:
        b = n & 0x7F
        n >>= 7
        out.append(b | (0x80 if n else 0))
        if not n:
            return bytes(out)


def _tag(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def _f_varint(field: int, value: int) -> bytes:
    return _tag(field, 0) + _varint(int(value))


def _f_bytes(field: int, data: bytes) -> bytes:
    return _tag(field, 2) + _varint(len(data)) + data


def _f_str(field: int, s: str) -> bytes:
    return _f_bytes(field, s.encode())


def _f_float(field: int, v: float) -> bytes:
    return _tag(field, 5) + struct.pack("<f", float(v))


def tensor_proto(name: str, arr: np.ndarray) -> bytes:
    arr = np.ascontiguousarray(arr)
    dt = DT.get(str(arr.dtype))
    if dt is None:
        raise ValueError(f"onnx: unsupported initializer dtype {arr.dtype}")
    msg = b"".join(_f_varint(1, d) for d in arr.shape)
    msg += _f_varint(2, dt)
    msg += _f_str(8, name)
    msg += _f_bytes(9, arr.tobytes())
    return msg


def attribute(name: str, value) -> bytes:
    msg = _f_str(1, name)
    if isinstance(value, bool):
        msg += _f_varint(3, int(value)) + _f_varint(20, ATTR_INT)
    elif isinstance(value, int):
        msg += _f_varint(3, value) + _f_varint(20, ATTR_INT)
    elif isinstance(value, float):
        msg += _f_float(2, value) + _f_varint(20, ATTR_FLOAT)
    elif isinstance(value, str):
        msg += _f_bytes(4, value.encode()) + _f_varint(20, ATTR_STRING)
    elif isinstance(value, np.ndarray):
        msg += _f_bytes(5, tensor_proto(name + "_t", value))
        msg += _f_varint(20, ATTR_TENSOR)
    elif isinstance(value, (list, tuple)):
        if all(isinstance(v, (int, np.integer)) for v in value):
            msg += b"".join(_f_varint(8, int(v)) for v in value)
            msg += _f_varint(20, ATTR_INTS)
        elif all(isinstance(v, float) for v in value):
            msg += b"".join(_f_float(7, v) for v in value)
            msg += _f_varint(20, ATTR_FLOATS)
        else:
            raise ValueError(f"onnx attribute {name}: mixed list {value!r}")
    else:
        raise ValueError(f"onnx attribute {name}: {type(value)} unsupported")
    return msg


def node(op_type: str, inputs: Sequence[str], outputs: Sequence[str],
         name: str = "", attrs: Optional[Dict[str, Any]] = None) -> bytes:
    msg = b"".join(_f_str(1, i) for i in inputs)
    msg += b"".join(_f_str(2, o) for o in outputs)
    if name:
        msg += _f_str(3, name)
    msg += _f_str(4, op_type)
    for k, v in (attrs or {}).items():
        msg += _f_bytes(5, attribute(k, v))
    return msg


def value_info(name: str, dtype: str, shape: Sequence) -> bytes:
    """shape entries: int -> dim_value; str -> dim_param (dynamic axis)."""
    dims = b"".join(
        _f_bytes(1, _f_str(2, d) if isinstance(d, str)
                 else _f_varint(1, int(d)))
        for d in shape)
    tensor_type = _f_varint(1, DT[str(dtype)]) + _f_bytes(2, dims)
    type_proto = _f_bytes(1, tensor_type)
    return _f_str(1, name) + _f_bytes(2, type_proto)


def graph(nodes: Sequence[bytes], name: str,
          initializers: Sequence[bytes],
          inputs: Sequence[bytes], outputs: Sequence[bytes]) -> bytes:
    msg = b"".join(_f_bytes(1, n) for n in nodes)
    msg += _f_str(2, name)
    msg += b"".join(_f_bytes(5, t) for t in initializers)
    msg += b"".join(_f_bytes(11, i) for i in inputs)
    msg += b"".join(_f_bytes(12, o) for o in outputs)
    return msg


def model(graph_bytes: bytes, opset: int = 13,
          producer: str = "paddle_tpu") -> bytes:
    opset_id = _f_str(1, "") + _f_varint(2, opset)
    return (_f_varint(1, 8)            # ir_version 8
            + _f_str(2, producer)
            + _f_bytes(7, graph_bytes)
            + _f_bytes(8, opset_id))


# --------------------------------------------------------------------------
# wire-format reader (test/tooling side): generic parse into nested dicts
# keyed by field number, then shaped by the message schemas above
# --------------------------------------------------------------------------
def _read_varint(buf: bytes, i: int) -> Tuple[int, int]:
    shift = n = 0
    while True:
        b = buf[i]
        i += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return n, i
        shift += 7


def parse_fields(buf: bytes) -> Dict[int, List]:
    """field number -> list of raw values (int for varint, bytes for
    length-delimited, float for fixed32)."""
    out: Dict[int, List] = {}
    i = 0
    while i < len(buf):
        key, i = _read_varint(buf, i)
        field, wire = key >> 3, key & 7
        if wire == 0:
            v, i = _read_varint(buf, i)
        elif wire == 2:
            ln, i = _read_varint(buf, i)
            v = buf[i:i + ln]
            i += ln
        elif wire == 5:
            v = struct.unpack("<f", buf[i:i + 4])[0]
            i += 4
        elif wire == 1:
            v = struct.unpack("<d", buf[i:i + 8])[0]
            i += 8
        else:
            raise ValueError(f"wire type {wire} unsupported")
        out.setdefault(field, []).append(v)
    return out


def parse_tensor(buf: bytes) -> Tuple[str, np.ndarray]:
    f = parse_fields(buf)
    dims = [int(d) for d in f.get(1, [])]
    dt = _NP_OF_DT[int(f[2][0])]
    name = f.get(8, [b""])[0].decode()
    if 9 in f:
        arr = np.frombuffer(f[9][0], dtype=dt).reshape(dims)
    else:
        raise ValueError("only raw_data tensors emitted/parsed")
    return name, arr


def parse_attribute(buf: bytes):
    f = parse_fields(buf)
    name = f[1][0].decode()
    at = int(f.get(20, [0])[0])
    if at == ATTR_INT:
        return name, int(f[3][0])
    if at == ATTR_FLOAT:
        return name, float(f[2][0])
    if at == ATTR_STRING:
        return name, f[4][0].decode()
    if at == ATTR_INTS:
        return name, [int(v) for v in f.get(8, [])]
    if at == ATTR_FLOATS:
        return name, [float(v) for v in f.get(7, [])]
    if at == ATTR_TENSOR:
        return name, parse_tensor(f[5][0])[1]
    raise ValueError(f"attribute type {at} unsupported")


def parse_node(buf: bytes) -> Dict[str, Any]:
    f = parse_fields(buf)
    return {
        "inputs": [b.decode() for b in f.get(1, [])],
        "outputs": [b.decode() for b in f.get(2, [])],
        "name": f.get(3, [b""])[0].decode(),
        "op_type": f[4][0].decode(),
        "attrs": dict(parse_attribute(a) for a in f.get(5, [])),
    }


def parse_value_info(buf: bytes) -> Dict[str, Any]:
    f = parse_fields(buf)
    name = f[1][0].decode()
    tt = parse_fields(parse_fields(f[2][0])[1][0])
    elem = int(tt[1][0])
    shape: List[Any] = []
    for dim in parse_fields(tt[2][0]).get(1, []):
        df = parse_fields(dim)
        if 2 in df:  # dim_param (dynamic axis)
            shape.append(df[2][0].decode())
        else:
            shape.append(int(df.get(1, [0])[0]))
    return {"name": name, "dtype": _NP_OF_DT[elem], "shape": shape}


def parse_model(buf: bytes) -> Dict[str, Any]:
    f = parse_fields(buf)
    g = parse_fields(f[7][0])
    opset = parse_fields(f[8][0]) if 8 in f else {2: [0]}
    return {
        "ir_version": int(f[1][0]),
        "producer": f.get(2, [b""])[0].decode(),
        "opset": int(opset.get(2, [0])[0]),
        "graph": {
            "name": g.get(2, [b""])[0].decode(),
            "nodes": [parse_node(n) for n in g.get(1, [])],
            "initializers": dict(parse_tensor(t) for t in g.get(5, [])),
            "inputs": [parse_value_info(v) for v in g.get(11, [])],
            "outputs": [parse_value_info(v) for v in g.get(12, [])],
        },
    }
