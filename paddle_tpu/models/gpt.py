"""GPT — decoder-only transformer LM (flagship model).

Capability target: the reference's fleet GPT examples (GPT-3 1.3B/6.7B hybrid
TP+PP configs in `BASELINE.json`). Architecture is GPT-2/3 style: learned
positions, pre-LN blocks, causal flash attention. The hybrid-parallel variant
lives in `paddle_tpu.distributed.hybrid` (stacked-layer pipeline + TP
shardings); this module is the single-device/DP definition.
"""
from __future__ import annotations

import dataclasses

import numpy as np

from .. import nn
from ..nn import functional as F
from ..framework.tensor import Tensor
from ..ops import arange, reshape, transpose


class PagedKVCache:
    """Paged decode KV cache: per-layer page pools + per-sequence block
    tables (ops/pallas/paged_attention.py layout).

    ``k_pages[l]`` / ``v_pages[l]`` are ``[num_pages, page_size, H, D]``;
    ``block_tables`` is ``[max_batch, pages_per_seq]`` int32 and
    ``context_lens`` ``[max_batch]`` int32. Page 0 is the NULL page: idle
    batch slots point at it and their decode-step writes land there (see
    the serving allocator). Registered as a pytree so a whole serving
    decode step jits over it with the pools donated."""

    def __init__(self, k_pages, v_pages, block_tables, context_lens,
                 page_size: int):
        self.k_pages = list(k_pages)
        self.v_pages = list(v_pages)
        self.block_tables = block_tables
        self.context_lens = context_lens
        self.page_size = int(page_size)

    @property
    def num_pages(self) -> int:
        return self.k_pages[0].shape[0]

    @property
    def pages_per_seq(self) -> int:
        return self.block_tables.shape[1]

    @property
    def max_batch(self) -> int:
        return self.block_tables.shape[0]

    def tree_flatten(self):
        return ((self.k_pages, self.v_pages, self.block_tables,
                 self.context_lens), (self.page_size,))

    @classmethod
    def tree_unflatten(cls, aux, children):
        k_pages, v_pages, block_tables, context_lens = children
        return cls(k_pages, v_pages, block_tables, context_lens, aux[0])


def _register_cache_pytree():
    import jax
    jax.tree_util.register_pytree_node(
        PagedKVCache, PagedKVCache.tree_flatten,
        PagedKVCache.tree_unflatten)


_register_cache_pytree()


@dataclasses.dataclass
class GPTConfig:
    vocab_size: int = 50304
    max_position_embeddings: int = 1024
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 0  # 0 => 4*hidden
    dropout: float = 0.1
    attn_dropout: float = 0.1
    tie_word_embeddings: bool = True
    # activation-checkpoint policy per block: "" (save-everything),
    # "dots" (selective: keep matmul outputs, recompute elementwise chains
    # in backward — HBM-for-VPU trade), "full" (recompute whole block)
    remat: str = ""

    def __post_init__(self):
        if not self.intermediate_size:
            self.intermediate_size = 4 * self.hidden_size
        if self.remat not in ("", "dots", "full"):
            raise ValueError(
                f"GPTConfig.remat must be '', 'dots' or 'full', "
                f"got {self.remat!r}")

    @staticmethod
    def gpt2_small():
        return GPTConfig(hidden_size=768, num_layers=12, num_heads=12)

    @staticmethod
    def gpt3_1p3b():
        return GPTConfig(hidden_size=2048, num_layers=24, num_heads=16,
                         max_position_embeddings=2048)

    @staticmethod
    def gpt3_6p7b():
        return GPTConfig(hidden_size=4096, num_layers=32, num_heads=32,
                         max_position_embeddings=2048)

    @staticmethod
    def tiny():
        return GPTConfig(vocab_size=1024, max_position_embeddings=128,
                         hidden_size=64, num_layers=2, num_heads=4, dropout=0.0,
                         attn_dropout=0.0)


class GPTAttention(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        h = cfg.hidden_size
        self.num_heads = cfg.num_heads
        self.head_dim = h // cfg.num_heads
        self.qkv = nn.Linear(h, 3 * h)
        self.proj = nn.Linear(h, h)
        self.attn_dropout = cfg.attn_dropout
        self.resid_drop = nn.Dropout(cfg.dropout)

    def forward(self, x):
        import jax
        # named scopes -> XLA op metadata: the trace-measured per-segment
        # breakdown (profiler/xplane.segment_breakdown) attributes work
        # events to attention/mlp/ln/... by these scope tags
        with jax.named_scope("attention"):
            B, L, H = x.shape
            qkv = self.qkv(x)
            qkv = reshape(qkv, [B, L, 3, self.num_heads, self.head_dim])
            q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
            out = F.scaled_dot_product_attention(
                q, k, v, is_causal=True, dropout_p=self.attn_dropout,
                training=self.training)
            out = reshape(out, [B, L, H])
            return self.resid_drop(self.proj(out))


class GPTMLP(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.fc1 = nn.Linear(cfg.hidden_size, cfg.intermediate_size)
        self.fc2 = nn.Linear(cfg.intermediate_size, cfg.hidden_size)
        self.drop = nn.Dropout(cfg.dropout)

    def forward(self, x):
        import jax
        with jax.named_scope("mlp"):
            return self.drop(self.fc2(F.gelu(self.fc1(x), approximate=True)))


class GPTBlock(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.ln1 = nn.LayerNorm(cfg.hidden_size)
        self.attn = GPTAttention(cfg)
        self.ln2 = nn.LayerNorm(cfg.hidden_size)
        self.mlp = GPTMLP(cfg)

    def forward(self, x):
        import jax
        with jax.named_scope("ln"):
            h = self.ln1(x)
        x = x + self.attn(h)
        with jax.named_scope("ln"):
            h = self.ln2(x)
        x = x + self.mlp(h)
        return x


class GPT(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        self.wte = nn.Embedding(cfg.vocab_size, cfg.hidden_size)
        self.wpe = nn.Embedding(cfg.max_position_embeddings, cfg.hidden_size)
        self.drop = nn.Dropout(cfg.dropout)
        self.blocks = nn.LayerList([GPTBlock(cfg) for _ in range(cfg.num_layers)])
        self.ln_f = nn.LayerNorm(cfg.hidden_size)
        if not cfg.tie_word_embeddings:
            self.lm_head = nn.Linear(cfg.hidden_size, cfg.vocab_size,
                                     bias_attr=False)

    # pipeline protocol (distributed.meta_parallel.pipeline_parallel):
    # pre -> scanned homogeneous blocks -> post
    def pipeline_pre(self, input_ids):
        import jax
        with jax.named_scope("embed"):
            B, L = input_ids.shape
            pos = arange(0, L, dtype="int32")
            x = self.wte(input_ids) + self.wpe(pos)
            return self.drop(x)

    def pipeline_post(self, x):
        import jax
        with jax.named_scope("ln"):
            x = self.ln_f(x)
        with jax.named_scope("logits"):
            if self.cfg.tie_word_embeddings:
                from ..ops import matmul
                return matmul(x, self.wte.weight, transpose_y=True)
            return self.lm_head(x)

    def forward(self, input_ids):
        x = self.pipeline_pre(input_ids)
        if self.cfg.remat and self.training:
            import jax

            from ..distributed.fleet.utils import recompute
            pol = (None if self.cfg.remat == "full" else
                   jax.checkpoint_policies.dots_with_no_batch_dims_saveable)
            for blk in self.blocks:
                x = recompute(blk, x, policy=pol)
        else:
            for blk in self.blocks:
                x = blk(x)
        return self.pipeline_post(x)

    def loss(self, input_ids, labels):
        logits = self(input_ids)
        return F.cross_entropy(logits, labels)

    def num_params(self):
        return sum(p.size for p in self.parameters())

    # ---------------- autoregressive decode (paged KV cache) ----------------
    #
    # The training forward above re-runs full-sequence attention for every
    # generated token — O(n^2) FLOPs and HBM traffic per sequence. The
    # decode path below is the serving shape: K/V of every past token live
    # in fixed-size pages (ops/pallas/paged_attention.py), prefill runs the
    # prompt once through the normal flash-attention path while scattering
    # its K/V into the pages, and each generated token is ONE incremental
    # step (append one K/V row, attend over the pages). All methods are
    # traceable — inference/serving.py jits the whole batched step with the
    # cache donated.

    def set_tp_mesh(self, mesh, axis: str = "tp"):
        """Arm the tensor-parallel decode path: `init_cache` shards the
        K/V page pools over `axis` on the HEAD dim, and the decode/
        prefill page paths run per-shard via shard_map (the attention
        output is gathered back to replicated before the proj matmul, so
        no floating-point contraction ever splits across devices —
        greedy decode stays bit-exact vs single-chip). Pass None to
        disarm. Weights stay replicated (decode is KV-bandwidth bound;
        the pool is the memory that scales N×)."""
        if mesh is not None:
            if axis not in mesh.shape:
                raise ValueError(f"set_tp_mesh: mesh has no axis "
                                 f"{axis!r} (axes: {dict(mesh.shape)})")
            if self.cfg.num_heads % mesh.shape[axis]:
                raise ValueError(
                    f"set_tp_mesh: num_heads {self.cfg.num_heads} does "
                    f"not divide over mesh axis {axis!r} of size "
                    f"{mesh.shape[axis]}")
        self._tp_mesh = mesh
        self._tp_axis = axis

    def tp_mesh(self):
        return getattr(self, "_tp_mesh", None)

    def init_cache(self, max_batch: int, max_len: int, page_size: int = 16,
                   num_pages: int = 0, dtype=None,
                   sharded: bool = True) -> PagedKVCache:
        """Build an empty paged KV cache for `max_batch` concurrent
        sequences of up to `max_len` tokens. `num_pages` defaults to full
        backing (every slot can reach max_len) + the null page; a serving
        deployment may pass less and rely on allocator preemption.

        With a TP mesh armed (`set_tp_mesh`) the pools allocate SHARDED
        over the head axis — each device holds 1/N of every layer's pool,
        which is the N×-larger-model capacity claim — while block tables
        and context lens replicate (they are host-updated control state).
        `sharded=False` builds a plain single-device cache regardless
        (the disaggregated prefill workers' private caches)."""
        import jax
        import jax.numpy as jnp
        if max_len > self.cfg.max_position_embeddings:
            raise ValueError(
                f"init_cache: max_len {max_len} exceeds "
                f"max_position_embeddings {self.cfg.max_position_embeddings}")
        pages_per_seq = -(-max_len // page_size)
        if not num_pages:
            num_pages = 1 + max_batch * pages_per_seq  # +1: the null page
        if dtype is None:
            dtype = self.wte.weight.dtype
        H, D = self.cfg.num_heads, self.cfg.hidden_size // self.cfg.num_heads
        shape = (num_pages, page_size, H, D)
        mesh = self.tp_mesh() if sharded else None
        if mesh is None:
            k_pages = [jnp.zeros(shape, dtype) for _ in self.blocks]
            v_pages = [jnp.zeros(shape, dtype) for _ in self.blocks]
            bt = jnp.zeros((max_batch, pages_per_seq), jnp.int32)
            cl = jnp.zeros((max_batch,), jnp.int32)
        else:
            from jax.sharding import NamedSharding, PartitionSpec as P
            pool_sh = NamedSharding(mesh, P(None, None, self._tp_axis,
                                            None))
            rep_sh = NamedSharding(mesh, P())
            # allocate THROUGH the sharding: each device materializes
            # only its pool shard — the whole point of TP decode is that
            # the full pool never exists on one chip
            zeros = jax.jit(lambda: jnp.zeros(shape, dtype),
                            out_shardings=pool_sh)
            k_pages = [zeros() for _ in self.blocks]
            v_pages = [zeros() for _ in self.blocks]
            bt = jax.device_put(
                jnp.zeros((max_batch, pages_per_seq), jnp.int32), rep_sh)
            cl = jax.device_put(jnp.zeros((max_batch,), jnp.int32), rep_sh)
        return PagedKVCache(k_pages, v_pages, bt, cl, page_size)

    def _block_qkv(self, blk, x):
        """(q, k, v) raw arrays [B, L, H, D] from one block's qkv proj."""
        B, L, _ = x.shape
        qkv = blk.attn.qkv(x)
        qkv = reshape(qkv, [B, L, 3, blk.attn.num_heads, blk.attn.head_dim])
        return qkv[:, :, 0].data, qkv[:, :, 1].data, qkv[:, :, 2].data

    def forward_prefill(self, input_ids, cache: PagedKVCache, slot,
                        length, write_start=0, use_tp: bool = True):
        """Prefill ONE sequence: run the prompt through the normal (flash)
        causal attention while scattering every position's K/V into the
        pages of batch slot `slot`. `input_ids` is [1, L_bucket] (L may be
        padded up to a shape bucket — the retrace watchdog stays quiet
        because serving always pads to a bucket); `length` is the real
        prompt length (traced ok). `write_start` masks the K/V scatter
        below that position: a request admitted with a SHARED prefix
        (serving's copy-on-write page fork) already has positions
        [0, write_start) in pages forked from another request, and must
        not re-write them — attention still runs over the full prompt
        (the logits need the whole context; only the scatter is masked).
        Returns (last-position logits [1, V], updated cache)."""
        import jax
        import jax.numpy as jnp
        from ..ops.pallas import paged_attention as _pa
        B, L = input_ids.shape
        if B != 1:
            raise ValueError(f"forward_prefill fills ONE slot's pages; got "
                             f"batch {B} (serving prefills per request)")
        with jax.named_scope("embed"):
            pos = arange(0, L, dtype="int32")
            x = self.wte(input_ids) + self.wpe(pos)
        slot = jnp.asarray(slot, jnp.int32)
        length = jnp.asarray(length, jnp.int32)
        write_start = jnp.asarray(write_start, jnp.int32)
        page_row = jnp.take(cache.block_tables, slot, axis=0)
        mesh = self.tp_mesh() if use_tp else None
        for li, blk in enumerate(self.blocks):
            with jax.named_scope("ln"):
                h = blk.ln1(x)
            with jax.named_scope("attention"):
                q, k, v = self._block_qkv(blk, h)
                if mesh is not None:
                    cache.k_pages[li], cache.v_pages[li] = \
                        _pa.prefill_append_tp(
                            cache.k_pages[li], cache.v_pages[li], k[0],
                            v[0], page_row, length, mesh,
                            axis=self._tp_axis, start=write_start)
                else:
                    cache.k_pages[li], cache.v_pages[li] = \
                        _pa.prefill_append(
                            cache.k_pages[li], cache.v_pages[li], k[0],
                            v[0], page_row, length, start=write_start)
                out = F.scaled_dot_product_attention(
                    Tensor(q), Tensor(k), Tensor(v), is_causal=True,
                    training=False)
                out = reshape(out, [B, L, self.cfg.hidden_size])
                x = x + blk.attn.proj(out)
            with jax.named_scope("ln"):
                h = blk.ln2(x)
            x = x + blk.mlp(h)
        cache.context_lens = cache.context_lens.at[slot].set(length)
        with jax.named_scope("logits"):
            # logits of the LAST REAL position only (bucket padding past
            # `length` attends causally to junk and is never read)
            last = Tensor(jax.lax.dynamic_index_in_dim(
                x.data, length - 1, axis=1, keepdims=False))
            logits = self.pipeline_post(last)
        return logits, cache

    def forward_decode(self, tokens, cache: PagedKVCache, active=None,
                       slot_map=None, use_tp: bool = True):
        """ONE incremental decode step: append each sequence's new token
        K/V to its pages, attend over the paged context. `tokens` is [B]
        int (the token sitting at position context_lens[b]); `active`
        [B] bool masks idle serving slots (their writes land on the null
        page, their logits are garbage nobody reads). Returns
        (logits [B, V], updated cache).

        `slot_map` [W] int32 switches to LANE mode (the serving engine's
        width-bucketed fused step): lane i computes the decode step for
        cache slot slot_map[i], so a batch with few active sequences
        runs a W << max_batch executable instead of the full-width one.
        Padding lanes carry slot_map[i] >= max_batch (the gather clamps,
        active[i] is False, and the context-length scatter-back drops
        them); `tokens`/`active` are then [W]-shaped per lane."""
        import jax
        import jax.numpy as jnp
        from ..ops.pallas import paged_attention as _pa
        lanes = slot_map is not None
        if lanes:
            slot_map = jnp.asarray(slot_map, jnp.int32)
            # clamp-gather: padding lanes read SOME real slot's row, but
            # their active mask parks writes on the null page and zeroes
            # their attention context
            bt = jnp.take(cache.block_tables, slot_map, axis=0,
                          mode="clip")
            ctx = jnp.take(cache.context_lens, slot_map, mode="clip")
            if active is None:
                active = slot_map < cache.max_batch
        else:
            bt = cache.block_tables
            ctx = cache.context_lens
            if active is None:
                active = jnp.ones((cache.max_batch,), bool)
        with jax.named_scope("embed"):
            # position of the incoming token = current context length
            pos = Tensor(jnp.minimum(
                ctx, self.cfg.max_position_embeddings - 1))
            x = self.wte(tokens) + self.wpe(pos)       # [B, hidden]
        B = x.shape[0]
        x = reshape(x, [B, 1, self.cfg.hidden_size])
        mesh = self.tp_mesh() if use_tp else None
        for li, blk in enumerate(self.blocks):
            with jax.named_scope("ln"):
                h = blk.ln1(x)
            with jax.named_scope("attention"):
                q, k, v = self._block_qkv(blk, h)      # [B, 1, H, D]
                if mesh is not None:
                    # TP: per-shard append + attention on the local head
                    # slice; `out` comes back REPLICATED so the proj
                    # contraction below never splits (bit-exactness)
                    out, cache.k_pages[li], cache.v_pages[li] = \
                        _pa.decode_step_tp(
                            q[:, 0], k[:, 0], v[:, 0], cache.k_pages[li],
                            cache.v_pages[li], bt, ctx, active, mesh,
                            axis=self._tp_axis)
                else:
                    cache.k_pages[li], cache.v_pages[li] = \
                        _pa.cache_append(
                            cache.k_pages[li], cache.v_pages[li],
                            k[:, 0], v[:, 0], bt, ctx, active)
                    out = _pa.paged_attention(
                        q[:, 0], cache.k_pages[li], cache.v_pages[li], bt,
                        # the new token is part of its own context
                        jnp.where(active, ctx + 1, 0))
                out = reshape(Tensor(out), [B, 1, self.cfg.hidden_size])
                x = x + blk.attn.proj(out)
            with jax.named_scope("ln"):
                h = blk.ln2(x)
            x = x + blk.mlp(h)
        if lanes:
            # scatter-back: +1 for each active lane's slot; padding-lane
            # sentinels (>= max_batch) drop instead of clamping onto a
            # real slot's counter
            cache.context_lens = cache.context_lens.at[slot_map].add(
                jnp.where(active, 1, 0).astype(jnp.int32), mode="drop")
        else:
            cache.context_lens = jnp.where(active, ctx + 1, ctx)
        with jax.named_scope("logits"):
            logits = self.pipeline_post(reshape(x, [B, self.cfg.hidden_size]))
        return logits, cache

    # -- reference decode loops (bench A/B + parity tests) -------------------

    def generate_dense(self, input_ids, max_new_tokens: int,
                       eos_id: int = -1):
        """Cacheless greedy decode: the O(n^2) baseline — every token
        re-runs the FULL forward over the whole growing sequence. Returns
        [B, L + max_new_tokens] (generation stops early only when every
        row hit eos_id)."""
        import numpy as np
        from ..ops import argmax, concat
        ids = input_ids
        for _ in range(max_new_tokens):
            logits = self(ids)                          # [B, L', V]
            nxt = argmax(logits[:, -1], axis=-1, dtype="int32")
            ids = concat([ids, reshape(nxt, [ids.shape[0], 1])], axis=1)
            if eos_id >= 0 and bool(np.all(np.asarray(nxt.data) == eos_id)):
                break
        return ids

    def generate_paged(self, input_ids, max_new_tokens: int,
                       eos_id: int = -1, page_size: int = 8):
        """Greedy decode through the paged path: prefill once, then one
        incremental `forward_decode` per token. The parity counterpart of
        `generate_dense` (inference/serving.py is the production loop —
        this helper allocates pages contiguously per row)."""
        import numpy as np
        import jax.numpy as jnp
        from ..ops import argmax, concat
        if max_new_tokens <= 0:
            return input_ids  # match generate_dense's [B, L] contract
        B, L = input_ids.shape
        max_len = L + max_new_tokens
        cache = self.init_cache(B, max_len, page_size=page_size)
        pps = cache.pages_per_seq
        # contiguous page plan: row b owns pages [1 + b*pps, 1 + (b+1)*pps)
        bt = 1 + np.arange(B * pps, dtype=np.int32).reshape(B, pps)
        cache.block_tables = jnp.asarray(bt)
        for b in range(B):
            logits, cache = self.forward_prefill(
                input_ids[b:b + 1], cache, b, L)
            last = logits if b == 0 else concat([last, logits], axis=0)
        ids = input_ids
        nxt = argmax(last, axis=-1, dtype="int32")
        ids = concat([ids, reshape(nxt, [B, 1])], axis=1)
        for _ in range(max_new_tokens - 1):
            if eos_id >= 0 and bool(np.all(np.asarray(nxt.data) == eos_id)):
                break
            logits, cache = self.forward_decode(nxt, cache)
            nxt = argmax(logits, axis=-1, dtype="int32")
            ids = concat([ids, reshape(nxt, [B, 1])], axis=1)
        return ids
