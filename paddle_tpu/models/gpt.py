"""GPT — decoder-only transformer LM (flagship model).

Capability target: the reference's fleet GPT examples (GPT-3 1.3B/6.7B hybrid
TP+PP configs in `BASELINE.json`). Architecture is GPT-2/3 style: learned
positions, pre-LN blocks, causal flash attention. The hybrid-parallel variant
lives in `paddle_tpu.distributed.hybrid` (stacked-layer pipeline + TP
shardings); this module is the single-device/DP definition.
"""
from __future__ import annotations

import dataclasses

import numpy as np

from .. import nn
from ..nn import functional as F
from ..framework.tensor import Tensor
from ..ops import arange, reshape, transpose


@dataclasses.dataclass
class GPTConfig:
    vocab_size: int = 50304
    max_position_embeddings: int = 1024
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 0  # 0 => 4*hidden
    dropout: float = 0.1
    attn_dropout: float = 0.1
    tie_word_embeddings: bool = True
    # activation-checkpoint policy per block: "" (save-everything),
    # "dots" (selective: keep matmul outputs, recompute elementwise chains
    # in backward — HBM-for-VPU trade), "full" (recompute whole block)
    remat: str = ""

    def __post_init__(self):
        if not self.intermediate_size:
            self.intermediate_size = 4 * self.hidden_size
        if self.remat not in ("", "dots", "full"):
            raise ValueError(
                f"GPTConfig.remat must be '', 'dots' or 'full', "
                f"got {self.remat!r}")

    @staticmethod
    def gpt2_small():
        return GPTConfig(hidden_size=768, num_layers=12, num_heads=12)

    @staticmethod
    def gpt3_1p3b():
        return GPTConfig(hidden_size=2048, num_layers=24, num_heads=16,
                         max_position_embeddings=2048)

    @staticmethod
    def gpt3_6p7b():
        return GPTConfig(hidden_size=4096, num_layers=32, num_heads=32,
                         max_position_embeddings=2048)

    @staticmethod
    def tiny():
        return GPTConfig(vocab_size=1024, max_position_embeddings=128,
                         hidden_size=64, num_layers=2, num_heads=4, dropout=0.0,
                         attn_dropout=0.0)


class GPTAttention(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        h = cfg.hidden_size
        self.num_heads = cfg.num_heads
        self.head_dim = h // cfg.num_heads
        self.qkv = nn.Linear(h, 3 * h)
        self.proj = nn.Linear(h, h)
        self.attn_dropout = cfg.attn_dropout
        self.resid_drop = nn.Dropout(cfg.dropout)

    def forward(self, x):
        import jax
        # named scopes -> XLA op metadata: the trace-measured per-segment
        # breakdown (profiler/xplane.segment_breakdown) attributes work
        # events to attention/mlp/ln/... by these scope tags
        with jax.named_scope("attention"):
            B, L, H = x.shape
            qkv = self.qkv(x)
            qkv = reshape(qkv, [B, L, 3, self.num_heads, self.head_dim])
            q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
            out = F.scaled_dot_product_attention(
                q, k, v, is_causal=True, dropout_p=self.attn_dropout,
                training=self.training)
            out = reshape(out, [B, L, H])
            return self.resid_drop(self.proj(out))


class GPTMLP(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.fc1 = nn.Linear(cfg.hidden_size, cfg.intermediate_size)
        self.fc2 = nn.Linear(cfg.intermediate_size, cfg.hidden_size)
        self.drop = nn.Dropout(cfg.dropout)

    def forward(self, x):
        import jax
        with jax.named_scope("mlp"):
            return self.drop(self.fc2(F.gelu(self.fc1(x), approximate=True)))


class GPTBlock(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.ln1 = nn.LayerNorm(cfg.hidden_size)
        self.attn = GPTAttention(cfg)
        self.ln2 = nn.LayerNorm(cfg.hidden_size)
        self.mlp = GPTMLP(cfg)

    def forward(self, x):
        import jax
        with jax.named_scope("ln"):
            h = self.ln1(x)
        x = x + self.attn(h)
        with jax.named_scope("ln"):
            h = self.ln2(x)
        x = x + self.mlp(h)
        return x


class GPT(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        self.wte = nn.Embedding(cfg.vocab_size, cfg.hidden_size)
        self.wpe = nn.Embedding(cfg.max_position_embeddings, cfg.hidden_size)
        self.drop = nn.Dropout(cfg.dropout)
        self.blocks = nn.LayerList([GPTBlock(cfg) for _ in range(cfg.num_layers)])
        self.ln_f = nn.LayerNorm(cfg.hidden_size)
        if not cfg.tie_word_embeddings:
            self.lm_head = nn.Linear(cfg.hidden_size, cfg.vocab_size,
                                     bias_attr=False)

    # pipeline protocol (distributed.meta_parallel.pipeline_parallel):
    # pre -> scanned homogeneous blocks -> post
    def pipeline_pre(self, input_ids):
        import jax
        with jax.named_scope("embed"):
            B, L = input_ids.shape
            pos = arange(0, L, dtype="int32")
            x = self.wte(input_ids) + self.wpe(pos)
            return self.drop(x)

    def pipeline_post(self, x):
        import jax
        with jax.named_scope("ln"):
            x = self.ln_f(x)
        with jax.named_scope("logits"):
            if self.cfg.tie_word_embeddings:
                from ..ops import matmul
                return matmul(x, self.wte.weight, transpose_y=True)
            return self.lm_head(x)

    def forward(self, input_ids):
        x = self.pipeline_pre(input_ids)
        if self.cfg.remat and self.training:
            import jax

            from ..distributed.fleet.utils import recompute
            pol = (None if self.cfg.remat == "full" else
                   jax.checkpoint_policies.dots_with_no_batch_dims_saveable)
            for blk in self.blocks:
                x = recompute(blk, x, policy=pol)
        else:
            for blk in self.blocks:
                x = blk(x)
        return self.pipeline_post(x)

    def loss(self, input_ids, labels):
        logits = self(input_ids)
        return F.cross_entropy(logits, labels)

    def num_params(self):
        return sum(p.size for p in self.parameters())
