"""Wide&Deep CTR model over PS-resident sparse embeddings.

Reference workload: the PS path's flagship model family (Wide&Deep / DeepFM,
see /root/reference/python/paddle/fluid/tests/unittests/test_dist_fleet_ctr.py
and `distributed/ps/` generally). Sparse slots hit `SparseEmbedding` (host PS
pull/push); the dense tower is ordinary XLA compute.
"""
from __future__ import annotations

from .. import nn
from .. import ops
from ..distributed.ps import SparseEmbedding


class WideDeep(nn.Layer):
    """`num_slots` categorical slots + `dense_dim` dense features -> CTR logit."""

    def __init__(self, num_slots: int = 4, embedding_dim: int = 8,
                 dense_dim: int = 4, hidden: int = 32,
                 sparse_lr: float = 0.05, table_base: int = 0,
                 client=None):
        super().__init__()
        self.num_slots = num_slots
        self.embedding_dim = embedding_dim
        self.embeddings = nn.LayerList([
            SparseEmbedding(table_id=table_base + i,
                            embedding_dim=embedding_dim,
                            optimizer="sgd", learning_rate=sparse_lr,
                            client=client)
            for i in range(num_slots)
        ])
        # "wide" half: one scalar weight per slot via a dim-1 PS table
        self.wide = SparseEmbedding(table_id=table_base + num_slots,
                                    embedding_dim=1, optimizer="sgd",
                                    learning_rate=sparse_lr, client=client)
        self.deep = nn.Sequential(
            nn.Linear(num_slots * embedding_dim + dense_dim, hidden),
            nn.ReLU(),
            nn.Linear(hidden, hidden),
            nn.ReLU(),
            nn.Linear(hidden, 1),
        )

    def forward(self, slot_ids, dense_x):
        """slot_ids: int [batch, num_slots]; dense_x: float [batch, dense_dim]."""
        embs = []
        for i, emb in enumerate(self.embeddings):
            embs.append(emb(slot_ids[:, i]))          # [batch, dim]
        deep_in = ops.concat(embs + [dense_x], axis=-1)
        deep_out = self.deep(deep_in)                  # [batch, 1]
        wide_out = self.wide(slot_ids).sum(axis=1)     # [batch, 1]
        return deep_out + wide_out
