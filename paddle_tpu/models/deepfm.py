"""DeepFM CTR model over PS-resident sparse embeddings.

Reference workload: the second PS-path flagship next to Wide&Deep
(BASELINE target configs; reference `test_dist_fleet_ctr.py` family).
FM half: first-order weights + pairwise second-order interactions via the
sum-square/square-sum identity; deep half: MLP over the concatenated
embeddings. Both halves share the PS embedding tables.
"""
from __future__ import annotations

from .. import nn
from .. import ops
from ..distributed.ps import SparseEmbedding


class DeepFM(nn.Layer):
    def __init__(self, num_slots: int = 4, embedding_dim: int = 8,
                 hidden: int = 32, sparse_lr: float = 0.05,
                 table_base: int = 100, client=None):
        super().__init__()
        self.num_slots = num_slots
        self.embedding_dim = embedding_dim
        # second-order factors [slot ids -> dim-d vectors]
        self.fm_embeddings = nn.LayerList([
            SparseEmbedding(table_id=table_base + i,
                            embedding_dim=embedding_dim,
                            optimizer="sgd", learning_rate=sparse_lr,
                            client=client)
            for i in range(num_slots)
        ])
        # first-order weights [slot ids -> scalars]
        self.fm_first = SparseEmbedding(table_id=table_base + num_slots,
                                        embedding_dim=1, optimizer="sgd",
                                        learning_rate=sparse_lr,
                                        client=client)
        self.dnn = nn.Sequential(
            nn.Linear(num_slots * embedding_dim, hidden),
            nn.ReLU(),
            nn.Linear(hidden, hidden),
            nn.ReLU(),
            nn.Linear(hidden, 1),
        )

    def forward(self, slot_ids):
        """slot_ids: int [batch, num_slots] -> CTR logit [batch, 1]."""
        embs = [emb(slot_ids[:, i]) for i, emb in enumerate(self.fm_embeddings)]
        stacked = ops.stack(embs, axis=1)            # [B, S, D]
        # FM second order: 0.5 * ((sum v)^2 - sum v^2) summed over D
        sum_v = stacked.sum(axis=1)                   # [B, D]
        sum_sq = (stacked * stacked).sum(axis=1)      # [B, D]
        second = 0.5 * (sum_v * sum_v - sum_sq).sum(axis=1, keepdim=True)
        first = self.fm_first(slot_ids).sum(axis=1)   # [B, 1]
        deep_in = ops.concat(embs, axis=-1)           # [B, S*D]
        deep = self.dnn(deep_in)
        return first + second + deep
