"""Model zoo.

Reference parity: `python/paddle/vision/models/` (LeNet, ResNet, VGG,
MobileNet) plus transformer language models matching the reference's
ERNIE/GPT fleet examples.
"""
from .lenet import LeNet  # noqa: F401
from .resnet import ResNet, resnet18, resnet34, resnet50, resnet101, resnet152  # noqa: F401
from .gpt import GPT, GPTConfig  # noqa: F401
from .bert import Bert, BertConfig  # noqa: F401
from .ernie import Ernie, ErnieConfig, ErnieForPretraining  # noqa: F401
from .wide_deep import WideDeep  # noqa: F401
from .deepfm import DeepFM  # noqa: F401
