"""ResNet family (reference: `python/paddle/vision/models/resnet.py`)."""
from __future__ import annotations

from .. import nn


def _conv_bn(conv, bn, x, residual=None):
    """Fused conv+BN block tail: routes through `F.conv2d_bn` (the
    single-pass 1x1-conv+stats Pallas chain) ONLY when the fused kernel
    will actually engage for this shape/platform; everywhere else the
    sublayers are called normally — `Layer.__call__` must keep running so
    forward hooks fire and the PR-9 NaN-attribution layer stack still
    names conv1/bn1 rather than the whole block."""
    from ..nn import functional as F
    from ..ops.pallas import fused_conv_bn as _fcb
    ugs = bn._use_global_stats
    if ugs is None:
        ugs = not bn.training
    xs = tuple(x.data.shape) if hasattr(x, "data") else tuple(x.shape)
    xdt = x.data.dtype if hasattr(x, "data") else x.dtype
    w = conv.weight
    ws = tuple(w.data.shape) if hasattr(w, "data") else tuple(w.shape)
    if (not ugs) and _fcb.eligible(xs, ws, conv._stride, conv._padding,
                                   conv._dilation, conv._groups,
                                   conv._data_format, xdt):
        return F.conv2d_bn(
            x, conv.weight, bn._mean, bn._variance, bn.weight, bn.bias,
            training=bn.training, momentum=bn._momentum,
            epsilon=bn._epsilon, stride=conv._stride,
            padding=conv._padding, dilation=conv._dilation,
            groups=conv._groups, data_format=conv._data_format,
            use_global_stats=bn._use_global_stats, act=bn._act,
            residual=residual)
    return bn(conv(x), residual)


class BasicBlock(nn.Layer):
    expansion = 1

    def __init__(self, inplanes, planes, stride=1, downsample=None,
                 groups=1, base_width=64, dilation=1, norm_layer=None,
                 data_format="NCHW", fused_conv_bn=True):
        super().__init__()
        # default BN -> fused BN(+add)+ReLU tails (Pallas kernels); a custom
        # norm_layer keeps the unfused composition (it has no act=/residual=)
        self._fused = norm_layer is None
        self._fused_conv = fused_conv_bn and self._fused
        norm_layer = norm_layer or nn.BatchNorm2D
        df = dict(data_format=data_format)
        act = dict(act="relu") if self._fused else {}
        self.conv1 = nn.Conv2D(inplanes, planes, 3, stride=stride, padding=1,
                               bias_attr=False, **df)
        self.bn1 = norm_layer(planes, **df, **act)
        self.relu = nn.ReLU()
        self.conv2 = nn.Conv2D(planes, planes, 3, padding=1, bias_attr=False,
                               **df)
        self.bn2 = norm_layer(planes, **df, **act)
        self.downsample = downsample
        self.stride = stride

    def forward(self, x):
        identity = x
        if self.downsample is not None:
            identity = self.downsample(x)
        if self._fused_conv:
            out = _conv_bn(self.conv1, self.bn1, x)
            return _conv_bn(self.conv2, self.bn2, out, identity)
        if self._fused:
            out = self.bn1(self.conv1(x))
            return self.bn2(self.conv2(out), identity)
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        return self.relu(out + identity)


class BottleneckBlock(nn.Layer):
    expansion = 4

    def __init__(self, inplanes, planes, stride=1, downsample=None,
                 groups=1, base_width=64, dilation=1, norm_layer=None,
                 data_format="NCHW", fused_conv_bn=True):
        super().__init__()
        self._fused = norm_layer is None
        self._fused_conv = fused_conv_bn and self._fused
        norm_layer = norm_layer or nn.BatchNorm2D
        df = dict(data_format=data_format)
        act = dict(act="relu") if self._fused else {}
        width = int(planes * (base_width / 64.0)) * groups
        self.conv1 = nn.Conv2D(inplanes, width, 1, bias_attr=False, **df)
        self.bn1 = norm_layer(width, **df, **act)
        self.conv2 = nn.Conv2D(width, width, 3, padding=1, stride=stride,
                               groups=groups, dilation=dilation,
                               bias_attr=False, **df)
        self.bn2 = norm_layer(width, **df, **act)
        self.conv3 = nn.Conv2D(width, planes * self.expansion, 1,
                               bias_attr=False, **df)
        self.bn3 = norm_layer(planes * self.expansion, **df, **act)
        self.relu = nn.ReLU()
        self.downsample = downsample

    def forward(self, x):
        identity = x
        if self.downsample is not None:
            identity = self.downsample(x)
        if self._fused_conv:
            # conv1/conv3 are the 1x1s the fused kernel targets; conv2
            # (3x3) falls back inside conv2d_bn to conv -> fused BN
            out = _conv_bn(self.conv1, self.bn1, x)
            out = _conv_bn(self.conv2, self.bn2, out)
            return _conv_bn(self.conv3, self.bn3, out, identity)
        if self._fused:
            out = self.bn1(self.conv1(x))
            out = self.bn2(self.conv2(out))
            return self.bn3(self.conv3(out), identity)
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        return self.relu(out + identity)


class ResNet(nn.Layer):
    def __init__(self, block, depth=50, width=64, num_classes=1000,
                 with_pool=True, groups=1, recompute=False,
                 data_format="NCHW", fused_bn=True, fused_conv_bn=True):
        """`recompute=True` rematerializes each residual STAGE's
        activations in backward (reference RecomputeFunction applied at
        `layer1..layer4` granularity): on a bandwidth-bound chip the
        re-run conv FLOPs are cheaper than round-tripping every
        intermediate activation through HBM.

        `data_format="NHWC"` runs the whole network feature-last
        (reference resnet.py exposes the same knob): on TPU this is XLA's
        preferred convolution layout and avoids transposes.

        `fused_bn=False` keeps every BN+ReLU(+add) as the unfused
        composition — the bench's fused-vs-unfused comparison knob.

        `fused_conv_bn=False` keeps the PR-1 behavior (conv, then fused
        BN(+add)+ReLU); True additionally routes the block tails through
        `F.conv2d_bn`, whose single-pass 1x1-conv+BN-stats Pallas kernel
        removes the separate full-activation statistics read on eligible
        shapes — the bench's conv-fusion A/B knob. Requires fused_bn."""
        super().__init__()
        self._recompute = recompute
        self._data_format = data_format
        self._fused_bn = fused_bn
        self._fused_conv_bn = fused_conv_bn and fused_bn
        layer_cfg = {18: [2, 2, 2, 2], 34: [3, 4, 6, 3], 50: [3, 4, 6, 3],
                     101: [3, 4, 23, 3], 152: [3, 8, 36, 3]}
        layers = layer_cfg[depth]
        self.groups = groups
        self.base_width = width
        self.num_classes = num_classes
        self.with_pool = with_pool
        self._norm_layer = nn.BatchNorm2D
        self.inplanes = 64
        self.dilation = 1
        df = dict(data_format=data_format)
        self.conv1 = nn.Conv2D(3, self.inplanes, 7, stride=2, padding=3,
                               bias_attr=False, **df)
        self.bn1 = self._norm_layer(self.inplanes, **df,
                                    act="relu" if fused_bn else None)
        self.relu = nn.ReLU()
        self.maxpool = nn.MaxPool2D(3, stride=2, padding=1, **df)
        self.layer1 = self._make_layer(block, 64, layers[0])
        self.layer2 = self._make_layer(block, 128, layers[1], stride=2)
        self.layer3 = self._make_layer(block, 256, layers[2], stride=2)
        self.layer4 = self._make_layer(block, 512, layers[3], stride=2)
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((1, 1), **df)
        if num_classes > 0:
            self.fc = nn.Linear(512 * block.expansion, num_classes)

    def _make_layer(self, block, planes, blocks, stride=1):
        norm_layer = self._norm_layer
        # blocks see norm_layer=None when fusion is on: the block picks the
        # fused BN(+add)+ReLU tails only for the default (our) BatchNorm2D
        block_norm = None if self._fused_bn else norm_layer
        df = dict(data_format=self._data_format)
        downsample = None
        if stride != 1 or self.inplanes != planes * block.expansion:
            downsample = nn.Sequential(
                nn.Conv2D(self.inplanes, planes * block.expansion, 1,
                          stride=stride, bias_attr=False, **df),
                norm_layer(planes * block.expansion, **df))
        layers = [block(self.inplanes, planes, stride, downsample,
                        self.groups, self.base_width, 1, block_norm,
                        data_format=self._data_format,
                        fused_conv_bn=self._fused_conv_bn)]
        self.inplanes = planes * block.expansion
        for _ in range(1, blocks):
            layers.append(block(self.inplanes, planes,
                                groups=self.groups, base_width=self.base_width,
                                norm_layer=block_norm,
                                data_format=self._data_format,
                                fused_conv_bn=self._fused_conv_bn))
        return nn.Sequential(*layers)

    def forward(self, x):
        x = self.bn1(self.conv1(x))
        if not self._fused_bn:  # fused stem BN already applied the ReLU
            x = self.relu(x)
        x = self.maxpool(x)
        if self._recompute and self.training:
            from ..distributed.fleet.utils import recompute
            for stage in (self.layer1, self.layer2, self.layer3,
                          self.layer4):
                x = recompute(stage, x)
        else:
            x = self.layer1(x)
            x = self.layer2(x)
            x = self.layer3(x)
            x = self.layer4(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            from ..ops import flatten
            x = flatten(x, 1)
            x = self.fc(x)
        return x


def _resnet(block, depth, **kwargs):
    return ResNet(block, depth, **kwargs)


def resnet18(pretrained=False, **kwargs):
    return _resnet(BasicBlock, 18, **kwargs)


def resnet34(pretrained=False, **kwargs):
    return _resnet(BasicBlock, 34, **kwargs)


def resnet50(pretrained=False, **kwargs):
    return _resnet(BottleneckBlock, 50, **kwargs)


def resnet101(pretrained=False, **kwargs):
    return _resnet(BottleneckBlock, 101, **kwargs)


def resnet152(pretrained=False, **kwargs):
    return _resnet(BottleneckBlock, 152, **kwargs)


def resnext50_32x4d(pretrained=False, **kwargs):
    """ResNeXt-50 32x4d (reference vision/models/resnext.py): grouped
    bottlenecks — groups=32, width-per-group=4."""
    _no_pretrained(pretrained)
    return ResNet(BottleneckBlock, 50, width=4, groups=32, **kwargs)


def resnext101_32x4d(pretrained=False, **kwargs):
    _no_pretrained(pretrained)
    return ResNet(BottleneckBlock, 101, width=4, groups=32, **kwargs)


def resnext101_64x4d(pretrained=False, **kwargs):
    _no_pretrained(pretrained)
    return ResNet(BottleneckBlock, 101, width=4, groups=64, **kwargs)


def resnext152_64x4d(pretrained=False, **kwargs):
    _no_pretrained(pretrained)
    return ResNet(BottleneckBlock, 152, width=4, groups=64, **kwargs)


def wide_resnet50_2(pretrained=False, **kwargs):
    """Wide ResNet-50-2 (reference wide_resnet.py): 2x bottleneck width."""
    _no_pretrained(pretrained)
    return ResNet(BottleneckBlock, 50, width=128, **kwargs)


def wide_resnet101_2(pretrained=False, **kwargs):
    _no_pretrained(pretrained)
    return ResNet(BottleneckBlock, 101, width=128, **kwargs)


def _no_pretrained(pretrained):
    if pretrained:
        raise ValueError("pretrained weights are unavailable in this "
                         "environment (zero egress); train from scratch or "
                         "load a local state_dict")
