"""BERT/ERNIE-style bidirectional encoder.

Capability target: the reference's ERNIE-3.0-Base benchmark config
(`BASELINE.json`); built on the paddle-parity `nn.TransformerEncoder`.
"""
from __future__ import annotations

import dataclasses

from .. import nn
from ..nn import functional as F
from ..ops import arange, zeros_like


@dataclasses.dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    dropout: float = 0.1

    @staticmethod
    def base():
        return BertConfig()

    @staticmethod
    def large():
        return BertConfig(hidden_size=1024, num_layers=24, num_heads=16,
                          intermediate_size=4096)

    @staticmethod
    def tiny():
        return BertConfig(vocab_size=1000, hidden_size=64, num_layers=2,
                          num_heads=4, intermediate_size=128, dropout=0.0)


class BertEmbeddings(nn.Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.word_embeddings = nn.Embedding(cfg.vocab_size, cfg.hidden_size)
        self.position_embeddings = nn.Embedding(cfg.max_position_embeddings,
                                                cfg.hidden_size)
        self.token_type_embeddings = nn.Embedding(cfg.type_vocab_size,
                                                  cfg.hidden_size)
        self.layer_norm = nn.LayerNorm(cfg.hidden_size, epsilon=1e-12)
        self.dropout = nn.Dropout(cfg.dropout)

    def forward(self, input_ids, token_type_ids=None):
        L = input_ids.shape[1]
        pos = arange(0, L, dtype="int32")
        if token_type_ids is None:
            token_type_ids = zeros_like(input_ids)
        x = (self.word_embeddings(input_ids)
             + self.position_embeddings(pos)
             + self.token_type_embeddings(token_type_ids))
        return self.dropout(self.layer_norm(x))


class BertPooler(nn.Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.dense = nn.Linear(cfg.hidden_size, cfg.hidden_size)

    def forward(self, hidden):
        return F.tanh(self.dense(hidden[:, 0]))


class Bert(nn.Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.cfg = cfg
        self.embeddings = BertEmbeddings(cfg)
        enc_layer = nn.TransformerEncoderLayer(
            cfg.hidden_size, cfg.num_heads, cfg.intermediate_size,
            dropout=cfg.dropout, activation="gelu")
        self.encoder = nn.TransformerEncoder(enc_layer, cfg.num_layers)
        self.pooler = BertPooler(cfg)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        x = self.embeddings(input_ids, token_type_ids)
        if attention_mask is not None:
            # [B, L] 1/0 mask -> additive [B, 1, 1, L]
            from ..ops import reshape, cast
            m = (1.0 - cast(attention_mask, "float32")) * -1e4
            attention_mask = reshape(m, [m.shape[0], 1, 1, m.shape[1]])
        seq = self.encoder(x, attention_mask)
        pooled = self.pooler(seq)
        return seq, pooled


class BertForPretraining(nn.Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.bert = Bert(cfg)
        self.mlm_head = nn.Linear(cfg.hidden_size, cfg.vocab_size)
        self.nsp_head = nn.Linear(cfg.hidden_size, 2)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        seq, pooled = self.bert(input_ids, token_type_ids, attention_mask)
        return self.mlm_head(seq), self.nsp_head(pooled)
