"""ERNIE — the reference flagship NLP family (BASELINE.json north star:
ERNIE-3.0-Base step time).

Reference: ERNIE shares BERT's encoder architecture (the reference trains it
through the same fleet stack; see `incubate/nn` fused transformer bindings);
what differs is the pretraining objective (knowledge-masking: whole-word /
entity spans instead of wordpiece tokens). This module reuses the BERT
encoder (`models/bert.py`) and adds the ERNIE config surface + the
knowledge-masked MLM head.
"""
from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from .. import nn
from ..nn import functional as F
from .bert import Bert, BertConfig


@dataclass
class ErnieConfig(BertConfig):
    @staticmethod
    def base():
        # ERNIE-3.0-Base: 12L, 768H, 12 heads (BASELINE target config)
        return ErnieConfig(vocab_size=40000, hidden_size=768, num_layers=12,
                           num_heads=12, intermediate_size=3072)

    @staticmethod
    def tiny():
        return ErnieConfig(vocab_size=1024, hidden_size=64, num_layers=2,
                           num_heads=2, intermediate_size=128,
                           max_position_embeddings=128, dropout=0.0)


class Ernie(Bert):
    """Encoder = BERT; kept as its own class for config/namespace parity
    (`ErnieModel` in the reference ecosystem)."""


class ErnieForPretraining(nn.Layer):
    """MLM head over the ERNIE encoder (knowledge-masked spans are a DATA
    transformation — see `ernie_mask_tokens` — not an architecture change)."""

    def __init__(self, cfg: ErnieConfig):
        super().__init__()
        self.ernie = Ernie(cfg)
        self.mlm_transform = nn.Linear(cfg.hidden_size, cfg.hidden_size)
        self.mlm_norm = nn.LayerNorm(cfg.hidden_size)
        self.mlm_head = nn.Linear(cfg.hidden_size, cfg.vocab_size)

    def forward(self, input_ids, token_type_ids=None):
        seq_out, _pooled = self.ernie(input_ids,
                                      token_type_ids=token_type_ids)
        h = F.gelu(self.mlm_transform(seq_out))
        h = self.mlm_norm(h)
        return self.mlm_head(h)

    def loss(self, input_ids, labels, token_type_ids=None,
             ignore_index: int = -100):
        logits = self(input_ids, token_type_ids=token_type_ids)
        return F.cross_entropy(logits, labels, ignore_index=ignore_index)


def ernie_mask_tokens(input_ids: np.ndarray, spans, mask_token_id: int,
                      ignore_index: int = -100):
    """Knowledge masking (the ERNIE objective): mask whole SPANS (words/
    entities/phrases), not independent wordpieces.

    spans: per batch row, a list of (start, end) half-open intervals.
    Returns (masked_ids, labels) — labels are ignore_index outside spans.
    """
    ids = np.array(input_ids, copy=True)
    labels = np.full_like(ids, ignore_index)
    for b, row_spans in enumerate(spans):
        for s, e in row_spans:
            labels[b, s:e] = ids[b, s:e]
            ids[b, s:e] = mask_token_id
    return ids, labels
