"""paddle.regularizer parity (reference `python/paddle/regularizer.py`,
`fluid/regularizer.py`): L1Decay/L2Decay objects accepted by optimizers'
`weight_decay` and by per-param `ParamAttr(regularizer=...)`."""
from __future__ import annotations


class WeightDecayRegularizer:
    def __init__(self, coeff: float = 0.0):
        self._coeff = float(coeff)

    @property
    def coeff(self) -> float:
        return self._coeff

    def __repr__(self):
        return f"{type(self).__name__}(coeff={self._coeff})"


class L2Decay(WeightDecayRegularizer):
    """Adds coeff * param to the gradient (decoupled form in AdamW)."""


class L1Decay(WeightDecayRegularizer):
    """Adds coeff * sign(param) to the gradient."""


__all__ = ["L1Decay", "L2Decay", "WeightDecayRegularizer"]
