"""FleetController: close the observe -> diagnose -> act loop.

PRs 5-9 built every sensor (fleet digests, straggler detection,
``step_diagnosis``, per-host ``health_status``) and every actuator
(elastic relaunch, elastic re-sharding restore, coordinated rollback,
compile-cache prewarm) of an autonomous fleet — but a straggling or
diverging host still raised an event and waited for an operator. This
module is the brain that connects them, supervisor-side (rank 0's
``tools/elastic_run.py --controller``):

* **Straggler eviction** — a host the :class:`FleetAggregator` flags as a
  straggler for ``PADDLE_TPU_CONTROLLER_CONFIRM_WINDOWS`` CONSECUTIVE
  collect windows (debounce: one slow step or a transient excursion never
  evicts) is evicted: every supervisor relaunches its trainer at N-1 with
  re-densified ranks, resuming from the newest fleet-committed step via
  the PR-7 elastic re-sharding restore; the evicted host's supervisor
  HOLDS its trainer and beats a probation ``ctl/ready`` key instead.
  Hysteresis: a host that leaves the straggler set re-arms its streak
  from zero, so recover-then-relapse produces two confirmed decisions.
* **Readmission** — once the evicted host's probation heartbeat has been
  fresh for ``PADDLE_TPU_CONTROLLER_READMIT_SEC``, the controller scales
  the fleet back to N (the original rank assignment).
* **Fleet-wide rollback** — one host's digest reporting
  ``health_status == "diverged"`` (the PR-9 sentinel) escalates to a
  COORDINATED rollback: every supervisor hard-kills its trainer (no
  preemption save — the in-flight state is the diverged state) and
  relaunches with ``PADDLE_TPU_RESUME_VALID_ONLY=1``, so the fleet
  negotiates the newest fleet-committed step whose weights are finite
  and every host restores the SAME one. This closes the carried-over
  PR-9 gap: the health response used to be per-host only.
* **Compile-cache prewarm** — every relaunch command carries
  ``PADDLE_TPU_COMPILE_CACHE_DIR`` (when configured) so the new
  generation's compiles hit the PR-8 persistent cache, and the
  controller measures ``relaunch_to_first_step_s`` per decision from
  the first fresh digest after actuation.

Every decision — acted, failed, or ``dry_run`` — is ONE structured
``controller_decision`` event (policy, evidence, action, outcome) in the
unified event log, and lands in ``controller_decisions_total`` plus the
per-action ``controller_{evictions,rollbacks,readmissions}_total``
families. ``status()`` is served live at the ObservabilityServer's
``/controller`` endpoint.

Actuation transport is the same retry-wrapped TCPStore the runtime
already trusts: the controller appends commands to a store-backed ledger
(:class:`ControllerCommandBus`) that every host's
:class:`~paddle_tpu.distributed.fleet.elastic.ElasticSupervisor` polls.
An unreachable store or failed publish degrades to a logged
``controller_decision{outcome="failed"}`` + warning — never an exception
out of the supervisor.

The controller also runs the SERVING resilience policies over every
live in-process :class:`~paddle_tpu.inference.serving.ServingEngine`
(the same observe→diagnose→act loop, actuated locally instead of via
the command bus): shed/queue-cap on sustained TTFT / queue-wait SLO
breach, watchdog restart of a wedged decode loop (in-flight requests
requeue through the preemption path), and post-hot-swap canary/SLO
rollback with a max-rollbacks→halt breaker (inference/hotswap.py).
Multi-straggler handling: up to ``world_size - min_world`` hosts may be
held evicted simultaneously, each confirmed by its own debounced
streak, readmitted independently.

Knobs: ``PADDLE_TPU_CONTROLLER_CONFIRM_WINDOWS`` (default 3),
``PADDLE_TPU_CONTROLLER_READMIT_SEC`` (default 30),
``PADDLE_TPU_CONTROLLER_POLL_SEC`` (supervisor command-poll + aggregator
poll cadence, default 1.0), ``PADDLE_TPU_CONTROLLER_MIN_WORLD``
(default 1), ``PADDLE_TPU_CONTROLLER_ROLLBACK_COOLDOWN_SEC``
(default 60), ``PADDLE_TPU_CONTROLLER_SLO_WINDOWS`` (default 3),
``PADDLE_TPU_CONTROLLER_WEDGE_WINDOWS`` (default 2),
``PADDLE_TPU_CONTROLLER_RESTART_COOLDOWN_SEC`` (default 30),
``PADDLE_TPU_CONTROLLER_MAX_SWAP_ROLLBACKS`` (default 2),
``PADDLE_TPU_CONTROLLER_SWAP_OBSERVE_SEC`` (default 60),
``PADDLE_TPU_SERVING_SHED_QUEUE_CAP`` (default 8), plus the HA-election
pair ``PADDLE_TPU_CONTROLLER_LEASE_TTL`` / ``PADDLE_TPU_CONTROLLER_STANDBYS``
(fleet/leader.py: ``--controller`` on several hosts elects ONE leader;
standbys observe and take over within a lease TTL, inheriting the
replicated ``ctl/ledger`` decision state; every actuation carries the
leader's fencing term).
"""
from __future__ import annotations

import json
import os
import threading
import time
import warnings
from collections import deque
from typing import Callable, Dict, List, Optional

from ...profiler import events as _events_mod
from ...profiler import metrics as _metrics_mod
from .leader import (ControllerFencedError, LeaderLease, LEDGER_KEY,
                     note_term)

__all__ = ["FleetController", "ControllerCommandBus", "set_controller",
           "get_controller", "GEN_STRIDE", "controller_from_env"]

#: generation floor stride per controller command: supervisors applying
#: command K relaunch at generation K*GEN_STRIDE, so every host lands in
#: the SAME checkpoint-barrier namespace after a controller action even
#: when their local failure-restart counts had drifted apart (failure
#: restarts keep bumping by 1 within the stride)
GEN_STRIDE = 1000

CMD_SEQ_KEY = "ctl/seq"
CMD_KEY_FMT = "ctl/cmd/{id}"
READY_KEY_FMT = "ctl/ready/{host}"
JOB_DONE_KEY = "ctl/job_done"
PRESENT_KEY = "ctl/present"

_REG = _metrics_mod.default_registry()
_M_DECISIONS = _REG.counter(
    "controller_decisions_total",
    "fleet-controller decisions, by policy (straggler_evict / "
    "straggler_skip / readmit / health_rollback / serving_shed / "
    "serving_restart / serving_swap_rollback / serving_swap_halt) and "
    "outcome (applied / dry_run / failed / fenced)")
_M_EVICTIONS = _REG.counter(
    "controller_evictions_total",
    "straggler evictions the controller actually published, by host")
_M_ROLLBACKS = _REG.counter(
    "controller_rollbacks_total",
    "fleet-wide rollbacks the controller actually published, by the "
    "diverged host that triggered them")
_M_READMISSIONS = _REG.counter(
    "controller_readmissions_total",
    "evicted hosts scaled back into the fleet, by host")
_M_FIRST_STEP = _REG.gauge(
    "controller_relaunch_to_first_step_seconds",
    "seconds from a controller actuation to the first fresh post-relaunch "
    "digest step, by policy of the decision that caused the relaunch")


# shared knob parsing: garbled values warn once + fall back (envparse)
from ...utils.envparse import env_float as _env_float  # noqa: E402


class ControllerCommandBus:
    """Store-backed command ledger: the controller appends, every
    supervisor polls. One monotonic sequence (`ctl/seq`, the store's
    atomic counter) orders commands fleet-wide; commands are immutable
    JSON values under `ctl/cmd/<id>`.

    Also carries the eviction probation channel: an evicted host's
    supervisor beats `ctl/ready/<host>` while holding its trainer, and
    the `ctl/job_done` flag lets held supervisors exit cleanly when the
    fleet finishes without them."""

    #: seconds a claimed-but-unwritten ledger id may stall the ordered
    #: scan before readers give up on it: the publisher died (or its set
    #: failed) between the atomic id claim and the value write, and its
    #: decision was logged failed / retried under a NEW id — waiting any
    #: longer would wedge the whole command plane on a permanent hole
    HOLE_TIMEOUT_S = 15.0

    def __init__(self, store):
        self.store = store
        self._hole = None  # (id, first_seen_monotonic) of the stall point
        self._present_marked = False

    # -- publishing (controller side) ---------------------------------------
    def publish(self, cmd: dict) -> int:
        """Append one command; returns its ledger id. The id is claimed
        atomically BEFORE the value write, so a reader that sees seq=N
        but no value yet simply retries that id on its next poll."""
        if not self._present_marked:
            # first publish arms every supervisor's ledger poll; if this
            # set fails the command set below fails too, and the whole
            # publish is retried (with the marking) on the next tick
            self.mark_present()
        cid = int(self.store.add(CMD_SEQ_KEY, 1))
        rec = dict(cmd)
        rec["id"] = cid
        rec["ts"] = time.time()
        self.store.set(CMD_KEY_FMT.format(id=cid), json.dumps(rec))
        return cid

    def last_id(self) -> int:
        """Current ledger head (0 = nothing published)."""
        return int(self.store.add(CMD_SEQ_KEY, 0))

    # -- consuming (supervisor side) ----------------------------------------
    def poll(self, after_id: int) -> List[dict]:
        """Commands with id > after_id, in order. A claimed-but-unwritten
        (or unreadable) id stops the scan — order matters: applying
        command K+1 before K could readmit before the evict — bounded by
        ``HOLE_TIMEOUT_S``, after which the id is abandoned as a
        synthetic ``{"action": "skipped_hole"}`` record so consumers
        advance their cursor past it (the publisher died between the id
        claim and the value write; a permanent hole must not silently
        disable every supervisor's command plane forever)."""
        out: List[dict] = []
        head = self.last_id()
        for cid in range(int(after_id) + 1, head + 1):
            key = CMD_KEY_FMT.format(id=cid)
            rec = None
            try:
                if self.store.check(key):
                    rec = json.loads(self.store.get(key).decode())
            except Exception:
                rec = None
            if rec is None:
                now = time.monotonic()
                if self._hole is None or self._hole[0] != cid:
                    self._hole = (cid, now)
                    break  # give the writer time: retried next poll
                if now - self._hole[1] < self.HOLE_TIMEOUT_S:
                    break
                warnings.warn(
                    f"controller command ledger id {cid} was claimed but "
                    f"never written (publisher died mid-publish?); "
                    f"skipping it so later commands can apply")
                self._hole = None
                out.append({"action": "skipped_hole", "id": cid})
                continue
            if self._hole is not None and self._hole[0] == cid:
                self._hole = None
            out.append(rec)
        return out

    # -- probation / completion ---------------------------------------------
    def beat_ready(self, host: str):
        self.store.set(READY_KEY_FMT.format(host=host), repr(time.time()))

    def ready_age(self, host: str) -> Optional[float]:
        """Seconds since `host` last beat its probation key, or None.
        NOTE: compares the beater's wall clock to the caller's — the
        controller's readmit policy uses :meth:`ready_value` change
        observation instead, which is skew-immune."""
        key = READY_KEY_FMT.format(host=host)
        try:
            if not self.store.check(key):
                return None
            return max(0.0, time.time() - float(self.store.get(key).decode()))
        except Exception:
            return None

    def ready_value(self, host: str) -> Optional[str]:
        """Raw probation-beat value for `host`, or None. Freshness is
        judged by the value CHANGING between the controller's own polls
        — never by comparing the beater's wall clock to ours (cross-host
        clock skew would silently block readmission forever, or read a
        dead host's last beat as fresh)."""
        key = READY_KEY_FMT.format(host=host)
        try:
            if not self.store.check(key):
                return None
            return self.store.get(key).decode()
        except Exception:
            return None

    def mark_present(self):
        """Arm the fleet's command plane. Supervisors probe this ONE key
        at a relaxed cadence until it appears, and only then start the
        per-``cmd_poll`` ledger scan — a job with no controller anywhere
        must not pay N supervisors x 1 Hz of ledger RPCs against the
        shared rendezvous store the checkpoint barrier also uses."""
        self.store.set(PRESENT_KEY, "1")
        self._present_marked = True

    def present(self) -> bool:
        """Has any controller ever attached to this job's store?"""
        try:
            return bool(self.store.check(PRESENT_KEY))
        except Exception:
            return False  # store blip: probed again next tick

    def mark_job_done(self):
        self.store.set(JOB_DONE_KEY, "1")

    def reset_job_done(self):
        """Clear a PREVIOUS job's done-flag (controller startup): in a
        long-lived --host-store rendezvous store the stale flag would
        make the next job's first evicted host exit instead of holding
        for readmission. Best-effort — a missing key is fine."""
        try:
            self.store.delete_key(JOB_DONE_KEY)
        except Exception:
            pass

    def job_done(self) -> bool:
        try:
            return bool(self.store.check(JOB_DONE_KEY))
        except Exception:
            return False


class FleetController:
    """The decision loop. Drive it with :meth:`on_collect` after each
    :meth:`FleetAggregator.collect` (``FleetAggregator.start_polling``
    does this on a background thread); every call observes the newest
    digests and may publish at most one actuation.

    ``dry_run=True`` computes and event-logs every decision
    (``outcome="dry_run"``) without publishing any command — the
    operator's rehearsal mode.
    """

    #: bounded decision history served by status()/the /controller endpoint
    MAX_DECISIONS = 64

    def __init__(self, aggregator, bus: Optional[ControllerCommandBus],
                 world_size: int, *, dry_run: bool = False,
                 confirm_windows: Optional[int] = None,
                 readmit_after_s: Optional[float] = None,
                 rollback_cooldown_s: Optional[float] = None,
                 min_world: Optional[int] = None,
                 prewarm_cache_dir: Optional[str] = None,
                 slo_windows: Optional[int] = None,
                 wedge_windows: Optional[int] = None,
                 restart_cooldown_s: Optional[float] = None,
                 max_swap_rollbacks: Optional[int] = None,
                 swap_observe_s: Optional[float] = None,
                 shed_queue_cap: Optional[int] = None,
                 serving_provider: Optional[Callable] = None,
                 lease: Optional[LeaderLease] = None):
        self.aggregator = aggregator
        self.bus = bus
        #: HA mode (PR 20): with a LeaderLease attached this controller
        #: is one of possibly many — policies only run while it HOLDS
        #: the lease; standbys observe and wait. lease=None preserves
        #: the original single-controller behavior exactly (leader by
        #: definition, no store election traffic).
        self.lease = lease
        self.world_size = int(world_size)
        self.dry_run = bool(dry_run)
        if confirm_windows is None:
            confirm_windows = int(_env_float(
                "PADDLE_TPU_CONTROLLER_CONFIRM_WINDOWS", 3))
        self.confirm_windows = max(int(confirm_windows), 1)
        if readmit_after_s is None:
            readmit_after_s = _env_float(
                "PADDLE_TPU_CONTROLLER_READMIT_SEC", 30.0)
        self.readmit_after_s = float(readmit_after_s)
        if rollback_cooldown_s is None:
            rollback_cooldown_s = _env_float(
                "PADDLE_TPU_CONTROLLER_ROLLBACK_COOLDOWN_SEC", 60.0)
        self.rollback_cooldown_s = float(rollback_cooldown_s)
        if min_world is None:
            min_world = int(_env_float("PADDLE_TPU_CONTROLLER_MIN_WORLD", 1))
        self.min_world = max(int(min_world), 1)
        if prewarm_cache_dir is None:
            prewarm_cache_dir = os.environ.get(
                "PADDLE_TPU_COMPILE_CACHE_DIR") or None
        self.prewarm_cache_dir = prewarm_cache_dir
        # serving-policy knobs (the serving resilience plane)
        if slo_windows is None:
            slo_windows = int(_env_float(
                "PADDLE_TPU_CONTROLLER_SLO_WINDOWS", 3))
        self.slo_windows = max(int(slo_windows), 1)
        if wedge_windows is None:
            wedge_windows = int(_env_float(
                "PADDLE_TPU_CONTROLLER_WEDGE_WINDOWS", 2))
        self.wedge_windows = max(int(wedge_windows), 1)
        if restart_cooldown_s is None:
            restart_cooldown_s = _env_float(
                "PADDLE_TPU_CONTROLLER_RESTART_COOLDOWN_SEC", 30.0)
        self.restart_cooldown_s = float(restart_cooldown_s)
        if max_swap_rollbacks is None:
            max_swap_rollbacks = int(_env_float(
                "PADDLE_TPU_CONTROLLER_MAX_SWAP_ROLLBACKS", 2))
        self.max_swap_rollbacks = max(int(max_swap_rollbacks), 1)
        if swap_observe_s is None:
            swap_observe_s = _env_float(
                "PADDLE_TPU_CONTROLLER_SWAP_OBSERVE_SEC", 60.0)
        self.swap_observe_s = float(swap_observe_s)
        if shed_queue_cap is None:
            shed_queue_cap = int(_env_float(
                "PADDLE_TPU_SERVING_SHED_QUEUE_CAP", 8))
        self.shed_queue_cap = max(int(shed_queue_cap), 1)
        #: engine source override (tests); default: the in-process
        #: serving registry, looked up lazily and without importing it
        self.serving_provider = serving_provider

        self._lock = threading.Lock()
        #: serializes whole ticks so _act may release _lock around the
        #: store publish (status()/the /controller endpoint must not
        #: block up to the store timeout behind a slow actuation) without
        #: a concurrent tick interleaving into the window
        self._tick_lock = threading.Lock()
        self._decision_seq = 0
        self.decisions: "deque[dict]" = deque(maxlen=self.MAX_DECISIONS)
        #: host -> consecutive straggling collect windows (the debounce)
        self._streaks: Dict[str, int] = {}
        #: host -> (ts, step) of the digest the last counted window saw:
        #: a streak only advances on FRESH evidence (see _straggler_policy)
        self._streak_obs: Dict[str, tuple] = {}
        #: hosts already decided this excursion (hysteresis: no re-fire
        #: until the host leaves the straggler set)
        self._suppressed: set = set()
        #: host -> rank assignment of the FULL fleet (learned from digests)
        self._assignment: Dict[str, int] = {}
        #: evicted hosts (empty = fleet at full strength):
        #: host -> {"host", "ts", "decision"}. Up to
        #: world_size - min_world hosts may be held at once (the
        #: N-quorum multi-straggler bound); each eviction still needs
        #: its own confirmed streak
        self._evicted: Dict[str, dict] = {}
        #: host -> (last probation-beat value, local monotonic ts when it
        #: last CHANGED) — freshness on OUR clock, immune to cross-host
        #: wall-clock skew
        self._ready_obs: Dict[str, tuple] = {}
        self._rollback_until = 0.0  # cooldown deadline
        self._rollback_suppressed: set = set()  # hosts already rolled back
        # serving-policy state, keyed by engine/model name
        self._srv_slo_streaks: Dict[str, int] = {}
        self._srv_recover_streaks: Dict[str, int] = {}
        self._srv_shed: set = set()
        self._srv_wedge_streaks: Dict[str, int] = {}
        self._srv_restart_after: Dict[str, float] = {}
        self._srv_rollbacks: Dict[str, int] = {}
        #: set when a decision changed replicable ledger state; the tick
        #: tail writes ONE ctl/ledger blob per dirty tick (not per
        #: decision) so a standby inherits cooldowns/probation/rollback
        #: counts on takeover
        self._ledger_dirty = False

    # -- observation --------------------------------------------------------
    def on_collect(self, digests: Dict[int, dict]):
        """One controller tick over the newest digests. Never raises —
        a controller bug or an unreachable store must not take down the
        supervisor's poll loop."""
        try:
            self._tick(digests)
        except Exception as e:
            warnings.warn(f"fleet controller tick failed: "
                          f"{type(e).__name__}: {e}")

    def is_leader(self) -> bool:
        """Without a lease this controller IS the control plane (the
        pre-HA single-controller deployment); with one, only the current
        lease holder may decide."""
        return self.lease is None or self.lease.is_leader

    def _tick(self, digests: Dict[int, dict]):
        with self._tick_lock:
            # election step first, OUTSIDE the status lock (store RPCs);
            # _tick_lock keeps concurrent ticks out
            if self.lease is not None and self.lease.tick() == "acquired":
                self._load_ledger()
            blob = None
            with self._lock:
                self._learn_assignment(digests)
                self._observe_first_steps(digests)
                if self.is_leader():
                    self._straggler_policy()
                    self._health_policy(digests)
                    self._readmit_policy()
                    self._serving_policy()
                if self._ledger_dirty and self.lease is not None \
                        and self.lease.is_leader:
                    blob = json.dumps(_json_safe(self._ledger_snapshot()))
                    self._ledger_dirty = False
            if blob is not None:
                try:
                    self.lease.store.set(LEDGER_KEY, blob)
                except Exception as e:
                    warnings.warn(f"controller ledger replication failed "
                                  f"({type(e).__name__}: {e}); retrying "
                                  f"next tick")
                    with self._lock:
                        self._ledger_dirty = True

    # -- ledger replication (HA takeover inheritance) -----------------------
    def _ledger_snapshot(self) -> dict:
        """Everything a NEW leader must inherit to not repeat a standing
        decision: eviction/probation state, hysteresis suppressions,
        rollback cooldown + counts, shed set, restart cooldowns, the
        learned rank assignment, and the last decision per policy.
        Deliberately NOT replicated: `_ready_obs` / `_streaks` — those
        are freshness observations on THIS process's monotonic clock and
        must be re-observed by the inheritor. Called under _lock."""
        last: Dict[str, dict] = {}
        for r in self.decisions:
            last[r["policy"]] = dict(r)
        return {
            "term": self.lease.term if self.lease is not None else 0,
            "decision_seq": self._decision_seq,
            "evicted": {h: dict(r) for h, r in self._evicted.items()},
            "suppressed": sorted(self._suppressed),
            "rollback_suppressed": sorted(self._rollback_suppressed),
            # wall-clock deadlines survive replication (cross-host skew
            # only shifts a cooldown by the skew, never re-arms it)
            "rollback_until": self._rollback_until,
            "srv_rollbacks": dict(self._srv_rollbacks),
            "srv_shed": sorted(self._srv_shed),
            "srv_restart_after": dict(self._srv_restart_after),
            "assignment": dict(self._assignment),
            "last_decision": last,
        }

    def _load_ledger(self):
        """Takeover: merge the deposed leader's replicated ledger into
        our own state — union/max merges, so a standby that already
        observed something locally never regresses. Without this, the
        new leader would re-evict a host mid-probation (its stale digest
        still reads slow) or re-roll-back an already-restored swap."""
        if self.lease is None:
            return
        try:
            store = self.lease.store
            if not store.check(LEDGER_KEY):
                return
            blob = json.loads(store.get(LEDGER_KEY).decode())
        except Exception as e:
            warnings.warn(f"controller ledger load failed "
                          f"({type(e).__name__}: {e}); starting from "
                          f"local state only")
            return
        with self._lock:
            self._decision_seq = max(self._decision_seq,
                                     int(blob.get("decision_seq", 0)))
            for h, r in (blob.get("evicted") or {}).items():
                self._evicted.setdefault(h, dict(r))
            self._suppressed.update(blob.get("suppressed") or ())
            self._rollback_suppressed.update(
                blob.get("rollback_suppressed") or ())
            self._rollback_until = max(
                self._rollback_until,
                float(blob.get("rollback_until", 0.0)))
            for k, v in (blob.get("srv_rollbacks") or {}).items():
                self._srv_rollbacks[k] = max(
                    self._srv_rollbacks.get(k, 0), int(v))
            self._srv_shed.update(blob.get("srv_shed") or ())
            for k, v in (blob.get("srv_restart_after") or {}).items():
                self._srv_restart_after[k] = max(
                    self._srv_restart_after.get(k, 0.0), float(v))
            for h, r in (blob.get("assignment") or {}).items():
                self._assignment.setdefault(h, int(r))
            # seed the decision history with the inherited last decision
            # per policy: status()/obs_tail show continuity across the
            # takeover, and _observe_first_steps keeps watching an
            # inherited in-flight relaunch for its first fresh digest
            have = {r["id"] for r in self.decisions}
            for rec in (blob.get("last_decision") or {}).values():
                if rec.get("id") not in have:
                    rec = dict(rec)
                    rec["inherited"] = True
                    self.decisions.append(rec)

    def _learn_assignment(self, digests: Dict[int, dict]):
        """host -> rank map of the FULL fleet, learned from the digests
        themselves (member ids are stable across re-ranking; an evicted
        host keeps its original rank reserved for readmission)."""
        for r, d in digests.items():
            host = d.get("host")
            if not host:
                continue
            if len(self._assignment) < self.world_size \
                    and host not in self._assignment:
                self._assignment[host] = int(d.get("rank", r))

    # -- policies -----------------------------------------------------------
    def _straggler_policy(self):
        straggling = set(self.aggregator.straggling())
        for host in list(self._streaks):
            if host not in straggling:
                # hysteresis re-arm: the host recovered (or its digest
                # went stale out of the vote); a relapse starts a fresh
                # streak and may produce a fresh decision
                self._streaks.pop(host, None)
                self._streak_obs.pop(host, None)
                self._suppressed.discard(host)
        evictable: List[str] = []
        for host in sorted(straggling):
            if host in self._evicted:
                continue  # its stale digest still reads slow while held
            # the debounce counts CONSECUTIVE collect windows of
            # evidence: the streak only advances when the host's digest
            # actually changed since the last counted window — the
            # aggregator re-flagging the same cached digest on every
            # poll tick must not let one slow sample confirm an
            # eviction in confirm_windows ticks. (The decision checks
            # below still run on stale evidence: an already-confirmed
            # streak blocked by e.g. a partial assignment must actuate
            # once the blocker clears.)
            d = self._host_digest(host) or {}
            obs = (d.get("ts"), d.get("step"))
            if self._streak_obs.get(host) != obs:
                self._streak_obs[host] = obs
                self._streaks[host] = self._streaks.get(host, 0) + 1
            if host in self._suppressed:
                continue
            if self._streaks[host] < self.confirm_windows:
                continue
            # diagnosis-aware evidence (ROADMAP item-3 follow-up): a
            # straggler whose own step_diagnosis names data_wait as the
            # dominant wall-time term is slow because the INPUT PIPELINE
            # is slow — evicting the host just moves the same stall to
            # rank N-1's shards. Decide a skip naming the real culprit
            # instead of an eviction; hysteresis applies like any other
            # decision (a relapse after recovery re-decides, and a later
            # excursion whose dominant term is the host itself evicts).
            # This check sits ABOVE the eviction-feasibility guards: a
            # skip publishes nothing, so the diagnosis must surface even
            # when eviction is impossible (another host held, min_world
            # floor, partial rank map). `d` is the digest the streak
            # check read — one observation backs both the confirmation
            # and the evidence.
            if d.get("diag_dominant") == "data_wait":
                self._decide_skip(host, d)
                continue
            # multi-straggler: up to world_size - min_world hosts may be
            # confirmed in the SAME tick — they batch into ONE decision
            # below (one command, one relaunch) instead of a sequence of
            # single-host evictions whose relaunch specs supersede each
            # other mid-apply; the quorum floor caps the batch
            if self.current_world() - (len(evictable) + 1) < self.min_world:
                continue  # never shrink below the floor
            if len(self._assignment) < self.world_size:
                # a survivor we have never seen a digest from would be
                # missing from the relaunch rank map and come back with
                # an out-of-range rank — no actuation until the full
                # fleet has reported once (a host with its reporter
                # disabled keeps the controller in observe-only mode)
                continue
            evictable.append(host)
        if evictable:
            self._decide_evict(evictable)

    def _decide_evict(self, hosts):
        """ONE debounced eviction decision covering every host in
        `hosts` (each arrived here on its own confirmed streak): a
        single command carries the full list, the post-eviction world
        size, and a rank map excluding every held + evicted host — the
        supervisors apply one relaunch, not a churn of N overlapping
        ones. `cmd["host"]` stays the first host for ledger/back-compat
        consumers; `cmd["hosts"]` is the authoritative list."""
        if isinstance(hosts, str):
            hosts = [hosts]
        per_host = {}
        for host in hosts:
            hv = {"windows": self._streaks.get(host, 0)}
            d = self._host_digest(host)
            if d:
                hv["p50_s"] = d.get("wall_p50_s")
                hv["step"] = d.get("step")
                hv["diag_dominant"] = d.get("diag_dominant")
            per_host[host] = hv
        evidence = {"hosts": per_host,
                    "windows": per_host[hosts[0]]["windows"],
                    "straggling": sorted(self.aggregator.straggling()),
                    "factor": getattr(self.aggregator, "straggler_factor",
                                      None)}
        if len(hosts) == 1:
            evidence.update(per_host[hosts[0]])
        new_np = self.current_world() - len(hosts)
        ranks = self._dense_ranks(exclude=set(self._evicted) | set(hosts))
        cmd = {"action": "evict", "host": hosts[0], "hosts": list(hosts),
               "np": new_np,
               "ranks": ranks, "env": self._relaunch_env(extra={
                   # the survivors may shrink to world 1, where the
                   # reporter would normally disarm — force it on so the
                   # controller keeps observing the N-1 fleet
                   "PADDLE_TPU_FLEET_REPORTER": "1"})}
        rec = self._act("straggler_evict", evidence, cmd)
        if rec["outcome"] != "failed":
            # a FAILED publish (store blip) is retried on the next tick;
            # suppressing it would mean one blip and a persistent
            # straggler is never evicted until it transiently recovers
            self._suppressed.update(hosts)
        if rec["outcome"] == "applied":
            for host in hosts:
                self._evicted[host] = {"host": host, "ts": time.time(),
                                       "decision": rec["id"]}
                if _metrics_mod.enabled():
                    _M_EVICTIONS.inc(host=host)

    def _decide_skip(self, host: str, d: dict):
        """A confirmed straggler whose dominant diagnosed term (in its
        digest `d`) is the input pipeline: record the decision NOT to
        evict (action="skip") with the evidence naming the culprit.
        Publishes nothing — doing nothing IS the applied action — and
        suppresses like an eviction so the standing excursion logs once,
        re-arming on recovery."""
        evidence = {"windows": self._streaks.get(host, 0),
                    "diag_dominant": d.get("diag_dominant"),
                    "culprit": "input_pipeline",
                    "p50_s": d.get("wall_p50_s"), "step": d.get("step")}
        self._act("straggler_skip", evidence,
                  {"action": "skip", "host": host}, publish=False)
        self._suppressed.add(host)

    def _health_policy(self, digests: Dict[int, dict]):
        now = time.time()
        # STALE digests don't vote here either (mirrors the aggregator's
        # straggler filter): a dead host's — or, with a long-lived
        # host-store, a previous incarnation's — frozen 'diverged' digest
        # must not hard-kill a healthy fleet
        stale = float(getattr(self.aggregator, "stale_sec", 0.0) or 0.0)
        bad = sorted(
            d.get("host", f"rank-{r}") for r, d in digests.items()
            if d.get("health_status") == "diverged"
            and (stale <= 0 or now - d.get("ts", now) <= stale))
        for host in list(self._rollback_suppressed):
            if host not in bad:
                self._rollback_suppressed.discard(host)
        bad = [h for h in bad if h not in self._rollback_suppressed]
        if not bad or now < self._rollback_until:
            return
        if len(self._assignment) < self.world_size:
            # same guard as the straggler policy: a re-densified rank map
            # built from a partial assignment would hand two hosts the
            # same rank (hosts absent from the map keep their old ranks)
            # and wedge every relaunched trainer in rendezvous
            return
        host = bad[0]  # first (alphabetically stable) diverged host
        evidence = {"diverged": bad,
                    "step": (self._host_digest(host) or {}).get("step")}
        # a rollback during evictions covers the shrunken fleet: every
        # held host stays out of the rank map (its supervisor consumes
        # the command without acting) or a survivor would land on a rank
        # >= np and wedge every relaunch
        cmd = {"action": "rollback", "host": host,
               "np": self.current_world(),
               "ranks": self._dense_ranks(exclude=set(self._evicted)),
               # every host resumes the newest fleet-committed step whose
               # weights are FINITE — the same one, by negotiation. The
               # valid-only knob is ONE-SHOT (env_once): it must not leak
               # into ordinary failure restarts for the rest of the job
               "env": self._relaunch_env(),
               "env_once": {"PADDLE_TPU_RESUME_VALID_ONLY": "1"}}
        rec = self._act("health_rollback", evidence, cmd)
        if rec["outcome"] == "failed":
            return  # not suppressed: retried on the next tick
        # suppress while the same host keeps reporting diverged (its stale
        # pre-relaunch digest) and for the cooldown after an actuation
        self._rollback_suppressed.update(bad)
        if rec["outcome"] == "applied":
            self._rollback_until = now + self.rollback_cooldown_s
            if _metrics_mod.enabled():
                _M_ROLLBACKS.inc(host=host)

    def _readmit_policy(self):
        if not self._evicted or self.bus is None:
            return
        if len(self._assignment) < self.world_size:
            return  # cannot rebuild the full-N rank map yet
        # observe EVERY held host's probation beat on EVERY tick,
        # including during the hold window: freshness tracking must span
        # the whole probation, or a supervisor that beat once and died
        # mid-hold would read age=0 at the first post-window look and a
        # dead host would be readmitted into the rank map (trainers then
        # wedge in rendezvous on the missing rank with no policy able to
        # recover)
        now_local = time.monotonic()
        for host in sorted(self._evicted):
            # the probation read is a store RPC (up to the client
            # timeout): run it OUTSIDE the status lock like _act's
            # publish, so status()/the /controller endpoint never stalls
            # behind a slow store — _tick_lock keeps a concurrent tick
            # out of the window
            self._lock.release()
            try:
                val = self.bus.ready_value(host)
            finally:
                self._lock.acquire()
            if val is not None:
                prev = self._ready_obs.get(host)
                if prev is None or prev[0] != val:
                    self._ready_obs[host] = (val, now_local)
        for host in sorted(self._evicted):
            held_for = time.time() - self._evicted[host]["ts"]
            if held_for < self.readmit_after_s:
                continue
            # the probation heartbeat must be FRESH: freshness = the beat
            # VALUE changed recently as observed on OUR clock — comparing
            # the beater's embedded wall-clock timestamp to ours would
            # let modest cross-host skew block readmission forever (or
            # read a dead host's last beat as fresh)
            obs = self._ready_obs.get(host)
            if obs is None:
                continue
            age = now_local - obs[1]
            if age > 3 * self._poll_interval() + 5.0:
                continue
            evidence = {"held_s": round(held_for, 3),
                        "ready_age_s": round(age, 3),
                        "evict_decision": self._evicted[host]["decision"]}
            # the readmitted host rejoins whatever strength the fleet is
            # at: full N (original assignment) once it is the last one
            # held, a partial re-densified map while others stay out
            remaining = set(self._evicted) - {host}
            ranks = (self._dense_ranks(exclude=remaining) if remaining
                     else dict(self._assignment))
            cmd = {"action": "readmit", "host": host,
                   "np": self.world_size - len(remaining),
                   "ranks": ranks,
                   "env": self._relaunch_env(extra={
                       "PADDLE_TPU_FLEET_REPORTER": "1"})}
            rec = self._act("straggler_readmit", evidence, cmd)
            if rec["outcome"] == "applied":
                self._evicted.pop(host, None)
                self._ready_obs.pop(host, None)
                if _metrics_mod.enabled():
                    _M_READMISSIONS.inc(host=host)
            return  # at most one readmission per tick (ledger ordering)

    # -- serving policies (the resilience plane over live engines) ----------
    def _serving_engines(self) -> list:
        """The engines this controller watches: an injected provider
        (tests / remote deployments) or the in-process serving registry,
        looked up WITHOUT importing the serving stack — a trainer-only
        controller must not pull jit/inference modules in."""
        if self.serving_provider is not None:
            return list(self.serving_provider())
        import sys
        mod = sys.modules.get("paddle_tpu.inference.serving")
        if mod is None:
            return []
        try:
            return [e for e in mod.live_engines()]
        except Exception:
            return []

    def _serving_policy(self):
        for eng in self._serving_engines():
            try:
                self._serving_wedge_policy(eng)
                self._serving_slo_policy(eng)
                self._serving_swap_policy(eng)
            except Exception as e:  # noqa: BLE001 — one engine's failure
                warnings.warn(                # must not mute the others
                    f"serving policy tick failed for engine "
                    f"{getattr(eng, 'name', '?')!r}: "
                    f"{type(e).__name__}: {e}")

    def _serving_wedge_policy(self, eng):
        """Liveness watchdog: an engine holding work without completing
        a decode iteration for the stall window, confirmed over
        `wedge_windows` consecutive ticks, is restarted — in-flight
        requests requeue through the preemption path (trace ids
        preserved), then the decode loop relaunches. Cooldown stops a
        permanently-sick engine from restart-thrashing."""
        name = eng.name
        if not eng.wedged():
            self._srv_wedge_streaks.pop(name, None)
            return
        n = self._srv_wedge_streaks.get(name, 0) + 1
        self._srv_wedge_streaks[name] = n
        if n < self.wedge_windows:
            return
        now = time.time()
        if now < self._srv_restart_after.get(name, 0.0):
            return
        evidence = {"windows": n,
                    "stall_s": round(eng.last_progress_age(), 3),
                    "queue_depth": eng.queue_depth()}
        rec = self._act("serving_restart", evidence,
                        {"action": "restart", "host": name, "model": name},
                        local_fn=lambda: eng.restart(reason="wedged",
                                                     term=self._term()))
        if rec["outcome"] != "failed":
            self._srv_restart_after[name] = now + self.restart_cooldown_s
            self._srv_wedge_streaks.pop(name, None)

    def _serving_slo_policy(self, eng):
        """Shed on sustained admission-side SLO breach (ttft /
        queue_wait — the signals a queue cap can actually relieve),
        confirmed over `slo_windows` ticks like the straggler debounce;
        un-shed after the same streak of clean windows."""
        name = eng.name
        try:
            breached = sorted(eng.slo.breached())
        except Exception:
            breached = []
        relevant = [s for s in breached if s in ("ttft", "queue_wait")]
        if relevant:
            self._srv_recover_streaks.pop(name, None)
            n = self._srv_slo_streaks.get(name, 0) + 1
            self._srv_slo_streaks[name] = n
            if name in self._srv_shed or n < self.slo_windows:
                return
            cap = self.shed_queue_cap
            rec = self._act(
                "serving_shed",
                {"windows": n, "breached": relevant,
                 "queue_depth": eng.queue_depth()},
                {"action": "shed", "host": name, "model": name,
                 "queue_cap": cap},
                local_fn=lambda: eng.set_queue_limit(cap,
                                                     term=self._term()))
            if rec["outcome"] != "failed":
                self._srv_shed.add(name)
                self._srv_slo_streaks.pop(name, None)
        else:
            self._srv_slo_streaks.pop(name, None)
            if name not in self._srv_shed:
                return
            n = self._srv_recover_streaks.get(name, 0) + 1
            self._srv_recover_streaks[name] = n
            if n < self.slo_windows:
                return
            rec = self._act(
                "serving_shed", {"recovered_windows": n},
                {"action": "unshed", "host": name, "model": name},
                local_fn=lambda: eng.set_queue_limit(None,
                                                     term=self._term()))
            if rec["outcome"] != "failed":
                self._srv_shed.discard(name)
                self._srv_recover_streaks.pop(name, None)

    def _serving_swap_policy(self, eng):
        """Post-swap watch: a hot-swapped checkpoint whose post-swap
        canary regresses (or whose engine breaches SLO inside the
        observe window) rolls back to the prior step; a swap that stays
        healthy through the window is vetted. More than
        `max_swap_rollbacks` rollbacks trips the breaker: one final
        rollback, then the hot-swap manager halts entirely."""
        mgr = getattr(eng, "hotswap", None)
        if mgr is None or mgr.vetted or mgr.halted:
            return
        if mgr.swapped_ts is None:
            return  # staged but not yet applied: nothing to judge
        name = eng.name
        age = time.time() - mgr.swapped_ts
        reason, regress = None, None
        try:
            breached = sorted(eng.slo.breached())
        except Exception:
            breached = []
        if breached:
            reason = "slo:" + ",".join(breached)
        else:
            try:
                regress = mgr.post_swap_regressed()
            except Exception:
                regress = None
            if regress and regress.get("regressed"):
                reason = "canary"
        if reason is None:
            if age > self.swap_observe_s:
                mgr.vetted = True  # healthy through the whole window
            return
        n = self._srv_rollbacks.get(name, 0) + 1
        self._srv_rollbacks[name] = n
        evidence = {"reason": reason, "post_swap_age_s": round(age, 3),
                    "step": mgr.current_step, "rollbacks": n}
        if regress:
            evidence["live_ppl"] = round(regress["live_ppl"], 4)
            evidence["baseline_ppl"] = round(regress["baseline_ppl"], 4)
        if n > self.max_swap_rollbacks:
            def roll_and_halt():
                mgr.rollback(reason=reason)
                mgr.halt(reason="max_rollbacks")
            self._act("serving_swap_halt", evidence,
                      {"action": "swap_halt", "host": name, "model": name},
                      local_fn=roll_and_halt)
            return
        self._act("serving_swap_rollback", evidence,
                  {"action": "swap_rollback", "host": name, "model": name,
                   "step": mgr.current_step},
                  local_fn=lambda: mgr.rollback(reason=reason))

    # -- decision plumbing --------------------------------------------------
    def _act(self, policy: str, evidence: dict, cmd: dict,
             publish: bool = True, local_fn=None) -> dict:
        """Record + event-log + (unless dry-run) actuate one decision.
        Three actuation shapes: publish to the command bus (the trainer
        fleet), call `local_fn` directly (serving policies actuate the
        in-process engine), or `publish=False` (skip: doing nothing IS
        the applied action). Failures degrade to outcome="failed" with a
        warning — never an exception out of the tick.

        HA: every command carries the deciding policy and (with a lease
        attached) the leader's fencing term, so consumers — elastic
        supervisors and the in-process serving gate — can reject an
        actuation a DEPOSED leader left in flight (outcome="fenced")."""
        self._decision_seq += 1
        self._ledger_dirty = True
        cmd = dict(cmd)
        cmd.setdefault("policy", policy)
        if self.lease is not None:
            cmd["term"] = int(self.lease.term)
        rec = {"id": self._decision_seq, "ts": time.time(),
               "policy": policy, "evidence": evidence,
               "action": {k: v for k, v in cmd.items()
                          if k not in ("env", "env_once")},
               "outcome": "dry_run", "cmd_id": None,
               "relaunch_to_first_step_s": None}
        if not self.dry_run:
            if local_fn is not None:
                # local actuation may be slow (an engine restart joins
                # the decode loop): release the status lock around it,
                # same as the store publish below
                self._lock.release()
                try:
                    local_fn()
                    rec["outcome"] = "applied"
                except ControllerFencedError as e:
                    # the in-process gate saw a newer term than ours: we
                    # were deposed between deciding and actuating — the
                    # new leader owns this incident now
                    rec["outcome"] = "fenced"
                    rec["error"] = str(e)
                except Exception as e:
                    rec["outcome"] = "failed"
                    rec["error"] = f"{type(e).__name__}: {e}"
                    warnings.warn(
                        f"fleet controller could not actuate "
                        f"{cmd.get('action')} ({rec['error']}); decision "
                        f"logged as failed")
                finally:
                    self._lock.acquire()
            elif not publish:
                rec["outcome"] = "applied"
            elif self.bus is None:
                rec["outcome"] = "failed"
                rec["error"] = "no command bus attached"
            else:
                # the publish is a store RPC (up to the client timeout):
                # run it OUTSIDE the status lock so /controller and
                # status() readers never stall behind a slow store —
                # _tick_lock keeps a concurrent tick out of the window
                self._lock.release()
                try:
                    rec["cmd_id"] = self.bus.publish(cmd)
                    rec["outcome"] = "applied"
                except Exception as e:
                    rec["outcome"] = "failed"
                    rec["error"] = f"{type(e).__name__}: {e}"
                    warnings.warn(
                        f"fleet controller could not publish "
                        f"{cmd.get('action')} ({rec['error']}); decision "
                        f"logged as failed")
                finally:
                    self._lock.acquire()
        self.decisions.append(rec)
        if _metrics_mod.enabled():
            _M_DECISIONS.inc(policy=policy, outcome=rec["outcome"])
        _events_mod.emit(
            "controller_decision",
            severity="warn" if rec["outcome"] != "failed" else "error",
            policy=policy, action=cmd.get("action"),
            target=cmd.get("host"), outcome=rec["outcome"],
            decision=rec["id"], np=cmd.get("np"),
            evidence=_json_safe(evidence),
            dry_run=self.dry_run)
        return rec

    def _observe_first_steps(self, digests: Dict[int, dict]):
        """Close the loop on applied decisions: the first digest whose
        publish timestamp is newer than the actuation is the relaunched
        fleet's first observed step — report relaunch_to_first_step_s
        per decision (the relaunch-cost number the compile-cache prewarm
        exists to shrink)."""
        pending = [r for r in self.decisions
                   if r["outcome"] == "applied"
                   and r["cmd_id"] is not None  # skip decisions actuate
                                                # nothing to observe
                   and r["relaunch_to_first_step_s"] is None]
        if not pending:
            return
        for rec in pending:
            # a digest is post-relaunch only when its GENERATION reached
            # the command's floor (cmd_id * GEN_STRIDE, what the applying
            # supervisors relaunch at) — a timestamp alone cannot tell
            # the new fleet's first step from a pre-relaunch straggler
            # that published during command-poll + SIGTERM-drain latency.
            # Digests without a gen field (older reporters) fall back to
            # a one-poll-interval timestamp floor. The reported duration
            # is decision -> first OBSERVATION, measured entirely on the
            # controller's clock (remote digest timestamps carry the
            # reporter's wall-clock skew; over-reports by at most one
            # digest-publish + one poll interval).
            gen_floor = (rec.get("cmd_id") or 0) * GEN_STRIDE
            ts_floor = rec["ts"] + self._poll_interval()
            hit = False
            for d in digests.values():
                if "gen" in d:
                    if int(d.get("gen") or 0) >= gen_floor:
                        hit = True
                        break
                else:
                    ts = d.get("ts")
                    if ts is not None and ts > ts_floor:
                        hit = True
                        break
            if not hit:
                continue
            dt = round(max(0.0, time.time() - rec["ts"]), 3)
            rec["relaunch_to_first_step_s"] = dt
            if _metrics_mod.enabled():
                _M_FIRST_STEP.set(dt, policy=rec["policy"])
            _events_mod.emit(
                "controller_decision", severity="info",
                policy=rec["policy"], action="relaunch_observed",
                outcome=rec["outcome"], decision=rec["id"],
                relaunch_to_first_step_s=dt, dry_run=self.dry_run)

    # -- helpers ------------------------------------------------------------
    def _term(self) -> Optional[int]:
        """Fencing term for locally-actuated commands (None pre-HA)."""
        return int(self.lease.term) if self.lease is not None else None

    def current_world(self) -> int:
        return self.world_size - len(self._evicted)

    def _poll_interval(self) -> float:
        return _env_float("PADDLE_TPU_CONTROLLER_POLL_SEC", 1.0)

    def _host_digest(self, host: str) -> Optional[dict]:
        for d in getattr(self.aggregator, "last", {}).values():
            if d.get("host") == host:
                return d
        return None

    def _dense_ranks(self, exclude=None) -> Dict[str, int]:
        """New rank assignment: surviving hosts ordered by their ORIGINAL
        rank, re-densified to 0..n-1 (the deterministic rule every
        supervisor can verify against its own member id). `exclude` is a
        host name or a set of them."""
        if exclude is None:
            exclude = set()
        elif isinstance(exclude, str):
            exclude = {exclude}
        survivors = sorted(
            (r, h) for h, r in self._assignment.items() if h not in exclude)
        return {h: i for i, (_r, h) in enumerate(survivors)}

    def _relaunch_env(self, extra: Optional[dict] = None) -> Dict[str, str]:
        env: Dict[str, str] = {}
        if self.prewarm_cache_dir:
            # prewarm: the relaunched generation compiles against the
            # persistent cache, so relaunch_to_first_step stays cheap
            env["PADDLE_TPU_COMPILE_CACHE_DIR"] = self.prewarm_cache_dir
        env.update(extra or {})
        return env

    def status(self) -> dict:
        """The /controller endpoint payload."""
        # the lease status reads the store (RPCs): take it OUTSIDE the
        # status lock, same rule as _act's publish
        lease_st = self.lease.status() if self.lease is not None else None
        with self._lock:
            return _json_safe({
                "dry_run": self.dry_run,
                "leader": lease_st,
                "is_leader": self.is_leader(),
                "world_size": self.world_size,
                "current_world": self.current_world(),
                "confirm_windows": self.confirm_windows,
                "readmit_after_s": self.readmit_after_s,
                "min_world": self.min_world,
                "prewarm_cache_dir": self.prewarm_cache_dir,
                "streaks": dict(self._streaks),
                "evicted": ({h: dict(r) for h, r in self._evicted.items()}
                            if self._evicted else None),
                "assignment": dict(self._assignment),
                "serving": {
                    "shed": sorted(self._srv_shed),
                    "slo_streaks": dict(self._srv_slo_streaks),
                    "wedge_streaks": dict(self._srv_wedge_streaks),
                    "swap_rollbacks": dict(self._srv_rollbacks),
                },
                "decisions": [dict(r) for r in self.decisions],
            })


def _json_safe(obj):
    """Evidence/status must serialize: anything exotic degrades to str."""
    try:
        json.dumps(obj)
        return obj
    except (TypeError, ValueError):
        if isinstance(obj, dict):
            return {str(k): _json_safe(v) for k, v in obj.items()}
        if isinstance(obj, (list, tuple, set)):
            return [_json_safe(v) for v in obj]
        return str(obj)


# -- process-wide registration (the /controller endpoint reads this) --------
_controller: Optional[FleetController] = None


def set_controller(controller: Optional[FleetController]):
    global _controller
    _controller = controller


def get_controller() -> Optional[FleetController]:
    return _controller


def controller_from_env(aggregator, store, *,
                        world_size: int,
                        dry_run: bool = False,
                        leader_elect: bool = True,
                        controller_id: Optional[str] = None,
                        lease_ttl: Optional[float] = None
                        ) -> FleetController:
    """Build the controller + bus for a supervisor that already holds an
    aggregator and a dedicated store connection (tools/elastic_run.py),
    register it for the /controller endpoint, and return it.

    ``leader_elect=True`` (the default since PR 20) attaches a
    :class:`~paddle_tpu.distributed.fleet.leader.LeaderLease`:
    ``--controller`` may now be passed on EVERY host — the first ticker
    bootstraps as leader, the rest stand by and take over within one
    ``PADDLE_TPU_CONTROLLER_LEASE_TTL`` of leader silence. A lone
    controller pays one lease renew per ``ttl/3`` and behaves exactly
    like the pre-HA deployment otherwise."""
    bus = ControllerCommandBus(store)
    # clearing a previous job's done-flag cannot race a live fleet, only
    # a finished one — with standbys this runs once per controller at
    # job start, before any eviction can have held a host
    bus.reset_job_done()
    try:
        # arm every supervisor's ledger poll up front so the FIRST
        # decision doesn't wait out the relaxed presence-probe cadence
        bus.mark_present()
    except Exception:
        pass  # re-tried by the first publish
    lease = (LeaderLease(store, controller_id=controller_id,
                         ttl=lease_ttl)
             if leader_elect else None)
    ctl = FleetController(aggregator, bus, world_size, dry_run=dry_run,
                          lease=lease)
    set_controller(ctl)
    return ctl
