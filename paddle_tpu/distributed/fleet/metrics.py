"""Distributed metrics — allreduced across trainers.

Reference: `python/paddle/distributed/fleet/metrics/metric.py` (sum/max/min/
auc aggregated with gloo allreduce across PS trainers). TPU translation:
under a live mesh the reduction is an XLA collective
(`distributed.collective.all_reduce`); in PS mode it runs over the table
server's barrier+dense-table path; single process returns the local value.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ... import ops  # noqa: F401  (Tensor methods)
from ...framework.tensor import Tensor


def _to_np(x) -> np.ndarray:
    if isinstance(x, Tensor):
        return np.asarray(x.numpy(), np.float64)
    return np.asarray(x, np.float64)


def _allreduce(arr: np.ndarray, op: str = "sum") -> np.ndarray:
    from ..ps import runtime as ps_runtime
    if ps_runtime._state["client"] is not None:
        return _allreduce_ps(arr, op)
    import jax
    if jax.process_count() > 1:
        from .. import collective
        t = Tensor(arr.astype(np.float32))
        collective.all_reduce(t)  # psum over the live mesh
        return np.asarray(t.numpy(), np.float64)
    return arr


_metric_round = {"n": 0}
_MAX_METRIC_ELEMS = 4096


def _flush(client):
    """Async-communicator clients buffer pushes; metrics are barrier-
    synchronized, so queued writes must land before each barrier."""
    if hasattr(client, "flush"):
        client.flush()


def _allreduce_ps(arr: np.ndarray, op: str) -> np.ndarray:
    """PS-mode allreduce via per-trainer slots in a scratch dense table:
    every trainer writes its fp32 value into its own slot, then all reduce
    locally in float64 after a barrier (exactness is limited only by fp32 of
    the LOCAL values; per-round barrier names stop back-to-back metric
    calls from racing on the shared table)."""
    from ..ps import runtime as ps_runtime
    from ..ps.client import TableConfig
    client = ps_runtime.get_client()
    n = ps_runtime.num_trainers()
    rank = ps_runtime.trainer_id()
    rnd = _metric_round["n"]
    _metric_round["n"] += 1
    tid = 990 + (rnd % 2)  # alternate scratch tables across rounds
    flat = arr.reshape(-1).astype(np.float32)
    if flat.size > _MAX_METRIC_ELEMS:
        raise ValueError(
            f"fleet.metrics: value has {flat.size} elements; the PS scratch "
            f"table caps at {_MAX_METRIC_ELEMS}")
    # FIXED-size table: server-side create_table is create-if-absent, so a
    # size that varied between calls would silently bind a stale table
    slot = _MAX_METRIC_ELEMS
    client.create_table(TableConfig(table_id=tid, kind="dense",
                                    dense_size=slot * n,
                                    optimizer="sgd", learning_rate=1.0,
                                    init_range=0.0))
    if rank == 0:
        client.set_dense(tid, np.zeros(slot * n, np.float32))
    _flush(client)
    ps_runtime.barrier_worker(f"metric_zero_{rnd}")
    mine = np.zeros(slot * n, np.float32)
    mine[rank * slot:rank * slot + flat.size] = flat
    client.push_dense(tid, -mine)  # sgd(lr=1): w -= -x  => w += x
    _flush(client)  # async communicator: land the push BEFORE the barrier
    ps_runtime.barrier_worker(f"metric_push_{rnd}")
    allv = client.pull_dense(tid).astype(np.float64).reshape(n, slot)
    allv = allv[:, :flat.size]
    ps_runtime.barrier_worker(f"metric_pull_{rnd}")  # table reusable after
    if op == "sum":
        red = allv.sum(axis=0)
    elif op == "max":
        red = allv.max(axis=0)
    elif op == "min":
        red = allv.min(axis=0)
    else:
        raise NotImplementedError(op)
    return red.reshape(arr.shape)


def sum(input, scope=None, util=None):
    return _allreduce(_to_np(input), "sum")


def max(input, scope=None, util=None):
    return _minmax(_to_np(input), is_max=True)


def min(input, scope=None, util=None):
    return _minmax(_to_np(input), is_max=False)


def _minmax(arr: np.ndarray, is_max: bool) -> np.ndarray:
    import jax
    from ..ps import runtime as ps_runtime
    if ps_runtime._state["client"] is not None:
        return _allreduce_ps(arr, "max" if is_max else "min")
    if jax.process_count() <= 1:
        return arr
    from .. import collective
    t = Tensor(arr.astype(np.float32))
    collective.all_reduce(t, op=collective.ReduceOp.MAX if is_max
                          else collective.ReduceOp.MIN)
    return np.asarray(t.numpy(), np.float64)


def acc(correct, total, scope=None, util=None):
    """Global accuracy = sum(correct)/sum(total) (reference metric.py acc)."""
    c = _allreduce(_to_np(correct), "sum")
    t = _allreduce(_to_np(total), "sum")
    return float(c) / float(np.maximum(t, 1e-12))


def auc(stat_pos, stat_neg, scope=None, util=None):
    """Global AUC from per-trainer positive/negative histogram buckets
    (reference metric.py auc)."""
    pos = _allreduce(_to_np(stat_pos), "sum")
    neg = _allreduce(_to_np(stat_neg), "sum")
    # standard trapezoid over cumulative TP/FP (buckets ordered by score)
    tot_pos = new_pos = 0.0
    tot_neg = new_neg = 0.0
    area = 0.0
    for i in range(len(pos) - 1, -1, -1):
        new_pos = tot_pos + pos[i]
        new_neg = tot_neg + neg[i]
        area += (new_neg - tot_neg) * (tot_pos + new_pos) / 2.0
        tot_pos, tot_neg = new_pos, new_neg
    if tot_pos == 0 or tot_neg == 0:
        return 0.5
    return float(area / (tot_pos * tot_neg))


__all__ = ["sum", "max", "min", "acc", "auc"]
