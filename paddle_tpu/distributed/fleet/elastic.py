"""Elastic training: membership watching + scale-in/out restart signaling.

Reference: /root/reference/python/paddle/distributed/fleet/elastic/
manager.py:130 — nodes register in etcd under TTL leases, the manager
watches membership, and on change rewrites the endpoint env and restarts
local trainers; exit code 101 (`ELASTIC_EXIT_CODE`) asks the launcher for a
full restart, 102 for an auto-parallel re-plan.

TPU translation: etcd is replaced by the native TCPStore
(`distributed/store.py` over `_native/csrc/store.cc`) hosted by the master:
each node heartbeats `beat/<host_id>` with a timestamp; the manager derives
alive membership from heartbeat age (the TTL lease). The launcher's
elastic_level>0 restart loop (`launch/main.py`) plays the reference
controller's role; `ElasticManager.watch()` is the membership change signal.

`ElasticSupervisor` closes the loop the reference leaves to operators: a
per-host supervisor that relaunches the trainer on crash / explicit
`ELASTIC_EXIT_CODE` / membership shrink (watch() → RESTART), with a bounded
restart budget and exponential backoff, exporting
`PADDLE_TPU_ELASTIC_RESTART_NUM` so the coordinated-checkpoint barrier
(`distributed/checkpoint.CheckpointCoordinator`) namespaces each generation
and the relaunched `Model.fit(resume=...)` re-enters without operator glue.
`tools/elastic_run.py` is the CLI face.
"""
from __future__ import annotations

import os
import signal
import threading
import time
import warnings
from typing import Dict, List, Optional, Sequence

from ...profiler import events as _events_mod
from ...profiler import metrics as _metrics_mod
from ...utils import envparse as _envparse

ELASTIC_EXIT_CODE = 101
ELASTIC_AUTO_PARALLEL_EXIT_CODE = 102

#: exported to every trainer generation; the coordinated-checkpoint barrier
#: namespaces its store keys by this so a restarted fleet can never collide
#: with prepare/abort flags left by the incarnation that died
RESTART_NUM_ENV = "PADDLE_TPU_ELASTIC_RESTART_NUM"

_REG = _metrics_mod.default_registry()
_M_RESTARTS = _REG.counter(
    "elastic_restarts_total",
    "trainer relaunches performed by the elastic supervisor, labeled by "
    "reason: failure / restart_requested / membership")


class ElasticStatus:
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"
    RESTART = "restart"
    EXIT = "exit"


class ElasticManager:
    def __init__(self, host_id: Optional[str] = None,
                 master: Optional[str] = None,
                 ttl: Optional[float] = None,
                 np: Optional[int] = None,
                 is_master: bool = False, store=None):
        from ..store import TCPStore
        self.host_id = host_id or os.environ.get(
            "PADDLE_CURRENT_ENDPOINT", f"host-{os.getpid()}")
        if ttl is None:  # resolve env at construction, not import
            ttl = float(os.environ.get("PADDLE_ELASTIC_TTL", 10))
        self.ttl = ttl
        self.np = np or int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        if store is not None:
            self._store = store
        else:
            addr = master or f"{os.environ.get('MASTER_ADDR', '127.0.0.1')}:" \
                             f"{os.environ.get('MASTER_PORT', '0')}"
            h, p = addr.rsplit(":", 1)
            self._store = TCPStore(h, int(p), is_master=is_master)
        self._stop = threading.Event()
        self._beat_thread: Optional[threading.Thread] = None
        self._slot: Optional[int] = None
        self.elastic_level = int(os.environ.get(
            "PADDLE_ELASTIC_FAULT_TOLERANC_LEVEL", "1"))

    def _key(self, name: str) -> str:
        """Membership keys are namespaced by the FLEET SIZE: an operator
        relaunching the fleet with a changed --np against the same
        (supervisor-hosted, long-lived) rendezvous store must not inherit
        the old world's member registrations — after a 4->2 scale-down the
        two stale trainer ids would read as permanently dead members and
        every supervisor's watch would SIGTERM its healthy trainer until
        the restart budget wedged. A different np is a different fleet;
        its membership starts empty."""
        return f"fleet{self.np}/{name}"

    # -- registration / heartbeats (reference: etcd TTL lease) -------------
    def register(self):
        self._beat()
        self._beat_thread = threading.Thread(target=self._beat_loop,
                                             daemon=True)
        self._beat_thread.start()

    def _beat(self):
        self._store.set(self._key(f"beat/{self.host_id}"), repr(time.time()))

    def _beat_loop(self):
        while not self._stop.wait(self.ttl / 3):
            try:
                self._beat()
            except Exception:
                return  # store gone: job is tearing down

    def alive_members(self) -> List[str]:
        members = []
        now = time.time()
        for hid in self._member_ids():
            key = self._key(f"beat/{hid}")
            try:
                # store.get blocks until the key exists — probe first (a
                # departed node deletes its beat key on exit)
                if not self._store.check(key):
                    continue
                ts = float(self._store.get(key).decode())
            except Exception:
                continue
            if now - ts <= self.ttl:
                members.append(hid)
        return sorted(members)

    def _member_ids(self) -> List[str]:
        # membership = per-slot keys claimed via the store's ATOMIC counter
        # (a shared CSV value would lose concurrent joins to read-modify-
        # write races)
        if not self._store.check(self._key("member_count")):
            return []
        import struct
        n = struct.unpack("<q", self._store.get(self._key("member_count")))[0]
        ids = []
        for i in range(int(n)):
            key = self._key(f"member/{i}")
            if self._store.check(key):
                v = self._store.get(key).decode()
                if v:  # "" = tombstone left by a clean exit
                    ids.append(v)
        # a restarted host re-joins into a fresh slot while its old slot
        # remains — dedupe by host id so it cannot count twice
        return list(dict.fromkeys(ids))

    def _reclaim_slot(self) -> Optional[int]:
        """Pop a slot freed by a clean exit. Each free-list index has its
        own monotonic claim counter, so `add(claim/i, 1) == 1` is won by
        exactly one joiner EVER — no hand-back, no double-claim window.
        If a won index's value is not yet visible (exit publishes the count
        after a concurrent exit's value write is still in flight), that
        freed slot stays tombstoned unreclaimed — safe, just unreused."""
        try:
            n = int(self._store.add(self._key("member_free_count"), 0))
            for i in range(n):
                if self._store.add(self._key(f"member_free_claim/{i}"), 1) != 1:
                    continue  # someone else owns this index forever
                key = self._key(f"member_free/{i}")
                if not self._store.check(key):
                    # won a claim whose value write is still in flight
                    # (concurrent exits publish the count once): that slot
                    # is unrecoverable, but later indices may not be —
                    # keep scanning
                    continue
                return int(self._store.get(key).decode())
            return None
        except Exception:
            return None

    def join(self):
        """Claim a membership slot atomically (any rank). Prefers a slot
        released by ElasticManager.exit() so member_count stays bounded
        across restart cycles instead of growing forever."""
        slot = self._reclaim_slot()
        if slot is None:
            slot = self._store.add(self._key("member_count"), 1) - 1
        self._store.set(self._key(f"member/{slot}"), self.host_id)
        self._slot = slot
        self._clear_done()
        self.register()

    # -- completion flags (supervisor watch) -------------------------------
    # A host whose training FINISHED stops heartbeating too; without a
    # completion flag a peer's supervisor could not tell "done" from "dead"
    # and would restart its own healthy trainer at job end.
    def mark_done(self, host_id: Optional[str] = None):
        """Publish that `host_id`'s (default: this manager's own) work
        completed cleanly — beats may stop without peers treating the
        silence as a failure. A supervisor passes its child's member id:
        it observes the clean exit, while most trainers never call this
        themselves."""
        try:
            self._store.set(self._key(f"done/{host_id or self.host_id}"), "1")
        except Exception:
            pass  # store gone: job is tearing down anyway

    def is_done(self, host_id: str) -> bool:
        try:
            return bool(self._store.check(self._key(f"done/{host_id}")))
        except Exception:
            return False

    def _clear_done(self):
        # a REJOINING host (new generation after restart) is not done
        try:
            if self._store.check(self._key(f"done/{self.host_id}")):
                self._store.delete_key(self._key(f"done/{self.host_id}"))
        except Exception:
            pass

    # -- watching (reference manager.watch:126) ----------------------------
    def watch(self, timeout: Optional[float] = None) -> str:
        """Block until membership changes or timeout; returns ElasticStatus."""
        want = self.np
        baseline = self.alive_members()
        deadline = None if timeout is None else time.time() + timeout
        while True:
            time.sleep(min(self.ttl / 3, 1.0))
            cur = self.alive_members()
            if cur != baseline:
                if len(cur) < want:
                    return ElasticStatus.HOLD if self.elastic_level < 2 \
                        else ElasticStatus.RESTART
                return ElasticStatus.RESTART
            if deadline is not None and time.time() >= deadline:
                return ElasticStatus.COMPLETED

    def abandon(self):
        """Stop heartbeating WITHOUT deregistering. For a supervisor whose
        restart budget died: the member stays registered while its beat
        goes stale, so every peer's membership watch detects the dead host.
        `exit()` here instead would tombstone the slot — the member list
        shrinks below `np`, peers' watches read it as 'fleet never
        assembled', and the death becomes invisible (peers hang in
        collectives/barriers instead of restarting)."""
        self._stop.set()
        if self._beat_thread is not None:
            self._beat_thread.join(timeout=2)

    def exit(self, completed: bool = True):
        self._stop.set()
        if self._beat_thread is not None:
            self._beat_thread.join(timeout=2)
        try:
            self._store.delete_key(self._key(f"beat/{self.host_id}"))
        except Exception:
            pass
        # release the membership slot: tombstone member/<i> and publish it
        # on the free list so the next joiner reuses it (without this,
        # member_count grows without bound across restart cycles)
        if self._slot is not None:
            try:
                self._store.set(self._key(f"member/{self._slot}"), "")
                j = self._store.add(self._key("member_free_next"), 1) - 1
                self._store.set(self._key(f"member_free/{j}"), str(self._slot))
                self._store.add(self._key("member_free_count"), 1)  # publish LAST
            except Exception:
                pass  # store gone: job is tearing down
            self._slot = None

    @staticmethod
    def request_restart():
        """Trainer-side: exit so the launcher's elastic loop redeploys."""
        raise SystemExit(ELASTIC_EXIT_CODE)


class RestartBudgetExceeded(RuntimeError):
    """The elastic supervisor exhausted its restart budget."""

    def __init__(self, restarts: int, budget: int, last_reason: str):
        super().__init__(
            f"elastic restart budget exhausted: {restarts} restarts "
            f"(budget {budget}), last failure reason: {last_reason}")
        self.restarts = restarts
        self.budget = budget
        self.last_reason = last_reason


class ElasticSupervisor:
    """Per-host auto-restart loop: crash / `ELASTIC_EXIT_CODE` / membership
    shrink → backoff → relaunch, with a bounded budget.

    Two modes:

    * ``run(train_fn)`` — in-process: call `train_fn` (which should end in
      `Model.fit(resume=ckpt_dir)` so every generation restores from the
      newest fleet-committed checkpoint); a raised exception or
      `SystemExit(ELASTIC_EXIT_CODE)` consumes one restart and re-enters.
    * ``supervise(cmd)`` — subprocess: spawn the trainer command and watch
      both the child (corpse / exit code) and, when a `manager` is given,
      fleet membership — a host whose heartbeat goes stale (the reference
      `watch() → RESTART` signal) SIGTERMs the local trainer (one final
      coordinated preemption save) and relaunches it, so EVERY host
      re-enters the same generation and the checkpoint barrier namespaces
      line up.

    Each generation sees `PADDLE_TPU_ELASTIC_RESTART_NUM` (env for
    subprocesses, os.environ for in-process) from a LOCAL generation
    counter kept in lockstep by the trainer contract, not shared state: a
    trainer whose coordinated save aborts must exit `ELASTIC_EXIT_CODE`
    so every host's supervisor bumps together. A host whose
    crash+relaunch slips under the heartbeat TTL runs one generation
    ahead until its peers' next coordinated save times out and aborts
    (bounded by the barrier timeout), at which point they exit 101 and
    catch up — a transient stall of at most one aborted save, not a
    wedge. A fleet-controller command (see below) re-anchors every
    host's generation to the command id times
    `controller.GEN_STRIDE`, so controller-driven relaunches land in one
    barrier namespace even when local failure counts had drifted.

    With `commands` (a `controller.ControllerCommandBus`), the
    supervisor also polls the fleet controller's command ledger and
    ACTS: `evict` naming this host's trainer stops the child and HOLDS
    (beating a probation `ctl/ready` key until `readmit` or job end);
    `evict` naming a peer / `readmit` / `rollback` stop the child and
    relaunch it under the command's np / rank / env contract (rollback
    kills hard — the in-flight state is the diverged state a preemption
    save must not capture). Controller relaunches are metered
    (`elastic_restarts_total{reason=controller_*}`) but never consume
    the restart budget — they are decisions, not failures.

    Knobs: `PADDLE_TPU_ELASTIC_MAX_RESTARTS` (default 3),
    `PADDLE_TPU_ELASTIC_BACKOFF` (base seconds, default 1.0, doubled per
    restart), `PADDLE_TPU_ELASTIC_BACKOFF_MAX` (default 30),
    `PADDLE_TPU_ELASTIC_BUDGET_RESET_SEC` (default 300; a child that ran
    healthily at least this long resets the consecutive-restart counter,
    so a flapping-then-fixed host doesn't wedge the fleet on a stale
    exhausted budget; 0 disables), `PADDLE_TPU_CONTROLLER_POLL_SEC`
    (command-ledger poll cadence, default 1.0). Every relaunch lands in
    `elastic_restarts_total{reason=}`.
    """

    #: a child that fails within this window of its launch is treated as
    #: never having gotten past resume: its one-shot env overlay
    #: (env_once, e.g. the rollback's PADDLE_TPU_RESUME_VALID_ONLY) is
    #: re-armed for the retry instead of being consumed by the failure
    ENV_ONCE_RETRY_S = 120.0

    def __init__(self, max_restarts: Optional[int] = None,
                 backoff: Optional[float] = None,
                 backoff_max: Optional[float] = None,
                 manager: Optional[ElasticManager] = None,
                 poll: float = 0.2, stop_grace: float = 10.0,
                 self_member: Optional[str] = None,
                 commands=None,
                 on_fleet_change=None,
                 budget_reset_s: Optional[float] = None,
                 cmd_poll: Optional[float] = None):
        if max_restarts is None:
            max_restarts = _envparse.env_int(
                "PADDLE_TPU_ELASTIC_MAX_RESTARTS", 3)
        if backoff is None:
            backoff = _envparse.env_float("PADDLE_TPU_ELASTIC_BACKOFF", 1.0)
        if backoff_max is None:
            backoff_max = _envparse.env_float(
                "PADDLE_TPU_ELASTIC_BACKOFF_MAX", 30.0)
        self.max_restarts = int(max_restarts)
        self.backoff = float(backoff)
        self.backoff_max = float(backoff_max)
        self.manager = manager
        self.poll = float(poll)
        self.stop_grace = float(stop_grace)
        # the member id the LOCAL trainer registers under (the manager
        # passed here is typically watch-only, under a different id). The
        # supervisor watches PEERS by heartbeat; its own child it watches
        # directly by process exit — so the child's id must be excluded
        # from staleness checks. Otherwise the child's own restart gap
        # (old process dead, new one still importing) reads as a stale
        # member the moment the rest of the fleet reassembles, and the
        # supervisor SIGTERMs its freshly relaunched trainer: generations
        # desync and every later barrier round times out fleet-wide.
        self.self_member = self_member
        if budget_reset_s is None:
            budget_reset_s = _envparse.env_float(
                "PADDLE_TPU_ELASTIC_BUDGET_RESET_SEC", 300.0)
        self.budget_reset_s = float(budget_reset_s)
        if cmd_poll is None:
            cmd_poll = _envparse.env_float(
                "PADDLE_TPU_CONTROLLER_POLL_SEC", 1.0)
        self.cmd_poll = max(float(cmd_poll), 0.05)
        if commands is not None and self_member is None:
            warnings.warn(
                "elastic supervisor: a controller command bus needs "
                "self_member (the trainer's stable member id) to apply "
                "rank assignments; ignoring the bus")
            commands = None
        self.commands = commands
        self.on_fleet_change = on_fleet_change
        #: latched once the presence key is seen: from then on the ledger
        #: is scanned every cmd_poll (see _wait_child's presence gate)
        self._ctl_present = False
        #: the pre-existing RESTART_NUM base supervise() honors; controller
        #: generation floors are taken net of it (see _apply_command)
        self._gen_base = 0
        self.restarts = 0
        #: the RESTART_NUM the next child sees (minus the env base).
        #: Bumped by 1 per failure relaunch like `restarts`, but never
        #: reset by the healthy-window budget reset (generations must
        #: stay monotonic) and re-anchored by controller commands.
        self.generation = 0
        self.last_reason: Optional[str] = None
        self._cmd_cursor: Optional[int] = None
        self._pending_cmd: Optional[dict] = None
        #: highest controller fencing term ever applied by this
        #: supervisor: a deposed leader's in-flight command (term below
        #: this, or below the CURRENT lease record's term) is consumed
        #: without actuation — see _command_fenced
        self._term_seen = 0
        #: controller-command env overlay (np / rank / prewarm changes
        #: accumulated from applied commands; persists across relaunches)
        self._cmd_env: Dict[str, str] = {}
        #: one-shot overlay (a command's env_once — e.g. the rollback's
        #: PADDLE_TPU_RESUME_VALID_ONLY): applied to the NEXT launch only,
        #: so resume-mode flags never leak into ordinary failure restarts
        self._cmd_env_once: Dict[str, str] = {}

    # -- shared restart accounting ------------------------------------------
    def _consume_restart(self, reason: str) -> bool:
        """True = budget left (counted + metered); False = exhausted."""
        self.restarts += 1
        self.last_reason = reason
        if self.restarts > self.max_restarts:
            return False
        if _metrics_mod.enabled():
            _M_RESTARTS.inc(reason=reason)
        _events_mod.emit("elastic_restart", severity="warn", reason=reason,
                         restart=self.restarts, budget=self.max_restarts)
        warnings.warn(
            f"elastic supervisor: restarting trainer "
            f"({self.restarts}/{self.max_restarts}, reason: {reason})")
        return True

    def _backoff_sleep(self):
        time.sleep(min(self.backoff * (2 ** max(0, self.restarts - 1)),
                       self.backoff_max))

    def _maybe_reset_budget(self, healthy_s: float):
        """A trainer that ran healthily for a sustained window earned its
        budget back: the next failure is a NEW incident, not the tail of
        the old flap — without this, a host that flapped up to the budget
        and then ran clean for hours is one hiccup away from a permanent
        wedge on a stale exhausted counter. Generations never reset."""
        if self.budget_reset_s <= 0 or self.restarts <= 0:
            return
        if healthy_s < self.budget_reset_s:
            return
        _events_mod.emit("elastic_budget_reset", severity="info",
                         healthy_s=round(healthy_s, 3),
                         restarts_forgiven=self.restarts,
                         budget=self.max_restarts)
        self.restarts = 0

    def _publish_done(self):
        """The local trainer finished cleanly but its heartbeats now stop:
        publish its done-flag so every PEER's membership watch reads the
        silence as completion, not death (most trainers never call
        mark_done() themselves). With self_member unset (in-process mode,
        where the manager typically IS the trainer's own) the flag lands
        on the manager's own member id."""
        if self.manager is not None:
            self.manager.mark_done(self.self_member)

    # -- in-process mode -----------------------------------------------------
    def run(self, train_fn):
        """Call `train_fn` under the restart budget; returns its result.
        The function should re-enter through `fit(resume=ckpt_dir)` so each
        generation restores the newest fleet-committed step. In-process
        mode has no membership watch (that is supervise()'s job); a
        manager given here is used only to publish the done-flag on clean
        completion."""
        base = int(os.environ.get(RESTART_NUM_ENV, "0"))
        while True:
            os.environ[RESTART_NUM_ENV] = str(base + self.generation)
            started = time.monotonic()
            err: BaseException
            try:
                result = train_fn()
                self._publish_done()
                return result
            except KeyboardInterrupt:
                raise
            except SystemExit as e:
                code = e.code or 0
                if code == 0:
                    self._publish_done()
                    return None
                reason = "restart_requested" if code == ELASTIC_EXIT_CODE \
                    else "failure"
                err = e
            except Exception as e:
                reason, err = "failure", e
            self._maybe_reset_budget(time.monotonic() - started)
            if not self._consume_restart(reason):
                raise RestartBudgetExceeded(self.restarts - 1,
                                            self.max_restarts, reason) from err
            self.generation += 1
            self._backoff_sleep()

    # -- subprocess mode -----------------------------------------------------
    def supervise(self, cmd: Sequence[str],
                  env: Optional[Dict[str, str]] = None) -> int:
        """Spawn `cmd`, relaunching on failure / ELASTIC_EXIT_CODE /
        membership shrink until it exits 0 or the budget runs out.
        Returns the final exit code (0 on success)."""
        import subprocess
        last_code = 1
        # honor a pre-existing generation base (an operator relaunching a
        # dead supervisor while peers are at generation N), same as run():
        # starting over at 0 would namespace the checkpoint barrier under
        # stale keys and every coordinated save would time out fleet-wide
        base = int(os.environ.get(RESTART_NUM_ENV, "0"))
        self._gen_base = base
        if self.commands is not None and self._cmd_cursor is None:
            # commands published before this supervisor existed belong to
            # a previous incarnation of the job, never to this one. On a
            # store blip the cursor stays None and _next_command retries
            # the anchor — falling back to 0 would REPLAY the previous
            # incarnation's ledger (a stale rollback hard-killing a
            # healthy fresh trainer) out of a long-lived host-store
            self._anchor_cmd_cursor()
        while True:
            child_env = dict(os.environ)
            child_env.update(env or {})
            child_env.update(self._cmd_env)
            once, self._cmd_env_once = self._cmd_env_once, {}
            child_env.update(once)
            child_env[RESTART_NUM_ENV] = str(base + self.generation)
            started = time.monotonic()
            proc = subprocess.Popen(list(cmd), env=child_env)
            reason, code = self._wait_child(proc)
            if reason is None:
                self._publish_done()
                return 0
            if once and time.monotonic() - started < self.ENV_ONCE_RETRY_S:
                # a child that died within the startup window never got
                # past its resume: retry with the SAME one-shot contract.
                # Concretely: the rollback's valid-only resume RAISES on
                # a nonfinite fleet-agreed step so the fleet renegotiates
                # — that renegotiation must also run valid-only, or the
                # relaunch silently restores exactly the diverged state
                # the rollback existed to skip. A crash hours later ran
                # healthily past resume and does NOT re-arm (the one-shot
                # flag must not leak into routine restarts).
                merged = dict(once)
                merged.update(self._cmd_env_once)  # newer commands win
                self._cmd_env_once = merged
            # a long-healthy child earns its budget back no matter WHY it
            # stopped — including a controller command: the reshape right
            # after is the likeliest moment for a rendezvous hiccup, and
            # a stale exhausted counter would turn it into a permanent
            # wedge on the relaunched fleet
            self._maybe_reset_budget(time.monotonic() - started)
            if reason == "controller":
                cmd_rec, self._pending_cmd = self._pending_cmd, None
                if self._apply_command(cmd_rec) == "hold":
                    readmit = self._hold_for_readmit()
                    if readmit is None:
                        return 0  # job finished without this host
                    self._apply_command(readmit)
                continue  # controller relaunch: no budget consumed
            last_code = code
            if not self._consume_restart(reason):
                return last_code if last_code else 1
            self.generation += 1
            self._backoff_sleep()

    def _wait_child(self, proc):
        """(None, 0) on clean exit; else (reason, exit_code). With a
        manager, a fleet member that is neither alive nor marked done —
        after the fleet was once fully assembled — triggers a coordinated
        local restart (SIGTERM the child, return 'membership')."""
        seen_full = False
        next_membership = 0.0
        next_cmd = 0.0
        while True:
            code = proc.poll()
            if code is not None:
                if code == 0:
                    return None, 0
                if code == ELASTIC_EXIT_CODE:
                    return "restart_requested", code
                return "failure", code
            if self.commands is not None and time.monotonic() >= next_cmd:
                if not self._ctl_present \
                        and not self.commands.present():
                    # no controller has ever attached to this job: probe
                    # the ONE presence key at a relaxed cadence instead
                    # of scanning the ledger — N supervisors x 1 Hz of
                    # ledger RPCs would tax the rendezvous store the
                    # checkpoint barrier and membership watch share, for
                    # a command plane nobody is driving
                    next_cmd = time.monotonic() + 5 * self.cmd_poll
                else:
                    self._ctl_present = True
                    next_cmd = time.monotonic() + self.cmd_poll
                    cmd = self._next_command()
                    if cmd is not None:
                        # rollback discards the in-flight (diverged)
                        # state: a graceful SIGTERM would let the
                        # preemption handler checkpoint exactly what the
                        # rollback exists to throw away. Evict/readmit
                        # stop gracefully so the fleet can barrier one
                        # final coordinated save first.
                        self._pending_cmd = cmd
                        self._stop_child(
                            proc, hard=(cmd.get("action") == "rollback"))
                        return "controller", ELASTIC_EXIT_CODE
            if self.manager is not None \
                    and time.monotonic() >= next_membership:
                # a membership check costs O(world_size) store RPCs: run
                # it on the heartbeat cadence (ttl/3), not the fast child
                # poll, or a large fleet's supervisors drown the one
                # rendezvous store the checkpoint barrier also polls
                next_membership = time.monotonic() + max(
                    getattr(self.manager, "ttl", 10.0) / 3, self.poll)
                missing = self._missing_members()
                if missing is not None:
                    if not missing:
                        seen_full = True
                    elif seen_full:
                        self._stop_child(proc)
                        return "membership", ELASTIC_EXIT_CODE
            time.sleep(self.poll)

    def _missing_members(self) -> Optional[List[str]]:
        """Members that are neither heartbeating nor marked done; None
        while the fleet has not fully assembled yet (startup grace)."""
        mgr = self.manager
        try:
            ids = mgr._member_ids()
            if len(ids) < mgr.np:
                return None
            alive = set(mgr.alive_members())
            return [i for i in ids
                    if i != self.self_member and i not in alive
                    and not mgr.is_done(i)]
        except Exception:
            return None  # store hiccup: never restart on a read failure

    def _stop_child(self, proc, hard: bool = False):
        try:
            if hard:
                proc.kill()
                proc.wait()
                return
            proc.send_signal(signal.SIGTERM)
        except OSError:
            return
        deadline = time.time() + self.stop_grace
        while proc.poll() is None and time.time() < deadline:
            time.sleep(0.05)
        if proc.poll() is None:
            proc.kill()
            proc.wait()

    # -- fleet-controller command application --------------------------------
    def _anchor_cmd_cursor(self):
        """Anchor the ledger cursor at the CURRENT head so only commands
        published after this supervisor started are ever applied."""
        try:
            self._cmd_cursor = self.commands.last_id()
        except Exception:
            pass  # retried from _next_command; never default to 0

    def _next_command(self) -> Optional[dict]:
        """Oldest unconsumed actionable ledger command, or None. Never
        raises (a store hiccup is retried on the next poll tick)."""
        if self._cmd_cursor is None:
            self._anchor_cmd_cursor()
            return None  # anchored just now (or still unreachable)
        try:
            for cmd in self.commands.poll(self._cmd_cursor or 0):
                if cmd.get("action") in ("evict", "readmit", "rollback"):
                    if self._command_fenced(cmd):
                        # stale term: consume WITHOUT actuating — the
                        # issuer was deposed and the new leader owns
                        # this incident (it may publish its own,
                        # current-term command any tick now)
                        self._cmd_cursor = max(self._cmd_cursor or 0,
                                               int(cmd.get("id", 0)))
                        continue
                    return cmd
                # unknown actions from a newer controller: consume + skip
                self._cmd_cursor = max(self._cmd_cursor or 0,
                                       int(cmd.get("id", 0)))
        except Exception:
            pass
        return None

    def _command_fenced(self, cmd: dict) -> bool:
        """Is this command's fencing term stale? Judged against the
        HIGHEST of (a) the term in the CURRENT lease record — never the
        raw term counter, which a failed acquirer bumps without ever
        holding the lease — and (b) the highest term this supervisor has
        already applied (covers a store blip hiding the lease record).
        Commands without a term (pre-HA controller) always pass."""
        term = cmd.get("term")
        if term is None:
            return False
        term = int(term)
        from . import leader as _leader
        cur = _leader.lease_term(self.commands.store)
        high = max(self._term_seen, int(cur or 0))
        if term < high:
            policy = str(cmd.get("policy", "?"))
            if _metrics_mod.enabled():
                _leader._M_FENCED.inc(policy=policy)
            _events_mod.emit(
                "controller_fenced", severity="warn", policy=policy,
                term=term, current_term=high,
                action=cmd.get("action"), command=int(cmd.get("id", 0)),
                target=cmd.get("host"))
            return True
        self._term_seen = max(self._term_seen, term)
        _leader.note_term(term)
        return False

    def _apply_command(self, cmd: dict) -> str:
        """Fold one controller command into the relaunch contract.
        Returns "hold" when the command evicts THIS host's trainer, else
        "relaunch". Metered + event-logged, never budget-consuming."""
        self._cmd_cursor = max(self._cmd_cursor or 0, int(cmd.get("id", 0)))
        action = cmd.get("action", "?")
        reason = f"controller_{action}"
        self.last_reason = reason
        # generation floor: every supervisor applying command K relaunches
        # into the SAME checkpoint-barrier namespace, even when their
        # local failure-restart counts had drifted apart. The child sees
        # base + generation (supervise() honors a pre-existing
        # RESTART_NUM base), so the floor is taken net of OUR base — a
        # supervisor relaunched with base N must land on K*GEN_STRIDE
        # like its base-0 peers, not N + K*GEN_STRIDE
        try:
            from .controller import GEN_STRIDE
            self.generation = max(
                self.generation + 1,
                int(cmd.get("id", 0)) * GEN_STRIDE - self._gen_base)
        except Exception:
            self.generation += 1
        if _metrics_mod.enabled():
            _M_RESTARTS.inc(reason=reason)
        _events_mod.emit("elastic_restart", severity="warn", reason=reason,
                         command=int(cmd.get("id", 0)),
                         target=cmd.get("host"), np=cmd.get("np"),
                         generation=self.generation)
        # batched multi-straggler eviction: one command may hold SEVERAL
        # hosts ("hosts" list); single-host commands carry "host" only
        held = action == "evict" and self.self_member in (
            cmd.get("hosts") or [cmd.get("host")])
        if not held:
            overlay = {}
            if cmd.get("np") is not None:
                overlay["PADDLE_TRAINERS_NUM"] = str(int(cmd["np"]))
            ranks = cmd.get("ranks") or {}
            if self.self_member in ranks:
                overlay["PADDLE_TRAINER_ID"] = str(int(
                    ranks[self.self_member]))
            overlay.update({str(k): str(v)
                            for k, v in (cmd.get("env") or {}).items()})
            self._cmd_env.update(overlay)
            self._cmd_env_once.update(
                {str(k): str(v)
                 for k, v in (cmd.get("env_once") or {}).items()})
        if self.on_fleet_change is not None:
            try:
                self.on_fleet_change(cmd, held)
            except Exception as e:
                warnings.warn(f"elastic supervisor: fleet-change hook "
                              f"failed ({type(e).__name__}: {e})")
        warnings.warn(
            f"elastic supervisor: applying controller command "
            f"#{cmd.get('id')} {action} (np={cmd.get('np')}, "
            f"{'holding local trainer' if held else 'relaunching'})")
        return "hold" if held else "relaunch"

    def _hold_for_readmit(self) -> Optional[dict]:
        """Evicted-host probation: the trainer stays down while this
        supervisor beats `ctl/ready/<member>` so the controller knows the
        host is alive and readmittable. Returns the readmit command, or
        None when the fleet finished without us (`ctl/job_done`) or the
        hold outlived `PADDLE_TPU_CONTROLLER_HOLD_MAX_SEC` (3600) —
        readmit and job_done are both published by the controller host,
        so if that host dies hard this supervisor would otherwise beat
        probation forever with no escape."""
        max_hold = _envparse.env_float(
            "PADDLE_TPU_CONTROLLER_HOLD_MAX_SEC", 3600.0)
        deadline = time.monotonic() + max_hold if max_hold > 0 else None
        while True:
            if deadline is not None and time.monotonic() >= deadline:
                warnings.warn(
                    "elastic supervisor: probation hold outlived "
                    f"PADDLE_TPU_CONTROLLER_HOLD_MAX_SEC ({max_hold}s) "
                    "with no readmit or job-done flag — the controller "
                    "host is likely dead; exiting the hold")
                _events_mod.emit(
                    "elastic_restart", severity="error",
                    reason="controller_hold_expired",
                    member=self.self_member, max_hold_s=max_hold)
                return None
            try:
                self.commands.beat_ready(self.self_member)
            except Exception:
                pass  # store blip: keep holding, beat next tick
            if self.commands.job_done():
                return None
            try:
                for cmd in self.commands.poll(self._cmd_cursor or 0):
                    self._cmd_cursor = max(self._cmd_cursor or 0,
                                           int(cmd.get("id", 0)))
                    if cmd.get("action") == "readmit" \
                            and cmd.get("host") == self.self_member:
                        return cmd
                    # anything else (a rollback of the N-1 fleet, an
                    # unknown action) does not involve the held trainer:
                    # consume and keep holding
            except Exception:
                pass
            time.sleep(self.cmd_poll)


def run_elastic(target, *, max_restarts: Optional[int] = None,
                backoff: Optional[float] = None,
                manager: Optional[ElasticManager] = None, **kw):
    """Supervised elastic execution: `target` is either a callable (run
    in-process; make it end in `Model.fit(resume=ckpt_dir)`) or an argv list
    (supervised subprocess). Restarts on crash / ELASTIC_EXIT_CODE — plus,
    in argv mode with a `manager`, fleet-membership shrink — with bounded
    budget + backoff; each generation sees `PADDLE_TPU_ELASTIC_RESTART_NUM`.
    Returns the callable's result, or the subprocess's final exit code."""
    sup = ElasticSupervisor(max_restarts=max_restarts, backoff=backoff,
                            manager=manager, **kw)
    if callable(target):
        return sup.run(target)
    return sup.supervise(list(target))


__all__ = ["ElasticManager", "ElasticStatus", "ElasticSupervisor",
           "RestartBudgetExceeded", "run_elastic", "ELASTIC_EXIT_CODE",
           "ELASTIC_AUTO_PARALLEL_EXIT_CODE", "RESTART_NUM_ENV"]
