"""Elastic training: membership watching + scale-in/out restart signaling.

Reference: /root/reference/python/paddle/distributed/fleet/elastic/
manager.py:130 — nodes register in etcd under TTL leases, the manager
watches membership, and on change rewrites the endpoint env and restarts
local trainers; exit code 101 (`ELASTIC_EXIT_CODE`) asks the launcher for a
full restart, 102 for an auto-parallel re-plan.

TPU translation: etcd is replaced by the native TCPStore
(`distributed/store.py` over `_native/csrc/store.cc`) hosted by the master:
each node heartbeats `beat/<host_id>` with a timestamp; the manager derives
alive membership from heartbeat age (the TTL lease). The launcher's
elastic_level>0 restart loop (`launch/main.py`) plays the reference
controller's role; `ElasticManager.watch()` is the membership change signal.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional

ELASTIC_EXIT_CODE = 101
ELASTIC_AUTO_PARALLEL_EXIT_CODE = 102


class ElasticStatus:
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"
    RESTART = "restart"
    EXIT = "exit"


class ElasticManager:
    def __init__(self, host_id: Optional[str] = None,
                 master: Optional[str] = None,
                 ttl: Optional[float] = None,
                 np: Optional[int] = None,
                 is_master: bool = False, store=None):
        from ..store import TCPStore
        self.host_id = host_id or os.environ.get(
            "PADDLE_CURRENT_ENDPOINT", f"host-{os.getpid()}")
        if ttl is None:  # resolve env at construction, not import
            ttl = float(os.environ.get("PADDLE_ELASTIC_TTL", 10))
        self.ttl = ttl
        self.np = np or int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        if store is not None:
            self._store = store
        else:
            addr = master or f"{os.environ.get('MASTER_ADDR', '127.0.0.1')}:" \
                             f"{os.environ.get('MASTER_PORT', '0')}"
            h, p = addr.rsplit(":", 1)
            self._store = TCPStore(h, int(p), is_master=is_master)
        self._stop = threading.Event()
        self._beat_thread: Optional[threading.Thread] = None
        self._slot: Optional[int] = None
        self.elastic_level = int(os.environ.get(
            "PADDLE_ELASTIC_FAULT_TOLERANC_LEVEL", "1"))

    # -- registration / heartbeats (reference: etcd TTL lease) -------------
    def register(self):
        self._beat()
        self._beat_thread = threading.Thread(target=self._beat_loop,
                                             daemon=True)
        self._beat_thread.start()

    def _beat(self):
        self._store.set(f"beat/{self.host_id}", repr(time.time()))

    def _beat_loop(self):
        while not self._stop.wait(self.ttl / 3):
            try:
                self._beat()
            except Exception:
                return  # store gone: job is tearing down

    def alive_members(self) -> List[str]:
        members = []
        now = time.time()
        for hid in self._member_ids():
            key = f"beat/{hid}"
            try:
                # store.get blocks until the key exists — probe first (a
                # departed node deletes its beat key on exit)
                if not self._store.check(key):
                    continue
                ts = float(self._store.get(key).decode())
            except Exception:
                continue
            if now - ts <= self.ttl:
                members.append(hid)
        return sorted(members)

    def _member_ids(self) -> List[str]:
        # membership = per-slot keys claimed via the store's ATOMIC counter
        # (a shared CSV value would lose concurrent joins to read-modify-
        # write races)
        if not self._store.check("member_count"):
            return []
        import struct
        n = struct.unpack("<q", self._store.get("member_count"))[0]
        ids = []
        for i in range(int(n)):
            key = f"member/{i}"
            if self._store.check(key):
                v = self._store.get(key).decode()
                if v:  # "" = tombstone left by a clean exit
                    ids.append(v)
        # a restarted host re-joins into a fresh slot while its old slot
        # remains — dedupe by host id so it cannot count twice
        return list(dict.fromkeys(ids))

    def _reclaim_slot(self) -> Optional[int]:
        """Pop a slot freed by a clean exit. Each free-list index has its
        own monotonic claim counter, so `add(claim/i, 1) == 1` is won by
        exactly one joiner EVER — no hand-back, no double-claim window.
        If a won index's value is not yet visible (exit publishes the count
        after a concurrent exit's value write is still in flight), that
        freed slot stays tombstoned unreclaimed — safe, just unreused."""
        try:
            n = int(self._store.add("member_free_count", 0))
            for i in range(n):
                if self._store.add(f"member_free_claim/{i}", 1) != 1:
                    continue  # someone else owns this index forever
                key = f"member_free/{i}"
                if not self._store.check(key):
                    # won a claim whose value write is still in flight
                    # (concurrent exits publish the count once): that slot
                    # is unrecoverable, but later indices may not be —
                    # keep scanning
                    continue
                return int(self._store.get(key).decode())
            return None
        except Exception:
            return None

    def join(self):
        """Claim a membership slot atomically (any rank). Prefers a slot
        released by ElasticManager.exit() so member_count stays bounded
        across restart cycles instead of growing forever."""
        slot = self._reclaim_slot()
        if slot is None:
            slot = self._store.add("member_count", 1) - 1
        self._store.set(f"member/{slot}", self.host_id)
        self._slot = slot
        self.register()

    # -- watching (reference manager.watch:126) ----------------------------
    def watch(self, timeout: Optional[float] = None) -> str:
        """Block until membership changes or timeout; returns ElasticStatus."""
        want = self.np
        baseline = self.alive_members()
        deadline = None if timeout is None else time.time() + timeout
        while True:
            time.sleep(min(self.ttl / 3, 1.0))
            cur = self.alive_members()
            if cur != baseline:
                if len(cur) < want:
                    return ElasticStatus.HOLD if self.elastic_level < 2 \
                        else ElasticStatus.RESTART
                return ElasticStatus.RESTART
            if deadline is not None and time.time() >= deadline:
                return ElasticStatus.COMPLETED

    def exit(self, completed: bool = True):
        self._stop.set()
        if self._beat_thread is not None:
            self._beat_thread.join(timeout=2)
        try:
            self._store.delete_key(f"beat/{self.host_id}")
        except Exception:
            pass
        # release the membership slot: tombstone member/<i> and publish it
        # on the free list so the next joiner reuses it (without this,
        # member_count grows without bound across restart cycles)
        if self._slot is not None:
            try:
                self._store.set(f"member/{self._slot}", "")
                j = self._store.add("member_free_next", 1) - 1
                self._store.set(f"member_free/{j}", str(self._slot))
                self._store.add("member_free_count", 1)  # publish LAST
            except Exception:
                pass  # store gone: job is tearing down
            self._slot = None

    @staticmethod
    def request_restart():
        """Trainer-side: exit so the launcher's elastic loop redeploys."""
        raise SystemExit(ELASTIC_EXIT_CODE)


__all__ = ["ElasticManager", "ElasticStatus", "ELASTIC_EXIT_CODE",
           "ELASTIC_AUTO_PARALLEL_EXIT_CODE"]
