"""fleet — the unified distributed-training facade.

Reference: `paddle.distributed.fleet`
(`/root/reference/python/paddle/distributed/fleet/base/fleet_base.py:139`):
`fleet.init(is_collective=..., strategy=...)`, `distributed_model`,
`distributed_optimizer`, role makers, meta-optimizer auto-selection.

TPU translation: `init` builds the mesh (`HybridCommunicateGroup`) from
`strategy.hybrid_configs` and initializes `jax.distributed` for multi-host;
`distributed_model`/`distributed_optimizer` mark the model/optimizer and the
actual engine is `HybridParallelTrainStep` (meta_parallel/engine.py), which
replaces the whole meta-optimizer program-rewrite pipeline with sharded jit.
"""
from __future__ import annotations

from typing import Optional

import jax

from ...nn.layer import Layer
from ..env import ParallelEnv
from ..parallel import DataParallel, init_parallel_env
from ..topology import (CommunicateTopology, HybridCommunicateGroup,
                        get_hybrid_communicate_group,
                        set_hybrid_communicate_group)
from .distributed_strategy import DistributedStrategy
from . import utils  # noqa: F401  (fleet.utils.recompute)
from . import dataset  # noqa: F401  (InMemoryDataset/QueueDataset)
from . import data_generator  # noqa: F401
from . import elastic  # noqa: F401
from . import metrics  # noqa: F401
from .dataset import InMemoryDataset, QueueDataset  # noqa: F401
from .data_generator import DataGenerator, MultiSlotDataGenerator  # noqa: F401
from ..meta_parallel.engine import HybridParallelTrainStep  # noqa: F401

__all__ = [
    "DistributedStrategy", "init", "distributed_model",
    "distributed_optimizer", "get_hybrid_communicate_group",
    "HybridParallelTrainStep", "UserDefinedRoleMaker", "PaddleCloudRoleMaker",
    "InMemoryDataset", "QueueDataset", "DataGenerator",
    "MultiSlotDataGenerator", "init_server", "run_server", "init_worker",
    "stop_worker", "is_server", "is_worker", "save_persistables",
    "load_persistables",
]


class _FleetState:
    def __init__(self):
        self.initialized = False
        self.strategy: Optional[DistributedStrategy] = None
        self.is_collective = True
        self.env: Optional[ParallelEnv] = None


_state = _FleetState()


class PaddleCloudRoleMaker:
    """Env-var role maker (reference `fleet/base/role_maker.py`).

    Collective mode: rank/world from the trainer env. PS mode
    (is_collective=False): role from TRAINING_ROLE (TRAINER | PSERVER) and
    server list from PADDLE_PSERVERS_IP_PORT_LIST — the launcher's PS
    controller env contract (reference launch/controllers/ps.py)."""

    def __init__(self, is_collective=True, **kwargs):
        self._is_collective = is_collective
        self._env = ParallelEnv()

    def worker_index(self):
        return self._env.rank

    def worker_num(self):
        return self._env.world_size

    def is_worker(self):
        from ..ps import runtime as ps_runtime
        return self._is_collective or ps_runtime.is_worker()

    def is_server(self):
        from ..ps import runtime as ps_runtime
        return (not self._is_collective) and ps_runtime.is_server()

    def is_first_worker(self):
        return self._env.rank == 0


UserDefinedRoleMaker = PaddleCloudRoleMaker


def init(role_maker=None, is_collective=True,
         strategy: Optional[DistributedStrategy] = None, log_level="INFO"):
    """fleet.init (reference fleet_base.py:206)."""
    if role_maker is not None:
        is_collective = getattr(role_maker, "_is_collective", is_collective)
    _state.strategy = strategy or DistributedStrategy()
    _state.is_collective = is_collective
    if not is_collective:
        # PS mode: no collective mesh; roles resolved via ps.runtime env
        _state.initialized = True
        return None
    _state.env = init_parallel_env()
    dims = _state.strategy.mesh_dims()
    if get_hybrid_communicate_group() is None or any(
            v > 1 for v in dims.values()):
        hcg = HybridCommunicateGroup(dims=dims)
        hcg.sp_mode = _state.strategy.hybrid_configs.get("sp_mode", "ring")
        set_hybrid_communicate_group(hcg)
    _state.initialized = True
    return None


def is_first_worker() -> bool:
    return worker_index() == 0


def worker_index() -> int:
    if not _state.is_collective:
        from ..ps import runtime as ps_runtime
        return ps_runtime.trainer_id()
    return jax.process_index()


def worker_num() -> int:
    if not _state.is_collective:
        from ..ps import runtime as ps_runtime
        return ps_runtime.num_trainers()
    return jax.process_count()


def barrier_worker():
    if not _state.is_collective:
        from ..ps import runtime as ps_runtime
        ps_runtime.barrier_worker()
        return
    from .. import collective
    collective.barrier()


def distributed_model(model: Layer):
    """Wrap per topology (reference fleet_base.py:932): pure-DP gets
    DataParallel; mp/pp/sharding models are driven by
    HybridParallelTrainStep (annotations already on the parallel layers)."""
    assert _state.initialized, "call fleet.init first"
    hcg = get_hybrid_communicate_group()
    if hcg is None:
        return model
    if hcg.get_parallel_mode() == "data_parallel" and \
            hcg.get_data_parallel_world_size() > 1:
        return DataParallel(model)
    return model


def distributed_optimizer(optimizer, strategy=None):
    """reference fleet_base.py:875 — on TPU the optimizer needs no wrapping
    (grad sync is the partitioner's job); kept for API parity."""
    if strategy is not None:
        _state.strategy = strategy
    optimizer._hybrid_strategy = _state.strategy
    return optimizer


def get_strategy() -> Optional[DistributedStrategy]:
    return _state.strategy


# ------------------------- parameter-server mode ---------------------------
# reference fleet_base.py: init_server:? / run_server / init_worker:617 /
# stop_worker — delegated to the native PS runtime (distributed/ps/runtime.py)

def is_server() -> bool:
    from ..ps import runtime as ps_runtime
    return (not _state.is_collective) and ps_runtime.is_server()


def is_worker() -> bool:
    from ..ps import runtime as ps_runtime
    return _state.is_collective or ps_runtime.is_worker()


def init_server(*args, **kwargs):
    from ..ps import runtime as ps_runtime
    return ps_runtime.init_server(*args, **kwargs)


def run_server():
    from ..ps import runtime as ps_runtime
    return ps_runtime.run_server()


def init_worker(*args, **kwargs):
    from ..ps import runtime as ps_runtime
    return ps_runtime.init_worker(*args, **kwargs)


def stop_worker():
    from ..ps import runtime as ps_runtime
    return ps_runtime.stop_worker()


def save_persistables(executor=None, dirname=None, *a, **kw):
    """Accepts both paddle's (executor, dirname, ...) and plain (dirname)."""
    from ..ps import runtime as ps_runtime
    if dirname is None and isinstance(executor, str):
        executor, dirname = None, executor
    return ps_runtime.save_persistables(dirname)


def load_persistables(executor=None, dirname=None, *a, **kw):
    from ..ps import runtime as ps_runtime
    if dirname is None and isinstance(executor, str):
        executor, dirname = None, executor
    return ps_runtime.load_persistables(dirname)


def get_hybrid_parallel_train_step(model, loss_fn, optimizer, **kw):
    return HybridParallelTrainStep(model, loss_fn, optimizer,
                                   strategy=_state.strategy, **kw)


# sub-namespace parity: fleet.meta_parallel.*
from .. import meta_parallel  # noqa: E402,F401
