"""DataGenerator — user hook that turns raw logs into MultiSlot lines.

Reference: `fleet/data_generator/data_generator.py`
(/root/reference/python/paddle/distributed/fleet/data_generator/): users
subclass and implement `generate_sample(line)` yielding
[(slot_name, [values]), ...]; `run_from_stdin` serializes to the MultiSlot
text protocol consumed by the native feed (`_native/csrc/datafeed.cc`):
per slot `<n> <v1> ... <vn>`, space-separated, one instance per line.
"""
from __future__ import annotations

import sys
from typing import Iterable, List, Sequence, Tuple

Sample = Sequence[Tuple[str, Sequence]]


class DataGenerator:
    def __init__(self):
        self._batch = 1

    def set_batch(self, batch: int):
        self._batch = batch

    # -- user hooks ---------------------------------------------------------
    def generate_sample(self, line):
        """Override: return a generator yielding one or more samples, each
        `[(slot_name, [values...]), ...]` in the feed's slot order."""
        raise NotImplementedError(
            "implement generate_sample(line) in your DataGenerator subclass")

    def generate_batch(self, samples):
        """Optional override for batch-level rewrites (negative sampling...)."""
        for s in samples:
            yield s

    # -- serialization ------------------------------------------------------
    @staticmethod
    def _serialize(sample: Sample) -> str:
        parts: List[str] = []
        for _, values in sample:
            parts.append(str(len(values)))
            parts.extend(str(v) for v in values)
        return " ".join(parts)

    def process(self, lines: Iterable[str]) -> Iterable[str]:
        buf = []
        for line in lines:
            gen = self.generate_sample(line)
            if gen is None:
                continue
            for sample in gen() if callable(gen) else gen:
                if sample is None:
                    continue
                buf.append(sample)
                if len(buf) == self._batch:
                    for s in self.generate_batch(buf):
                        yield self._serialize(s)
                    buf = []
        for s in self.generate_batch(buf):
            yield self._serialize(s)

    def run_from_stdin(self):
        for out in self.process(sys.stdin):
            sys.stdout.write(out + "\n")

    def run_from_file(self, path: str, out_path: str):
        with open(path) as fin, open(out_path, "w") as fout:
            for out in self.process(fin):
                fout.write(out + "\n")


MultiSlotDataGenerator = DataGenerator
