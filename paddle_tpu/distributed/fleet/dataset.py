"""Dataset wrappers over the native multi-threaded data feed.

Reference: `paddle.distributed.fleet` dataset API
(/root/reference/python/paddle/distributed/fleet/dataset/dataset.py wrapping
C++ `MultiSlotDataset`, `framework/data_set.h:47`): `InMemoryDataset`
(load_into_memory + local_shuffle, PS/CTR training) and `QueueDataset`
(streaming). Batches come back as numpy per slot: sparse slots as
(values uint64, lod int64 offsets) ragged pairs; float slots reshaped
[batch, dim] when rectangular.

`SlotBatch.padded(slot, max_len)` converts a ragged sparse slot to a fixed
[batch, max_len] id matrix + mask — the TPU-side bridge, since XLA wants
static shapes (SURVEY §7 "dynamic shapes" hard part).
"""
from __future__ import annotations

import ctypes
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from ... import _native

_U64P = ctypes.POINTER(ctypes.c_uint64)
_F32P = ctypes.POINTER(ctypes.c_float)
_I64P = ctypes.POINTER(ctypes.c_int64)


class SlotBatch:
    """One assembled batch; per-slot ragged or dense numpy views."""

    def __init__(self, num_instances: int, slots: Sequence[str],
                 values: Dict[str, np.ndarray], lods: Dict[str, np.ndarray]):
        self.batch_size = num_instances
        self.slots = list(slots)
        self._values = values
        self._lods = lods

    def values(self, slot: str) -> np.ndarray:
        return self._values[slot]

    def lod(self, slot: str) -> np.ndarray:
        return self._lods[slot]

    def dense(self, slot: str) -> np.ndarray:
        """Rectangular view [batch, dim]; raises if ragged."""
        v, lod = self._values[slot], self._lods[slot]
        widths = np.diff(lod)
        if widths.size and not (widths == widths[0]).all():
            raise ValueError(f"slot {slot} is ragged; use padded()")
        dim = int(widths[0]) if widths.size else 0
        return v.reshape(self.batch_size, dim)

    def padded(self, slot: str, max_len: int,
               pad_value: int = 0) -> Tuple[np.ndarray, np.ndarray]:
        """Ragged sparse slot -> ([batch, max_len] ids, [batch, max_len] mask)."""
        v, lod = self._values[slot], self._lods[slot]
        out = np.full((self.batch_size, max_len), pad_value, v.dtype)
        mask = np.zeros((self.batch_size, max_len), np.float32)
        for i in range(self.batch_size):
            seg = v[lod[i]:lod[i + 1]][:max_len]
            out[i, :seg.size] = seg
            mask[i, :seg.size] = 1.0
        return out, mask


class DatasetBase:
    """Common config (reference DatasetBase, dataset.py)."""

    _mode = 0  # 0 queue, 1 memory

    def __init__(self):
        self._lib = _native.load()
        self._batch_size = 1
        self._thread_num = 1
        self._filelist: List[str] = []
        self._slots: List[str] = []
        self._slot_types: List[str] = []
        self._handle: Optional[int] = None

    def init(self, batch_size=1, thread_num=1, use_var=None, **kwargs):
        self.set_batch_size(batch_size)
        self.set_thread(thread_num)
        if use_var:
            self.set_use_var(use_var)

    def set_batch_size(self, batch_size: int):
        self._batch_size = int(batch_size)
        self._invalidate(stale_data=False)

    def set_thread(self, thread_num: int):
        self._thread_num = int(thread_num)

    def set_filelist(self, filelist: List[str]):
        self._filelist = list(filelist)
        self._invalidate(stale_data=True)

    def _invalidate(self, stale_data: bool):
        """Config changed: drop the native feed so it is rebuilt with the new
        config on next use (a kept handle would silently serve the old one).
        Subclasses holding loaded data decide whether it must be re-loaded."""
        if self._handle is not None:
            self._lib.feed_destroy(self._handle)
            self._handle = None

    def set_use_var(self, slots, types: Optional[List[str]] = None):
        """slots: names in file order; types: 'uint64' (default) or 'float'."""
        self._slots = [getattr(s, "name", s) for s in slots]
        if types is None:
            types = ["uint64"] * len(self._slots)
        if len(types) != len(self._slots):
            raise ValueError(
                f"set_use_var: {len(self._slots)} slots but {len(types)} types")
        bad = [t for t in types if t not in ("uint64", "float")]
        if bad:
            raise ValueError(f"set_use_var: unknown slot types {bad}")
        self._slot_types = list(types)
        self._invalidate(stale_data=True)

    def _ensure_feed(self):
        if self._handle is not None:
            return
        n = len(self._slots)
        if n == 0:
            raise RuntimeError("set_use_var first")
        arr = (ctypes.c_int * n)(*[1 if t == "float" else 0
                                   for t in self._slot_types])
        self._handle = self._lib.feed_create(n, arr, self._batch_size)
        files = (ctypes.c_char_p * len(self._filelist))(
            *[f.encode() for f in self._filelist])
        self._lib.feed_set_filelist(self._handle, files, len(self._filelist))

    def _fetch(self, bh: int) -> Optional[SlotBatch]:
        if bh < 0:
            return None
        lib = self._lib
        n_ins = lib.feed_batch_num_instances(bh)
        values, lods = {}, {}
        for s, (name, typ) in enumerate(zip(self._slots, self._slot_types)):
            nv = lib.feed_batch_slot_values(bh, s)
            lod = np.empty(n_ins + 1, np.int64)
            lib.feed_batch_copy_lod(bh, s, lod.ctypes.data_as(_I64P))
            if typ == "float":
                v = np.empty(nv, np.float32)
                if nv:
                    lib.feed_batch_copy_f32(bh, s, v.ctypes.data_as(_F32P))
            else:
                v = np.empty(nv, np.uint64)
                if nv:
                    lib.feed_batch_copy_u64(bh, s, v.ctypes.data_as(_U64P))
            values[name], lods[name] = v, lod
        lib.feed_release_batch(bh)
        return SlotBatch(int(n_ins), self._slots, values, lods)


class QueueDataset(DatasetBase):
    """Streaming dataset (reference QueueDataset): worker threads tail the
    file list; iteration yields batches until EOF."""

    _mode = 0

    def __iter__(self) -> Iterator[SlotBatch]:
        self._ensure_feed()
        self._lib.feed_start(self._handle, self._thread_num)
        try:
            while True:
                b = self._fetch(self._lib.feed_next_batch(self._handle, 0))
                if b is None:
                    break
                yield b
            if self._lib.feed_has_error(self._handle):
                raise RuntimeError(
                    "QueueDataset: a worker hit a malformed file; epoch is "
                    "incomplete (check the MultiSlot format of the filelist)")
        finally:
            # teardown even on early exit (break / GeneratorExit), else the
            # next epoch would serve leftover batches from this one
            self._lib.feed_destroy(self._handle)
            self._handle = None


class InMemoryDataset(DatasetBase):
    """Load-then-shuffle dataset (reference InMemoryDataset,
    `data_set.h` in-memory shuffle contract)."""

    _mode = 1

    def __init__(self):
        super().__init__()
        self._loaded = False

    def _invalidate(self, stale_data: bool):
        was_loaded = self._loaded and self._handle is not None
        super()._invalidate(stale_data)
        if stale_data:
            # new filelist/slots: the loaded epoch is meaningless now
            self._loaded = False
        elif was_loaded:
            # serving-param change (batch size): transparently re-load so the
            # data does not silently vanish with the destroyed feed
            self.load_into_memory()

    def load_into_memory(self):
        self._ensure_feed()
        rc = self._lib.feed_load_into_memory(self._handle, self._thread_num)
        if rc != 0:
            raise RuntimeError("load_into_memory failed (bad file or format)")
        self._loaded = True

    def local_shuffle(self, seed: int = 0):
        self._ensure_feed()
        self._lib.feed_local_shuffle(self._handle, seed)

    def get_memory_data_size(self) -> int:
        self._ensure_feed()
        return int(self._lib.feed_memory_size(self._handle))

    def release_memory(self):
        if self._handle is not None:
            self._lib.feed_destroy(self._handle)  # frees the loaded instances
            self._handle = None
        self._loaded = False

    def __iter__(self) -> Iterator[SlotBatch]:
        if not self._loaded:
            raise RuntimeError(
                "InMemoryDataset: call load_into_memory() before iterating "
                "(set_filelist/set_use_var reset any previously loaded data)")
        self._ensure_feed()
        self._lib.feed_reset_memory_cursor(self._handle)
        while True:
            b = self._fetch(self._lib.feed_next_batch(self._handle, 1))
            if b is None:
                break
            yield b
