"""Filesystem abstraction for checkpoint/model IO.

Reference: `python/paddle/distributed/fleet/utils/fs.py` (`LocalFS`,
`HDFSClient` shelling to the hadoop CLI) over C++ `framework/io/fs.cc`.
LocalFS is fully implemented; HDFSClient keeps the exact API and delegates
to a `hadoop fs` binary when one exists (none in this environment — then
every call raises with guidance rather than silently no-oping).
"""
from __future__ import annotations

import os
import shutil
import subprocess
from typing import List, Optional, Tuple


class ExecuteError(Exception):
    pass


class FSFileExistsError(Exception):
    pass


class FSFileNotExistsError(Exception):
    pass


class FS:
    def ls_dir(self, fs_path):
        raise NotImplementedError

    def is_dir(self, fs_path):
        raise NotImplementedError

    def is_file(self, fs_path):
        raise NotImplementedError

    def is_exist(self, fs_path):
        raise NotImplementedError

    def upload(self, local_path, fs_path):
        raise NotImplementedError

    def download(self, fs_path, local_path):
        raise NotImplementedError

    def mkdirs(self, fs_path):
        raise NotImplementedError

    def delete(self, fs_path):
        raise NotImplementedError

    def mv(self, fs_src_path, fs_dst_path, overwrite=False):
        raise NotImplementedError


class LocalFS(FS):
    """reference fs.py LocalFS."""

    def ls_dir(self, fs_path) -> Tuple[List[str], List[str]]:
        if not self.is_exist(fs_path):
            return [], []
        dirs, files = [], []
        for name in sorted(os.listdir(fs_path)):
            (dirs if os.path.isdir(os.path.join(fs_path, name))
             else files).append(name)
        return dirs, files

    def is_dir(self, fs_path) -> bool:
        return os.path.isdir(fs_path)

    def is_file(self, fs_path) -> bool:
        return os.path.isfile(fs_path)

    def is_exist(self, fs_path) -> bool:
        return os.path.exists(fs_path)

    def mkdirs(self, fs_path):
        os.makedirs(fs_path, exist_ok=True)

    def rename(self, fs_src_path, fs_dst_path):
        os.rename(fs_src_path, fs_dst_path)

    def delete(self, fs_path):
        if self.is_dir(fs_path):
            shutil.rmtree(fs_path)
        elif self.is_file(fs_path):
            os.remove(fs_path)

    def need_upload_download(self) -> bool:
        return False

    def upload(self, local_path, fs_path):
        shutil.copy(local_path, fs_path)

    def download(self, fs_path, local_path):
        shutil.copy(fs_path, local_path)

    def touch(self, fs_path, exist_ok=True):
        if self.is_exist(fs_path) and not exist_ok:
            raise FSFileExistsError(fs_path)
        open(fs_path, "a").close()

    def mv(self, src_path, dst_path, overwrite=False, test_exists=False):
        if not overwrite and self.is_exist(dst_path):
            raise FSFileExistsError(dst_path)
        os.replace(src_path, dst_path)

    def list_dirs(self, fs_path) -> List[str]:
        return self.ls_dir(fs_path)[0]


class HDFSClient(FS):
    """reference fs.py HDFSClient — shells out to `hadoop fs`."""

    def __init__(self, hadoop_home: Optional[str] = None, configs=None,
                 time_out=5 * 60 * 1000, sleep_inter=1000):
        self._hadoop = None
        home = hadoop_home or os.environ.get("HADOOP_HOME")
        if home:
            cand = os.path.join(home, "bin", "hadoop")
            if os.path.exists(cand):
                self._hadoop = cand
        if self._hadoop is None:  # PATH fallback even when HADOOP_HOME is stale
            self._hadoop = shutil.which("hadoop")
        self._configs = configs or {}

    def _run(self, *args) -> str:
        if self._hadoop is None:
            raise ExecuteError(
                "no hadoop binary found (set HADOOP_HOME); this environment "
                "has no HDFS — use LocalFS or mount the data locally")
        cfg = []
        for k, v in self._configs.items():
            cfg += ["-D", f"{k}={v}"]
        out = subprocess.run([self._hadoop, "fs"] + cfg + list(args),
                             capture_output=True, text=True)
        if out.returncode != 0:
            raise ExecuteError(out.stderr.strip())
        return out.stdout

    def is_exist(self, fs_path) -> bool:
        try:
            self._run("-test", "-e", fs_path)
            return True
        except ExecuteError:
            return False

    def is_dir(self, fs_path) -> bool:
        try:
            self._run("-test", "-d", fs_path)
            return True
        except ExecuteError:
            return False

    def is_file(self, fs_path) -> bool:
        return self.is_exist(fs_path) and not self.is_dir(fs_path)

    def ls_dir(self, fs_path):
        out = self._run("-ls", fs_path)
        dirs, files = [], []
        for line in out.splitlines():
            parts = line.split()
            if len(parts) < 8:
                continue
            name = os.path.basename(parts[-1])
            (dirs if parts[0].startswith("d") else files).append(name)
        return dirs, files

    def mkdirs(self, fs_path):
        self._run("-mkdir", "-p", fs_path)

    def delete(self, fs_path):
        self._run("-rm", "-r", "-f", fs_path)

    def upload(self, local_path, fs_path):
        self._run("-put", "-f", local_path, fs_path)

    def download(self, fs_path, local_path):
        self._run("-get", fs_path, local_path)

    def mv(self, fs_src_path, fs_dst_path, overwrite=False):
        if overwrite and self.is_exist(fs_dst_path):
            self.delete(fs_dst_path)
        self._run("-mv", fs_src_path, fs_dst_path)

    def need_upload_download(self) -> bool:
        return True


__all__ = ["FS", "LocalFS", "HDFSClient", "ExecuteError",
           "FSFileExistsError", "FSFileNotExistsError"]
