"""fleet.utils — activation recomputation (checkpointing).

Reference: `RecomputeFunction`
(`/root/reference/python/paddle/distributed/fleet/utils/recompute.py:199`,
eager variant at `:65`) — a PyLayer that stashes RNG state + inputs in
forward and replays the segment under grad in backward. TPU-native:
`jax.checkpoint` IS that mechanism (residuals = inputs, recompute in the
vjp), so `recompute(fn, *args)` wraps the segment in `jax.checkpoint` and
routes it through the op tape; the RNG key is an explicit input, which
gives exact dropout replay for free (no CUDA RNG-state juggling).

Layers are discovered from `function` itself, its `__self__`, and its
closure cells / partial args, so `recompute(self.block, x)` and
`recompute(lambda a: self.block(a), x)` both thread the right parameters
through the checkpointed vjp. Params of collected layers that the segment
does not touch receive zero gradients (not None) — same caveat as the
reference's `detach`-based capture.

Works in eager mode (tape records the checkpointed vjp) and under the
compiled engine (`jax.checkpoint` composes with jit/grad/scan).
"""
from __future__ import annotations

import functools
from typing import Any, List

import jax

from ....framework import random as random_mod
from ....framework.tensor import Tensor
from ....nn.layer import Layer
from ....ops import _dispatch as _d

__all__ = ["recompute"]


def _collect_layers(function) -> List[Layer]:
    """Layers reachable from `function`: itself, bound owner, closure cells,
    functools.partial payload (one level — the reference captures whatever
    autograd sees; this captures whatever the callable references)."""
    found: List[Layer] = []
    seen = set()

    def add(obj):
        if isinstance(obj, Layer) and id(obj) not in seen:
            seen.add(id(obj))
            found.append(obj)

    add(function)
    add(getattr(function, "__self__", None))
    if isinstance(function, functools.partial):
        add(function.func)
        add(getattr(function.func, "__self__", None))
        for a in function.args:
            add(a)
        for a in function.keywords.values():
            add(a)
    for cell in (getattr(function, "__closure__", None) or ()):
        try:
            add(cell.cell_contents)
        except ValueError:
            pass
    return found


def recompute(function, *args, preserve_rng_state: bool = True,
              use_reentrant: bool = True, policy=None, **kwargs):
    """Run `function(*args)` without saving intermediate activations;
    re-run it during backward (reference recompute.py:199 semantics).

    `preserve_rng_state` is accepted for parity; RNG replay is exact either
    way here (the key is a checkpointed input). `policy` (a
    `jax.checkpoint_policies` predicate, e.g. `dots_with_no_batch_dims_saveable`)
    selects SELECTIVE remat: matmul outputs are saved, elementwise chains
    (gelu, layernorm internals) recompute in backward — trades a few VPU
    flops for the HBM round trips of their residuals."""
    from ....jit import _swapped_state
    from ....framework import tape as tape_mod

    layers = _collect_layers(function)
    rng = random_mod.next_key()

    # Tensor kwargs must be checkpointed inputs (not baked constants) or
    # their gradients would silently vanish
    kw_names = [k for k, v in kwargs.items() if isinstance(v, Tensor)]
    kw_tensors = [kwargs[k] for k in kw_names]
    static_kwargs = {k: v for k, v in kwargs.items() if k not in kw_names}

    # merged parameter/buffer views, prefixed per layer
    named: dict = {}
    buffers_by_layer = []
    for li, layer in enumerate(layers):
        for k, p in layer.named_parameters():
            named[f"{li}::{k}"] = p
        buffers_by_layer.append({k: b.data for k, b in
                                 layer.named_buffers()})
    keys = list(named)

    # buffer updates (BatchNorm running stats) produced INSIDE the
    # checkpointed region must come back out as extra outputs and be
    # written to the live layers, or recompute silently freezes them
    buf_names = [(li, k) for li, layer in enumerate(layers)
                 for k, _ in layer.named_buffers()]
    shape_info = {"n_out": None, "tuple_out": False}

    def impl(rng_key, *arrs):
        import contextlib
        pvals = arrs[:len(keys)]
        rest = arrs[len(keys):]
        inputs = rest[:len(rest) - len(kw_names)]
        kw_vals = rest[len(rest) - len(kw_names):]
        with contextlib.ExitStack() as st:
            st.enter_context(tape_mod.no_grad())
            ctxs = []
            for li, layer in enumerate(layers):
                pref = f"{li}::"
                sub = {k[len(pref):]: v for k, v in
                       zip(keys, pvals) if k.startswith(pref)}
                ctxs.append(st.enter_context(
                    _swapped_state(layer, sub, buffers_by_layer[li])))
            st.enter_context(random_mod.rng_scope(rng_key))
            out = function(*[Tensor(a) for a in inputs],
                           **dict(zip(kw_names,
                                      (Tensor(a) for a in kw_vals))),
                           **static_kwargs)
            new_bufs = []
            for li, _layer in enumerate(layers):
                swapped = dict(ctxs[li].items()) if hasattr(
                    ctxs[li], "items") else {}
                for (bl, bk) in buf_names:
                    if bl == li:
                        t = swapped.get(bk)
                        new_bufs.append(t.data if t is not None
                                        else buffers_by_layer[li][bk])
        if isinstance(out, (tuple, list)):
            outs = tuple(o.data if isinstance(o, Tensor) else o for o in out)
            shape_info["tuple_out"] = True
        else:
            outs = (out.data if isinstance(out, Tensor) else out,)
        shape_info["n_out"] = len(outs)
        return outs + tuple(new_bufs)

    tensors = [rng] + [named[k] for k in keys] + list(args) + kw_tensors
    ckpt = (jax.checkpoint(impl) if policy is None
            else jax.checkpoint(impl, policy=policy))
    res = _d.call(ckpt, tensors, name="recompute")
    if not buf_names and not shape_info["tuple_out"]:
        return res if not isinstance(res, (tuple, list)) else res[0]
    res = res if isinstance(res, (tuple, list)) else (res,)
    n_out = shape_info["n_out"]
    out_part, buf_part = res[:n_out], res[n_out:]
    for (li, bk), val in zip(buf_names, buf_part):
        named_b = dict(layers[li].named_buffers())
        if bk in named_b:
            named_b[bk].data = (val.data if isinstance(val, Tensor) else val)
    if shape_info["tuple_out"]:
        return tuple(out_part)
    return out_part[0]

from . import fs  # noqa: F401,E402
from .fs import LocalFS, HDFSClient  # noqa: F401,E402
