"""Lease-based leader election + term fencing for the FleetController.

Every self-healing policy PRs 13/18 built (evict/readmit, coordinated
rollback, serving wedge-restart, swap-rollback) ran on ONE supervisor —
kill it and the fleet loses its brain mid-incident. This module makes
the control plane itself highly available over the same retry-wrapped
TCPStore the runtime already trusts, with no new dependency:

* **Lease** — the leader holds ``ctl/leader/lease``, a JSON record
  ``{id, term, beat}`` it rewrites every ``ttl/3`` seconds with a fresh
  ``beat`` sequence number. Standbys judge freshness by the VALUE
  CHANGING between their own polls on their own monotonic clock — the
  same skew-immune convention as the probation ``ready_value`` channel;
  comparing the holder's wall clock to ours would read a dead leader as
  alive (or a live one as dead) under cross-host clock skew.
* **Term** — a fleet-monotonic epoch from the store's atomic counter
  ``ctl/leader/term``. Acquiring bumps it; the new value rides in the
  lease record and in EVERY actuation the leader issues (elastic
  commands, serving ``restart``/``set_queue_limit``/``try_swap``). A
  deposed leader that pauses mid-actuation and resumes after a takeover
  carries a stale term and is rejected (fenced) — terms may skip values
  when the at-least-once store retry double-counts an ``add``, which is
  harmless: only ordering matters, not density.
* **Takeover** — a standby that watched the lease value stay frozen for
  one full TTL bumps the term, writes its own record, and re-reads to
  confirm (last-writer-wins resolves acquire races; the loser observes a
  foreign record and stays standby). Before each renew the holder
  re-reads the record and DEMOTES itself if a higher term appears — the
  two-leaders window after a pause/resume closes at the deposed
  leader's next renew, and fencing covers the window itself.
* **Self-fencing** — a leader whose renews keep failing (store down,
  injected ``controller.lease`` fault) demotes itself once its last
  successful renew is a full TTL old: it can no longer prove the fleet
  hasn't elected someone else, so it must stop actuating.

Standby registry: each controller claims a slot via the atomic
``ctl/leader/nmembers`` counter and beats ``ctl/leader/member/<slot>``
(the store has no key listing). ``standby_count`` = fresh member beats
minus the leader — surfaced at ``/controller`` and in ``obs_tail
--controller`` so an operator sees at a glance whether failover cover
actually exists.

In-process fencing gate: serving actuators run in the leader's own
process (no command bus), so :func:`check_term` fences against a
module-level high-water mark of every term this process has observed
(:func:`note_term` — fed by lease renews/observations and by applied
commands). Elastic supervisors fence commands against
:func:`lease_term` (the record's CURRENT term read from the store) —
never against the raw counter: a standby that bumps the counter but
loses the lease-write race would otherwise falsely fence the real
leader.

Knobs: ``PADDLE_TPU_CONTROLLER_LEASE_TTL`` (seconds, default 5.0) and
``PADDLE_TPU_CONTROLLER_STANDBYS`` (expected standby count, default 0 —
purely informational: surfaced in status so dashboards can alert when
actual < expected).
"""
from __future__ import annotations

import json
import os
import threading
import time
import warnings
from typing import Optional

from ...profiler import events as _events_mod
from ...profiler import metrics as _metrics_mod
from ...utils.envparse import env_float as _env_float
from ...utils.envparse import env_int as _env_int

__all__ = ["LeaderLease", "ControllerFencedError", "note_term",
           "check_term", "lease_term", "LEASE_KEY", "TERM_KEY"]

LEASE_KEY = "ctl/leader/lease"
TERM_KEY = "ctl/leader/term"
NMEMBERS_KEY = "ctl/leader/nmembers"
MEMBER_KEY_FMT = "ctl/leader/member/{slot}"
LEDGER_KEY = "ctl/ledger"

_REG = _metrics_mod.default_registry()
_M_TERM = _REG.gauge(
    "controller_leader_term",
    "fencing term of the lease this controller currently holds (or last "
    "held) — fleet-monotonic; a step up means a takeover happened")
_M_TAKEOVERS = _REG.counter(
    "controller_takeovers_total",
    "successful leadership acquisitions, by reason (bootstrap: no lease "
    "existed / lease_expired: the previous holder's beat went stale)")
_M_FENCED = _REG.counter(
    "controller_fenced_total",
    "actuations rejected for carrying a stale term, by policy of the "
    "fenced command (a deposed leader tried to act after a takeover)")


class ControllerFencedError(RuntimeError):
    """An actuation carried a term older than one this process has
    already observed — the issuer was deposed; the action must not run."""


# --- in-process fencing gate -------------------------------------------
# Serving actuators (engine.restart / set_queue_limit / hotswap.try_swap)
# execute inside the controller process itself, so there is no command
# bus to fence at. Instead every lease renew/observation and every
# applied command raises this process-wide high-water mark, and the
# actuators call check_term() before touching anything.
_gate_lock = threading.Lock()
_term_high_water = 0


def note_term(term: Optional[int]):
    """Raise the process-wide term high-water mark (monotonic)."""
    global _term_high_water
    if term is None:
        return
    with _gate_lock:
        if int(term) > _term_high_water:
            _term_high_water = int(term)


def term_high_water() -> int:
    with _gate_lock:
        return _term_high_water


def reset_gate():
    """Test hook: forget every observed term (process-wide)."""
    global _term_high_water
    with _gate_lock:
        _term_high_water = 0


def check_term(term: Optional[int], policy: str = "serving"):
    """Fence an in-process actuation. ``term=None`` (no controller /
    operator-issued) always passes — fencing only rejects an actuation
    that CLAIMS an epoch and claims a stale one."""
    if term is None:
        return
    hw = term_high_water()
    if int(term) < hw:
        if _metrics_mod.enabled():
            _M_FENCED.inc(policy=policy)
        _events_mod.emit("controller_fenced", severity="warn",
                         policy=policy, term=int(term), current_term=hw)
        raise ControllerFencedError(
            f"stale controller term {int(term)} < {hw} for {policy!r}: "
            f"issuer was deposed; actuation rejected")


def lease_term(store) -> Optional[int]:
    """Term in the CURRENT lease record, or None (no lease / store
    blip). This — not the raw ``ctl/leader/term`` counter — is what
    command consumers fence against: a failed acquirer bumps the counter
    without ever holding the key."""
    try:
        if not store.check(LEASE_KEY):
            return None
        rec = json.loads(store.get(LEASE_KEY).decode())
        return int(rec["term"])
    except Exception:
        return None


class LeaderLease:
    """One controller's handle on the leadership lease. Drive it with
    :meth:`tick` at the aggregator-poll cadence; it acquires, renews,
    observes, and demotes as the store's lease record dictates.

    The very first tick of the very first controller acquires
    immediately (reason ``bootstrap``); after that a takeover costs one
    full TTL of observed silence."""

    def __init__(self, store, *, controller_id: Optional[str] = None,
                 ttl: Optional[float] = None,
                 expected_standbys: Optional[int] = None,
                 register: bool = True):
        from ...profiler.events import host_id
        self.store = store
        self.id = controller_id or f"{host_id()}:{os.getpid()}"
        self.ttl = float(ttl) if ttl is not None else _env_float(
            "PADDLE_TPU_CONTROLLER_LEASE_TTL", 5.0)
        self.expected_standbys = (
            int(expected_standbys) if expected_standbys is not None
            else _env_int("PADDLE_TPU_CONTROLLER_STANDBYS", 0))
        self.term = 0                 # term of the lease we hold/held
        self.takeovers = 0
        self._leader = False
        self._beat_seq = 0
        self._last_renew_ok = 0.0     # monotonic; 0 = never
        self._renew_failures = 0
        # standby-side freshness: (raw lease value, monotonic ts it was
        # first seen) — staleness is silence on OUR clock, never theirs
        self._obs: Optional[tuple] = None
        self._ever_saw_lease = False
        # member slot (standby registry)
        self._slot: Optional[int] = None
        self._member_obs: dict = {}   # slot -> (value, monotonic ts)
        self._standbys = 0
        if register:
            try:
                self._slot = self.store.add(NMEMBERS_KEY, 1) - 1
            except Exception:
                self._slot = None     # registry is best-effort cosmetics

    # -- leadership ------------------------------------------------------

    @property
    def is_leader(self) -> bool:
        return self._leader

    def tick(self) -> Optional[str]:
        """One election step. Returns ``"acquired"`` on a takeover this
        tick (the controller must reload the replicated ledger),
        ``"demoted"`` on losing leadership, else None."""
        self._beat_member()
        self._count_standbys()
        if self._leader:
            return self._tick_leader()
        return self._tick_standby()

    def _tick_leader(self) -> Optional[str]:
        now = time.monotonic()
        if now - self._last_renew_ok < self.ttl / 3.0:
            return None
        # read-before-renew: a higher term in the record means the fleet
        # elected someone else while we were paused — stand down without
        # clobbering the new leader's lease
        rec = self._read()
        if rec is not None and int(rec.get("term", 0)) > self.term:
            note_term(int(rec["term"]))
            self._demote("superseded by term %d" % int(rec["term"]))
            return "demoted"
        try:
            self._write_lease(renew=True)
            self._last_renew_ok = now
            self._renew_failures = 0
        except Exception as e:
            self._renew_failures += 1
            # self-fence: past a full TTL of failed renews we can no
            # longer prove nobody else took over — stop actuating
            if now - self._last_renew_ok > self.ttl:
                self._demote(f"renew failed {self._renew_failures}x "
                             f"({type(e).__name__}: {e})")
                return "demoted"
        return None

    def _tick_standby(self) -> Optional[str]:
        raw = self._read_raw()
        now = time.monotonic()
        if raw is None:
            # no lease at all: bootstrap (or the holder released it)
            if self._acquire("bootstrap" if not self._ever_saw_lease
                             else "lease_expired"):
                return "acquired"
            return None
        self._ever_saw_lease = True
        try:
            note_term(int(json.loads(raw.decode())["term"]))
        except Exception:
            pass
        if self._obs is None or self._obs[0] != raw:
            self._obs = (raw, now)    # value changed: holder is alive
            return None
        if now - self._obs[1] > self.ttl:
            if self._acquire("lease_expired"):
                return "acquired"
            self._obs = None          # lost the race: re-arm the timer
        return None

    def _acquire(self, reason: str) -> bool:
        try:
            term = int(self.store.add(TERM_KEY, 1))
            self.term = term
            self._write_lease(renew=False)
            rec = self._read()        # last-writer-wins: confirm it's us
            if rec is None or rec.get("id") != self.id or \
                    int(rec.get("term", -1)) != term:
                note_term(int(rec["term"]) if rec else None)
                return False
        except Exception as e:
            warnings.warn(f"controller lease acquire failed: {e}")
            return False
        self._leader = True
        self._last_renew_ok = time.monotonic()
        self._renew_failures = 0
        self.takeovers += 1
        note_term(term)
        if _metrics_mod.enabled():
            _M_TERM.set(term)
            _M_TAKEOVERS.inc(reason=reason)
        _events_mod.emit("controller_takeover", severity="warn",
                         leader=self.id, term=term, reason=reason)
        return True

    def _demote(self, why: str):
        self._leader = False
        self._obs = None
        warnings.warn(f"controller {self.id} demoted (term {self.term}): "
                      f"{why}")

    def release(self):
        """Voluntary hand-off (clean shutdown): drop the lease key so a
        standby acquires on its next tick instead of waiting out a TTL."""
        if not self._leader:
            return
        self._leader = False
        try:
            self.store.delete_key(LEASE_KEY)
        except Exception:
            pass                      # standbys fall back to TTL expiry

    def _write_lease(self, renew: bool):
        if renew:
            from ...fault import site as _fault_site
            _fault_site("controller.lease")
        self._beat_seq += 1
        self.store.set(LEASE_KEY, json.dumps(
            {"id": self.id, "term": self.term, "beat": self._beat_seq}))

    def _read_raw(self) -> Optional[bytes]:
        try:
            if not self.store.check(LEASE_KEY):
                return None
            return self.store.get(LEASE_KEY)
        except Exception:
            return None               # store blip reads as "no news"

    def _read(self) -> Optional[dict]:
        raw = self._read_raw()
        if raw is None:
            return None
        try:
            return json.loads(raw.decode())
        except Exception:
            return None

    # -- standby registry ------------------------------------------------

    def _beat_member(self):
        if self._slot is None:
            return
        try:
            self.store.set(MEMBER_KEY_FMT.format(slot=self._slot),
                           repr(time.time()))
        except Exception:
            pass

    def _count_standbys(self):
        """Fresh member beats (value-change on our clock), minus the
        leader itself. Best-effort — a store blip keeps the last count."""
        try:
            n = int(self.store.add(NMEMBERS_KEY, 0))
        except Exception:
            return
        now = time.monotonic()
        alive = 0
        for slot in range(n):
            try:
                key = MEMBER_KEY_FMT.format(slot=slot)
                if not self.store.check(key):
                    continue
                val = self.store.get(key)
            except Exception:
                continue
            prev = self._member_obs.get(slot)
            if prev is None or prev[0] != val:
                self._member_obs[slot] = (val, now)
                alive += 1
            elif now - prev[1] <= max(self.ttl, 3.0):
                alive += 1
        self._standbys = max(0, alive - 1)

    # -- introspection ---------------------------------------------------

    def leader_id(self) -> Optional[str]:
        rec = self._read()
        return rec.get("id") if rec else None

    def lease_age_s(self) -> Optional[float]:
        """Seconds since WE last saw the lease value change (or renewed
        it ourselves). None until anything was observed."""
        if self._leader:
            return max(0.0, time.monotonic() - self._last_renew_ok)
        if self._obs is None:
            return None
        return max(0.0, time.monotonic() - self._obs[1])

    def standby_count(self) -> int:
        return self._standbys

    def status(self) -> dict:
        rec = self._read()
        return {
            "id": self.id,
            "is_leader": self._leader,
            "leader": rec.get("id") if rec else None,
            "term": int(rec["term"]) if rec else self.term,
            "lease_ttl_s": self.ttl,
            "lease_age_s": self.lease_age_s(),
            "standbys": self._standbys,
            "expected_standbys": self.expected_standbys,
            "takeovers": self.takeovers,
        }
