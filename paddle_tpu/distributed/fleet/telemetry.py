"""Fleet metrics plane: per-host step digests + rank-0 aggregation +
cross-host straggler detection.

The PR-5 multi-host runtime coordinates hosts (barrier, supervisor,
heter-PS pipeline) but gives the operator no cross-host view: which host is
slow, whose steps stalled, who aborted the round. This module closes it
with the same transport the runtime already trusts — the retry-wrapped
TCPStore:

* every host runs a :class:`FleetReporter`: per train step it folds the
  measured step wall into a rolling window and publishes a compact JSON
  digest under ``obs/digest/<rank>`` (step index, wall p50, data-wait
  fraction, barrier-wait and heter-stage seconds pulled from the local
  metrics registry) — one small ``store.set`` per step;
* rank 0 (and/or any supervisor holding a store connection) runs a
  :class:`FleetAggregator`: each ``collect()`` reads every rank's digest,
  mirrors it into the local registry as ``fleet_*`` gauges labeled
  ``host=`` (so the ObservabilityServer's `/metrics` serves the whole
  fleet from one scrape), and runs straggler detection: a host whose
  rolling step-wall p50 exceeds the fleet median by
  ``PADDLE_TPU_STRAGGLER_FACTOR`` (default 2.0) enters the straggler set,
  emitting exactly ONE ``fleet_straggler`` event (+
  ``fleet_straggler_total{host=}``) per excursion; it re-arms after the
  host returns under the threshold.

Chaos hook: ``FleetReporter.note_step`` declares the ``fleet.step`` fault
site — arm it with ``fleet.step=N:delay`` (sleep length
``PADDLE_TPU_FAULT_DELAY``) to turn any host into a straggler without
touching the model.
"""
from __future__ import annotations

import json
import os
import statistics
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from ...fault import site as _fault_site
from ...profiler import events as _events_mod
from ...profiler import metrics as _metrics_mod
from ...utils import envparse as _envparse

__all__ = ["FleetReporter", "FleetAggregator", "reporter_from_env",
           "aggregator_from_env", "DIGEST_KEY_FMT"]

DIGEST_KEY_FMT = "obs/digest/{rank}"

_REG = _metrics_mod.default_registry()
_M_LAST_STEP = _REG.gauge(
    "fleet_last_step",
    "newest step index each host's digest reports, by host")
_M_STEP_AGE = _REG.gauge(
    "fleet_step_age_seconds",
    "age of each host's newest digest at collect time, by host — a growing "
    "age with a fixed step means the host stalled or died")
_M_WALL_P50 = _REG.gauge(
    "fleet_step_wall_p50_seconds",
    "each host's rolling step-wall median from its digest, by host")
_M_DATA_WAIT = _REG.gauge(
    "fleet_data_wait_frac",
    "each host's reported DataLoader wait fraction, by host")
_M_STRAGGLER = _REG.counter(
    "fleet_straggler_total",
    "straggler excursions detected (host p50 exceeded fleet median by the "
    "configured factor), by host")
_M_HEALTH = _REG.gauge(
    "fleet_health_status",
    "training-health status code each host's digest reports "
    "(0 ok, 1 warn, 2 diverged), by host")

#: digest health_status string -> fleet_health_status gauge code
HEALTH_CODES = {"ok": 0, "warn": 1, "diverged": 2}


def _hist_sum(name: str) -> float:
    """Total seconds accumulated by a local histogram family (all series)."""
    m = _REG.get(name)
    if m is None:
        return 0.0
    try:
        return float(sum(v.get("sum", 0.0) for v in m.snapshot()["values"]))
    except Exception:
        return 0.0


class FleetReporter:
    """Publishes this host's per-step digest to the TCPStore.

    Drive it with :meth:`note_step` once per train step (the profiler's
    liveness tracker does this automatically when the reporter is
    installed); walls are measured between consecutive notes, or pass
    ``wall_s`` explicitly (tests, custom loops)."""

    def __init__(self, store, rank: int, window: Optional[int] = None,
                 min_interval_s: Optional[float] = None,
                 host: Optional[str] = None):
        self.store = store
        self.rank = int(rank)
        # the digest's host identity; overridable for multi-reporter tests
        # (every real rank is its own process with its own endpoint id)
        self.host = host or _events_mod.host_id()
        if window is None:
            window = _envparse.env_int("PADDLE_TPU_DIGEST_WINDOW", 20)
        self.walls: "deque[float]" = deque(maxlen=max(int(window), 2))
        if min_interval_s is None:
            # every note still feeds the rolling window, but the store RPC
            # is rate-limited: a per-step synchronous publish would sit in
            # the timed train/bench loop AND congest the one rendezvous
            # store the checkpoint barrier polls at fleet scale
            min_interval_s = _envparse.env_float(
                "PADDLE_TPU_DIGEST_INTERVAL", 0.5)
        self.min_interval_s = float(min_interval_s)
        self._last_note: Optional[float] = None
        self._last_publish = 0.0
        self._last_reader_wait = 0.0
        self._last_reader_ts: Optional[float] = None
        self._fail_streak = 0
        self._disabled = False

    #: consecutive publish failures before the reporter gives up (the
    #: store client already retries internally per call, so a streak this
    #: long means the store is gone, not hiccuping)
    MAX_FAIL_STREAK = 3

    def note_step(self, step: int, wall_s: Optional[float] = None):
        """Record one completed train step and (rate-limited) publish the
        digest. Never raises — telemetry must not take down training."""
        # chaos: an armed `fleet.step=N:delay` sleeps here, inflating the
        # measured wall exactly like a slow host would
        try:
            _fault_site("fleet.step")
        except Exception:
            pass  # only delay/no-op kinds make sense here; ignore others
        now = time.perf_counter()
        if wall_s is None:
            wall_s = (now - self._last_note) if self._last_note is not None \
                else None
        self._last_note = now
        if wall_s is not None:
            self.walls.append(float(wall_s))
        if self._disabled:
            return
        if time.time() - self._last_publish < self.min_interval_s:
            return
        try:
            self.publish(step)
            self._fail_streak = 0
        except Exception:
            # one failed publish is a hiccup (a store blip during a
            # barrier); only a STREAK of them means the store is gone —
            # then stop trying rather than stall the train loop
            self._fail_streak += 1
            if self._fail_streak >= self.MAX_FAIL_STREAK:
                self._disabled = True

    def _data_wait_frac(self) -> Optional[float]:
        """DataLoader wait fraction since the previous digest, from the
        global Benchmark reader averager."""
        try:
            from ...profiler.timer import benchmark
            wait = float(benchmark().reader.total_time)
        except Exception:
            return None
        now = time.perf_counter()
        frac = None
        if self._last_reader_ts is not None:
            dt = now - self._last_reader_ts
            if dt > 0:
                frac = max(0.0, min(1.0, (wait - self._last_reader_wait) / dt))
        self._last_reader_ts = now
        self._last_reader_wait = wait
        return frac

    def digest(self, step: int) -> dict:
        p50 = statistics.median(self.walls) if self.walls else None
        try:
            from ...profiler.monitor import last_diagnosis
            diag = (last_diagnosis() or {}).get("dominant")
        except Exception:
            diag = None
        return {
            "rank": self.rank,
            "host": self.host,
            "step": int(step),
            "ts": time.time(),
            # elastic generation of this incarnation: the controller uses
            # it to tell a post-relaunch digest from a pre-relaunch
            # straggler that published just after a decision fired
            "gen": self._generation(),
            "wall_p50_s": p50,
            "last_wall_s": self.walls[-1] if self.walls else None,
            "window": len(self.walls),
            "data_wait_frac": self._data_wait_frac(),
            # newest step_diagnosis dominant term (null until one runs):
            # the aggregator's fleet view names each host's bottleneck
            "diag_dominant": diag,
            # training-health status (profiler/health.py; null until the
            # health plane saw a step) — the rank-0 aggregator uses it to
            # name the first host whose numerics went bad
            "health_status": self._health_status(),
            # serving-SLO status ('ok' / 'breach:<signals>'; null until a
            # serving engine runs here) — the same transition-shaped
            # signal as health_status, so controller policies can consume
            # serving health exactly like trainer health
            "serving_slo": self._serving_slo_status(),
            # leader of the HA control plane as THIS host sees it (null
            # when no controller attached / store blip): display-level
            # fleet state for /fleet + obs_tail; the aggregator's
            # fleet_leaderless detection watches the lease key itself
            # (value-change freshness), not this cached snapshot
            "controller_leader": self._controller_leader(),
            "barrier_wait_s": round(_hist_sum("ckpt_barrier_wait_seconds"), 6),
            "heter": {
                "route_s": round(_hist_sum("heter_route_seconds"), 6),
                "pull_s": round(_hist_sum("heter_pull_seconds"), 6),
                "push_s": round(_hist_sum("heter_push_seconds"), 6),
                "step_wall_s": round(_hist_sum("heter_step_wall_seconds"), 6),
            },
        }

    @staticmethod
    def _generation() -> int:
        return _envparse.env_int("PADDLE_TPU_ELASTIC_RESTART_NUM", 0)

    def _controller_leader(self) -> Optional[str]:
        try:
            from .leader import LEASE_KEY
            if not self.store.check(LEASE_KEY):
                return None
            return json.loads(
                self.store.get(LEASE_KEY).decode()).get("id")
        except Exception:
            return None

    @staticmethod
    def _health_status():
        try:
            from ...profiler.health import last_status
            return last_status()
        except Exception:
            return None

    @staticmethod
    def _serving_slo_status():
        try:
            from ...profiler.slo import last_status
            return last_status()
        except Exception:
            return None

    def publish(self, step: int):
        self.store.set(DIGEST_KEY_FMT.format(rank=self.rank),
                       json.dumps(self.digest(step)))
        self._last_publish = time.time()


class FleetAggregator:
    """Merges every host's digest into fleet_* gauges + straggler events.

    Thread-safe (the ObservabilityServer scrapes from handler threads and
    the native store client is one socket)."""

    MIN_WINDOW = 3  # digests with fewer walls don't vote (startup noise)

    def __init__(self, store, world_size: int,
                 straggler_factor: Optional[float] = None,
                 stale_sec: Optional[float] = None):
        self.store = store
        self.world_size = int(world_size)
        if straggler_factor is None:
            straggler_factor = _envparse.env_float(
                "PADDLE_TPU_STRAGGLER_FACTOR", 2.0)
        self.straggler_factor = float(straggler_factor)
        if stale_sec is None:
            stale_sec = _envparse.env_float(
                "PADDLE_TPU_DIGEST_STALE_SEC", 120.0)
        self.stale_sec = float(stale_sec)
        self._lock = threading.Lock()
        self._straggling: set = set()
        self._unhealthy: Dict[str, str] = {}  # host -> last non-ok status
        self.last: Dict[int, dict] = {}
        #: leader-lease observation: (raw value, monotonic ts it last
        #: CHANGED) — the leaderless check is value-change freshness on
        #: OUR clock, the same skew-immune rule standby controllers use
        self._lease_obs: Optional[tuple] = None
        self._leaderless_fired = False
        self._poll_thread: Optional[threading.Thread] = None
        self._poll_stop = threading.Event()
        self._poll_hook = None

    def collect(self) -> Dict[int, dict]:
        """Read every rank's digest, mirror into the registry, run the
        straggler check. Returns {rank: digest} for what was readable."""
        with self._lock:
            out: Dict[int, dict] = {}
            for r in range(self.world_size):
                key = DIGEST_KEY_FMT.format(rank=r)
                try:
                    if not self.store.check(key):
                        continue
                    out[r] = json.loads(self.store.get(key).decode())
                except Exception:
                    continue
            self.last = out
            now = time.time()
            m_on = _metrics_mod.enabled()
            for r, d in out.items():
                host = d.get("host", f"rank-{r}")
                if m_on:
                    _M_LAST_STEP.set(d.get("step", -1), host=host)
                    _M_STEP_AGE.set(max(0.0, now - d.get("ts", now)),
                                    host=host)
                    if d.get("wall_p50_s") is not None:
                        _M_WALL_P50.set(d["wall_p50_s"], host=host)
                    if d.get("data_wait_frac") is not None:
                        _M_DATA_WAIT.set(d["data_wait_frac"], host=host)
                    if d.get("health_status") in HEALTH_CODES:
                        _M_HEALTH.set(HEALTH_CODES[d["health_status"]],
                                      host=host)
            self._detect_stragglers(out)
            self._detect_unhealthy(out)
            self._detect_leaderless()
            return out

    def _detect_leaderless(self):
        """One `fleet_leaderless` event when the leader lease stops
        being renewed for over one TTL (every standby is gone too, or
        they would have taken over by then): the fleet's self-healing
        plane is down and an operator must know. Re-armed when the
        lease value moves again. A job with no controller attached (no
        lease key at all) never alarms."""
        try:
            from .leader import LEASE_KEY
            raw = (self.store.get(LEASE_KEY)
                   if self.store.check(LEASE_KEY) else None)
        except Exception:
            return  # store blip: no verdict this round
        now = time.monotonic()
        if raw is None:
            self._lease_obs = None
            return  # controller-less (or cleanly released): legal
        if self._lease_obs is None or self._lease_obs[0] != raw:
            self._lease_obs = (raw, now)
            self._leaderless_fired = False
            return
        ttl = _envparse.env_float("PADDLE_TPU_CONTROLLER_LEASE_TTL", 5.0)
        silent = now - self._lease_obs[1]
        if not self._leaderless_fired and silent > ttl:
            self._leaderless_fired = True
            try:
                rec = json.loads(raw.decode())
            except Exception:
                rec = {}
            _events_mod.emit(
                "fleet_leaderless", severity="warn",
                leader=rec.get("id"), term=rec.get("term"),
                silent_s=round(silent, 3), ttl_s=ttl)

    def _detect_unhealthy(self, digests: Dict[int, dict]):
        """One `fleet_health` event per status TRANSITION: emitted when a
        host's digest first reports a non-ok health status (the events
        are timestamped, so the FIRST such event names the first host
        whose numerics went bad) and again when the status changes (a
        warn host escalating to diverged must still fire the
        severity=error alert operators page on); re-armed when the host
        reports ok again."""
        for r, d in digests.items():
            host = d.get("host", f"rank-{r}")
            status = d.get("health_status")
            if status in ("warn", "diverged"):
                if self._unhealthy.get(host) != status:
                    self._unhealthy[host] = status
                    _events_mod.emit(
                        "fleet_health",
                        severity="error" if status == "diverged" else "warn",
                        unhealthy=host, status=status, step=d.get("step"))
            elif status == "ok":
                self._unhealthy.pop(host, None)

    def _detect_stragglers(self, digests: Dict[int, dict]):
        """One `fleet_straggler` event per excursion: emitted when a host's
        rolling p50 first exceeds factor x the median of the OTHER hosts'
        p50s, re-armed when it returns under. Leave-one-out matters: in a
        small fleet a straggler inflates a plain fleet median enough to
        hide itself (2 hosts at 10ms/100ms have median 55ms — the slow one
        would pass a 2x check against it)."""
        now = time.time()
        voting = {d.get("host", f"rank-{r}"): d["wall_p50_s"]
                  for r, d in digests.items()
                  if d.get("wall_p50_s") is not None
                  and d.get("window", 0) >= self.MIN_WINDOW
                  # a STALE digest no longer describes the host: an
                  # evicted/dead host's frozen slow p50 must not keep
                  # skewing the leave-one-out baseline of the live fleet
                  and (self.stale_sec <= 0
                       or now - d.get("ts", now) <= self.stale_sec)}
        for host in list(self._straggling):
            if host not in voting:
                # a host that stopped voting (stale/absent digest) must
                # LEAVE the straggler set: its frozen verdict is no longer
                # evidence, and the controller's eviction debounce counts
                # membership here as consecutive straggling windows
                self._straggling.discard(host)
        if len(voting) < 2:
            return  # a fleet of one has no straggler semantics
        for host, p50 in voting.items():
            others = [v for h, v in voting.items() if h != host]
            baseline = statistics.median(others)
            if baseline <= 0:
                continue
            if p50 > self.straggler_factor * baseline:
                if host not in self._straggling:
                    self._straggling.add(host)
                    if _metrics_mod.enabled():
                        _M_STRAGGLER.inc(host=host)
                    _events_mod.emit(
                        "fleet_straggler", severity="warn", straggler=host,
                        p50_s=round(p50, 6),
                        fleet_median_s=round(baseline, 6),
                        factor=self.straggler_factor)
            else:
                self._straggling.discard(host)

    # -- background polling ---------------------------------------------------
    def start_polling(self, interval: Optional[float] = None,
                      hook=None) -> bool:
        """Run collect() on a background daemon thread so digest
        mirroring, straggler detection and health transitions no longer
        depend on an external /metrics scraper.

        `interval`: seconds between collects; default
        `PADDLE_TPU_FLEET_POLL_SEC` — and when that is unset/0 the loop
        stays OFF unless a `hook` is given (a fleet CONTROLLER is
        attached), in which case it defaults to
        `PADDLE_TPU_CONTROLLER_POLL_SEC` (1.0s). `hook(digests)` runs
        after every collect; hook exceptions are swallowed with a
        warning (telemetry must not die of a consumer bug). Returns
        True when the loop started."""
        if interval is None:
            interval = _envparse.env_float("PADDLE_TPU_FLEET_POLL_SEC", 0.0)
            if interval <= 0 and hook is not None:
                interval = _envparse.env_float(
                    "PADDLE_TPU_CONTROLLER_POLL_SEC", 1.0)
        if interval is None or interval <= 0:
            return False
        if self._poll_thread is not None and self._poll_thread.is_alive():
            if hook is None or hook is self._poll_hook:
                return True  # already polling with this consumer
            # a controller attaching AFTER a hookless metrics-server poll
            # started (elastic_run starts the server first) must not be
            # silently dropped — re-arm the loop with the new hook
            self.stop_polling()
        self._poll_hook = hook
        # each loop closes over its OWN stop event: a predecessor thread
        # that outlived stop_polling's bounded join (blocked in a store
        # RPC longer than the join timeout) keeps seeing ITS set event
        # and exits — a shared cleared event would resurrect it alongside
        # the new loop
        stop = threading.Event()

        def loop():
            while not stop.wait(interval):
                try:
                    digests = self.collect()
                except Exception:
                    continue  # store hiccup: try again next tick
                if hook is not None:
                    try:
                        hook(digests)
                    except Exception as e:
                        import warnings
                        warnings.warn(f"fleet poll hook failed: "
                                      f"{type(e).__name__}: {e}")

        self._poll_stop = stop
        self._poll_thread = threading.Thread(
            target=loop, daemon=True, name="fleet-aggregator-poll")
        self._poll_thread.start()
        return True

    def stop_polling(self):
        self._poll_stop.set()
        t = self._poll_thread
        if t is not None:
            t.join(timeout=5)
        self._poll_thread = None

    def straggling(self) -> List[str]:
        with self._lock:
            return sorted(self._straggling)

    def snapshot(self) -> dict:
        """JSON view for the server's /snapshot endpoint."""
        with self._lock:
            return {"world_size": self.world_size,
                    "straggler_factor": self.straggler_factor,
                    "straggling": sorted(self._straggling),
                    "unhealthy": sorted(self._unhealthy),
                    "hosts": {str(r): d for r, d in self.last.items()}}


def _store_from_env(timeout: int = 10):
    from ..store import TCPStore
    addr = os.environ.get("MASTER_ADDR")
    port = os.environ.get("MASTER_PORT")
    if not addr or not port:
        return None
    try:
        return TCPStore(addr, int(port), is_master=False, timeout=timeout)
    except Exception:
        return None


def reporter_from_env() -> Optional[FleetReporter]:
    """A FleetReporter from the trainer env contract (own store
    connection), or None for single-host jobs / no master reachable.

    `PADDLE_TPU_FLEET_REPORTER` overrides the world-size gate: "0"
    disables reporting outright; "1" forces it even at world size 1 —
    the fleet controller sets this on N-1 relaunches so it keeps
    observing a fleet it shrank to a single host."""
    force = os.environ.get("PADDLE_TPU_FLEET_REPORTER", "").strip().lower()
    if force in ("0", "false", "off", "no"):
        return None
    try:
        world = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    except ValueError:
        return None
    if world < 2 and force not in ("1", "true", "on", "yes", "force"):
        return None
    store = _store_from_env()
    if store is None:
        return None
    return FleetReporter(store, rank)


def aggregator_from_env() -> Optional[FleetAggregator]:
    """A FleetAggregator for rank 0 of a >=2 fleet (own store connection),
    else None."""
    try:
        world = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    except ValueError:
        return None
    if world < 2 or rank != 0:
        return None
    store = _store_from_env()
    if store is None:
        return None
    return FleetAggregator(store, world)
