"""DistributedStrategy — the single config object for every feature.

Reference: protobuf-backed `DistributedStrategy`
(`/root/reference/python/paddle/distributed/fleet/base/distributed_strategy.py:109`
↔ `paddle/fluid/framework/distributed_strategy.proto`): one message per
feature (amp, recompute, sharding, pipeline, tensor_parallel, hybrid_configs,
…). TPU translation per SURVEY.md §5.6: dataclasses serialized to JSON —
same shape, no protobuf dependency.
"""
from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, Optional


@dataclasses.dataclass
class AMPConfig:
    enable: bool = False
    dtype: str = "bfloat16"          # TPU-first: bf16, no loss scaling needed
    level: str = "O1"
    init_loss_scaling: float = 32768.0
    use_dynamic_loss_scaling: bool = True
    custom_white_list: tuple = ()
    custom_black_list: tuple = ()


@dataclasses.dataclass
class RecomputeConfig:
    enable: bool = False
    checkpoints: tuple = ()          # layer names to checkpoint at


@dataclasses.dataclass
class ShardingConfig:
    enable: bool = False
    stage: int = 1                   # ZeRO stage 1/2/3
    degree: int = 1
    offload: bool = False
    segment_broadcast_MB: float = 32.0


@dataclasses.dataclass
class PipelineConfig:
    enable: bool = False
    micro_batch_size: int = 1
    accumulate_steps: int = 1
    schedule_mode: str = "1F1B"


@dataclasses.dataclass
class TensorParallelConfig:
    enable: bool = False
    tensor_parallel_degree: int = 1
    tensor_init_seed: int = -1


@dataclasses.dataclass
class HybridConfig:
    dp_degree: int = -1              # -1: absorb remaining devices
    mp_degree: int = 1
    pp_degree: int = 1
    sharding_degree: int = 1
    sep_degree: int = 1              # sequence/context parallel (ours)
    sp_mode: str = "ring"            # "ring" | "ulysses" attention flavor


class DistributedStrategy:
    """Feature-flag container, attribute-compatible with the reference's
    strategy object (`strategy.amp = True`, `strategy.hybrid_configs = {...}`)."""

    def __init__(self):
        self._amp = AMPConfig()
        self._recompute = RecomputeConfig()
        self._sharding = ShardingConfig()
        self._pipeline = PipelineConfig()
        self._tensor_parallel = TensorParallelConfig()
        self._hybrid = HybridConfig()
        self.gradient_merge = False
        self.gradient_merge_configs: Dict[str, Any] = {"k_steps": 1}
        self.find_unused_parameters = False
        self.fuse_all_reduce_ops = True   # XLA always fuses; parity flag
        self.nccl_comm_num = 1
        self.heter_ccl_mode = False

    # -- feature switches mirror reference property style -------------------
    @property
    def amp(self) -> bool:
        return self._amp.enable

    @amp.setter
    def amp(self, flag: bool):
        self._amp.enable = bool(flag)

    @property
    def amp_configs(self):
        return dataclasses.asdict(self._amp)

    @amp_configs.setter
    def amp_configs(self, cfg: Dict[str, Any]):
        for k, v in cfg.items():
            if hasattr(self._amp, k):
                setattr(self._amp, k, v)

    @property
    def recompute(self) -> bool:
        return self._recompute.enable

    @recompute.setter
    def recompute(self, flag: bool):
        self._recompute.enable = bool(flag)

    @property
    def recompute_configs(self):
        return dataclasses.asdict(self._recompute)

    @recompute_configs.setter
    def recompute_configs(self, cfg):
        for k, v in cfg.items():
            if hasattr(self._recompute, k):
                setattr(self._recompute, k, v)

    @property
    def sharding(self) -> bool:
        return self._sharding.enable

    @sharding.setter
    def sharding(self, flag: bool):
        self._sharding.enable = bool(flag)

    @property
    def sharding_configs(self):
        return dataclasses.asdict(self._sharding)

    @sharding_configs.setter
    def sharding_configs(self, cfg):
        for k, v in cfg.items():
            if hasattr(self._sharding, k):
                setattr(self._sharding, k, v)

    @property
    def pipeline(self) -> bool:
        return self._pipeline.enable

    @pipeline.setter
    def pipeline(self, flag: bool):
        self._pipeline.enable = bool(flag)

    @property
    def pipeline_configs(self):
        return dataclasses.asdict(self._pipeline)

    @pipeline_configs.setter
    def pipeline_configs(self, cfg):
        for k, v in cfg.items():
            if hasattr(self._pipeline, k):
                setattr(self._pipeline, k, v)

    @property
    def tensor_parallel(self) -> bool:
        return self._tensor_parallel.enable

    @tensor_parallel.setter
    def tensor_parallel(self, flag: bool):
        self._tensor_parallel.enable = bool(flag)

    @property
    def tensor_parallel_configs(self):
        return dataclasses.asdict(self._tensor_parallel)

    @tensor_parallel_configs.setter
    def tensor_parallel_configs(self, cfg):
        for k, v in cfg.items():
            if hasattr(self._tensor_parallel, k):
                setattr(self._tensor_parallel, k, v)

    @property
    def hybrid_configs(self):
        return dataclasses.asdict(self._hybrid)

    @hybrid_configs.setter
    def hybrid_configs(self, cfg: Dict[str, Any]):
        for k, v in cfg.items():
            if hasattr(self._hybrid, k):
                setattr(self._hybrid, k, v)

    # -- mesh dims derived from hybrid config --------------------------------
    def mesh_dims(self) -> Dict[str, int]:
        h = self._hybrid
        dims = {"pp": h.pp_degree, "sharding": max(
            h.sharding_degree, self._sharding.degree
            if self._sharding.enable else 1),
            "sp": h.sep_degree, "mp": h.mp_degree}
        if h.dp_degree > 0:
            dims["dp"] = h.dp_degree
        return dims

    # -- (de)serialization ---------------------------------------------------
    def to_dict(self) -> Dict[str, Any]:
        return {
            "amp": self.amp_configs,
            "recompute": self.recompute_configs,
            "sharding": self.sharding_configs,
            "pipeline": self.pipeline_configs,
            "tensor_parallel": self.tensor_parallel_configs,
            "hybrid_configs": self.hybrid_configs,
            "gradient_merge": self.gradient_merge,
            "gradient_merge_configs": self.gradient_merge_configs,
        }

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2, default=list)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "DistributedStrategy":
        s = cls()
        for key in ("amp", "recompute", "sharding", "pipeline",
                    "tensor_parallel"):
            if key in d:
                setattr(s, key + "_configs", d[key])
                setattr(s, key, d[key].get("enable", False))
        if "hybrid_configs" in d:
            s.hybrid_configs = d["hybrid_configs"]
        s.gradient_merge = d.get("gradient_merge", False)
        s.gradient_merge_configs = d.get("gradient_merge_configs",
                                         {"k_steps": 1})
        return s

    @classmethod
    def from_json(cls, text: str) -> "DistributedStrategy":
        return cls.from_dict(json.loads(text))

    def __repr__(self):
        return f"DistributedStrategy({self.to_dict()!r})"
