"""Sharding-aware distributed checkpointing.

Reference: the reference saves sharded state per rank with dist attrs and
re-shards on load (auto_parallel `dist_saver.py` + `converter.py`; stage-3
sharding gathers on save, `sharding/group_sharded.py:201`). TPU translation
follows the orbax/tensorstore pattern: save once from the addressable host
(jax gathers), record each array's PartitionSpec, and on restore
`jax.device_put` under the target sharding — mesh-shape changes re-shard
transparently. `save(..., async_save=True)` snapshots to host immediately
and writes in a background thread (the reference's async auto-checkpoint).
"""
from __future__ import annotations

import os
import pickle
import threading
from typing import Any, Dict, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor

_pending_saves: list = []
_save_errors: list = []


def _spec_of(arr) -> Optional[tuple]:
    shard = getattr(arr, "sharding", None)
    spec = getattr(shard, "spec", None)
    if spec is None:
        return None
    return tuple(None if p is None else (tuple(p) if isinstance(p, tuple)
                                         else str(p)) for p in spec)


def _to_host(obj, specs: Dict[str, tuple], prefix: str = ""):
    if isinstance(obj, Tensor):
        obj = obj.data
    if isinstance(obj, jax.Array):
        s = _spec_of(obj)
        if s is not None:
            specs[prefix] = s
        return np.asarray(obj)
    if isinstance(obj, dict):
        return {k: _to_host(v, specs, f"{prefix}/{k}") for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_host(v, specs, f"{prefix}/{i}")
                         for i, v in enumerate(obj))
    return obj


def save(state: Any, path: str, async_save: bool = False):
    """Checkpoint a pytree of arrays/Tensors with sharding metadata."""
    specs: Dict[str, tuple] = {}
    host_state = _to_host(state, specs)  # synchronous device->host snapshot

    def write():
        import tempfile
        target_dir = os.path.dirname(os.path.abspath(path)) or "."
        os.makedirs(target_dir, exist_ok=True)
        # unique tmp per writer: concurrent saves to the same path must not
        # share a tmp file (interleaved writes would corrupt the publish)
        fd, tmp = tempfile.mkstemp(dir=target_dir,
                                   prefix=os.path.basename(path) + ".tmp.")
        try:
            with os.fdopen(fd, "wb") as f:
                pickle.dump({"state": host_state, "specs": specs,
                             "version": 1}, f, protocol=4)
            os.replace(tmp, path)  # atomic publish — no torn checkpoints
        except BaseException:
            if os.path.exists(tmp):
                os.remove(tmp)
            raise

    def write_logged():
        try:
            write()
        except BaseException as e:  # surfaced by wait_all
            _save_errors.append(e)

    if async_save:
        t = threading.Thread(target=write_logged, daemon=True)
        t.start()
        _pending_saves.append(t)
    else:
        write()


def wait_all():
    """Block until every async save has been published; re-raises the first
    background failure (a silently lost checkpoint is worse than a crash)."""
    while _pending_saves:
        _pending_saves.pop().join()
    if _save_errors:
        err = _save_errors[0]
        _save_errors.clear()
        raise err


def _apply_shardings(obj, specs: Dict[str, tuple], mesh, prefix: str = ""):
    if isinstance(obj, np.ndarray):
        arr = jnp.asarray(obj)
        spec = specs.get(prefix)
        if spec is not None and mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            names = set(mesh.axis_names)
            cleaned = []
            for p in spec:
                # drop axes that do not exist in the TARGET mesh — restoring
                # onto a smaller/different mesh replicates those dims
                if p is None:
                    cleaned.append(None)
                elif isinstance(p, tuple):
                    kept = tuple(a for a in p if a in names)
                    cleaned.append(kept if kept else None)
                else:
                    cleaned.append(p if p in names else None)
            try:
                arr = jax.device_put(arr, NamedSharding(mesh, P(*cleaned)))
            except Exception:
                pass  # incompatible spec (divisibility): keep replicated
        return arr
    if isinstance(obj, dict):
        return {k: _apply_shardings(v, specs, mesh, f"{prefix}/{k}")
                for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_apply_shardings(v, specs, mesh, f"{prefix}/{i}")
                        for i, v in enumerate(obj))
    return obj


def load(path: str, mesh=None) -> Any:
    """Restore; with `mesh`, arrays are re-laid-out per their saved specs
    (axes missing from the target mesh fall back to replication)."""
    with open(path, "rb") as f:
        blob = pickle.load(f)
    return _apply_shardings(blob["state"], blob.get("specs", {}), mesh)


def latest(dirname: str, prefix: str = "ckpt") -> Optional[str]:
    """Newest checkpoint file `<prefix>_<step>` in dirname, or None."""
    if not os.path.isdir(dirname):
        return None
    best, best_step = None, -1
    for fn in os.listdir(dirname):
        if fn.startswith(prefix + "_") and not fn.endswith(".tmp"):
            try:
                step = int(fn.rsplit("_", 1)[1])
            except ValueError:
                continue
            if step > best_step:
                best, best_step = os.path.join(dirname, fn), step
    return best
