"""Sharding-aware distributed checkpointing with corruption recovery.

Reference: the reference saves sharded state per rank with dist attrs and
re-shards on load (auto_parallel `dist_saver.py` + `converter.py`; stage-3
sharding gathers on save, `sharding/group_sharded.py:201`). TPU translation
follows the orbax/tensorstore pattern: save once from the addressable host
(jax gathers), record each array's PartitionSpec, and on restore
`jax.device_put` under the target sharding — mesh-shape changes re-shard
transparently. `save(..., async_save=True)` snapshots to host immediately
and writes in a background thread (the reference's async auto-checkpoint).

Robustness layer (reference `incubate/checkpoint/auto_checkpoint.py` +
fleet elastic):

* every file carries a fixed header — magic, format version, CRC32 and
  length of the pickled payload — so `load` detects truncated, bit-flipped,
  and torn files and raises `CheckpointCorruptError` instead of a pickle
  traceback;
* `latest_valid` walks checkpoints newest-first and returns the newest one
  that verifies, so a corrupt final snapshot costs one save interval, not
  the job;
* `CheckpointManager` adds keep-last-N garbage collection, orphaned
  `.tmp.*` cleanup, and a SIGTERM handler that performs one final
  synchronous save before exit (TPU-pod preemption sends SIGTERM);
* `CheckpointCoordinator` turns multi-host saves into a two-phase
  coordinated commit over the TCPStore — every host publishes step N or
  none does, so `latest_valid` can never disagree across the fleet — and
  `negotiate_resume` picks the newest step committed on EVERY host at
  restart (the elastic supervisors re-enter `fit(resume=...)` with it).

Every save/load/skip/GC event lands in the metrics registry so recovery is
visible in the prometheus/JSON snapshot.
"""
from __future__ import annotations

import os
import pickle
import signal
import struct
import threading
import time
import warnings
import zlib
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor
from ..profiler import events as _events_mod
from ..profiler import metrics as _metrics_mod

_REG = _metrics_mod.default_registry()
_M_SAVES = _REG.counter("checkpoint_saves_total",
                        "checkpoint files published (atomic replace)")
_M_LOADS = _REG.counter("checkpoint_loads_total",
                        "checkpoint files loaded and verified")
_M_CORRUPT = _REG.counter(
    "checkpoint_corrupt_skipped_total",
    "corrupt/truncated checkpoint files detected and skipped")
_M_GC = _REG.counter("checkpoint_gc_removed_total",
                     "checkpoint and orphaned tmp files garbage-collected")
_M_PREEMPT = _REG.counter(
    "checkpoint_preemption_saves_total",
    "final synchronous saves performed by the SIGTERM preemption handler")
_M_RESHARD_FALLBACK = _REG.counter(
    "checkpoint_reshard_fallback_total",
    "arrays whose saved sharding could not be applied and were "
    "replicated, by tree path")
_M_SAVE_SECONDS = _REG.histogram("checkpoint_save_seconds",
                                 "wall time of checkpoint writes")
_M_BARRIER_WAIT = _REG.histogram(
    "ckpt_barrier_wait_seconds",
    "time spent waiting for every host to prepare a coordinated checkpoint")
_M_BARRIER_ABORTS = _REG.counter(
    "ckpt_barrier_aborts_total",
    "coordinated checkpoint rounds aborted (no host published a final "
    "file), labeled by reason: timeout / peer_abort / error")
_M_BARRIER_COMMITS = _REG.counter(
    "ckpt_barrier_commits_total",
    "coordinated checkpoint commits (this host renamed tmp -> final after "
    "all hosts prepared)")
_M_SKIP_NONFINITE = _REG.counter(
    "checkpoint_resume_skipped_nonfinite_total",
    "CRC-valid checkpoints skipped at resume because their weights held "
    "NaN/Inf (valid-only resume, the fleet-rollback path)")

_pending_saves: list = []
_save_errors: list = []

# header: magic(8) | crc32(payload)(4, LE) | payload_len(8, LE)
_MAGIC = b"PTCKPT01"
_HEADER_FMT = struct.Struct("<8sIQ")

from ..framework.io import _atomic_write


class CheckpointCorruptError(RuntimeError):
    """A checkpoint file failed verification (truncated/bit-flipped/torn)."""

    def __init__(self, path: str, reason: str):
        super().__init__(f"corrupt checkpoint {path}: {reason}")
        self.path = path
        self.reason = reason


def _spec_of(arr) -> Optional[tuple]:
    shard = getattr(arr, "sharding", None)
    spec = getattr(shard, "spec", None)
    if spec is None:
        return None
    return tuple(None if p is None else (tuple(p) if isinstance(p, tuple)
                                         else str(p)) for p in spec)


def _to_host(obj, specs: Dict[str, tuple], prefix: str = ""):
    if isinstance(obj, Tensor):
        obj = obj.data
    if isinstance(obj, jax.Array):
        s = _spec_of(obj)
        if s is not None:
            specs[prefix] = s
        return np.asarray(obj)
    if isinstance(obj, dict):
        return {k: _to_host(v, specs, f"{prefix}/{k}") for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_host(v, specs, f"{prefix}/{i}")
                         for i, v in enumerate(obj))
    return obj


def _encode(blob: dict) -> bytes:
    payload = pickle.dumps(blob, protocol=4)
    return _HEADER_FMT.pack(_MAGIC, zlib.crc32(payload) & 0xFFFFFFFF,
                            len(payload)) + payload


def _verified_payload(path: str, data: bytes) -> bytes:
    """Header+length+CRC check; returns the pickled payload or raises
    CheckpointCorruptError. Files without the magic are legacy plain
    pickles and pass through for best-effort unpickling."""
    if not data.startswith(_MAGIC):
        return data
    if len(data) < _HEADER_FMT.size:
        raise CheckpointCorruptError(path, "truncated header")
    _, crc, length = _HEADER_FMT.unpack_from(data)
    payload = data[_HEADER_FMT.size:]
    if len(payload) != length:
        raise CheckpointCorruptError(
            path, f"payload truncated: header says {length} bytes, "
                  f"file has {len(payload)}")
    if zlib.crc32(payload) & 0xFFFFFFFF != crc:
        raise CheckpointCorruptError(
            path, f"CRC32 mismatch (stored {crc:#010x})")
    return payload


def _decode(path: str, data: bytes) -> dict:
    """Verify header+CRC and unpickle; raises CheckpointCorruptError."""
    payload = _verified_payload(path, data)
    if not payload:
        raise CheckpointCorruptError(path, "empty file")
    try:
        blob = pickle.loads(payload)
    except Exception as e:
        raise CheckpointCorruptError(
            path, f"unpickle failed: {type(e).__name__}: {e}") from e
    if not isinstance(blob, dict) or "state" not in blob:
        raise CheckpointCorruptError(path, "payload is not a checkpoint blob")
    return blob


def _encode_snapshot(host_state, specs: Dict[str, tuple]) -> bytes:
    """The one place the on-disk blob layout is defined — both the plain
    and the coordinated save paths write exactly this."""
    return _encode({"state": host_state, "specs": specs, "version": 2})


def save(state: Any, path: str, async_save: bool = False):
    """Checkpoint a pytree of arrays/Tensors with sharding metadata."""
    specs: Dict[str, tuple] = {}
    host_state = _to_host(state, specs)  # synchronous device->host snapshot

    def write():
        t0 = time.perf_counter()
        _atomic_write(path, _encode_snapshot(host_state, specs))
        if _metrics_mod.enabled():
            _M_SAVES.inc()
            _M_SAVE_SECONDS.observe(time.perf_counter() - t0)

    def write_logged():
        try:
            write()
        except BaseException as e:  # surfaced by wait_all
            _save_errors.append(e)

    if async_save:
        t = threading.Thread(target=write_logged, daemon=True)
        t.start()
        _pending_saves.append(t)
    else:
        write()


def wait_all():
    """Block until every async save has been published; re-raises the first
    background failure (a silently lost checkpoint is worse than a crash)."""
    while _pending_saves:
        _pending_saves.pop().join()
    if _save_errors:
        err = _save_errors[0]
        _save_errors.clear()
        raise err


def _clean_spec(spec, mesh) -> tuple:
    """Re-target a saved PartitionSpec at `mesh`: axes the target mesh
    does not have are dropped (those dims replicate) — restoring onto a
    smaller/different mesh re-shards what it can. Accepts tuple or list
    entries (JSON-roundtripped sharded manifests store lists)."""
    names = set(mesh.axis_names)
    cleaned = []
    for p in spec:
        if p is None:
            cleaned.append(None)
        elif isinstance(p, (tuple, list)):
            kept = tuple(a for a in p if a in names)
            cleaned.append(kept if kept else None)
        else:
            cleaned.append(p if p in names else None)
    return tuple(cleaned)


def _warn_reshard_fallback(path: str, spec, mesh, exc: BaseException):
    """Incompatible spec (divisibility, bad axis): the array stays
    replicated — but LOUDLY, so silent replication can't masquerade as
    sharding."""
    warnings.warn(
        f"checkpoint restore: could not apply saved sharding to "
        f"{path or '<root>'} (spec={tuple(spec)}, "
        f"mesh axes={dict(zip(mesh.axis_names, mesh.devices.shape))}"
        f"): {type(exc).__name__}: {exc}; keeping the array replicated")
    if _metrics_mod.enabled():
        _M_RESHARD_FALLBACK.inc(path=path or "<root>")


def _apply_shardings(obj, specs: Dict[str, tuple], mesh, prefix: str = ""):
    if isinstance(obj, np.ndarray):
        arr = jnp.asarray(obj)
        spec = specs.get(prefix)
        if spec is not None and mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            cleaned = _clean_spec(spec, mesh)
            try:
                arr = jax.device_put(arr, NamedSharding(mesh, P(*cleaned)))
            except Exception as e:
                _warn_reshard_fallback(prefix, cleaned, mesh, e)
        return arr
    if isinstance(obj, dict):
        return {k: _apply_shardings(v, specs, mesh, f"{prefix}/{k}")
                for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_apply_shardings(v, specs, mesh, f"{prefix}/{i}")
                        for i, v in enumerate(obj))
    return obj


def load(path: str, mesh=None) -> Any:
    """Restore; with `mesh`, arrays are re-laid-out per their saved specs
    (axes missing from the target mesh fall back to replication).
    Raises CheckpointCorruptError (never a bare pickle traceback) when the
    file fails header/CRC verification."""
    with open(path, "rb") as f:
        data = f.read()
    blob = _decode(path, data)
    if _metrics_mod.enabled():
        _M_LOADS.inc()
    return _apply_shardings(blob["state"], blob.get("specs", {}), mesh)


def verify(path: str) -> Tuple[bool, Optional[str]]:
    """Cheap validity probe: (True, None) when the file's header, length
    and CRC check out (legacy files are fully unpickled to verify)."""
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError as e:
        return False, f"unreadable: {e}"
    try:
        if data.startswith(_MAGIC):
            # header verification only — no need to unpickle the payload
            _verified_payload(path, data)
        else:
            _decode(path, data)
    except CheckpointCorruptError as e:
        return False, e.reason
    return True, None


def _step_files(dirname: str, prefix: str) -> List[Tuple[int, str]]:
    """[(step, path)] for `<prefix>_<step>` files, newest step first.
    Step DIRECTORIES (the sharded/chunked layout) are not this backend's
    to read — `sharded_checkpoint._step_dirs` owns those."""
    if not os.path.isdir(dirname):
        return []
    out = []
    for fn in os.listdir(dirname):
        if not fn.startswith(prefix + "_") or ".tmp." in fn \
                or fn.endswith(".tmp"):
            continue
        try:
            step = int(fn.rsplit("_", 1)[1])
        except ValueError:
            continue
        path = os.path.join(dirname, fn)
        if os.path.isdir(path):
            continue
        out.append((step, path))
    out.sort(reverse=True)
    return out


def latest(dirname: str, prefix: str = "ckpt") -> Optional[str]:
    """Newest checkpoint file `<prefix>_<step>` in dirname, or None.
    Does NOT verify — use `latest_valid` when corruption is possible."""
    files = _step_files(dirname, prefix)
    return files[0][1] if files else None


def resume_valid_only() -> bool:
    """`PADDLE_TPU_RESUME_VALID_ONLY=1`: resume must skip checkpoints
    whose weights hold NaN/Inf even when they are CRC-valid. The fleet
    controller's coordinated-rollback relaunch sets this so every host
    negotiates (and restores) the same last NUMERICALLY-valid committed
    step — a CRC can't see a divergence that was checkpointed before the
    sentinel's detection latency caught it."""
    return os.environ.get("PADDLE_TPU_RESUME_VALID_ONLY", "0") \
        .strip().lower() in ("1", "true", "on", "yes")


def tree_finite(obj) -> bool:
    """True when every floating-point array leaf in a checkpoint state
    tree is finite. Walks dicts/lists/tuples and Tensor-like leaves; an
    unrecognized leaf is accepted (nothing to judge). Rollback-path
    only — never per step."""
    try:
        if isinstance(obj, dict):
            return all(tree_finite(v) for v in obj.values())
        if isinstance(obj, (list, tuple)):
            return all(tree_finite(v) for v in obj)
        # array-like leaf, or a Tensor-like wrapper around one (probe
        # `.data` only when the leaf itself has no dtype — an ndarray's
        # own `.data` is a memoryview, not the array)
        a = obj if hasattr(obj, "dtype") else getattr(obj, "data", obj)
        if not hasattr(a, "dtype") or not hasattr(a, "shape"):
            return True
        a = np.asarray(a)
        if a.dtype.kind == "f":
            pass
        elif "float" in str(a.dtype):  # bfloat16/float8 via ml_dtypes
            a = a.astype(np.float32)
        else:
            return True
        return bool(np.all(np.isfinite(a)))
    except Exception:
        return True  # unjudgeable: accept rather than wedge a resume


def _note_nonfinite_skip(path: str):
    """Shared warn + metric for a CRC-valid candidate skipped at resume
    because its weights hold NaN/Inf (valid-only mode) — one definition
    so the six skip sites across both layouts cannot drift."""
    warnings.warn(f"skipping numerically-invalid checkpoint {path} "
                  f"(nonfinite weights; valid-only resume)")
    if _metrics_mod.enabled():
        _M_SKIP_NONFINITE.inc()


def latest_valid(dirname: str, prefix: str = "ckpt") -> Optional[str]:
    """Newest checkpoint that passes verification; corrupt files are
    skipped with a warning + metric instead of crashing the resume."""
    for step, path in _step_files(dirname, prefix):
        ok, reason = verify(path)
        if ok:
            return path
        warnings.warn(f"skipping corrupt checkpoint {path}: {reason}")
        if _metrics_mod.enabled():
            _M_CORRUPT.inc()
    return None


def load_latest_valid(dirname: str, prefix: str = "ckpt",
                      mesh=None, valid_only: Optional[bool] = None
                      ) -> Optional[Tuple[Any, int, str]]:
    """(state, step, path) from the newest checkpoint that decodes cleanly,
    or None. Each candidate is read and CRC-verified ONCE (the decode
    reuses the bytes) — restore is the preemption-recovery critical path
    and must not double a multi-GB file's I/O. Corrupt candidates warn,
    count, and fall through to the next-newest. With `valid_only`
    (default: the PADDLE_TPU_RESUME_VALID_ONLY env knob), candidates
    whose weights hold NaN/Inf are skipped the same way."""
    if valid_only is None:
        valid_only = resume_valid_only()
    for step, path in _step_files(dirname, prefix):
        try:
            with open(path, "rb") as f:
                data = f.read()
            blob = _decode(path, data)
        except (OSError, CheckpointCorruptError) as e:
            warnings.warn(f"skipping corrupt checkpoint {path}: {e}")
            if _metrics_mod.enabled():
                _M_CORRUPT.inc()
            continue
        if valid_only and not tree_finite(blob.get("state")):
            _note_nonfinite_skip(path)
            continue
        if _metrics_mod.enabled():
            _M_LOADS.inc()
        return (_apply_shardings(blob["state"], blob.get("specs", {}), mesh),
                step, path)
    return None


def cleanup_tmp(dirname: str, prefix: str = "ckpt") -> int:
    """Remove orphaned `<prefix>_*.tmp.*` files left by crashed writers."""
    if not os.path.isdir(dirname):
        return 0
    removed = 0
    for fn in os.listdir(dirname):
        if fn.startswith(prefix + "_") and ".tmp." in fn:
            try:
                os.remove(os.path.join(dirname, fn))
                removed += 1
            except OSError:
                pass
    if removed and _metrics_mod.enabled():
        _M_GC.inc(removed)
    return removed


class CheckpointCoordinator:
    """Two-phase coordinated commit over a TCPStore: all hosts publish
    step N, or none do.

    Protocol (per step, every host):

    1. **prepare** — write the full CRC'd payload to ``<final>.tmp.prep``
       (durable, fsync'd; invisible to ``latest_valid``/``_step_files``).
    2. **commit** — publish a per-host "prepared" key, wait until all
       ``world_size`` hosts have published (bounded by ``timeout``), then
       atomically rename tmp -> final (the last in-phase step). A host that
       times out — or fails anywhere in the commit phase — publishes an
       abort flag instead, which every other host's wait loop observes, so
       the whole fleet drops its tmp and nobody publishes a final file.

    The fault site ``ckpt.commit`` sits at the top of the commit phase: a
    host killed there has a durable tmp but never voted, so its peers time
    out and abort — the exact "died between prepare and commit" failure.

    Residual window (two-generals): a host that dies AFTER the barrier
    opened but BEFORE its own rename leaves peers that already renamed.
    ``negotiate_resume`` closes it at restart: every host publishes its
    newest locally-committed step and the fleet resumes from the minimum —
    the newest step committed *everywhere* — never the lexically newest
    file of any single host.

    Keys are namespaced by ``PADDLE_TPU_ELASTIC_RESTART_NUM`` (exported by
    the elastic supervisors) so a restarted generation's rounds can never
    collide with stale prepare/abort flags from the incarnation that died.
    Within a generation every ``commit()`` call additionally consumes a
    monotonically increasing round id (hosts call ``commit`` in lockstep —
    the same save sequence on every host, like ``negotiate_resume``), so a
    re-used *step number* (an epoch-end save followed by a SIGTERM
    preemption save before the next step, or a step retried after an
    aborted round) gets a fresh barrier instead of being decided by the
    previous round's stale votes or abort flag.
    Resolved rounds' store keys are garbage-collected with a lag of
    ``GC_LAG`` rounds: when round R resolves (commit or abort), each host
    deletes its OWN prep key and the abort flag of round R-2 — lockstep
    guarantees nobody can still be reading that round — so flags no longer
    accrete in the master store for the job's lifetime (same rule for
    resume-negotiation keys).

    Give the coordinator its own store client connection: the native store
    client is a single socket and is not thread-safe across subsystems.

    Directory topology depends on the LAYOUT. With the default file
    layout every host MUST use its own checkpoint directory: the barrier
    coordinates *steps*, not storage, and hosts sharing one directory
    (NFS) would clobber each other's fixed-name ``.tmp.prep``, race the
    final rename, and GC each other's in-flight tmps. The sharded layout
    (`sharded_checkpoint.ShardedCheckpointManager`) closes exactly this:
    chunk files and manifests are rank-namespaced and the commit renames
    only this rank's manifest, so one shared NFS/GCS-style directory is
    safe — and required for elastic re-sharding restore across a changed
    world size.
    """

    def __init__(self, store, rank: int, world_size: int,
                 timeout: Optional[float] = None,
                 resume_timeout: Optional[float] = None,
                 namespace: Optional[str] = None,
                 poll_interval: float = 0.05):
        if world_size < 2:
            raise ValueError("CheckpointCoordinator needs world_size >= 2; "
                             "single-host saves do not barrier")
        self.store = store
        self.rank = int(rank)
        self.world_size = int(world_size)
        from ..utils.envparse import env_float
        if timeout is None:
            timeout = env_float("PADDLE_TPU_CKPT_BARRIER_TIMEOUT", 60.0)
        self.timeout = float(timeout)
        if resume_timeout is None:
            resume_timeout = env_float("PADDLE_TPU_CKPT_RESUME_TIMEOUT",
                                       max(self.timeout, 120.0))
        # resume negotiation tolerates much more skew than a save barrier:
        # restarted hosts arrive staggered by backoff + process startup +
        # jit warmup, while mid-training saves are lockstep
        self.resume_timeout = float(resume_timeout)
        if namespace is None:
            namespace = "ckptbar/" + os.environ.get(
                "PADDLE_TPU_ELASTIC_RESTART_NUM", "0")
        self.namespace = namespace
        self.poll_interval = float(poll_interval)
        self._resume_round = 0
        self._commit_round = 0
        self._round_steps: Dict[int, int] = {}  # round id -> step (for GC)

    def _k(self, *parts) -> str:
        return "/".join((self.namespace,) + tuple(str(p) for p in parts))

    # -- store-key GC --------------------------------------------------------
    GC_LAG = 2  # rounds a resolved round's keys outlive it

    def _gc_round_keys(self, finished_round: int):
        """Lag-2 deletion of this host's OWN keys for a long-resolved
        round, so prep/abort flags stop accreting in the master store for
        the job's lifetime. Safe by lockstep on the COMMIT path:
        completing round R with all votes proves every host voted in R,
        hence left round R-1 — nobody can still be reading round R-2's
        keys. On a TIMEOUT path a host lagging two full rounds behind
        could miss a just-deleted R-2 abort flag and burn its own timeout
        before aborting — the same abort outcome, reached slowly, never a
        torn commit. Best-effort: a failed delete costs memory on the
        master, never correctness."""
        r = finished_round - self.GC_LAG
        step = self._round_steps.pop(r, None)
        if step is None:
            return
        for key in (self._k("prep", r, step, self.rank),
                    self._k("abort", r, step)):
            try:
                self.store.delete_key(key)
            except Exception:
                pass

    def _gc_resume_keys(self, finished_round: int):
        """Same lag-2 rule for resume-negotiation keys."""
        r = finished_round - self.GC_LAG
        if r < 1:  # resume rounds start at 1
            return
        for key in (self._k("resume", r, self.rank),
                    self._k("resume_abort", r)):
            try:
                self.store.delete_key(key)
            except Exception:
                pass

    def _wait_keys(self, keys, deadline: float,
                   abort_key: Optional[str] = None) -> str:
        """Poll until every key exists -> 'ok'; abort flag -> 'abort';
        deadline -> 'timeout'."""
        missing = list(keys)
        while True:
            if abort_key is not None and self.store.check(abort_key):
                return "abort"
            missing = [k for k in missing if not self.store.check(k)]
            if not missing:
                return "ok"
            if time.time() >= deadline:
                return "timeout"
            time.sleep(self.poll_interval)

    def mark_abort(self, step: int, reason: str,
                   round_id: Optional[int] = None):
        """Publish the abort flag for `step` (best effort) and count it.
        `round_id` defaults to the round the NEXT local `commit()` would
        run — the right value for a host poisoning a round it has not
        entered itself (commit passes its own round explicitly)."""
        if round_id is None:
            round_id = self._commit_round
        self._round_steps.setdefault(int(round_id), int(step))
        try:
            self.store.set(self._k("abort", int(round_id), int(step)), reason)
        except Exception:
            pass  # store gone: peers will hit their own timeout
        if _metrics_mod.enabled():
            _M_BARRIER_ABORTS.inc(reason=reason)
        _events_mod.emit("barrier_abort", severity="warn", step=int(step),
                         round=int(round_id), reason=reason)

    def abort_next_round(self, step: int, reason: str = "error"):
        """Poison and CONSUME the round this host would run for `step` —
        for failures BEFORE commit() was entered (prepare-phase errors).
        Peers already in commit() for this step observe a prompt abort
        instead of burning the barrier timeout, and if this host survives
        and keeps training its round counter stays lockstep with the
        fleet's (otherwise every later save would land on a stale round)."""
        round_id = self._commit_round
        self._commit_round += 1
        self.mark_abort(step, reason, round_id)

    def commit(self, step: int, publish_fn: Callable[[], None]) -> bool:
        """Run the commit phase for `step`; `publish_fn` performs the local
        atomic rename. True = committed everywhere we can observe; False =
        aborted (caller must GC its tmp). Raises whatever `publish_fn` or
        the store raises after flagging the abort for the peers."""
        from ..fault import site as _fault_site
        step = int(step)
        # one round id per commit() call, consumed even on abort — hosts
        # run the same save sequence, so a re-used step number can never
        # see a previous round's votes or abort flag
        round_id = self._commit_round
        self._commit_round += 1
        self._round_steps[round_id] = step
        abort_key = self._k("abort", round_id, step)
        try:
            # a kill injected here (host dies between prepare and commit)
            # has a durable tmp but never votes NOR flags: peers time out
            # and abort, and no final file appears anywhere. A non-fatal
            # failure anywhere in the phase flags the abort below so peers
            # observe a prompt peer_abort instead of burning the timeout.
            _fault_site("ckpt.commit")
            self.store.set(self._k("prep", round_id, step, self.rank), "1")
            prep_keys = [self._k("prep", round_id, step, r)
                         for r in range(self.world_size)]
            t0 = time.perf_counter()
            outcome = self._wait_keys(prep_keys, time.time() + self.timeout,
                                      abort_key)
            if _metrics_mod.enabled():
                _M_BARRIER_WAIT.observe(time.perf_counter() - t0)
            if outcome != "ok":
                reason = "peer_abort" if outcome == "abort" else "timeout"
                self.mark_abort(step, reason, round_id)
                self._gc_round_keys(round_id)
                return False
            if self.store.check(abort_key):
                # a slower host timed out after we saw all votes: honor it
                self.mark_abort(step, "peer_abort", round_id)
                self._gc_round_keys(round_id)
                return False
            # publish_fn is the LAST in-phase operation: anything after the
            # rename that could fail would mark_abort a round this host has
            # already committed on disk — peers would GC their prepared
            # tmps and the fleet's newest-committed steps would diverge
            publish_fn()
        except BaseException:
            self.mark_abort(step, "error", round_id)
            raise
        if _metrics_mod.enabled():
            _M_BARRIER_COMMITS.inc()
        _events_mod.emit("barrier_commit", step=step, round=round_id)
        self._gc_round_keys(round_id)
        return True

    def negotiate_resume(self, local_step: Optional[int]) -> Optional[int]:
        """Fleet agreement on the resume step: publish this host's newest
        locally-valid committed step, wait for every host, return the
        minimum — the newest step that exists on ALL hosts. Returns None
        (fresh start) when any host has nothing. Hosts must call this in
        lockstep (same number of times per generation).

        Consistency over availability: a wait timeout poisons the round
        (abort flag) and RAISES. Falling back to the local step here would
        split-brain the fleet — a peer arriving just past the deadline
        finds every key present, resumes the fleet minimum, and trains
        against this host's different parameters with no error anywhere.
        A fleet that cannot assemble within the deadline cannot train
        (collectives need every host), so failing loudly and letting the
        elastic supervisor's budget drive relaunch is strictly safer."""
        self._resume_round += 1
        abort_key = self._k("resume_abort", self._resume_round)
        mine = -1 if local_step is None else int(local_step)
        self.store.set(self._k("resume", self._resume_round, self.rank),
                       str(mine))
        keys = [self._k("resume", self._resume_round, r)
                for r in range(self.world_size)]
        outcome = self._wait_keys(keys, time.time() + self.resume_timeout,
                                  abort_key)
        if outcome != "ok" or self.store.check(abort_key):
            try:
                self.store.set(abort_key, "timeout")
            except Exception:
                pass  # store gone: peers hit their own timeout
            raise RuntimeError(
                f"checkpoint resume negotiation "
                f"{'abandoned by a peer' if outcome == 'abort' else 'timed out'}"
                f" after {self.resume_timeout}s waiting for "
                f"{self.world_size} hosts (rank {self.rank}); refusing to "
                f"fall back to a local step — peers that did assemble "
                f"would resume a different one. Relaunch the fleet "
                f"together (the elastic supervisor does this).")
        steps = [int(self.store.get(k).decode()) for k in keys]
        self._gc_resume_keys(self._resume_round)
        if any(s < 0 for s in steps):
            return None
        return min(steps)


def coordinator_from_env(timeout: Optional[float] = None,
                         resume_timeout: Optional[float] = None
                         ) -> Optional[CheckpointCoordinator]:
    """Build a CheckpointCoordinator from the standard trainer env contract
    (PADDLE_TRAINERS_NUM / PADDLE_TRAINER_ID / MASTER_ADDR / MASTER_PORT —
    what `paddle_tpu.distributed.launch` and `tools/elastic_run.py` export),
    or None for single-host jobs / when `PADDLE_TPU_CKPT_BARRIER=0`.

    Opens its OWN store client connection — the native client is one socket
    and the barrier must not interleave frames with init_parallel_env's
    rendezvous traffic."""
    if os.environ.get("PADDLE_TPU_CKPT_BARRIER", "1") == "0":
        return None
    try:
        world = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    except ValueError:
        return None
    if world < 2 or not os.environ.get("MASTER_ADDR") \
            or not os.environ.get("MASTER_PORT"):
        return None
    try:
        port = int(os.environ["MASTER_PORT"])
    except ValueError:
        # NOT a silent degrade: PADDLE_TRAINERS_NUM says this host is part
        # of a >=2 fleet, so quietly returning None would disable the
        # checkpoint barrier on this host alone while its peers wait on it
        raise ValueError(
            f"MASTER_PORT={os.environ['MASTER_PORT']!r} is not a port "
            f"number but PADDLE_TRAINERS_NUM={world} expects a coordinated "
            f"fleet; fix the launcher env (tools/elastic_run.py exports it) "
            f"or set PADDLE_TPU_CKPT_BARRIER=0 to opt out of the barrier")
    try:
        rank = int(os.environ["PADDLE_TRAINER_ID"])
    except (KeyError, ValueError):
        # defaulting to rank 0 here would have EVERY host of the fleet
        # publish prepare votes as rank 0 and wait forever for the others:
        # each coordinated save burns the barrier timeout with no message
        # naming the real cause
        raise ValueError(
            f"PADDLE_TRAINER_ID={os.environ.get('PADDLE_TRAINER_ID')!r} "
            f"but PADDLE_TRAINERS_NUM={world} expects a coordinated fleet; "
            f"every host needs a distinct rank (tools/elastic_run.py "
            f"exports it from --rank) or set PADDLE_TPU_CKPT_BARRIER=0 to "
            f"opt out of the barrier")
    from .store import TCPStore
    store = TCPStore(os.environ["MASTER_ADDR"], port, is_master=False)
    return CheckpointCoordinator(store, rank, world, timeout=timeout,
                                 resume_timeout=resume_timeout)


def detect_layout(dirname: str, prefix: str = "ckpt") -> Optional[str]:
    """What checkpoint layout lives in `dirname`: "sharded" (step
    DIRECTORIES holding PTSHARD01 manifests/chunks), "file" (monolithic
    `<prefix>_<step>` files), or None (empty/fresh directory).

    A directory holding BOTH (a run migrated from the file layout to the
    sharded one in place) resolves to the layout of the NEWEST step —
    resume must follow the most recent progress, never the accident of
    os.listdir order. A tie on step number prefers "sharded" (the file
    of that step is the older artifact of the two writers)."""
    if not os.path.isdir(dirname):
        return None
    from .sharded_checkpoint import _step_dirs, is_step_dir
    files = _step_files(dirname, prefix)
    dirs = [(s, p) for s, p in _step_dirs(dirname, prefix)
            if is_step_dir(p)]
    if not files and not dirs:
        return None
    if not dirs:
        return "file"
    if not files:
        return "sharded"
    return "file" if files[0][0] > dirs[0][0] else "sharded"


def open_manager(dirname: str, layout: str = "auto", prefix: str = "ckpt",
                 **kw) -> "CheckpointManager":
    """Build the right CheckpointManager for `dirname`.

    `layout`: "file" (monolithic per-host pickles, the PR-3/PR-5 path),
    "sharded" (chunked shared-directory backend,
    `sharded_checkpoint.ShardedCheckpointManager`), or "auto" — detect
    from what is already on disk, defaulting to "file" for a fresh
    directory (pass "sharded" explicitly to start a new sharded run)."""
    if layout == "auto":
        layout = detect_layout(dirname, prefix) or "file"
    if layout == "sharded":
        from .sharded_checkpoint import ShardedCheckpointManager
        return ShardedCheckpointManager(dirname, prefix=prefix, **kw)
    if layout != "file":
        raise ValueError(f"unknown checkpoint layout {layout!r} "
                         f"(expected 'file', 'sharded' or 'auto')")
    return CheckpointManager(dirname, prefix=prefix, **kw)


class CheckpointManager:
    """Stepped checkpoints with GC, corruption-tolerant resume, and a
    preemption hook.

    usage::

        mgr = CheckpointManager(dir, keep_last_n=3)
        mgr.install_preemption_handler(lambda: capture_state())
        ...
        mgr.save(state, step=it)                 # atomic, CRC'd, GC'd
        ...
        restored = mgr.load_latest()             # (state, step) or None
    """

    layout = "file"

    def __init__(self, dirname: str, prefix: str = "ckpt",
                 keep_last_n: int = 5, async_save: bool = False,
                 mesh=None, coordinator: Optional[CheckpointCoordinator] = None,
                 store=None, rank: int = 0, world_size: int = 1,
                 barrier_timeout: Optional[float] = None):
        self.dirname = str(dirname)
        self.prefix = prefix
        self.keep_last_n = max(1, int(keep_last_n))
        self.async_save = async_save
        self.mesh = mesh
        if coordinator is None and store is not None and int(world_size) > 1:
            coordinator = CheckpointCoordinator(store, rank, world_size,
                                                timeout=barrier_timeout)
        # world_size == 1 degrades to the plain local save — no barrier
        self.coordinator = coordinator
        if coordinator is not None and self.keep_last_n < 2:
            # one step of commit skew between hosts is inherent to the
            # two-generals window: a host that renamed step N just before
            # the fleet died negotiates resume at N-1 (the fleet minimum),
            # and with keep_last_n=1 its own GC already deleted N-1 — the
            # agreed step would be unreadable here and every relaunch
            # would raise until the restart budget wedged the job
            self.keep_last_n = 2
        self._prev_sigterm = None
        self._preempt_state_fn: Optional[Callable[[], Any]] = None
        self._last_step: Optional[int] = None
        self._save_in_flight = False
        os.makedirs(self.dirname, exist_ok=True)
        if not _pending_saves:  # crashed predecessors only — never a tmp
            cleanup_tmp(self.dirname, self.prefix)  # still being written

    def path_for(self, step: int) -> str:
        return os.path.join(self.dirname, f"{self.prefix}_{int(step)}")

    def steps(self) -> List[int]:
        return [s for s, _ in _step_files(self.dirname, self.prefix)]

    def save(self, state: Any, step: int) -> bool:
        """Publish one checkpoint. Coordinated two-phase commit when a
        coordinator is configured (multi-host), plain atomic save
        otherwise. Returns False when a coordinated round aborted (the
        checkpoint was skipped fleet-wide); training should continue."""
        if self.coordinator is not None:
            committed = self._save_coordinated(state, step)
        else:
            save(state, self.path_for(step), async_save=self.async_save)
            committed = True
        self._last_step = int(step)
        self.gc()
        return committed

    def _save_coordinated(self, state: Any, step: int) -> bool:
        """Two-phase commit of step N: durable tmp (prepare), then the
        coordinator's all-or-nothing rename (commit). Always synchronous —
        a barrier over a background write would publish a file the fleet
        already voted on while this host could still fail the write."""
        # the in-flight flag covers the WHOLE save, prepare included: a
        # SIGTERM during _to_host/tmp-write/fsync (the longest phase of a
        # multi-GB save) re-entering a nested coordinated save would
        # consume a round id peers spend on a different step
        self._save_in_flight = True
        try:
            final = self.path_for(step)
            tmp = final + ".tmp.prep"
            try:
                t0 = time.perf_counter()
                specs: Dict[str, tuple] = {}
                host_state = _to_host(state, specs)
                with open(tmp, "wb") as f:
                    f.write(_encode_snapshot(host_state, specs))
                    f.flush()
                    os.fsync(f.fileno())
            except BaseException:
                # prepare failed (disk full, SIGTERM-driven SystemExit, …):
                # poison + consume this host's round so peers abort
                # promptly instead of burning the barrier timeout, and so
                # a caller that survives and keeps training stays round-
                # lockstep with the fleet
                self.coordinator.abort_next_round(step)
                self._rm_quiet(tmp)
                raise
            # write time only — the commit wait is already measured by
            # ckpt_barrier_wait_seconds, and folding a slow peer's 60s
            # barrier into checkpoint_save_seconds would misread skew as
            # an I/O cost
            write_secs = time.perf_counter() - t0
            try:
                committed = self.coordinator.commit(
                    step, lambda: os.replace(tmp, final))
            except BaseException:
                # commit() already flagged the abort for the peers (unless
                # the process was killed outright); here just drop the tmp
                # and surface the error
                self._rm_quiet(tmp)
                raise
            if not committed:
                self._rm_quiet(tmp)
                warnings.warn(
                    f"coordinated checkpoint step {int(step)} aborted — "
                    f"not every host prepared in time; no host published a "
                    f"final file for this step (see "
                    f"ckpt_barrier_aborts_total)")
                return False
            if _metrics_mod.enabled():
                _M_SAVES.inc()
                _M_SAVE_SECONDS.observe(write_secs)
            return True
        finally:
            self._save_in_flight = False

    @staticmethod
    def _rm_quiet(path: str):
        try:
            os.remove(path)
        except OSError:
            pass

    def gc(self) -> int:
        """Keep the newest `keep_last_n` checkpoints; drop the rest and any
        orphaned tmp files. The tmp sweep only runs while no async save is
        in flight — a live writer's tmp file is not an orphan, and sweeping
        it would kill the publish mid-write."""
        removed = 0
        if not _pending_saves:
            removed = cleanup_tmp(self.dirname, self.prefix)
        for step, path in _step_files(self.dirname, self.prefix)[
                self.keep_last_n:]:
            try:
                os.remove(path)
                removed += 1
                if _metrics_mod.enabled():
                    _M_GC.inc()
            except OSError:
                pass
        return removed

    def drain(self):
        """Block until every background save this manager may have issued
        is published; re-raises the first background failure (a silently
        lost checkpoint is worse than a late crash). Call at end of
        training — the async writer is a daemon thread, and a process
        exiting right after `fit()` would otherwise reap it mid-write,
        leaving the final checkpoint torn while `save()` reported it
        submitted."""
        wait_all()

    def latest_valid_path(self) -> Optional[str]:
        if self.async_save:
            wait_all()  # a half-written newest file must finish publishing
        return latest_valid(self.dirname, self.prefix)

    def _local_latest_valid(self) -> Tuple[Optional[int], Optional[dict]]:
        """(step, decoded blob) of the newest locally-valid checkpoint, or
        (None, None). Decodes rather than just CRC-verifying: the agreed
        resume step is almost always this file, and re-reading a multi-GB
        blob after negotiation would double restore I/O on the
        preemption-recovery critical path. Under valid-only resume
        (PADDLE_TPU_RESUME_VALID_ONLY, the fleet-rollback relaunch mode)
        CRC-valid blobs holding NaN/Inf weights are walked past too, so
        the fleet negotiation runs over NUMERICALLY-valid steps."""
        valid_only = resume_valid_only()
        for step, path in _step_files(self.dirname, self.prefix):
            try:
                with open(path, "rb") as f:
                    blob = _decode(path, f.read())
            except (OSError, CheckpointCorruptError) as e:
                warnings.warn(f"skipping corrupt checkpoint {path}: {e}")
                if _metrics_mod.enabled():
                    _M_CORRUPT.inc()
                continue
            if valid_only and not tree_finite(blob.get("state")):
                _note_nonfinite_skip(path)
                continue
            return step, blob
        return None, None

    def load_latest(self) -> Optional[Tuple[Any, int]]:
        """(state, step) from the newest VALID checkpoint, or None.

        Coordinated managers negotiate first: the fleet resumes from the
        newest step committed on EVERY host (the barrier-committed step),
        never this host's lexically-newest file — a host that renamed just
        before the fleet died may be one step ahead of its peers."""
        # drain in-process async saves unconditionally: THIS manager may be
        # sync while another writer (a prior fit's callback) is still
        # publishing into the same directory
        wait_all()
        if self.coordinator is not None:
            local_step, local_blob = self._local_latest_valid()
            agreed = self.coordinator.negotiate_resume(local_step)
            if agreed is None:
                return None
            if agreed == local_step:
                blob = local_blob  # already read + CRC'd: don't re-read
            else:
                blob = self._read_agreed(agreed)
            if _metrics_mod.enabled():
                _M_LOADS.inc()
            return (_apply_shardings(blob["state"], blob.get("specs", {}),
                                     self.mesh), agreed)
        found = load_latest_valid(self.dirname, self.prefix, mesh=self.mesh)
        if found is None:
            return None
        state, step, _ = found
        return state, step

    def _read_agreed(self, agreed: int) -> dict:
        """Read the fleet-agreed resume step when it is NOT this host's
        newest valid file (a peer was behind)."""
        path = self.path_for(agreed)
        try:
            with open(path, "rb") as f:
                blob = _decode(path, f.read())
        except (OSError, CheckpointCorruptError) as e:
            # do NOT fall back locally: peers are restoring the agreed
            # step, so a silent fresh start (or an older local step)
            # would resume this host with divergent parameters that
            # data-parallel all_reduce then averages into the run.
            # Failing loudly names the file so an operator can restore
            # or delete it fleet-wide.
            if _metrics_mod.enabled():
                _M_CORRUPT.inc()
            raise CheckpointCorruptError(
                path,
                f"fleet-agreed resume step {agreed} is unreadable on "
                f"this host ({e}); refusing to diverge from peers that "
                f"can read it") from e
        if resume_valid_only() and not tree_finite(blob.get("state")):
            # the agreed step must honor the valid-only guarantee on EVERY
            # host: silently restoring a nonfinite local copy would resume
            # diverged weights that data-parallel all_reduce averages into
            # the run — fail loudly like the unreadable case (the
            # supervisor relaunches and the fleet renegotiates)
            if _metrics_mod.enabled():
                _M_SKIP_NONFINITE.inc()
            raise CheckpointCorruptError(
                path,
                f"fleet-agreed resume step {agreed} holds nonfinite "
                f"weights on this host under valid-only resume")
        return blob

    def _publish_sync(self, state: Any, step: int) -> bool:
        """One synchronous publish through the configured path: the
        coordinated two-phase commit when a coordinator is present (TPU-pod
        preemption SIGTERMs every host at once, so the fleet barriers the
        final save too), plain local save when world_size == 1."""
        if self.coordinator is not None:
            return self._save_coordinated(state, step)
        save(state, self.path_for(step), async_save=False)
        return True

    # -- preemption ---------------------------------------------------------
    def install_preemption_handler(self, state_fn: Callable[[], Any],
                                   step_fn: Optional[Callable[[], int]] = None):
        """On SIGTERM (the TPU-pod preemption signal) perform ONE final
        synchronous save of `state_fn()` at step `step_fn()` before exiting.
        Routes through the coordinated barrier when configured. Chains any
        previously installed handler; without one, exits 143."""
        self._preempt_state_fn = state_fn
        self._preempt_step_fn = step_fn

        def handler(signum, frame):
            if self.coordinator is not None and self._save_in_flight:
                # SIGTERM landed INSIDE an in-flight coordinated save (the
                # handler runs on the main thread, interrupting commit()'s
                # wait loop): re-entering commit() here would consume a
                # second round id mid-round while peers not mid-save run
                # their preemption round at the old one — mismatched
                # rounds, every host burning the full barrier timeout in
                # its preemption grace period. Skip the extra save: the
                # SystemExit below unwinds through the in-flight save
                # (prepare or commit phase alike), which flags a PROMPT
                # abort for the peers, and the fleet resumes from the
                # newest fully-committed step.
                warnings.warn("preemption during an in-flight coordinated "
                              "save: skipping the final preemption save "
                              "(resume uses the newest committed step)")
            else:
                try:
                    step = step_fn() if step_fn is not None else \
                        (self._last_step or 0) + 1
                    # synchronous even if the manager is async: the process
                    # is about to die, a background thread would be reaped
                    # mid-write
                    if self._publish_sync(state_fn(), step):
                        # only a COMMITTED save counts: an aborted barrier
                        # round published nothing anywhere, and reporting
                        # it would send the operator hunting for a step-N
                        # file that never existed
                        self._last_step = int(step)
                        if _metrics_mod.enabled():
                            _M_PREEMPT.inc()
                except Exception as e:
                    warnings.warn(f"preemption save failed: {e}")
            prev = self._prev_sigterm
            if callable(prev):
                prev(signum, frame)
            else:
                raise SystemExit(143)

        try:
            self._prev_sigterm = signal.signal(signal.SIGTERM, handler)
        except ValueError:  # not in the main thread: caller keeps polling
            self._prev_sigterm = None
            return False
        return True

    def uninstall_preemption_handler(self):
        if self._preempt_state_fn is None:
            return
        self._preempt_state_fn = None
        try:
            signal.signal(signal.SIGTERM,
                          self._prev_sigterm or signal.SIG_DFL)
        except ValueError:
            pass
        self._prev_sigterm = None
