"""Sharding-aware distributed checkpointing with corruption recovery.

Reference: the reference saves sharded state per rank with dist attrs and
re-shards on load (auto_parallel `dist_saver.py` + `converter.py`; stage-3
sharding gathers on save, `sharding/group_sharded.py:201`). TPU translation
follows the orbax/tensorstore pattern: save once from the addressable host
(jax gathers), record each array's PartitionSpec, and on restore
`jax.device_put` under the target sharding — mesh-shape changes re-shard
transparently. `save(..., async_save=True)` snapshots to host immediately
and writes in a background thread (the reference's async auto-checkpoint).

Robustness layer (reference `incubate/checkpoint/auto_checkpoint.py` +
fleet elastic):

* every file carries a fixed header — magic, format version, CRC32 and
  length of the pickled payload — so `load` detects truncated, bit-flipped,
  and torn files and raises `CheckpointCorruptError` instead of a pickle
  traceback;
* `latest_valid` walks checkpoints newest-first and returns the newest one
  that verifies, so a corrupt final snapshot costs one save interval, not
  the job;
* `CheckpointManager` adds keep-last-N garbage collection, orphaned
  `.tmp.*` cleanup, and a SIGTERM handler that performs one final
  synchronous save before exit (TPU-pod preemption sends SIGTERM).

Every save/load/skip/GC event lands in the metrics registry so recovery is
visible in the prometheus/JSON snapshot.
"""
from __future__ import annotations

import os
import pickle
import signal
import struct
import threading
import time
import warnings
import zlib
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor
from ..profiler import metrics as _metrics_mod

_REG = _metrics_mod.default_registry()
_M_SAVES = _REG.counter("checkpoint_saves_total",
                        "checkpoint files published (atomic replace)")
_M_LOADS = _REG.counter("checkpoint_loads_total",
                        "checkpoint files loaded and verified")
_M_CORRUPT = _REG.counter(
    "checkpoint_corrupt_skipped_total",
    "corrupt/truncated checkpoint files detected and skipped")
_M_GC = _REG.counter("checkpoint_gc_removed_total",
                     "checkpoint and orphaned tmp files garbage-collected")
_M_PREEMPT = _REG.counter(
    "checkpoint_preemption_saves_total",
    "final synchronous saves performed by the SIGTERM preemption handler")
_M_RESHARD_FALLBACK = _REG.counter(
    "checkpoint_reshard_fallback_total",
    "arrays whose saved sharding could not be applied and were replicated")
_M_SAVE_SECONDS = _REG.histogram("checkpoint_save_seconds",
                                 "wall time of checkpoint writes")

_pending_saves: list = []
_save_errors: list = []

# header: magic(8) | crc32(payload)(4, LE) | payload_len(8, LE)
_MAGIC = b"PTCKPT01"
_HEADER_FMT = struct.Struct("<8sIQ")

from ..framework.io import _atomic_write


class CheckpointCorruptError(RuntimeError):
    """A checkpoint file failed verification (truncated/bit-flipped/torn)."""

    def __init__(self, path: str, reason: str):
        super().__init__(f"corrupt checkpoint {path}: {reason}")
        self.path = path
        self.reason = reason


def _spec_of(arr) -> Optional[tuple]:
    shard = getattr(arr, "sharding", None)
    spec = getattr(shard, "spec", None)
    if spec is None:
        return None
    return tuple(None if p is None else (tuple(p) if isinstance(p, tuple)
                                         else str(p)) for p in spec)


def _to_host(obj, specs: Dict[str, tuple], prefix: str = ""):
    if isinstance(obj, Tensor):
        obj = obj.data
    if isinstance(obj, jax.Array):
        s = _spec_of(obj)
        if s is not None:
            specs[prefix] = s
        return np.asarray(obj)
    if isinstance(obj, dict):
        return {k: _to_host(v, specs, f"{prefix}/{k}") for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_host(v, specs, f"{prefix}/{i}")
                         for i, v in enumerate(obj))
    return obj


def _encode(blob: dict) -> bytes:
    payload = pickle.dumps(blob, protocol=4)
    return _HEADER_FMT.pack(_MAGIC, zlib.crc32(payload) & 0xFFFFFFFF,
                            len(payload)) + payload


def _verified_payload(path: str, data: bytes) -> bytes:
    """Header+length+CRC check; returns the pickled payload or raises
    CheckpointCorruptError. Files without the magic are legacy plain
    pickles and pass through for best-effort unpickling."""
    if not data.startswith(_MAGIC):
        return data
    if len(data) < _HEADER_FMT.size:
        raise CheckpointCorruptError(path, "truncated header")
    _, crc, length = _HEADER_FMT.unpack_from(data)
    payload = data[_HEADER_FMT.size:]
    if len(payload) != length:
        raise CheckpointCorruptError(
            path, f"payload truncated: header says {length} bytes, "
                  f"file has {len(payload)}")
    if zlib.crc32(payload) & 0xFFFFFFFF != crc:
        raise CheckpointCorruptError(
            path, f"CRC32 mismatch (stored {crc:#010x})")
    return payload


def _decode(path: str, data: bytes) -> dict:
    """Verify header+CRC and unpickle; raises CheckpointCorruptError."""
    payload = _verified_payload(path, data)
    if not payload:
        raise CheckpointCorruptError(path, "empty file")
    try:
        blob = pickle.loads(payload)
    except Exception as e:
        raise CheckpointCorruptError(
            path, f"unpickle failed: {type(e).__name__}: {e}") from e
    if not isinstance(blob, dict) or "state" not in blob:
        raise CheckpointCorruptError(path, "payload is not a checkpoint blob")
    return blob


def save(state: Any, path: str, async_save: bool = False):
    """Checkpoint a pytree of arrays/Tensors with sharding metadata."""
    specs: Dict[str, tuple] = {}
    host_state = _to_host(state, specs)  # synchronous device->host snapshot

    def write():
        t0 = time.perf_counter()
        _atomic_write(path, _encode({"state": host_state, "specs": specs,
                                     "version": 2}))
        if _metrics_mod.enabled():
            _M_SAVES.inc()
            _M_SAVE_SECONDS.observe(time.perf_counter() - t0)

    def write_logged():
        try:
            write()
        except BaseException as e:  # surfaced by wait_all
            _save_errors.append(e)

    if async_save:
        t = threading.Thread(target=write_logged, daemon=True)
        t.start()
        _pending_saves.append(t)
    else:
        write()


def wait_all():
    """Block until every async save has been published; re-raises the first
    background failure (a silently lost checkpoint is worse than a crash)."""
    while _pending_saves:
        _pending_saves.pop().join()
    if _save_errors:
        err = _save_errors[0]
        _save_errors.clear()
        raise err


def _apply_shardings(obj, specs: Dict[str, tuple], mesh, prefix: str = ""):
    if isinstance(obj, np.ndarray):
        arr = jnp.asarray(obj)
        spec = specs.get(prefix)
        if spec is not None and mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            names = set(mesh.axis_names)
            cleaned = []
            for p in spec:
                # drop axes that do not exist in the TARGET mesh — restoring
                # onto a smaller/different mesh replicates those dims
                if p is None:
                    cleaned.append(None)
                elif isinstance(p, tuple):
                    kept = tuple(a for a in p if a in names)
                    cleaned.append(kept if kept else None)
                else:
                    cleaned.append(p if p in names else None)
            try:
                arr = jax.device_put(arr, NamedSharding(mesh, P(*cleaned)))
            except Exception as e:
                # incompatible spec (divisibility): keep replicated — but
                # LOUDLY, so silent replication can't masquerade as sharding
                warnings.warn(
                    f"checkpoint restore: could not apply saved sharding to "
                    f"{prefix or '<root>'} (spec={tuple(cleaned)}, "
                    f"mesh axes={dict(zip(mesh.axis_names, mesh.devices.shape))}"
                    f"): {type(e).__name__}: {e}; keeping the array "
                    f"replicated")
                if _metrics_mod.enabled():
                    _M_RESHARD_FALLBACK.inc(path=prefix or "<root>")
        return arr
    if isinstance(obj, dict):
        return {k: _apply_shardings(v, specs, mesh, f"{prefix}/{k}")
                for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_apply_shardings(v, specs, mesh, f"{prefix}/{i}")
                        for i, v in enumerate(obj))
    return obj


def load(path: str, mesh=None) -> Any:
    """Restore; with `mesh`, arrays are re-laid-out per their saved specs
    (axes missing from the target mesh fall back to replication).
    Raises CheckpointCorruptError (never a bare pickle traceback) when the
    file fails header/CRC verification."""
    with open(path, "rb") as f:
        data = f.read()
    blob = _decode(path, data)
    if _metrics_mod.enabled():
        _M_LOADS.inc()
    return _apply_shardings(blob["state"], blob.get("specs", {}), mesh)


def verify(path: str) -> Tuple[bool, Optional[str]]:
    """Cheap validity probe: (True, None) when the file's header, length
    and CRC check out (legacy files are fully unpickled to verify)."""
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError as e:
        return False, f"unreadable: {e}"
    try:
        if data.startswith(_MAGIC):
            # header verification only — no need to unpickle the payload
            _verified_payload(path, data)
        else:
            _decode(path, data)
    except CheckpointCorruptError as e:
        return False, e.reason
    return True, None


def _step_files(dirname: str, prefix: str) -> List[Tuple[int, str]]:
    """[(step, path)] for `<prefix>_<step>` files, newest step first."""
    if not os.path.isdir(dirname):
        return []
    out = []
    for fn in os.listdir(dirname):
        if not fn.startswith(prefix + "_") or ".tmp." in fn \
                or fn.endswith(".tmp"):
            continue
        try:
            step = int(fn.rsplit("_", 1)[1])
        except ValueError:
            continue
        out.append((step, os.path.join(dirname, fn)))
    out.sort(reverse=True)
    return out


def latest(dirname: str, prefix: str = "ckpt") -> Optional[str]:
    """Newest checkpoint file `<prefix>_<step>` in dirname, or None.
    Does NOT verify — use `latest_valid` when corruption is possible."""
    files = _step_files(dirname, prefix)
    return files[0][1] if files else None


def latest_valid(dirname: str, prefix: str = "ckpt") -> Optional[str]:
    """Newest checkpoint that passes verification; corrupt files are
    skipped with a warning + metric instead of crashing the resume."""
    for step, path in _step_files(dirname, prefix):
        ok, reason = verify(path)
        if ok:
            return path
        warnings.warn(f"skipping corrupt checkpoint {path}: {reason}")
        if _metrics_mod.enabled():
            _M_CORRUPT.inc()
    return None


def load_latest_valid(dirname: str, prefix: str = "ckpt",
                      mesh=None) -> Optional[Tuple[Any, int, str]]:
    """(state, step, path) from the newest checkpoint that decodes cleanly,
    or None. Each candidate is read and CRC-verified ONCE (the decode
    reuses the bytes) — restore is the preemption-recovery critical path
    and must not double a multi-GB file's I/O. Corrupt candidates warn,
    count, and fall through to the next-newest."""
    for step, path in _step_files(dirname, prefix):
        try:
            with open(path, "rb") as f:
                data = f.read()
            blob = _decode(path, data)
        except (OSError, CheckpointCorruptError) as e:
            warnings.warn(f"skipping corrupt checkpoint {path}: {e}")
            if _metrics_mod.enabled():
                _M_CORRUPT.inc()
            continue
        if _metrics_mod.enabled():
            _M_LOADS.inc()
        return (_apply_shardings(blob["state"], blob.get("specs", {}), mesh),
                step, path)
    return None


def cleanup_tmp(dirname: str, prefix: str = "ckpt") -> int:
    """Remove orphaned `<prefix>_*.tmp.*` files left by crashed writers."""
    if not os.path.isdir(dirname):
        return 0
    removed = 0
    for fn in os.listdir(dirname):
        if fn.startswith(prefix + "_") and ".tmp." in fn:
            try:
                os.remove(os.path.join(dirname, fn))
                removed += 1
            except OSError:
                pass
    if removed and _metrics_mod.enabled():
        _M_GC.inc(removed)
    return removed


class CheckpointManager:
    """Stepped checkpoints with GC, corruption-tolerant resume, and a
    preemption hook.

    usage::

        mgr = CheckpointManager(dir, keep_last_n=3)
        mgr.install_preemption_handler(lambda: capture_state())
        ...
        mgr.save(state, step=it)                 # atomic, CRC'd, GC'd
        ...
        restored = mgr.load_latest()             # (state, step) or None
    """

    def __init__(self, dirname: str, prefix: str = "ckpt",
                 keep_last_n: int = 5, async_save: bool = False,
                 mesh=None):
        self.dirname = str(dirname)
        self.prefix = prefix
        self.keep_last_n = max(1, int(keep_last_n))
        self.async_save = async_save
        self.mesh = mesh
        self._prev_sigterm = None
        self._preempt_state_fn: Optional[Callable[[], Any]] = None
        self._last_step: Optional[int] = None
        os.makedirs(self.dirname, exist_ok=True)
        if not _pending_saves:  # crashed predecessors only — never a tmp
            cleanup_tmp(self.dirname, self.prefix)  # still being written

    def path_for(self, step: int) -> str:
        return os.path.join(self.dirname, f"{self.prefix}_{int(step)}")

    def steps(self) -> List[int]:
        return [s for s, _ in _step_files(self.dirname, self.prefix)]

    def save(self, state: Any, step: int):
        save(state, self.path_for(step), async_save=self.async_save)
        self._last_step = int(step)
        self.gc()

    def gc(self) -> int:
        """Keep the newest `keep_last_n` checkpoints; drop the rest and any
        orphaned tmp files. The tmp sweep only runs while no async save is
        in flight — a live writer's tmp file is not an orphan, and sweeping
        it would kill the publish mid-write."""
        removed = 0
        if not _pending_saves:
            removed = cleanup_tmp(self.dirname, self.prefix)
        for step, path in _step_files(self.dirname, self.prefix)[
                self.keep_last_n:]:
            try:
                os.remove(path)
                removed += 1
                if _metrics_mod.enabled():
                    _M_GC.inc()
            except OSError:
                pass
        return removed

    def latest_valid_path(self) -> Optional[str]:
        if self.async_save:
            wait_all()  # a half-written newest file must finish publishing
        return latest_valid(self.dirname, self.prefix)

    def load_latest(self) -> Optional[Tuple[Any, int]]:
        """(state, step) from the newest VALID checkpoint, or None."""
        # drain in-process async saves unconditionally: THIS manager may be
        # sync while another writer (a prior fit's callback) is still
        # publishing into the same directory
        wait_all()
        found = load_latest_valid(self.dirname, self.prefix, mesh=self.mesh)
        if found is None:
            return None
        state, step, _ = found
        return state, step

    # -- preemption ---------------------------------------------------------
    def install_preemption_handler(self, state_fn: Callable[[], Any],
                                   step_fn: Optional[Callable[[], int]] = None):
        """On SIGTERM (the TPU-pod preemption signal) perform ONE final
        synchronous save of `state_fn()` at step `step_fn()` before exiting.
        Chains any previously installed handler; without one, exits 143."""
        self._preempt_state_fn = state_fn
        self._preempt_step_fn = step_fn

        def handler(signum, frame):
            try:
                step = step_fn() if step_fn is not None else \
                    (self._last_step or 0) + 1
                # synchronous even if the manager is async: the process is
                # about to die, a background thread would be reaped mid-write
                save(state_fn(), self.path_for(step), async_save=False)
                self._last_step = int(step)
                if _metrics_mod.enabled():
                    _M_PREEMPT.inc()
            except Exception as e:
                warnings.warn(f"preemption save failed: {e}")
            prev = self._prev_sigterm
            if callable(prev):
                prev(signum, frame)
            else:
                raise SystemExit(143)

        try:
            self._prev_sigterm = signal.signal(signal.SIGTERM, handler)
        except ValueError:  # not in the main thread: caller keeps polling
            self._prev_sigterm = None
            return False
        return True

    def uninstall_preemption_handler(self):
        if self._preempt_state_fn is None:
            return
        self._preempt_state_fn = None
        try:
            signal.signal(signal.SIGTERM,
                          self._prev_sigterm or signal.SIG_DFL)
        except ValueError:
            pass
        self._prev_sigterm = None
