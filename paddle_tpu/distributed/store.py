"""TCPStore — blocking KV rendezvous store (native-backed).

Python face of `paddle_tpu/_native/csrc/store.cc`; API mirrors the
reference's `core.TCPStore` (/root/reference/paddle/fluid/distributed/store/
tcp_store.h:91) as used by `init_parallel_env`
(`python/paddle/distributed/parallel.py:232`): the master rank hosts the
server in-process, every rank (master included) is a client.
"""
from __future__ import annotations

import ctypes
from typing import List, Optional

from .. import _native

_GET_CAP = 1 << 20


class TCPStore:
    def __init__(self, host: str, port: int, is_master: bool = False,
                 world_size: int = 1, timeout: int = 120):
        self._lib = _native.load()
        self._server_h: Optional[int] = None
        if is_master:
            self._server_h = self._lib.store_server_create(port)
            if self._server_h < 0:
                raise RuntimeError(f"TCPStore: cannot bind port {port}")
            port = self._lib.store_server_port(self._server_h)
        self._port = port
        self._h = self._lib.store_connect(host.encode(), port,
                                          int(timeout * 1000))
        if self._h < 0:
            raise RuntimeError(f"TCPStore: cannot connect {host}:{port}")

    @property
    def port(self) -> int:
        return self._port

    def set(self, key: str, value):
        if isinstance(value, str):
            value = value.encode()
        if self._lib.store_set(self._h, key.encode(), value, len(value)) != 0:
            raise RuntimeError("TCPStore.set failed")

    def get(self, key: str) -> bytes:
        buf = ctypes.create_string_buffer(_GET_CAP)
        n = self._lib.store_get(self._h, key.encode(), buf, _GET_CAP)
        if n < 0:
            raise RuntimeError("TCPStore.get failed")
        return buf.raw[:n]

    def add(self, key: str, delta: int) -> int:
        v = self._lib.store_add(self._h, key.encode(), delta)
        if v == -(2 ** 63):
            raise RuntimeError("TCPStore.add failed")
        return v

    def wait(self, keys: List[str]):
        arr = (ctypes.c_char_p * len(keys))(*[k.encode() for k in keys])
        if self._lib.store_wait(self._h, arr, len(keys)) != 0:
            raise RuntimeError("TCPStore.wait failed")

    def check(self, key: str) -> bool:
        rc = self._lib.store_check(self._h, key.encode())
        if rc < 0:
            raise RuntimeError("TCPStore.check failed")
        return bool(rc)

    def delete_key(self, key: str):
        if self._lib.store_delete(self._h, key.encode()) != 0:
            raise RuntimeError("TCPStore.delete failed")

    def stop(self):
        if self._server_h is not None:
            self._lib.store_server_stop(self._server_h)
            self._server_h = None

    def __del__(self):
        try:
            self.stop()
        except Exception:
            pass
