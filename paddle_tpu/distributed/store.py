"""TCPStore — blocking KV rendezvous store (native-backed).

Python face of `paddle_tpu/_native/csrc/store.cc`; API mirrors the
reference's `core.TCPStore` (/root/reference/paddle/fluid/distributed/store/
tcp_store.h:91) as used by `init_parallel_env`
(`python/paddle/distributed/parallel.py:232`): the master rank hosts the
server in-process, every rank (master included) is a client.

get/set/add run under a bounded retry+backoff policy (knobs:
`PADDLE_TPU_STORE_RETRIES` / `PADDLE_TPU_STORE_BACKOFF`, or pass
`retry=RetryPolicy(...)`): a transient master hiccup during rendezvous
should cost milliseconds, not the job. `add` is retried too — the native
call fails atomically before applying, but a network-partitioned success
whose ACK was lost would re-apply, so treat add as at-least-once under
retry. Each op declares a fault site (`store.get` etc.) for chaos tests.
"""
from __future__ import annotations

import ctypes
from typing import List, Optional

from .. import _native
from ..fault import RetryPolicy
from ..fault import site as _fault_site

_GET_CAP = 1 << 20


class TCPStore:
    def __init__(self, host: str, port: int, is_master: bool = False,
                 world_size: int = 1, timeout: int = 120,
                 retry: Optional[RetryPolicy] = None):
        self._retry = retry or RetryPolicy.from_env(
            "STORE", max_attempts=3, base_delay=0.05, max_delay=1.0)
        self._lib = _native.load()
        self._server_h: Optional[int] = None
        if is_master:
            self._server_h = self._lib.store_server_create(port)
            if self._server_h < 0:
                raise RuntimeError(f"TCPStore: cannot bind port {port}")
            port = self._lib.store_server_port(self._server_h)
        self._port = port
        self._h = self._lib.store_connect(host.encode(), port,
                                          int(timeout * 1000))
        if self._h < 0:
            raise RuntimeError(f"TCPStore: cannot connect {host}:{port}")

    @property
    def port(self) -> int:
        return self._port

    def set(self, key: str, value):
        if isinstance(value, str):
            value = value.encode()

        def _do():
            _fault_site("store.set")
            if self._lib.store_set(self._h, key.encode(), value,
                                   len(value)) != 0:
                raise RuntimeError(f"TCPStore.set({key!r}) failed")
        self._retry.call(_do, op="store.set")

    def get(self, key: str) -> bytes:
        def _do():
            _fault_site("store.get")
            buf = ctypes.create_string_buffer(_GET_CAP)
            n = self._lib.store_get(self._h, key.encode(), buf, _GET_CAP)
            if n < 0:
                raise RuntimeError(f"TCPStore.get({key!r}) failed")
            return buf.raw[:n]
        return self._retry.call(_do, op="store.get")

    def add(self, key: str, delta: int) -> int:
        def _do():
            _fault_site("store.add")
            v = self._lib.store_add(self._h, key.encode(), delta)
            if v == -(2 ** 63):
                raise RuntimeError(f"TCPStore.add({key!r}) failed")
            return v
        return self._retry.call(_do, op="store.add")

    def wait(self, keys: List[str]):
        arr = (ctypes.c_char_p * len(keys))(*[k.encode() for k in keys])
        if self._lib.store_wait(self._h, arr, len(keys)) != 0:
            raise RuntimeError("TCPStore.wait failed")

    def check(self, key: str) -> bool:
        # retried like get/set/add: the coordinated-checkpoint barrier
        # polls through check(), and a transient master hiccup mid-poll
        # must cost a backoff, not a fleet-wide checkpoint abort
        def _do():
            _fault_site("store.check")
            rc = self._lib.store_check(self._h, key.encode())
            if rc < 0:
                raise RuntimeError(f"TCPStore.check({key!r}) failed")
            return bool(rc)
        return self._retry.call(_do, op="store.check")

    def delete_key(self, key: str):
        if self._lib.store_delete(self._h, key.encode()) != 0:
            raise RuntimeError("TCPStore.delete failed")

    def stop(self):
        if self._server_h is not None:
            self._lib.store_server_stop(self._server_h)
            self._server_h = None

    def __del__(self):
        try:
            self.stop()
        except Exception:
            pass
