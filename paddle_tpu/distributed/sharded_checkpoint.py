"""Sharded, chunked, async checkpoint backend (orbax/tensorstore-style).

The PR-3/PR-5 checkpoint stack writes ONE monolithic CRC'd pickle per host,
blocks the step loop for the full serialize+fsync, requires a private
directory per host, and can only resume into the same world size. This
backend removes all four limits:

* **per-array chunked on-disk format** — a checkpoint step is a DIRECTORY
  ``<prefix>_<step>/`` holding one raw-bytes file per array shard plus one
  JSON manifest per rank. Every rank's manifest records the full tree
  structure (deterministic across ranks), the global shape/dtype/
  PartitionSpec of every array, the mesh axes, the world size, and a
  CRC32 + byte length for each chunk *this rank wrote*. Chunk files and
  manifests are rank- and generation/attempt-namespaced, so hosts sharing
  one NFS/GCS-style directory never clobber each other — the per-host-dir
  restriction the ``CheckpointCoordinator`` docstring used to document is
  closed by this layout.
* **async save off the step critical path** — ``save()`` snapshots
  device→host synchronously (cheap: one transfer), then a bounded
  background writer thread serializes/fsyncs while training continues.
  ``checkpoint_async_pending`` / ``checkpoint_async_bytes`` /
  ``checkpoint_async_seconds`` make the hidden cost visible, and a save
  submitted while the previous one is still in flight blocks (bounded
  memory: at most one queued snapshot). Coordinated saves run their
  two-phase barrier ON the writer thread, after the write drains — hosts
  submit the same save sequence, so round ids stay lockstep.
* **elastic re-sharding restore** — ``load_step`` takes the NEW mesh and
  reassembles each array from whichever chunks exist (reading only the
  chunks that overlap what this host's NamedSharding needs), then places
  it via ``jax.make_array_from_callback`` under the new PartitionSpec.
  A checkpoint restores onto a DIFFERENT host count through one
  world-size-agnostic path (2→1 and 1→2 proven bit-identical end to end
  in tests/test_elastic_reshard_e2e.py); axes missing from the target
  mesh replicate with the same loud warning + metric as the file
  backend.

Commit protocol (shared directory safe): prepare writes this rank's chunk
files and ``manifest-r<rank>.json.tmp.prep`` (fsync'd); the commit phase —
the existing ``CheckpointCoordinator`` two-phase barrier — renames only
this rank's manifest. A step is *complete* when every rank's manifest of
its world size verifies, *partial* when manifests/chunks are missing but
the surviving chunks still cover every array (restore proceeds), *torn*
when only ``.tmp.prep`` manifests exist (barrier abort / death between
prepare and commit — skipped by resume, GC'd later). After each commit,
rank r additionally replicates peer ``(r+1)%world``'s committed manifest
to ``manifest-r<peer>.json.mirror`` (retried lag-1 from the next save),
so losing one owner's manifest file degrades the step to ``partial`` —
restorable from the mirror — instead of orphaning that rank's chunks.

Fault sites: ``ckpt.chunk_write`` (per chunk file write — a writer-thread
death mid-save aborts the barrier round promptly via
``abort_next_round``, so peers see ``peer_abort`` instead of burning the
barrier timeout) and ``ckpt.reshard`` (restore-side reassembly).
"""
from __future__ import annotations

import base64
import json
import os
import pickle
import shutil
import threading
import time
import warnings
import zlib
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor
from ..profiler import metrics as _metrics_mod
from . import checkpoint as _ck
from .checkpoint import CheckpointCorruptError, CheckpointManager

_REG = _metrics_mod.default_registry()
_M_ASYNC_PENDING = _REG.gauge(
    "checkpoint_async_pending",
    "background checkpoint saves queued or in flight on this host")
_M_ASYNC_BYTES = _REG.counter(
    "checkpoint_async_bytes",
    "bytes written to disk by the background checkpoint writer")
_M_ASYNC_SECONDS = _REG.histogram(
    "checkpoint_async_seconds",
    "wall time of background checkpoint writes (the cost hidden off the "
    "step critical path)")

MANIFEST_MAGIC = "PTSHARD01"
_MANIFEST_VERSION = 1


def _manifest_name(rank: int) -> str:
    return f"manifest-r{int(rank)}.json"


_MIRROR_SUFFIX = ".mirror"


def _mirror_name(rank: int) -> str:
    """Peer-written replica of rank `rank`'s manifest: after each commit,
    rank r copies rank (r+1)%world's committed manifest to this name, so
    losing (or corrupting) one owner's manifest file still leaves a
    readable copy and the step stays `partial`-restorable instead of
    dropping a rank's chunks on the floor."""
    return _manifest_name(rank) + _MIRROR_SUFFIX


def _parse_manifest_name(fn: str) -> Optional[int]:
    if fn.startswith("manifest-r") and fn.endswith(".json"):
        try:
            return int(fn[len("manifest-r"):-len(".json")])
        except ValueError:
            return None
    return None


def is_step_dir(path: str) -> bool:
    """Is `path` a sharded/chunked step DIRECTORY? The one definition of
    the on-disk detection predicate — `checkpoint.detect_layout` and
    `tools/ckpt_inspect.py` both delegate here so the inspector and the
    layout auto-detector can never disagree about a directory."""
    if not os.path.isdir(path):
        return False
    try:
        return any(fn.startswith("manifest-r") or fn.endswith(".chunk")
                   for fn in os.listdir(path))
    except OSError:
        return False


# ---------------------------------------------------------------------------
# snapshot: device -> host, preserving shard structure
# ---------------------------------------------------------------------------

@dataclass
class _ArraySnap:
    shape: Tuple[int, ...]
    dtype: str
    spec: Optional[tuple]
    # [(index_boxes, np_array)] — index is [[start, stop], ...] per dim
    chunks: List[tuple] = field(default_factory=list)
    # False only for arrays jax shards across NON-addressable devices
    # (a real multi-host pod): then every host must write its own shards
    # and the single-owner dedup below does not apply
    fully_addressable: bool = True


@dataclass
class _Snapshot:
    tree: Any                      # JSON-able skeleton
    arrays: Dict[str, _ArraySnap]  # tree path -> snap
    mesh_axes: Optional[Dict[str, int]] = None


def _norm_index(index, shape) -> List[List[int]]:
    """Normalize a shard's tuple-of-slices index to [[start, stop], ...]."""
    out = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = int(dim) if sl.stop is None else int(sl.stop)
        out.append([start, stop])
    return out


def _box_volume(box) -> int:
    v = 1
    for a, b in box:
        v *= max(0, b - a)
    return v


def _whole_box(shape) -> List[List[int]]:
    return [[0, int(d)] for d in shape]


def snapshot_tree(state: Any) -> _Snapshot:
    """Synchronous device→host snapshot preserving shard structure.

    Array leaves (jax arrays / Tensors / np arrays) become `_ArraySnap`s
    with one host-side chunk per addressable replica-0 shard; everything
    else lands inline in the JSON skeleton (exotic leaves as base64
    pickle). This is the only part of a save that must run on the step
    thread — writing the chunks is the background writer's job."""
    snap = _Snapshot(tree=None, arrays={})

    def walk(obj, prefix):
        if isinstance(obj, Tensor):
            obj = obj.data
        if isinstance(obj, jax.Array):
            spec = _ck._spec_of(obj)
            shard_list = []
            addressable = True
            sharding = getattr(obj, "sharding", None)
            mesh = getattr(sharding, "mesh", None)
            if mesh is not None and snap.mesh_axes is None:
                try:
                    snap.mesh_axes = dict(zip(
                        mesh.axis_names, (int(d) for d in mesh.devices.shape)))
                except Exception:
                    pass
            try:
                addressable = bool(getattr(sharding, "is_fully_addressable",
                                           True))
                for sh in obj.addressable_shards:
                    if getattr(sh, "replica_id", 0) != 0:
                        continue
                    shard_list.append((_norm_index(sh.index, obj.shape),
                                       np.asarray(sh.data)))
            except Exception:
                shard_list = []
            if not shard_list:
                shard_list = [(_whole_box(obj.shape), np.asarray(obj))]
            snap.arrays[prefix] = _ArraySnap(
                shape=tuple(int(d) for d in obj.shape),
                dtype=str(np.asarray(shard_list[0][1]).dtype),
                spec=spec, chunks=shard_list,
                fully_addressable=addressable)
            return {"__ptarray__": prefix}
        if isinstance(obj, np.ndarray):
            snap.arrays[prefix] = _ArraySnap(
                shape=tuple(obj.shape), dtype=str(obj.dtype), spec=None,
                chunks=[(_whole_box(obj.shape), obj)])
            return {"__ptarray__": prefix}
        if isinstance(obj, dict):
            if all(isinstance(k, str) and not k.startswith("__pt")
                   for k in obj):
                return {k: walk(v, f"{prefix}/{k}") for k, v in obj.items()}
            return {"__ptdict__": [
                [walk(k, f"{prefix}/k{i}"), walk(v, f"{prefix}/{i}")]
                for i, (k, v) in enumerate(obj.items())]}
        if isinstance(obj, tuple):
            return {"__pttuple__": [walk(v, f"{prefix}/{i}")
                                    for i, v in enumerate(obj)]}
        if isinstance(obj, list):
            return [walk(v, f"{prefix}/{i}") for i, v in enumerate(obj)]
        if obj is None or isinstance(obj, (bool, int, float, str)):
            return obj
        return {"__ptpickle__": base64.b64encode(
            pickle.dumps(obj, protocol=4)).decode("ascii")}

    snap.tree = walk(state, "")
    return snap


def _decode_tree(node, arrays: Dict[str, Any]):
    """Rebuild the pytree from a manifest skeleton + restored arrays."""
    if isinstance(node, dict):
        if "__ptarray__" in node:
            return arrays[node["__ptarray__"]]
        if "__pttuple__" in node:
            return tuple(_decode_tree(v, arrays)
                         for v in node["__pttuple__"])
        if "__ptdict__" in node:
            return {_decode_tree(k, arrays): _decode_tree(v, arrays)
                    for k, v in node["__ptdict__"]}
        if "__ptpickle__" in node:
            return pickle.loads(base64.b64decode(node["__ptpickle__"]))
        return {k: _decode_tree(v, arrays) for k, v in node.items()}
    if isinstance(node, list):
        return [_decode_tree(v, arrays) for v in node]
    return node


def _np_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # jax dependency: bfloat16 & friends
        return np.dtype(getattr(ml_dtypes, name))


def owner_rank(path: str, world_size: int) -> int:
    """Deterministic fleet-level owner of a host-replicated array: exactly
    one rank writes it, spreading load by tree path. Arrays jax shards
    across non-addressable devices skip this dedup (every host owns its
    local shards)."""
    return zlib.crc32(path.encode()) % max(1, int(world_size))


# ---------------------------------------------------------------------------
# write side
# ---------------------------------------------------------------------------

def write_shards(step_dir: str, step: int, rank: int, world_size: int,
                 snap: _Snapshot, *, generation: Optional[int] = None,
                 attempt: int = 0) -> Tuple[str, int]:
    """Prepare phase: write this rank's chunk files + its manifest to
    ``manifest-r<rank>.json.tmp.prep`` (everything fsync'd). Returns
    (manifest_tmp_path, bytes_written). Nothing is visible to readers
    until the manifest is renamed (the commit)."""
    from ..fault import site as _fault_site
    if generation is None:
        from ..utils.envparse import env_int
        generation = env_int("PADDLE_TPU_ELASTIC_RESTART_NUM", 0)
    os.makedirs(step_dir, exist_ok=True)
    rank, world_size = int(rank), max(1, int(world_size))
    suffix = f"g{int(generation)}a{int(attempt)}"
    chunk_records = []
    arrays_meta = {}
    nbytes_total = 0
    seq = 0
    for path in sorted(snap.arrays):
        a = snap.arrays[path]
        arrays_meta[path] = {
            "shape": list(a.shape), "dtype": a.dtype,
            "spec": _spec_to_json(a.spec),
        }
        if a.fully_addressable and owner_rank(path, world_size) != rank:
            continue  # another rank owns this replicated array's bytes
        for box, arr in a.chunks:
            fn = f"r{rank}-{seq:04d}.{suffix}.chunk"
            seq += 1
            data = np.ascontiguousarray(arr).tobytes()
            _fault_site("ckpt.chunk_write")
            with open(os.path.join(step_dir, fn), "wb") as f:
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            chunk_records.append({
                "file": fn, "path": path, "index": box,
                "crc32": zlib.crc32(data) & 0xFFFFFFFF, "bytes": len(data),
            })
            nbytes_total += len(data)
    manifest = {
        "magic": MANIFEST_MAGIC, "version": _MANIFEST_VERSION,
        "step": int(step), "rank": rank, "world_size": world_size,
        "generation": int(generation), "wall_time": time.time(),
        "mesh_axes": snap.mesh_axes, "tree": snap.tree,
        "arrays": arrays_meta, "chunks": chunk_records,
    }
    tmp = os.path.join(step_dir, _manifest_name(rank) + ".tmp.prep")
    payload = json.dumps(manifest).encode()
    with open(tmp, "wb") as f:
        f.write(payload)
        f.flush()
        os.fsync(f.fileno())
    return tmp, nbytes_total + len(payload)


def _spec_to_json(spec):
    if spec is None:
        return None
    return [list(p) if isinstance(p, (tuple, list)) else p for p in spec]


def _spec_from_json(spec):
    if spec is None:
        return None
    return tuple(tuple(p) if isinstance(p, list) else p for p in spec)


# ---------------------------------------------------------------------------
# scan / verify
# ---------------------------------------------------------------------------

@dataclass
class StepScan:
    step_dir: str
    manifests: Dict[int, dict] = field(default_factory=dict)  # committed
    bad_manifests: List[Tuple[str, str]] = field(default_factory=list)
    tmp_manifests: List[str] = field(default_factory=list)
    world_size: Optional[int] = None
    #: ranks whose manifest came from a peer-written `.mirror` copy (the
    #: owner's own manifest was missing or unreadable)
    mirrored: List[int] = field(default_factory=list)


def _read_manifest(path: str) -> dict:
    """Read + validate one committed manifest (raises on anything that
    downstream consumers — verify, coverage, load — could not trust)."""
    with open(path, "rb") as f:
        m = json.loads(f.read().decode())
    if m.get("magic") != MANIFEST_MAGIC or "tree" not in m \
            or not isinstance(m.get("chunks"), list) \
            or not isinstance(m.get("arrays"), dict):
        raise ValueError("not a PTSHARD01 manifest")
    int(m["world_size"]), int(m["rank"])
    for rec in m["chunks"]:
        # validate here so every downstream consumer can trust the record
        # shape — a garbled record must mean "bad manifest", never a
        # KeyError leaking out of a resume path
        if not isinstance(rec, dict) or \
                not isinstance(rec["file"], str) or \
                not isinstance(rec["path"], str):
            raise ValueError("malformed chunk record")
        int(rec["bytes"]), int(rec["crc32"])
        [(int(a), int(b)) for a, b in rec["index"]]
    return m


def scan_step(step_dir: str) -> StepScan:
    """Read every committed manifest in a step directory. When manifests
    of DIFFERENT world sizes coexist (a step number re-used after an
    elastic resize into the same shared dir), the group written most
    recently wins — stale other-world manifests are ignored, not an
    error. A rank whose own manifest is missing/corrupt falls back to the
    peer-written ``.mirror`` copy (recorded in ``scan.mirrored``)."""
    scan = StepScan(step_dir=step_dir)
    if not os.path.isdir(step_dir):
        return scan
    groups: Dict[int, Dict[int, dict]] = {}
    mirror_groups: Dict[int, Dict[int, dict]] = {}
    for fn in sorted(os.listdir(step_dir)):
        if fn.endswith(".tmp.prep") and _parse_manifest_name(
                fn[:-len(".tmp.prep")]) is not None:
            scan.tmp_manifests.append(os.path.join(step_dir, fn))
            continue
        mirror = fn.endswith(_MIRROR_SUFFIX)
        rank = _parse_manifest_name(fn[:-len(_MIRROR_SUFFIX)]) if mirror \
            else _parse_manifest_name(fn)
        if rank is None:
            continue
        path = os.path.join(step_dir, fn)
        try:
            m = _read_manifest(path)
            world, rank_m = int(m["world_size"]), int(m["rank"])
        except (OSError, ValueError, KeyError, TypeError) as e:
            if not mirror:
                # an unreadable MIRROR is not evidence of a bad step —
                # the original may be intact; only originals land in
                # bad_manifests (which can flip the verdict to corrupt)
                scan.bad_manifests.append(
                    (path, f"{type(e).__name__}: {e}"))
            continue
        if mirror:
            mirror_groups.setdefault(world, {})[rank_m] = m
        else:
            groups.setdefault(world, {})[rank_m] = m
    # fallback: a mirror fills a (world, rank) slot ONLY when the owner's
    # own manifest is gone — an intact original always wins (the mirror
    # may lag one save behind)
    mirrored_by_world: Dict[int, List[int]] = {}
    for world, ms in mirror_groups.items():
        for rank_m, m in ms.items():
            if rank_m not in groups.get(world, {}):
                groups.setdefault(world, {})[rank_m] = m
                mirrored_by_world.setdefault(world, []).append(rank_m)
    if groups:
        def freshness(item):
            _, ms = item
            # generation FIRST: it is a monotonic logical counter across
            # restarts, while wall_time comes from per-host clocks — a
            # relaunched host whose clock runs behind must still beat the
            # dead generation's group
            return max((int(m.get("generation", 0)),
                        float(m.get("wall_time", 0.0)))
                       for m in ms.values())
        world, manifests = max(groups.items(), key=freshness)
        scan.world_size = world
        scan.manifests = manifests
        scan.mirrored = sorted(mirrored_by_world.get(world, []))
    return scan


def _chunk_ok(step_dir: str, rec: dict, deep: bool) -> Tuple[bool, str]:
    path = os.path.join(step_dir, rec["file"])
    try:
        size = os.path.getsize(path)
    except OSError:
        return False, f"{rec['file']}: missing"
    if size != int(rec["bytes"]):
        return False, (f"{rec['file']}: {size} bytes on disk, manifest "
                       f"says {rec['bytes']}")
    if deep:
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError as e:
            return False, f"{rec['file']}: unreadable: {e}"
        if zlib.crc32(data) & 0xFFFFFFFF != int(rec["crc32"]):
            return False, f"{rec['file']}: CRC32 mismatch"
    return True, ""


def verify_step(step_dir: str, deep: bool = False) -> Tuple[str, str]:
    """(status, detail) for one sharded step directory.

    * ``complete`` — every rank's manifest of the step's world size is
      committed and every referenced chunk is intact;
    * ``partial``  — manifests or chunks are missing/corrupt but the
      surviving intact chunks still cover every array: restore works;
    * ``torn``     — only ``.tmp.prep`` manifests exist (barrier abort, or
      a host died between prepare and commit);
    * ``corrupt``  — some array can no longer be fully reassembled;
    * ``empty``    — no manifest at all.

    ``deep=True`` CRC-verifies every chunk (reads all bytes); the default
    checks existence + byte length only — cheap enough for resume
    negotiation over a multi-GB checkpoint."""
    status, detail, _scan, _verdicts = _verify_step_detail(step_dir, deep)
    return status, detail


def _verify_step_detail(step_dir: str, deep: bool
                        ) -> Tuple[str, str, StepScan, Dict[str, str]]:
    """verify_step plus its working state: the StepScan and the per-chunk
    verdicts ({file: "ok" | reason}) — so a reporting caller
    (tools/ckpt_inspect.py) renders the per-chunk table without reading
    and CRC-ing every chunk a second time."""
    verdicts: Dict[str, str] = {}
    scan = scan_step(step_dir)
    if not scan.manifests:
        if scan.tmp_manifests:
            return ("torn", f"{len(scan.tmp_manifests)} prepared "
                            f"manifest(s), none committed", scan, verdicts)
        if scan.bad_manifests:
            return "corrupt", scan.bad_manifests[0][1], scan, verdicts
        return "empty", "no manifests", scan, verdicts
    world = scan.world_size
    problems = []
    missing_ranks = sorted(set(range(world)) - set(scan.manifests))
    if missing_ranks:
        problems.append(f"missing manifest(s) for rank(s) {missing_ranks} "
                        f"of world {world}")
    if scan.mirrored:
        # a mirror may lag one save behind the lost original, so a step
        # leaning on one is at best `partial` — restorable, not pristine
        problems.append(f"rank(s) {scan.mirrored} recovered via "
                        f"peer-mirrored manifest(s)")
    # coverage: available volume per array from intact chunks only
    # (chunks are disjoint by construction: replica-0 shards partition the
    # array and replicated arrays have exactly one fleet-level owner)
    any_manifest = next(iter(scan.manifests.values()))
    covered: Dict[str, int] = {p: 0 for p in any_manifest["arrays"]}
    for m in scan.manifests.values():
        for rec in m["chunks"]:
            ok, why = _chunk_ok(step_dir, rec, deep)
            verdicts[rec["file"]] = "ok" if ok else why
            if not ok:
                problems.append(why)
                continue
            covered[rec["path"]] = covered.get(rec["path"], 0) + \
                _box_volume(rec["index"])
    holes = []
    for path, meta in any_manifest["arrays"].items():
        need = 1
        for d in meta["shape"]:
            need *= int(d)
        if covered.get(path, 0) < need:
            holes.append(path)
    if holes:
        return ("corrupt",
                f"array(s) {holes[:3]} cannot be reassembled "
                f"({'; '.join(problems[:3]) or 'chunks lost'})",
                scan, verdicts)
    if problems:
        return "partial", "; ".join(problems[:4]), scan, verdicts
    return ("complete",
            f"world {world}, "
            f"{sum(len(m['chunks']) for m in scan.manifests.values())} chunks",
            scan, verdicts)


# ---------------------------------------------------------------------------
# load side: reassembly + elastic re-sharding
# ---------------------------------------------------------------------------

def _needed_box(sharding, shape) -> List[List[int]]:
    """Bounding box of the indices this host's devices need under
    `sharding` (the union of its addressable per-device slices)."""
    try:
        idx_map = sharding.addressable_devices_indices_map(tuple(shape))
    except Exception:
        return _whole_box(shape)
    box = None
    for index in idx_map.values():
        b = _norm_index(index, shape)
        if box is None:
            box = [list(x) for x in b]
        else:
            for i, (a, c) in enumerate(b):
                box[i][0] = min(box[i][0], a)
                box[i][1] = max(box[i][1], c)
    return box if box is not None else _whole_box(shape)


def _boxes_overlap(a, b) -> bool:
    return all(x0 < y1 and y0 < x1 for (x0, x1), (y0, y1) in zip(a, b))


def _read_chunk_into(step_dir: str, rec: dict, dtype: np.dtype,
                     buf: np.ndarray):
    """CRC-verify one chunk file and copy it into the full-shape buffer."""
    path = os.path.join(step_dir, rec["file"])
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError as e:
        raise CheckpointCorruptError(path, f"chunk unreadable: {e}")
    if len(data) != int(rec["bytes"]):
        raise CheckpointCorruptError(
            path, f"chunk truncated: {len(data)} bytes, manifest says "
                  f"{rec['bytes']}")
    if zlib.crc32(data) & 0xFFFFFFFF != int(rec["crc32"]):
        raise CheckpointCorruptError(
            path, f"chunk CRC32 mismatch (stored {int(rec['crc32']):#010x})")
    shape = tuple(b - a for a, b in rec["index"])
    arr = np.frombuffer(data, dtype=dtype).reshape(shape)
    buf[tuple(slice(a, b) for a, b in rec["index"])] = arr


def load_step(step_dir: str, mesh=None) -> Any:
    """Reassemble one sharded checkpoint step and place it for THIS host.

    With a `mesh`, each array is laid out under its recorded PartitionSpec
    re-targeted at the new mesh (axes the new mesh lacks replicate, with
    the same warning + `checkpoint_reshard_fallback_total` metric as the
    file backend) — and only the chunks overlapping what this host's
    NamedSharding needs are read and CRC-verified. Without a mesh the
    full arrays are assembled and placed replicated.

    Raises CheckpointCorruptError when any needed array cannot be
    reassembled (missing/truncated/bit-flipped chunks, bad manifests) —
    never a raw unpickling error."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from ..fault import site as _fault_site
    scan = scan_step(step_dir)
    if not scan.manifests:
        reason = "no committed manifests"
        if scan.tmp_manifests:
            reason += " (prepared-but-uncommitted tmps present: torn step)"
        if scan.bad_manifests:
            reason += f"; bad: {scan.bad_manifests[0][1]}"
        raise CheckpointCorruptError(step_dir, reason)
    base = next(iter(scan.manifests.values()))
    chunks_by_path: Dict[str, List[dict]] = {}
    for m in scan.manifests.values():
        for rec in m["chunks"]:
            chunks_by_path.setdefault(rec["path"], []).append(rec)
    _fault_site("ckpt.reshard")
    arrays: Dict[str, Any] = {}
    for path, meta in base["arrays"].items():
        shape = tuple(int(d) for d in meta["shape"])
        dtype = _np_dtype(meta["dtype"])
        spec = _spec_from_json(meta.get("spec"))
        recs = chunks_by_path.get(path, [])
        sharding = None
        if mesh is not None and spec is not None:
            cleaned = _ck._clean_spec(spec, mesh)
            try:
                sharding = NamedSharding(mesh, P(*cleaned))
            except Exception as e:
                _ck._warn_reshard_fallback(path, cleaned, mesh, e)
                sharding = None
        need = _needed_box(sharding, shape) if sharding is not None \
            else _whole_box(shape)
        buf = np.zeros(shape, dtype=dtype)
        read = set()
        for rec in recs:
            if not _boxes_overlap(rec["index"], need):
                continue
            _read_chunk_into(step_dir, rec, dtype, buf)
            read.add(rec["file"])
        if not _covers(recs, read, need):
            raise CheckpointCorruptError(
                step_dir, f"array {path!r}: chunks do not cover the "
                          f"needed region {need} (have "
                          f"{sorted(read) or 'none'})")
        if sharding is not None:
            try:
                arrays[path] = jax.make_array_from_callback(
                    shape, sharding, lambda idx, _b=buf: _b[idx])
                continue
            except Exception as e:
                _ck._warn_reshard_fallback(path, spec, mesh, e)
                for rec in recs:  # replication needs the full array
                    if rec["file"] not in read:
                        _read_chunk_into(step_dir, rec, dtype, buf)
        arrays[path] = jnp.asarray(buf)
    try:
        return _decode_tree(base["tree"], arrays)
    except CheckpointCorruptError:
        raise
    except Exception as e:
        # a damaged-but-parseable manifest (bit-flipped base64 pickle leaf,
        # mangled skeleton) must surface as corruption, never a raw
        # unpickling traceback — same contract as the file backend
        raise CheckpointCorruptError(
            step_dir, f"manifest tree decode failed: "
                      f"{type(e).__name__}: {e}") from e


def _covers(recs, read_files, need) -> bool:
    """Do the chunks we read fully cover the needed box? (chunks are
    disjoint by construction, so clipped-volume sum is exact)."""
    total = 0
    for rec in recs:
        if rec["file"] not in read_files:
            continue
        clipped = [[max(a, c), min(b, d)]
                   for (a, b), (c, d) in zip(rec["index"], need)]
        total += _box_volume(clipped)
    return total >= _box_volume(need)


# ---------------------------------------------------------------------------
# background writer
# ---------------------------------------------------------------------------

class _AsyncWriter:
    """One background writer per manager: depth-1 queue with backpressure.

    `submit()` blocks while a previous save is still being written (the
    step loop stalls only when it outruns the disk — bounded memory, and
    the stall is itself the signal the save cadence is too hot), then
    hands the job to a daemon thread and returns. Background failures are
    kept and re-raised by the next `drain()`/`submit()` — a silently
    lost checkpoint is worse than a late crash."""

    def __init__(self):
        self._lock = threading.Lock()
        self._idle = threading.Condition(self._lock)
        self._job = None
        self._thread: Optional[threading.Thread] = None
        self._errors: List[BaseException] = []
        self._results: List[bool] = []

    def _loop(self):
        while True:
            with self._lock:
                while self._job is None:
                    self._idle.wait()
                job = self._job
            t0 = time.perf_counter()
            try:
                committed = job()
                self._results.append(bool(committed))
            except BaseException as e:
                self._errors.append(e)
                self._results.append(False)
            finally:
                if _metrics_mod.enabled():
                    _M_ASYNC_SECONDS.observe(time.perf_counter() - t0)
                with self._lock:
                    self._job = None
                    if _metrics_mod.enabled():
                        _M_ASYNC_PENDING.set(0.0)
                    self._idle.notify_all()

    def submit(self, job):
        with self._lock:
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._loop, daemon=True,
                    name="sharded-ckpt-writer")
                self._thread.start()
            while self._job is not None:  # backpressure: one in flight
                self._idle.wait()
            self._job = job
            if _metrics_mod.enabled():
                _M_ASYNC_PENDING.set(1.0)
            self._idle.notify_all()
        self._raise_pending()

    def drain(self):
        """Block until the in-flight save (if any) is published; re-raise
        the first background failure."""
        with self._lock:
            while self._job is not None:
                self._idle.wait()
        self._raise_pending()

    def busy(self) -> bool:
        with self._lock:
            return self._job is not None

    def take_results(self) -> List[bool]:
        out, self._results = self._results, []
        return out

    def _raise_pending(self):
        if self._errors:
            err = self._errors[0]
            self._errors.clear()
            raise err


# ---------------------------------------------------------------------------
# manager
# ---------------------------------------------------------------------------

def _step_dirs(dirname: str, prefix: str) -> List[Tuple[int, str]]:
    """[(step, path)] for `<prefix>_<step>` DIRECTORIES, newest first."""
    if not os.path.isdir(dirname):
        return []
    out = []
    for fn in os.listdir(dirname):
        if not fn.startswith(prefix + "_"):
            continue
        try:
            step = int(fn.rsplit("_", 1)[1])
        except ValueError:
            continue
        path = os.path.join(dirname, fn)
        if os.path.isdir(path):
            out.append((step, path))
    out.sort(reverse=True)
    return out


def newest_committed_step(dirname: str, prefix: str = "ckpt",
                          min_step: int = -1,
                          skip: Optional[set] = None
                          ) -> Optional[Tuple[int, str]]:
    """Cheapest answer to "is there a NEWER complete checkpoint?" —
    the serving hot-swap poller's watch primitive. Scans step
    directories newest-first and returns the first `(step, path)` whose
    manifests verify "complete", skipping steps <= `min_step` and any
    in `skip` (canary-rejected pushes are skipped forever rather than
    re-scored every poll). Returns None when nothing qualifies. Shallow
    verification only (manifest + chunk presence/size); the loader's
    checksum pass still guards the actual swap."""
    for step, path in _step_dirs(dirname, prefix):
        if step <= min_step:
            return None  # newest-first: everything below is older too
        if skip and step in skip:
            continue
        if verify_step(path)[0] == "complete":
            return step, path
    return None


class ShardedCheckpointManager(CheckpointManager):
    """CheckpointManager over the chunked layout (module docstring).

    Differences from the file-per-host base:

    * one SHARED directory serves the whole fleet (rank-namespaced chunk
      files + per-rank manifests; the commit renames only this rank's
      manifest, so hosts never clobber each other);
    * ``async_save=True`` takes the serialize+fsync off the step critical
      path (synchronous device→host snapshot, background write, barrier
      on the writer thread after the write drains, backpressure when a
      save is still in flight). For coordinated async saves the commit
      outcome is only known one save later: ``save()`` reports the
      previous round's outcome — the abort-streak/resync contract in
      `FaultTolerantCheckpoint` works with lag 1;
    * ``load_latest`` negotiates the fleet resume step over MANIFESTS
      (cheap existence/size scan), never by unpickling payloads, and the
      restore re-shards onto ``mesh`` — including a mesh/world size the
      checkpoint was not written with.

    `rank`/`world_size` come from the coordinator when one is configured;
    otherwise from the trainer env contract (PADDLE_TRAINER_ID /
    PADDLE_TRAINERS_NUM) so a barrier-opted-out (PADDLE_TPU_CKPT_BARRIER=0)
    fleet sharing a directory still writes non-colliding rank namespaces.
    """

    layout = "sharded"

    # The preemption handler must not start a nested coordinated save
    # while ANY save is queued or running — base code toggles a plain
    # attribute around its synchronous save, but here an async save lives
    # on the writer, so the flag is derived: explicitly-set (sync path /
    # inside _publish) OR the writer holds a queued/running job.
    @property
    def _save_in_flight(self) -> bool:
        return self._sif_flag or (self.async_save and self._writer.busy())

    @_save_in_flight.setter
    def _save_in_flight(self, value: bool):
        self._sif_flag = bool(value)

    def __init__(self, dirname: str, prefix: str = "ckpt",
                 keep_last_n: int = 5, async_save: bool = False,
                 mesh=None, coordinator=None, store=None, rank: int = 0,
                 world_size: int = 1, barrier_timeout: Optional[float] = None):
        self._writer = _AsyncWriter()  # before super(): the
        self._sif_flag = False         # _save_in_flight property needs both
        super().__init__(dirname, prefix=prefix, keep_last_n=keep_last_n,
                         async_save=async_save, mesh=mesh,
                         coordinator=coordinator, store=store, rank=rank,
                         world_size=world_size,
                         barrier_timeout=barrier_timeout)
        if self.coordinator is not None:
            self._rank = self.coordinator.rank
            self._world = self.coordinator.world_size
        else:
            env_rank = os.environ.get("PADDLE_TRAINER_ID")
            env_world = os.environ.get("PADDLE_TRAINERS_NUM")
            try:
                self._rank = int(env_rank) if rank == 0 and env_rank \
                    else int(rank)
                self._world = int(env_world) \
                    if world_size == 1 and env_world else int(world_size)
            except ValueError:
                # NOT a silent rank-0 default: the sharded layout
                # namespaces chunk files and manifests BY RANK, so every
                # host of a barrier-opted-out fleet falling back to rank 0
                # would clobber each other's files in the shared directory
                # (and each host's orphan sweep would delete the others'
                # live chunks as its own strays)
                raise ValueError(
                    f"PADDLE_TRAINER_ID={env_rank!r} / "
                    f"PADDLE_TRAINERS_NUM={env_world!r} must be integers: "
                    f"the sharded checkpoint layout namespaces files by "
                    f"rank, and a silent rank-0 fallback would collide "
                    f"every host's chunks in a shared directory")
        self._attempt = 0
        #: (step, state) loaded by a valid-only _local_restorable_step
        #: walk, reused when the fleet agrees on exactly that step
        self._resume_cache = None
        self._sweep_orphans()

    # -- save ----------------------------------------------------------------
    def save(self, state: Any, step: int) -> bool:
        """Publish one chunked checkpoint. The device→host snapshot is
        synchronous; with ``async_save`` the write+commit happens on the
        background writer (returns the PREVIOUS async round's outcome),
        otherwise inline. Returns False when a coordinated round aborted
        (or, async, when the previous one did)."""
        self._attempt += 1
        attempt = self._attempt
        prev = self._last_step
        if prev is not None and prev != int(step):
            # lag-1 backfill: the post-commit mirror attempt may race a
            # slow peer's rename; by the NEXT save the peer's commit has
            # long landed, so this retry closes the gap
            self._mirror_peer_manifest(self.path_for(prev))
        snap = snapshot_tree(state)
        if self.async_save:
            if self.coordinator is not None:
                self._save_in_flight = True  # covers queued+running write
            self._writer.submit(
                lambda: self._publish(snap, step, attempt))
            committed = all(self._writer.take_results())
        else:
            committed = self._publish(snap, step, attempt)
        self._last_step = int(step)
        self.gc()
        return committed

    def _publish(self, snap: _Snapshot, step: int, attempt: int) -> bool:
        """Write this rank's shards and commit — through the two-phase
        barrier when coordinated, plain rename otherwise. Runs on the
        writer thread for async saves."""
        step_dir = self.path_for(step)
        final = os.path.join(step_dir, _manifest_name(self._rank))
        tmp = None
        try:
            if self.coordinator is not None:
                # sync path: nothing else marks the save in flight (async
                # covers it via writer.busy()), and a SIGTERM landing in
                # commit()'s wait loop must not re-enter a nested
                # coordinated save — that consumes a second round id
                # mid-round and desyncs the fleet's barrier rounds
                self._save_in_flight = True
            t0 = time.perf_counter()
            try:
                tmp, nbytes = write_shards(step_dir, step, self._rank,
                                           self._world, snap,
                                           attempt=attempt)
            except BaseException:
                if self.coordinator is not None:
                    # prepare failed (disk full, injected chunk-write fault,
                    # writer-thread death): poison + consume the round so
                    # peers abort promptly instead of burning the barrier
                    # timeout, and this host stays round-lockstep
                    self.coordinator.abort_next_round(step)
                self._gc_attempt(step_dir, attempt)
                raise
            write_secs = time.perf_counter() - t0
            if _metrics_mod.enabled():
                _M_ASYNC_BYTES.inc(nbytes)
            if self.coordinator is not None:
                try:
                    committed = self.coordinator.commit(
                        step, lambda: os.replace(tmp, final))
                except BaseException:
                    self._gc_attempt(step_dir, attempt)
                    raise
                if not committed:
                    self._gc_attempt(step_dir, attempt)
                    warnings.warn(
                        f"coordinated sharded checkpoint step {int(step)} "
                        f"aborted — not every host prepared in time; no "
                        f"host committed its manifest for this step")
                    return False
            else:
                os.replace(tmp, final)
            if _metrics_mod.enabled():
                _ck._M_SAVES.inc()
                _ck._M_SAVE_SECONDS.observe(write_secs)
            self._mirror_peer_manifest(step_dir)
            return True
        finally:
            if self.coordinator is not None:
                self._save_in_flight = False

    def _mirror_peer_manifest(self, step_dir: str):
        """Replicate peer ``(rank+1)%world``'s committed manifest to its
        ``.mirror`` name (atomic tmp+rename, best-effort). Called after
        each commit and again lag-1 from the next ``save()``, so losing
        one owner's manifest file still leaves the step
        ``partial``-restorable from the peer's copy. A single-rank
        world has no peer — a self-mirror would only change the
        single-host corruption semantics (a torn manifest must stay a
        hard fallback-to-previous-step, not a silent self-heal)."""
        if self._world <= 1:
            return
        peer = (self._rank + 1) % self._world
        src = os.path.join(step_dir, _manifest_name(peer))
        dst = os.path.join(step_dir, _mirror_name(peer))
        tmp = dst + f".tmp.r{self._rank}"
        try:
            data = None
            # the coordinated commit barrier proves the peer PREPARED,
            # but its final rename races ours — give it a beat to land
            # before falling back to the next save's lag-1 backfill
            deadline = time.monotonic() + 0.5
            while True:
                try:
                    with open(src, "rb") as f:
                        data = f.read()
                    break
                except FileNotFoundError:
                    if time.monotonic() >= deadline:
                        return
                    time.sleep(0.01)
            with open(tmp, "wb") as f:
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, dst)
        except OSError:
            # peer not committed yet (post-commit race), step dir GC'd,
            # or a torn write — the next save's backfill retries
            self._rm_quiet(tmp)

    def _gc_attempt(self, step_dir: str, attempt: int):
        """Drop this rank's files of one failed/aborted save attempt."""
        marker = f"a{int(attempt)}."
        own = f"r{self._rank}-"
        try:
            names = os.listdir(step_dir)
        except OSError:
            return
        for fn in names:
            if (fn.startswith(own) and marker in fn) or \
                    fn == _manifest_name(self._rank) + ".tmp.prep":
                self._rm_quiet(os.path.join(step_dir, fn))
        try:  # a failed FIRST attempt may leave an empty step dir behind
            os.rmdir(step_dir)
        except OSError:
            pass

    def _publish_sync(self, state: Any, step: int) -> bool:
        """Preemption path: drain the background writer (its in-flight
        save must finish publishing first — it holds a barrier round),
        then one synchronous publish."""
        try:
            self._writer.drain()
        except BaseException as e:
            warnings.warn(f"pending background checkpoint save failed "
                          f"during preemption drain: {e}")
        self._attempt += 1
        snap = snapshot_tree(state)
        return self._publish(snap, step, self._attempt)

    # -- read ----------------------------------------------------------------
    def drain(self):
        self._writer.drain()
        _ck.wait_all()

    def steps(self) -> List[int]:
        return [s for s, _ in _step_dirs(self.dirname, self.prefix)]

    def _local_restorable_step(self) -> Optional[int]:
        """Newest step restore could use — decided from MANIFESTS (cheap
        existence/byte-size scan), never by reading array payloads. This
        is what the fleet negotiates over at resume.

        Under valid-only resume (PADDLE_TPU_RESUME_VALID_ONLY, the
        fleet-rollback relaunch mode) each candidate IS loaded and its
        weights checked finite — payload reads are the price of
        negotiating over numerically-valid steps, paid only on the rare
        rollback path; the loaded state is cached so the agreed-step
        restore does not read it twice."""
        self._resume_cache = None
        valid_only = _ck.resume_valid_only()
        for step, path in _step_dirs(self.dirname, self.prefix):
            status, _ = verify_step(path)
            if status not in ("complete", "partial"):
                continue
            if valid_only:
                try:
                    state = load_step(path, mesh=self.mesh)
                except (OSError, CheckpointCorruptError):
                    continue
                if not _ck.tree_finite(state):
                    _ck._note_nonfinite_skip(path)
                    continue
                self._resume_cache = (step, state)
            return step
        return None

    def latest_valid_path(self) -> Optional[str]:
        self._writer.drain()
        step = self._local_restorable_step()
        # only load_latest's agreed-step restore consumes the valid-only
        # walk's cached state; a path-only query must not leave a full
        # model-state copy pinned on the manager for the rest of the run
        self._resume_cache = None
        return None if step is None else self.path_for(step)

    def load_latest(self) -> Optional[Tuple[Any, int]]:
        """(state, step) from the newest restorable step, or None.

        Coordinated managers negotiate the fleet minimum over manifests
        first; a fleet-agreed step that then fails chunk CRC raises
        CheckpointCorruptError (peers are restoring it — silently
        diverging is worse, same contract as the file backend). Without a
        coordinator, corrupt steps warn + fall back to the next-newest
        restorable one."""
        self._writer.drain()
        _ck.wait_all()
        valid_only = _ck.resume_valid_only()
        if self.coordinator is not None:
            agreed = self.coordinator.negotiate_resume(
                self._local_restorable_step())
            # drop the valid-only walk's cached state up front: on a
            # fresh-start (agreed None) or a mismatch it would otherwise
            # pin a full model-state copy on this manager for the rest
            # of the run
            cache, self._resume_cache = self._resume_cache, None
            if agreed is None:
                return None
            if cache is not None and cache[0] == int(agreed):
                state = cache[1]  # the valid-only walk already loaded it
            else:
                cache = None  # release before the second full load
                state = load_step(self.path_for(agreed), mesh=self.mesh)
                if valid_only and not _ck.tree_finite(state):
                    # the agreed step (a peer was behind this host's
                    # newest valid one) must honor the valid-only
                    # guarantee too — never silently restore nonfinite
                    # weights the rollback exists to discard
                    if _metrics_mod.enabled():
                        _ck._M_SKIP_NONFINITE.inc()
                    raise CheckpointCorruptError(
                        self.path_for(agreed),
                        f"fleet-agreed resume step {agreed} holds "
                        f"nonfinite weights under valid-only resume")
            if _metrics_mod.enabled():
                _ck._M_LOADS.inc()
            return state, int(agreed)
        for step, path in _step_dirs(self.dirname, self.prefix):
            status, detail = verify_step(path)
            if status not in ("complete", "partial"):
                if status in ("corrupt",):
                    warnings.warn(
                        f"skipping corrupt sharded checkpoint {path}: "
                        f"{detail}")
                    if _metrics_mod.enabled():
                        _ck._M_CORRUPT.inc()
                continue
            try:
                state = load_step(path, mesh=self.mesh)
            except (OSError, CheckpointCorruptError) as e:
                warnings.warn(f"skipping corrupt sharded checkpoint "
                              f"{path}: {e}")
                if _metrics_mod.enabled():
                    _ck._M_CORRUPT.inc()
                continue
            if valid_only and not _ck.tree_finite(state):
                _ck._note_nonfinite_skip(path)
                continue
            if _metrics_mod.enabled():
                _ck._M_LOADS.inc()
            return state, step
        return None

    # -- gc ------------------------------------------------------------------
    def gc(self) -> int:
        """Keep the newest `keep_last_n` step directories, remove the rest
        (shared dir: every host GCs, deletions race benignly), and sweep
        this rank's orphans while no background save is in flight."""
        removed = 0
        for step, path in _step_dirs(self.dirname, self.prefix)[
                self.keep_last_n:]:
            shutil.rmtree(path, ignore_errors=True)
            if not os.path.isdir(path):
                removed += 1
                if _metrics_mod.enabled():
                    _ck._M_GC.inc()
        if not self._writer.busy():
            removed += self._sweep_orphans()
        return removed

    def _sweep_orphans(self) -> int:
        """Remove THIS rank's leftovers from crashed/aborted attempts:
        tmp manifests, and own-rank chunk files not referenced by this
        rank's committed manifest. Peers' files are never touched — in a
        shared directory another host's tmp may be a LIVE prepare."""
        removed = 0
        for _step, step_dir in _step_dirs(self.dirname, self.prefix):
            try:
                names = os.listdir(step_dir)
            except OSError:
                continue
            referenced = set()
            mine = _manifest_name(self._rank)
            if mine in names:
                try:
                    with open(os.path.join(step_dir, mine), "rb") as f:
                        m = json.loads(f.read().decode())
                    referenced = {rec["file"] for rec in m.get("chunks", [])}
                except (OSError, ValueError, KeyError):
                    referenced = None  # unreadable own manifest: keep all
            own = f"r{self._rank}-"
            for fn in names:
                path = os.path.join(step_dir, fn)
                if fn == mine + ".tmp.prep":
                    self._rm_quiet(path)
                    removed += 1
                elif fn.endswith(_MIRROR_SUFFIX + f".tmp.r{self._rank}"):
                    # this rank's torn mirror-replication write
                    self._rm_quiet(path)
                    removed += 1
                elif referenced is not None and fn.startswith(own) \
                        and fn.endswith(".chunk") and fn not in referenced:
                    self._rm_quiet(path)
                    removed += 1
        if removed and _metrics_mod.enabled():
            _ck._M_GC.inc(removed)
        return removed


__all__ = ["ShardedCheckpointManager", "snapshot_tree", "write_shards",
           "scan_step", "verify_step", "load_step", "owner_rank",
           "is_step_dir", "newest_committed_step", "MANIFEST_MAGIC"]
