"""Collective communication API.

Reference: `python/paddle/distributed/collective.py` (all_reduce/all_gather/
broadcast/reduce/scatter/alltoall/send/recv over `ProcessGroup`,
`/root/reference/paddle/fluid/distributed/collective/ProcessGroup.h:53`) and
the static-graph `c_*` ops (`/root/reference/paddle/fluid/operators/collective/`).

TPU-native translation: a `Group` is a (Mesh, axis-names) view — no comm
init, no ring_id, no NCCL uniqueId exchange. Each collective has two paths:

* **SPMD path** (inside `shard_map`/`pjit` tracing): lowers to the XLA
  collective over ICI — `lax.psum`, `lax.all_gather`, `lax.ppermute`,
  `lax.all_to_all`. This is the hot path; it is what the parallel layers use.
* **Eager path** (plain `Tensor` outside a trace): wraps the op in a
  one-shot `shard_map` over the group's mesh so per-device shards behave
  like per-rank buffers. A replicated input is treated as every "rank"
  holding the same value (so all_reduce multiplies by group size — identical
  to N real ranks all holding x).

Multi-host: `jax.distributed.initialize` (done by `init_parallel_env`) makes
the same mesh span hosts; nothing here changes — the mesh is the cluster.
"""
from __future__ import annotations

import os
import threading
import time
from typing import List, Optional, Sequence, Tuple, Union

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from .._jax_compat import shard_map

from ..cost_model import array_bytes as _array_bytes
from ..framework.tensor import Tensor
from ..profiler import events as _events_mod
from ..profiler import metrics as _metrics_mod

_REG = _metrics_mod.default_registry()
_M_COLL_CALLS = _REG.counter(
    "collective_calls_total",
    "eager collective launches by kind and link class (ici/dcn)")
_M_COLL_BYTES = _REG.counter(
    "collective_bytes_total",
    "estimated per-device bytes moved by eager collectives, by kind, "
    "attributed to the slowest link the group's mesh axes cross "
    "(cluster-mapper pricing)")
_M_COLL_TIMEOUT = _REG.counter(
    "collective_timeout_total",
    "eager collectives that exceeded the deadline (or hit the armed "
    "collective.timeout fault site), by kind and group")
_M_COLL_SECONDS = _REG.histogram(
    "collective_seconds",
    "eager collective wall time (launch through completion of the guarded "
    "thunk) by kind — the step-diagnosis 'collective' signal; traced/SPMD "
    "collectives run inside compiled programs and are not timed here")


class CollectiveTimeoutError(RuntimeError):
    """An eager collective exceeded its deadline instead of completing.

    Raised (instead of hanging) when `PADDLE_TPU_COLLECTIVE_TIMEOUT` is set
    and the launch+completion of an eager collective outlives it — the
    classic symptom of a peer host that died mid-rendezvous — or when the
    `collective.timeout` fault site is armed (chaos testing). Names the
    group and this process's rank so the stuck member is identifiable from
    any host's log.

    Recovery contract: restart the PROCESS (the supervisor's `supervise`
    argv mode), not just the train loop. Python cannot cancel the
    abandoned watchdog thread, and if the fleet was slow rather than dead
    its collective can still complete later — re-entering training in the
    same process (`ElasticSupervisor.run`) risks that stale completion
    interleaving an unmatched collective into the next generation and
    desyncing cross-rank ordering."""

    def __init__(self, kind: str, group: "Group", rank: int,
                 timeout: float, detail: str = ""):
        msg = (f"collective {kind!r} over group {group.name!r} "
               f"(axes {group.axis_names}, {group.nranks} ranks) "
               f"did not complete within {timeout:g}s on process rank {rank}")
        if detail:
            msg += f" ({detail})"
        super().__init__(msg)
        self.kind = kind
        self.group_name = group.name
        self.rank = rank
        self.timeout = timeout


class ReduceOp:
    """Reduction kinds (reference `distributed/collective.py` ReduceOp)."""
    SUM = 0
    MAX = 1
    MIN = 2
    PROD = 3
    AVG = 4


def _reduce_fn(op):
    return {ReduceOp.SUM: lax.psum, ReduceOp.MAX: lax.pmax,
            ReduceOp.MIN: lax.pmin}.get(op)


class Group:
    """A communication group = a named-axis view of a Mesh."""

    _next_id = 0

    def __init__(self, mesh: Mesh, axis_names: Tuple[str, ...],
                 ranks: Optional[List[int]] = None, name: str = ""):
        self.mesh = mesh
        self.axis_names = tuple(axis_names)
        sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
        self.nranks = int(np.prod([sizes[a] for a in self.axis_names]))
        self.ranks = ranks if ranks is not None else list(range(self.nranks))
        self.name = name or "_".join(self.axis_names)
        self.id = Group._next_id
        Group._next_id += 1

    @property
    def axis(self) -> Union[str, Tuple[str, ...]]:
        return self.axis_names[0] if len(self.axis_names) == 1 \
            else self.axis_names

    @property
    def world_size(self) -> int:
        return self.nranks

    @property
    def rank(self) -> int:
        return 0  # per-device rank is lax.axis_index(self.axis) in-trace

    def get_group_rank(self, rank: int) -> int:
        return self.ranks.index(rank) if rank in self.ranks else -1

    def process_group(self):
        return self

    def __repr__(self):
        return (f"Group(id={self.id}, axes={self.axis_names}, "
                f"nranks={self.nranks})")


_default_group: Optional[Group] = None
_groups_by_id = {}


def _world_mesh() -> Mesh:
    from .topology import get_hybrid_communicate_group
    hcg = get_hybrid_communicate_group()
    if hcg is not None:
        return hcg.mesh
    devs = np.array(jax.devices())
    return Mesh(devs, ("world",))


def set_default_group(group: Group):
    global _default_group
    _default_group = group
    _groups_by_id[group.id] = group


def _get_default_group() -> Group:
    global _default_group
    if _default_group is None:
        mesh = _world_mesh()
        _default_group = Group(mesh, tuple(mesh.axis_names), name="default")
        _groups_by_id[_default_group.id] = _default_group
    return _default_group


def _resolve(group) -> Group:
    if group is None:
        return _get_default_group()
    if isinstance(group, Group):
        return group
    if isinstance(group, int):
        return _groups_by_id[group]
    raise TypeError(f"not a group: {group!r}")


def get_group(gid: int = 0) -> Group:
    return _groups_by_id.get(gid, _get_default_group())


def new_group(ranks=None, backend=None, timeout=None,
              axis_name: Optional[str] = None) -> Group:
    """Create a group. TPU semantics: a group over a mesh axis. `ranks` is
    accepted for API parity; when given without `axis_name` the group spans
    the whole default mesh (single-controller has no per-rank comm setup)."""
    mesh = _world_mesh()
    if axis_name is not None:
        g = Group(mesh, (axis_name,))
    else:
        g = Group(mesh, tuple(mesh.axis_names), ranks=ranks)
    _groups_by_id[g.id] = g
    return g


def is_initialized() -> bool:
    return _default_group is not None


def destroy_process_group(group=None):
    global _default_group
    if group is None:
        _default_group = None
        _groups_by_id.clear()


# ---------------------------------------------------------------------------
# tracer detection + eager shard_map wrapper
# ---------------------------------------------------------------------------
def _is_tracer(x) -> bool:
    return isinstance(x, jax.core.Tracer)


def _unwrap(t):
    return t.data if isinstance(t, Tensor) else t


def _spec_of(arr, mesh) -> P:
    sh = getattr(arr, "sharding", None)
    if isinstance(sh, NamedSharding) and sh.mesh.shape == mesh.shape:
        return sh.spec
    return P()


def _proc_rank() -> int:
    try:
        return int(jax.process_index())
    except Exception:
        return 0


def _deadline_seconds() -> float:
    """0 = guard disabled (the default: zero overhead, unchanged async
    dispatch). Set `PADDLE_TPU_COLLECTIVE_TIMEOUT` (seconds) to bound every
    eager collective: launch + completion run on a watchdog thread and a
    blown deadline raises CollectiveTimeoutError instead of hanging.

    The deadline covers the WHOLE thunk — including shard_map tracing and
    XLA compilation the first time a shape is seen — so size it to cover a
    cold-start compile (tens of seconds on a pod), not just the wire time:
    a too-tight value turns a healthy first-step compile into a false
    dead-peer diagnosis that burns an elastic restart."""
    from ..utils.envparse import env_float
    return env_float("PADDLE_TPU_COLLECTIVE_TIMEOUT", 0.0)


def _timed_out(kind: str, group: Group):
    if _metrics_mod.enabled():
        _M_COLL_TIMEOUT.inc(kind=kind, group=group.name)
    _events_mod.emit("collective_timeout", severity="error",
                     collective=kind, group=group.name, rank=_proc_rank())


class _GuardWorker:
    """A long-lived watchdog thread serving guarded eager collectives,
    instead of a spawn+join per call (thread creation on the per-op eager
    path costs ~100us and churns native stacks). A `None` job is the exit
    sentinel (surplus workers retire instead of idling forever)."""

    def __init__(self):
        import queue
        self.jobs: "queue.SimpleQueue" = queue.SimpleQueue()
        self.thread = threading.Thread(target=self._loop, daemon=True,
                                       name="collective-guard-worker")
        self.thread.start()

    def _loop(self):
        while True:
            job = self.jobs.get()
            if job is None:
                return
            thunk, box, done = job
            try:
                r = thunk()
                jax.block_until_ready(r)  # deadline covers completion, not
                box["v"] = r              # just the async enqueue
            except BaseException as e:
                box["e"] = e
            done.set()


_guard_worker: Optional[_GuardWorker] = None
_guard_worker_lock = threading.Lock()
_guard_worker_spawns = 0  # regression-test hook: reuse means this is flat


def _run_on_guard_worker(thunk, timeout: float):
    """Run `thunk` on a pooled watchdog worker, bounded by `timeout`.
    Returns the result box, or None on deadline.

    Check-out/check-in: the ONE pooled worker is taken exclusively for the
    job's duration, so sequential guarded collectives (the only real
    pattern — they come from the train loop) reuse a single thread, while
    a concurrent caller finding the pool empty gets its own fresh worker
    and its deadline never includes another caller's thunk. On return, the
    worker goes back to the pool (or retires if the pool refilled). A
    timed-out worker is simply ABANDONED — never checked back in — because
    its thread may be wedged inside the hung collective and Python cannot
    cancel it; abandoning it can never touch a healthy worker another
    thread is using."""
    global _guard_worker, _guard_worker_spawns
    with _guard_worker_lock:
        w = _guard_worker
        _guard_worker = None  # checked out (exclusive) while running
        if w is None or not w.thread.is_alive():
            w = _GuardWorker()
            _guard_worker_spawns += 1
    box: dict = {}
    done = threading.Event()
    w.jobs.put((thunk, box, done))
    if not done.wait(timeout):
        return None  # abandoned: may still be executing the hung thunk
    with _guard_worker_lock:
        if _guard_worker is None:
            _guard_worker = w  # back in the pool for the next call
        else:
            w.jobs.put(None)  # pool refilled concurrently: retire this one
    return box


def _guard_collective(kind: str, group: Group, thunk):
    """Run one eager collective under the timeout contract.

    Only the EAGER entry points funnel through here — traced/SPMD
    collectives execute inside compiled programs where XLA owns scheduling
    (a hang there surfaces via the runtime's own deadline, not Python).
    The `collective.timeout` fault site lets chaos tests simulate the hang
    without a real dead peer."""
    from ..fault import InjectedFault, InjectedIOError, site as _fault_site
    try:
        _fault_site("collective.timeout")
    except (TimeoutError, InjectedFault, InjectedIOError) as e:
        # every injected kind at this site models the same thing — a hung
        # collective — so the bare spec `collective.timeout=1` (default
        # kind=error) must surface as the typed timeout too, not escape as
        # a raw InjectedFault that skips the metric
        _timed_out(kind, group)
        raise CollectiveTimeoutError(kind, group, _proc_rank(), 0.0,
                                     detail="injected fault") from e
    timeout = _deadline_seconds()
    if timeout <= 0:
        if not _metrics_mod.enabled():
            return thunk()
        t0 = time.perf_counter()
        try:
            return thunk()
        finally:
            _M_COLL_SECONDS.observe(time.perf_counter() - t0, kind=kind)
    t0 = time.perf_counter()
    box = _run_on_guard_worker(thunk, timeout)
    if box is not None and _metrics_mod.enabled():
        _M_COLL_SECONDS.observe(time.perf_counter() - t0, kind=kind)
    if box is None:
        # the worker is abandoned, not cancelled (Python can't), so a
        # slow-but-alive fleet may still complete this collective later:
        # recover by restarting the process, not the loop — see the
        # CollectiveTimeoutError docstring
        _timed_out(kind, group)
        raise CollectiveTimeoutError(kind, group, _proc_rank(), timeout)
    if "e" in box:
        raise box["e"]
    return box["v"]


def _eager(group: Group, fn, *arrs, out_specs=None, kind: str = "collective"):
    """Run `fn` (which uses lax collectives over group.axis) via shard_map."""
    in_specs = tuple(_spec_of(a, group.mesh) for a in arrs)
    if out_specs is None:
        out_specs = in_specs[0]
    return _guard_collective(
        kind, group,
        lambda: shard_map(fn, mesh=group.mesh, in_specs=in_specs,
                          out_specs=out_specs, check_vma=False)(*arrs))


def _group_link(g: Group) -> str:
    """'ici' or 'dcn': the slowest link class the group's mesh axes cross,
    via the auto-parallel cluster mapper (PR-1 pricing). Slice topology off
    a real multislice job comes from `PADDLE_TPU_NUM_SLICES`; default is one
    slice, so everything is ICI. A bad env value or mapper failure falls
    back to 'ici' but is LOGGED once — a silent fallback would zero the
    dcn breakdown on exactly the multislice jobs it exists for."""
    cached = getattr(g, "_link_class", None)
    if cached is not None:
        return cached
    import logging
    import os
    log = logging.getLogger("paddle_tpu.collective")
    link = "ici"
    from ..utils.envparse import env_int
    # garbled -> single-slice fallback (all ici link attribution)
    n_slices = env_int("PADDLE_TPU_NUM_SLICES", 1)
    if n_slices > 1:
        try:
            from .auto_parallel.cluster import Cluster, Mapper
            ndev = int(np.prod(g.mesh.devices.shape))
            cluster = Cluster(n_slices=n_slices,
                              chips_per_slice=max(1, ndev // n_slices))
            mesh_dims = dict(zip(g.mesh.axis_names, g.mesh.devices.shape))
            links = Mapper(cluster).axis_links(mesh_dims)
            if any(links.get(a) == "dcn" for a in g.axis_names):
                link = "dcn"
        except Exception as e:
            log.warning("cluster mapper failed for group %s (%s: %s); "
                        "collective link attribution falls back to ici",
                        g.name, type(e).__name__, e)
    g._link_class = link
    return link


def _account(kind: str, group: Group, *arrs):
    """Count one eager collective into the metrics registry (traced/SPMD
    collectives execute inside compiled programs and are priced by the
    planner's HLO walk instead — counting the trace would be once-ever)."""
    if not _metrics_mod.enabled():
        return
    try:
        link = _group_link(group)
        _M_COLL_CALLS.inc(kind=kind, link=link)
        _M_COLL_BYTES.inc(sum(_array_bytes(a) for a in arrs),
                          kind=kind, link=link)
    except Exception:
        pass


def _eager_acct(kind: str, group: Group, fn, *arrs, out_specs=None):
    _account(kind, group, *arrs)
    return _eager(group, fn, *arrs, out_specs=out_specs, kind=kind)


def _wrap_like(t, arr):
    if isinstance(t, Tensor):
        t.data = arr
        return t
    return arr


# ---------------------------------------------------------------------------
# collectives
# ---------------------------------------------------------------------------
def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True,
               use_calc_stream=False):
    """In-place all-reduce (reference `collective.py` all_reduce /
    `c_allreduce_sum_op`). Returns the tensor (task.wait() is a no-op: XLA
    async collectives are scheduled by the compiler).

    SEMANTICS (single-controller!): the tensor is treated as N per-rank
    values laid out over the group's mesh axis — exactly N real processes
    calling the NCCL op in the reference. Two consequences:

    * a tensor whose data is SHARDED over the group axis reduces the
      per-shard values, matching the reference rank-for-rank (the case
      that matters in real pipelines — see tests);
    * a REPLICATED tensor is "the same value on every rank", so SUM
      multiplies it by group size — identical to N ranks all-reducing
      equal values. If you want the identity here, you wanted broadcast
      (or no collective at all), not all_reduce.
    """
    g = _resolve(group)
    x = _unwrap(tensor)
    red = _reduce_fn(op)

    def f(a):
        if red is not None:
            return red(a, g.axis)
        if op == ReduceOp.AVG:
            return lax.pmean(a, g.axis)
        # PROD via exp/sum-of-logs is lossy; use all_gather+prod
        ga = lax.all_gather(a, g.axis, axis=0)
        return jnp.prod(ga, axis=0)

    out = f(x) if _is_tracer(x) else _eager_acct("all_reduce", g, f, x)
    return _wrap_like(tensor, out)


def all_gather(tensor_list, tensor=None, group=None, sync_op=True, axis=0):
    """reference: all_gather(tensor_list, tensor). Also usable
    functionally: `out = all_gather(None, x)` returns the stacked array."""
    if tensor is None and not isinstance(tensor_list, list):
        tensor_list, tensor = None, tensor_list
    g = _resolve(group)
    x = _unwrap(tensor)

    def f(a):
        return lax.all_gather(a, g.axis, axis=0)

    if _is_tracer(x):
        out = f(x)
    else:
        # gathered result is identical on every device -> replicated output
        out = _eager_acct("all_gather", g, f, x, out_specs=P())
    if isinstance(tensor_list, list):
        for i in range(g.nranks):
            tensor_list.append(Tensor(out[i]) if isinstance(tensor, Tensor)
                               else out[i])
        return tensor_list
    res = out if axis == 0 else None
    if axis != 0:
        res = jnp.concatenate([out[i] for i in range(out.shape[0])], axis=axis) \
            if not _is_tracer(x) else jnp.concatenate(
                jnp.split(out, g.nranks, axis=0), axis=axis + 1)[0]
    return Tensor(res) if isinstance(tensor, Tensor) else res


def all_gather_object(object_list, obj, group=None):
    # single-controller: every "rank" holds the same python object
    g = _resolve(group)
    object_list.extend([obj] * g.nranks)
    return object_list


def broadcast(tensor, src=0, group=None, sync_op=True):
    """Broadcast from group-rank `src` (reference `c_broadcast_op`)."""
    g = _resolve(group)
    x = _unwrap(tensor)

    def f(a):
        ga = lax.all_gather(a, g.axis, axis=0)
        return ga[src]

    out = f(x) if _is_tracer(x) else _eager_acct("broadcast", g, f, x)
    return _wrap_like(tensor, out)


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    """On TPU SPMD every device computes the reduction (same cost over ICI);
    non-dst ranks keep the reduced value too (superset of reference
    semantics — documented divergence)."""
    return all_reduce(tensor, op=op, group=group)


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    g = _resolve(group)
    if tensor_list is not None:
        stacked = jnp.stack([_unwrap(t) for t in tensor_list], axis=0)

        def f(_):
            i = lax.axis_index(g.axis)
            return lax.dynamic_index_in_dim(stacked, i, axis=0,
                                            keepdims=False)

        x = _unwrap(tensor)
        out = f(x) if _is_tracer(x) else _eager_acct("scatter", g, f, x)
        return _wrap_like(tensor, out)
    raise ValueError("scatter requires tensor_list on TPU SPMD")


def reduce_scatter(tensor, tensor_or_list, op=ReduceOp.SUM, group=None,
                   sync_op=True):
    """reference `c_reducescatter_op`: reduce then shard along dim 0."""
    g = _resolve(group)
    if isinstance(tensor_or_list, (list, tuple)):
        x = jnp.concatenate([_unwrap(t) for t in tensor_or_list], axis=0)
    else:
        x = _unwrap(tensor_or_list)

    def f(a):
        return lax.psum_scatter(a, g.axis, scatter_dimension=0, tiled=True)

    if _is_tracer(x):
        out = f(x)
    else:
        spec = _spec_of(x, g.mesh)

        def f_eager(a):
            # drop the rank axis so each device's shard is its rank tensor
            if len(spec) > 0 and spec[0] is not None and a.shape[0] == 1:
                a = a[0]
            return f(a)

        out = _eager_acct("reduce_scatter", g, f_eager, x,
                          out_specs=P(g.axis))
    return _wrap_like(tensor, out)


def alltoall(in_tensor_list, out_tensor_list=None, group=None, sync_op=True):
    """reference `alltoall_op` (MoE global_scatter/gather ancestor)."""
    g = _resolve(group)
    if isinstance(in_tensor_list, (list, tuple)):
        x = jnp.stack([_unwrap(t) for t in in_tensor_list], axis=0)
    else:
        x = _unwrap(in_tensor_list)  # leading dim == nranks

    def f(a):
        # a: [nranks, ...] local; exchange chunk i -> rank i
        return lax.all_to_all(a, g.axis, split_axis=0, concat_axis=0,
                              tiled=False)

    if _is_tracer(x):
        out = f(x)
    else:
        spec = _spec_of(x, g.mesh)
        out = _eager_acct("alltoall", g, f, x, out_specs=spec)
    if isinstance(out_tensor_list, list):
        for i in range(g.nranks):
            out_tensor_list.append(Tensor(out[i]))
        return out_tensor_list
    return Tensor(out) if isinstance(in_tensor_list, Tensor) else out


alltoall_single = alltoall


def send(tensor, dst=0, group=None, sync_op=True):
    raise NotImplementedError(
        "point-to-point send/recv do not exist on TPU SPMD; use "
        "paddle_tpu.distributed.p2p.ppermute (pipeline engine) — XLA "
        "collective-permute replaces NCCL send/recv "
        "(reference operators/collective/partial_send_op.cc)")


recv = send
isend = send
irecv = send


def ppermute(x, group=None, perm=None):
    """collective_permute: the TPU replacement for PP send/recv pairs."""
    g = _resolve(group)
    if perm is None:  # ring shift by +1
        n = g.nranks
        perm = [(i, (i + 1) % n) for i in range(n)]
    arr = _unwrap(x)

    def f(a):
        return lax.ppermute(a, g.axis, perm)

    out = f(arr) if _is_tracer(arr) else _eager_acct("ppermute", g, f, arr)
    return Tensor(out) if isinstance(x, Tensor) else out


def barrier(group=None):
    """Device barrier: a tiny psum forces a sync point."""
    g = _resolve(group)
    x = jnp.zeros((), jnp.float32)
    _eager(g, lambda a: lax.psum(a, g.axis), x,
           kind="barrier").block_until_ready()


def wait(tensor, group=None, use_calc_stream=True):
    x = _unwrap(tensor)
    if not _is_tracer(x):
        x.block_until_ready()
    return tensor


def stream_synchronize():
    (jnp.zeros(()) + 0).block_until_ready()


# in-trace rank/size helpers (SPMD analogue of get_rank inside layers)
def axis_rank(group=None):
    g = _resolve(group)
    return lax.axis_index(g.axis)


def get_world_size_in_group(group=None) -> int:
    return _resolve(group).nranks


# ---------------------------------------------------------------------------
# paddle.distributed.split — sharded linear/embedding helper
# (reference collective.py:1436)
# ---------------------------------------------------------------------------
def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    from .meta_parallel import parallel_layers as _pl
    if operation == "linear":
        layer_cls = _pl.ColumnParallelLinear if axis == 1 \
            else _pl.RowParallelLinear
        layer = layer_cls(size[0], size[1], weight_attr=weight_attr,
                          has_bias=bias_attr is not False,
                          gather_output=gather_out,
                          input_is_parallel=False)
        return layer(x)
    if operation == "embedding":
        layer = _pl.VocabParallelEmbedding(size[0], size[1],
                                           weight_attr=weight_attr)
        return layer(x)
    raise ValueError(f"unsupported split operation {operation!r}")
