"""PS runtime: role wiring between fleet and the native server/client.

Reference: `TheOnePSRuntime`
(/root/reference/python/paddle/distributed/ps/the_one_ps.py:819) and the env
contract set by the launcher (`PADDLE_PSERVERS_IP_PORT_LIST`,
`PADDLE_TRAINERS_NUM`, `TRAINING_ROLE`, `PADDLE_TRAINER_ID` — see
`fleet/base/role_maker.py`). The same contract is kept so
`paddle_tpu.distributed.launch --server_num N --trainer_num M train.py`
scripts port over unchanged.

Dense parameters can also live on the PS (`sync_dense` helpers): trainer 0
seeds the tables from its initial weights, every trainer pulls before a step
and pushes grads after — the reference's pull_dense/push_dense async loop
(`ps/service/communicator/communicator.h:232`), synchronous variant.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional

import numpy as np

from .client import PSClient, TableConfig
from .server import PSServer

_state = {
    "server": None,       # PSServer on PSERVER ranks
    "client": None,       # PSClient on TRAINER ranks
    "dense_map": None,    # param name -> table_id
}

# Dense tables get ids from 1000 up; sparse tables use user ids (0..999) —
# mirrors the reference's table-id partitioning in PsDescBuilder.
DENSE_TABLE_BASE = 1000


def role() -> str:
    return os.environ.get("TRAINING_ROLE", "TRAINER").upper()


def is_server() -> bool:
    return role() == "PSERVER"


def is_worker() -> bool:
    return not is_server()


def server_endpoints() -> List[str]:
    eps = os.environ.get("PADDLE_PSERVERS_IP_PORT_LIST", "")
    return [e for e in eps.split(",") if e]


def trainer_id() -> int:
    return int(os.environ.get("PADDLE_TRAINER_ID", "0"))


def num_trainers() -> int:
    return int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))


# ------------------------------ server side --------------------------------

def init_server(port: Optional[int] = None) -> PSServer:
    """Start this rank's table server (reference fleet.init_server)."""
    if _state["server"] is not None:
        return _state["server"]
    if port is None:
        port = int(os.environ.get("PADDLE_PORT", "0"))
    _state["server"] = PSServer(port)
    return _state["server"]


def run_server():
    """Serve until a worker calls shutdown() (reference fleet.run_server)."""
    if _state["server"] is None:
        init_server()
    _state["server"].run()


# ------------------------------ worker side --------------------------------

def init_worker(endpoints: Optional[List[str]] = None,
                mode: str = "sync", geo_lr: Optional[float] = None,
                geo_push_steps: Optional[int] = None) -> PSClient:
    """Connect to all table servers (reference fleet.init_worker).

    mode="async" wraps the client in a background Communicator (reference
    AsyncCommunicator): pushes batch+merge off the critical path; pulls see
    slightly stale server state.

    mode="geo" wraps it in a GeoCommunicator (reference GeoCommunicator +
    memory_sparse_geo_table): local-SGD on a cached sparse table with
    periodic weight-delta push/merge — create sparse tables with
    optimizer="sum" for this mode."""
    if _state["client"] is not None:
        return _state["client"]
    eps = endpoints or server_endpoints()
    if not eps:
        raise RuntimeError(
            "init_worker: no PS endpoints (set PADDLE_PSERVERS_IP_PORT_LIST)")
    client = PSClient(eps)
    # an explicit non-default mode argument wins; the env is a fallback for
    # launcher-driven configs where user code passes no mode
    if mode == "sync":
        mode = os.environ.get("PADDLE_PS_MODE", mode)
    if mode == "geo":
        from .communicator import GeoCommunicator
        lr = geo_lr if geo_lr is not None else float(
            os.environ.get("PADDLE_PS_GEO_LR", 0.01))
        steps = geo_push_steps if geo_push_steps is not None else int(
            os.environ.get("PADDLE_PS_GEO_PUSH_STEPS", 8))
        geo = GeoCommunicator(client, lr=lr, geo_push_steps=steps)
        _state["client"] = geo
        return geo
    if mode == "async":
        from .communicator import Communicator
        comm = Communicator(client)
        comm.start()
        client = comm
    _state["client"] = client
    return _state["client"]


def get_client() -> PSClient:
    if _state["client"] is None:
        return init_worker()
    return _state["client"]


def barrier_worker(name: str = "worker"):
    """Barrier across trainers, coordinated by server 0."""
    get_client().barrier(name, num_trainers())


def stop_worker():
    """Trainer-side teardown: final barrier, then trainer 0 stops servers."""
    c = _state["client"]
    if c is None:
        return
    if hasattr(c, "flush"):  # async communicator: land queued grads first
        c.stop()
    c.barrier("stop_worker", num_trainers())
    if trainer_id() == 0:
        c.stop_servers()
    _state["client"] = None


def shutdown():
    """Force-stop servers from any process (tests / emergency path)."""
    if _state["client"] is not None:
        _state["client"].stop_servers()
        _state["client"] = None
    if _state["server"] is not None:
        _state["server"].stop()
        _state["server"] = None


def save_persistables(dirname: str):
    get_client().save(dirname)


def load_persistables(dirname: str):
    get_client().load(dirname)


# --------------------- dense-on-PS (sync mode) helpers ----------------------

def register_dense_params(model, optimizer: str = "sgd",
                          learning_rate: float = 0.01) -> Dict[str, int]:
    """Create one dense table per parameter; trainer 0 seeds initial values.

    Returns the param-name -> table-id map (also cached for the sync helpers).
    """
    client = get_client()
    mapping: Dict[str, int] = {}
    for i, (name, p) in enumerate(model.named_parameters()):
        tid = DENSE_TABLE_BASE + i
        client.create_table(TableConfig(
            table_id=tid, kind="dense", dense_size=int(np.prod(p.shape)),
            optimizer=optimizer, learning_rate=learning_rate))
        mapping[name] = tid
    if trainer_id() == 0:
        for name, p in model.named_parameters():
            client.set_dense(mapping[name], p.numpy())
    barrier_worker("dense_init")
    _state["dense_map"] = mapping
    return mapping


def pull_dense_params(model):
    """Refresh local params from the PS (start-of-step in sync mode)."""
    client = get_client()
    mapping = _state["dense_map"]
    for name, p in model.named_parameters():
        vals = client.pull_dense(mapping[name]).reshape(p.shape)
        p.set_value(vals)


def push_dense_grads(model, scale: float = 1.0):
    """Push local grads; the server-side optimizer applies the update."""
    client = get_client()
    mapping = _state["dense_map"]
    for name, p in model.named_parameters():
        if p.grad is not None:
            client.push_dense(mapping[name], p.grad.numpy() * scale)
