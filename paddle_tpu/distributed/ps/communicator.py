"""Async gradient communicator for PS training.

Reference: the C++ Communicator
(/root/reference/paddle/fluid/distributed/ps/service/communicator/
communicator.h:232 — Async:402 / HalfAsync:492 / Sync:537): trainer-side
background threads batch gradients, merge duplicates, and push to the
servers off the critical path, which is where PS-mode's async speedup (and
its staleness) comes from.

This wraps `PSClient` with the same pull/push surface: pushes enqueue and a
sender thread merges per table — sparse grads segment-summed by key, dense
grads accumulated — and flushes every `send_wait_ms` or `merge_size`
pending pushes. Pulls pass through (reads see server state, i.e. slightly
stale during training, exactly the reference's async semantics).
"""
from __future__ import annotations

import queue
import threading
import time
from typing import Dict, Optional, Tuple

import numpy as np

from ...profiler import metrics as _metrics_mod

_REG = _metrics_mod.default_registry()
_H_SEND = _REG.histogram(
    "ps_comm_send_seconds",
    "communicator sender-thread drain latency (merged push RPC round)")
_M_MERGED = _REG.counter(
    "ps_comm_merged_rows_total",
    "sparse gradient rows merged by the communicator before pushing")


class Communicator:
    def __init__(self, client, merge_size: int = 8, send_wait_ms: int = 20,
                 queue_size: int = 1024):
        self._client = client
        self.merge_size = merge_size
        self.send_wait_ms = send_wait_ms
        self._q: "queue.Queue" = queue.Queue(maxsize=queue_size)
        self._running = False
        self._thread: Optional[threading.Thread] = None
        self._flush_done = threading.Event()
        self._error: Optional[BaseException] = None

    # -------------------------- lifecycle ---------------------------------
    def start(self):
        if self._running:
            return
        self._running = True
        self._thread = threading.Thread(target=self._send_loop, daemon=True)
        self._thread.start()

    def stop(self):
        if not self._running:
            return
        self.flush()
        self._running = False
        self._q.put(None)
        self._thread.join(timeout=10)

    def flush(self):
        """Block until everything enqueued so far reaches the servers."""
        if not self._running:
            return
        self._flush_done.clear()
        self._q.put("__flush__")
        while not self._flush_done.wait(timeout=1.0):
            if not self._thread.is_alive():  # belt-and-braces vs deadlock
                raise RuntimeError(
                    "PS communicator sender thread died unexpectedly")
        if self._error is not None:
            err, self._error = self._error, None
            raise err

    # --------------------------- push/pull --------------------------------
    def push_sparse(self, table_id: int, keys: np.ndarray,
                    grads: np.ndarray):
        self._check_error()
        self._q.put(("sparse", table_id, np.asarray(keys, np.uint64),
                     np.asarray(grads, np.float32)))

    def push_dense(self, table_id: int, grad: np.ndarray):
        self._check_error()
        self._q.put(("dense", table_id, np.asarray(grad, np.float32)))

    def _check_error(self):
        if self._error is not None:
            err, self._error = self._error, None
            raise err

    def __getattr__(self, item):  # pulls, table mgmt, barriers: passthrough
        return getattr(self._client, item)

    # --------------------------- sender -----------------------------------
    def _send_loop(self):
        sparse: Dict[int, Dict[int, np.ndarray]] = {}  # tid -> key -> grad
        dense: Dict[int, np.ndarray] = {}
        pending = 0
        last_send = time.monotonic()

        def drain():
            nonlocal pending, last_send
            t0 = time.monotonic()
            merged_rows = 0
            ok = True
            try:
                for tid, merged in sparse.items():
                    if merged:
                        keys = np.fromiter(merged.keys(), np.uint64,
                                           len(merged))
                        grads = np.stack([merged[k] for k in keys])
                        self._client.push_sparse(tid, keys, grads)
                        merged_rows += keys.size
                for tid, g in dense.items():
                    self._client.push_dense(tid, g)
            except BaseException as e:  # surfaced on next push/flush
                self._error = e
                ok = False
            sparse.clear()
            dense.clear()
            pending = 0
            last_send = time.monotonic()
            # only a CLEAN round is recorded: counting rows from an
            # aborted push would show data flowing during an outage
            if ok and _metrics_mod.enabled() and merged_rows:
                _H_SEND.observe(time.monotonic() - t0)
                _M_MERGED.inc(merged_rows)

        while True:
            timeout = self.send_wait_ms / 1000.0
            try:
                item = self._q.get(timeout=timeout)
            except queue.Empty:
                if pending:
                    drain()
                continue
            if item is None:
                drain()
                return
            if item == "__flush__":
                drain()
                self._flush_done.set()
                continue
            try:  # a bad item must not kill the thread: flush()/stop()
                # would then deadlock on _flush_done forever
                kind, tid = item[0], item[1]
                if kind == "sparse":
                    _, _, keys, grads = item
                    grads = grads.reshape(keys.size, -1)
                    bucket = sparse.setdefault(tid, {})
                    for k, g in zip(keys.tolist(), grads):
                        if k in bucket:
                            bucket[k] = bucket[k] + g
                        else:
                            bucket[k] = np.array(g, np.float32)
                else:
                    _, _, g = item
                    dense[tid] = dense.get(tid, 0) + g
                pending += 1
            except BaseException as e:
                self._error = e
                continue
            if pending >= self.merge_size:
                drain()


__all__ = ["Communicator"]


class GeoCommunicator:
    """Geo-SGD trainer-side communicator (reference GeoCommunicator,
    `ps/service/communicator/communicator.h:566` + server table
    `ps/table/memory_sparse_geo_table.cc`).

    Geo mode: each trainer trains against a LOCAL copy of the sparse table
    (optimizer applied locally, zero RPCs on the critical path); every
    `trainers * geo_need_push_nums`-ish steps it pushes the accumulated
    WEIGHT DELTA (w_local - w_base) to the server — whose table is created
    with optimizer="sum" so deltas from all trainers merge additively —
    and re-pulls the merged rows. Convergence is app-level eventual
    consistency: exactly the reference's trade of freshness for throughput.
    """

    def __init__(self, client, lr: float = 0.01, geo_push_steps: int = 8):
        self._client = client
        self.lr = lr
        self.geo_push_steps = geo_push_steps
        # table_id -> key -> (local_vec, base_vec)
        self._local: Dict[int, Dict[int, Tuple[np.ndarray, np.ndarray]]] = {}
        self._dirty: Dict[int, set] = {}
        self._push_counts: Dict[int, int] = {}
        self._ever_pushed: set = set()

    # ---------------- sparse path (local-first) ----------------------------
    def _materialize(self, table_id: int, keys: np.ndarray) -> dict:
        """Ensure every key has a local (value, base) pair; one batched RPC
        for the misses only. Returns the table's local dict."""
        tbl = self._local.setdefault(table_id, {})
        missing = [k for k in keys.tolist() if k not in tbl]
        if missing:
            vals = self._client.pull_sparse(
                table_id, np.asarray(missing, np.uint64))
            for k, v in zip(missing, vals):
                tbl[k] = (np.array(v, np.float32), np.array(v, np.float32))
        return tbl

    def pull_sparse(self, table_id: int, keys: np.ndarray) -> np.ndarray:
        keys = np.asarray(keys, np.uint64).ravel()
        if keys.size == 0:
            return np.empty((0, self._client.table(table_id).dim), np.float32)
        tbl = self._materialize(table_id, keys)
        return np.stack([tbl[k][0] for k in keys.tolist()])

    def push_sparse(self, table_id: int, keys: np.ndarray,
                    grads: np.ndarray):
        """LOCAL SGD apply + delta bookkeeping; periodic delta push."""
        keys = np.asarray(keys, np.uint64).ravel()
        if keys.size == 0:
            return
        grads = np.asarray(grads, np.float32).reshape(keys.size, -1)
        tbl = self._materialize(table_id, keys)
        dirty = self._dirty.setdefault(table_id, set())
        for k, g in zip(keys.tolist(), grads):
            local, base = tbl[k]
            local -= self.lr * g
            dirty.add(k)
        # per-TABLE push counters (ADVICE r2): each table is pushed once per
        # training step, so geo_sync must fire every geo_push_steps STEPS,
        # not every geo_push_steps/num_tables push-calls (the reference
        # keeps per-variable send counters for the same reason). Trigger on
        # min over seen tables: the sync lands after the LAST table of a
        # step pushed, so no table's counter leads after the reset (a
        # max/any trigger drifts to steps 4,7,11,... for 2 tables). A table
        # pushed only in some steps delays the cadence accordingly.
        self._push_counts[table_id] = self._push_counts.get(table_id, 0) + 1
        # trigger on min over tables EVER pushed in this run (ADVICE r3):
        # at geo_push_steps=1 with multiple tables, min over merely-seen-
        # this-round tables fired after the FIRST table's push — mid-step.
        # Ever-pushed membership also keeps a registered-but-frozen table
        # (pull-only embedding) from suppressing the cadence; the one
        # artifact is that the very first sync of a run can land mid-step,
        # before later tables' first pushes are known. Counter resets keep
        # zeros for known tables, so steady state syncs on step boundaries.
        self._ever_pushed.add(table_id)
        counts = [self._push_counts.get(t, 0) for t in self._ever_pushed]
        # min-trigger keeps the sync on step boundaries; the max escape
        # hatch bounds staleness if some table stops being pushed (a frozen
        # counter would otherwise starve geo_sync forever)
        if (min(counts) >= self.geo_push_steps
                or max(counts) >= 2 * self.geo_push_steps):
            self.geo_sync()
            # forget tables that pushed nothing this round (frozen mid-run):
            # a permanent zero would pin min(counts)=0 and silently double
            # the cadence via the max escape for the rest of the run
            self._ever_pushed = {
                t for t in self._ever_pushed
                if self._push_counts.get(t, 0) > 0}
            self._push_counts = {}

    def geo_sync(self):
        """Push accumulated deltas, re-pull merged state (one geo round)."""
        for table_id, dirty in self._dirty.items():
            if not dirty:
                continue
            tbl = self._local[table_id]
            keys = np.asarray(sorted(dirty), np.uint64)
            deltas = np.stack([tbl[int(k)][0] - tbl[int(k)][1]
                               for k in keys.tolist()])
            self._client.push_sparse(table_id, keys, deltas)  # server: w += d
            merged = self._client.pull_sparse(table_id, keys)
            for k, v in zip(keys.tolist(), merged):
                tbl[k] = (np.array(v, np.float32), np.array(v, np.float32))
            dirty.clear()

    def flush(self):
        self.geo_sync()

    def stop(self):
        """Final teardown: land every accumulated delta on the servers."""
        self.geo_sync()

    # everything else (dense ops, tables, barriers) passes through
    def push_dense(self, table_id: int, grad: np.ndarray):
        self._client.push_dense(table_id, grad)

    def __getattr__(self, item):
        return getattr(self._client, item)


__all__ = ["Communicator", "GeoCommunicator"]
