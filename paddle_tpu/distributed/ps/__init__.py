"""Parameter-server training (sparse/CTR path).

TPU-native rebuild of the reference PS stack
(/root/reference/paddle/fluid/distributed/ps/ — BrpcPsServer/BrpcPsClient,
memory_sparse_table; python side `paddle.distributed.fleet` PS mode +
`the_one_ps.py:819`). The giant embedding tables live on host-side C++
servers (`paddle_tpu/_native/csrc/ps.cc`); the TPU runs the dense math. A
trainer pulls rows for the feasigns in its batch, computes on device, and
pushes sparse gradients back; the optimizer for PS-resident state runs inside
the table (server-side SGD/Adagrad/Adam), exactly the reference's
CommonAccessor/sparse_sgd_rule design.
"""
from .client import PSClient, TableConfig
from .server import PSServer
from .embedding import SparseEmbedding
from .cache import HotRowCache
from . import runtime
from .runtime import (init_server, run_server, init_worker, stop_worker,
                      barrier_worker, get_client, is_server, is_worker,
                      save_persistables, load_persistables, shutdown)

__all__ = [
    "PSClient", "PSServer", "TableConfig", "SparseEmbedding", "HotRowCache",
    "init_server", "run_server", "init_worker", "stop_worker",
    "barrier_worker", "get_client", "is_server", "is_worker",
    "save_persistables", "load_persistables", "shutdown", "runtime",
]
