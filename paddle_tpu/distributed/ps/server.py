"""PS server process wrapper over the native table server.

Reference: `BrpcPsServer` (/root/reference/paddle/fluid/distributed/ps/service/
brpc_ps_server.cc) started by `fleet.run_server()`
(`distributed/ps/the_one_ps.py:1095`). Tables are created lazily by client
CREATE_TABLE requests, so the server itself needs no table configs up front.
"""
from __future__ import annotations

from .. import env as env_mod
from ... import _native


class PSServer:
    """One host-side table server. `run()` blocks until a client sends STOP."""

    def __init__(self, port: int = 0):
        self._lib = _native.load()
        self._h = self._lib.ps_server_create(port)
        if self._h < 0:
            raise RuntimeError(f"PSServer: cannot bind port {port}")
        self._lib.ps_server_start(self._h)
        self._stopped = False

    @property
    def port(self) -> int:
        return self._lib.ps_server_port(self._h)

    @property
    def endpoint(self) -> str:
        return f"127.0.0.1:{self.port}"

    def run(self):
        """Block until STOP (reference `fleet.run_server` blocking loop)."""
        self._lib.ps_server_wait(self._h)
        self.stop()

    def stop(self):
        if not self._stopped:
            self._stopped = True
            self._lib.ps_server_stop(self._h)

    def __del__(self):
        try:
            self.stop()
        except Exception:
            pass
