"""Heterogeneous PS training: host-side sparse PS + compiled dense step.

Reference: the heterogeneous trainer family — GPU/accelerator dense net with
sparse embedding pull/push against the CPU parameter server
(`/root/reference/paddle/fluid/framework/fleet/heter_ps/`,
`ps/service/heter_client.cc`, `HeterPipelineTrainer` in
`framework/trainer.h:336`). The round-2 repo ran the WHOLE Wide&Deep
trainer eagerly on host CPU (the one BASELINE config that never touched the
chip — VERDICT r2 missing #1); this module is the SURVEY §7 design: "C++
host-side sparse embedding server + TPU dense path".

Per step:

1. **route** — a once-traced, XLA-compiled host function maps the batch to
   each `SparseEmbedding`'s incoming id tensor (captured by stubbing the
   embeddings during one trace; the dense compute is dead-code-eliminated,
   so routing costs microseconds). No per-model protocol needed: any
   id-routing that is a function of the batch (slicing, reshapes, concat)
   is captured.
2. **pull (host)** — per embedding call: np.unique over the ids, one
   `pull_sparse` RPC for the unique rows, pad rows to a power-of-two
   bucket (bounds recompiles; the padded tail is masked by construction:
   `inverse` only addresses real rows).
3. **dense step (device, ONE jit)** — the model runs with embeddings
   consuming (rows, inverse) as traced arguments; `jax.value_and_grad`
   differentiates the loss w.r.t. dense params AND the pulled rows — the
   gather's transpose IS the duplicate-merging segment-sum, so the row
   gradient comes back already merged per unique key. The dense optimizer
   update happens on-chip in the same executable.
4. **push (host)** — the first n_unique row-gradients go back with one
   `push_sparse` RPC per table; the server-side rule (sgd/adagrad/adam in
   `_native/csrc/ps.cc`) applies the sparse update.

Two modes (reference: sync vs a_sync trainers,
`ps/service/communicator/communicator.h:402,537`):

- ``mode="sync"`` (default) — each step's pushes land before the next
  step's pulls; loss-for-loss identical to the eager PS loop (tested).
  The host blocks on the row gradients at the end of every step.
- ``mode="async"`` — software-pipelined: route/pull for step *i* happens
  BEFORE step *i-1*'s push is drained, and the push RPC + gradient
  device→host transfer overlap the chip executing step *i* (jax dispatch
  is asynchronous). Pulls may miss the single outstanding push (staleness
  ≤ 1 step) — precisely the reference's a_sync communicator contract,
  where background threads batch pushes while workers keep pulling.
  Call :meth:`flush` before reading final state.

Routing additionally runs on the host CPU backend when one is visible:
the ids are a trivial function of the batch, and compiling the router for
the accelerator would cost a host↔chip round trip per step just to learn
which rows to pull (the r4 heter bench was latency-bound on exactly that).
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...framework import random as random_mod
from ...framework.tensor import Tensor
from ...nn.layer import Layer

_ROUTE = threading.local()  # .capture: list appended by SparseEmbedding
_FEED = threading.local()   # .queue: per-call (rows, inverse, shape) feeds


def _capturing() -> Optional[list]:
    return getattr(_ROUTE, "capture", None)


def _feeding() -> Optional[list]:
    return getattr(_FEED, "queue", None)


def _bucket(n: int, minimum: int = 64) -> int:
    b = minimum
    while b < n:
        b *= 2
    return b


class HeterPSTrainStep:
    """Compiled dense-net training around a live parameter server.

    `model` may contain any number of `SparseEmbedding` layers (tables on
    the PS, no local params) plus ordinary dense layers; `optimizer` only
    ever sees the dense params — sparse updates run server-side, as in the
    reference's DownpourWorker split."""

    def __init__(self, model: Layer, loss_fn: Callable, optimizer,
                 donate: bool = True, mode: str = "sync"):
        from ...jit import functionalize
        from .embedding import SparseEmbedding

        assert mode in ("sync", "async"), mode
        self.layer = model
        self.mode = mode
        self._pending = None  # async mode: (grows, push_meta) not yet pushed
        self._push_fut = None
        self._push_pool = None  # lazy single worker: pushes stay ordered
        try:
            self._cpu_dev = jax.devices("cpu")[0]
        except Exception:
            self._cpu_dev = None
        self.optimizer = optimizer
        self._embeddings: List[SparseEmbedding] = [
            m for _, m in model.named_sublayers()
            if isinstance(m, SparseEmbedding)]
        assert self._embeddings, (
            "HeterPSTrainStep needs at least one SparseEmbedding; use "
            "jit.TrainStep for fully-dense models")
        for e in self._embeddings:
            e._ensure_table()
        self.apply_fn, params, buffers = functionalize(model)
        self.params = jax.tree_util.tree_map(jnp.copy, params)
        self.buffers = jax.tree_util.tree_map(jnp.copy, buffers)
        self.opt_state = optimizer.init_state_tree(params)
        self._t = 0
        self._router = None  # compiled (batch -> per-call ids), built lazily
        self._plan = None    # (embedding, ids-shape) per call, set on trace
        loss_fn_ = loss_fn

        def step(params, buffers_, opt_state, rows, invs, rng, lr, t,
                 *batch):
            """rows/invs: per-embedding-call padded unique rows + inverse."""
            def loss_of(p_rows):
                p, rws = p_rows
                _FEED.queue = [
                    {"rows": r, "inverse": iv} for r, iv in zip(rws, invs)]
                try:
                    out, new_buffers = self.apply_fn(p, buffers_, rng,
                                                     *batch[:-1])
                finally:
                    _FEED.queue = None
                loss = loss_fn_(jax.tree_util.tree_map(Tensor, out),
                                Tensor(batch[-1]))
                return (loss.data if isinstance(loss, Tensor) else loss,
                        new_buffers)
            (loss, new_buffers), (gparams, grows) = jax.value_and_grad(
                loss_of, has_aux=True)((params, rows))
            new_params, new_opt = optimizer.apply_fn(params, gparams,
                                                     opt_state, lr=lr, t=t)
            return loss, new_params, new_buffers, new_opt, grows

        donate_args = (0, 2) if donate else ()
        self._step = jax.jit(step, donate_argnums=donate_args)

    # -- id routing ---------------------------------------------------------
    def _route(self, arrs):
        """Map the batch to each SparseEmbedding call's concrete ids.

        One jit trace with stubbed embeddings captures (batch -> ids); the
        embeddings record (layer, ids-shape) into `_ROUTE.plan` as a
        trace-time side effect. A batch-shape change RETRACES the router
        (jax.jit cache miss), so the plan is refreshed whenever a trace
        actually ran and kept otherwise — partial last batches work."""
        apply_fn = self.apply_fn

        def route(params, buffers, *batch):
            # params/buffers arrive as ARGUMENTS, not closure constants:
            # closing over the live arrays would bake a duplicate of the
            # whole parameter memory into the routing executable (ADVICE
            # r3); ids never depend on them, so jit's default unused-arg
            # dropping elides them from the compiled program entirely
            _ROUTE.capture = []
            try:
                apply_fn(params, buffers, None, *batch[:-1])
                return tuple(_ROUTE.capture)
            finally:
                _ROUTE.capture = None

        if self._router is None:
            self._router = jax.jit(route)
        _ROUTE.plan = []
        try:
            if self._cpu_dev is not None:
                # ids are a function of the batch alone (params/buffers are
                # unused jit args, dropped at trace, hence never transferred)
                # — compile + run the router on host CPU so learning which
                # rows to pull never round-trips the accelerator tunnel
                with jax.default_device(self._cpu_dev):
                    ids = self._router(self.params, self.buffers, *arrs)
            else:
                ids = self._router(self.params, self.buffers, *arrs)
            if _ROUTE.plan:  # a (re)trace ran: adopt the fresh plan
                self._plan = list(_ROUTE.plan)
        finally:
            _ROUTE.plan = None
        assert self._plan and len(ids) == len(self._plan), (
            "id routing captured no SparseEmbedding calls — does the "
            "model's forward reach its embeddings?")
        return ids

    # -- one training step --------------------------------------------------
    def _pull(self, ids_list):
        # ONE batched device->host fetch for every table's ids: per-array
        # np.asarray costs a full dispatch round trip EACH (~120ms over a
        # TPU tunnel, ~1s/step at 8 tables — the r4 heter bench's actual
        # bottleneck), while device_get transfers the whole tuple in one
        ids_host = jax.device_get(tuple(ids_list))
        rows_list, inv_list, push_meta = [], [], []
        for ids, (emb, shape) in zip(ids_host, self._plan):
            flat = np.asarray(ids).reshape(-1).astype(np.uint64)
            uniq, inverse = np.unique(flat, return_inverse=True)
            n = uniq.size
            U = _bucket(n)
            rows = emb.client.pull_sparse(emb._table_cfg.table_id, uniq)
            rows_p = np.zeros((U, emb._dim), np.float32)
            rows_p[:n] = rows
            rows_list.append(rows_p)
            inv_list.append(inverse.astype(np.int32))
            push_meta.append((emb, uniq))
        # one batched host->device transfer for the pulled rows + inverses
        rows_list, inv_list = jax.device_put((tuple(rows_list),
                                              tuple(inv_list)))
        return list(rows_list), list(inv_list), push_meta

    def _push(self, grows, push_meta):
        # batched fetch (blocks until the producing step finishes on device)
        grows_host = jax.device_get(tuple(grows))
        for g, (emb, uniq) in zip(grows_host, push_meta):
            merged = np.asarray(g, dtype=np.float32)[:uniq.size]
            emb.client.push_sparse(emb._table_cfg.table_id, uniq, merged)

    def _drain_fut(self):
        if self._push_fut is not None:
            fut, self._push_fut = self._push_fut, None
            fut.result()  # propagate background push errors

    def flush(self):
        """Async mode: land the outstanding push (no-op when none/sync)."""
        self._drain_fut()
        if self._pending is not None:
            grows, meta = self._pending
            self._pending = None
            self._push(grows, meta)

    def close(self):
        """Teardown: best-effort land outstanding pushes, then join the
        worker thread. Safe on the error path BEFORE stopping the PS —
        otherwise an in-flight background push races server shutdown and
        the non-daemon executor thread can wedge interpreter exit."""
        try:
            self.flush()
        except Exception:
            self._pending = None  # teardown must not mask the original error
        if self._push_pool is not None:
            self._push_pool.shutdown(wait=True)
            self._push_pool = None

    def __del__(self):
        try:
            if self._push_pool is not None:
                self._push_pool.shutdown(wait=True)
        except Exception:
            pass

    def __call__(self, *batch):
        self._t += 1
        arrs = tuple(a.data if isinstance(a, Tensor) else jnp.asarray(a)
                     for a in batch)
        if self.mode == "sync":
            self.flush()  # defensive: a mode flip mid-run must not drop grads
        elif self._pending is not None:
            # hand last step's push to the single worker thread NOW: its
            # grad fetch + push RPC run concurrently with this step's route
            # fetch + pull RPC (the C++ client serializes per-connection
            # requests under a mutex; ctypes releases the GIL)
            import concurrent.futures
            if self._push_pool is None:
                self._push_pool = concurrent.futures.ThreadPoolExecutor(
                    max_workers=1)
            self._drain_fut()  # at most ONE background push in flight
            prev, self._pending = self._pending, None
            self._push_fut = self._push_pool.submit(self._push, *prev)
        ids_list = self._route(arrs)
        rows_list, inv_list, push_meta = self._pull(ids_list)

        rng = random_mod.default_generator().split()
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        (loss, self.params, self.buffers, self.opt_state,
         grows) = self._step(
            self.params, self.buffers, self.opt_state, tuple(rows_list),
            tuple(inv_list), rng, lr, self._t, *arrs)

        if self.mode == "async":
            # dispatch is asynchronous: the chip is now executing step t;
            # its push drains at the START of call t+1, overlapped with
            # that call's route/pull (staleness <= 1 step — the reference
            # a_sync communicator contract)
            self._pending = (grows, push_meta)
        else:
            self._push(grows, push_meta)
        return Tensor(loss)

    # -- state --------------------------------------------------------------
    def sync_to_layer(self):
        self.flush()
        named = dict(self.layer.named_parameters())
        for k, v in self.params.items():
            named[k].data = v
        named_b = dict(self.layer.named_buffers())
        for k, v in self.buffers.items():
            if k in named_b:
                named_b[k].data = v
