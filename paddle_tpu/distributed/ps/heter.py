"""Heterogeneous PS training: host-side sparse PS + compiled dense step.

Reference: the heterogeneous trainer family — GPU/accelerator dense net with
sparse embedding pull/push against the CPU parameter server
(`/root/reference/paddle/fluid/framework/fleet/heter_ps/`,
`ps/service/heter_client.cc`, `HeterPipelineTrainer` in
`framework/trainer.h:336`). The round-2 repo ran the WHOLE Wide&Deep
trainer eagerly on host CPU (the one BASELINE config that never touched the
chip — VERDICT r2 missing #1); this module is the SURVEY §7 design: "C++
host-side sparse embedding server + TPU dense path".

Per step:

1. **route** — a once-traced, XLA-compiled host function maps the batch to
   each `SparseEmbedding`'s incoming id tensor (captured by stubbing the
   embeddings during one trace; the dense compute is dead-code-eliminated,
   so routing costs microseconds). No per-model protocol needed: any
   id-routing that is a function of the batch (slicing, reshapes, concat)
   is captured.
2. **pull (host)** — per embedding call: np.unique over the ids, then ONE
   overlapped multi-table RPC round (`PSClient.pull_sparse_multi`) for all
   tables' unique rows, padded to a power-of-two bucket (bounds recompiles;
   the padded tail is masked by construction: `inverse` only addresses real
   rows). With the hot-row cache on, only cache MISSES ride the RPC and
   cache hits are gathered on-chip (`cache.py`).
3. **dense step (device, ONE jit)** — the model runs with embeddings
   consuming (rows, inverse) as traced arguments; `jax.value_and_grad`
   differentiates the loss w.r.t. dense params AND the pulled rows — the
   gather's transpose IS the duplicate-merging segment-sum, so the row
   gradient comes back already merged per unique key. The dense optimizer
   update happens on-chip in the same executable.
4. **push (host)** — the first n_unique row-gradients go back with one
   `push_sparse` RPC per non-cached table; cached tables absorb gradients
   on-chip and write back on eviction/flush (server-side SGD is linear in
   the gradient, so the deferred push is equivalent — see cache.py).

Three modes (reference: sync vs a_sync trainers,
`ps/service/communicator/communicator.h:402,537`, plus the heter pipeline
trainer's stage threads, `framework/trainer.h:336`):

- ``mode="sync"`` (default) — each step's pushes land before the next
  step's pulls; loss-for-loss identical to the eager PS loop (tested).
  The host blocks on the row gradients at the end of every step.
- ``mode="async"`` — the push RPC + gradient device→host transfer overlap
  the chip executing the next step (jax dispatch is asynchronous). Pulls
  may miss the single outstanding push (staleness ≤ 1 step). Call
  :meth:`flush` before reading final state.
- ``mode="pipelined"`` — full software pipeline: route→unique→pull→
  `device_put` run as a background *prepare* stage on a prefetch thread
  while the chip executes the previous step, and the push stage runs on a
  second worker thread — pulls, pushes, and both H2D/D2H transfers all
  come off the critical path; per-step wall time approaches
  ``max(prepare, on-chip compute)``. Callers that know the next batch can
  hand it to :meth:`prefetch` right after a step so the prepare stage
  truly runs one batch ahead. The staleness contract is UNCHANGED from
  async — a pull may miss at most the ONE in-flight push (the previous
  step's): outstanding push futures are drained before a new prepare may
  pull (for a ``prefetch()``-issued prepare the wait is chained onto the
  prefetch thread, so ``prefetch()`` itself never blocks), so pulls for
  step *t* always observe pushes through step *t−2* and possibly step
  *t−1*. Bounded at 1 step, tested with and without prefetch().

Pipeline-stage failures go through the PR-3 `RetryPolicy` with named fault
sites (``heter.pull`` / ``heter.push``, knobs `PADDLE_TPU_HETER_*`) ON TOP
of the per-RPC retry inside `PSClient`, so a mid-pipeline PS hiccup retries
the stage instead of wedging the prefetch thread; exhaustion surfaces on
the main thread at the next step.

Routing additionally runs on the host CPU backend when one is visible:
the ids are a trivial function of the batch, and compiling the router for
the accelerator would cost a host↔chip round trip per step just to learn
which rows to pull (the r4 heter bench was latency-bound on exactly that).

Stage latencies land in the metrics registry as histograms
(``heter_route_seconds`` / ``heter_pull_seconds`` / ``heter_push_seconds``
/ ``heter_step_wall_seconds``) and cumulative per-stage seconds are
exposed on :attr:`stage_totals` for the bench overlap breakdown.
"""
from __future__ import annotations

import sys
import threading
import time
import warnings
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...fault import RetryPolicy
from ...fault import site as _fault_site
from ...framework import random as random_mod
from ...framework.tensor import Tensor
from ...nn.layer import Layer
from ...profiler import metrics as _metrics_mod

_ROUTE = threading.local()  # .capture: list appended by SparseEmbedding
_FEED = threading.local()   # .queue: per-call (rows, inverse, shape) feeds

_REG = _metrics_mod.default_registry()
_H_ROUTE = _REG.histogram("heter_route_seconds",
                          "heter-PS id-routing stage latency")
_H_PULL = _REG.histogram("heter_pull_seconds",
                         "heter-PS sparse pull stage latency (RPC round)")
_H_PUSH = _REG.histogram("heter_push_seconds",
                         "heter-PS sparse push stage latency (incl. D2H)")
_H_STEP = _REG.histogram(
    "heter_step_wall_seconds",
    "heter-PS per-step wall time on the main thread, by mode")


def _capturing() -> Optional[list]:
    return getattr(_ROUTE, "capture", None)


def _feeding() -> Optional[list]:
    return getattr(_FEED, "queue", None)


def _bucket(n: int, minimum: int = 64) -> int:
    b = minimum
    while b < n:
        b *= 2
    return b


@dataclass
class _Call:
    """One SparseEmbedding call's prepared sparse inputs for a step."""
    emb: object
    uniq: np.ndarray
    cache: object = None           # HotRowCache or None
    cplan: object = None           # CachePlan (cache path only)
    plan_dev: tuple = None         # (slot_idx, hit_mask, miss_idx) on device
    evict_keys: Optional[np.ndarray] = None
    evict_slots_dev: object = None


@dataclass
class _Bundle:
    """Output of the prepare stage: everything the dispatch needs."""
    arrs: tuple
    calls: List[_Call]
    rows: tuple                     # per-call padded device rows (misses or
                                    # full bucket for uncached tables)
    invs: tuple
    timings: Dict[str, float] = field(default_factory=dict)


class HeterPSTrainStep:
    """Compiled dense-net training around a live parameter server.

    `model` may contain any number of `SparseEmbedding` layers (tables on
    the PS, no local params) plus ordinary dense layers; `optimizer` only
    ever sees the dense params — sparse updates run server-side, as in the
    reference's DownpourWorker split.

    ``cache_capacity`` > 0 enables the device-side hot-row cache
    (`cache.py`) for every SGD-family sparse table: high-skew id
    distributions then skip the PS round trip entirely on hits.
    """

    def __init__(self, model: Layer, loss_fn: Callable, optimizer,
                 donate: bool = True, mode: str = "sync",
                 cache_capacity: int = 0):
        from ...jit import functionalize
        from .embedding import SparseEmbedding

        assert mode in ("sync", "async", "pipelined"), mode
        self.layer = model
        self.mode = mode
        self._pending = None  # overlapped modes: (grows, push_meta) to push
        self._push_futs: list = []
        self._push_pool = None  # lazy single worker: pushes stay ordered
        self._prefetch_pool = None  # pipelined: single prepare worker
        self._prefetched = None     # (arrs, future) queued by prefetch()
        self._stage_retry = RetryPolicy.from_env(
            "HETER", max_attempts=3, base_delay=0.05, max_delay=1.0)
        self.stage_totals: Dict[str, float] = {
            "route_s": 0.0, "pull_s": 0.0, "put_s": 0.0, "push_s": 0.0,
            "steps": 0}
        self._totals_lock = threading.Lock()
        try:
            self._cpu_dev = jax.devices("cpu")[0]
        except Exception:
            self._cpu_dev = None
        self.optimizer = optimizer
        self._embeddings: List[SparseEmbedding] = [
            m for _, m in model.named_sublayers()
            if isinstance(m, SparseEmbedding)]
        assert self._embeddings, (
            "HeterPSTrainStep needs at least one SparseEmbedding; use "
            "jit.TrainStep for fully-dense models")
        for e in self._embeddings:
            e._ensure_table()
        self._caches: Dict[int, object] = {}
        if cache_capacity:
            from .cache import build_caches
            self._caches = build_caches(self._embeddings, cache_capacity)
        self.apply_fn, params, buffers = functionalize(model)
        self.params = jax.tree_util.tree_map(jnp.copy, params)
        self.buffers = jax.tree_util.tree_map(jnp.copy, buffers)
        self.opt_state = optimizer.init_state_tree(params)
        self._t = 0
        self._router = None  # compiled (batch -> per-call ids), built lazily
        self._plan = None    # (embedding, ids-shape) per call, set on trace
        loss_fn_ = loss_fn

        def step(params, buffers_, opt_state, rows, invs, rng, lr, t,
                 *batch):
            """rows/invs: per-embedding-call padded unique rows + inverse."""
            def loss_of(p_rows):
                p, rws = p_rows
                _FEED.queue = [
                    {"rows": r, "inverse": iv} for r, iv in zip(rws, invs)]
                try:
                    out, new_buffers = self.apply_fn(p, buffers_, rng,
                                                     *batch[:-1])
                finally:
                    _FEED.queue = None
                loss = loss_fn_(jax.tree_util.tree_map(Tensor, out),
                                Tensor(batch[-1]))
                return (loss.data if isinstance(loss, Tensor) else loss,
                        new_buffers)
            (loss, new_buffers), (gparams, grows) = jax.value_and_grad(
                loss_of, has_aux=True)((params, rows))
            new_params, new_opt = optimizer.apply_fn(params, gparams,
                                                     opt_state, lr=lr, t=t)
            return loss, new_params, new_buffers, new_opt, grows

        donate_args = (0, 2) if donate else ()
        self._step = jax.jit(step, donate_argnums=donate_args)

    @property
    def caches(self) -> Dict[int, object]:
        return self._caches

    # -- id routing ---------------------------------------------------------
    def _route(self, arrs):
        """Map the batch to each SparseEmbedding call's concrete ids.

        One jit trace with stubbed embeddings captures (batch -> ids); the
        embeddings record (layer, ids-shape) into `_ROUTE.plan` as a
        trace-time side effect. A batch-shape change RETRACES the router
        (jax.jit cache miss), so the plan is refreshed whenever a trace
        actually ran and kept otherwise — partial last batches work."""
        apply_fn = self.apply_fn

        def route(params, buffers, *batch):
            # params/buffers arrive as ARGUMENTS, not closure constants:
            # closing over the live arrays would bake a duplicate of the
            # whole parameter memory into the routing executable (ADVICE
            # r3); ids never depend on them, so jit's default unused-arg
            # dropping elides them from the compiled program entirely
            _ROUTE.capture = []
            try:
                apply_fn(params, buffers, None, *batch[:-1])
                return tuple(_ROUTE.capture)
            finally:
                _ROUTE.capture = None

        if self._router is None:
            self._router = jax.jit(route)
        _ROUTE.plan = []
        try:
            if self._cpu_dev is not None:
                # ids are a function of the batch alone (params/buffers are
                # unused jit args, dropped at trace, hence never transferred)
                # — compile + run the router on host CPU so learning which
                # rows to pull never round-trips the accelerator tunnel
                with jax.default_device(self._cpu_dev):
                    ids = self._router(self.params, self.buffers, *arrs)
            else:
                ids = self._router(self.params, self.buffers, *arrs)
            if _ROUTE.plan:  # a (re)trace ran: adopt the fresh plan
                self._plan = list(_ROUTE.plan)
        finally:
            _ROUTE.plan = None
        assert self._plan and len(ids) == len(self._plan), (
            "id routing captured no SparseEmbedding calls — does the "
            "model's forward reach its embeddings?")
        return ids

    # -- prepare stage (route + unique + pull + H2D) ------------------------
    def _prepare(self, arrs) -> _Bundle:
        """Stage 1 of the pipeline. Runs on the prefetch thread in
        pipelined mode, inline otherwise; touches NO cache device state and
        commits no cache index mutations (those happen at dispatch on the
        main thread), so an abandoned bundle is side-effect-free."""
        record = _metrics_mod.enabled()
        t0 = time.perf_counter()
        ids_list = self._route(arrs)
        # ONE batched device->host fetch for every table's ids: per-array
        # np.asarray costs a full dispatch round trip EACH (~120ms over a
        # TPU tunnel, ~1s/step at 8 tables — the r4 heter bench's actual
        # bottleneck), while device_get transfers the whole tuple in one
        ids_host = jax.device_get(tuple(ids_list))
        route_s = time.perf_counter() - t0

        if self._caches:
            # a table consumed by MORE THAN ONE embedding call per step
            # cannot be cached: each call's plan() would start from the
            # same committed index/free-list state and hand the same slots
            # to different keys, and the double commit would corrupt the
            # free list. Drop such tables' caches (flushing pending grads
            # first — nothing is lost, the rows just go back to the
            # per-step pull/push path). The plan is adopted on (re)trace,
            # so this triggers on the first prepare that sees the model.
            seen, dups = set(), set()
            for emb, _ in self._plan:
                tid = emb._table_cfg.table_id
                (dups if tid in seen else seen).add(tid)
            for tid in dups:
                dropped = self._caches.pop(tid, None)
                if dropped is not None:
                    dropped.flush()
                    warnings.warn(
                        f"hot-row cache disabled for table {tid}: it is "
                        "consumed by multiple embedding calls in one step "
                        "(per-step cache plans would collide); this "
                        "table's rows use the per-step pull/push path")

        calls: List[_Call] = []
        inv_list: List[np.ndarray] = []
        pull_reqs = []  # (client, table_id, keys) in call order
        for ids, (emb, shape) in zip(ids_host, self._plan):
            flat = np.asarray(ids).reshape(-1).astype(np.uint64)
            uniq, inverse = np.unique(flat, return_inverse=True)
            inv_list.append(inverse.astype(np.int32))
            cache = self._caches.get(emb._table_cfg.table_id)
            if cache is None:
                calls.append(_Call(emb=emb, uniq=uniq))
                pull_reqs.append((emb.client, emb._table_cfg.table_id, uniq))
            else:
                cplan = cache.plan(uniq, _bucket(uniq.size))
                calls.append(_Call(emb=emb, uniq=uniq, cache=cache,
                                   cplan=cplan))
                pull_reqs.append((emb.client, emb._table_cfg.table_id,
                                  cplan.miss_keys))

        t1 = time.perf_counter()
        pulled = self._stage_retry.call(
            self._pull_round, pull_reqs, op="heter.pull")
        pull_s = time.perf_counter() - t1

        t2 = time.perf_counter()
        rows_host, aux_host = [], []
        for c, rows in zip(calls, pulled):
            if c.cache is None:
                U = _bucket(c.uniq.size)
                rows_p = np.zeros((U, c.emb._dim), np.float32)
                rows_p[:c.uniq.size] = rows
                rows_host.append(rows_p)
                aux_host.append(None)
            else:
                p = c.cplan
                M = _bucket(len(p.miss_keys), minimum=8)
                rows_p = np.zeros((M, c.emb._dim), np.float32)
                rows_p[:len(p.miss_keys)] = rows
                rows_host.append(rows_p)
                ev_slots = (np.asarray([s for _, s in p.evicts], np.int32)
                            if p.evicts else None)
                aux_host.append((p.slot_idx, p.hit_mask, p.miss_idx,
                                 ev_slots))
        # one batched host->device transfer for rows + inverses + cache maps
        rows_dev, invs_dev, aux_dev = jax.device_put(
            (tuple(rows_host), tuple(inv_list),
             tuple(a for a in aux_host if a is not None)))
        aux_iter = iter(aux_dev)
        for c, a in zip(calls, aux_host):
            if a is None:
                continue
            slot_idx, hit_mask, miss_idx, ev_slots = next(aux_iter)
            c.plan_dev = (slot_idx, hit_mask, miss_idx)
            if a[3] is not None:
                c.evict_keys = np.asarray([k for k, _ in c.cplan.evicts],
                                          np.uint64)
                c.evict_slots_dev = ev_slots
        put_s = time.perf_counter() - t2

        if record:
            _H_ROUTE.observe(route_s)
            _H_PULL.observe(pull_s)
        with self._totals_lock:
            self.stage_totals["route_s"] += route_s
            self.stage_totals["pull_s"] += pull_s
            self.stage_totals["put_s"] += put_s
        return _Bundle(arrs=arrs, calls=calls, rows=rows_dev, invs=invs_dev,
                       timings={"route_s": route_s, "pull_s": pull_s,
                                "put_s": put_s})

    @staticmethod
    def _pull_round(pull_reqs):
        """One overlapped pull round across tables. Requests sharing a
        client go through its `pull_sparse_multi` (concurrent lane
        connections — one RPC round of latency instead of one per table);
        results return in request order."""
        _fault_site("heter.pull")
        by_client: Dict[int, list] = {}
        for pos, (client, tid, keys) in enumerate(pull_reqs):
            by_client.setdefault(id(client), (client, []))[1].append(
                (pos, tid, keys))
        out = [None] * len(pull_reqs)
        for client, items in by_client.values():
            multi = getattr(client, "pull_sparse_multi", None)
            if multi is not None and len(items) > 1:
                got = multi([(tid, keys) for _, tid, keys in items])
            else:
                got = [client.pull_sparse(tid, keys)
                       for _, tid, keys in items]
            for (pos, _, _), rows in zip(items, got):
                out[pos] = rows
        return out

    # -- push stage ---------------------------------------------------------
    def _push(self, grows, push_meta):
        """Immediate push for non-cached tables (blocks until the producing
        step finishes on device, then one RPC per table)."""
        _fault_site("heter.push")
        t0 = time.perf_counter()
        grows_host = jax.device_get(tuple(grows))
        for g, (emb, uniq) in zip(grows_host, push_meta):
            merged = np.asarray(g, dtype=np.float32)[:uniq.size]
            emb.client.push_sparse(emb._table_cfg.table_id, uniq, merged)
        dt = time.perf_counter() - t0
        if _metrics_mod.enabled():
            _H_PUSH.observe(dt)
        with self._totals_lock:
            self.stage_totals["push_s"] += dt

    def _push_retrying(self, grows, push_meta):
        # stage-level retry on top of the per-RPC retry inside PSClient: it
        # re-runs the WHOLE multi-table push, so it is at-least-once across
        # tables. That only matters after the client's own retry exhausted
        # (server genuinely down, job failing anyway); injected faults at
        # the `heter.push` site fire before any RPC and retry cleanly.
        self._stage_retry.call(self._push, grows, push_meta,
                               op="heter.push")

    def _submit_push(self, fn, *args):
        import concurrent.futures
        if self._push_pool is None:
            self._push_pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=1)
        self._push_futs.append(self._push_pool.submit(fn, *args))

    def _drain_fut(self):
        if self._push_futs:
            futs, self._push_futs = self._push_futs, []
            for f in futs:
                f.result()  # propagate background push errors

    # -- pipelined prefetch -------------------------------------------------
    def prefetch(self, *batch):
        """Pipelined mode: hand the NEXT batch to the prepare stage so its
        route/unique/pull/H2D run while the chip executes the current step.
        The following ``__call__`` MUST receive this same batch (enforced
        by object identity on the batch elements); an unconsumed prefetch
        is discarded side-effect-free by flush().

        Staleness stays bounded at 1 step: the prepare is CHAINED behind
        every push future already in flight (pushes through step t−1 plus
        eviction write-backs — the wait runs on the prefetch thread, so
        this call never blocks), and the pending step-t push is submitted
        here so at most that ONE push can race the prefetched pull."""
        assert self.mode == "pipelined", "prefetch() requires pipelined mode"
        assert self._prefetched is None, (
            "one prefetch may be outstanding; call the step first")
        arrs = tuple(a.data if isinstance(a, Tensor) else jnp.asarray(a)
                     for a in batch)
        # capture the in-flight pushes BEFORE submitting the pending one:
        # the prepare must observe pushes through step t-1 (and any
        # eviction write-backs), while step t's push may overlap it
        waits = list(self._push_futs)
        if self._pending is not None:
            prev, self._pending = self._pending, None
            self._submit_push(self._push_retrying, *prev)
        self._prefetched = (batch, self._submit_prepare(arrs, waits=waits))

    def _submit_prepare(self, arrs, waits=()):
        import concurrent.futures
        if self._prefetch_pool is None:
            self._prefetch_pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=1)
        if not waits:
            return self._prefetch_pool.submit(self._prepare, arrs)

        def chained():
            for f in waits:  # push errors surface at bundle.result()
                f.result()
            return self._prepare(arrs)

        return self._prefetch_pool.submit(chained)

    def _take_prefetched(self, batch, arrs):
        """Match a queued prefetch to this call, or submit one now."""
        if self._prefetched is not None:
            pre_batch, fut = self._prefetched
            self._prefetched = None
            # identity on the ORIGINAL batch objects: the converted arrays
            # (jnp.asarray of a numpy input) are fresh objects every call
            if len(pre_batch) == len(batch) and all(
                    a is b for a, b in zip(pre_batch, batch)):
                return fut
            fut.result()  # surface errors; bundle itself is side-effect-free
            raise RuntimeError(
                "prefetch()/step batch mismatch: the batch handed to "
                "prefetch() must be the next one passed to the step "
                "(prefetched objects were not the ones just received)")
        return self._submit_prepare(arrs)

    # -- lifecycle ----------------------------------------------------------
    def _flush_pushes(self):
        """Drain the push worker + land the pending step's push (keeps the
        cache accumulators resident — see flush())."""
        if self._prefetched is not None:
            _, fut = self._prefetched
            self._prefetched = None
            try:  # abandoned bundles are side-effect-free by contract
                fut.result()
            except Exception:
                pass
        self._drain_fut()
        if self._pending is not None:
            grows, meta = self._pending
            self._pending = None
            if meta:
                self._push_retrying(grows, meta)

    def flush(self):
        """Land every outstanding push: drain the push worker, push the
        pending step's gradients, and write back all cache-resident
        gradient accumulators (no-op where nothing is outstanding)."""
        self._flush_pushes()
        if self._caches:
            from .cache import flush_all
            flush_all(self._caches.values())

    def close(self):
        """Teardown: land outstanding pushes, then join the worker threads.
        Safe on the error path BEFORE stopping the PS — otherwise an
        in-flight background push races server shutdown and the non-daemon
        executor threads can wedge interpreter exit. A flush failure is
        only swallowed when close() runs during exception unwinding
        (ADVICE r5: a clean close must not silently drop the last step's
        gradients)."""
        unwinding = sys.exc_info()[0] is not None
        try:
            self.flush()
        except Exception:
            self._pending = None  # teardown must not mask the original error
            if not unwinding:
                self._shutdown_pools()
                raise
        self._shutdown_pools()

    def _shutdown_pools(self):
        for attr in ("_push_pool", "_prefetch_pool"):
            pool = getattr(self, attr)
            if pool is not None:
                pool.shutdown(wait=True)
                setattr(self, attr, None)

    def __del__(self):
        try:
            self._shutdown_pools()
        except Exception:
            pass

    # -- one training step --------------------------------------------------
    def __call__(self, *batch):
        t_wall = time.perf_counter()
        self._t += 1
        arrs = tuple(a.data if isinstance(a, Tensor) else jnp.asarray(a)
                     for a in batch)
        if self.mode == "sync":
            # defensive: a mode flip mid-run must not drop grads (cache
            # accumulators stay resident — flushing them every step would
            # re-serialize the path the cache exists to avoid)
            self._flush_pushes()
            bundle = self._prepare(arrs)
        elif self.mode == "async":
            if self._pending is not None:
                # hand last step's push to the single worker thread NOW: its
                # grad fetch + push RPC run concurrently with this step's
                # route fetch + pull RPC (the C++ client serializes
                # per-connection requests under a mutex; ctypes releases
                # the GIL)
                self._drain_fut()  # at most ONE background push in flight
                prev, self._pending = self._pending, None
                self._submit_push(self._push_retrying, *prev)
            bundle = self._prepare(arrs)
        else:  # pipelined
            # drain BEFORE the new prepare can pull: pulls for step t then
            # observe every push through step t-2 and can miss at most the
            # one about to be submitted (staleness <= 1, tested)
            self._drain_fut()
            fut = self._take_prefetched(batch, arrs)
            if self._pending is not None:
                prev, self._pending = self._pending, None
                self._submit_push(self._push_retrying, *prev)
            bundle = fut.result()

        loss, grows_push, push_meta = self._dispatch(bundle)

        if self.mode == "sync":
            if push_meta:
                self._push_retrying(grows_push, push_meta)
        elif push_meta:
            # dispatch is asynchronous: the chip is now executing step t;
            # its push drains at the START of call t+1, overlapped with
            # that call's route/pull (staleness <= 1 step — the reference
            # a_sync communicator contract). Fully-cached steps have
            # nothing to push: gradients were absorbed on-chip.
            self._pending = (grows_push, push_meta)
        dt = time.perf_counter() - t_wall
        if _metrics_mod.enabled():
            _H_STEP.observe(dt, mode=self.mode)
        with self._totals_lock:
            self.stage_totals["steps"] += 1
        return Tensor(loss)

    def _dispatch(self, bundle: _Bundle):
        """Stage 2+3 on the main thread: cache combine/commit, the ONE
        compiled dense step, cache apply, and push composition. All cached
        tables' gathers go out in ONE device dispatch (and one apply) —
        per-call dispatch latency is what the tunnel charges for."""
        cached_ix = [i for i, c in enumerate(bundle.calls)
                     if c.cache is not None]
        for i in cached_ix:
            c = bundle.calls[i]
            # eviction write-back: gather the evicted slots' pending grads
            # BEFORE this step's apply reuses the slots (jax orders the
            # gather ahead of the donated-buffer overwrite)
            if c.evict_keys is not None and c.evict_keys.size:
                wb = c.cache.writeback_rows(c.evict_slots_dev)
                c.cache.note_writeback(int(c.evict_keys.size))
                self._submit_push(self._writeback_push, c.emb, c.evict_keys,
                                  wb)
        rows_list = list(bundle.rows)
        if cached_ix:
            from .cache import apply_batch, combine_batch
            served = combine_batch(
                [bundle.calls[i].cache for i in cached_ix],
                [bundle.calls[i].plan_dev for i in cached_ix],
                [bundle.rows[i] for i in cached_ix])
            for i, rows in zip(cached_ix, served):
                rows_list[i] = rows

        rng = random_mod.default_generator().split()
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        (loss, self.params, self.buffers, self.opt_state,
         grows) = self._step(
            self.params, self.buffers, self.opt_state, tuple(rows_list),
            tuple(bundle.invs), rng, lr, self._t, *bundle.arrs)

        grows_push, push_meta = [], []
        for c, g in zip(bundle.calls, grows):
            if c.cache is None:
                grows_push.append(g)
                push_meta.append((c.emb, c.uniq))
                continue
            c.cache.commit(c.cplan)
            if c.cplan.overflow:
                # rare: unique keys beyond capacity found no slot — their
                # grads must reach the PS now (apply drops them)
                pos = np.asarray(c.cplan.overflow, np.int64)
                grows_push.append(jnp.take(g, pos, axis=0))
                push_meta.append((c.emb, c.uniq[pos]))
        if cached_ix:
            apply_batch([bundle.calls[i].cache for i in cached_ix],
                        [bundle.calls[i].plan_dev for i in cached_ix],
                        [rows_list[i] for i in cached_ix],
                        [grows[i] for i in cached_ix])
        return loss, tuple(grows_push), push_meta

    @staticmethod
    def _writeback_push(emb, keys, wb_dev):
        """Push worker task: land an eviction write-back on the PS."""
        g = np.asarray(jax.device_get(wb_dev), np.float32)
        emb.client.push_sparse(emb._table_cfg.table_id, keys, g)

    # -- state --------------------------------------------------------------
    def sync_to_layer(self):
        self.flush()
        named = dict(self.layer.named_parameters())
        for k, v in self.params.items():
            named[k].data = v
        named_b = dict(self.layer.named_buffers())
        for k, v in self.buffers.items():
            if k in named_b:
                named_b[k].data = v
