"""Distributed (PS-resident) sparse embedding lookup with autograd.

Reference: the PS rewrite turns `embedding` lookups into
`distributed_lookup_table` / `distributed_push_sparse` ops
(/root/reference/python/paddle/distributed/passes/ps_trainer_pass.py,
`paddle/fluid/operators/pscore/distributed_lookup_table_op.cc`): forward
pulls rows for the batch's feasigns from the PS, backward pushes per-row
gradients; the optimizer update happens inside the server table.

TPU design: the pull happens on host (numpy), the gathered dense block is
then a normal device tensor — so everything downstream is XLA. Backward is a
custom tape node whose vjp segment-sums duplicate keys and pushes to the PS
(grad w.r.t. the int ids is None). Unique-ing keys before the pull both
shrinks RPC traffic and makes the push a correct duplicate-accumulating
scatter, like the reference's sparse gradient merge.
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from ...framework import tape as tape_mod
from ...framework.tensor import Tensor
from ...nn.layer import Layer
from .client import TableConfig


class SparseEmbedding(Layer):
    """Embedding whose table lives on the parameter servers.

    Unlike `nn.Embedding` there is no local weight parameter; `parameters()`
    is empty and the optimizer never sees this layer — updates are applied
    server-side on `backward()` (reference: server-side sgd rules,
    `ps/table/sparse_sgd_rule.cc`).
    """

    def __init__(self, table_id: int, embedding_dim: int,
                 optimizer: str = "sgd", learning_rate: float = 0.01,
                 init_range: float = 0.05, seed: int = 0,
                 client=None, name: Optional[str] = None):
        super().__init__()
        self._table_cfg = TableConfig(
            table_id=table_id, kind="sparse", dim=embedding_dim,
            optimizer=optimizer, learning_rate=learning_rate,
            init_range=init_range, seed=seed)
        self._dim = embedding_dim
        self._client = client
        self._created = False

    @property
    def client(self):
        if self._client is None:
            from .runtime import get_client
            self._client = get_client()
        return self._client

    def _ensure_table(self):
        if not self._created:
            self.client.create_table(self._table_cfg)
            self._created = True

    def forward(self, ids) -> Tensor:
        """ids: int tensor [...]-shaped -> embeddings [..., dim].

        Three modes: eager host pull (default); ROUTING capture and ROWS
        feed under `HeterPSTrainStep` (heter.py), where the lookup becomes
        `rows[inverse]` over traced arrays so the dense step compiles and
        the gather's transpose segment-sums duplicate-key gradients."""
        from . import heter as _heter

        cap = _heter._capturing()
        feed = _heter._feeding()
        if cap is not None or feed is not None:
            # under HeterPSTrainStep ids is already a tracer-backed Tensor;
            # the eager branch below never pays this conversion
            ids_arr = ids.data if isinstance(ids, Tensor) else jnp.asarray(ids)
            if cap is not None:
                cap.append(ids_arr)
                _heter._ROUTE.plan.append((self, tuple(ids_arr.shape)))
                return Tensor(jnp.zeros(tuple(ids_arr.shape) + (self._dim,),
                                        jnp.float32))
            item = feed.pop(0)
            rows, inverse = item["rows"], item["inverse"]
            out = jnp.take(rows, inverse, axis=0).reshape(
                tuple(ids_arr.shape) + (self._dim,))
            return Tensor(out)

        self._ensure_table()
        client = self.client
        tid = self._table_cfg.table_id

        ids_np = np.asarray(ids.numpy() if isinstance(ids, Tensor) else ids)
        shape = ids_np.shape
        flat = ids_np.reshape(-1).astype(np.uint64)
        uniq, inverse = np.unique(flat, return_inverse=True)

        rows = client.pull_sparse(tid, uniq)               # [u, dim] host
        gathered = rows[inverse].reshape(*shape, self._dim)
        out = Tensor(jnp.asarray(gathered), stop_gradient=False)

        if tape_mod.grad_enabled():
            dim = self._dim

            def vjp_fn(out_grads):
                g = np.asarray(out_grads[0]).reshape(-1, dim)
                # segment-sum duplicate ids -> one grad row per unique key
                merged = np.zeros((uniq.size, dim), np.float32)
                np.add.at(merged, inverse, g.astype(np.float32))
                client.push_sparse(tid, uniq, merged)
                return (None,)

            ids_ref = ids if isinstance(ids, Tensor) else None
            tape_mod.record(vjp_fn, [ids_ref], [out],
                            name="distributed_lookup_table")
        return out
