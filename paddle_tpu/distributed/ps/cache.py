"""Device-side hot-row embedding cache for heterogeneous-PS training.

Reference analogue: the heter-PS GPU row cache
(`/root/reference/paddle/fluid/framework/fleet/heter_ps/hashtable.h` — hot
feasigns live in accelerator memory, the CPU PS is the backing store). On
TPU the cache is a fixed-capacity ``[capacity, dim]`` device buffer per
table plus a host-side LRU index keyed by feasign:

* **hit** — the row is gathered ON-CHIP out of the cache buffer; no pull
  RPC, no host→device transfer for that row.
* **miss** — only the missing rows ride the pull RPC; a free (or LRU-evicted)
  slot is assigned and the row becomes device-resident for later steps.
* **gradients** — cached rows are updated locally on-chip
  (``w -= lr * g``, the table's SGD rule) and the RAW gradient accumulates
  into a per-slot ``gsum`` buffer. The PS only sees the row again on
  **eviction or flush**, when the accumulated gradient is pushed in one
  write-back RPC and the server applies ``w -= lr * Σg`` — bitwise-close to
  having pushed every step, because SGD is linear in the gradient. This is
  why the cache REQUIRES ``optimizer="sgd"`` (or the additive ``"sum"``)
  tables: adagrad/adam server state is a function of the push schedule, so
  deferral would change numerics. Non-SGD tables are skipped with a warning.

Concurrency contract (enforced by `HeterPSTrainStep`): ``plan()`` runs on
the prefetch thread but is PURE with respect to the index — it computes the
hit/miss split and slot assignments against the last committed state and
returns them in a `CachePlan`. The owning trainer calls ``commit(plan)`` on
the main thread right before dispatching the step that consumes the plan;
an abandoned prefetch (mode flip, flush with a queued bundle) is simply
never committed, so the index can't drift from the device buffers. All
device-array mutation (``combine_rows`` / ``apply_step`` / write-back
gathers) happens on the main thread, ordered by jax's functional semantics.

Cache events land in the PR-2 metrics registry:
``embed_cache_events_total{event=hit|miss|eviction|writeback,table=}``.
"""
from __future__ import annotations

import functools
import warnings
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, List

import jax
import jax.numpy as jnp
import numpy as np

from ...profiler import metrics as _metrics_mod

_REG = _metrics_mod.default_registry()
_M_EVENTS = _REG.counter(
    "embed_cache_events_total",
    "hot-row embedding cache events by event kind and table "
    "(hit/miss/eviction/writeback are per ROW, overflow counts rows that "
    "found no slot)")

# optimizers whose server-side update is linear in the pushed gradient, so
# deferring the push to eviction/flush is numerically equivalent. The local
# on-chip rule must MATCH the server rule: plain SGD applies w -= lr*g;
# "sum"/"geo" tables (server OPT_SUM, ps.cc: w += g, lr ignored) are the
# lr = -1 special case of the same rule, wired up in build_caches.
CACHEABLE_OPTIMIZERS = ("sgd", "sum", "geo")


@dataclass
class CachePlan:
    """One batch's hit/miss decisions, computed against committed state.

    All index arrays are sized to the padded unique bucket ``U``; positions
    past ``n_unique``, and overflow positions that found no slot, carry the
    ``capacity`` sentinel in ``slot_idx`` so device scatters drop them.
    """
    uniq: np.ndarray                 # [n] uint64 unique feasigns
    slot_idx: np.ndarray             # [U] int32, sentinel=capacity
    hit_mask: np.ndarray             # [U] bool
    miss_idx: np.ndarray             # [U] int32 into the miss-row bucket
    miss_keys: np.ndarray            # [m] uint64 keys to pull from the PS
    hits: List[int] = field(default_factory=list)        # keys to LRU-touch
    inserts: List[tuple] = field(default_factory=list)   # (key, slot)
    evicts: List[tuple] = field(default_factory=list)    # (key, slot)
    overflow: List[int] = field(default_factory=list)    # positions w/o slot

    @property
    def n_unique(self) -> int:
        return int(self.uniq.size)


@functools.partial(jax.jit, donate_argnums=(0, 1))
def _apply_step(values, gsum, slot_idx, hit_mask, rows, grows, lr):
    """Post-step cache update: local SGD on the served rows + gradient
    accumulation. Sentinel slots (padded tail / overflow) drop out of the
    scatters; a miss slot's stale gsum (from the evicted previous tenant,
    already written back) is reset rather than inherited."""
    upd = rows - lr * grows
    new_values = values.at[slot_idx].set(upd, mode="drop")
    prev = jnp.where(hit_mask[:, None],
                     gsum.at[slot_idx].get(mode="fill", fill_value=0.0),
                     0.0)
    new_gsum = gsum.at[slot_idx].set(prev + grows, mode="drop")
    return new_values, new_gsum


@jax.jit
def _combine_rows(values, slot_idx, hit_mask, miss_rows, miss_idx):
    """Serve the padded unique bucket: cache rows for hits (on-chip gather),
    freshly-pulled rows for misses. Padded-tail positions read junk that the
    inverse never addresses."""
    cached = values.at[slot_idx].get(mode="fill", fill_value=0.0)
    pulled = jnp.take(miss_rows, miss_idx, axis=0)
    return jnp.where(hit_mask[:, None], cached, pulled)


# multi-table variants: ONE dispatch per step for every cached table's
# gather (and one for every apply) instead of one per table — dispatch
# overhead is per-call, and over an accelerator tunnel per-call costs real
# latency (the r4 heter analysis)
@jax.jit
def _combine_many(values_t, slot_t, hit_t, miss_t, midx_t):
    return tuple(
        jnp.where(h[:, None], v.at[s].get(mode="fill", fill_value=0.0),
                  jnp.take(m, mi, axis=0))
        for v, s, h, m, mi in zip(values_t, slot_t, hit_t, miss_t, midx_t))


@functools.partial(jax.jit, donate_argnums=(0, 1))
def _apply_many(values_t, gsum_t, slot_t, hit_t, rows_t, grows_t, lr_t):
    new_v, new_g = [], []
    for v, g, s, h, r, gr, lr in zip(values_t, gsum_t, slot_t, hit_t,
                                     rows_t, grows_t, lr_t):
        upd = r - lr * gr
        new_v.append(v.at[s].set(upd, mode="drop"))
        prev = jnp.where(h[:, None],
                         g.at[s].get(mode="fill", fill_value=0.0), 0.0)
        new_g.append(g.at[s].set(prev + gr, mode="drop"))
    return tuple(new_v), tuple(new_g)


def combine_batch(caches, plans_dev, miss_rows_t):
    """Serve every cached table's padded bucket in ONE jit dispatch.
    `plans_dev[i]` is (slot_idx, hit_mask, miss_idx) on device."""
    values_t = tuple(c.values for c in caches)
    slot_t = tuple(p[0] for p in plans_dev)
    hit_t = tuple(p[1] for p in plans_dev)
    midx_t = tuple(p[2] for p in plans_dev)
    return _combine_many(values_t, slot_t, hit_t, tuple(miss_rows_t), midx_t)


def apply_batch(caches, plans_dev, rows_t, grows_t):
    """Consume every cached table's row gradients in ONE jit dispatch,
    updating each cache's device buffers in place (donated)."""
    values_t = tuple(c.values for c in caches)
    gsum_t = tuple(c.gsum for c in caches)
    slot_t = tuple(p[0] for p in plans_dev)
    hit_t = tuple(p[1] for p in plans_dev)
    lr_t = tuple(c.lr for c in caches)
    new_v, new_g = _apply_many(values_t, gsum_t, slot_t, hit_t,
                               tuple(rows_t), tuple(grows_t), lr_t)
    for c, v, g in zip(caches, new_v, new_g):
        c.values, c.gsum = v, g


class HotRowCache:
    """Per-table device-resident LRU row cache (see module docstring)."""

    def __init__(self, table_id: int, dim: int, capacity: int,
                 learning_rate: float, client, device=None):
        if capacity < 1:
            raise ValueError("cache capacity must be >= 1")
        self.table_id = int(table_id)
        self.dim = int(dim)
        self.capacity = int(capacity)
        self.lr = jnp.asarray(learning_rate, jnp.float32)
        self.client = client
        put = (lambda x: jax.device_put(x, device)) if device is not None \
            else jax.device_put
        self.values = put(jnp.zeros((self.capacity, self.dim), jnp.float32))
        self.gsum = put(jnp.zeros((self.capacity, self.dim), jnp.float32))
        # feasign -> slot, in LRU order (front = coldest)
        self._slots: "OrderedDict[int, int]" = OrderedDict()
        self._free: List[int] = list(range(self.capacity - 1, -1, -1))
        self.stats = {"hit": 0, "miss": 0, "eviction": 0, "writeback": 0,
                      "overflow": 0, "invalidation": 0}
        # server-side lifecycle hook: PSClient.shrink() must flush +
        # invalidate this cache or evicted rows would be served stale
        reg = getattr(client, "register_row_cache", None)
        if callable(reg):
            reg(self)

    # ------------------------------ planning -------------------------------
    def plan(self, uniq: np.ndarray, bucket: int) -> CachePlan:
        """Pure hit/miss split + slot assignment for one batch's unique keys
        (no index mutation — see the concurrency contract above)."""
        n = uniq.size
        slot_idx = np.full(bucket, self.capacity, np.int32)
        hit_mask = np.zeros(bucket, bool)
        miss_idx = np.zeros(bucket, np.int32)
        miss_keys: List[int] = []
        plan = CachePlan(uniq=uniq, slot_idx=slot_idx, hit_mask=hit_mask,
                         miss_idx=miss_idx, miss_keys=uniq[:0])
        batch_keys = set(int(k) for k in uniq)
        free_cursor = len(self._free)
        # lazily walk LRU victims, skipping rows this batch itself uses and
        # rows already claimed by an earlier miss in this same plan. A
        # GENERATOR, not a list: an all-hit steady-state batch must not pay
        # an O(cache size) scan per step (it never draws a victim)
        victims = ((k, s) for k, s in self._slots.items()
                   if k not in batch_keys)
        for i in range(n):
            k = int(uniq[i])
            slot = self._slots.get(k)
            if slot is not None:
                hit_mask[i] = True
                slot_idx[i] = slot
                plan.hits.append(k)
                continue
            miss_idx[i] = len(miss_keys)
            miss_keys.append(k)
            if free_cursor > 0:
                free_cursor -= 1
                slot = self._free[free_cursor]
            else:
                nxt = next(victims, None)
                if nxt is None:
                    plan.overflow.append(i)
                    continue
                vk, slot = nxt
                plan.evicts.append((vk, slot))
            slot_idx[i] = slot
            plan.inserts.append((k, slot))
        plan.miss_keys = np.asarray(miss_keys, np.uint64)
        return plan

    def commit(self, plan: CachePlan):
        """Apply a plan's index mutations (main thread, at dispatch time)."""
        for k in plan.hits:
            self._slots.move_to_end(k)
        for vk, _slot in plan.evicts:
            del self._slots[vk]
        n_ins = len(plan.inserts)
        if n_ins:
            del self._free[len(self._free) - (n_ins - len(plan.evicts)):]
        for k, slot in plan.inserts:
            self._slots[k] = slot
        self.stats["hit"] += len(plan.hits)
        self.stats["miss"] += len(plan.inserts) + len(plan.overflow)
        self.stats["eviction"] += len(plan.evicts)
        self.stats["overflow"] += len(plan.overflow)
        if _metrics_mod.enabled():
            t = str(self.table_id)
            if plan.hits:
                _M_EVENTS.inc(len(plan.hits), event="hit", table=t)
            misses = len(plan.inserts) + len(plan.overflow)
            if misses:
                _M_EVENTS.inc(misses, event="miss", table=t)
            if plan.evicts:
                _M_EVENTS.inc(len(plan.evicts), event="eviction", table=t)
            if plan.overflow:
                _M_EVENTS.inc(len(plan.overflow), event="overflow", table=t)

    # --------------------------- device ops --------------------------------
    def combine(self, plan_dev, miss_rows):
        """Device gather serving the padded bucket (main thread)."""
        slot_idx, hit_mask, miss_idx = plan_dev
        return _combine_rows(self.values, slot_idx, hit_mask, miss_rows,
                             miss_idx)

    def apply(self, plan_dev, rows, grows):
        """Consume the step's row gradients into the cache buffers."""
        slot_idx, hit_mask, _ = plan_dev
        self.values, self.gsum = _apply_step(
            self.values, self.gsum, slot_idx, hit_mask, rows, grows, self.lr)

    def writeback_rows(self, slots_dev):
        """Gather pending gradients for evicted slots. MUST be dispatched
        before this step's `apply` so it reads the pre-overwrite gsum."""
        return jnp.take(self.gsum, slots_dev, axis=0)

    # ------------------------------ flush ----------------------------------
    def flush(self, push_fn=None) -> int:
        """Push every slot's accumulated gradient to the PS and zero the
        accumulator; cached VALUES stay resident (server now agrees with
        them). Returns rows written back."""
        if not self._slots:
            return 0
        keys = np.fromiter(self._slots.keys(), np.uint64, len(self._slots))
        slots = np.fromiter(self._slots.values(), np.int64, len(self._slots))
        g = np.asarray(jax.device_get(jnp.take(self.gsum, slots, axis=0)),
                       np.float32)
        nz = np.any(g != 0.0, axis=1)
        n = int(nz.sum())
        if n:
            push = push_fn or (lambda k, v: self.client.push_sparse(
                self.table_id, k, v))
            push(keys[nz], g[nz])
            self.gsum = jnp.zeros_like(self.gsum)
            self.stats["writeback"] += n
            if _metrics_mod.enabled():
                _M_EVENTS.inc(n, event="writeback",
                              table=str(self.table_id))
        return n

    def invalidate(self) -> int:
        """Drop EVERY cached row (index + gradient accumulators). For
        server-side shrink/eviction: the server just changed or removed
        rows out from under the cache, so any device-resident copy may be
        stale — the next batch misses and pulls fresh. Call `flush()`
        FIRST when gradients may be pending (PSClient.shrink does): the
        accumulators are zeroed here, and an un-flushed gradient would be
        silently dropped. Returns the number of rows invalidated."""
        n = len(self._slots)
        self._slots.clear()
        self._free = list(range(self.capacity - 1, -1, -1))
        self.gsum = jnp.zeros_like(self.gsum)
        self.stats["invalidation"] += n
        if _metrics_mod.enabled() and n:
            _M_EVENTS.inc(n, event="invalidation", table=str(self.table_id))
        return n

    def note_writeback(self, n: int):
        """Record an eviction write-back issued by the owning trainer."""
        self.stats["writeback"] += n
        if _metrics_mod.enabled() and n:
            _M_EVENTS.inc(n, event="writeback", table=str(self.table_id))

    def __len__(self) -> int:
        return len(self._slots)

    def hit_rate(self) -> float:
        tot = self.stats["hit"] + self.stats["miss"]
        return self.stats["hit"] / tot if tot else 0.0


def flush_all(caches) -> int:
    """Write back every cache's pending gradients with ONE batched
    device→host transfer (a per-table device_get costs a full round trip
    each over an accelerator tunnel). Returns total rows written back."""
    caches = [c for c in caches if len(c)]
    if not caches:
        return 0
    keys_l, slots_l = [], []
    for c in caches:
        keys_l.append(np.fromiter(c._slots.keys(), np.uint64, len(c._slots)))
        slots_l.append(np.fromiter(c._slots.values(), np.int64,
                                   len(c._slots)))
    gathered = jax.device_get(tuple(
        jnp.take(c.gsum, s, axis=0) for c, s in zip(caches, slots_l)))
    total = 0
    for c, keys, g in zip(caches, keys_l, gathered):
        g = np.asarray(g, np.float32)
        nz = np.any(g != 0.0, axis=1)
        n = int(nz.sum())
        if n:
            c.client.push_sparse(c.table_id, keys[nz], g[nz])
            c.gsum = jnp.zeros_like(c.gsum)
            c.stats["writeback"] += n
            if _metrics_mod.enabled():
                _M_EVENTS.inc(n, event="writeback", table=str(c.table_id))
        total += n
    return total


def build_caches(embeddings, capacity: int, device=None
                 ) -> Dict[int, HotRowCache]:
    """One cache per DISTINCT cacheable table among `embeddings`; non-SGD
    tables are skipped with a warning (see CACHEABLE_OPTIMIZERS)."""
    caches: Dict[int, HotRowCache] = {}
    for e in embeddings:
        cfg = e._table_cfg
        if cfg.table_id in caches:
            continue
        if cfg.optimizer not in CACHEABLE_OPTIMIZERS:
            warnings.warn(
                f"hot-row cache skipped for table {cfg.table_id}: server "
                f"optimizer {cfg.optimizer!r} is not linear in the gradient "
                f"(cacheable: {CACHEABLE_OPTIMIZERS}); rows of this table "
                "keep the per-step pull/push path")
            continue
        # sum/geo tables: the server applies w += g (lr ignored), which is
        # the lr = -1 case of the SGD rule the cache computes on-chip —
        # using cfg.learning_rate here would silently change numerics
        lr = -1.0 if cfg.optimizer in ("sum", "geo") else cfg.learning_rate
        caches[cfg.table_id] = HotRowCache(
            cfg.table_id, cfg.dim, capacity, lr, e.client, device=device)
    return caches


__all__ = ["HotRowCache", "CachePlan", "build_caches", "combine_batch",
           "apply_batch", "flush_all", "CACHEABLE_OPTIMIZERS"]
