"""PS client: shards requests across servers, exposes numpy in/out.

Reference: `BrpcPsClient`
(/root/reference/paddle/fluid/distributed/ps/service/brpc_ps_client.h:137 —
pull_dense/push_dense/pull_sparse/push_sparse over brpc, feasigns sharded
across servers). Sharding rule kept: feasign -> server by key % n_servers;
dense tables are placed on server (table_id % n_servers).
"""
from __future__ import annotations

import ctypes
from dataclasses import dataclass
from typing import Dict, List, Sequence

import numpy as np

from ... import _native

_F32P = ctypes.POINTER(ctypes.c_float)
_U64P = ctypes.POINTER(ctypes.c_uint64)

OPTIMIZERS = {"sgd": 0, "adagrad": 1, "adam": 2}


@dataclass
class TableConfig:
    """Mirror of the reference's TableParameter proto (the_one_ps.py Table)."""
    table_id: int
    kind: str = "sparse"          # "dense" | "sparse"
    dim: int = 8                  # embedding dim (sparse)
    dense_size: int = 0           # flat length (dense)
    optimizer: str = "sgd"
    learning_rate: float = 0.01
    init_range: float = 0.05
    seed: int = 0


class PSClient:
    def __init__(self, endpoints: Sequence[str], timeout_ms: int = 60000):
        self._lib = _native.load()
        self._endpoints = list(endpoints)
        self._handles: List[int] = []
        self._tables: Dict[int, TableConfig] = {}
        for ep in self._endpoints:
            host, port = ep.rsplit(":", 1)
            h = self._lib.ps_connect(host.encode(), int(port), timeout_ms)
            if h < 0:
                raise RuntimeError(f"PSClient: cannot connect to {ep}")
            self._handles.append(h)

    @property
    def num_servers(self) -> int:
        return len(self._handles)

    def create_table(self, cfg: TableConfig):
        """Create on every server (idempotent server-side)."""
        kind = 0 if cfg.kind == "dense" else 1
        opt = OPTIMIZERS[cfg.optimizer]
        for h in self._handles:
            rc = self._lib.ps_create_table(
                h, cfg.table_id, kind, cfg.dim, cfg.dense_size, opt,
                cfg.learning_rate, cfg.init_range, cfg.seed)
            if rc != 0:
                raise RuntimeError(f"create_table({cfg.table_id}) failed")
        self._tables[cfg.table_id] = cfg

    def table(self, table_id: int) -> TableConfig:
        return self._tables[table_id]

    # ------------------------------ dense ---------------------------------

    def _dense_handle(self, table_id: int) -> int:
        return self._handles[table_id % self.num_servers]

    def pull_dense(self, table_id: int) -> np.ndarray:
        cfg = self._tables[table_id]
        out = np.empty(cfg.dense_size, np.float32)
        rc = self._lib.ps_pull_dense(
            self._dense_handle(table_id), table_id,
            out.ctypes.data_as(_F32P), cfg.dense_size)
        if rc != 0:
            raise RuntimeError(f"pull_dense({table_id}) failed")
        return out

    def push_dense(self, table_id: int, grad: np.ndarray):
        g = np.ascontiguousarray(grad, np.float32).ravel()
        rc = self._lib.ps_push_dense(
            self._dense_handle(table_id), table_id,
            g.ctypes.data_as(_F32P), g.size)
        if rc != 0:
            raise RuntimeError(f"push_dense({table_id}) failed")

    def set_dense(self, table_id: int, values: np.ndarray):
        v = np.ascontiguousarray(values, np.float32).ravel()
        rc = self._lib.ps_set_dense(
            self._dense_handle(table_id), table_id,
            v.ctypes.data_as(_F32P), v.size)
        if rc != 0:
            raise RuntimeError(f"set_dense({table_id}) failed")

    # ------------------------------ sparse --------------------------------

    def pull_sparse(self, table_id: int, keys: np.ndarray) -> np.ndarray:
        """keys: uint64 [n] -> values float32 [n, dim]."""
        cfg = self._tables[table_id]
        keys = np.ascontiguousarray(keys, np.uint64).ravel()
        n = keys.size
        out = np.empty((n, cfg.dim), np.float32)
        if n == 0:
            return out
        ns = self.num_servers
        if ns == 1:
            self._pull_shard(0, table_id, keys, out)
            return out
        shard = (keys % np.uint64(ns)).astype(np.int64)
        for s in range(ns):
            idx = np.nonzero(shard == s)[0]
            if idx.size == 0:
                continue
            part = np.empty((idx.size, cfg.dim), np.float32)
            self._pull_shard(s, table_id, np.ascontiguousarray(keys[idx]), part)
            out[idx] = part
        return out

    def _pull_shard(self, s: int, table_id: int, keys: np.ndarray,
                    out: np.ndarray):
        rc = self._lib.ps_pull_sparse(
            self._handles[s], table_id, keys.ctypes.data_as(_U64P), keys.size,
            out.ctypes.data_as(_F32P), out.size)
        if rc != 0:
            raise RuntimeError(f"pull_sparse({table_id}) failed")

    def push_sparse(self, table_id: int, keys: np.ndarray, grads: np.ndarray):
        """keys uint64 [n], grads float32 [n, dim]."""
        keys = np.ascontiguousarray(keys, np.uint64).ravel()
        grads = np.ascontiguousarray(grads, np.float32).reshape(keys.size, -1)
        n = keys.size
        if n == 0:
            return
        ns = self.num_servers
        if ns == 1:
            self._push_shard(0, table_id, keys, grads)
            return
        shard = (keys % np.uint64(ns)).astype(np.int64)
        for s in range(ns):
            idx = np.nonzero(shard == s)[0]
            if idx.size == 0:
                continue
            self._push_shard(s, table_id, np.ascontiguousarray(keys[idx]),
                             np.ascontiguousarray(grads[idx]))

    def _push_shard(self, s: int, table_id: int, keys: np.ndarray,
                    grads: np.ndarray):
        rc = self._lib.ps_push_sparse(
            self._handles[s], table_id, keys.ctypes.data_as(_U64P), keys.size,
            grads.ctypes.data_as(_F32P), grads.size)
        if rc != 0:
            raise RuntimeError(f"push_sparse({table_id}) failed")

    # ------------------------- control plane ------------------------------

    def table_size(self, table_id: int) -> int:
        return sum(self._lib.ps_table_size(h, table_id) for h in self._handles)

    def save(self, dirname: str):
        import os
        for i, h in enumerate(self._handles):
            d = os.path.join(dirname, f"server_{i}")
            os.makedirs(d, exist_ok=True)
            if self._lib.ps_save(h, d.encode()) != 0:
                raise RuntimeError("ps save failed")

    def load(self, dirname: str):
        import os
        for i, h in enumerate(self._handles):
            d = os.path.join(dirname, f"server_{i}")
            if self._lib.ps_load(h, d.encode()) != 0:
                raise RuntimeError("ps load failed")

    def barrier(self, name: str, world: int):
        """Barrier across `world` participants, coordinated by server 0."""
        if self._lib.ps_barrier(self._handles[0], name.encode(), world) != 0:
            raise RuntimeError("ps barrier failed")

    def stop_servers(self):
        for h in self._handles:
            self._lib.ps_stop_server(h)
