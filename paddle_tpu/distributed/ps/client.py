"""PS client: shards requests across servers, exposes numpy in/out.

Reference: `BrpcPsClient`
(/root/reference/paddle/fluid/distributed/ps/service/brpc_ps_client.h:137 —
pull_dense/push_dense/pull_sparse/push_sparse over brpc, feasigns sharded
across servers). Sharding rule kept: feasign -> server by key % n_servers;
dense tables are placed on server (table_id % n_servers).
"""
from __future__ import annotations

import ctypes
import os
import threading
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ... import _native
from ...fault import RetryExhaustedError, RetryPolicy
from ...fault import site as _fault_site


class PSRequestError(RuntimeError):
    """A PS RPC failed every retry. Names the dead endpoint and table so an
    operator can tell WHICH server to look at (the reference's brpc client
    logs the channel address on `FLAGS_pserver_timeout_ms` exhaustion)."""

    def __init__(self, op: str, endpoint: str, table_id: int,
                 attempts: int, last: BaseException):
        super().__init__(
            f"PS request {op!r} to server {endpoint} (table {table_id}) "
            f"failed after {attempts} attempt(s): "
            f"{type(last).__name__}: {last}")
        self.op = op
        self.endpoint = endpoint
        self.table_id = table_id
        self.attempts = attempts
        self.last = last

_F32P = ctypes.POINTER(ctypes.c_float)
_U64P = ctypes.POINTER(ctypes.c_uint64)
_I32P = ctypes.POINTER(ctypes.c_int32)

# "sum" = raw delta-merge (w += g) — the server side of geo-SGD
# (reference memory_sparse_geo_table.cc)
OPTIMIZERS = {"sgd": 0, "adagrad": 1, "adam": 2, "sum": 3, "geo": 3}

# per-request sparse batch budget (bytes of values); keeps every frame far
# under the transport's 256MB kMaxFrameLen regardless of caller batch size
_SPARSE_CHUNK_BYTES = 64 * 1024 * 1024


@dataclass
class TableConfig:
    """Mirror of the reference's TableParameter proto (the_one_ps.py Table)."""
    table_id: int
    kind: str = "sparse"          # "dense" | "sparse"
    dim: int = 8                  # embedding dim (sparse)
    dense_size: int = 0           # flat length (dense)
    optimizer: str = "sgd"
    learning_rate: float = 0.01
    init_range: float = 0.05
    seed: int = 0


class PSClient:
    def __init__(self, endpoints: Sequence[str], timeout_ms: int = 60000,
                 retry: Optional[RetryPolicy] = None,
                 pull_lanes: Optional[int] = None):
        if retry is None:
            retry = RetryPolicy.from_env(
                "PS", max_attempts=3, base_delay=0.1, max_delay=2.0)
        # never thread-abandon a native RPC (caller-supplied policies
        # included): an abandoned attempt keeps writing into the caller-
        # owned numpy buffer that its retry (and even the returned array)
        # also uses. Per-attempt deadlines belong to the transport's
        # timeout_ms, not the retry layer.
        if retry.attempt_timeout is not None:
            import copy
            import warnings
            warnings.warn(
                "PSClient ignores RetryPolicy.attempt_timeout (and "
                "PADDLE_TPU_PS_TIMEOUT): PS RPCs write caller-owned "
                "buffers and cannot be thread-abandoned; bound individual "
                "RPCs with PSClient(timeout_ms=...) instead")
            retry = copy.copy(retry)  # don't mutate the caller's policy
            retry.attempt_timeout = None
        self._retry = retry
        self._lib = _native.load()
        self._endpoints = list(endpoints)
        self._timeout_ms = timeout_ms
        self._handles: List[int] = []
        self._tables: Dict[int, TableConfig] = {}
        for ep in self._endpoints:
            host, port = ep.rsplit(":", 1)
            h = self._lib.ps_connect(host.encode(), int(port), timeout_ms)
            if h < 0:
                raise RuntimeError(f"PSClient: cannot connect to {ep}")
            self._handles.append(h)
        # extra "lane" connections for pull_sparse_multi: the native client
        # serializes requests per connection under a mutex, so overlapping
        # pulls across tables needs one connection set per concurrent lane
        # (the server spawns a thread per connection). Built lazily.
        if pull_lanes is None:
            from ...utils.envparse import env_int
            pull_lanes = env_int("PADDLE_TPU_PS_PULL_LANES", 4)
        self._max_pull_lanes = max(1, pull_lanes)
        self._lanes: List[List[int]] = []
        self._lane_lock = threading.Lock()
        self._lane_pool = None

    @property
    def num_servers(self) -> int:
        return len(self._handles)

    def _rpc(self, op: str, server_idx: int, table_id: int,
             call: Callable[[], int]):
        """Run one native RPC under retry+backoff with a fault site
        (`ps.<op>`); after exhaustion raise PSRequestError naming the dead
        endpoint. `call` returns the native rc (0 = ok). Pull/set calls
        rewrite the same buffer and are safe to replay; merge-style pushes
        are at-least-once under retry (the native transport fails before
        the server applies, so a replayed push did not apply the first
        time)."""
        def _do():
            _fault_site(f"ps.{op}")
            rc = call()
            if rc != 0:
                raise RuntimeError(f"{op} rpc returned {rc}")
        try:
            self._retry.call(_do, op=f"ps.{op}")
        except RetryExhaustedError as e:
            raise PSRequestError(op, self._endpoints[server_idx], table_id,
                                 e.attempts, e.last) from e

    def create_table(self, cfg: TableConfig):
        """Create on every server (idempotent server-side)."""
        kind = 0 if cfg.kind == "dense" else 1
        opt = OPTIMIZERS[cfg.optimizer]
        for h in self._handles:
            rc = self._lib.ps_create_table(
                h, cfg.table_id, kind, cfg.dim, cfg.dense_size, opt,
                cfg.learning_rate, cfg.init_range, cfg.seed)
            if rc != 0:
                raise RuntimeError(f"create_table({cfg.table_id}) failed")
        self._tables[cfg.table_id] = cfg

    def table(self, table_id: int) -> TableConfig:
        return self._tables[table_id]

    # ------------------------------ dense ---------------------------------

    def _dense_server(self, table_id: int):
        """(server_idx, handle) hosting a dense table — the one routing
        rule, shared by every dense op."""
        s = table_id % self.num_servers
        return s, self._handles[s]

    # dense tables of any size: transport in <=16M-float (64MB) chunks so
    # frames stay far under the 256MB transport cap
    _DENSE_CHUNK = 16 * 1024 * 1024

    def pull_dense(self, table_id: int) -> np.ndarray:
        cfg = self._tables[table_id]
        out = np.empty(cfg.dense_size, np.float32)
        s, h = self._dense_server(table_id)
        for off in range(0, cfg.dense_size, self._DENSE_CHUNK):
            ln = min(self._DENSE_CHUNK, cfg.dense_size - off)
            chunk = out[off:off + ln]
            self._rpc("pull_dense", s, table_id,
                      lambda: self._lib.ps_pull_dense(
                          h, table_id, chunk.ctypes.data_as(_F32P), off, ln))
        return out

    def push_dense(self, table_id: int, grad: np.ndarray):
        g = np.ascontiguousarray(grad, np.float32).ravel()
        s, h = self._dense_server(table_id)
        for off in range(0, g.size, self._DENSE_CHUNK):
            ln = min(self._DENSE_CHUNK, g.size - off)
            chunk = np.ascontiguousarray(g[off:off + ln])
            self._rpc("push_dense", s, table_id,
                      lambda: self._lib.ps_push_dense(
                          h, table_id, chunk.ctypes.data_as(_F32P), off, ln))

    def set_dense(self, table_id: int, values: np.ndarray):
        v = np.ascontiguousarray(values, np.float32).ravel()
        s, h = self._dense_server(table_id)
        for off in range(0, v.size, self._DENSE_CHUNK):
            ln = min(self._DENSE_CHUNK, v.size - off)
            chunk = np.ascontiguousarray(v[off:off + ln])
            self._rpc("set_dense", s, table_id,
                      lambda: self._lib.ps_set_dense(
                          h, table_id, chunk.ctypes.data_as(_F32P), off, ln))

    # ------------------------------ sparse --------------------------------

    def _shard_indices(self, keys: np.ndarray):
        """Yield (server_idx, positions) for the keys%num_servers routing
        shared by every sparse op. positions is None for the single-server
        fast path (callers use the arrays directly, no fancy-index copies).
        """
        ns = self.num_servers
        if ns == 1:
            yield 0, None
            return
        shard = (keys % np.uint64(ns)).astype(np.int64)
        for s in range(ns):
            idx = np.nonzero(shard == s)[0]
            if idx.size:
                yield s, idx

    def pull_sparse(self, table_id: int, keys: np.ndarray,
                    handles: Optional[List[int]] = None) -> np.ndarray:
        """keys: uint64 [n] -> values float32 [n, dim]."""
        cfg = self._tables[table_id]
        keys = np.ascontiguousarray(keys, np.uint64).ravel()
        out = np.empty((keys.size, cfg.dim), np.float32)
        if keys.size == 0:
            return out
        for s, idx in self._shard_indices(keys):
            if idx is None:
                self._pull_shard(s, table_id, keys, out, handles)
                continue
            part = np.empty((idx.size, cfg.dim), np.float32)
            self._pull_shard(s, table_id, np.ascontiguousarray(keys[idx]),
                             part, handles)
            out[idx] = part
        return out

    # -------------------- overlapped multi-table pull -----------------------

    def _ensure_lanes(self, n: int) -> int:
        """Grow the lane-connection pool to min(n, max_pull_lanes) lanes;
        returns the usable lane count. Lane 0 reuses the primary handles."""
        n = min(max(n, 1), self._max_pull_lanes)
        with self._lane_lock:
            if not self._lanes:
                self._lanes.append(self._handles)
            while len(self._lanes) < n:
                lane = []
                for ep in self._endpoints:
                    host, port = ep.rsplit(":", 1)
                    h = self._lib.ps_connect(host.encode(), int(port),
                                             self._timeout_ms)
                    if h < 0:  # degraded server: fall back to fewer lanes
                        lane = None
                        break
                    lane.append(h)
                if lane is None:
                    # cap at what we achieved and STOP trying: there is no
                    # native disconnect, so re-attempting on every pull
                    # would strand one handle per healthy endpoint per
                    # step and pay blocking connects on the prepare stage
                    self._max_pull_lanes = len(self._lanes)
                    break
                self._lanes.append(lane)
            if self._lane_pool is None and len(self._lanes) > 1:
                import concurrent.futures
                self._lane_pool = concurrent.futures.ThreadPoolExecutor(
                    max_workers=self._max_pull_lanes,
                    thread_name_prefix="ps-pull-lane")
            return len(self._lanes)

    def pull_sparse_multi(
            self, requests: Sequence[Tuple[int, np.ndarray]]
    ) -> List[np.ndarray]:
        """Pull several tables' rows in ONE overlapped RPC round.

        `requests` is a sequence of ``(table_id, keys)``; the result list
        matches it by position. Each concurrent request runs over its own
        lane connection (the per-connection mutex in the native client —
        and the blocking socket under it — would serialize them otherwise),
        so the wall cost is one round trip, not ``len(requests)``. The
        per-RPC retry/fault-site machinery (`ps.pull_sparse`) applies
        unchanged on every lane."""
        reqs = [(tid, np.ascontiguousarray(k, np.uint64).ravel())
                for tid, k in requests]
        live = [i for i, (_, k) in enumerate(reqs) if k.size]
        if len(live) <= 1:
            return [self.pull_sparse(tid, k) for tid, k in reqs]
        lanes = self._ensure_lanes(len(live))
        if lanes <= 1 or self._lane_pool is None:
            return [self.pull_sparse(tid, k) for tid, k in reqs]
        out: List[Optional[np.ndarray]] = [
            None if i in set(live) else self.pull_sparse(*reqs[i])
            for i in range(len(reqs))]
        futs = {}
        for j, i in enumerate(live):
            tid, k = reqs[i]
            futs[i] = self._lane_pool.submit(
                self.pull_sparse, tid, k, self._lanes[j % lanes])
        for i, f in futs.items():
            out[i] = f.result()
        return out

    def _sparse_chunk(self, dim: int) -> int:
        return max(1, _SPARSE_CHUNK_BYTES // max(dim * 4, 16))

    def _pull_shard(self, s: int, table_id: int, keys: np.ndarray,
                    out: np.ndarray, handles: Optional[List[int]] = None):
        h = (handles or self._handles)[s]
        step = self._sparse_chunk(out.shape[1] if out.ndim > 1 else 1)
        for i in range(0, keys.size, step):
            k = keys[i:i + step]
            o = out[i:i + step]
            self._rpc("pull_sparse", s, table_id,
                      lambda: self._lib.ps_pull_sparse(
                          h, table_id,
                          k.ctypes.data_as(_U64P), k.size,
                          o.ctypes.data_as(_F32P), o.size))

    def push_sparse(self, table_id: int, keys: np.ndarray, grads: np.ndarray):
        """keys uint64 [n], grads float32 [n, dim]."""
        keys = np.ascontiguousarray(keys, np.uint64).ravel()
        grads = np.ascontiguousarray(grads, np.float32).reshape(keys.size, -1)
        if keys.size == 0:
            return
        for s, idx in self._shard_indices(keys):
            if idx is None:
                self._push_shard(s, table_id, keys, grads)
                continue
            self._push_shard(s, table_id, np.ascontiguousarray(keys[idx]),
                             np.ascontiguousarray(grads[idx]))

    def _push_shard(self, s: int, table_id: int, keys: np.ndarray,
                    grads: np.ndarray):
        step = self._sparse_chunk(grads.shape[1] if grads.ndim > 1 else 1)
        for i in range(0, keys.size, step):
            k = np.ascontiguousarray(keys[i:i + step])
            g = np.ascontiguousarray(grads[i:i + step])
            self._rpc("push_sparse", s, table_id,
                      lambda: self._lib.ps_push_sparse(
                          self._handles[s], table_id,
                          k.ctypes.data_as(_U64P), k.size,
                          g.ctypes.data_as(_F32P), g.size))

    # -------------------- CTR lifecycle (ctr_accessor) ---------------------

    def push_show_click(self, table_id: int, keys: np.ndarray,
                        shows: np.ndarray, clicks: np.ndarray):
        """Accumulate impression/click counters on sparse rows (reference
        CtrCommonAccessor: show/click feed the eviction score)."""
        keys = np.ascontiguousarray(keys, np.uint64).ravel()
        shows = np.ascontiguousarray(shows, np.float32).ravel()
        clicks = np.ascontiguousarray(clicks, np.float32).ravel()
        for s, idx in self._shard_indices(keys):
            if idx is None:
                k, sh, cl = keys, shows, clicks
            else:
                k = np.ascontiguousarray(keys[idx])
                sh = np.ascontiguousarray(shows[idx])
                cl = np.ascontiguousarray(clicks[idx])
            step = self._sparse_chunk(4)
            for i in range(0, k.size, step):
                ks = np.ascontiguousarray(k[i:i + step])
                rc = self._lib.ps_push_show_click(
                    self._handles[s], table_id,
                    ks.ctypes.data_as(_U64P), ks.size,
                    np.ascontiguousarray(sh[i:i + step]).ctypes.data_as(_F32P),
                    np.ascontiguousarray(cl[i:i + step]).ctypes.data_as(_F32P))
                if rc != 0:
                    raise RuntimeError(f"push_show_click({table_id}) failed")

    def register_row_cache(self, cache):
        """Register a device-side hot-row cache serving one of this
        client's tables (`distributed/ps/cache.py` does this at
        construction), so server-side lifecycle operations that evict
        rows — `shrink()` — can flush + invalidate it. Held by weakref:
        a dropped cache unregisters itself."""
        import weakref
        if not hasattr(self, "_row_caches"):
            self._row_caches = []
        self._row_caches.append(weakref.ref(cache))

    def _table_caches(self, table_id: int):
        out = []
        for ref in list(getattr(self, "_row_caches", ())):
            c = ref()
            if c is None:
                self._row_caches.remove(ref)
            elif c.table_id == int(table_id):
                out.append(c)
        return out

    def shrink(self, table_id: int, threshold: float = 0.0,
               max_unseen_days: int = 7) -> int:
        """One day-tick: decay show/click, age rows, evict below-threshold
        stale rows on every server. Returns total evicted rows.

        Device hot-row caches registered for this table are part of the
        lifecycle: their pending gradients are FLUSHED first (so the
        eviction decision sees fully-accounted rows, and no post-shrink
        write-back can resurrect an evicted key), then — after the
        server-side eviction — every cached row is INVALIDATED. Without
        this, a shrunk row stayed device-resident and was served stale on
        every later hit (the PR-4 follow-up this closes). Call shrink at
        a step boundary with no planned-but-undispatched batch in flight
        (pipelined heter trainers: `HeterPSTrainStep.flush()` first) —
        a cache plan computed before the invalidation must not be
        committed after it."""
        caches = self._table_caches(table_id)
        for c in caches:
            c.flush()
        total = 0
        for h in self._handles:
            n = self._lib.ps_shrink(h, table_id, float(threshold),
                                    int(max_unseen_days))
            if n < 0:
                raise RuntimeError(f"shrink({table_id}) failed")
            total += int(n)
        for c in caches:
            c.invalidate()
        return total

    def pull_meta(self, table_id: int, keys: np.ndarray):
        """Per-key (show, click, unseen_days); unseen_days=-1 if evicted."""
        keys = np.ascontiguousarray(keys, np.uint64).ravel()
        n = keys.size
        show = np.empty(n, np.float32)
        click = np.empty(n, np.float32)
        unseen = np.empty(n, np.int32)
        for s, idx in self._shard_indices(keys):
            if idx is None:
                k, sh, cl, un = keys, show, click, unseen
            else:
                k = np.ascontiguousarray(keys[idx])
                sh = np.empty(idx.size, np.float32)
                cl = np.empty(idx.size, np.float32)
                un = np.empty(idx.size, np.int32)
            step = self._sparse_chunk(4)
            for i in range(0, k.size, step):
                ks = np.ascontiguousarray(k[i:i + step])
                rc = self._lib.ps_pull_meta(
                    self._handles[s], table_id, ks.ctypes.data_as(_U64P),
                    ks.size, sh[i:i + step].ctypes.data_as(_F32P),
                    cl[i:i + step].ctypes.data_as(_F32P),
                    un[i:i + step].ctypes.data_as(_I32P))
                if rc != 0:
                    raise RuntimeError(f"pull_meta({table_id}) failed")
            if idx is not None:
                show[idx], click[idx], unseen[idx] = sh, cl, un
        return show, click, unseen

    # -------------------- graph tables (common_graph_table) ----------------

    def graph_add_edges(self, table_id: int, src: np.ndarray,
                        dst: np.ndarray, weights=None):
        """Append directed edges (reference common_graph_table.cc): nodes
        shard across servers by src id; weights default to 1."""
        src = np.ascontiguousarray(src, np.uint64).ravel()
        dst = np.ascontiguousarray(dst, np.uint64).ravel()
        w = (None if weights is None
             else np.ascontiguousarray(weights, np.float32).ravel())
        step = _SPARSE_CHUNK_BYTES // 20  # 8+8+4 bytes per edge
        for s, idx in self._shard_indices(src):
            ks = src if idx is None else np.ascontiguousarray(src[idx])
            kd = dst if idx is None else np.ascontiguousarray(dst[idx])
            kw = (None if w is None else
                  (w if idx is None else np.ascontiguousarray(w[idx])))
            for i in range(0, ks.size, step):
                cs = np.ascontiguousarray(ks[i:i + step])
                cd = np.ascontiguousarray(kd[i:i + step])
                cw = (None if kw is None
                      else np.ascontiguousarray(kw[i:i + step]))
                rc = self._lib.ps_graph_add_edges(
                    self._handles[s], table_id, cs.ctypes.data_as(_U64P),
                    cd.ctypes.data_as(_U64P),
                    (cw.ctypes.data_as(_F32P) if cw is not None
                     else ctypes.cast(None, _F32P)), cs.size)
                if rc != 0:
                    raise RuntimeError(
                        f"graph_add_edges({table_id}) failed")

    def graph_sample_neighbors(self, table_id: int, nodes: np.ndarray,
                               k: int, seed: int = 0):
        """Sample up to k neighbors per node (weight-proportional without
        replacement; all neighbors when degree <= k). Returns (neighbors
        [n, k] uint64 padded with 0, counts [n] int32)."""
        nodes = np.ascontiguousarray(nodes, np.uint64).ravel()
        n = nodes.size
        counts = np.zeros(n, np.int32)
        padded = np.zeros((n, max(k, 1)), np.uint64)
        step = max(1, _SPARSE_CHUNK_BYTES // (12 + 8 * max(k, 1)))
        for s, idx in self._shard_indices(nodes):
            ks = nodes if idx is None else np.ascontiguousarray(nodes[idx])
            cc = np.zeros(ks.size, np.int32)
            rows = np.zeros((ks.size, max(k, 1)), np.uint64)
            for i0 in range(0, ks.size, step):
                chunk = np.ascontiguousarray(ks[i0:i0 + step])
                c_chunk = np.zeros(chunk.size, np.int32)
                flat = np.zeros(chunk.size * max(k, 1), np.uint64)
                total = self._lib.ps_graph_sample(
                    self._handles[s], table_id, chunk.ctypes.data_as(_U64P),
                    chunk.size, int(k), int(seed),
                    c_chunk.ctypes.data_as(_I32P),
                    flat.ctypes.data_as(_U64P))
                if total < 0:
                    raise RuntimeError(f"graph_sample({table_id}) failed")
                pos = 0
                for i, c_ in enumerate(c_chunk):
                    rows[i0 + i, :c_] = flat[pos:pos + c_]
                    pos += int(c_)
                cc[i0:i0 + chunk.size] = c_chunk
            if idx is None:
                counts, padded = cc, rows
            else:
                counts[idx] = cc
                padded[idx] = rows
        return padded, counts

    def graph_khop_sample(self, table_id: int, nodes: np.ndarray,
                          sample_sizes, seed: int = 0):
        """Multi-hop neighbor sampling (reference graph service khop, the
        server-side counterpart of incubate.graph_khop_sampler): hop i
        samples `sample_sizes[i]` neighbors of the previous frontier.
        Returns a list of (neighbors [n_i, k_i] uint64, counts [n_i] int32,
        frontier [n_i] uint64) per hop; the next frontier is the unique set
        of sampled neighbors."""
        frontier = np.ascontiguousarray(nodes, np.uint64).ravel()
        hops = []
        for hop, k in enumerate(sample_sizes):
            nb, cnt = self.graph_sample_neighbors(
                table_id, frontier, int(k), seed=seed + hop)
            hops.append((nb, cnt, frontier))
            if cnt.sum() == 0:
                break
            mask = np.arange(nb.shape[1]) < cnt[:, None]
            frontier = np.unique(nb[mask])
            if frontier.size == 0:
                break
        return hops

    def graph_degree(self, table_id: int, nodes: np.ndarray) -> np.ndarray:
        nodes = np.ascontiguousarray(nodes, np.uint64).ravel()
        out = np.zeros(nodes.size, np.int64)
        step = _SPARSE_CHUNK_BYTES // 16
        for s, idx in self._shard_indices(nodes):
            ks = nodes if idx is None else np.ascontiguousarray(nodes[idx])
            dd = np.zeros(ks.size, np.int64)
            for i in range(0, ks.size, step):
                chunk = np.ascontiguousarray(ks[i:i + step])
                rc = self._lib.ps_graph_degree(
                    self._handles[s], table_id, chunk.ctypes.data_as(_U64P),
                    chunk.size,
                    dd[i:i + step].ctypes.data_as(
                        ctypes.POINTER(ctypes.c_int64)))
                if rc != 0:
                    raise RuntimeError(f"graph_degree({table_id}) failed")
            if idx is None:
                out = dd
            else:
                out[idx] = dd
        return out

    # -------------------- disk spill (ssd_sparse_table) --------------------

    def set_spill(self, table_id: int, dirname: str):
        """Enable disk spill for a sparse table: cold rows move to an
        append-only file per server, RAM keeps a key->offset index
        (reference ps/table/ssd_sparse_table.cc over rocksdb)."""
        import os
        os.makedirs(dirname, exist_ok=True)
        for i, h in enumerate(self._handles):
            path = os.path.join(dirname, f"spill_{table_id}_srv{i}.bin")
            if self._lib.ps_set_spill(h, table_id, path.encode()) != 0:
                raise RuntimeError(f"set_spill({table_id}) failed")

    def spill_cold(self, table_id: int, max_unseen_days: int = 1) -> int:
        """Move rows unseen for more than N day-ticks to disk; they restore
        transparently on next pull/push. Returns rows spilled.

        `shrink()` owns the day tick — spill_cold only COMPARES the age, so
        daily maintenance pairs them: `shrink(tid, thr, evict_days)` then
        `spill_cold(tid, spill_days)`. For spill-only maintenance use an
        age-only shrink (negative threshold evicts nothing but ages)."""
        total = 0
        for h in self._handles:
            n = self._lib.ps_spill_cold(h, table_id, int(max_unseen_days))
            if n < 0:
                raise RuntimeError(f"spill_cold({table_id}) failed "
                                   "(set_spill first?)")
            total += int(n)
        return total

    def spilled_size(self, table_id: int) -> int:
        return sum(int(self._lib.ps_spilled_size(h, table_id))
                   for h in self._handles)

    # ------------------------- control plane ------------------------------

    def table_size(self, table_id: int) -> int:
        return sum(self._lib.ps_table_size(h, table_id) for h in self._handles)

    def save(self, dirname: str):
        import os
        for i, h in enumerate(self._handles):
            d = os.path.join(dirname, f"server_{i}")
            os.makedirs(d, exist_ok=True)
            if self._lib.ps_save(h, d.encode()) != 0:
                raise RuntimeError("ps save failed")

    def load(self, dirname: str):
        import os
        for i, h in enumerate(self._handles):
            d = os.path.join(dirname, f"server_{i}")
            if self._lib.ps_load(h, d.encode()) != 0:
                raise RuntimeError("ps load failed")

    def barrier(self, name: str, world: int):
        """Barrier across `world` participants, coordinated by server 0."""
        if self._lib.ps_barrier(self._handles[0], name.encode(), world) != 0:
            raise RuntimeError("ps barrier failed")

    def stop_servers(self):
        for h in self._handles:
            self._lib.ps_stop_server(h)
