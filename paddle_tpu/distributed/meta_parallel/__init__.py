"""Hybrid-parallel building blocks (reference
`python/paddle/distributed/fleet/meta_parallel/`)."""
from .parallel_layers import (  # noqa: F401
    ColumnParallelLinear, ParallelCrossEntropy, RNGStatesTracker,
    RowParallelLinear, VocabParallelEmbedding, get_rng_state_tracker,
    model_parallel_random_seed,
)
from . import parallel_layers  # noqa: F401
from .pp_layers import (  # noqa: F401
    LayerDesc, PipelineLayer, SharedLayerDesc,
)
from .pipeline_parallel import (  # noqa: F401
    PipelineParallel, PipelineParallelTrainStep,
)
