"""Tensor (model) parallel layers + parallel RNG.

Reference: `VocabParallelEmbedding` / `ColumnParallelLinear` /
`RowParallelLinear` / `ParallelCrossEntropy`
(`/root/reference/python/paddle/distributed/fleet/meta_parallel/
parallel_layers/mp_layers.py:30,97,170,249`) and `RNGStatesTracker`
(`parallel_layers/random.py:32`).

TPU-native translation (Megatron math, GSPMD mechanics): each layer holds the
FULL logical weight annotated with a `dist_spec` PartitionSpec; eager forward
is the plain math (bitwise-identical to single device), and under `jit` the
hybrid engine feeds `dist_spec` to `in_shardings` while the layer pins
activation layouts with `with_sharding_constraint`. XLA then emits exactly
the reference's collectives: column f/row g identity-allreduce pairs
(`mp_layers.py:82,154`) become partitioner-inserted all-reduces over the
`mp` ICI axis. No per-rank weight slices, no manual `c_identity` ops — and
the same layer runs unchanged at mp=1.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ...framework.tensor import Tensor
from ...nn.layer import Layer
from ...nn import functional as F
from ...nn.initializer import XavierUniform
from ..topology import get_hybrid_communicate_group


def _mp_degree() -> int:
    hcg = get_hybrid_communicate_group()
    return hcg.axis_size("mp") if hcg is not None else 1


def _constrain(x, *spec):
    """Pin a sharding on an activation inside a trace (no-op at mp=1 or in
    plain eager mode)."""
    hcg = get_hybrid_communicate_group()
    if hcg is None or hcg.axis_size("mp") <= 1:
        return x
    arr = x.data if isinstance(x, Tensor) else x
    if not isinstance(arr, jax.core.Tracer):
        return x
    sh = NamedSharding(hcg.mesh, P(*spec))
    try:
        out = jax.lax.with_sharding_constraint(arr, sh)
    except Exception:
        return x  # inside shard_map or meshless trace: constraint not valid
    if isinstance(x, Tensor):
        t = Tensor(out, stop_gradient=x.stop_gradient)
        t._node = x._node
        return t
    return out


def mark_as_sequence_parallel_parameter(param):
    param.dist_spec = P()
    return param


class VocabParallelEmbedding(Layer):
    """Embedding with the vocab dim sharded over `mp`
    (reference mp_layers.py:30; lookup + allreduce via `c_embedding`,
    `operators/collective/c_embedding_op.cc`)."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim), attr=weight_attr,
            default_initializer=None if weight_attr is not None
            else XavierUniform())
        self.weight.dist_spec = P("mp", None)
        self.weight.is_distributed = _mp_degree() > 1

    def forward(self, x):
        out = F.embedding(x, self.weight)
        return _constrain(out, None, None, None)  # replicated (allreduced)


class ColumnParallelLinear(Layer):
    """Linear with out_features split over `mp`; forward is the Megatron
    "f" block (identity fwd / allreduce bwd) (reference mp_layers.py:97)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.gather_output = gather_output
        self.weight = self.create_parameter(
            (in_features, out_features), attr=weight_attr,
            default_initializer=None if weight_attr is not None
            else XavierUniform())
        self.weight.dist_spec = P(None, "mp")
        self.weight.is_distributed = _mp_degree() > 1
        if has_bias:
            self.bias = self.create_parameter((out_features,),
                                              attr=None, is_bias=True)
            self.bias.dist_spec = P("mp")
        else:
            self.bias = None
            self._parameters["bias"] = None

    def forward(self, x):
        y = F.linear(x, self.weight, self.bias)
        if self.gather_output:
            return _constrain(y, *((None,) * len(y.shape)))
        return _constrain(y, *((None,) * (len(y.shape) - 1) + ("mp",)))


class RowParallelLinear(Layer):
    """Linear with in_features split over `mp`; forward ends in the Megatron
    "g" block allreduce (reference mp_layers.py:170)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False,
                 fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter(
            (in_features, out_features), attr=weight_attr,
            default_initializer=None if weight_attr is not None
            else XavierUniform())
        self.weight.dist_spec = P("mp", None)
        self.weight.is_distributed = _mp_degree() > 1
        if has_bias:
            self.bias = self.create_parameter((out_features,),
                                              attr=None, is_bias=True)
            self.bias.dist_spec = P()
        else:
            self.bias = None
            self._parameters["bias"] = None

    def forward(self, x):
        if self.input_is_parallel or _mp_degree() > 1:
            x = _constrain(x, *((None,) * (len(x.shape) - 1) + ("mp",)))
        y = F.linear(x, self.weight, self.bias)
        return _constrain(y, *((None,) * len(y.shape)))


class ParallelCrossEntropy(Layer):
    """Cross-entropy over vocab-sharded logits (reference mp_layers.py:249 →
    `c_softmax_with_cross_entropy_op`). GSPMD partitions the log-softmax
    reduction over `mp` (max/sum become all-reduces) when logits carry an
    `mp` sharding on the class dim."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        x = _constrain(input, *((None,) * (len(input.shape) - 1) + ("mp",)))
        return F.cross_entropy(x, label, reduction="none",
                               ignore_index=self.ignore_index)


# ---------------------------------------------------------------------------
# parallel RNG (reference parallel_layers/random.py:32)
# ---------------------------------------------------------------------------
class RNGStatesTracker:
    """Named RNG streams. The reference snapshots per-mp-rank CUDA states so
    dropout differs across mp ranks on sharded activations; in
    single-controller JAX a dropout mask on a logical (sharded) array is
    already computed per-shard by construction, so streams here are jax
    PRNG-key folds — kept for API parity and for recompute replay."""

    def __init__(self):
        self.states_ = {}
        self.seeds_ = set()

    def reset(self):
        self.states_.clear()
        self.seeds_.clear()

    def add(self, name, seed):
        if seed in self.seeds_:
            raise ValueError(f"seed {seed} already exists")
        if name in self.states_:
            raise ValueError(f"state {name} already exists")
        self.seeds_.add(seed)
        self.states_[name] = jax.random.PRNGKey(seed)

    def get_states_tracker(self):
        return dict(self.states_)

    def set_states_tracker(self, states):
        self.states_ = dict(states)

    def rng_state(self, name="model-parallel-rng"):
        import contextlib

        @contextlib.contextmanager
        def cm():
            if name not in self.states_:
                raise ValueError(f"state {name} not added")
            from ...framework import random as random_mod
            key = self.states_[name]
            key, sub = jax.random.split(key)
            self.states_[name] = key
            with random_mod.rng_scope(sub):
                yield
        return cm()


_RNG_STATE_TRACKER = RNGStatesTracker()
MODEL_PARALLEL_RNG = "model-parallel-rng"


def get_rng_state_tracker() -> RNGStatesTracker:
    return _RNG_STATE_TRACKER


def model_parallel_random_seed(seed=None):
    import random as pyrandom
    seed = seed if seed is not None else pyrandom.randint(0, 2**31 - 1)
    _RNG_STATE_TRACKER.reset()
    from ...framework import random as random_mod
    random_mod.seed(seed)
    _RNG_STATE_TRACKER.add(MODEL_PARALLEL_RNG, seed + 1)
