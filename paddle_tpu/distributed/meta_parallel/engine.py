"""Hybrid-parallel compiled training engine.

The TPU replacement for the reference's whole tower of distributed
machinery: `HybridParallelOptimizer` + `Reducer` + sharding-stage wrappers +
meta-optimizer program rewrites
(`/root/reference/python/paddle/distributed/fleet/meta_parallel/`,
`fleet/meta_optimizers/`). One `jax.jit` over the Mesh does what those do
with explicit collective ops:

* **DP**: batch sharded over `dp` -> XLA psums parameter grads (Reducer).
* **TP**: params carry `dist_spec` over `mp` (set by the parallel layers) ->
  partitioner emits Megatron's f/g collectives.
* **ZeRO 1/2**: optimizer slots sharded over `sharding`
  (reference `DygraphShardingOptimizer`/`ShardingStage2`) — XLA's
  weight-update sharding: grads reduce-scatter in, updated shard
  all-gathers out.
* **ZeRO 3**: params themselves sharded over `sharding`
  (reference `ShardingStage3`) — all-gather on use, inserted by XLA.
* **SP**: sequence dim sharded over `sp` (no reference equivalent —
  SURVEY.md §5.7).
* **recompute / gradient-merge**: `jax.checkpoint` + a `lax.scan` over
  micro-batches (reference `RecomputeFunction`, `gradient_merge_optimizer`).
"""
from __future__ import annotations

from typing import Callable, Dict, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ...framework import random as random_mod
from ...framework.tensor import Tensor
from ...nn.layer import Layer
from ..topology import (HybridCommunicateGroup, get_hybrid_communicate_group)


def _axis_sizes(mesh: Mesh) -> Dict[str, int]:
    return dict(zip(mesh.axis_names, mesh.devices.shape))


def _scaler_config(strategy):
    """fp16 dynamic-loss-scaling hyperparams (reference grad_scaler.py:26);
    scaling runs INSIDE the compiled step (state carried as arrays), so the
    parallel engines support strategy amp dtype='float16' end-to-end."""
    cfg = strategy.amp_configs if strategy is not None else {}
    return {
        "init_scale": float(cfg.get("init_loss_scaling", 2.0 ** 15)),
        "incr_every": int(cfg.get("incr_every_n_steps", 1000)),
        "incr_ratio": float(cfg.get("incr_ratio", 2.0)),
        "decr_ratio": float(cfg.get("decr_ratio", 0.5)),
    }


def _apply_scaled_update(optimizer, params, grads, opt_state, lr, t,
                         scaler_state, sc):
    """Unscale grads, skip the update on non-finite grads, and update the
    dynamic scale — the whole check_finite_and_unscale/update_loss_scaling
    pattern fused into the step."""
    scale = scaler_state["scale"]
    good = scaler_state["good"]
    grads = jax.tree_util.tree_map(lambda g: g / scale, grads)
    finite = jnp.array(True)
    for g in jax.tree_util.tree_leaves(grads):
        finite = finite & jnp.all(jnp.isfinite(g))
    new_params, new_opt = optimizer.apply_fn(params, grads, opt_state,
                                             lr=lr, t=t)
    new_params = jax.tree_util.tree_map(
        lambda new, old: jnp.where(finite, new, old), new_params, params)
    new_opt = jax.tree_util.tree_map(
        lambda new, old: jnp.where(finite, new, old), new_opt, opt_state)
    grew = finite & (good + 1 >= sc["incr_every"])
    new_scale = jnp.where(
        finite,
        jnp.where(grew, scale * sc["incr_ratio"], scale),
        jnp.maximum(scale * sc["decr_ratio"], 1.0))
    new_good = jnp.where(finite, jnp.where(grew, 0, good + 1), 0)
    return new_params, new_opt, {"scale": new_scale, "good": new_good}


def _build_health_probe(params: Dict[str, object], health):
    """The PR-9 in-graph numerics sentinel for the parallel engines, which
    build their own compiled steps and did not carry it (carried-over
    ROADMAP follow-up). Returns (probe | None, interval). `health=None`
    follows PADDLE_TPU_HEALTH / FLAGS_check_nan_inf like jit.TrainStep."""
    from ...profiler import health as _health_mod
    if health is None:
        health = _health_mod.enabled()
    probe = _health_mod.HealthProbe(params) if health else None
    return probe, _health_mod.interval()


def _health_grads(grads, scaler_state, fp16: bool):
    """Grads as the health sentinel should see them. Under fp16 dynamic
    loss scaling the raw grads are loss-SCALED (norms inflated by the
    scale, up to 2^15) and an occasional non-finite scaled grad is the
    scaler's NORMAL overflow signal (the update is skipped and the scale
    halves, GradScaler semantics) — not a divergence: unscale, and mask
    non-finite lanes to 0 so scaler events never trip the sentinel (real
    divergence still shows through the loss flag and the pre-update param
    flags). bf16/fp32 paths pass through untouched."""
    if not fp16:
        return grads
    inv = 1.0 / scaler_state["scale"]
    return jax.tree_util.tree_map(
        lambda g: jnp.where(jnp.isfinite(g), g * inv, 0.0), grads)


def _note_health(step_obj, hvec):
    """Decode + record one sentinel vector (the tier's single device->host
    fetch). Parallel steps record like jit.TrainStep but skip the eager
    replay (the sharded batch has no eager single-host replay path); the
    per-group PRE-UPDATE param flags still name the first bad layer group.
    Never raises."""
    from ...profiler import health as _health_mod
    try:
        stats = step_obj._health_probe.decode(hvec)
        step_obj.last_health = _health_mod.record_step_stats(
            stats, step=step_obj._t, source="sentinel")
    except Exception:
        pass


def _parse_strategy(strategy, sizes):
    """(amp_enabled, amp_dtype, recompute, sharding_stage, accum_steps)."""
    amp_enabled = bool(strategy and strategy.amp)
    amp_dtype = jnp.bfloat16 if not strategy else (
        jnp.float16 if strategy.amp_configs.get("dtype") == "float16"
        else jnp.bfloat16)
    recompute = bool(strategy and strategy.recompute)
    sharding_stage = 0
    if strategy and strategy.sharding:
        sharding_stage = int(strategy.sharding_configs.get("stage", 1))
    if sizes.get("sharding", 1) > 1 and sharding_stage == 0:
        sharding_stage = 1
    accum = 1
    if strategy is not None:
        if strategy.gradient_merge:
            accum = int(strategy.gradient_merge_configs.get("k_steps", 1))
        elif strategy.pipeline:
            accum = int(strategy.pipeline_configs.get("accumulate_steps", 1))
    return amp_enabled, amp_dtype, recompute, sharding_stage, max(1, accum)


def _filter_spec(base: P, ndim: int, sizes) -> P:
    """Pad `base` to ndim and drop axes absent from / trivial on the mesh."""
    return P(*[a if (a in sizes and sizes[a] > 1) else None
               for a in (tuple(base) + (None,) * (ndim - len(base)))])


def _slot_shardings(optimizer, flat_params, specs, sizes, sharding_stage,
                    mesh):
    """Per-slot NamedShardings: param-shaped slots inherit the param spec
    (+ ZeRO `sharding` axis for stage>=1), scalars replicate."""
    opt_shape = jax.eval_shape(optimizer.init_state_tree, flat_params)
    out = {}
    for k, slots in opt_shape.items():
        base = specs[k]
        per = {}
        for sname, sval in slots.items():
            if tuple(sval.shape) == tuple(flat_params[k].shape):
                s = base
                if sharding_stage >= 1:
                    s = _with_sharding_axis(s, "sharding", sval.shape, sizes)
                per[sname] = NamedSharding(mesh, s)
            else:
                per[sname] = NamedSharding(mesh, P())
        out[k] = per
    return out


def _data_axes_of(sizes):
    return tuple(a for a in ("dp", "sharding") if sizes.get(a, 1) > 1) or None


def _with_sharding_axis(spec: P, axis: str, shape, sizes) -> P:
    """Insert `axis` into the first unsharded, divisible dim of `spec`."""
    n = sizes.get(axis, 1)
    if n <= 1:
        return spec
    parts = list(spec) + [None] * (len(shape) - len(spec))
    for i, (p, d) in enumerate(zip(parts, shape)):
        if p is None and d % n == 0 and d >= n:
            parts[i] = axis
            return P(*parts)
    return spec  # nothing shardable: keep replicated on this axis


class HybridParallelTrainStep:
    """Compile fwd+bwd+optimizer into one sharded XLA executable.

    batch_specs: optional per-input PartitionSpec list. Default: dim0 over
    (dp, sharding), dim1 over sp for rank>=2 inputs.
    """

    def __init__(self, layer: Layer, loss_fn: Callable, optimizer,
                 hcg: Optional[HybridCommunicateGroup] = None,
                 strategy=None, batch_specs: Optional[Sequence[P]] = None,
                 donate: bool = True, health=None):
        from ...jit import functionalize
        self.layer = layer
        self.optimizer = optimizer
        self.hcg = hcg or get_hybrid_communicate_group()
        assert self.hcg is not None, \
            "set up fleet.init(...) / HybridCommunicateGroup first"
        mesh = self.hcg.mesh
        self.mesh = mesh
        sizes = _axis_sizes(mesh)
        self.strategy = strategy
        self._t = 0

        (amp_enabled, amp_dtype, recompute, sharding_stage,
         accum) = _parse_strategy(strategy, sizes)
        self.accumulate_steps = accum

        apply_fn, params, buffers = functionalize(layer)
        if recompute:
            apply_fn = jax.checkpoint(apply_fn)
        self.apply_fn = apply_fn

        # ---- parameter sharding specs (TP dist_spec + ZeRO stage 3) -------
        named = dict(layer.named_parameters())
        pspecs: Dict[str, P] = {}
        for k, arr in params.items():
            base = _filter_spec(
                getattr(named.get(k), "dist_spec", None) or P(),
                arr.ndim, sizes)
            if sharding_stage >= 3:
                base = _with_sharding_axis(base, "sharding", arr.shape, sizes)
            pspecs[k] = base
        self.param_shardings = {k: NamedSharding(mesh, s)
                                for k, s in pspecs.items()}

        # ---- optimizer slot specs (ZeRO stages 1/2) -----------------------
        self.opt_shardings = _slot_shardings(
            optimizer, params, pspecs, sizes, sharding_stage, mesh)

        # ---- place initial state ------------------------------------------
        self.params = {k: jax.device_put(v, self.param_shardings[k])
                       for k, v in params.items()}
        self.buffers = {k: jax.device_put(v, NamedSharding(mesh, P()))
                        for k, v in buffers.items()}
        self.opt_state = jax.jit(
            optimizer.init_state_tree,
            out_shardings=self.opt_shardings)(self.params)

        # ---- batch specs ---------------------------------------------------
        data_axes = _data_axes_of(sizes)
        sp_on = sizes.get("sp", 1) > 1
        self._default_batch_spec = lambda ndim: P(
            *((data_axes,) + (("sp",) if (sp_on and ndim >= 2) else ())
              + (None,) * max(0, ndim - 2)))
        self.batch_specs = batch_specs

        self._health_probe, self._health_interval = _build_health_probe(
            self.params, health)
        self.last_health = None
        health_probe = self._health_probe

        loss_fn_ = loss_fn
        n_micro = self.accumulate_steps
        fp16 = amp_enabled and amp_dtype == jnp.float16
        sc = _scaler_config(strategy)
        self.scaler_state = {
            "scale": jnp.asarray(sc["init_scale"] if fp16 else 1.0,
                                 jnp.float32),
            "good": jnp.asarray(0, jnp.int32)}
        self._fp16 = fp16

        def one_micro(p, buf, rng, micro, loss_mult):
            def loss_of(pp):
                out, new_buf = apply_fn(pp, buf, rng, *micro[:-1])
                loss = loss_fn_(jax.tree_util.tree_map(Tensor, out),
                                Tensor(micro[-1]))
                loss = loss.data if isinstance(loss, Tensor) else loss
                # fp16: backprop the SCALED loss; primal aux keeps the raw
                return (loss.astype(jnp.float32) * loss_mult,
                        (loss, new_buf))
            (_, (loss, new_buf)), grads = jax.value_and_grad(
                loss_of, has_aux=True)(p)
            return loss, grads, new_buf

        def step(params, buffers, opt_state, scaler_state, rng, lr, t,
                 *batch):
            compute_params = params
            if amp_enabled:
                compute_params = {
                    k: (v.astype(amp_dtype)
                        if jnp.issubdtype(v.dtype, jnp.floating) else v)
                    for k, v in params.items()}
            loss_mult = scaler_state["scale"] if fp16 else jnp.asarray(
                1.0, jnp.float32)
            if n_micro == 1:
                loss, grads, new_buf = one_micro(compute_params, buffers,
                                                 rng, batch, loss_mult)
            else:
                stacked = jax.tree_util.tree_map(
                    lambda a: a.reshape((n_micro, a.shape[0] // n_micro)
                                        + a.shape[1:]), tuple(batch))
                rngs = jax.random.split(rng, n_micro)

                def body(carry, xs):
                    acc, buf = carry
                    r, micro = xs
                    loss, grads, new_buf = one_micro(compute_params, buf,
                                                     r, micro, loss_mult)
                    acc = jax.tree_util.tree_map(jnp.add, acc, grads)
                    return (acc, new_buf), loss

                zero = jax.tree_util.tree_map(
                    lambda a: jnp.zeros(a.shape, jnp.float32),
                    compute_params)
                (grads, new_buf), losses = jax.lax.scan(
                    body, (zero, buffers), (rngs, stacked))
                grads = jax.tree_util.tree_map(
                    lambda g: g / n_micro, grads)
                loss = losses.mean()
            grads = jax.tree_util.tree_map(
                lambda g, p: g.astype(jnp.float32), grads, compute_params)
            if fp16:
                new_params, new_opt, new_scaler = _apply_scaled_update(
                    optimizer, params, grads, opt_state, lr, t,
                    scaler_state, sc)
            else:
                new_params, new_opt = optimizer.apply_fn(
                    params, grads, opt_state, lr=lr, t=t)
                new_scaler = scaler_state
            if health_probe is None:
                return loss, new_params, new_buf, new_opt, new_scaler
            hvec = health_probe.stats_vec(
                loss, _health_grads(grads, scaler_state, fp16), params,
                new_params)
            return loss, new_params, new_buf, new_opt, new_scaler, hvec

        donate_args = (0, 2) if donate else ()
        self._step = jax.jit(step, donate_argnums=donate_args)

    # -- data placement ------------------------------------------------------
    def shard_batch(self, *batch):
        out = []
        for i, t in enumerate(batch):
            arr = t.data if isinstance(t, Tensor) else jnp.asarray(t)
            spec = (self.batch_specs[i] if self.batch_specs is not None
                    else self._default_batch_spec(arr.ndim))
            out.append(jax.device_put(arr, NamedSharding(self.mesh, spec)))
        return out

    def __call__(self, *batch):
        self._t += 1
        rng = random_mod.default_generator().split()
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        arrs = self.shard_batch(*batch)
        with self.mesh:
            out = self._step(
                self.params, self.buffers, self.opt_state,
                self.scaler_state, rng, lr, self._t, *arrs)
        (loss, self.params, self.buffers, self.opt_state,
         self.scaler_state) = out[:5]
        if self._health_probe is not None \
                and self._t % self._health_interval == 0:
            _note_health(self, out[5])
        return Tensor(loss)

    def sync_to_layer(self):
        named = dict(self.layer.named_parameters())
        for k, v in self.params.items():
            named[k].data = v
        named_b = dict(self.layer.named_buffers())
        for k, v in self.buffers.items():
            if k in named_b:
                named_b[k].data = v
