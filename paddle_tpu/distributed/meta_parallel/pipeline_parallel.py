"""SPMD pipeline parallelism — the TPU-native 1F1B.

Reference: `PipelineParallel.forward_backward_pipeline`
(`/root/reference/python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py:80`)
— a host-driven 1F1B schedule (startup / steady / cooldown) moving
micro-batch activations between ranks with batched NCCL isend/irecv
(`pp_utils/p2p_communication.py:216`) — and the static-graph equivalents
(`SectionWorker` `framework/device_worker.h:615`, fleet_executor
interceptors).

None of that actor machinery translates to XLA's static schedule. Instead
the whole pipeline is ONE compiled program over a mesh with a `pp` axis:

* per-layer block params are stacked to `[S, L/S, ...]`, dim 0 sharded over
  `pp` — each stage's chip holds only its own layers (same memory split as
  the reference's per-rank partition);
* a stage buffer `buf[S, B, T, D]` (dim 0 on `pp`) holds each stage's
  in-flight micro-batch; one schedule tick = `vmap` of the stage body over
  dim 0 (XLA partitions it so every stage computes concurrently) followed by
  `jnp.roll(out, 1, axis=0)` which GSPMD lowers to a collective-permute over
  ICI — exactly the reference's send_forward/recv_forward pair;
* micro-batch `t` is injected at stage 0 each tick; when stage S-1 emits
  a finished micro-batch its loss is computed IN the same tick (nothing is
  accumulated across ticks — 1F1B's bounded in-flight memory); after
  `M + S - 1` ticks all M are done (bubble (S-1)/(M+S-1));
* `jax.grad` through the schedule yields the reverse pipeline (backward
  collective-permutes run in the opposite direction) with gradient
  accumulation across micro-batches falling out of the scan — no explicit
  cooldown phase, no `allreduce_shared_weight_gradients` (tied weights are
  literally the same array in the jaxpr).

Composes with the other axes: dp/sp shard the batch dims of `buf`, TP specs
on the stacked params keep their `mp` axes (shifted right by the two stage
dims), ZeRO shards optimizer slots over `sharding`.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ...framework import random as random_mod
from ...framework import tape as tape_mod
from ...framework.tensor import Tensor
from ...nn.layer import Layer
from ..topology import HybridCommunicateGroup, get_hybrid_communicate_group
from .engine import (_apply_scaled_update, _axis_sizes, _data_axes_of,
                     _filter_spec, _parse_strategy, _scaler_config,
                     _slot_shardings)
from .pp_layers import PipelineLayer


def _stage_dist_spec(base: P, sizes) -> P:
    """Shift a per-layer TP spec right past the [stage, layer] dims."""
    parts = ["pp", None] + [a if (a in sizes and sizes[a] > 1) else None
                            for a in tuple(base)]
    return P(*parts)


def _uniform_counts(n: int, stages: int) -> List[int]:
    """n layers over `stages` parts, remainder to the earlier stages
    (reference `SegmentLayers.uniform`, pp_layers.py:63)."""
    per, rem = divmod(n, stages)
    return [per + (1 if s < rem else 0) for s in range(stages)]


class _BlockRun:
    """The homogeneous scanned region: one block apply + stacked params.

    Uneven segmentation (reference `SegmentLayers` cost/uniform splits,
    pp_layers.py:63,282): `counts[s]` layers land on stage s; stacking pads
    every stage to max(counts) and `active` [S, Lp] masks the pad slots out
    of the scan — a padded slot's apply result is dropped by a select, so
    its (copied) parameters receive zero gradient.
    """

    def __init__(self, model: Layer, block_layers: Sequence[Layer],
                 names: Sequence[str], num_stages: int,
                 counts: Optional[Sequence[int]] = None):
        from ...jit import functionalize
        self.num_layers = len(block_layers)
        self.num_stages = num_stages
        if counts is None:
            counts = _uniform_counts(self.num_layers, num_stages)
        counts = list(counts)
        assert len(counts) == num_stages and sum(counts) == self.num_layers, (
            f"stage counts {counts} do not cover {self.num_layers} layers "
            f"over {num_stages} stages")
        assert min(counts) >= 1, (
            f"every pipeline stage needs at least one layer, got {counts}")
        self.counts = counts
        self.offsets = [sum(counts[:s]) for s in range(num_stages)]
        self.layers_per_stage = Lp = max(counts)
        self.prefixes = list(names)  # full-model param-name prefix per layer
        b0 = block_layers[0]
        self.apply0, params0, buffers0 = functionalize(b0)
        if buffers0:
            raise ValueError(
                "pipeline-scanned blocks must be buffer-free: found "
                f"buffers {list(buffers0)}. BatchNorm-family layers keep "
                "running stats that cannot be threaded through the compiled "
                "1F1B schedule — use LayerNorm/GroupNorm inside pipeline "
                "stages (reference PP shares this shape: SectionWorker "
                "replays a per-stage program with no cross-stage state)")
        self.keys = list(params0.keys())
        per_layer = []
        for lyr in block_layers:
            p = {k: v.data for k, v in lyr.named_parameters()}
            per_layer.append([p[k] for k in self.keys])
        S = num_stages
        # slot (s, j) -> layer offsets[s]+j, padded slots reuse the stage's
        # last layer (values are irrelevant: `active` masks them out)
        slot_idx = [[self.offsets[s] + min(j, counts[s] - 1)
                     for j in range(Lp)] for s in range(S)]
        self.stacked = {
            k: jnp.stack([
                jnp.stack([per_layer[slot_idx[s][j]][kj] for j in range(Lp)])
                for s in range(S)])
            for kj, k in enumerate(self.keys)}
        self.active = jnp.asarray(
            [[j < counts[s] for j in range(Lp)] for s in range(S)])
        # TP specs from layer 0's parameters, shifted past [S, Lp]
        named0 = dict(b0.named_parameters())
        self.base_specs = {k: getattr(named0.get(k), "dist_spec", None) or P()
                           for k in self.keys}

    def stage_apply(self, stage_params, x, rng, active):
        """Apply this stage's layers sequentially (lax.scan); `active` [Lp]
        masks padded slots (their apply is computed and dropped — the
        pipeline schedule is shape-static, so every stage runs Lp ticks)."""
        def body(h, xs):
            layer_params, r, a = xs
            out, _ = self.apply0(layer_params, {}, r, h)
            return jnp.where(a, out, h), None
        rngs = jax.random.split(rng, self.layers_per_stage)
        out, _ = jax.lax.scan(body, x, (stage_params, rngs, active))
        return out

    def unstack_into(self, stacked: Dict[str, jnp.ndarray],
                     named_full: Dict[str, "object"]):
        """Write stacked [S, Lp, ...] values back into eager per-layer
        params (pad slots skipped)."""
        for k, arr in stacked.items():
            for s in range(self.num_stages):
                for j in range(self.counts[s]):
                    i = self.offsets[s] + j
                    pref = self.prefixes[i]
                    full = f"{pref}.{k}" if pref else k
                    if full in named_full:
                        named_full[full].data = arr[s, j]


def _gpt_like_parts(model: Layer):
    """(pre_fn, blocks, block_prefixes, post_fn) for models exposing the
    `pipeline_pre/blocks/pipeline_post` protocol (models/gpt.py) or a
    PipelineLayer's detected scan region."""
    if isinstance(model, PipelineLayer):
        start, stop = model.scan_region()
        layers = list(model.run_function)
        assert stop > start, "PipelineLayer has no homogeneous scan region"

        def pre(m, *inputs):
            x = inputs[0] if len(inputs) == 1 else inputs
            for lyr in layers[:start]:
                x = lyr(x)
            return x

        def post(m, x):
            for lyr in layers[stop:]:
                x = lyr(x)
            return x
        prefixes = [f"run_function.{i}" for i in range(start, stop)]
        return pre, layers[start:stop], prefixes, post
    if hasattr(model, "pipeline_pre") and hasattr(model, "pipeline_post"):
        blocks = list(model.blocks)
        prefixes = [f"blocks.{i}" for i in range(len(blocks))]
        return (type(model).pipeline_pre, blocks, prefixes,
                type(model).pipeline_post)
    raise TypeError(
        f"{type(model).__name__} is not pipeline-able: pass a PipelineLayer "
        "or implement pipeline_pre(inputs)->hidden / blocks / "
        "pipeline_post(hidden)->out")


class PipelineParallelTrainStep:
    """Compile fwd+bwd+optimizer of a pipelined model into one executable.

    The `HybridParallelTrainStep` counterpart when the mesh has a `pp` axis;
    handles dp / sp / mp / ZeRO-1 alongside the pipeline.
    """

    def __init__(self, model: Layer, loss_fn: Callable, optimizer,
                 hcg: Optional[HybridCommunicateGroup] = None,
                 strategy=None, num_micro: Optional[int] = None,
                 donate: bool = True, health=None):
        from ...jit import functionalize
        self.layer = model
        self.optimizer = optimizer
        self.hcg = hcg or get_hybrid_communicate_group()
        assert self.hcg is not None, "fleet.init(...) first"
        mesh = self.hcg.mesh
        self.mesh = mesh
        sizes = _axis_sizes(mesh)
        S = sizes.get("pp", 1)
        assert S > 1, "mesh has no pp axis; use HybridParallelTrainStep"
        self._t = 0

        (amp_enabled, amp_dtype, recompute, sharding_stage,
         _accum) = _parse_strategy(strategy, sizes)
        if num_micro is None:
            num_micro = 1
            if strategy is not None and strategy.pipeline:
                num_micro = int(strategy.pipeline_configs.get(
                    "accumulate_steps", 1))
            num_micro = max(num_micro, S)
        self.num_micro = M = num_micro

        if isinstance(model, PipelineLayer) and model.num_stages != S:
            raise ValueError(
                f"PipelineLayer was built for {model.num_stages} stages but "
                f"the mesh pp axis has {S}; make them agree")
        pre_fn, blocks, prefixes, post_fn = _gpt_like_parts(model)
        counts = None
        if isinstance(model, PipelineLayer):
            # honor the model's segmentation (seg_method uniform/"layer:X")
            # restricted to the scanned region; pre/post layers are
            # replicated and don't consume stage slots
            start, stop = model.scan_region()
            bounds = model.segment()
            counts = [max(0, min(bounds[s + 1], stop) - max(bounds[s], start))
                      for s in range(S)]
            assert min(counts) >= 1, (
                f"seg_method={model.seg_method!r} gives stage block counts "
                f"{counts}; every stage needs >= 1 scanned layer")
        self.run = _BlockRun(model, blocks, prefixes, S, counts=counts)

        # ---- non-block ("edge") params: embeddings, final LN, head --------
        _, all_params, buffers = functionalize(model)
        if buffers:
            raise ValueError(
                "pipelined models must be buffer-free: found buffers "
                f"{list(buffers)}. BatchNorm-family running stats cannot be "
                "threaded through the compiled 1F1B schedule; use "
                "LayerNorm/GroupNorm (or FrozenBatchNorm) in pipelined "
                "models")
        block_full = {f"{pref}.{k}" for pref in prefixes
                      for k in self.run.keys}
        edge_params = {k: v for k, v in all_params.items()
                       if k not in block_full}
        named = dict(model.named_parameters())

        edge_specs = {
            k: _filter_spec(getattr(named.get(k), "dist_spec", None) or P(),
                            arr.ndim, sizes)
            for k, arr in edge_params.items()}
        blk_specs = {k: _stage_dist_spec(self.run.base_specs[k], sizes)
                     for k in self.run.keys}

        def flat(tree):
            return {**{f"edge.{k}": v for k, v in tree["edge"].items()},
                    **{f"blocks.{k}": v for k, v in tree["blocks"].items()}}

        def unflat(d):
            return {"edge": {k[5:]: v for k, v in d.items()
                             if k.startswith("edge.")},
                    "blocks": {k[7:]: v for k, v in d.items()
                               if k.startswith("blocks.")}}
        self._flat, self._unflat = flat, unflat

        self.param_shardings = {
            "edge": {k: NamedSharding(mesh, s) for k, s in edge_specs.items()},
            "blocks": {k: NamedSharding(mesh, s)
                       for k, s in blk_specs.items()}}
        params_tree = {
            "edge": {k: jax.device_put(v, self.param_shardings["edge"][k])
                     for k, v in edge_params.items()},
            "blocks": {k: jax.device_put(v, self.param_shardings["blocks"][k])
                       for k, v in self.run.stacked.items()}}
        self.buffers = {k: jax.device_put(v, NamedSharding(mesh, P()))
                        for k, v in buffers.items()}

        # ---- optimizer slots (ZeRO-1 over `sharding`) ---------------------
        flat_params = flat(params_tree)
        flat_specs = {**{f"edge.{k}": s for k, s in edge_specs.items()},
                      **{f"blocks.{k}": s for k, s in blk_specs.items()}}
        self.opt_shardings = _slot_shardings(
            optimizer, flat_params, flat_specs, sizes, sharding_stage, mesh)
        self.opt_state = jax.jit(optimizer.init_state_tree,
                                 out_shardings=self.opt_shardings)(flat_params)

        # ---- batch placement ----------------------------------------------
        data_axes = _data_axes_of(sizes)
        sp_on = sizes.get("sp", 1) > 1
        self._micro_spec = lambda ndim: P(
            *((None, data_axes) + (("sp",) if (sp_on and ndim >= 3) else ())
              + (None,) * max(0, ndim - 3)))
        buf_data_spec = lambda ndim: P(
            *(("pp", data_axes) + (("sp",) if (sp_on and ndim >= 3) else ())
              + (None,) * max(0, ndim - 3)))

        loss_fn_ = loss_fn
        run = self.run
        # remat each stage tick: only stage-boundary activations live across
        # the schedule (reference RecomputeFunction, at stage-tick
        # granularity; `strategy.recompute` additionally remats inside the
        # per-layer scan via the same policy so it is subsumed here)
        stage_apply = jax.checkpoint(run.stage_apply)
        del recompute

        def pre_apply(params_tree, bufs, rng, inputs):
            tin = jax.tree_util.tree_map(Tensor, inputs)
            with tape_mod.no_grad(), \
                    _model_state(model, params_tree, bufs, run, prefixes):
                with random_mod.rng_scope(rng):
                    out = pre_fn(model, *tin)
            return out.data if isinstance(out, Tensor) else out

        def post_loss(params_tree, bufs, rng, h, labels):
            with tape_mod.no_grad(), \
                    _model_state(model, params_tree, bufs, run, prefixes):
                with random_mod.rng_scope(rng):
                    out = post_fn(model, Tensor(h))
                    loss = loss_fn_(out, Tensor(labels))
            return loss.data if isinstance(loss, Tensor) else loss

        post_loss_ckpt = jax.checkpoint(post_loss)

        def pipeline_loss(params, buffers_, rng, *batch):
            """params = {'edge':…, 'blocks':…}; batch = (*inputs, labels),
            every array micro-batched with leading dim M.

            1F1B memory behavior: each micro-batch's loss is computed INSIDE
            the tick in which stage S-1 emits it — nothing is collected
            across ticks, so live activations are the stage buffer
            [S, B, T, D] (dim 0 on `pp`) plus the per-tick boundary
            activations the scan saves for backward (one [B,T,D] per stage
            per tick under remat). The round-1 design instead accumulated
            all M outputs into a pp-replicated [M, B, T, D] buffer and ran a
            separate loss phase — an extra M·B·T·D live per chip.
            """
            inputs, labels = batch[:-1], batch[-1]
            r_pre, r_pipe, r_post = jax.random.split(rng, 3)
            # embeddings for all micro-batches at once (single big gather)
            embed = jax.vmap(
                lambda mb_rng, *mb: pre_apply(params, buffers_, mb_rng, mb)
            )(jax.random.split(r_pre, M), *inputs)
            D_tail = embed.shape[2:]
            B = embed.shape[1]
            buf = jnp.zeros((S, B) + D_tail, embed.dtype)
            stage_ids = jnp.arange(S)

            def tick(carry, t):
                buf, total = carry
                buf = buf.at[0].set(embed[jnp.minimum(t, M - 1)])
                buf = jax.lax.with_sharding_constraint(
                    buf, buf_data_spec(buf.ndim))
                rngs = jax.vmap(
                    lambda s: jax.random.fold_in(
                        jax.random.fold_in(r_pipe, t), s))(stage_ids)
                out = jax.vmap(stage_apply)(params["blocks"], buf, rngs,
                                            run.active)
                out = jax.lax.with_sharding_constraint(
                    out, buf_data_spec(out.ndim))
                # drain: micro-batch m finishes when stage S-1 emits it
                m = jnp.clip(t - (S - 1), 0, M - 1)
                y = jax.lax.dynamic_index_in_dim(labels, m, keepdims=False)
                l = post_loss_ckpt(params, buffers_,
                                   jax.random.fold_in(r_post, m),
                                   out[S - 1], y)
                # warmup ticks (t < S-1) run the head on pipeline-bubble
                # garbage; the select drops both their value and gradient
                total = total + jnp.where(t >= S - 1, l, 0.0)
                buf = jnp.roll(out, 1, axis=0)  # -> collective-permute on pp
                return (buf, total), None

            (_, total), _ = jax.lax.scan(
                tick, (buf, jnp.asarray(0.0, jnp.float32)),
                jnp.arange(M + S - 1))
            return total / M

        fp16 = amp_enabled and amp_dtype == jnp.float16
        sc = _scaler_config(strategy)
        self.scaler_state = {
            "scale": jnp.asarray(sc["init_scale"] if fp16 else 1.0,
                                 jnp.float32),
            "good": jnp.asarray(0, jnp.int32)}

        from .engine import _build_health_probe
        self._health_probe, self._health_interval = _build_health_probe(
            flat_params, health)
        self.last_health = None
        health_probe = self._health_probe

        def step(flat_params, buffers_, opt_state, scaler_state, rng, lr, t,
                 *batch):
            params = unflat(flat_params)
            compute = jax.tree_util.tree_map(
                lambda v: (v.astype(amp_dtype)
                           if amp_enabled and jnp.issubdtype(
                               v.dtype, jnp.floating) else v), params)
            loss_mult = scaler_state["scale"] if fp16 else jnp.asarray(
                1.0, jnp.float32)
            loss, grads = jax.value_and_grad(
                lambda p: pipeline_loss(p, buffers_, rng, *batch).astype(
                    jnp.float32) * loss_mult)(compute)
            loss = loss / loss_mult  # report the UNscaled loss
            fgrads = jax.tree_util.tree_map(
                lambda g: g.astype(jnp.float32), flat(grads))
            if fp16:
                new_params, new_opt, new_scaler = _apply_scaled_update(
                    optimizer, flat_params, fgrads, opt_state, lr, t,
                    scaler_state, sc)
            else:
                new_params, new_opt = optimizer.apply_fn(
                    flat_params, fgrads, opt_state, lr=lr, t=t)
                new_scaler = scaler_state
            if health_probe is None:
                return loss, new_params, new_opt, new_scaler
            from .engine import _health_grads
            hvec = health_probe.stats_vec(
                loss, _health_grads(fgrads, scaler_state, fp16),
                flat_params, new_params)
            return loss, new_params, new_opt, new_scaler, hvec

        donate_args = (0, 2) if donate else ()
        self._step = jax.jit(step, donate_argnums=donate_args)
        self._flat_params = flat_params

    # -- data: split the global batch into micro-batches --------------------
    def shard_batch(self, *batch):
        out = []
        M = self.num_micro
        for t in batch:
            arr = t.data if isinstance(t, Tensor) else jnp.asarray(t)
            assert arr.shape[0] % M == 0, (
                f"batch dim {arr.shape[0]} not divisible by "
                f"{M} micro-batches")
            arr = arr.reshape((M, arr.shape[0] // M) + arr.shape[1:])
            out.append(jax.device_put(
                arr, NamedSharding(self.mesh, self._micro_spec(arr.ndim))))
        return out

    def __call__(self, *batch):
        self._t += 1
        rng = random_mod.default_generator().split()
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        arrs = self.shard_batch(*batch)
        with self.mesh:
            out = self._step(
                self._flat_params, self.buffers, self.opt_state,
                self.scaler_state, rng, lr, self._t, *arrs)
        (loss, self._flat_params, self.opt_state,
         self.scaler_state) = out[:4]
        if self._health_probe is not None \
                and self._t % self._health_interval == 0:
            from .engine import _note_health
            _note_health(self, out[4])
        return Tensor(loss)

    @property
    def params(self):
        return self._unflat(self._flat_params)

    @params.setter
    def params(self, tree):
        self._flat_params = self._flat(tree)

    def sync_to_layer(self):
        named = dict(self.layer.named_parameters())
        tree = self.params
        for k, v in tree["edge"].items():
            if k in named:
                named[k].data = v
        self.run.unstack_into(tree["blocks"], named)


class _model_state:
    """Bind edge params + one reference block's params into the eager model
    so pre/post functions (which may touch tied block weights) trace against
    the live traced values."""

    def __init__(self, model, params_tree, buffers, run, prefixes):
        from ...jit import _swapped_state
        merged = dict(params_tree["edge"])
        # layer i's params from the stacked tree (used by tied weights only;
        # cheap slices, DCE'd when unused); slot (s, j) holds layer
        # offsets[s]+j — pad slots are skipped
        for k in run.keys:
            arr = params_tree["blocks"][k]
            for s in range(run.num_stages):
                for j in range(run.counts[s]):
                    pref = prefixes[run.offsets[s] + j]
                    merged[f"{pref}.{k}"] = arr[s, j]
        self._cm = _swapped_state(model, merged, dict(buffers))

    def __enter__(self):
        return self._cm.__enter__()

    def __exit__(self, *exc):
        return self._cm.__exit__(*exc)


class PipelineParallel(Layer):
    """Reference-parity wrapper (`meta_parallel/pipeline_parallel.py:30`):
    `model = PipelineParallel(pipeline_layer, hcg, strategy)`, then
    `loss = model.train_batch([data, labels], optimizer, lr_scheduler)`."""

    def __init__(self, layers, hcg=None, strategy=None, **kw):
        super().__init__()
        self._layers = layers
        self._hcg = hcg or get_hybrid_communicate_group()
        self._strategy = strategy
        self._train_step = None

    def forward(self, *args, **kw):
        return self._layers(*args, **kw)

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        # fp16 dynamic loss scaling runs INSIDE the compiled step (the
        # engine carries scale/good-steps as arrays) when the strategy sets
        # amp dtype='float16'; a user-passed GradScaler is therefore
        # redundant here and its state is left untouched. bf16 needs no
        # scaling at all (fp32 exponent range).
        if (self._train_step is not None
                and self._train_step.optimizer is not optimizer):
            raise ValueError(
                "train_batch was compiled against a different optimizer; "
                "build a new PipelineParallel to swap optimizers")
        if self._train_step is None:
            loss_fn = getattr(self._layers, "_loss_fn", None)
            if loss_fn is None:
                from ...nn import functional as F
                loss_fn = F.cross_entropy
            self._train_step = PipelineParallelTrainStep(
                self._layers, loss_fn, optimizer, hcg=self._hcg,
                strategy=self._strategy)
        inputs = data if isinstance(data, (list, tuple)) else [data]
        loss = self._train_step(*inputs)
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss
