"""Pipeline model description — LayerDesc / SharedLayerDesc / PipelineLayer.

Reference: `fleet/meta_parallel/parallel_layers/pp_layers.py:31,49,132`
(`/root/reference/python/paddle/distributed/fleet/meta_parallel/parallel_layers/pp_layers.py`)
where `PipelineLayer` cuts a flat `LayerDesc` list into per-rank stages and
`PipelineParallel` moves activations with NCCL p2p. TPU translation: the cut
is a *sharding*, not a process split — `PipelineLayer` here builds the whole
model in every process (SPMD), `PipelineParallelTrainStep` stacks the
homogeneous middle run of layers into one leading `num_layers` dim sharded
over the `pp` mesh axis, and the 1F1B schedule becomes a rotation of a
pp-sharded stage buffer (see pipeline_parallel.py).

Eager `forward` runs the layers sequentially, so a PipelineLayer is also a
correct single-device model (debug parity with reference dygraph).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence

from ...nn.layer import Layer


class LayerDesc:
    """Deferred layer construction (reference pp_layers.py:31)."""

    def __init__(self, layer_cls, *args, **kwargs):
        if not issubclass(layer_cls, Layer):
            raise TypeError(f"LayerDesc needs a Layer subclass, got {layer_cls}")
        self.layer_cls = layer_cls
        self.args = args
        self.kwargs = kwargs

    def build_layer(self) -> Layer:
        return self.layer_cls(*self.args, **self.kwargs)

    def __repr__(self):
        return f"LayerDesc({self.layer_cls.__name__})"


class SharedLayerDesc(LayerDesc):
    """Weight-tied layer (reference pp_layers.py:49 — e.g. input/output
    embeddings). All descs with the same `key` share ONE layer instance; in
    the single-program SPMD pipeline tying is free (same array, grads sum
    through the jaxpr) — no `allreduce_shared_weight_gradients` step needed.
    `forward_func(layer, x)` customizes the reuse call (e.g. logits =
    x @ embedding.weight.T for the output head)."""

    def __init__(self, key, layer_cls, forward_func: Optional[Callable] = None,
                 shared_weight_attr: str = "weight", *args, **kwargs):
        super().__init__(layer_cls, *args, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class _SharedCall(Layer):
    """Wrapper calling a shared instance without re-registering its params
    (held via object.__setattr__ so named_parameters sees them once, on the
    PipelineLayer-owned original)."""

    def __init__(self, shared: Layer, forward_func: Optional[Callable]):
        super().__init__()
        object.__setattr__(self, "_shared_ref", shared)
        object.__setattr__(self, "_forward_func", forward_func)

    def forward(self, *args, **kw):
        if self._forward_func is not None:
            return self._forward_func(self._shared_ref, *args, **kw)
        return self._shared_ref(*args, **kw)


def _param_signature(layer: Layer):
    """Structural signature: sorted (name, shape, dtype) of the sub-tree."""
    return tuple(sorted((k, tuple(p.shape), str(p.dtype))
                        for k, p in layer.named_parameters()))


class PipelineLayer(Layer):
    """Flat layer list + stage segmentation (reference pp_layers.py:132).

    Args mirror the reference: `layers` is a list of Layer / LayerDesc /
    SharedLayerDesc / plain callables; `num_stages` or `topology` gives the
    pp degree; `seg_method` "uniform" or "layer:ClassName" (cut before each
    instance of ClassName).
    """

    def __init__(self, layers: Sequence[Any], num_stages: Optional[int] = None,
                 topology=None, seg_method: str = "uniform",
                 recompute_interval: int = 0, loss_fn=None, **kw):
        super().__init__()
        self._num_stages = num_stages or (
            topology.get_dim("pipe") if topology is not None else 1)
        self.seg_method = seg_method
        self.recompute_interval = recompute_interval
        self._loss_fn = loss_fn
        self._shared: Dict[str, Layer] = {}
        built: List[Layer] = []
        for d in layers:
            if isinstance(d, SharedLayerDesc):
                if d.layer_name not in self._shared:
                    shared = d.build_layer()
                    self._shared[d.layer_name] = shared
                    # register owned instance so its params are tracked once
                    setattr(self, f"shared_{d.layer_name}", shared)
                built.append(_SharedCall(self._shared[d.layer_name],
                                         d.forward_func))
            elif isinstance(d, LayerDesc):
                built.append(d.build_layer())
            elif isinstance(d, Layer):
                built.append(d)
            elif callable(d):
                built.append(_FnLayer(d))
            else:
                raise TypeError(f"bad pipeline item {d!r}")
        from ...nn.layers_common import LayerList
        self.run_function = LayerList(built)

    # -- eager path ---------------------------------------------------------
    def forward(self, x):
        for i, lyr in enumerate(self.run_function):
            x = lyr(x)
        return x

    # -- segmentation -------------------------------------------------------
    @property
    def num_stages(self) -> int:
        return self._num_stages

    def segment(self) -> List[int]:
        """Return stage boundary indices [b0..bS] over the layer list."""
        n = len(self.run_function)
        S = self._num_stages
        if self.seg_method.startswith("layer:"):
            cls_name = self.seg_method.split(":", 1)[1]
            cuts = [i for i, l in enumerate(self.run_function)
                    if type(l).__name__ == cls_name]
            # uniform split of the cut layers across stages; leading
            # non-cut layers join stage 0, trailing join the last stage
            assert len(cuts) >= S, \
                f"{len(cuts)} x {cls_name} layers < {S} stages"
            per = len(cuts) // S
            bounds = [0]
            for s in range(1, S):
                bounds.append(cuts[s * per])
            bounds.append(n)
            return bounds
        # uniform
        per, rem = divmod(n, S)
        bounds = [0]
        for s in range(S):
            bounds.append(bounds[-1] + per + (1 if s < rem else 0))
        return bounds

    def get_stage_of(self, layer_idx: int) -> int:
        b = self.segment()
        for s in range(self._num_stages):
            if b[s] <= layer_idx < b[s + 1]:
                return s
        raise IndexError(layer_idx)

    # -- homogeneous-run detection for the SPMD stacked pipeline ------------
    def scan_region(self):
        """Longest run of structurally identical consecutive layers.

        Returns (start, stop): layers[start:stop] all share one param-tree
        signature. Layers before the run form the replicated pre-part,
        after it the post-part. `stop-start` need NOT divide num_stages:
        the compiled pipeline pads stages to max(counts) with masked slots
        (reference supports uneven SegmentLayers splits, pp_layers.py:63)."""
        layers = list(self.run_function)
        sigs = [_param_signature(l) for l in layers]
        best = (0, 0)
        i = 0
        while i < len(sigs):
            j = i + 1
            while j < len(sigs) and sigs[j] == sigs[i] and sigs[i]:
                j += 1
            if j - i > best[1] - best[0]:
                best = (i, j)
            i = max(j, i + 1)
        return best


class _FnLayer(Layer):
    def __init__(self, fn):
        super().__init__()
        object.__setattr__(self, "_fn", fn)

    def forward(self, *a, **k):
        return self._fn(*a, **k)
