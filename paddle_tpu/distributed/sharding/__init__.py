"""group_sharded_parallel — ZeRO stages as sharding placements.

Reference: `group_sharded_parallel`
(`/root/reference/python/paddle/distributed/sharding/group_sharded.py:31`)
wires up `ShardingStage2`/`ShardingStage3` wrappers + sharded optimizers
(`fleet/meta_parallel/sharding/sharding_stage2.py:43`, `sharding_stage3.py:50`)
that scatter params/grads/opt-state across ranks and broadcast/all-gather on
demand. TPU-native: a ZeRO stage is just a *placement* — optimizer slots
(stage >=1) and parameters (stage 3) are `device_put` with a NamedSharding
over the `sharding` mesh axis; XLA's weight-update sharding inserts the
reduce-scatter/all-gather the reference codes by hand. Eager ops run
distributed on the sharded arrays; the compiled engine
(`HybridParallelTrainStep`) reads the same strategy.

Levels (reference group_sharded.py): "os" = optimizer state (stage 1),
"os_g" = +gradients (stage 2; in SPMD grads are transient, so placement-wise
identical to stage 1), "p_g_os" = +parameters (stage 3).
"""
from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ...framework.tensor import Tensor
from ..meta_parallel.engine import _axis_sizes, _with_sharding_axis
from ..topology import (HybridCommunicateGroup,
                        get_hybrid_communicate_group,
                        set_hybrid_communicate_group)

__all__ = ["group_sharded_parallel", "save_group_sharded_model"]

_LEVELS = {"os": 1, "os_g": 2, "p_g_os": 3}


def _get_mesh(group=None):
    """(mesh, shard_axis). Honors an explicit `group`; otherwise requires —
    or creates, only when none exists — a global HCG with a sharding axis
    (never silently replaces a user topology)."""
    if group is not None and getattr(group, "mesh", None) is not None:
        axes = getattr(group, "_axis_names", None) or \
            getattr(group, "axis", None)
        axis = axes[0] if isinstance(axes, (tuple, list)) else (
            axes or "sharding")
        return group.mesh, axis
    hcg = get_hybrid_communicate_group()
    if hcg is None:
        hcg = HybridCommunicateGroup(
            dims={"sharding": len(jax.devices())})
        set_hybrid_communicate_group(hcg)
    elif _axis_sizes(hcg.mesh).get("sharding", 1) <= 1:
        raise ValueError(
            "group_sharded_parallel needs a 'sharding' axis in the active "
            f"topology (got {dict(_axis_sizes(hcg.mesh))}); include "
            "sharding_degree in fleet.init/HybridCommunicateGroup or pass "
            "group=")
    return hcg.mesh, "sharding"


def _shard_put(arr, mesh, sizes, axis="sharding"):
    spec = _with_sharding_axis(P(), axis, arr.shape, sizes)
    return jax.device_put(arr, NamedSharding(mesh, spec))


class _ShardedStepMixin:
    """Wraps Optimizer.step so slots created on the fly get sharded."""

    def __init__(self, opt, mesh, axis="sharding"):
        self._opt = opt
        self._mesh = mesh
        self._axis = axis
        self._sizes = _axis_sizes(mesh)
        self._sharded_ids = set()

    def __getattr__(self, name):
        return getattr(self._opt, name)

    def _shard_new_slots(self):
        for sid, slots in self._opt._slots.items():
            if sid in self._sharded_ids:
                continue
            self._opt._slots[sid] = {
                k: (_shard_put(v, self._mesh, self._sizes, self._axis)
                    if hasattr(v, "shape") and getattr(v, "ndim", 0) >= 1
                    else v)
                for k, v in slots.items()}
            self._sharded_ids.add(sid)

    def step(self):
        # materialize slots sharded before the update (incl. params whose
        # grads first appear on a later step)
        for p in self._opt._parameter_list:
            if (not p.stop_gradient and p.grad is not None
                    and id(p) not in self._opt._slots):
                self._opt._slots[id(p)] = self._opt._init_slots(p)
        self._shard_new_slots()
        self._opt.step()

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        loss.backward()
        self.step()
        self._opt.clear_grad()
        return [], []

    def state_dict(self):
        return self._opt.state_dict()

    def set_state_dict(self, sd):
        self._opt.set_state_dict(sd)
        self._sharded_ids.clear()
        self._shard_new_slots()


def group_sharded_parallel(model, optimizer, level: str, scaler=None,
                           group=None, offload: bool = False,
                           sync_buffers: bool = False,
                           buffer_max_size: int = 2 ** 23,
                           segment_size: int = 2 ** 20,
                           sync_comm: bool = False,
                           dp_group=None, **kwargs):
    """Reference group_sharded.py:31 parity: returns (model, optimizer,
    scaler) with ZeRO-style sharded placement over the `sharding` axis."""
    if level not in _LEVELS:
        raise ValueError(f"level must be one of {sorted(_LEVELS)}, "
                         f"got {level!r}")
    if offload:
        raise NotImplementedError(
            "CPU offload: use jax.checkpoint / host offload policies "
            "instead on TPU")
    stage = _LEVELS[level]
    mesh, axis = _get_mesh(group)
    sizes = _axis_sizes(mesh)

    if stage >= 3:
        for p in model.parameters():
            if p.data.ndim >= 1:
                p.data = _shard_put(p.data, mesh, sizes, axis)

    wrapped_opt = _ShardedStepMixin(optimizer, mesh, axis)
    return model, wrapped_opt, scaler


def save_group_sharded_model(model, output: str, optimizer=None):
    """Reference group_sharded.py:201: gather-and-save. SPMD arrays gather
    implicitly on host transfer, so this is plain save."""
    import os
    from ...framework.io import save
    assert not output.endswith((".pdmodel", ".pdparams")), \
        "output is a directory"
    os.makedirs(output, exist_ok=True)
    save(model.state_dict(), os.path.join(output, "model.pdparams"))
    if optimizer is not None:
        save(optimizer.state_dict(), os.path.join(output, "model.pdopt"))
