"""Process/cluster environment contract.

Mirrors the reference's trainer env-var contract set by
`paddle.distributed.launch` (`/root/reference/python/paddle/distributed/launch/`
and consumed by `ParallelEnv`,
`/root/reference/python/paddle/fluid/dygraph/parallel.py:96`):
``PADDLE_TRAINER_ID``, ``PADDLE_TRAINERS_NUM``, ``PADDLE_TRAINER_ENDPOINTS``,
``PADDLE_CURRENT_ENDPOINT``, ``PADDLE_DISTRI_BACKEND``.

On TPU one *process* drives many chips (single-controller JAX), so the
"trainer" here is a host process of a multi-host job: rank ==
``jax.process_index()`` once `jax.distributed` is live. Devices inside the
process are addressed by the mesh, not by rank.
"""
from __future__ import annotations

import os
from typing import List

import jax


def find_free_port() -> int:
    import socket
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


class ParallelEnv:
    """Cluster env view (reference `fluid/dygraph/parallel.py:96`)."""

    def __init__(self):
        self._rank = _env_int("PADDLE_TRAINER_ID", 0)
        self._world_size = _env_int("PADDLE_TRAINERS_NUM", 1)
        self._device_id = _env_int("FLAGS_selected_tpus",
                                   _env_int("FLAGS_selected_gpus", 0))
        self._current_endpoint = os.environ.get("PADDLE_CURRENT_ENDPOINT", "")
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        self._trainer_endpoints: List[str] = eps.split(",") if eps else []
        self._nrings = _env_int("FLAGS_nccl_nrings", 1)

    @property
    def rank(self) -> int:
        return self._rank

    @property
    def world_size(self) -> int:
        return self._world_size

    @property
    def device_id(self) -> int:
        return self._device_id

    @property
    def device_type(self) -> str:
        return jax.default_backend()

    @property
    def current_endpoint(self) -> str:
        return self._current_endpoint

    @property
    def trainer_endpoints(self) -> List[str]:
        return self._trainer_endpoints

    @property
    def nrings(self) -> int:
        return self._nrings

    # legacy aliases (reference keeps both spellings)
    local_rank = rank
    nranks = world_size
    dev_id = device_id


def get_cluster_env() -> ParallelEnv:
    return ParallelEnv()
