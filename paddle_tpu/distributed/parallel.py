"""Parallel environment bootstrap + dygraph DataParallel.

Reference: `init_parallel_env` (`/root/reference/python/paddle/distributed/
parallel.py:89` — TCPStore rendezvous + ProcessGroupNCCL init) and
`paddle.DataParallel` (`fluid/dygraph/parallel.py:411` — C++ Reducer with
bucketed overlap-allreduce, `imperative/reducer.h:126`).

TPU-native translation:
* rendezvous/uniqueId exchange -> `jax.distributed.initialize` (coordinator
  service); single-host jobs need nothing.
* per-rank eager + Reducer -> single-controller SPMD. Parameters are
  replicated over the `dp` mesh axis, batches sharded along it; XLA's
  partitioner emits the gradient all-reduce inside the backward, already
  overlapped (latency-hiding scheduler) — the entire Reducer (bucketing,
  ready-counting, comm-stream events) dissolves into the compiler.
"""
from __future__ import annotations

import os
from typing import Optional

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..framework.tensor import Tensor
from ..nn.layer import Layer
from .env import ParallelEnv
from . import collective as C
from .topology import get_hybrid_communicate_group

_parallel_env_initialized = False


def _multihost_env() -> Optional[dict]:
    eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
    n = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    if n > 1 and eps:
        master = eps.split(",")[0]
        return {"coordinator_address": master,
                "num_processes": n,
                "process_id": int(os.environ.get("PADDLE_TRAINER_ID", "0"))}
    return None


def _rendezvous_initialize(mh: dict):
    """Bring up the jax coordinator rendezvous under the PR-3 STORE retry
    policy (knobs: `PADDLE_TPU_STORE_{RETRIES,BACKOFF}`), with a named
    fault site for chaos tests. The reference retries rendezvous at the
    brpc/etcd layer; here a transient coordinator hiccup at job start
    costs a backoff, not the job (ROADMAP "retry-aware collective init")."""
    from ..fault import RetryPolicy
    from ..fault import site as _fault_site

    policy = RetryPolicy.from_env("STORE", max_attempts=3, base_delay=0.05,
                                  max_delay=1.0)
    # per-attempt thread-abandonment is wrong here for the same reason as
    # PSClient: an abandoned initialize keeps mutating global jax state
    if policy.attempt_timeout is not None:
        import copy
        policy = copy.copy(policy)
        policy.attempt_timeout = None

    def _do():
        _fault_site("parallel.init")
        jax.distributed.initialize(**mh)

    policy.call(_do, op="parallel.init")


def init_parallel_env() -> ParallelEnv:
    """Initialize the distributed context (idempotent)."""
    global _parallel_env_initialized
    env = ParallelEnv()
    if _parallel_env_initialized:
        return env
    mh = _multihost_env()
    if mh is not None and jax.process_count() == 1:
        _rendezvous_initialize(mh)
    C._get_default_group()
    _parallel_env_initialized = True
    return env


def get_rank(group=None) -> int:
    """Process rank (multi-host) — reference `paddle.distributed.get_rank`."""
    if group is not None:
        return C._resolve(group).rank
    try:
        return jax.process_index()
    except Exception:
        return ParallelEnv().rank


def get_world_size(group=None) -> int:
    if group is not None:
        return C._resolve(group).nranks
    env_n = ParallelEnv().world_size
    try:
        return max(jax.process_count(), env_n)
    except Exception:
        return env_n


def is_available() -> bool:
    return True


def parallel_device_count() -> int:
    return jax.device_count()


# ---------------------------------------------------------------------------
# data helpers
# ---------------------------------------------------------------------------
def _dp_mesh() -> Mesh:
    hcg = get_hybrid_communicate_group()
    if hcg is not None:
        return hcg.mesh
    return C._world_mesh()


def _dp_axis(mesh: Mesh) -> str:
    return "dp" if "dp" in mesh.axis_names else mesh.axis_names[0]


def shard_batch(t, mesh: Optional[Mesh] = None, axis: Optional[str] = None):
    """Shard a host batch along the data-parallel mesh axis (the TPU
    equivalent of each rank loading its own shard)."""
    mesh = mesh or _dp_mesh()
    axis = axis or _dp_axis(mesh)
    arr = t.data if isinstance(t, Tensor) else t
    spec = P(*((axis,) + (None,) * (arr.ndim - 1)))
    out = jax.device_put(arr, NamedSharding(mesh, spec))
    return Tensor(out, stop_gradient=getattr(t, "stop_gradient", True)) \
        if isinstance(t, Tensor) else out


def replicate(t, mesh: Optional[Mesh] = None):
    mesh = mesh or _dp_mesh()
    arr = t.data if isinstance(t, Tensor) else t
    out = jax.device_put(arr, NamedSharding(mesh, P()))
    if isinstance(t, Tensor):
        t.data = out
        return t
    return out


class DataParallel(Layer):
    """reference `paddle.DataParallel` (fluid/dygraph/parallel.py:411).

    Replicates parameters over the mesh; `shard_batch` the inputs and the
    backward's parameter gradients are automatically all-reduced by XLA's
    partitioner (Reducer equivalent). Loss scale / gradient division by
    nranks follows the reference: gradients are averaged over the data axis
    because each device computes mean-loss over its shard and XLA psums the
    contributions; with `comm_buffer_size` etc. accepted for parity.
    """

    def __init__(self, layers: Layer, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers
        self.group = group
        self.find_unused_parameters = find_unused_parameters
        mesh = C._resolve(group).mesh if group is not None else _dp_mesh()
        self._mesh = mesh
        for p in layers.parameters():
            p.data = jax.device_put(p.data, NamedSharding(mesh, P()))

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def scale_loss(self, loss):
        return loss  # grads are mean over dp shards already

    def apply_collective_grads(self):
        pass  # XLA partitioner already reduced them

    # delegation
    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._layers.set_state_dict(*a, **k)

    def parameters(self, *a, **k):
        return self._layers.parameters(*a, **k)

    def named_parameters(self, *a, **k):
        return self._layers.named_parameters(*a, **k)

    def __getattr__(self, name):
        # only reached when normal lookup fails: delegate to wrapped layer
        return getattr(object.__getattribute__(self, "_layers"), name)
