"""Hybrid-parallel topology — the device mesh and its named axes.

Reference: `CommunicateTopology` / `HybridCommunicateGroup`
(`/root/reference/python/paddle/distributed/fleet/base/topology.py:36,117`),
which carves the world into cartesian axes [data, pipe, sharding, model] and
creates a NCCL ring per axis slice. TPU-native translation: ONE
`jax.sharding.Mesh` whose named axes are the parallelism axes; "creating a
group" costs nothing (a `Group` is a mesh-axis view) and collectives become
XLA ops over ICI (`lax.psum(..., 'mp')` etc.) instead of `c_allreduce` with a
`ring_id`.

Axis canon (superset of the reference's four; `sep`/seq is our long-context
addition, SURVEY.md §5.7):

    dp        data parallel            (batch axis)
    pp        pipeline parallel        (stage axis)
    sharding  ZeRO parameter/optimizer sharding
    sp        sequence/context parallel (ring attention)
    mp        tensor/model parallel    (innermost => fastest ICI)
"""
from __future__ import annotations

import collections
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh

AXIS_CANON = ("dp", "pp", "sharding", "sp", "mp")

# reference axis-name spellings -> ours
_AXIS_ALIASES = {"data": "dp", "pipe": "pp", "model": "mp", "sep": "sp",
                 "sequence": "sp", "tensor": "mp", "expert": "ep"}


def canon_axis(name: str) -> str:
    return _AXIS_ALIASES.get(name, name)


class CommunicateTopology:
    """Cartesian rank topology (reference `topology.py:36`)."""

    def __init__(self,
                 hybrid_group_names: Sequence[str] = ("data", "pipe",
                                                      "sharding", "model"),
                 dims: Sequence[int] = (1, 1, 1, 1)):
        assert len(hybrid_group_names) == len(dims)
        self._parallel_names = [canon_axis(n) for n in hybrid_group_names]
        self._dims = list(int(d) for d in dims)
        self._world_size = int(np.prod(self._dims))
        ranks = np.arange(self._world_size).reshape(self._dims)
        self._rank_grid = ranks
        self._coord_of = {}
        for coord in np.ndindex(*self._dims):
            self._coord_of[int(ranks[coord])] = tuple(int(c) for c in coord)

    def get_hybrid_group_names(self) -> List[str]:
        return list(self._parallel_names)

    def get_dim(self, axis_name: str) -> int:
        return self._dims[self._parallel_names.index(canon_axis(axis_name))]

    get_dim_size = get_dim

    def world_size(self) -> int:
        return self._world_size

    def get_rank(self, **coords) -> int:
        idx = [coords[n] for n in self._parallel_names]
        return int(self._rank_grid[tuple(idx)])

    def get_coord(self, rank: int) -> Tuple[int, ...]:
        return self._coord_of[rank]

    def get_axis_list(self, axis_name: str, index: int) -> List[int]:
        """All ranks whose coordinate on `axis_name` equals `index`."""
        ax = self._parallel_names.index(canon_axis(axis_name))
        return sorted(int(r) for r, c in self._coord_of.items()
                      if c[ax] == index)

    def get_comm_list(self, axis_name: str) -> List[List[int]]:
        """Rank groups that communicate along `axis_name` (reference
        `topology.py:87`): one list per combination of the other axes."""
        ax = self._parallel_names.index(canon_axis(axis_name))
        groups = collections.defaultdict(list)
        for r in range(self._world_size):
            c = self._coord_of[r]
            key = c[:ax] + c[ax + 1:]
            groups[key].append(r)
        return [sorted(v) for _, v in sorted(groups.items())]

    def get_rank_from_stage(self, global_rank: int, **kwargs) -> int:
        coord = dict(zip(self._parallel_names, self.get_coord(global_rank)))
        coord.update({canon_axis(k): v for k, v in kwargs.items()})
        return self.get_rank(**coord)


def build_mesh(dims: Dict[str, int],
               devices: Optional[Sequence] = None) -> Mesh:
    """Build the global Mesh from {axis: size}. Axes ordered per AXIS_CANON
    (outermost=dp ... innermost=mp so mp collectives ride nearest-neighbor
    ICI), extra axes appended in given order."""
    dims = {canon_axis(k): v for k, v in dims.items() if v is not None}
    names = [a for a in AXIS_CANON if dims.get(a, 1) > 1 or a in dims]
    names += [a for a in dims if a not in names]
    if not names:
        names = ["dp"]
    sizes = [max(1, int(dims.get(a, 1))) for a in names]
    if devices is None:
        devices = jax.devices()
    need = int(np.prod(sizes))
    # the dp axis absorbs the remaining devices (created if absent)
    if need < len(devices) and len(devices) % need == 0:
        if "dp" in names:
            sizes[names.index("dp")] *= len(devices) // need
        else:
            names.insert(0, "dp")
            sizes.insert(0, len(devices) // need)
        need = len(devices)
    assert need <= len(devices), (
        f"mesh {dict(zip(names, sizes))} needs {need} devices, "
        f"have {len(devices)}")
    dev_array = np.array(devices[:need]).reshape(sizes)
    return Mesh(dev_array, tuple(names))


class HybridCommunicateGroup:
    """Per-axis group views over one Mesh (reference `topology.py:117`).

    Unlike the reference there is no comm setup here — groups are cheap
    (mesh, axis) descriptors; `paddle_tpu.distributed.collective.Group`
    objects are created lazily.
    """

    def __init__(self, topology: Optional[CommunicateTopology] = None,
                 mesh: Optional[Mesh] = None,
                 dims: Optional[Dict[str, int]] = None):
        if mesh is None:
            if topology is not None:
                dims = dict(zip(topology.get_hybrid_group_names(),
                                topology._dims))
            assert dims is not None, "need topology, mesh or dims"
            mesh = build_mesh(dims)
        self._mesh = mesh
        # sequence-parallel attention flavor: "ring" (ppermute ring, never
        # materializes full K/V — extreme L) or "ulysses" (2 all-to-alls,
        # full-seq flash kernel per head group — moderate L, needs H%sp==0)
        self.sp_mode = "ring"
        ax = dict(zip(mesh.axis_names, mesh.devices.shape))
        self._dp_degree = ax.get("dp", 1)
        self._pp_degree = ax.get("pp", 1)
        self._sharding_degree = ax.get("sharding", 1)
        self._sp_degree = ax.get("sp", 1)
        self._mp_degree = ax.get("mp", 1)
        self._ep_degree = ax.get("ep", 1)
        self._topo = topology or CommunicateTopology(
            list(mesh.axis_names), list(mesh.devices.shape))
        self._groups = {}

    # -- mesh ----------------------------------------------------------------
    @property
    def mesh(self) -> Mesh:
        return self._mesh

    @property
    def topology(self) -> CommunicateTopology:
        return self._topo

    def axis_size(self, name: str) -> int:
        name = canon_axis(name)
        ax = dict(zip(self._mesh.axis_names, self._mesh.devices.shape))
        return ax.get(name, 1)

    def _axis_group(self, name: str):
        name = canon_axis(name)
        if name not in self._groups:
            from .collective import Group
            self._groups[name] = Group(mesh=self._mesh, axis_names=(name,))
        return self._groups[name]

    # -- reference API parity ------------------------------------------------
    def get_parallel_mode(self) -> str:
        if self._pp_degree > 1:
            return "pipeline"
        if self._sharding_degree > 1:
            return "sharding_parallel"
        if self._mp_degree > 1:
            return "model_parallel"
        return "data_parallel"

    def get_global_rank(self) -> int:
        return jax.process_index()

    # data parallel
    def get_data_parallel_world_size(self) -> int:
        return self._dp_degree

    def get_data_parallel_rank(self) -> int:
        return 0  # single-controller: per-device rank is lax.axis_index('dp')

    def get_data_parallel_group(self):
        return self._axis_group("dp")

    # model (tensor) parallel
    def get_model_parallel_world_size(self) -> int:
        return self._mp_degree

    def get_model_parallel_rank(self) -> int:
        return 0

    def get_model_parallel_group(self):
        return self._axis_group("mp")

    # pipeline
    def get_pipe_parallel_world_size(self) -> int:
        return self._pp_degree

    def get_stage_id(self) -> int:
        return 0

    def get_pipe_parallel_group(self):
        return self._axis_group("pp")

    # sharding
    def get_sharding_parallel_world_size(self) -> int:
        return self._sharding_degree

    def get_sharding_parallel_rank(self) -> int:
        return 0

    def get_sharding_parallel_group(self):
        return self._axis_group("sharding")

    # sequence/context
    def get_sep_parallel_world_size(self) -> int:
        return self._sp_degree

    def get_sep_parallel_group(self):
        return self._axis_group("sp")

    # expert parallel (MoE)
    def get_expert_parallel_world_size(self) -> int:
        return self._ep_degree

    def get_expert_parallel_group(self):
        return self._axis_group("ep")

    def get_check_parallel_group(self):
        from .collective import Group
        return Group(mesh=self._mesh, axis_names=tuple(self._mesh.axis_names))

    def topology_description(self) -> str:
        return (f"HybridCommunicateGroup(dp={self._dp_degree}, "
                f"pp={self._pp_degree}, sharding={self._sharding_degree}, "
                f"sp={self._sp_degree}, mp={self._mp_degree})")

    __repr__ = topology_description


_HCG: Optional[HybridCommunicateGroup] = None


def set_hybrid_communicate_group(hcg: HybridCommunicateGroup):
    global _HCG
    _HCG = hcg


def get_hybrid_communicate_group() -> Optional[HybridCommunicateGroup]:
    return _HCG
