"""`python -m paddle_tpu.distributed.launch` — distributed job launcher.

Reference: `paddle.distributed.launch`
(`/root/reference/python/paddle/distributed/launch/main.py:18`, collective
controller `launch/controllers/collective.py:23`): builds a Job/Pod model,
exports the trainer env contract (PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM /
PADDLE_TRAINER_ENDPOINTS / PADDLE_CURRENT_ENDPOINT), spawns and supervises
local worker processes, restarts them per elastic level.

TPU mapping: one worker process per HOST (single-controller JAX drives all
local chips), so `--nproc_per_node` defaults to 1; the coordinator is the
master endpoint consumed by `init_parallel_env` →
`jax.distributed.initialize`. `--nproc_per_node > 1` remains useful for
CPU-simulation clusters (the reference's localhost-subprocess test pattern,
`test_dist_base.py:968`).
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time
from typing import List, Optional


def parse_args(argv: Optional[List[str]] = None):
    p = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="launch a distributed training job")
    p.add_argument("--master", default=None,
                   help="ip:port of rank-0 host (default: localhost:PORT)")
    p.add_argument("--nnodes", type=int,
                   default=int(os.environ.get("PADDLE_NNODES", "1")))
    p.add_argument("--rank", type=int,
                   default=int(os.environ.get("PADDLE_NODE_RANK", "0")),
                   help="this node's rank in [0, nnodes)")
    p.add_argument("--nproc_per_node", type=int,
                   default=int(os.environ.get("PADDLE_NPROC_PER_NODE", "1")))
    p.add_argument("--log_dir", default="log")
    p.add_argument("--job_id", default="default")
    p.add_argument("--devices", default=None,
                   help="visible device selection (sets JAX_VISIBLE_DEVICES)")
    p.add_argument("--elastic_level", type=int, default=int(os.environ.get(
        "PADDLE_ELASTIC_FAULT_TOLERANC_LEVEL", "0")))
    p.add_argument("--max_restart", type=int, default=3)
    p.add_argument("--host", default=None, help="this node's address")
    p.add_argument("--module", action="store_true",
                   help="treat training_script as a module (python -m)")
    p.add_argument("training_script",
                   help="training script path (or module name with --module)")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


class Pod:
    """Local process group of one node (reference launch job/pod model)."""

    def __init__(self, args):
        from ..env import find_free_port
        self.args = args
        host = args.host or "127.0.0.1"
        master = args.master or f"127.0.0.1:{find_free_port()}"
        if ":" not in master:
            master = f"{master}:{find_free_port()}"
        self.master = master
        nproc = args.nproc_per_node
        world = args.nnodes * nproc
        mhost, mport = master.rsplit(":", 1)
        # endpoint list: one per worker process, rank-major over nodes,
        # ports deterministic from the master port so every node derives the
        # same list without a KV server (the reference uses a master KV).
        # Only eps[0] (the coordinator) must be reachable — that is what
        # init_parallel_env hands to jax.distributed.initialize; other
        # nodes' workers are listed under the master host, which keeps the
        # list identical on every node.
        base = int(mport)
        self.endpoints = []
        for node in range(args.nnodes):
            nh = host if node == args.rank else mhost
            for i in range(nproc):
                self.endpoints.append(f"{nh}:{base + node * nproc + i}")
        self.world_size = world
        self.local_ranks = list(range(args.rank * nproc,
                                      (args.rank + 1) * nproc))
        self.procs: List[subprocess.Popen] = []

    def env_for(self, global_rank: int, local_rank: int) -> dict:
        env = dict(os.environ)
        env.update({
            "PADDLE_TRAINER_ID": str(global_rank),
            "PADDLE_TRAINERS_NUM": str(self.world_size),
            "PADDLE_TRAINER_ENDPOINTS": ",".join(self.endpoints),
            "PADDLE_CURRENT_ENDPOINT": self.endpoints[global_rank],
            "PADDLE_LOCAL_RANK": str(local_rank),
            "PADDLE_JOB_ID": self.args.job_id,
            "MASTER_ADDR": self.master.rsplit(":", 1)[0],
            "MASTER_PORT": self.master.rsplit(":", 1)[1],
        })
        if self.args.devices is not None:
            devs = self.args.devices.split(",")
            nproc = self.args.nproc_per_node
            if len(devs) >= nproc and len(devs) % nproc == 0:
                per = len(devs) // nproc  # partition across local workers
                mine = ",".join(devs[local_rank * per:(local_rank + 1) * per])
            else:
                mine = self.args.devices
            env["JAX_VISIBLE_DEVICES"] = mine
            env["CUDA_VISIBLE_DEVICES"] = mine
        return env

    def deploy(self):
        os.makedirs(self.args.log_dir, exist_ok=True)
        self.procs = []
        cmd = [sys.executable, "-u"]
        if self.args.module:
            cmd += ["-m", self.args.training_script]
        else:
            cmd += [self.args.training_script]
        script_args = self.args.training_script_args
        for local_rank, global_rank in enumerate(self.local_ranks):
            log = open(os.path.join(self.args.log_dir,
                                    f"workerlog.{global_rank}"), "ab")
            proc = subprocess.Popen(
                cmd + script_args, env=self.env_for(global_rank, local_rank),
                stdout=log if local_rank != 0 else None,
                stderr=subprocess.STDOUT if local_rank != 0 else None)
            proc._log_file = log  # keep for close
            self.procs.append(proc)

    def poll(self) -> Optional[int]:
        """None while all running; else first non-zero code or 0 if all OK."""
        codes = [p.poll() for p in self.procs]
        for c in codes:
            if c is not None and c != 0:
                return c
        if all(c == 0 for c in codes):
            return 0
        return None

    def stop(self, sig=signal.SIGTERM):
        for p in self.procs:
            if p.poll() is None:
                try:
                    p.send_signal(sig)
                except OSError:
                    pass
        deadline = time.time() + 10
        for p in self.procs:
            try:
                p.wait(max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                p.kill()
        for p in self.procs:
            f = getattr(p, "_log_file", None)
            if f is not None:
                f.close()


def launch(argv: Optional[List[str]] = None) -> int:
    args = parse_args(argv)
    restarts = 0
    while True:
        pod = Pod(args)
        pod.deploy()
        code = None
        try:
            while code is None:
                time.sleep(0.2)
                code = pod.poll()
        except KeyboardInterrupt:
            pod.stop(signal.SIGINT)
            return 130
        pod.stop()
        if code == 0:
            return 0
        from ..fleet.elastic import ELASTIC_EXIT_CODE
        # exit 101 is an explicit restart request (reference ELASTIC_EXIT_CODE
        # semantics, elastic/manager.py:37) — honored at any elastic level
        if (code == ELASTIC_EXIT_CODE or args.elastic_level > 0) \
                and restarts < args.max_restart:
            restarts += 1
            print(f"[launch] worker failed (exit {code}); restart "
                  f"{restarts}/{args.max_restart}", file=sys.stderr)
            continue
        return code


def main():
    sys.exit(launch())


if __name__ == "__main__":
    main()
