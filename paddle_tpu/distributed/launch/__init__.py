"""paddle.distributed.launch parity (see main.py)."""
from .main import launch, main, parse_args  # noqa: F401
