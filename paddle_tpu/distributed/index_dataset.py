"""Tree index + samplers for retrieval recommenders (TDM), native-backed.

Reference: /root/reference/paddle/fluid/distributed/index_dataset/
(`index_wrapper.cc` TreeIndex, `index_sampler.cc` LayerWiseSampler) with the
python face `python/paddle/distributed/fleet/dataset/index_dataset.py`.
The tree lives in C++ (`_native/csrc/index_dataset.cc`); training draws
per-layer positive/negative node samples, serving beam-searches the tree
with the caller's scoring model.
"""
from __future__ import annotations

import ctypes
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from .. import _native

_U64P = ctypes.POINTER(ctypes.c_uint64)
_I64P = ctypes.POINTER(ctypes.c_int64)


class TreeIndex:
    """Complete K-ary tree over an ordered item list (leaf order = the
    given order; pre-sort by category/embedding for a meaningful
    hierarchy, as the reference's tree-building tools do)."""

    def __init__(self, item_ids: Sequence[int], branch: int = 2):
        self._lib = _native.load()
        items = np.ascontiguousarray(item_ids, np.uint64)
        if items.ndim != 1 or items.size == 0:
            raise ValueError("item_ids must be a non-empty 1-D sequence")
        self._h = self._lib.tdm_tree_create(
            items.ctypes.data_as(_U64P), items.size, branch)
        if self._h < 0:
            raise RuntimeError("tdm_tree_create failed")
        self.branch = max(2, branch)
        self.n_items = int(items.size)

    @property
    def height(self) -> int:
        return self._lib.tdm_tree_height(self._h)

    def total_node_nums(self) -> int:
        return int(self._lib.tdm_tree_total_nodes(self._h))

    def layer_size(self, layer: int) -> int:
        return int(self._lib.tdm_tree_layer_size(self._h, layer))

    def get_ancestors(self, items, layer: int) -> np.ndarray:
        arr = np.ascontiguousarray(items, np.uint64).reshape(-1)
        out = np.empty(arr.size, np.int64)
        rc = self._lib.tdm_tree_ancestors(
            self._h, arr.ctypes.data_as(_U64P), arr.size, layer,
            out.ctypes.data_as(_I64P))
        if rc != 0:
            raise RuntimeError("tdm_tree_ancestors failed")
        return out

    def get_children(self, nodes) -> np.ndarray:
        arr = np.ascontiguousarray(nodes, np.int64).reshape(-1)
        out = np.empty(arr.size * self.branch, np.int64)
        rc = self._lib.tdm_tree_children(
            self._h, arr.ctypes.data_as(_I64P), arr.size,
            out.ctypes.data_as(_I64P))
        if rc != 0:
            raise RuntimeError("tdm_tree_children failed")
        return out.reshape(arr.size, self.branch)

    def node_items(self, nodes) -> np.ndarray:
        """Leaf node ids -> item ids (-1 for internal nodes)."""
        arr = np.ascontiguousarray(nodes, np.int64).reshape(-1)
        out = np.empty(arr.size, np.int64)
        rc = self._lib.tdm_tree_node_items(
            self._h, arr.ctypes.data_as(_I64P), arr.size,
            out.ctypes.data_as(_I64P))
        if rc != 0:
            raise RuntimeError("tdm_tree_node_items failed")
        return out

    def __del__(self):
        try:
            self._lib.tdm_tree_destroy(self._h)
        except Exception:
            pass


class LayerWiseSampler:
    """reference index_sampler.cc LayerWiseSampler: per (user, item) pair,
    per layer: the item's ancestor as positive + uniform same-layer
    negatives."""

    def __init__(self, tree: TreeIndex, start_layer: int = 1,
                 neg_per_layer: int = 2, seed: int = 0):
        self.tree = tree
        self.start_layer = start_layer
        self.neg_per_layer = neg_per_layer
        self._seed = seed

    def sample(self, target_items) -> Tuple[np.ndarray, np.ndarray]:
        """-> (nodes [n, layers*(1+neg)], labels same shape)."""
        items = np.ascontiguousarray(target_items, np.uint64).reshape(-1)
        layers = self.tree.height - self.start_layer
        per_item = layers * (1 + self.neg_per_layer)
        nodes = np.empty(items.size * per_item, np.int64)
        labels = np.empty_like(nodes)
        self._seed += 1
        rc = self.tree._lib.tdm_layerwise_sample(
            self.tree._h, items.ctypes.data_as(_U64P), items.size,
            self.start_layer, self.neg_per_layer, self._seed,
            nodes.ctypes.data_as(_I64P), labels.ctypes.data_as(_I64P))
        if rc == -2:
            raise KeyError("sample: an item id is not in the tree")
        if rc != 0:
            raise RuntimeError("tdm_layerwise_sample failed")
        return (nodes.reshape(items.size, per_item),
                labels.reshape(items.size, per_item))


def beam_search_retrieval(tree: TreeIndex, score_fn: Callable, beam: int,
                          topk: Optional[int] = None) -> np.ndarray:
    """Serve-time retrieval (reference beam search over the tree): walk from
    the root keeping the `beam` best nodes per layer under `score_fn(nodes)
    -> scores`, return the top item ids at the leaves."""
    nodes = np.array([0], np.int64)
    for _ in range(tree.height - 1):
        children = tree.get_children(nodes).reshape(-1)
        children = children[children >= 0]
        if children.size == 0:
            break
        scores = np.asarray(score_fn(children), np.float64).reshape(-1)
        keep = min(beam, children.size)
        idx = np.argpartition(-scores, keep - 1)[:keep]
        nodes = children[idx]
    items = tree.node_items(nodes)
    items = items[items >= 0]
    if topk is not None and items.size > topk:
        scores = np.asarray(score_fn(nodes[:len(items)]),
                            np.float64).reshape(-1)
        items = items[np.argsort(-scores)[:topk]]
    return items


__all__ = ["TreeIndex", "LayerWiseSampler", "beam_search_retrieval"]
