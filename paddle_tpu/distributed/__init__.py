"""paddle_tpu.distributed — the distributed stack.

Reference surface: `python/paddle/distributed/` (collective API, parallel
env, fleet facade, launch CLI, hybrid parallelism). TPU translation notes in
each submodule; the unifying idea is ONE `jax.sharding.Mesh` whose named
axes (dp/pp/sharding/sp/mp) replace the reference's NCCL ring-per-group
world (`fleet/base/topology.py:117`).
"""
from __future__ import annotations

from .env import ParallelEnv  # noqa: F401
from .collective import (  # noqa: F401
    Group, ReduceOp, all_gather, all_gather_object, all_reduce, alltoall,
    alltoall_single, barrier, broadcast, destroy_process_group, get_group,
    is_initialized, new_group, ppermute, recv, reduce, reduce_scatter,
    scatter, send, split, stream_synchronize, wait,
)
from .parallel import (  # noqa: F401
    DataParallel, get_rank, get_world_size, init_parallel_env, is_available,
    replicate, shard_batch,
)
from .topology import (  # noqa: F401
    CommunicateTopology, HybridCommunicateGroup, build_mesh,
    get_hybrid_communicate_group, set_hybrid_communicate_group,
)
from . import meta_parallel  # noqa: F401
from . import fleet  # noqa: F401
from . import sharding  # noqa: F401
from . import launch  # noqa: F401
from . import auto_parallel  # noqa: F401
from . import checkpoint  # noqa: F401
from . import sharded_checkpoint  # noqa: F401
from . import ps  # noqa: F401
from .auto_parallel import ProcessMesh, shard_tensor, shard_op  # noqa: F401
from .store import TCPStore  # noqa: F401
from .spawn import spawn  # noqa: F401
# dataset classes live on fleet but the reference also exposes them at
# `paddle.distributed.*` (`python/paddle/distributed/__init__.py`)
from .fleet.dataset import InMemoryDataset, QueueDataset  # noqa: F401

# bind paddle.DataParallel lazily (top-level package avoids import cycle)
import paddle_tpu as _paddle

_paddle.DataParallel = DataParallel


def get_backend() -> str:
    return "xla"  # ICI/DCN collectives via XLA, not nccl/gloo


QUEUE_DTYPE = None  # reserved


# spawn: real multiprocessing implementation lives in spawn.py (imported
# above); nprocs<=1 degenerates to an inline call there.
