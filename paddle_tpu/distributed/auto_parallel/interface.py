"""shard_tensor / shard_op — the semi-auto parallel annotation API.

Reference: `paddle.distributed.shard_tensor`
(/root/reference/python/paddle/distributed/auto_parallel/interface.py):
annotate a tensor with (mesh, shard_spec); the Completer propagates dist
attrs through the graph, the Partitioner splits the program, the Resharder
inserts comm. TPU translation: the annotation becomes a `NamedSharding` —
eagerly applied with `jax.device_put` (so the array is physically laid out
across the mesh immediately), and GSPMD does completion/partition/reshard
when the consuming computation is jitted.
"""
from __future__ import annotations

from typing import List, Optional

import jax

from ...framework.tensor import Tensor
from .dist_attribute import TensorDistAttr
from .process_mesh import ProcessMesh, get_current_process_mesh


def shard_tensor(x, process_mesh: Optional[ProcessMesh] = None,
                 shard_spec: Optional[List[Optional[str]]] = None):
    """Annotate + physically shard `x` over `process_mesh`.

    Returns the same Tensor object with `.dist_attr` set and its array
    re-laid-out under the mesh (replicated dims stay replicated).
    """
    mesh = process_mesh or get_current_process_mesh()
    if mesh is None:
        raise ValueError("shard_tensor: no process_mesh (pass one or use "
                         "`with ProcessMesh(...):`)")
    t = x if isinstance(x, Tensor) else Tensor(x)
    if shard_spec is None:
        shard_spec = [None] * t.ndim
    if len(shard_spec) != t.ndim:
        raise ValueError(
            f"shard_spec length {len(shard_spec)} != tensor ndim {t.ndim}")
    attr = TensorDistAttr.from_shard_spec(mesh, shard_spec)
    jmesh = mesh.to_jax()
    sharding = attr.to_sharding(jmesh)
    t.data = jax.device_put(t.data, sharding)
    t.dist_attr = attr
    # parameters feed the hybrid/auto engines through dist_spec
    from ...framework.param import Parameter
    if isinstance(t, Parameter):
        t.dist_spec = attr.to_partition_spec()
    return t


def shard_op(op, process_mesh: Optional[ProcessMesh] = None,
             in_shard_specs=None, out_shard_specs=None):
    """Annotate a callable: inputs/outputs get shard_tensor'd per the specs
    (reference interface.py shard_op). The ambient mesh is resolved at CALL
    time so `with ProcessMesh(...):` around the call site works."""

    def wrapped(*args, **kwargs):
        mesh = process_mesh or get_current_process_mesh()
        if mesh is None:
            if in_shard_specs or out_shard_specs:
                raise ValueError(
                    "shard_op: shard specs given but no process_mesh is "
                    "active (pass one or use `with ProcessMesh(...):`)")
            return op(*args, **kwargs)
        if in_shard_specs is not None:
            if len(in_shard_specs) != len(args):
                raise ValueError(
                    f"shard_op: {len(args)} inputs but "
                    f"{len(in_shard_specs)} in_shard_specs")
            args = tuple(
                a if s is None else shard_tensor(a, mesh, s)
                for a, s in zip(args, in_shard_specs))
        out = op(*args, **kwargs)
        if out_shard_specs is None:
            return out
        if isinstance(out, (list, tuple)):
            if len(out_shard_specs) != len(out):
                raise ValueError(
                    f"shard_op: op returned {len(out)} outputs but "
                    f"{len(out_shard_specs)} out_shard_specs were given")
            return type(out)(
                o if s is None else shard_tensor(o, mesh, s)
                for o, s in zip(out, out_shard_specs))
        return shard_tensor(out, mesh, out_shard_specs[0]
                            if isinstance(out_shard_specs[0], (list, type(None)))
                            else out_shard_specs)
    return wrapped
