"""Cluster description + mesh->cluster mapper for the auto-parallel planner.

Reference: `auto_parallel/mapper.py:81` (`mapping(dist_context, machines)` —
place the process graph onto machines by link capability) and
`cluster.py`'s Machine/Link model. The TPU translation: a cluster is a set
of SLICES (pods connected by DCN); chips within a slice talk over ICI.
Mapping a logical mesh onto it is a question of WHICH MESH AXES cross the
slice boundary — the mapper classifies every axis as ici or dcn, and the
planner prices each collective by the slowest link its replica groups
actually cross, extending the single-fabric ICI roofline term
(`planner.py` `_collective_bytes`).

Device order contract: `jax.devices()` is slice-major (devices of slice 0
first), which is jax's actual ordering on multislice; the mapper assumes
it and `Plan.build_mesh` preserves it.
"""
from __future__ import annotations

import dataclasses
import re
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

# effective per-chip bandwidths; only ratios matter for ranking
DEFAULT_ICI_BW = 90e9
DEFAULT_DCN_BW = 6.25e9  # ~50 Gbit/s per-chip share of the DCN NIC


@dataclasses.dataclass
class Cluster:
    """Slices x chips-per-slice with per-link bandwidths (reference
    `auto_parallel/cluster.py` Machine/Link graph, collapsed to the two
    link classes a TPU fleet actually has)."""
    n_slices: int = 1
    chips_per_slice: int = 8
    hosts_per_slice: int = 1            # informational (DCN NIC sharing)
    peak_flops: float = 197e12
    hbm_bw: float = 819e9
    ici_bw: float = DEFAULT_ICI_BW
    dcn_bw: float = DEFAULT_DCN_BW

    @property
    def n_devices(self) -> int:
        return self.n_slices * self.chips_per_slice

    def slice_of(self, device_id: int) -> int:
        return device_id // self.chips_per_slice


class Mapper:
    """Classify logical mesh axes (and compiled collectives) by the link
    they ride when the mesh is laid slice-major onto the cluster."""

    def __init__(self, cluster: Cluster):
        self.cluster = cluster

    def axis_links(self, mesh_dims: Dict[str, int]) -> Dict[str, str]:
        """axis name -> "ici" | "dcn". With devices numbered slice-major
        and the mesh reshaped row-major, an axis with inner-stride `st`
        and size `sz` connects ids {base + j*st}; it crosses a slice
        boundary iff st*sz > chips_per_slice (axes of size 1 are local)."""
        out: Dict[str, str] = {}
        stride = 1
        for name in reversed(list(mesh_dims)):  # innermost first
            sz = int(mesh_dims[name])
            spans = sz > 1 and stride * sz > self.cluster.chips_per_slice
            out[name] = "dcn" if spans else "ici"
            stride *= sz
        return out

    # -- compiled-HLO collective attribution --------------------------------
    def collective_bytes_by_link(self, compiled) -> Tuple[float, float]:
        """(ici_bytes, dcn_bytes) from the optimized per-device HLO: each
        collective's moved bytes are attributed to DCN when any of its
        replica groups contains devices from different slices."""
        from .planner import _iter_collective_lines
        ici = dcn = 0.0
        for nbytes, line in _iter_collective_lines(compiled):
            groups = _parse_replica_groups(line)
            if groups:
                crosses = any(
                    len({self.cluster.slice_of(d) for d in g}) > 1
                    for g in groups)
            else:
                pairs = _parse_source_target_pairs(line)
                if pairs is not None:
                    # collective-permute: priced by its actual pairs (it
                    # never carries replica_groups — a ring over an ICI
                    # axis must NOT be billed at DCN rates)
                    crosses = any(
                        self.cluster.slice_of(s) != self.cluster.slice_of(t)
                        for s, t in pairs)
                else:
                    # XLA's all-replica form `replica_groups={}` ([] here)
                    # and a group-carrying collective with the attribute
                    # missing both span every device: on a >1-slice cluster
                    # that necessarily crosses DCN
                    crosses = self.cluster.n_slices > 1
            if crosses:
                dcn += nbytes
            else:
                ici += nbytes
        return ici, dcn


def _parse_replica_groups(line: str) -> Optional[List[List[int]]]:
    """Parse HLO `replica_groups=` — explicit `{{0,1},{2,3}}` lists and the
    iota form `[G,S]<=[dims](T(perm))?`. Returns None when absent and []
    for the empty all-replica form `replica_groups={}` (one group spanning
    every device — the caller attributes it by cluster topology)."""
    if re.search(r"replica_groups=\{\s*\}", line):
        return []
    m = re.search(r"replica_groups=\{\{([^}]*(?:\},\{[^}]*)*)\}\}", line)
    if m:
        return [[int(x) for x in grp.split(",") if x.strip() != ""]
                for grp in m.group(1).split("},{")]
    m = re.search(r"replica_groups=\[(\d+),(\d+)\]<=\[([\d,]+)\]"
                  r"(?:T\(([\d,]+)\))?", line)
    if m:
        g, s = int(m.group(1)), int(m.group(2))
        dims = [int(x) for x in m.group(3).split(",")]
        ids = np.arange(int(np.prod(dims))).reshape(dims)
        if m.group(4):
            perm = [int(x) for x in m.group(4).split(",")]
            ids = ids.transpose(perm)
        return ids.reshape(g, s).tolist()
    return None


def _parse_source_target_pairs(line: str):
    """Parse collective-permute's `source_target_pairs={{0,1},{1,2}}`.
    Returns a list of (src, dst) pairs, or None when absent."""
    m = re.search(r"source_target_pairs=\{\{([^}]*(?:\},\{[^}]*)*)\}\}", line)
    if not m:
        return None
    out = []
    for grp in m.group(1).split("},{"):
        s, t = (int(x) for x in grp.split(","))
        out.append((s, t))
    return out


__all__ = ["Cluster", "Mapper", "DEFAULT_ICI_BW", "DEFAULT_DCN_BW"]
