"""Auto-parallel planner — searches for a sharding plan by compiled cost.

Reference: `Planner`/cost-model search
(/root/reference/python/paddle/distributed/auto_parallel/planner.py,
`cost_model.py`): enumerate partitioning candidates for the serial program,
estimate each with an analytic per-op + comm cost model, pick the cheapest.

TPU translation: the cost model IS the compiler. Each candidate here is a
(mesh factorization, TP-template) pair; the whole train step is lowered and
compiled under that candidate's shardings (GSPMD partitions it) and scored
from `compiled.cost_analysis()` with a roofline estimate
    t = max(flops / peak_flops, bytes / hbm_bw)
over the PER-DEVICE SPMD module — so compute/bandwidth/collective traffic
are all priced by the same compiler that will execute the plan, replacing
the reference's hand-maintained op cost tables at a fraction of the code.

Templates (reference `mp_layers.py` Megatron layouts + hybrid axes):
  * "dp"             — pure data parallel, params replicated
  * "tp_alternating" — consecutive Linear layers alternate column/row
                       parallel over `mp` (one allreduce per pair)
  * "pp"             — the REAL compiled 1F1B pipeline step
                       (PipelineParallelTrainStep) over a pp axis —
                       stage-sharded params, collective-permute rotation
  * "sp_ulysses"     — sequence parallelism over an sp axis (the engine's
                       sp batch sharding; sdpa routes through
                       Ulysses/ring attention)

The roofline score carries an ICI term (round-2 review: a score without
one mis-ranks candidates that trade FLOPs for collectives):
    t = max(flops / peak_flops, bytes / hbm_bw, coll_bytes / ici_bw)
with coll_bytes summed from the collective ops (all-reduce / all-gather /
reduce-scatter / collective-permute / all-to-all) of the optimized
per-device HLO.
"""
from __future__ import annotations

import dataclasses
import re
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ...framework.tensor import Tensor
from ...nn.layer import Layer

# Roofline constants (v5e). Only the RATIOS matter for ranking plans; all
# are overridable for other parts.
PEAK_FLOPS = 197e12
HBM_BW = 819e9
ICI_BW = 90e9  # effective per-chip ICI bandwidth

_DTYPE_BYTES = {"f64": 8, "f32": 4, "f16": 2, "bf16": 2, "s64": 8,
                "u64": 8, "s32": 4, "u32": 4, "s16": 2, "u16": 2,
                "s8": 1, "u8": 1, "pred": 1, "f8e4m3fn": 1, "f8e5m2": 1}

_COLLECTIVES = ("all-reduce", "all-gather", "reduce-scatter",
                "collective-permute", "all-to-all")

_SHAPE_RE = re.compile(r"\b([a-z0-9]+)\[([0-9,]*)\]")


def _iter_collective_lines(compiled):
    """Yield (moved_bytes, hlo_line) per collective op of the optimized
    per-device HLO. XLA's cost_analysis does not break out inter-chip
    traffic, so callers price it from the module text: for every line
    whose op is a collective, the result shapes left of the op name are
    the moved data. Shared by the single-fabric scorer below and the
    Cluster mapper's per-link attribution (cluster.py)."""
    try:
        txt = compiled.as_text()
    except Exception:
        return
    for line in txt.splitlines():
        stripped = line.strip()
        head = None
        for c in _COLLECTIVES:
            # "-start" variants count once; "-done" (no trailing "(")
            # repeats the start's shapes and is skipped. The head is cut at
            # the OP NAME, not the first "(": combined/async collectives
            # return TUPLE shapes "(f32[..], f32[..])" whose open-paren
            # would otherwise truncate every shape away
            m = re.search(rf"\b{c}(-start)?\(", stripped)
            if m and "= " in stripped[:m.start()]:
                head = stripped[:m.start()]
                break
        if head is None:
            continue
        nbytes = 0.0
        for dt, dims in _SHAPE_RE.findall(head):
            if dt not in _DTYPE_BYTES:
                continue
            n = 1
            for d in dims.split(","):
                if d:
                    n *= int(d)
            nbytes += n * _DTYPE_BYTES[dt]
        yield nbytes, stripped


def _collective_bytes(compiled) -> float:
    return sum(nb for nb, _ in _iter_collective_lines(compiled))


@dataclasses.dataclass
class Plan:
    mesh_dims: Dict[str, int]              # e.g. {"dp": 4, "mp": 2}
    param_specs: Dict[str, P]              # name -> PartitionSpec
    template: str
    score: float                           # estimated step seconds (roofline)
    cost: Dict[str, float]                 # raw flops / bytes

    def build_mesh(self, devices=None) -> Mesh:
        devs = np.array(devices if devices is not None else jax.devices())
        shape = tuple(self.mesh_dims.values())
        return Mesh(devs[:int(np.prod(shape))].reshape(shape),
                    tuple(self.mesh_dims.keys()))


def _divisor_pairs(n: int) -> List[Tuple[int, int]]:
    """(dp, mp) factorizations of n, mp ascending."""
    out = []
    mp = 1
    while mp <= n:
        if n % mp == 0:
            out.append((n // mp, mp))
        mp *= 2
    return out


def _ordered_linears(model: Layer):
    from ...nn import layers_common as L
    return [(name, lyr) for name, lyr in model.named_sublayers()
            if isinstance(lyr, L.Linear)]


def _template_specs(model: Layer, template: str, mp: int) -> Dict[str, P]:
    """Param-name -> spec for a TP template (empty for pure dp)."""
    specs: Dict[str, P] = {}
    if template == "dp" or mp == 1:
        return specs
    if template == "tp_alternating":
        # Megatron MLP layout: col-parallel then row-parallel, repeating —
        # activations stay sharded between the pair, one psum at the row end
        for i, (name, lyr) in enumerate(_ordered_linears(model)):
            w = f"{name}.weight"
            b = f"{name}.bias"
            out_features = lyr.weight.shape[1]
            in_features = lyr.weight.shape[0]
            if i % 2 == 0:
                if out_features % mp == 0:
                    specs[w] = P(None, "mp")
                    specs[b] = P("mp")
            else:
                if in_features % mp == 0:
                    specs[w] = P("mp", None)
        return specs
    raise ValueError(f"unknown template {template!r}")


class Planner:
    """Searches (mesh, template) candidates for a model + loss (+ optimizer).

    `plan(*batch)` compiles one train (or forward) step per candidate and
    returns the argmin-score `Plan`.
    """

    def __init__(self, model: Layer, loss_fn: Callable, optimizer=None,
                 n_devices: Optional[int] = None,
                 templates: Sequence[str] = ("dp", "tp_alternating", "pp",
                                             "sp_ulysses"),
                 data_axis: str = "dp", cluster=None):
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.n = n_devices or len(jax.devices())
        self.templates = list(templates)
        self.data_axis = data_axis
        # Optional Cluster (cluster.py): prices collectives per LINK — a
        # replica group crossing a slice boundary rides DCN, not ICI
        # (reference `auto_parallel/mapper.py:81` link-aware mapping)
        self.cluster = cluster
        if cluster is not None and cluster.n_devices < self.n:
            raise ValueError(f"cluster has {cluster.n_devices} devices, "
                             f"planner needs {self.n}")

    # -- one candidate ------------------------------------------------------
    def _score_candidate(self, dp: int, mp: int, template: str,
                         batch: Tuple) -> Optional[Plan]:
        from ...jit import functionalize
        specs = _template_specs(self.model, template, mp)
        if template != "dp" and mp > 1 and not specs:
            return None  # template found nothing to shard: skip duplicate
        if batch[0].shape[0] % dp:
            return None  # batch not divisible over the data axis
        mesh_dims = {"dp": dp, "mp": mp}
        devs = np.array(jax.devices()[:self.n]).reshape(dp, mp)
        mesh = Mesh(devs, ("dp", "mp"))

        apply_fn, params, buffers = functionalize(self.model)
        pshard = {k: NamedSharding(mesh, specs.get(k, P()))
                  for k in params}
        repl = NamedSharding(mesh, P())
        bshard = NamedSharding(mesh, P("dp"))
        loss_fn = self.loss_fn
        optimizer = self.optimizer

        def step(params, buffers, rng, *batch):
            def loss_of(p):
                out, _ = apply_fn(p, buffers, rng, *batch[:-1])
                loss = loss_fn(jax.tree_util.tree_map(Tensor, out),
                               Tensor(batch[-1]))
                return loss.data if isinstance(loss, Tensor) else loss
            if optimizer is None:
                return loss_of(params)
            loss, grads = jax.value_and_grad(loss_of)(params)
            new_params, _ = optimizer.apply_fn(
                params, grads, optimizer.init_state_tree(params),
                lr=jnp.asarray(1e-3, jnp.float32), t=1)
            return loss, new_params

        in_shardings = (pshard, {k: repl for k in buffers}, repl) + \
            tuple(bshard for _ in batch)
        with mesh:
            lowered = jax.jit(step, in_shardings=in_shardings).lower(
                params, buffers, jax.random.PRNGKey(0), *batch)
            compiled = lowered.compile()
        return self._plan_from_compiled(compiled, mesh_dims, specs, template)

    def _plan_from_compiled(self, compiled, mesh_dims, specs,
                            template) -> Plan:
        an = compiled.cost_analysis()
        if isinstance(an, list):
            an = an[0]
        flops = float(an.get("flops", 0.0))
        nbytes = float(an.get("bytes accessed", 0.0))
        if self.cluster is not None:
            from .cluster import Mapper
            c = self.cluster
            ici, dcn = Mapper(c).collective_bytes_by_link(compiled)
            score = max(flops / c.peak_flops, nbytes / c.hbm_bw,
                        ici / c.ici_bw, dcn / c.dcn_bw)
            return Plan(mesh_dims=mesh_dims, param_specs=specs,
                        template=template, score=score,
                        cost={"flops": flops, "bytes": nbytes,
                              "ici_bytes": ici, "dcn_bytes": dcn})
        ici = _collective_bytes(compiled)
        score = max(flops / PEAK_FLOPS, nbytes / HBM_BW, ici / ICI_BW)
        return Plan(mesh_dims=mesh_dims, param_specs=specs,
                    template=template, score=score,
                    cost={"flops": flops, "bytes": nbytes,
                          "ici_bytes": ici})

    # -- pipeline candidate: price the REAL compiled 1F1B step --------------
    def _score_pp(self, dp: int, pp: int, batch: Tuple) -> Optional[Plan]:
        from ..meta_parallel.pipeline_parallel import PipelineParallelTrainStep
        from ..topology import HybridCommunicateGroup
        if self.optimizer is None:
            return None
        if batch[0].shape[0] % (pp * max(dp, 1)):
            return None
        hcg = HybridCommunicateGroup(dims={"dp": dp, "pp": pp})
        # donate=False is deliberate (PR-10 donation audit): this step is a
        # scoring PROBE — lower().compile() + cost_analysis only, never
        # invoked — so donation buys nothing here, and a donated executable
        # would consume the probe's param/opt buffers if a future refactor
        # ever ran the winner directly. The production engine built from
        # the returned Plan keeps its donate=True default.
        step = PipelineParallelTrainStep(
            self.model, self.loss_fn, self.optimizer, hcg=hcg,
            num_micro=pp, donate=False)
        arrs = step.shard_batch(*batch)
        rng = jax.random.PRNGKey(0)
        lr = jnp.asarray(1e-3, jnp.float32)
        with step.mesh:
            compiled = step._step.lower(
                step._flat_params, step.buffers, step.opt_state,
                step.scaler_state, rng, lr, 1, *arrs).compile()
        # dp first: matches topology.AXIS_CANON, so Plan.build_mesh
        # reproduces the device layout the candidate was scored on
        return self._plan_from_compiled(
            compiled, {"dp": dp, "pp": pp}, {}, "pp")

    # -- sequence-parallel candidate ----------------------------------------
    def _score_sp(self, dp: int, sp: int, batch: Tuple) -> Optional[Plan]:
        from ..meta_parallel.engine import HybridParallelTrainStep
        from ..topology import (HybridCommunicateGroup,
                                get_hybrid_communicate_group,
                                set_hybrid_communicate_group)
        if self.optimizer is None:
            return None
        if batch[0].shape[0] % max(dp, 1):
            return None
        if any(b.ndim >= 2 and b.shape[1] % sp for b in batch):
            return None  # seq dim must divide over sp
        hcg = HybridCommunicateGroup(dims={"dp": dp, "sp": sp})
        hcg.sp_mode = "ulysses"
        prev = get_hybrid_communicate_group()
        set_hybrid_communicate_group(hcg)  # sdpa routes by the global hcg
        try:
            # donate=False: compile-only scoring probe, same reasoning as
            # the pp candidate above — never executed, so donation could
            # only hurt (consuming probe state if ever invoked)
            step = HybridParallelTrainStep(
                self.model, self.loss_fn, self.optimizer, hcg=hcg,
                donate=False)
            arrs = step.shard_batch(*batch)
            rng = jax.random.PRNGKey(0)
            lr = jnp.asarray(1e-3, jnp.float32)
            with step.mesh:
                compiled = step._step.lower(
                    step.params, step.buffers, step.opt_state,
                    step.scaler_state, rng, lr, 1, *arrs).compile()
        finally:
            set_hybrid_communicate_group(prev)
        return self._plan_from_compiled(
            compiled, {"dp": dp, "sp": sp}, {}, "sp_ulysses")

    # -- the search ---------------------------------------------------------
    def plan(self, *batch) -> Plan:
        arrs = tuple(b.data if isinstance(b, Tensor) else jnp.asarray(b)
                     for b in batch)
        candidates: List[Plan] = []
        errors: List[str] = []
        for dp, mp in _divisor_pairs(self.n):
            for template in self.templates:
                if template == "dp" and mp > 1:
                    continue  # replicated-over-mp duplicates pure dp
                if template not in ("dp", "tp_alternating"):
                    continue  # pp/sp enumerate over their own axes below
                if template != "dp" and mp == 1:
                    continue  # no mp axis: identical to pure dp
                try:
                    p = self._score_candidate(dp, mp, template, arrs)
                except Exception as e:  # an uncompilable candidate is skipped
                    errors.append(f"dp={dp},mp={mp},{template}: "
                                  f"{type(e).__name__}: {e}")
                    continue
                if p is not None:
                    candidates.append(p)
        for dp, other in _divisor_pairs(self.n):
            if other == 1:
                continue
            if "pp" in self.templates:
                try:
                    p = self._score_pp(dp, other, arrs)
                    if p is not None:
                        candidates.append(p)
                except Exception as e:  # not pipeline-able / not divisible
                    errors.append(f"dp={dp},pp={other}: "
                                  f"{type(e).__name__}: {e}")
            if "sp_ulysses" in self.templates:
                try:
                    p = self._score_sp(dp, other, arrs)
                    if p is not None:
                        candidates.append(p)
                except Exception as e:
                    errors.append(f"dp={dp},sp={other}: "
                                  f"{type(e).__name__}: {e}")
        if not candidates:
            raise RuntimeError(
                "auto-parallel planner: no viable candidate. Per-candidate "
                "failures:\n  " + "\n  ".join(errors or ["(none tried)"]))
        best = min(candidates, key=lambda p: p.score)
        best.cost["n_candidates"] = len(candidates)
        return best

    def apply(self, plan: Plan):
        """Annotate the model's parameters with the winning specs."""
        named = dict(self.model.named_parameters())
        for k, spec in plan.param_specs.items():
            if k in named:
                named[k].dist_spec = spec
        return plan


__all__ = ["Plan", "Planner", "PEAK_FLOPS", "HBM_BW", "ICI_BW"]
